"""Polarized (I, Q, U) destriping.

Parity target: the reference's polarization self-test path
(``MapMaking/Destriper.py:617-753`` ``testpol``), where each sample
carries a ``special_weight`` pair (cos 2chi, sin 2chi) and the map solve
becomes a per-pixel 3x3 system:

    d_t = I[p_t] + Q[p_t] cos(2 psi_t) + U[p_t] sin(2 psi_t) + (F a)_t + n_t

TPU-native formulation: the six unique entries of ``A_p = sum_t w s s^T``
(``s = [1, cos 2psi, sin 2psi]``) and the three of ``b_p = sum_t w d s``
are nine ``segment_sum``s; the per-pixel solves are one batched 3x3
``linalg.solve`` (MXU-friendly). The destriper CG is the same operator
chain as the unpolarized solver with ``Z`` replaced by its polarized
version; offsets remain per-sample scalars.

Pixels with insufficient angle diversity are rank-deficient; they get a
Tikhonov floor and are masked in the returned condition map.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from comapreduce_tpu.mapmaking.binning import _sanitize
from comapreduce_tpu.mapmaking.destriper import _cg_loop, _jacobi_inverse
from comapreduce_tpu.mapmaking.pointing_plan import binned_window_sum

__all__ = ["PolMapState", "pol_map_solve", "destripe_pol",
           "destripe_pol_planned", "PolDestriperResult"]


# Jacobi degeneracy floor for the planned pol CG: offsets whose diag(A)
# falls below this fraction of their plain sum-w diagonal are treated as
# near-degenerate and scaled by sum w instead of 1/diag(A). 0.05 was the
# most robust of the sweep {0.01 (still breaks), 0.05, 0.1, 0.3} — see
# destripe_pol_planned's docstring for the measured behavior.
_POL_JACOBI_FLOOR = 0.05


class PolMapState(NamedTuple):
    """Per-pixel normal-equation pieces for the IQU solve."""

    ata: jax.Array   # f32[npix, 3, 3]
    hits: jax.Array  # f32[npix]
    rcond_ok: jax.Array  # bool[npix] — pixel solvable


class PolDestriperResult(NamedTuple):
    offsets: jax.Array        # f32[n_offsets]
    iqu_destriped: jax.Array  # f32[npix, 3]
    iqu_naive: jax.Array      # f32[npix, 3]
    hit_map: jax.Array        # f32[npix]
    solvable: jax.Array       # bool[npix]
    n_iter: jax.Array
    residual: jax.Array


def _stokes_basis(c2, s2):
    """s_t = [1, cos 2psi, sin 2psi] stacked (N, 3)."""
    one = jnp.ones_like(c2)
    return jnp.stack([one, c2, s2], axis=-1)


def _ata_scale(ata):
    """Per-pixel Tikhonov scale (trace/3) — cheap, used every solve."""
    return jnp.maximum(jnp.trace(ata, axis1=-2, axis2=-1) / 3.0, 1e-30)


def _ata_scale_solvable(ata, hits):
    """(scale, rcond_ok) of per-pixel normal matrices — ONE home for the
    solvability criterion and Tikhonov scale, shared by the scatter and
    planned paths (drift here would mask different pixel sets). Runs a
    per-pixel determinant: call once at setup, not per CG iteration.

    Normalise by the trace BEFORE the determinant — weights can be huge
    (1/sigma^2) and det(A) ~ w^3 overflows f32."""
    scale = _ata_scale(ata)
    det_n = jnp.linalg.det(ata / scale[:, None, None])
    rcond_ok = (hits >= 3) & (det_n > 1e-6)
    return scale, rcond_ok


def _tikhonov(ata, scale):
    """Per-pixel floor scaled to each pixel's weight magnitude."""
    eye = jnp.eye(3, dtype=ata.dtype)
    return ata + (1e-6 * scale)[:, None, None] * eye


def _pol_accumulate(pixels, weights, c2, s2, npix, axis_name):
    s = _stokes_basis(c2, s2)                       # (N, 3)
    outer = s[:, :, None] * s[:, None, :]           # (N, 3, 3)
    w_outer = outer * weights[:, None, None]
    pix = _sanitize(pixels, npix)
    ata = jax.ops.segment_sum(w_outer, pix, num_segments=npix)
    hits = jax.ops.segment_sum(jnp.ones_like(weights) * (weights > 0),
                               pix, num_segments=npix)
    if axis_name is not None:
        ata = jax.lax.psum(ata, axis_name)
        hits = jax.lax.psum(hits, axis_name)
    _, rcond_ok = _ata_scale_solvable(ata, hits)
    return PolMapState(ata, hits, rcond_ok)


def pol_map_solve(d, pixels, weights, c2, s2, npix, state: PolMapState,
                  axis_name=None):
    """Weighted IQU map: solve ``A_p m_p = b_p`` per pixel. f32[npix, 3]."""
    s = _stokes_basis(c2, s2)
    wd = (weights * d)[:, None] * s                 # (N, 3)
    pix = _sanitize(pixels, npix)
    b = jax.ops.segment_sum(wd, pix, num_segments=npix)
    if axis_name is not None:
        b = jax.lax.psum(b, axis_name)
    a_reg = _tikhonov(state.ata, _ata_scale(state.ata))
    m = jnp.linalg.solve(a_reg, b[..., None])[..., 0]
    return jnp.where(state.rcond_ok[:, None], m, 0.0)


def destripe_pol(tod, pixels, weights, psi, npix: int,
                 offset_length: int = 50, n_iter: int = 100,
                 threshold: float = 1e-6, axis_name: str | None = None
                 ) -> PolDestriperResult:
    """Destripe a polarized TOD. ``psi``: f32[N] polarization/parallactic
    angle [rad]. Same contract as :func:`destriper.destripe` otherwise."""
    n = tod.shape[0]
    n_offsets = n // offset_length
    c2 = jnp.cos(2.0 * psi)
    s2 = jnp.sin(2.0 * psi)
    state = _pol_accumulate(pixels, weights, c2, s2, npix, axis_name)
    s_basis = _stokes_basis(c2, s2)

    def sample_iqu(m):
        safe = jnp.clip(pixels, 0, npix - 1)
        valid = ((pixels >= 0) & (pixels < npix)
                 & state.rcond_ok[safe])
        proj = jnp.sum(m[safe] * s_basis, axis=-1)
        return jnp.where(valid, proj, 0.0)

    def Z(d):
        m = pol_map_solve(d, pixels, weights, c2, s2, npix, state,
                          axis_name)
        return weights * (d - sample_iqu(m))

    def FT(wr):
        return jnp.sum(wr.reshape(n_offsets, offset_length), axis=1)

    def matvec(a):
        d = jnp.repeat(a, offset_length, total_repeat_length=n)
        return FT(Z(d))

    def dot(x, y):
        v = jnp.sum(x * y)
        return jax.lax.psum(v, axis_name) if axis_name is not None else v

    b = FT(Z(tod))
    # shared (P)CG driver: same breakdown guard and convergence test as
    # every other destriper solve (without a preconditioner, rz == rr,
    # so the criterion matches the old inline loop)
    a, rz, k, b_norm, _, _ = _cg_loop(matvec, b, dot, n_iter, threshold)

    # A constant offset vector is (near-)degenerate with the I map — the
    # Tikhonov floor in the map solve tips the balance so CG parks the
    # global mean in the offsets. Pin the offsets to zero mean (the
    # reference's maps carry the same convention: destriped maps are
    # defined up to a constant).
    tot = jnp.sum(a)
    cnt = jnp.asarray(n_offsets, tod.dtype)
    if axis_name is not None:
        tot = jax.lax.psum(tot, axis_name)
        cnt = jax.lax.psum(cnt, axis_name)
    a = a - tot / cnt

    template = jnp.repeat(a, offset_length, total_repeat_length=n)
    iqu_naive = pol_map_solve(tod, pixels, weights, c2, s2, npix, state,
                              axis_name)
    iqu_destriped = pol_map_solve(tod - template, pixels, weights, c2, s2,
                                  npix, state, axis_name)
    residual = jnp.sqrt(rz / jnp.maximum(b_norm, 1e-30))
    return PolDestriperResult(a, iqu_destriped, iqu_naive, state.hits,
                              state.rcond_ok, k, residual)


destripe_pol_jit = jax.jit(
    destripe_pol,
    static_argnames=("npix", "offset_length", "n_iter", "threshold",
                     "axis_name"))


def destripe_pol_planned(tod, weights, psi, plan, n_iter: int = 100,
                         threshold: float = 1e-6) -> PolDestriperResult:
    """Scatter-free polarized destriping on a :class:`PointingPlan`.

    The unpolarized planned path (``destriper.destripe_planned``)
    generalises: within a (pixel, offset) pair the Stokes basis varies
    per sample, so the pair aggregates become per-pair 3-vectors
    ``pws_k = sum_t w s_k`` and 6-vectors ``pwss`` (the unique entries
    of ``w s s^T``) — carried as LEADING axes through the same windowed
    one-hot binning (one one-hot per chunk, contracted against all
    Stokes rows in one MXU matmul). The per-pixel 3x3 systems are
    prefactored ONCE (masked inverse of the Tikhonov-regularised
    ``A_p``), so each CG iteration is binning + two small batched
    matmuls — no per-iteration scatter, no per-iteration solves.

    Same math as :func:`destripe_pol` (parity-tested); single-process,
    single-RHS (the sharded pol solve stays on the scatter path).

    Unlike the scatter oracle (deliberately plain CG), this path runs
    FLOORED-Jacobi-preconditioned CG: ``diag(A)`` comes exactly from
    the pair aggregates (``sum w`` per offset minus each pair's
    ``s^T A_p^{-1} s`` quadratic), but offsets more than
    ``1 - _POL_JACOBI_FLOOR`` absorbed by the per-pixel 3x3 blocks are
    scaled by the plain ``sum w`` instead — the pol pixels eat 3 DOF
    each, so near-degenerate offsets are common and an aggressive
    1/diag excites f32 breakdown within ~6 iterations (measured).
    Measured effect at the production budget: plain CG BREAKS DOWN
    mid-solve (iteration ~142, residual degrading 3.4e-3 -> 1.5e-2 and
    the I map error growing 14 -> 20); floored Jacobi keeps descending
    through the same budget (1.6e-3 at 150, map error still improving).
    A pol two-level coarse grid was prototyped and measured to add
    nothing over this (the slow modes are entangled with the
    ridge-regularised pixel blocks, not plain offset drifts) — not
    shipped.
    """
    if tod.ndim != 1:
        # a batched (nb, N) input would broadcast band rows against the
        # 3 Stokes rows and return plausible-looking garbage
        raise ValueError("destripe_pol_planned is single-RHS: tod must "
                         f"be 1-D, got shape {tod.shape}")
    dv = plan.device()
    f32 = tod.dtype
    n_off, n_rank = plan.n_offsets, plan.n_rank
    P_pad = int(dv["pair_rank"].shape[0])
    N_pad = int(dv["sample_perm"].shape[0])
    N = tod.shape[-1]

    perm = dv["sample_perm"]
    pad_mask = (jnp.arange(N_pad) < N).astype(f32)
    w_s = jnp.take(weights, perm, axis=-1) * pad_mask
    d_s = jnp.take(tod, perm, axis=-1)
    c2_s = jnp.take(jnp.cos(2.0 * psi), perm, axis=-1)
    s2_s = jnp.take(jnp.sin(2.0 * psi), perm, axis=-1)

    def pair_sum(v):
        return binned_window_sum(v, dv["sample_pair"], dv["sample_base"],
                                 plan.sample_window, plan.sample_chunk,
                                 P_pad)

    def rank_sum(pv):
        return binned_window_sum(pv, dv["pair_rank"], dv["rank_base"],
                                 plan.rank_window, plan.pair_chunk, n_rank)

    perm_off = dv["pair_perm_off"]
    po_off = jnp.take(dv["pair_offset"], perm_off, axis=-1)
    pr_off = jnp.take(dv["pair_rank"], perm_off, axis=-1)

    def off_sum(pv_off):
        return binned_window_sum(pv_off, po_off, dv["off_base"],
                                 plan.off_window, plan.pair_chunk, n_off)

    # -- one-time pair/rank aggregates: ONE stacked binning pass -------
    # rows 0-2: w*s_k (pws); 3-5: w*d*s_k (pwds); 6-8: w*[cc, cs, ss]
    # (the ss^T entries pws rows 0-2 don't already cover); 9: hit counts
    stacked = pair_sum(jnp.stack(
        [w_s, w_s * c2_s, w_s * s2_s,
         w_s * d_s, w_s * d_s * c2_s, w_s * d_s * s2_s,
         w_s * c2_s * c2_s, w_s * c2_s * s2_s, w_s * s2_s * s2_s,
         (w_s > 0).astype(f32)]))                        # (10, P_pad)
    pws = stacked[0:3]
    pwds = stacked[3:6]
    ranked = rank_sum(jnp.concatenate(
        [stacked[0:3], stacked[6:9], stacked[9:10]]))    # (7, n_rank)
    e0, e1, e2, e3, e4, e5 = ranked[:6]
    hits = ranked[6]
    ata = jnp.stack([jnp.stack([e0, e1, e2], -1),
                     jnp.stack([e1, e3, e4], -1),
                     jnp.stack([e2, e4, e5], -1)], -2)   # (n_rank, 3, 3)
    scale, rcond_ok = _ata_scale_solvable(ata, hits)
    a_reg = _tikhonov(ata, scale)
    # masked prefactor: bad pixels read an all-zero inverse, so their
    # maps and per-sample projections vanish exactly like the scatter
    # path's rcond mask
    inv_a = jnp.where(rcond_ok[:, None, None], jnp.linalg.inv(a_reg), 0.0)

    pws_off = jnp.take(pws, perm_off, axis=-1)
    pwds_off = jnp.take(pwds, perm_off, axis=-1)
    diag = off_sum(pws_off[0])                           # sum_w per offset

    # exact diag(A): sum_w per offset minus each pair's s^T A_p^{-1} s
    # quadratic (the pol analogue of the unpolarized Jacobi correction),
    # FLOORED: see the docstring
    inv_a_off = jnp.take(inv_a, jnp.clip(pr_off, 0, n_rank - 1), axis=0)
    inv_a_off = jnp.where((pr_off < n_rank)[:, None, None], inv_a_off,
                          0.0)
    quad = jnp.einsum("pij,ip,jp->p", inv_a_off, pws_off, pws_off)
    inv_diag = _jacobi_inverse(diag - off_sum(quad), diag,
                               floor=_POL_JACOBI_FLOOR)

    def apply_precond(v):
        return v * inv_diag

    def solve_map(b_rank):
        """m = masked A^-1 b, (3, n_rank) -> (3, n_rank)."""
        return jnp.einsum("rkj,jr->kr", inv_a, b_rank)

    def gather_a(a):
        return jnp.take(a, jnp.clip(dv["pair_offset"], 0, n_off - 1),
                        axis=-1)

    def gather_m(m):
        return jnp.where(pr_off < n_rank,
                         jnp.take(m, jnp.clip(pr_off, 0, n_rank - 1),
                                  axis=-1), 0.0)

    def matvec(a):
        b_rank = rank_sum(pws * gather_a(a))             # (3, n_rank)
        m = solve_map(b_rank)
        return diag * a - off_sum(jnp.sum(
            pws_off * gather_m(m), axis=0))

    m_d = solve_map(rank_sum(pwds))                      # naive IQU
    b = off_sum(pwds_off[0]
                - jnp.sum(pws_off * gather_m(m_d), axis=0))

    a, rz, k, b_norm, _, _ = _cg_loop(
        matvec, b, lambda u, v: jnp.sum(u * v, axis=-1), n_iter,
        threshold, precond=apply_precond)
    # zero-mean pinning: same convention as the scatter path (a constant
    # offset vector is near-degenerate with the I map)
    a = a - jnp.mean(a)

    pair_res = pwds - pws * gather_a(a)
    iqu_destriped_c = solve_map(rank_sum(pair_res))      # (3, n_rank)

    uniq = dv["uniq_pixels"]

    def expand(cmp):
        return jnp.zeros(cmp.shape[:-1] + (plan.npix,), f32).at[
            ..., uniq].set(cmp, mode="drop", unique_indices=True)

    residual = jnp.sqrt(rz / jnp.maximum(b_norm, 1e-30))
    return PolDestriperResult(
        a, expand(iqu_destriped_c).T, expand(m_d).T,
        expand(hits), expand(rcond_ok.astype(f32)) > 0, k, residual)
