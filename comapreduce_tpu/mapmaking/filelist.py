"""Map-making filelist curation (``MapMaking/CreateFilelist.py`` parity).

Splits a Level-2 filelist into good/rejected sets by the white-noise
quality cut: median per-scan 1/f-fit white level (or the TOD auto-rms
fallback) under ``sigma_cut_mk`` millikelvin (reference threshold 4 mK,
``CreateFilelist.py:17-63``).
"""

from __future__ import annotations

import logging

import numpy as np

__all__ = ["noise_level_mk", "create_filelist", "write_filelist"]

logger = logging.getLogger("comapreduce_tpu")


def noise_level_mk(lvl2, band: int = 0) -> float:
    """Median white-noise level [mK] across feeds/scans of one band."""
    if "fnoise_fits/auto_rms" in lvl2:
        rms = np.asarray(lvl2["fnoise_fits/auto_rms"])[:, band]
        rms = rms[np.isfinite(rms) & (rms > 0)]
        if rms.size:
            return float(np.median(rms)) * 1e3
    tod = np.asarray(lvl2["averaged_tod/tod"])[:, band]
    vals = []
    for row in tod:
        nz = row[row != 0]
        n = nz.size // 2 * 2
        if n >= 2:
            vals.append(np.nanstd(nz[0:n:2] - nz[1:n:2]) / np.sqrt(2.0))
    return float(np.median(vals)) * 1e3 if vals else np.inf


def create_filelist(level2_files, band: int = 0,
                    sigma_cut_mk: float = 4.0,
                    prefetch: int = 0, cache=None):
    """Returns ``(good, rejected)`` file lists by the noise cut.

    ``prefetch``/``cache`` route the reads through the streaming ingest
    subsystem (``ingest.level2_stream``): curation ahead of a destriper
    run shares its :class:`~comapreduce_tpu.ingest.cache.BlockCache`,
    so the map-maker's first pass over the curated list skips the
    decode entirely."""
    from comapreduce_tpu.ingest import level2_stream

    good, rejected = [], []
    stream = level2_stream(level2_files, prefetch=prefetch, cache=cache)
    try:
        for item in stream:
            fname = item.filename
            try:
                if item.error is not None:
                    raise item.error
                sigma = noise_level_mk(item.payload, band)
            except (OSError, KeyError, IndexError) as exc:
                # IndexError: a band beyond the file's band count —
                # reject the file (and warn) rather than crash the
                # whole curation
                logger.warning("create_filelist: BAD FILE %s (%s)",
                               fname, exc)
                rejected.append(fname)
                continue
            (good if sigma < sigma_cut_mk else rejected).append(fname)
    finally:
        stream.close()  # stop the read-ahead worker deterministically
    return good, rejected


def write_filelist(path: str, files) -> None:
    with open(path, "w") as f:
        for line in files:
            f.write(f"{line}\n")
