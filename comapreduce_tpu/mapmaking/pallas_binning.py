"""Mosaic (Pallas-TPU) kernels for the destriper's pointing matvec.

ROOFLINE round 5 xprof pinned the fine-level CG matvec as gather-bound:
the merged one-hot contraction in ``pointing_plan.binned_window_sum``
re-materialises an ``(chunk, window)`` one-hot in HBM-visible form every
chunk, and the PR 6 multigrid V-cycle re-pays that cost ``2*mg_smooth``
extra times per iteration.  This module replaces the hot sums with real
VMEM kernels:

``binned_window_sum_pallas``
    Segment-accumulated scatter.  Pairs are pre-sorted by the plan so
    every chunk's ids live in one contiguous window ``[base[c],
    base[c]+window)``: the kernel DMAs that window of the output from
    HBM into VMEM scratch once per chunk, accumulates the chunk's
    contribution on the MXU (equality one-hot built transposed in
    registers, never round-tripped through HBM), and DMAs the window
    back — one read + one write of each output window per chunk instead
    of XLA's read-modify-write through the fori carry.  The sequential
    grid keeps overlapping windows race-free.  Ids outside the window
    (plan sentinels) drop, exactly like the XLA paths' one-hot
    mismatch / ``mode="drop"``; ids ``>= out_size`` land in the sliced-
    off alignment padding, mirroring the XLA paths' ``out_size +
    window`` staging buffer.

``windowed_gather_pallas``
    The mirror image for windowed gathers (``out[..., e] =
    src[..., ids[e]]``): DMA the source window once, select per element
    with a one-hot matmul.  Out-of-window ids return 0.0 — callers must
    only use this where sentinel lanes carry zero weight downstream
    (true for every plan-sorted gather in ``destriper.py``).

Mosaic in jax 0.4.37 lowers no gather/scatter/sort primitives, so both
kernels are built strictly from the demonstrated-lowerable set: async
copies with dynamic sublane/lane offsets, ``broadcasted_iota``
equality one-hots, ``dot_general`` (MXU), and static lane sub-slices
(dynamic LANE slicing is not lowerable — the chunk axis is walked by an
unrolled Python loop over static ``SUB``-wide tiles).

Exactness contract: the gather is bit-exact (each output element is one
``1.0 * src`` MXU product).  The scatter is exact up to f32 summation
order — the kernel accumulates ``chunk // SUB`` partial matmuls where
XLA contracts the whole chunk at once — so parity is pinned at an
accumulation-order rtol (see ``tests/test_pallas_binning.py``), the
same contract PR 4 pinned for ``pair_batch`` re-chunking.

Everything here must stay importable (and the ``interpret=True`` path
runnable) on CPU-only hosts: ``pl.pallas_call`` only lowers Mosaic when
actually compiled for TPU, and the trace-time gates in
``pointing_plan.binned_window_sum``/``destriper.destripe_planned`` keep
these kernels out of CPU jaxprs entirely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from comapreduce_tpu.ops.pallas_median import pallas_supported

__all__ = [
    "binned_window_sum_pallas",
    "windowed_gather_pallas",
    "pallas_binning_ok",
    "resolve_kernels",
    "binning_logical_bytes",
    "KERNELS_CHOICES",
    "MAX_PALLAS_BIN_WINDOW",
]

_ROWS = 8          # f32 sublane tile
_LANE = 128        # lane tile
# Hard cap on the scatter/gather window: beyond this even a one-row
# accumulator plus one one-hot sub-tile blows the VMEM budget.
MAX_PALLAS_BIN_WINDOW = 16384
# Conservative per-core VMEM budget for gating (bytes). Real cores have
# ~16 MiB; leave headroom for Mosaic's own double-buffering.
_VMEM_BUDGET = 8 * 1024 * 1024

KERNELS_CHOICES = ("auto", "xla", "pallas", "interpret")


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def resolve_kernels(kernels: str, platform: str | None = None) -> str:
    """Resolve the ``[Destriper] kernels`` knob to a concrete impl.

    ``auto`` becomes ``pallas`` when the (optionally overridden)
    platform is a TPU backend and ``xla`` everywhere else — the
    resolution happens at TRACE time, so with ``auto`` on a CPU host
    the Mosaic branch never enters the jaxpr and CPU behaviour is
    byte-identical to the pre-kernel pipeline.  ``platform`` is the
    mixed-host override threaded from ``destripe_planned(...,
    kernels_platform=)``.
    """
    if kernels not in KERNELS_CHOICES:
        raise ValueError(
            f"kernels must be one of {KERNELS_CHOICES}, got {kernels!r}")
    if kernels == "auto":
        return "pallas" if pallas_supported(platform=platform) else "xla"
    return kernels


def _pick_sub(chunk: int) -> int | None:
    """Static lane sub-tile width for walking the chunk axis.

    Mosaic cannot slice the lane axis dynamically, so the kernels unroll
    a Python loop over static ``SUB``-wide tiles; ``SUB`` must divide
    ``chunk`` and stay small enough that the ``(wp, SUB)`` one-hot fits
    VMEM at production windows."""
    if chunk <= 512:
        return chunk
    for s in (512, 256, 128):
        if chunk % s == 0:
            return s
    return None


def pallas_binning_ok(window: int, chunk: int, rows: int = 1,
                      interpret: bool = False) -> bool:
    """Trace-time gate: can the binning kernels handle this shape?

    Checks the structural constraints (a static sub-tile exists, the
    window is bounded) always, and the VMEM budget for the compiled
    path (``interpret=True`` skips the budget — the interpreter has no
    VMEM).  Mirrors ``pallas_window_ok`` for the median kernel: callers
    consult this BEFORE tracing so unsupported shapes silently keep the
    XLA path."""
    if window <= 0 or window > MAX_PALLAS_BIN_WINDOW:
        return False
    sub = _pick_sub(chunk)
    if sub is None:
        return False
    if interpret:
        return True
    if chunk % _LANE != 0:
        return False
    r8 = _round_up(max(rows, 1), _ROWS)
    wp = _round_up(window + _LANE - 1, _LANE)
    # acc scratch + one-hot sub-tile + double-buffered values block +
    # ids block
    need = 4 * (r8 * wp + wp * sub + 2 * r8 * chunk + 2 * chunk)
    return need <= _VMEM_BUDGET


def binning_logical_bytes(rows: int, M: int, window: int, chunk: int,
                          out_size: int) -> dict:
    """Accounted HBM traffic (bytes) for one scatter matvec, XLA fori
    path vs the Pallas kernel — the machine-independent quantity the
    kernels bench and ``tools/check_perf.py`` gate on."""
    n_chunks = M // chunk if chunk else 0
    r8 = _round_up(max(rows, 1), _ROWS)
    wp = _round_up(window + _LANE - 1, _LANE)
    out_pad = _round_up(out_size, _LANE) + wp
    xla = 4 * (rows * M + M                       # values + ids read
               + rows * (out_size + window)       # carry init
               + 2 * rows * window * n_chunks     # RMW window per chunk
               + rows * out_size)                 # final slice copy
    pallas = 4 * (r8 * M + M                      # values + ids read
                  + r8 * out_pad                  # aliased zeros init
                  + 2 * r8 * wp * n_chunks        # DMA in + out per chunk
                  + r8 * out_size)                # final slice copy
    return {"xla_bytes": int(xla), "pallas_bytes": int(pallas),
            "ratio": float(xla) / float(max(pallas, 1))}


def _scatter_kernel(b0_ref, bc_ref, ids_ref, v_ref, oz_ref, out_hbm,
                    acc_ref, sem_in, sem_out, *, window, wp, chunk, sub):
    del oz_ref  # aliased straight into out_hbm; never read as an input
    c = pl.program_id(0)
    b0 = b0_ref[c]
    bc = bc_ref[c]
    cp_in = pltpu.make_async_copy(out_hbm.at[:, pl.ds(b0, wp)], acc_ref,
                                  sem_in)
    cp_in.start()
    cp_in.wait()
    ids = ids_ref[...]                                 # (1, chunk) i32
    valid = (ids >= bc) & (ids < bc + window)
    # -1 never matches the iota rows, so sentinel lanes drop — the same
    # semantics as the XLA paths' one-hot mismatch / mode="drop"
    local = jnp.where(valid, ids - b0, -1)
    v = v_ref[...]                                     # (R8, chunk)
    row = jax.lax.broadcasted_iota(jnp.int32, (wp, sub), 0)
    for s in range(chunk // sub):
        loc_s = jax.lax.slice_in_dim(local, s * sub, (s + 1) * sub,
                                     axis=1)           # static lane slice
        oh_t = (loc_s == row).astype(jnp.float32)      # (wp, sub)
        v_s = jax.lax.slice_in_dim(v, s * sub, (s + 1) * sub, axis=1)
        acc_ref[...] += jax.lax.dot_general(
            v_s, oh_t, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)       # (R8, wp)
    cp_out = pltpu.make_async_copy(acc_ref, out_hbm.at[:, pl.ds(b0, wp)],
                                   sem_out)
    cp_out.start()
    cp_out.wait()


def binned_window_sum_pallas(values: jax.Array, ids: jax.Array,
                             base: jax.Array, window: int, chunk: int,
                             out_size: int,
                             interpret: bool = False) -> jax.Array:
    """Pallas segment scatter with ``binned_window_sum`` semantics.

    Same contract as ``pointing_plan.binned_window_sum``: ``values``
    f32[..., M] with ``M % chunk == 0``, ids of chunk ``c`` windowed in
    ``[base[c], base[c]+window)`` (sentinels outside drop).  Result
    matches the XLA paths to f32 accumulation-order rtol; see module
    docstring.  Callers gate on ``pallas_binning_ok`` first — this
    function raises on structurally unsupported shapes."""
    sub = _pick_sub(chunk)
    if sub is None or window <= 0 or window > MAX_PALLAS_BIN_WINDOW:
        raise ValueError(
            f"binned_window_sum_pallas: unsupported shape "
            f"(window={window}, chunk={chunk}); gate with "
            f"pallas_binning_ok() before calling")
    M = values.shape[-1]
    lead = values.shape[:-1]
    R = int(np.prod(lead)) if lead else 1
    if M == 0:
        return jnp.zeros(lead + (out_size,), jnp.float32)
    n_chunks = M // chunk
    R8 = _round_up(max(R, 1), _ROWS)
    wp = _round_up(window + _LANE - 1, _LANE)
    out_pad = _round_up(out_size, _LANE) + wp
    v = jnp.pad(values.reshape(R, M).astype(jnp.float32),
                ((0, R8 - R), (0, 0)))
    # Clamp window starts exactly like _binned_window_sum_fori: landing
    # positions stay absolute and out-of-range windows drop into the
    # alignment padding.  b0 is the 128-aligned DMA base.
    bc = jnp.clip(base, 0, out_size).astype(jnp.int32)
    b0 = (bc // _LANE) * _LANE
    oz = jnp.zeros((R8, out_pad), jnp.float32)
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, window=window, wp=wp,
                          chunk=chunk, sub=sub),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, chunk), lambda c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R8, chunk), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((R8, out_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R8, wp), jnp.float32),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA],
        input_output_aliases={4: 0},
        interpret=interpret,
    )(b0, bc, ids.reshape(n_chunks, chunk).astype(jnp.int32), v, oz)
    return out[:R, :out_size].reshape(lead + (out_size,))


def _gather_kernel(b0_ref, bc_ref, ids_ref, src_hbm, o_ref,
                   win_ref, sem, *, window, wp, chunk, sub):
    c = pl.program_id(0)
    b0 = b0_ref[c]
    bc = bc_ref[c]
    cp = pltpu.make_async_copy(src_hbm.at[:, pl.ds(b0, wp)], win_ref, sem)
    cp.start()
    cp.wait()
    ids = ids_ref[...]                                 # (1, chunk) i32
    valid = (ids >= bc) & (ids < bc + window)
    local = jnp.where(valid, ids - b0, -1)             # -1 -> all-zero col
    win = win_ref[...]                                 # (R8, wp)
    row = jax.lax.broadcasted_iota(jnp.int32, (wp, sub), 0)
    for s in range(chunk // sub):
        loc_s = jax.lax.slice_in_dim(local, s * sub, (s + 1) * sub,
                                     axis=1)
        oh = (loc_s == row).astype(jnp.float32)        # (wp, sub)
        o_ref[:, s * sub:(s + 1) * sub] = jax.lax.dot_general(
            win, oh, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)       # (R8, sub)


def windowed_gather_pallas(src: jax.Array, ids: jax.Array,
                           base: jax.Array, window: int, chunk: int,
                           interpret: bool = False) -> jax.Array:
    """``out[..., e] = src[..., ids[e]]`` for plan-sorted windowed ids.

    The dual of ``binned_window_sum_pallas``: chunk ``c``'s ids live in
    ``[base[c], base[c]+window)``, so the kernel DMAs one source window
    per chunk and selects with a one-hot MXU product — bit-exact for
    in-window ids (one ``1.0 * src`` term each).  OUT-OF-WINDOW IDS
    RETURN 0.0, unlike ``jnp.take(src, clip(ids, 0, S-1))`` which
    returns a clamped element — callers must only substitute this where
    sentinel lanes carry zero weight downstream (the destriper's
    ground-pickup gathers, where ``paz_off``/``pair_w_off`` are zero at
    padding pairs)."""
    sub = _pick_sub(chunk)
    if sub is None or window <= 0 or window > MAX_PALLAS_BIN_WINDOW:
        raise ValueError(
            f"windowed_gather_pallas: unsupported shape "
            f"(window={window}, chunk={chunk}); gate with "
            f"pallas_binning_ok() before calling")
    S = src.shape[-1]
    lead = src.shape[:-1]
    M = ids.shape[0]
    R = int(np.prod(lead)) if lead else 1
    if M == 0:
        return jnp.zeros(lead + (0,), jnp.float32)
    n_chunks = M // chunk
    R8 = _round_up(max(R, 1), _ROWS)
    wp = _round_up(window + _LANE - 1, _LANE)
    S_pad = _round_up(max(S, 1), _LANE) + wp
    s2 = jnp.pad(src.reshape(R, S).astype(jnp.float32),
                 ((0, R8 - R), (0, S_pad - S)))
    bc = jnp.clip(base, 0, S).astype(jnp.int32)
    b0 = (bc // _LANE) * _LANE
    out = pl.pallas_call(
        functools.partial(_gather_kernel, window=window, wp=wp,
                          chunk=chunk, sub=sub),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, chunk), lambda c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((R8, chunk), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R8, M), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R8, wp), jnp.float32),
                        pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )(b0, bc, ids.reshape(n_chunks, chunk).astype(jnp.int32), s2)
    return out[:R, :M].reshape(lead + (M,))
