"""Level-2 files -> flat destriper vectors (``MapMaking/COMAPData.py``).

Capability parity with ``read_comap_data`` (``COMAPData.py:471-577``) and
``get_tod`` (``:247-380``):

- per-file, per-feed extraction of the band's averaged TOD and weights;
- calibrator files use ``tod_original`` (no gain filter), field files get
  a rolling-median (400-sample) high-pass (``:255-258, 359-360``);
- spike-mask zero-weighting, first/last ``edge_frac`` of every scan
  zero-weighted, scans truncated to offset multiples (``countDataSize``,
  ``:163-187``);
- astronomical calibration factors applied when present, bad feeds
  dropped (``:238-244, 306-314``);
- WCS or HEALPix pixelisation with optional celestial->galactic rotation
  (``read_pixels``/``read_pixels_healpix``, ``:383-469``);
- HEALPix seen-pixel compaction: the destriper solves on the compact
  pixel set and maps re-expand on write (``:43-70, 570-574`` — the
  reference allgathers seen pixels across ranks; here each host compacts
  its own shard and the sharded destriper psums compact maps over a
  shared index space built host-side).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.astro.coordinates import e2g
from comapreduce_tpu.data.level import COMAPLevel2
from comapreduce_tpu.mapmaking import healpix as hp
from comapreduce_tpu.mapmaking.pixel_space import PixelSpace
from comapreduce_tpu.mapmaking.wcs import WCS
from comapreduce_tpu.ops.median_filter import rolling_median
from comapreduce_tpu.resilience.tripwires import scrub_tod_host

__all__ = ["DestriperData", "read_comap_data", "scan_speed_mask",
           "sun_centric_coords", "export_madam"]

logger = logging.getLogger("comapreduce_tpu")


def sun_centric_coords(ra_deg, dec_deg, mjd0: float):
    """Rotate RA/Dec into sun-relative coordinates: the sun (at ``mjd0``,
    from the framework's own ephemeris) sits at (lon, lat) = (0, 0).

    Parity: ``get_sun_centric_coords`` (``COMAPData.py:213-232``), which
    rotates with healpy's Rotator about the astropy sun position at the
    first sample. Here it is the framework's own ephemeris
    (``astro.core.sun_position``) + the tested source-relative rotation
    (``astro.coordinates.rotate``) — no healpy/astropy. NaN pointing
    rides through as NaN. Returns (lon, lat) in degrees, lon in
    (-180, 180].
    """
    from comapreduce_tpu.astro.coordinates import rotate
    from comapreduce_tpu.astro.core import sun_position

    ra_s, dec_s, _ = sun_position(np.atleast_1d(float(mjd0)))
    return rotate(np.asarray(ra_deg, np.float64),
                  np.asarray(dec_deg, np.float64),
                  float(np.degrees(ra_s[0])), float(np.degrees(dec_s[0])))


@dataclass
class DestriperData:
    """Flat, concatenated inputs for the destriper."""

    tod: np.ndarray            # f32[N]
    pixels: np.ndarray         # i32[N] (solver ids: compact when
    #                            pixel_space.compacted)
    weights: np.ndarray        # f32[N]
    ground_ids: np.ndarray     # i32[N] — per (file, feed) group
    az: np.ndarray             # f32[N] — normalised azimuth per group
    n_groups: int
    npix: int                  # solver segment count (= n_compact when
    #                            compacted — the dense sky count never
    #                            reaches the solver)
    wcs: WCS | None = None
    nside: int | None = None
    sky_pixels: np.ndarray | None = None  # compact -> sky pixel id
    files: list = field(default_factory=list)
    # the seen-pixel dictionary the solver ids live in; None = dense
    # (legacy WCS default). Writers scatter compact maps to the sky
    # through it at write time (PixelSpace.expand) — the only place an
    # npix_sky-sized vector may exist.
    pixel_space: PixelSpace | None = None
    # per ground-id group (one per kept (file, feed), in ground_ids
    # order): {"file": basename, "feed": i, "sample_rate": Hz,
    # "n_samples": kept samples} — the noise_weight builder joins these
    # against the quality ledger's per-(file, feed, band) 1/f fits
    groups: list = field(default_factory=list)

    def expand_map(self, compact_map: np.ndarray) -> np.ndarray:
        """Compact-pixel map -> full-sky-indexable (pixels, values)."""
        if self.sky_pixels is None:
            return compact_map
        return compact_map  # values already align with ``sky_pixels``


def _truncated_scan_mask(edges: np.ndarray, T: int, offset_length: int,
                         edge_frac: float):
    """(use, wzero): use[t] selects samples kept (scans truncated to offset
    multiples); wzero[t] marks the first/last ``edge_frac`` of each scan
    (kept but zero-weighted, ``COMAPData.py:332-366``)."""
    use = np.zeros(T, bool)
    wzero = np.zeros(T, bool)
    for s, e in edges:
        L = ((e - s) // offset_length) * offset_length
        if L <= 0:
            continue
        use[s:s + L] = True
        k = int(L * edge_frac)
        if k > 0:
            wzero[s:s + k] = True
            wzero[s + L - k:s + L] = True
    return use, wzero


def scan_speed_mask(az: np.ndarray, el: np.ndarray,
                    sample_rate: float = 50.0,
                    speed_range: tuple = (0.1, 0.45)) -> np.ndarray:
    """True where the on-sky scan speed is inside ``speed_range`` [deg/s]
    — masks azimuth-sweep turnarounds (``DataReader.py:332-336,386``)."""
    az = np.asarray(az, np.float64)
    el = np.asarray(el, np.float64)
    # unwrap: a sweep crossing 0/360 must not register as a 360 deg jump
    az = np.degrees(np.unwrap(np.radians(az), axis=-1))
    daz = np.gradient(az, axis=-1) * np.cos(np.radians(el))
    de = np.gradient(el, axis=-1)
    speed = np.hypot(daz, de) * sample_rate
    return (speed > speed_range[0]) & (speed < speed_range[1])


def _read_averaged(lvl2, band: int, tod_variant: str):
    """The gain-corrected Level-2 products: returns
    ``(tod[F,T] | None, weights[F,T], (F,B,T))`` for one band (None when
    the band is out of range)."""
    tod_all = np.asarray(lvl2["averaged_tod/tod"], np.float32)
    F, B, T = tod_all.shape
    if not 0 <= band < B:
        return None, None, (F, B, T)
    want_orig = (tod_variant == "original"
                 or (tod_variant == "auto" and lvl2.is_calibrator))
    if want_orig and "averaged_tod/tod_original" in lvl2:
        tod_fb = np.asarray(lvl2["averaged_tod/tod_original"],
                            np.float32)[:, band]
    elif tod_variant == "original":
        raise KeyError("averaged_tod/tod_original")
    else:
        tod_fb = tod_all[:, band]
    weights = np.asarray(lvl2["averaged_tod/weights"],
                         np.float32)[:, band].copy()
    return tod_fb, weights, (F, B, T)


def _read_frequency_binned(lvl2, band: int):
    """The plain ``Level1Averaging`` product: inverse-variance combine
    the coarse channels; the summed ``1/stddev^2`` doubles as the
    destriper weight (matching the reference's naive-weight convention
    for its no-gain-filter reductions).

    Returns ``(tod, weights, (F, B, T), n_masked[F])``: a non-finite
    coarse-channel sample is EXCLUDED from the combine (its inverse
    variance zeroed) — the old ``nan_to_num`` alone turned a NaN into
    value 0 under a live weight, biasing its pixel toward zero, the
    exact failure the tripwires exist to stop. A sample with every
    channel bad ends at weight 0. ``n_masked`` counts excluded channel
    samples per feed so the caller can ledger the unit."""
    x = np.asarray(lvl2["frequency_binned/tod"], np.float32)
    F, B, nb, T = x.shape
    if not 0 <= band < B:
        return None, None, (F, B, T), np.zeros(F, np.int64)
    x = x[:, band]                                        # (F, nb, T)
    s = np.asarray(lvl2["frequency_binned/tod_stddev"],
                   np.float32)[:, band]
    finite = np.isfinite(x) & np.isfinite(s)
    iv = np.where(finite & (s > 0), 1.0 / np.maximum(s, 1e-20) ** 2,
                  0.0)
    den = iv.sum(axis=1)                                  # (F, T)
    num = (np.nan_to_num(x) * iv).sum(axis=1)
    # den==0 samples carry zero weight downstream; their value is moot
    tod = num / np.maximum(den, 1e-30)
    n_masked = (~finite).sum(axis=(1, 2))
    return (tod.astype(np.float32), den.astype(np.float32), (F, B, T),
            n_masked)


def read_comap_data(filenames, band: int = 0, wcs: WCS | None = None,
                    nside: int | None = None, galactic: bool = False,
                    offset_length: int = 50, medfilt_window: int = 400,
                    edge_frac: float = 0.1, use_calibration: bool = True,
                    feed_mask: np.ndarray | None = None,
                    mask_turnarounds: bool = False,
                    speed_range: tuple = (0.1, 0.45),
                    sun_centric: bool = False,
                    min_sun_distance_deg: float = 10.0,
                    tod_variant: str = "auto",
                    prefetch: int = 0, cache=None,
                    resilience=None, compact="auto",
                    pixel_space: PixelSpace | None = None,
                    tod_dtype: str = "f32") -> DestriperData:
    """Read + flatten a filelist for one band. Exactly one of ``wcs`` /
    ``nside`` selects the pixelisation. ``mask_turnarounds`` zero-weights
    samples outside the ``speed_range`` deg/s scan-speed band (the legacy
    fg-survey pipeline's turnaround cut); the sample rate comes from each
    file's own MJD axis. ``sun_centric`` maps in sun-relative
    coordinates (per-file sun position at the first sample; parity
    ``COMAPData.py:326-327``) and zero-weights samples within
    ``min_sun_distance_deg`` of the sun (the reference's 10-degree cut,
    ``:335``); it overrides ``galactic``.

    ``tod_variant`` selects which Level-2 TOD product feeds the map (the
    reference chooses per use-case among the analogous datasets,
    ``COMAPData.py:255-258``):

    - ``"auto"`` (default): ``averaged_tod/tod``, switching calibrator
      files to ``averaged_tod/tod_original`` when present (the
      reference's ``use_gain_filter``/source rule);
    - ``"gain_filtered"``: always ``averaged_tod/tod``;
    - ``"original"``: always ``averaged_tod/tod_original``;
    - ``"frequency_binned"``: the plain (no gain-correction)
      ``Level1Averaging`` product — coarse channels are combined by
      inverse-variance (``1/stddev^2``) and those variances also supply
      the destriper weights (a frequency_binned-only store has no
      ``averaged_tod/weights``).

    ``prefetch >= 1`` reads ahead on a background thread (bounded queue
    of that depth) so HDF5 decode overlaps the per-file host compute;
    ``cache`` (a :class:`~comapreduce_tpu.ingest.cache.BlockCache`)
    lets multi-pass workloads — the per-band destriper loop over one
    filelist — skip redundant decode. Both paths share one iteration
    (``ingest.level2_stream``), so results are identical.

    ``compact`` selects the seen-pixel compaction
    (``mapmaking.pixel_space``): ``"auto"`` (default) compacts HEALPix
    (the survey regime — nside 4096 is ~201M sky pixels of which a
    field hits well under 1%) and keeps WCS dense (legacy default for
    small rasters); ``True``/``False`` force it either way. Compacted,
    the solver ids in ``pixels`` index the campaign-level seen-pixel
    dictionary (``pixel_space``) — the union of hit pixels across ALL
    files of this filelist — and ``npix`` is its ``n_compact``, so
    every downstream map vector is coverage-, never sky-, sized.
    ``pixel_space`` overrides the locally-built dictionary with a
    precomputed one (e.g. the union across every rank's filelist shard,
    ``pixel_space.build_seen_pixel_space``) so all ranks agree on the
    compacted ids and their partial maps coadd without re-indexing.

    ``resilience`` (a ``resilience.Resilience`` bundle) adds the fault
    layer: files the quarantine ledger marks bad are skipped without a
    read, transient read failures retry with backoff, injected chaos
    wraps the loader, failures are ledgered, any non-finite
    TOD/weight sample is zero-weighted (with a 'masked' ledger event
    naming the file/feed/band) before it can reach the destriper, and
    — with a watchdog configured — each read runs under the
    ``ingest.read`` soft/hard deadline: a hung read is cancelled
    (``HangError``, an ``OSError``, lands in the same per-file net
    below), retried with a fresh budget, and on exhaustion ledgered
    ``hang``/``rejected`` with the file excluded from this run's
    map.

    ``tod_dtype`` ("f32" default, "bf16") is the ``[Precision]``
    policy's storage dtype for the streamed TOD payloads
    (OPERATIONS.md §15): bf16 halves the shared multi-band cache's TOD
    bytes. The per-feed extraction below widens back to f32 on the
    host (``np.asarray(..., np.float32)``), so the flattened
    ``DestriperData`` vectors — and every solve — stay f32; bf16
    changes the stored/streamed representation only. Requires a
    compacted pixel space for HEALPix (see the CLI's combo check): the
    point of narrowing is memory headroom, which a dense nside-4096
    sky map vector would instantly squander."""
    from comapreduce_tpu.ingest import level2_stream

    if (wcs is None) == (nside is None):
        raise ValueError("pass exactly one of wcs= or nside=")
    variants = ("auto", "gain_filtered", "original", "frequency_binned")
    if tod_variant not in variants:
        raise ValueError(f"tod_variant must be one of {variants}")
    # validate the compaction knob BEFORE any file I/O (the section
    # rule: a typo'd knob fails before work starts, not after a
    # campaign-scale ingest)
    if isinstance(compact, str):
        c = compact.strip().lower()
        if c not in ("auto", "true", "false"):
            raise ValueError(f"compact must be auto|true|false, got "
                             f"{compact!r}")
        do_compact = (nside is not None) if c == "auto" else (c == "true")
    else:
        do_compact = bool(compact)
    if resilience is None:
        from comapreduce_tpu.resilience import Resilience

        resilience = Resilience()  # all capabilities off
    admitted = []
    for f in filenames:
        if resilience.admit(f):
            admitted.append(f)
        else:
            # same per-file visibility as Runner._admitted: a map
            # missing an observation must be traceable in THIS run's
            # log, not only in the end-of-run ledger summary
            logger.warning("%s is quarantined — skipping (re-admit "
                           "with --retry-quarantined)", f)
    filenames = admitted
    tods, pixs, wgts, gids, azs = [], [], [], [], []
    group = 0
    kept_files = []
    groups_meta = []
    stream = level2_stream(filenames, prefetch=prefetch, cache=cache,
                           tod_dtype=tod_dtype,
                           retry=resilience.retry,
                           chaos=resilience.chaos,
                           watchdog=resilience.watchdog,
                           on_hang=lambda f: resilience.record_hang(
                               f, stage="destriper.close",
                               message="loader never returned; "
                                       "prefetcher abandoned"))
    try:
        for item in stream:
            fname = item.filename
            if item.error is None:
                # a retry-saved read: bookkeeping only, never skipped
                resilience.record_recovered(fname, item.retries,
                                            stage="destriper.read")
            try:
                if item.error is not None:
                    raise item.error  # per-file: same handling as a
                    # decode error below; non-(OSError, KeyError)
                    # still propagates
                lvl2 = item.payload
                if tod_variant == "frequency_binned":
                    (tod_fb, weights, (F, B, T),
                     fb_masked) = _read_frequency_binned(lvl2, band)
                    for ifeed in np.flatnonzero(fb_masked):
                        logger.warning(
                            "%s: feed %d band %d: %d non-finite coarse-"
                            "channel sample(s) excluded from the "
                            "inverse-variance combine", fname, ifeed,
                            band, int(fb_masked[ifeed]))
                        resilience.record_masked(
                            fname, int(fb_masked[ifeed]),
                            stage="destriper.tripwire",
                            feed=int(ifeed), band=band)
                else:
                    tod_fb, weights, (F, B, T) = _read_averaged(
                        lvl2, band, tod_variant)
            except (OSError, KeyError) as exc:
                logger.warning("BAD FILE %s (%s)", fname, exc)
                resilience.record_failure(fname, exc,
                                          stage="destriper.read")
                continue
            if tod_fb is None:
                logger.warning("%s: band %d out of range", fname, band)
                continue

            def tripwire(t, w, ifeed, fname=fname):
                """Scrub one feed's samples to (value 0, weight 0);
                warn + ledger the (file, feed, band) unit when anything
                was masked. The ONE home for the rule — used before
                the median filter and again per feed at the end."""
                t2, w2, n_bad = scrub_tod_host(np.asarray(t),
                                               np.asarray(w))
                if n_bad:
                    logger.warning(
                        "%s: feed %d band %d: %d non-finite sample(s) "
                        "zero-weighted", fname, ifeed, band, n_bad)
                    resilience.record_masked(
                        fname, n_bad, stage="destriper.tripwire",
                        feed=int(ifeed), band=band)
                return t2, w2

            # numerical tripwire, BEFORE the rolling-median high-pass: a
            # NaN inside a filter window would shift every neighbouring
            # sample's filtered value (jnp sort parks NaNs at the end,
            # silently biasing the median) — the burst must become
            # (value 0, weight 0) before any cross-sample operator sees
            # it.
            if not (np.isfinite(tod_fb).all()
                    and np.isfinite(weights).all()):
                pairs = [tripwire(tod_fb[i], weights[i], i)
                         for i in range(tod_fb.shape[0])]
                tod_fb = np.stack([t for t, _ in pairs])
                weights = np.stack([w for _, w in pairs])
            is_cal = lvl2.is_calibrator
            src_name = lvl2.source_name
            edges = np.asarray(lvl2.scan_edges)
            use, wzero = _truncated_scan_mask(edges, T, offset_length, edge_frac)
            if not use.any():
                logger.warning("%s: no usable scans", fname)
                continue
            weights[:, wzero] = 0.0
            if "spikes/spike_mask" in lvl2:
                sm = np.asarray(lvl2["spikes/spike_mask"])[:, band] > 0
                weights[sm] = 0.0
            if use_calibration and "astro_calibration/calibration_factors" \
                    in lvl2:
                fac = np.asarray(
                    lvl2["astro_calibration/calibration_factors"])[:, band]
                good = np.asarray(
                    lvl2["astro_calibration/calibration_good"])[:, band] > 0
                safe = np.where(good & (fac > 0), fac, 1.0)
                tod_fb = tod_fb / safe[:, None].astype(np.float32)
                weights[~good] = 0.0
            if not is_cal and medfilt_window > 1:
                w = min(medfilt_window, max(3, T // 2 * 2 - 1))
                tod_fb = tod_fb - np.asarray(rolling_median(
                    jnp.asarray(tod_fb), w))
            ra = np.asarray(lvl2.ra, np.float64)
            dec = np.asarray(lvl2.dec, np.float64)
            az_full = np.asarray(lvl2.az, np.float64)
            if mask_turnarounds:
                el_full = np.asarray(lvl2.el, np.float64)
                mjd_t = np.asarray(lvl2.mjd, np.float64)
                dt = np.median(np.diff(mjd_t)) * 86400.0 if mjd_t.size > 1 \
                    else 0.02
                ok_speed = scan_speed_mask(az_full, el_full,
                                           sample_rate=1.0 / max(dt, 1e-6),
                                           speed_range=speed_range)
                weights[~ok_speed] = 0.0
            if sun_centric:
                from comapreduce_tpu.mapmaking.wcs import angular_separation

                mjd0 = float(np.asarray(lvl2.mjd, np.float64)[0])
                lon, lat = sun_centric_coords(ra, dec, mjd0)
                if min_sun_distance_deg > 0:
                    near = angular_separation(0.0, 0.0, lon, lat) \
                        < min_sun_distance_deg
                    weights[near] = 0.0
            else:
                lon, lat = (e2g(ra, dec) if galactic else (ra, dec))
            # per-file sample rate from the MJD axis (the quality
            # ledger's 1/f fits are in Hz; the noise_weight builder
            # needs the offset rate fs/L). 50 Hz is the COMAP default
            # when the store carries no usable time axis.
            try:
                mjd_t = np.asarray(lvl2.mjd, np.float64)
                dt_s = (np.median(np.diff(mjd_t)) * 86400.0
                        if mjd_t.size > 1 else 0.0)
                fs = 1.0 / dt_s if dt_s > 0 else 50.0
            except (AttributeError, KeyError, TypeError, ValueError):
                fs = 50.0
            for ifeed in range(F):
                if feed_mask is not None and not feed_mask[ifeed]:
                    continue
                w_f = weights[ifeed, use]
                if not (w_f > 0).any():
                    continue
                if wcs is not None:
                    pix = wcs.ang2pix(lon[ifeed, use], lat[ifeed, use])
                    pix = np.asarray(pix, np.int64)
                else:
                    pix = np.asarray(hp.ang2pix_lonlat(
                        nside, lon[ifeed, use], lat[ifeed, use]), np.int64)
                a = az_full[ifeed, use]
                throw = max(np.max(a) - np.min(a), 1e-3)
                a_norm = (2.0 * (a - np.min(a)) / throw - 1.0).astype(np.float32)
                # final guard behind the pre-filter scrub: catches
                # non-finites INTRODUCED since (a fully-masked median
                # window, a degenerate calibration factor). A non-finite
                # sample becomes (value 0, weight 0) — NOT value 0 with
                # live weight, which would bias the map at its pixel.
                t_f, w_f = tripwire(tod_fb[ifeed, use], w_f, ifeed)
                tods.append(t_f)
                pixs.append(pix)
                wgts.append(w_f)
                gids.append(np.full(w_f.size, group, np.int32))
                azs.append(a_norm)
                groups_meta.append({"file": os.path.basename(fname),
                                    "feed": int(ifeed),
                                    "sample_rate": float(fs),
                                    "n_samples": int(w_f.size)})
                group += 1
            kept_files.append(fname)
    finally:
        stream.close()  # stop the read-ahead worker even on an
        # exception the per-file (OSError, KeyError) net does not catch

    if not tods:
        raise RuntimeError("no usable data in filelist "
                           f"({len(filenames)} files)")
    tod = np.concatenate(tods)
    pixels = np.concatenate(pixs)
    weights = np.concatenate(wgts)
    ground_ids = np.concatenate(gids)
    az = np.concatenate(azs)

    npix_sky = wcs.npix if wcs is not None else hp.nside2npix(nside)
    if pixel_space is not None:
        if pixel_space.npix_sky != npix_sky:
            raise ValueError(f"pixel_space is over {pixel_space.npix_sky} "
                             f"sky pixels, the pixelisation has "
                             f"{npix_sky}")
        space = pixel_space
    elif do_compact:
        # seen-pixel compaction (COMAPData.py:43-70,570-574): the
        # campaign-level dictionary is the union over every file of
        # THIS filelist (pixels concatenated above)
        space = PixelSpace.from_pixels(pixels, npix_sky)
    else:
        space = PixelSpace.dense(npix_sky)
    # remap pointing ONCE (sky -> solver ids; invalid/unseen -> the
    # drop sentinel n_solve)
    pixels32 = space.remap(pixels)
    return DestriperData(tod=tod.astype(np.float32), pixels=pixels32,
                         weights=weights.astype(np.float32),
                         ground_ids=ground_ids, az=az, n_groups=group,
                         npix=space.n_solve, wcs=wcs, nside=nside,
                         sky_pixels=space.pixels, files=kept_files,
                         pixel_space=space, groups=groups_meta)


def export_madam(data: DestriperData, path: str) -> None:
    """Export flat destriper vectors as a MADAM-style NEST-ordered HDF5
    bundle (the ``ReadDataLevel2_MADAM`` role, ``DataReader.py:450-667``):
    per-sample tod/weight/NEST-pixel vectors plus the geometry needed by
    an external maximum-likelihood map-maker."""
    import h5py

    if data.nside is None:
        raise ValueError("MADAM export requires HEALPix pixelisation")
    if data.sky_pixels is not None:
        sky = data.sky_pixels[np.clip(data.pixels, 0, data.npix - 1)]
    else:   # dense (compact=False) healpix: solver ids ARE sky ids
        sky = np.clip(data.pixels, 0, data.npix - 1).astype(np.int64)
    invalid = data.pixels >= data.npix
    nest_pix = hp.ring2nest(data.nside, sky)
    nest_pix = np.where(invalid, -1, np.asarray(nest_pix))
    with h5py.File(path, "w") as f:
        f.create_dataset("tod", data=data.tod)
        f.create_dataset("pixels_nest", data=nest_pix.astype(np.int64))
        f.create_dataset("weights", data=data.weights)
        f.create_dataset("ground_ids", data=data.ground_ids)
        f.attrs["nside"] = data.nside
        f.attrs["ordering"] = "NESTED"
        f.attrs["n_files"] = len(data.files)
