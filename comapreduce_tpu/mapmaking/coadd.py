"""Co-add per-rank partial maps into one map.

A multi-process ``run_destriper`` launch shards the filelist and writes
``{prefix}_band{b}_rank{r}.fits`` per rank (``cli/run_destriper.py``);
the reference instead Allreduces into one map inside MPI
(``MapMaking/Destriper.py:61-75``). This module is the offline
equivalent: inverse-variance co-addition of the rank maps —

    map = sum_r w_r m_r / sum_r w_r,   w = WEIGHTS,  hits add

— for both the WCS FITS layout and the partial-sky HEALPix layout
(ranks may cover different pixel sets; the union is taken).

Inputs may also name a serving EPOCH (an ``epoch-NNNNNN`` directory, a
``manifest.json`` path, or an epochs root — resolved through the
``current`` pointer): the manifest's file census, not a glob, decides
which map products co-add (:func:`epoch_map_inputs`), so "co-add
everything in epoch N" cannot race a concurrent publish. A TILE source
(a tiles root or a tile manifest, ``tiles/``) also works: the map is
reassembled from its content-addressed tiles (bit-identical to the
FITS it was cut from), so a mirror holding only the tile tier can
co-add without the original epoch dirs.
"""

from __future__ import annotations

import os

import numpy as np

from comapreduce_tpu.mapmaking.fits_io import (read_fits_image,
                                               write_fits_image,
                                               write_healpix_map)
from comapreduce_tpu.mapmaking.healpix import nside2npix
from comapreduce_tpu.mapmaking.pixel_space import PixelSpace

__all__ = ["coadd_maps", "coadd_fits_files", "epoch_map_inputs"]

_WEIGHTED = ("DESTRIPED", "NAIVE")   # weight-averaged products
_SUMMED = ("WEIGHTS", "HITS")        # additive products


def coadd_maps(rank_maps: list[dict]) -> dict:
    """Inverse-variance co-add of per-rank map dicts (same pixel grid).

    Each dict holds flat/2-D arrays for ``DESTRIPED``/``NAIVE`` (map
    units), ``WEIGHTS`` (1/variance) and ``HITS``. Pixels with zero
    total weight come back 0 (the destriper's unhit convention).
    """
    if not rank_maps:
        raise ValueError("coadd_maps: no rank maps")
    w_tot = np.sum([np.asarray(m["WEIGHTS"], np.float64)
                    for m in rank_maps], axis=0)
    # DESTRIPED first: write_fits_image makes the first key the primary
    # HDU, and the rank maps (write_band_map, mirroring the reference
    # layout) lead with the destriped sky map
    out = {}
    for key in _WEIGHTED:
        if not all(key in m for m in rank_maps):
            continue
        num = np.sum([np.asarray(m[key], np.float64)
                      * np.asarray(m["WEIGHTS"], np.float64)
                      for m in rank_maps], axis=0)
        out[key] = np.where(w_tot > 0, num / np.maximum(w_tot, 1e-30),
                            0.0).astype(np.float32)
    out["WEIGHTS"] = w_tot.astype(np.float32)
    if all("HITS" in m for m in rank_maps):
        out["HITS"] = np.sum([np.asarray(m["HITS"], np.float64)
                              for m in rank_maps], axis=0).astype(
            np.float32)
    return out


def epoch_map_inputs(path: str, band: int | None = None) -> list[str]:
    """Map product paths named by a serving epoch's manifest.

    ``path`` may be an ``epoch-NNNNNN`` directory, a direct
    ``manifest.json`` path, or an epochs ROOT — the latter resolves
    through the ``current`` pointer (falling back to the newest
    complete epoch), so "co-add the currently-served maps" needs no
    epoch number. ``band`` filters to one band's products. Raises
    ``ValueError`` when no complete epoch is found — an epoch without
    a readable manifest is not a co-addable fact.
    """
    from comapreduce_tpu.serving.epochs import (EpochStore,
                                                read_epoch_manifest)

    p = str(path)
    man = read_epoch_manifest(p)
    if man is None and os.path.isdir(p):
        store = EpochStore(p)
        n = store.current()
        if n is None:
            n = store.latest()
        if n is not None:
            p = store.epoch_dir(n)
            man = store.manifest(n)
    if man is None:
        raise ValueError(f"coadd: {path} is not a complete epoch "
                         "(no readable manifest.json)")
    d = p if os.path.isdir(p) else os.path.dirname(p)
    maps = [str(m) for m in man.get("maps", [])]
    if band is not None:
        maps = [m for m in maps if f"band{int(band)}" in m]
    if not maps:
        raise ValueError(f"coadd: epoch manifest at {d} lists no map "
                         f"products" + (f" for band {band}"
                                        if band is not None else ""))
    return [os.path.join(d, m) for m in maps]


def _expand_inputs(inputs: list[str]) -> list[str]:
    """Resolve epoch references (dirs / manifest paths) among plain
    FITS inputs to the manifest-listed map products. Tile sources
    (a tiles root or a tile manifest — ``tiles.tiler``) pass through
    whole; the parse stage reassembles them."""
    from comapreduce_tpu.tiles.tiler import is_tile_source

    out: list[str] = []
    for p in inputs:
        if is_tile_source(p):
            out.append(p)
        elif os.path.isdir(p) or os.path.basename(p) == "manifest.json":
            out.extend(epoch_map_inputs(p))
        else:
            out.append(p)
    return out


def _parse_input(path: str) -> list:
    """One input -> ``read_fits_image``-shaped HDU tuples. A tile
    source reassembles through ``tiles.cutout.reconstruct_hdus`` —
    bit-identical to the FITS it was tiled from, so a tile manifest
    co-adds interchangeably with rank maps and epoch products."""
    from comapreduce_tpu.tiles.tiler import is_tile_source

    if is_tile_source(path):
        from comapreduce_tpu.tiles.cutout import reconstruct_hdus

        return reconstruct_hdus(path)
    return read_fits_image(path)


def coadd_fits_files(inputs: list[str], output: str) -> dict:
    """Co-add rank map FILES (all WCS or all partial-HEALPix) into
    ``output``; epoch directories / manifests among ``inputs`` expand
    to their manifest's map products (:func:`epoch_map_inputs`).
    Returns the co-added maps dict."""
    inputs = _expand_inputs(list(inputs))
    if not inputs:
        raise ValueError("coadd_fits_files: no inputs")
    # one parse per file; layout detected from the parsed headers so a
    # glob mixing HEALPix and WCS maps fails with a clear message
    parsed = [_parse_input(p) for p in inputs]
    is_hp = [hdus[0][1].get("PIXTYPE") == "HEALPIX" for hdus in parsed]
    if any(is_hp) and not all(is_hp):
        mixed = {p: ("healpix" if h else "wcs")
                 for p, h in zip(inputs, is_hp)}
        raise ValueError(f"coadd: mixed map layouts {mixed}")
    if all(is_hp):
        # union of the ranks' seen-pixel DICTIONARIES — partial maps
        # stay partial: every intermediate is union-of-coverage sized,
        # the dense sky vector (201M px at nside 4096) never exists
        loaded = []
        for hdus in parsed:
            maps = {n: d for n, _, d in hdus if n != "PIXELS"}
            pix = next(d for n, _, d in hdus if n == "PIXELS")
            hdr = hdus[0][1]
            loaded.append((maps, pix, hdr["NSIDE"],
                           hdr.get("ORDERING", "RING") == "NESTED"))
        nside, nest = loaded[0][2], loaded[0][3]
        for (_, _, ns, ne), path in zip(loaded[1:], inputs[1:]):
            if ns != nside or ne != nest:
                # name BOTH offenders: at campaign scale the glob spans
                # hundreds of rank files and "mixed nside" without a
                # filename is an hour of bisection
                raise ValueError(
                    f"coadd: mixed nside/ordering — {inputs[0]} is "
                    f"nside {nside} "
                    f"{'NESTED' if nest else 'RING'}, {path} is "
                    f"nside {ns} {'NESTED' if ne else 'RING'}")
        npix_sky = nside2npix(nside)
        for (_, pix, _, _), path in zip(loaded, inputs):
            bad = (np.asarray(pix) < 0) | (np.asarray(pix) >= npix_sky)
            if bad.any():
                # from_pixels would silently DROP these from the
                # dictionary and the remap below would then scatter out
                # of bounds — name the corrupt file instead
                raise ValueError(
                    f"coadd: {path} PIXELS outside [0, {npix_sky}) for "
                    f"nside {nside} (e.g. {int(np.asarray(pix)[bad][0])})"
                    " — corrupt partial map?")
        spaces = [PixelSpace.from_pixels(pix, npix_sky)
                  for _, pix, _, _ in loaded]
        union = spaces[0].union(*spaces[1:])
        rank_maps = []
        for (maps, pix, _, _), space in zip(loaded, spaces):
            # vectorised dictionary remap (rank ids -> union ids); the
            # per-pixel Python dict this replaces was O(coverage) hash
            # lookups per rank file
            sel = union.remap(pix)
            dense = {}
            for k, v in maps.items():
                full = np.zeros(union.n_compact, np.float64)
                full[sel] = v
                dense[k] = full
            rank_maps.append(dense)
        out = coadd_maps(rank_maps)
        write_healpix_map(output, out, union, nside, nest=nest)
        return out
    header = dict(parsed[0][0][1])
    rank_maps = [{name: data for name, _, data in hdus} for hdus in parsed]
    shape0 = rank_maps[0]["WEIGHTS"].shape
    for m, path in zip(rank_maps[1:], inputs[1:]):
        if m["WEIGHTS"].shape != shape0:
            raise ValueError(
                f"coadd: mixed map shapes — {inputs[0]} is {shape0}, "
                f"{path} is {m['WEIGHTS'].shape}")
    out = coadd_maps(rank_maps)
    keep = {k: header[k] for k in header
            if k.startswith(("CRVAL", "CRPIX", "CDELT", "CTYPE", "CUNIT"))}
    write_fits_image(output, out, header=keep)
    return out
