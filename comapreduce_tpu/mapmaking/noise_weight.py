"""Measured-noise banded offset weighting (``[Destriper] noise_weight``).

The destriper's normal operator ``F^T W Z F`` treats the offset
amplitudes as free parameters — the maximum-likelihood solution under
WHITE noise only. The production regime (MADAM, arXiv:astro-ph/0412517;
MAPPRAISER, arXiv:2112.03370) adds the measured correlated-noise prior:

    A' = F^T W Z F + C_a^{-1}

with ``C_a`` the offset-amplitude covariance implied by each
(file, feed, band)'s 1/f noise model. ``C_a`` is Toeplitz within one
(file, feed) group (stationary noise at the offset rate ``fs / L``), so
its inverse is well-approximated by a BANDED symmetric matrix: this
module assembles that band per group from the quality ledger's measured
``white_sigma/fknee_hz/alpha`` fits (PR 14) and hands the destriper the
``(c0, cs)`` storage its CG matvec applies in O(q · n_off)
(:func:`~comapreduce_tpu.mapmaking.destriper.destripe_planned`'s
``banded=``).

Layout contract (what makes the sharded apply purely local):

- ``c0`` f32[n_off] — the prior diagonal; exactly 0.0 on white-fallback
  groups and padding offsets (the prior contributes nothing there, so a
  run whose every group falls back is numerically identical to
  ``noise_weight = white`` — and :func:`build_banded_weight` returns
  ``None`` outright then, keeping the compiled program byte-identical).
- ``cs`` f32[q, n_off] — the upper off-diagonal bands,
  ``cs[j-1, i] = B[i, i+j]``; zeroed wherever ``i`` and ``i+j``
  straddle a (file, feed) group boundary or a shard boundary
  (``n_shards``), so no coupling ever crosses an ownership edge.

SPD is enforced per group by strict diagonal dominance: the truncated
band's off-diagonals are scaled so ``sum_j 2 |b_j| <= 0.95 b_0``
(Gershgorin then keeps every eigenvalue in ``[0.05, 1.95] b_0`` —
positive, and ``lambda(D^{-1}(A+B)) <= 2`` still holds, so the
multigrid smoother damping stays in its proven-safe range).

Every fallback is ledgered: the returned report names each
(file, feed) that kept white weighting and why — absent fit, flagged
record, unusable parameters, or a knee below the group's resolved
bandwidth.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["build_banded_weight", "stack_banded", "quality_index"]

# fraction of the diagonal the off-diagonal row sum may reach — strict
# diagonal dominance margin (see module docstring)
_DOMINANCE = 0.95


def quality_index(records: list, band: int) -> dict:
    """``{(file_basename, feed): record}`` for one band from
    :func:`~comapreduce_tpu.telemetry.quality.read_quality` output
    (already latest-wins per (file, feed, band))."""
    out = {}
    for rec in records:
        try:
            if int(rec.get("band", -1)) != int(band):
                continue
            key = (os.path.basename(str(rec.get("file", ""))),
                   int(rec.get("feed", -1)))
        except (TypeError, ValueError):
            continue
        out[key] = rec
    return out


def _band_coefficients(white_sigma: float, fknee_hz: float, alpha: float,
                       f_off: float, n_grid: int, bandwidth: int,
                       prior_scale: float) -> np.ndarray | None:
    """Toeplitz band ``[b_0, b_1, ..., b_q]`` of the inverse offset
    covariance for one stationary 1/f model, or ``None`` when the model
    carries no usable correlated power at the offset rate.

    The offset sequence is treated as a discrete series at rate
    ``f_off = fs / L`` whose correlated PSD is the measured red part
    ``sigma^2 (f / fknee)^alpha`` (per-sample convention — the white
    part already lives in ``F^T W F``). The inverse spectrum
    ``1 / P_a`` is sampled on an ``n_grid``-point rfft grid and
    inverse-transformed; lags past ``bandwidth`` are dropped and the
    rest rescaled for strict diagonal dominance (SPD by Gershgorin —
    exactness matters less than definiteness for a prior).
    """
    f_min = f_off / n_grid
    if not (white_sigma > 0 and fknee_hz > 0 and np.isfinite(alpha)
            and alpha < 0):
        return None
    if fknee_hz <= f_min:
        # the knee sits below the lowest represented offset-rate
        # frequency: correlated power < white everywhere in band — the
        # prior would be numerically void; keep white weighting
        return None
    freqs = np.fft.rfftfreq(n_grid, d=1.0 / f_off)
    f = np.maximum(freqs, f_min)          # clamp the DC bin
    p_a = (white_sigma ** 2) * (f / fknee_hz) ** alpha
    inv_p = 1.0 / np.maximum(p_a, 1e-300)
    row = np.fft.irfft(inv_p, n=n_grid)
    b = row[: bandwidth + 1].astype(np.float64) * float(prior_scale)
    if not (b[0] > 0 and np.isfinite(b).all()):
        return None
    off_sum = 2.0 * np.abs(b[1:]).sum()
    limit = _DOMINANCE * b[0]
    if off_sum > limit:
        b[1:] *= limit / off_sum
    return b


def build_banded_weight(groups: list, quality: list, n_offsets: int,
                        offset_length: int, band: int = 0,
                        bandwidth: int = 4, n_grid: int = 512,
                        n_shards: int = 1,
                        prior_scale: float = 1.0):
    """Assemble the ``(c0, cs)`` banded prior for one band's solve.

    Parameters
    ----------
    groups : ``DestriperData.groups`` — per ground-id group metadata
        ``{"file", "feed", "sample_rate", "n_samples"}`` in
        concatenation order (each group owns whole offsets; the data
        layer truncates scans to offset multiples).
    quality : :func:`~comapreduce_tpu.telemetry.quality.read_quality`
        records (any bands; filtered here).
    n_offsets : TOTAL offset count of the solve vector — the PADDED
        global count on sharded runs (``pad_for_shards`` quantum), so
        padding offsets land beyond every group and stay zero.
    offset_length, band : solve geometry / which band's fits to join.
    bandwidth : half-bandwidth ``q`` of the stored prior (lags 1..q).
    n_grid : rfft grid size for the inverse-spectrum transform.
    n_shards : zero couplings across ``n_offsets / n_shards``
        boundaries so the shard_map apply needs no halo exchange.
    prior_scale : overall multiplier on the prior (A/B runs).

    Returns ``(banded, report)``: ``banded`` is ``(c0, cs)`` float32
    arrays of shape ``(n_offsets,)`` / ``(bandwidth, n_offsets)``, or
    ``None`` when EVERY group fell back to white (callers then omit the
    kwarg entirely — byte-identical compiled program, exact parity).
    ``report`` is ``{"banded": n, "white": n, "fallbacks": [{"file",
    "feed", "reason"}, ...]}`` with one entry per white group —
    ``reason`` one of ``absent | flagged | bad_fit | fknee_low``.
    """
    L = int(offset_length)
    n_off = int(n_offsets)
    q = max(int(bandwidth), 1)
    qidx = quality_index(quality, band)
    c0 = np.zeros(n_off, np.float64)
    cs = np.zeros((q, n_off), np.float64)
    report = {"banded": 0, "white": 0, "fallbacks": []}

    def fallback(g, reason):
        report["white"] += 1
        report["fallbacks"].append({"file": g.get("file", "?"),
                                    "feed": int(g.get("feed", -1)),
                                    "reason": reason})

    o0 = 0
    for g in groups:
        ng = int(g.get("n_samples", 0)) // L
        if ng <= 0:
            continue
        o1 = min(o0 + ng, n_off)
        rec = qidx.get((os.path.basename(str(g.get("file", ""))),
                        int(g.get("feed", -1))))
        if rec is None:
            fallback(g, "absent")
        elif rec.get("flagged"):
            fallback(g, "flagged")
        else:
            try:
                sig = float(rec.get("white_sigma") or 0.0)
                fk = float(rec.get("fknee_hz") or 0.0)
                al = float(rec.get("alpha")
                           if rec.get("alpha") is not None else np.nan)
            except (TypeError, ValueError):
                sig, fk, al = 0.0, 0.0, np.nan
            fs = float(g.get("sample_rate", 50.0))
            f_off = fs / L if fs > 0 else 1.0 / L
            b = _band_coefficients(sig, fk, al, f_off, int(n_grid), q,
                                   prior_scale)
            if b is None:
                reason = ("fknee_low"
                          if (sig > 0 and fk > 0 and np.isfinite(al)
                              and al < 0) else "bad_fit")
                fallback(g, reason)
            else:
                report["banded"] += 1
                c0[o0:o1] = b[0]
                for j in range(1, q + 1):
                    if j < len(b) and o1 - j > o0:
                        # cs[j-1, i] couples i and i+j: the last j
                        # offsets of the group couple into the next
                        # group and stay zero
                        cs[j - 1, o0:o1 - j] = b[j]
        o0 += ng
    if report["banded"] == 0:
        return None, report
    # shard-boundary zeroing: offsets i and i+j in different shards
    # must not couple (each shard owns a contiguous n_off/n_shards run)
    ns = max(int(n_shards), 1)
    if ns > 1:
        if n_off % ns:
            raise ValueError(f"n_offsets={n_off} not divisible by "
                             f"n_shards={ns} — pass the padded global "
                             "offset count (pad_for_shards quantum)")
        per = n_off // ns
        idx = np.arange(n_off)
        for j in range(1, q + 1):
            cross = (idx // per) != ((idx + j) // per)
            cs[j - 1, cross] = 0.0
    return (c0.astype(np.float32), cs.astype(np.float32)), report


def stack_banded(banded_list: list):
    """Stack per-band ``(c0, cs)`` priors (some possibly ``None``) into
    ONE multi-RHS operand with a leading band axis — ``None`` entries
    become zero blocks (white weighting for that band). Returns ``None``
    when every band is ``None`` (callers then omit the kwarg — the
    multi-RHS analogue of the single-band exact-parity rule)."""
    if all(b is None for b in banded_list):
        return None
    shapes = [np.asarray(b[0]).shape[-1] for b in banded_list
              if b is not None]
    qs = [np.asarray(b[1]).shape[-2] for b in banded_list
          if b is not None]
    n_off, q = shapes[0], qs[0]
    if any(s != n_off for s in shapes) or any(x != q for x in qs):
        raise ValueError("per-band banded priors disagree on geometry")
    c0s, css = [], []
    for b in banded_list:
        if b is None:
            c0s.append(np.zeros(n_off, np.float32))
            css.append(np.zeros((q, n_off), np.float32))
        else:
            c0s.append(np.asarray(b[0], np.float32))
            css.append(np.asarray(b[1], np.float32))
    return np.stack(c0s), np.stack(css)
