"""Maximum-likelihood destriper: jitted conjugate-gradient solve.

TPU-native re-design of ``MapMaking/Destriper.py`` (Sutton et al. 2011
offset-model destriping). The model: ``d = P m + F a + n`` with ``F``
stretching one offset over ``L`` consecutive samples. Destriping solves the
normal equations

    F^T W Z F a = F^T W Z d,      Z = I - P (P^T W P)^{-1} P^T W

by CG (``Destriper.py:85-152``), where every matvec is:

    repeat (F) -> segment_sum to map (P^T W) -> normalize -> gather (P)
    -> subtract (Z) -> per-offset reduce (F^T W)

All device math. The reference's per-matvec MPI ``Gather+Bcast`` of the map
(``share_map`` :183-204) and per-iteration ``Allreduce`` scalars (:61-69)
become ``psum`` over the mesh axis when run under ``shard_map`` with the
time axis sharded (each shard owns whole offsets; the map and CG scalars
are the only shared objects — SURVEY.md §2.5).

The optional ground template (per-(obsid, feed) linear-in-azimuth terms,
``op_Ax_with_ground`` :265-336) adds a small replicated unknown block
solved jointly in the same CG.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.mapmaking.binning import (accumulate_weights, bin_map,
                                               naive_map, sample_map)
from comapreduce_tpu.mapmaking.pixel_space import PixelSpace, resolve_npix
from comapreduce_tpu.mapmaking.pointing_plan import (PointingPlan,
                                                     binned_window_sum)
from comapreduce_tpu.resilience.tripwires import scrub_tod

__all__ = ["CONFIG_KERNELS", "CONFIG_PRECONDITIONERS",
           "DestriperResult", "destripe",
           "destripe_jit", "destripe_planned", "ground_ids_per_offset",
           "build_coarse_preconditioner", "coarse_pattern",
           "multigrid_levels", "multigrid_patterns",
           "build_multigrid_hierarchy", "stack_multigrid",
           "MultigridUnavailable", "watched_solve",
           "save_solver_checkpoint", "load_solver_checkpoint"]

logger = logging.getLogger("comapreduce_tpu")


class MultigridUnavailable(ValueError):
    """The geometry admits no multigrid ladder (every offset-block
    level would have < 2 unknowns). A DEDICATED type so the config
    layer's Jacobi fallback catches exactly this refusal and never
    masks a genuine build bug (length mismatch, corrupt dictionary)
    as 'multigrid unavailable'."""

#: the config-level preconditioner names ([Destriper] preconditioner =,
#: BENCH_PRECOND) — ONE home so the CLI parser and bench can't drift
#: from each other. The SOLVER-level rule is narrower (_check_precond:
#: jacobi|none; twolevel = jacobi + coarse=...; multigrid = jacobi +
#: mg=...) by design.
CONFIG_PRECONDITIONERS = ("none", "jacobi", "twolevel", "multigrid")

#: ``[Destriper] kernels`` knob values (PR 11) — re-exported from the
#: kernel module (ONE home: ``pallas_binning.KERNELS_CHOICES``) so the
#: CLI parser, bench, and the solver entry points can't drift. ``auto``
#: resolves at trace time (Pallas on TPU, XLA elsewhere); ``interpret``
#: runs the Pallas kernels under the interpreter for CPU parity
#: testing. See ``mapmaking/pallas_binning.resolve_kernels``.
from comapreduce_tpu.mapmaking.pallas_binning import (    # noqa: E402
    KERNELS_CHOICES as CONFIG_KERNELS)

# CG divergence tripwire: a system is diverged when its true residual
# sits more than sqrt(DIVERGENCE_GROWTH)x above the best iterate's for
# DIVERGENCE_K CONSECUTIVE checks (and is not already converged). It
# then freezes at its best iterate and ``DestriperResult.diverged``
# reports it (the host-side fallback in cli/run_destriper re-solves
# under Jacobi). The thresholds are set from measured trajectories, not
# taste: |r| is not the quantity PCG minimises, and on the singular
# ground-template solves the TRUE residual of a perfectly healthy run
# spikes to ~90x its floor for one-two iterations before snapping back
# (tier-1 CES geometry, see ISSUE 2 notes) — so short streaks and big
# single spikes must NOT trip. A genuinely poisoned operator (non-SPD
# coarse inverse, skew-dominant matvec) grows monotonically without
# recovery and crosses 10x-in-norm-for-6-straight-checks within a
# handful of iterations.
DIVERGENCE_K = 6
DIVERGENCE_GROWTH = 100.0  # squared-norm factor over the best iterate


class DestriperResult(NamedTuple):
    """Everything ``destriper_iteration`` produces (``Destriper.py:402-453``)."""

    offsets: jax.Array        # f32[n_offsets]
    ground: jax.Array         # f32[n_groups, 2] (zeros if unused)
    destriped_map: jax.Array  # f32[npix]
    naive_map: jax.Array      # f32[npix]
    weight_map: jax.Array     # f32[npix]
    hit_map: jax.Array        # f32[npix]
    n_iter: jax.Array         # i32 — CG iterations actually run
    residual: jax.Array       # f32 — final |r|/|b|
    # i32 0/1 (per system for multi-RHS) — the CG divergence monitor
    # tripped and the result is the best iterate, not a converged one.
    # Trailing default keeps positional construction of the 8 original
    # fields working everywhere.
    diverged: jax.Array = 0
    # the seen-pixel dictionary when the solve ran in a COMPACTED
    # PixelSpace: host i64[n_compact] sky ids aligning with the compact
    # map vectors above. None inside jitted programs (a None leaf is an
    # empty pytree node, so shard_map out_specs are unchanged); host
    # wrappers attach it via `_replace` so writers/coadd can scatter to
    # the sky at write time without a side channel.
    sky_pixels: object = None
    # per-iteration CG histories when the solve ran with trace_iters>0:
    # (rr_hist, alpha_hist, beta_hist, b_norm) f32 arrays of shape
    # (trace_iters,) + system shape. None (an empty pytree node) when
    # untraced — sharded/scatter paths never set it, so out_specs and
    # the compiled programs are unchanged. Hosts render it into
    # solver.rank{r}.jsonl via telemetry.solver_trace.
    trace: object = None


def watched_solve(solve, watchdog=None, name: str = "mapmaking.cg_solve",
                  unit: str = ""):
    """Run one (jitted, device-driving) CG solve under a wall budget.

    Device compute cannot be cancelled in place, so this is the
    UNCANCELLABLE arm of the watchdog (``Watchdog.watch``): the soft
    deadline fires the structured ``stalled`` warning + ledger event
    mid-solve; a blown hard deadline sets ``state.hard_expired`` and
    the caller routes the late result through the SAME operator signal
    path as a tripped divergence monitor — a loud warning naming the
    band, never a silent late map. Completed solve durations feed the
    watchdog's adaptive percentile, so a campaign's per-CG budget
    tightens around measured behaviour (hard = p95 x scale, floored by
    config).

    Returns ``(result, state)``; ``state`` is None when unwatched.
    ONE home for the rule — ``cli.run_destriper.solve_band`` and the
    chaos drill must not drift apart.
    """
    if watchdog is None:
        return solve(), None
    with watchdog.watch(name, unit=unit) as state:
        result = solve()
    return result, state


def save_solver_checkpoint(path: str, offsets, n_done: int,
                           residuals, precond_id: str,
                           durable: bool = True) -> None:
    """Durably snapshot a partial CG solve: ``(x, iter, residual
    history, preconditioner id)`` — written every ``[Destriper]
    checkpoint_every`` iterations by the chunked solve in
    ``cli.run_destriper`` so a solve killed at iteration 140/142
    resumes from 140, not 0.

    Same discipline as every other checkpoint in the repo
    (``data/durable.py``): full write + fsync to a temp file, then an
    atomic replace — a SIGKILL mid-save leaves either the previous
    complete snapshot or a stray temp file, never a torn snapshot
    under the live name.
    """
    from comapreduce_tpu.resilience.integrity import committed_replace

    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".solver.", suffix=".tmp",
                               dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, schema=np.int64(1),
                     offsets=np.asarray(offsets),
                     n_done=np.int64(n_done),
                     residuals=np.asarray(residuals, dtype=np.float64),
                     precond_id=np.bytes_(
                         str(precond_id).encode("utf-8")))
        committed_replace(tmp, path, kind="solver", durable=durable)
        tmp = ""
    finally:
        if tmp:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load_solver_checkpoint(path: str,
                           precond_id: str | None = None) -> dict | None:
    """Read a solver snapshot; None when absent, torn, foreign-schema
    or written under a DIFFERENT preconditioner/geometry id (warm-
    starting CG from another operator's iterate is a correctness trap,
    not a resume) — every None falls back to a fresh solve, never an
    error: a corrupt snapshot must cost iterations, not the campaign.

    Returns ``{"offsets": f32[n], "n_done": int, "residuals":
    [float...], "precond_id": str}``.
    """
    from comapreduce_tpu.resilience.integrity import (
        CorruptArtifactError, drop_sidecar, verify_file)

    if not path or not os.path.exists(path):
        return None
    try:
        # verify-on-read: a bit-rotted snapshot must be detected here
        # and cost a cold solve — warm-starting CG from damaged floats
        # would converge to a silently wrong map
        verify_file(path, kind="solver")
    except CorruptArtifactError as exc:
        logger.warning("solver checkpoint %s failed its sha256 "
                       "manifest (%s); unlinking — the solve restarts "
                       "fresh", path, exc)
        try:
            os.unlink(path)
        except OSError:
            pass
        drop_sidecar(path)
        return None
    try:
        with np.load(path) as z:
            if int(z["schema"]) != 1:
                logger.warning("solver checkpoint %s: unknown schema "
                               "%s; starting fresh", path,
                               int(z["schema"]))
                return None
            snap = {
                "offsets": np.asarray(z["offsets"]),
                "n_done": int(z["n_done"]),
                "residuals": [float(v) for v in z["residuals"]],
                "precond_id": bytes(z["precond_id"].item()
                                    if z["precond_id"].shape == ()
                                    else z["precond_id"]
                                    ).decode("utf-8", "replace"),
            }
    except Exception as exc:
        logger.warning("solver checkpoint %s unreadable (%s: %s); "
                       "starting the solve fresh", path,
                       type(exc).__name__, exc)
        return None
    if precond_id is not None and snap["precond_id"] != str(precond_id):
        logger.warning(
            "solver checkpoint %s was written under %r but this solve "
            "is %r (preconditioner/geometry changed); starting fresh",
            path, snap["precond_id"], str(precond_id))
        return None
    return snap


def _expand(offsets, ground, ground_ids, az, n_samples, offset_length):
    """Apply the template operator: ``F a (+ G g)`` -> TOD domain."""
    d = jnp.repeat(offsets, offset_length, total_repeat_length=n_samples)
    if ground is not None:
        d = d + ground[ground_ids, 0] + ground[ground_ids, 1] * az
    return d


def _reduce(wr, ground_ids, az, n_offsets, offset_length, n_groups,
            with_ground, axis_name):
    """Apply the adjoint: TOD -> (per-offset sums, per-group az sums)."""
    a = jnp.sum(wr.reshape(n_offsets, offset_length), axis=1)
    if not with_ground:
        return a, None
    g0 = jax.ops.segment_sum(wr, ground_ids, num_segments=n_groups)
    g1 = jax.ops.segment_sum(wr * az, ground_ids, num_segments=n_groups)
    g = jnp.stack([g0, g1], axis=-1)
    if axis_name is not None:
        g = jax.lax.psum(g, axis_name)  # ground unknowns are replicated
    return a, g


def _dot(x, y, axis_name):
    """CG inner product over the (offsets, ground) unknown pytree.

    Offsets are shard-local (psum'd); the ground block is replicated
    across shards (already globally consistent, no psum).
    """
    s = jnp.sum(x[0] * y[0])
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
    if x[1] is not None:
        s = s + jnp.sum(x[1] * y[1])
    return s


def _dot_compensated(x, y, axis_name):
    """Compensated (float-float) variant of :func:`_dot` for the
    ``cg_dot = compensated`` precision policy (OPERATIONS.md §15).

    Each leaf is contracted with :func:`~comapreduce_tpu.ops.precision.
    precise_dot` (~f64 accuracy from f32 state); the cross-shard psum
    stays plain f32 — it sums one term per shard, so its rounding is
    negligible next to the per-leaf accumulation it replaces.
    """
    from comapreduce_tpu.ops.precision import precise_dot

    s = precise_dot(x[0], y[0])
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
    if x[1] is not None:
        s = s + precise_dot(x[1], y[1])
    return s


def _check_cg_dot(cg_dot: str) -> None:
    if cg_dot not in ("f32", "compensated"):
        raise ValueError(
            f"cg_dot must be 'f32' or 'compensated', got {cg_dot!r}")


def _jacobi_inverse(diag_a: jax.Array, diag_fwf: jax.Array,
                    floor: float = 1e-6) -> jax.Array:
    """1/diag(A) with fallbacks for degenerate offsets.

    An offset whose samples are alone in their pixels has A_oo ~ 0 (Z
    removes it entirely — a null direction): fall back to the plain
    F^T W F diagonal there, and to identity on zero-weight (padding)
    offsets. ``floor`` is the degeneracy cut as a fraction of the plain
    diagonal — 1e-6 for the intensity solves; the polarized path raises
    it to ``polarization._POL_JACOBI_FLOOR`` (pol pixels absorb 3 DOF
    each, and aggressive 1/diag on nearly-absorbed offsets excites f32
    CG breakdown)."""
    cut = floor * jnp.maximum(diag_fwf, 1e-30)
    safe = jnp.where(diag_a > cut, diag_a,
                     jnp.where(diag_fwf > 0, diag_fwf, 1.0))
    return 1.0 / safe


def _cg_loop(matvec, b, dot, n_iter: int, threshold: float, precond=None,
             x0=None, divergence_k: int = DIVERGENCE_K, trace_n: int = 0):
    """Shared (P)CG driver over an arbitrary pytree of unknowns.

    Both destriper paths (scatter and planned) use this one loop so the
    singular-system breakdown guard and convergence criterion cannot drift
    apart: the system is SPD but singular (a global constant offset is in
    the null space once Z removes the map mean), and in f32 roundoff can
    eventually push the search direction out of the range space and
    ``p^T A p`` to <= 0 — detect the breakdown and stop with the current
    iterate rather than dividing into a NaN. ``dot`` supplies the (possibly
    psum-reduced) inner product; ``precond`` an optional SPD ``M^{-1}``
    application (e.g. Jacobi). Convergence tests the TRUE residual norm
    ``|r|^2`` against ``threshold^2 |b|^2`` in both cases. Returns
    ``(x, rz, k, b_norm, diverged, trace)`` with ``rz = |r|^2``,
    ``diverged`` an i32 0/1 flag (per system) and ``trace`` either
    ``None`` (``trace_n=0``) or ``(rr_hist, alpha_hist, beta_hist)``
    per-iteration histories (see below).

    ``dot`` may return a BATCH of inner products (shape ``(nb,)`` for a
    multi-RHS solve over per-band leaves ``(nb, n)``): alpha/beta and the
    breakdown guard then act per system — equivalent to independent CG
    runs sharing one program — and the loop exits when every system has
    converged or broken down.

    Resilience additions (both cheap next to one matvec):

    - divergence monitor — ``divergence_k`` CONSECUTIVE checks with the
      true residual more than ``DIVERGENCE_GROWTH``x (squared) above
      the best iterate's mark the system diverged (a poisoned or
      indefinite preconditioner walks the iterate away from the
      solution and never recovers; healthy singular solves spike and
      snap back — see the constants' comment). A diverged system
      freezes like a breakdown and sets its flag.
    - best-iterate tracking — a DIVERGED system returns the iterate
      with the lowest true residual seen instead of the runaway one
      (healthy systems keep the plain final iterate); the host-side
      Jacobi fallback restarts from exactly this point.
    - ``x0`` — optional warm start (the fallback's restart vector);
      ``None`` keeps the zero start.
    - ``trace_n`` — static trace depth. When > 0 the loop carries
      ``(trace_n,) + shape(b_norm)`` f32 histories of the true residual
      ``|r|^2``, alpha and beta through the while-loop state (three
      scalar scatters per iteration per system — negligible next to one
      matvec) and the return gains them as a sixth element; 0 (the
      default) keeps the compiled program identical to the untraced one
      and returns ``None`` there. Iterations past ``trace_n`` overwrite
      the last slot so the array bound can never be exceeded; frozen
      (broken-down/diverged) systems keep their last recorded value,
      matching the state's own freeze semantics.
    """
    b_norm = dot(b, b)
    minv = precond if precond is not None else (lambda v: v)

    def bcast(s, leaf):
        """Align a per-system scalar (shape S) onto a leaf (S + trailing)."""
        s = jnp.asarray(s)
        return s.reshape(s.shape + (1,) * (leaf.ndim - s.ndim))

    def axpy(a, x, y):
        return jax.tree.map(lambda xi, yi: xi + bcast(a, xi) * yi, x, y)

    def sel_where(mask, new, old):
        return jax.tree.map(
            lambda a_, b_: jnp.where(bcast(mask, a_), a_, b_), new, old)

    def cond(state):
        rr, k, done = state[4], state[5], state[6]
        live = ~done & (rr > threshold**2 * jnp.maximum(b_norm, 1e-30))
        return (k < n_iter) & jnp.any(live)

    def body(state):
        (x, r, p, rz, rr, k, done, xb, rrb, inc, div, hist) = state
        q = matvec(p)
        pq = dot(p, q)
        ok = jnp.isfinite(pq) & (pq > 0) & ~done
        alpha = jnp.where(ok, rz / jnp.where(ok, pq, 1.0), 0.0)
        x_new = axpy(alpha, x, p)
        r_new = axpy(-alpha, r, q)
        z_new = minv(r_new)
        rz_new = dot(r_new, z_new)
        rr_new = dot(r_new, r_new)
        ok = ok & jnp.isfinite(rz_new) & jnp.isfinite(rr_new)
        # divergence monitor: count consecutive checks the residual
        # spends far above the best iterate's (not mere increases —
        # healthy singular solves have long non-monotone streaks; see
        # the DIVERGENCE_* constants). Already-converged systems are
        # exempt: f32 wobble at the floor is 'far above' a tiny best.
        not_conv = rr_new > threshold**2 * jnp.maximum(b_norm, 1e-30)
        elevated = ok & not_conv & (rr_new > DIVERGENCE_GROWTH * rrb)
        inc_new = jnp.where(elevated, inc + 1, jnp.where(ok, 0, inc))
        div_new = div | (inc_new >= divergence_k)
        # best-iterate tracking (live systems only)
        better = ok & (rr_new < rrb)
        xb_new = sel_where(better, x_new, xb)
        rrb_new = jnp.where(better, rr_new, rrb)
        beta = jnp.where(ok, rz_new / jnp.maximum(rz, 1e-30), 0.0)
        p_new = axpy(beta, z_new, p)
        # on breakdown OR divergence: freeze that system's iterate, keep
        # its last good residual, and (once every system is done) exit
        adv = ok & ~div_new
        if trace_n:
            rr_h, al_h, be_h = hist
            idx = jnp.minimum(k, trace_n - 1)
            hist = (rr_h.at[idx].set(jnp.where(adv, rr_new, rr)),
                    al_h.at[idx].set(alpha),
                    be_h.at[idx].set(beta))
        return (sel_where(adv, x_new, x), sel_where(adv, r_new, r),
                sel_where(adv, p_new, p),
                jnp.where(adv, rz_new, rz), jnp.where(adv, rr_new, rr),
                k + 1, done | ~ok | div_new, xb_new, rrb_new, inc_new,
                div_new, hist)

    if x0 is None:
        x_start = jax.tree.map(jnp.zeros_like, b)
        r0 = b
    else:
        x_start = x0
        q0 = matvec(x0)
        r0 = jax.tree.map(lambda bi, qi: bi - qi, b, q0)
    rr0 = dot(r0, r0)
    z0 = minv(r0)
    zeros = jnp.zeros(jnp.shape(b_norm))
    if trace_n:
        tshape = (int(trace_n),) + tuple(jnp.shape(b_norm))
        hist0 = (jnp.zeros(tshape, jnp.float32),
                 jnp.zeros(tshape, jnp.float32),
                 jnp.zeros(tshape, jnp.float32))
    else:
        hist0 = None  # empty pytree node: program identical to untraced
    state0 = (x_start, r0, z0, dot(r0, z0), rr0,
              jnp.asarray(0, jnp.int32), zeros.astype(bool),
              x_start, rr0, zeros.astype(jnp.int32), zeros.astype(bool),
              hist0)
    x, _, _, _, rr, k, _, xb, rrb, _, div, hist = jax.lax.while_loop(
        cond, body, state0)
    # a DIVERGED system hands back its best iterate, never the runaway
    # one. Healthy systems keep the final iterate untouched: in the
    # near-degenerate subspaces of these solves (ground template vs sky
    # gradient) iterates of almost equal residual differ meaningfully,
    # and swapping one in would silently move converged results.
    use_best = div & (rrb < rr)
    x = sel_where(use_best, xb, x)
    rr = jnp.where(use_best, rrb, rr)
    return x, rr, k, b_norm, div.astype(jnp.int32), hist


def _check_precond(precond: str, coarse=None, mg=None) -> str:
    """ONE home for the preconditioner-name rule (``destripe``,
    ``destripe_planned`` and the config layer must not drift):
    ``jacobi`` (default) | ``none``; the two-level preconditioner is
    Jacobi + the coarse correction, so ``coarse`` requires ``jacobi``;
    the multigrid V-cycle smooths with Jacobi, so ``mg`` requires
    ``jacobi`` too and excludes ``coarse`` (the coarsest V-cycle level
    IS the coarse solve — passing both would apply it twice)."""
    if precond not in ("jacobi", "none"):
        raise ValueError(f"precond must be 'jacobi' or 'none', got "
                         f"{precond!r} (the two-level preconditioner is "
                         "selected by passing coarse=..., the multigrid "
                         "one by passing mg=...)")
    if coarse is not None and precond != "jacobi":
        raise ValueError("the two-level preconditioner is additive over "
                         "Jacobi; coarse=... requires precond='jacobi'")
    if mg is not None and precond != "jacobi":
        raise ValueError("the multigrid V-cycle smooths with Jacobi; "
                         "mg=... requires precond='jacobi'")
    if mg is not None and coarse is not None:
        raise ValueError("pass coarse=... (two-level) OR mg=... "
                         "(multigrid), not both — the V-cycle's coarsest "
                         "level already is the coarse solve")
    return precond


def destripe(tod: jax.Array, pixels: jax.Array, weights: jax.Array,
             npix: int, offset_length: int = 50, n_iter: int = 100,
             threshold: float = 1e-6, axis_name: str | None = None,
             ground_ids: jax.Array | None = None,
             az: jax.Array | None = None, n_groups: int = 0,
             precond: str = "jacobi",
             kernels: str = "auto",
             cg_dot: str = "f32") -> DestriperResult:
    """Destripe a flat TOD vector.

    Parameters
    ----------
    tod, weights: f32[N] with ``N`` a multiple of ``offset_length``
        (the data layer truncates scans to offset multiples, the reference's
        ``countDataSize``, ``COMAPData.py:163-187``; zero-weight samples are
        ignored everywhere).
    pixels: i32[N]; invalid samples carry ``pixels >= npix``.
    npix: segment count of the map vectors — an int, or a
        :class:`PixelSpace` (content-hashable, so it rides the jit
        static argument like the int): a COMPACTED space solves over
        ``n_compact`` hit pixels with ``pixels`` already remapped
        through ``PixelSpace.remap`` (once, host-side); every map
        product comes back compact and the caller scatters to the sky
        at write time only.
    ground_ids, az: optional i32[N]/f32[N] enabling the joint ground
        template (az should be pre-normalised to ~[-1, 1]).
    axis_name: mesh axis name when called inside ``shard_map`` with the
        time/offset axis sharded.
    precond: ``"jacobi"`` (default) or ``"none"`` — plain CG without the
        diagonal scaling, for A/B runs and the
        ``[Destriper] preconditioner`` config knob. Same fixed point
        either way; only the iteration path changes.
    kernels: validated for parity with :func:`destripe_planned` but a
        NO-OP here — this scatter path is the oracle the Pallas kernels
        are tested against, and its per-sample scatter-adds have no
        windowed structure for them to exploit. The CLI threads the
        ``[Destriper] kernels`` knob to both entry points uniformly.
    cg_dot: ``"f32"`` (default, byte-identical to the pre-policy
        solver) or ``"compensated"`` — swap the CG recurrence dots for
        the float-float :func:`~comapreduce_tpu.ops.precision.
        precise_dot` so tight tolerances stop stalling at the f32
        rounding floor (``[Precision] cg_dot``, OPERATIONS.md §15).
    """
    _check_precond(precond)
    _check_cg_dot(cg_dot)
    from comapreduce_tpu.mapmaking.pallas_binning import resolve_kernels
    resolve_kernels(kernels)   # validate the knob; path unchanged
    n = tod.shape[0]
    n_offsets = n // offset_length
    with_ground = ground_ids is not None
    f32 = tod.dtype

    # numerical tripwire: one NaN/Inf sample would poison every CG inner
    # product — mask to (value 0, weight 0), exactly the solve on clean
    # data with those samples zero-weighted (resilience/tripwires.py)
    tod, weights = scrub_tod(tod, weights)

    sum_w = accumulate_weights(pixels, weights, npix, axis_name)

    def Zmap(d):
        """W Z d = W (d - P bin(d)) in the TOD domain."""
        m = bin_map(d, pixels, weights, npix, sum_w=sum_w,
                    axis_name=axis_name)
        return weights * (d - sample_map(m, pixels))

    def matvec(x):
        offs, grd = x
        d = _expand(offs, grd, ground_ids, az, n, offset_length)
        return _reduce(Zmap(d), ground_ids, az, n_offsets, offset_length,
                       n_groups, with_ground, axis_name)

    b = _reduce(Zmap(tod), ground_ids, az, n_offsets, offset_length,
                n_groups, with_ground, axis_name)

    # Jacobi preconditioner. True diagonal: A_oo = sum_i w_i -
    # sum_p w_po^2 / sumw_p; without pair aggregates the correction is
    # approximated per sample (sum_i w_i^2 / sumw_{pix_i} <= the true
    # correction), which overestimates diag(A) — still SPD, still a valid
    # (slightly weaker) preconditioner. The planned path uses the exact
    # pair form.
    if precond == "none":
        precond_fn = None
    else:
        inv_sw = jnp.where(sum_w > 0, 1.0 / jnp.maximum(sum_w, 1e-30), 0.0)
        d_fwf = jnp.sum(weights.reshape(n_offsets, offset_length), axis=1)
        corr = jnp.sum((weights * weights
                        * sample_map(inv_sw, pixels)
                        ).reshape(n_offsets, offset_length), axis=1)
        inv_diag = _jacobi_inverse(d_fwf - corr, d_fwf)

        def precond_fn(v):
            # identity on the ground block, deliberately: the unprojected
            # G^T W G diagonal overestimates the true (Z-projected) ground
            # diagonal by orders of magnitude when the template is nearly
            # degenerate with the sky, and scaling by it starves those ~2 *
            # n_groups directions (measured: ground slopes collapse from the
            # injected truth to ~0). With only a handful of ground unknowns,
            # unpreconditioned directions cost a few CG iterations at most.
            return (v[0] * inv_diag, v[1])

    dot = (_dot_compensated if cg_dot == "compensated" else _dot)
    x, rz, k, b_norm, diverged, _ = _cg_loop(
        matvec, b, lambda u, v: dot(u, v, axis_name), n_iter, threshold,
        precond=precond_fn)
    offsets, ground = x

    # final products
    template = _expand(offsets, ground, ground_ids, az, n, offset_length)
    m_naive, w_map, h_map = naive_map(tod, pixels, weights, npix, axis_name,
                                      sum_w=sum_w)
    m_destriped = bin_map(tod - template, pixels, weights, npix,
                          sum_w=sum_w, axis_name=axis_name)
    if ground is None:
        ground = jnp.zeros((0, 2), f32)
    residual = jnp.sqrt(rz / jnp.maximum(b_norm, 1e-30))
    return DestriperResult(offsets, ground, m_destriped, m_naive, w_map,
                           h_map, k, residual, diverged)


destripe_jit = jax.jit(
    destripe,
    static_argnames=("npix", "offset_length", "n_iter", "threshold",
                     "axis_name", "n_groups", "precond", "kernels",
                     "cg_dot"))


def ground_ids_per_offset(ground_ids: np.ndarray,
                          offset_length: int) -> np.ndarray:
    """Per-offset ground-group ids from per-sample ids (host helper).

    The planned ground solve needs each offset to live inside ONE group;
    the data layer guarantees it (scans are truncated to offset
    multiples per (file, feed) group, ``COMAPData.py:163-187``), and
    this validates rather than assumes."""
    ids = np.asarray(ground_ids)
    n = (ids.shape[0] // offset_length) * offset_length
    blocks = ids[:n].reshape(-1, offset_length)
    if not (blocks == blocks[:, :1]).all():
        raise ValueError("ground_ids change inside an offset; the "
                         "planned ground solve needs offset-aligned "
                         "groups (use the scatter path)")
    return blocks[:, 0].astype(np.int32)


def coarse_pattern(pixels, npix: int, offset_length: int,
                   block: int = 32, max_coarse: int = 4096) -> dict:
    """Weights-independent half of the coarse-preconditioner build: the
    clipped pixel stream, offset/block maps, and the sorted
    (pixel, coarse-block) index pattern. A multi-band joint solve shares
    ONE pattern (pixels are band-invariant) and runs only the per-band
    weight bincounts through :func:`build_coarse_preconditioner`.
    ``npix`` may be a :class:`PixelSpace` (compacted solves build their
    coarse systems over ``n_compact`` pixels — the bincounts below are
    coverage-, never sky-, sized)."""
    npix = resolve_npix(npix)
    pixels = np.asarray(pixels)
    L = int(offset_length)
    n = (pixels.size // L) * L
    pixels = pixels[:n]
    bad = (pixels < 0) | (pixels >= npix)
    pix = np.clip(pixels, 0, npix - 1).astype(np.int64)
    n_off = n // L
    K = max(int(block), 1)
    while -(-n_off // K) > max_coarse:
        K *= 2
    off_id = np.arange(n) // L
    grp = (np.arange(n_off) // K).astype(np.int32)
    n_c = int(grp[-1]) + 1 if n_off else 1
    key = pix * n_c + grp[off_id]
    uk, inv = np.unique(key, return_inverse=True)
    return {"n": n, "bad": bad, "pix": pix, "off_id": off_id,
            "grp": grp, "n_c": n_c, "inv": inv,
            "rows": uk // n_c, "cols": uk % n_c, "npix": int(npix),
            "offset_length": L, "block": int(block)}


def build_coarse_preconditioner(pixels, weights, npix: int,
                                offset_length: int, block: int = 32,
                                ridge: float = 3e-3,
                                max_coarse: int = 4096,
                                pattern: dict | None = None):
    """Two-level (coarse-offset) preconditioner setup — host side, f64.

    The destriper normal matrix's small eigenvalues live on LONG offset
    drifts (the large-scale stripes): Jacobi-preconditioned CG stalls on
    them (measured: residual floor ~3e-5 after 400 iterations on a
    production-like 1/f problem, while threshold 1e-6 is the production
    spec). The coarse space P averages ``block`` consecutive offsets and
    the Galerkin coarse matrix ``A_c = P^T A P`` is assembled EXACTLY
    from the (pixel, coarse-block) pair aggregates::

        A_c = diag(sum w per block) - Mat^T diag(1/sumw_pix) Mat,
        Mat[pix, c] = sum of weights of block c's samples in pix

    (same algebra as the fine system, one level up). The
    global-constant null mode is pinned by a RANK-ONE shift along the
    constant vector (+ mean(diag) * 11^T / n_c — exact for the null
    direction, leaves every other eigenvalue untouched) plus a ``ridge``
    sized for f32: small ridges (<=1e-3) leave A_c^-1 ill-conditioned
    enough that the f32 preconditioner application can lose
    positive-definiteness and trip the PCG breakdown guard mid-solve
    (observed on two raster geometries; rounding-order dependent).
    3e-3, with the inverse symmetrised after the f32 cast, converged on
    every geometry tested at a few extra iterations. Inverted once
    (n_c <= ``max_coarse``; ``block`` doubles as needed). The additive
    correction ``M^-1 v = v/diag(A) + P A_c^-1 P^T v`` then costs one
    tiny segment-sum + an (n_c, n_c) matmul per CG iteration — measured
    to take the same f32 problem from "never converges past 2.7e-5
    (400 iterations)" to threshold 1e-6 in 181 iterations at these
    defaults (see ROOFLINE.md round-5 notes).

    Returns ``(grp, ac_inv)`` for :func:`destripe_planned`'s ``coarse``
    argument: ``grp`` i32[n_off] (offset -> coarse block) and ``ac_inv``
    f32[n_c, n_c]. Build once per (pointing, weights); bands with their
    own weights need their own ``ac_inv`` (stack them (nb, n_c, n_c)
    for a multi-RHS solve), sharing one :func:`coarse_pattern` so the
    pixel-side sort/unique work is not repeated per band.

    Method lineage (public map-making literature, PAPERS.md): two-grid /
    multigrid map-making CG (MAPCUMBA, astro-ph/0101112), coarse-mode
    deflation preconditioners for scanning patterns (arXiv:1309.7473)
    and the two-level preconditioners in MAPPRAISER
    (arXiv:2112.03370); the pair-aggregate Galerkin assembly and the
    TPU-side application are this framework's own.
    """
    import scipy.sparse as sp

    npix = resolve_npix(npix)
    if pattern is None:
        pattern = coarse_pattern(pixels, npix, offset_length,
                                 block=block, max_coarse=max_coarse)
    elif (pattern["npix"] != int(npix)
          or pattern["offset_length"] != int(offset_length)
          or pattern["block"] != int(block)):
        raise ValueError(
            "pattern geometry mismatch: built for (npix, offset_length,"
            f" block) = ({pattern['npix']}, {pattern['offset_length']},"
            f" {pattern['block']}), called with ({npix},"
            f" {offset_length}, {block})")
    n, pix, off_id = pattern["n"], pattern["pix"], pattern["off_id"]
    if np.asarray(weights).shape[0] < n:
        raise ValueError(f"weights size {np.asarray(weights).shape[0]} "
                         f"< pattern sample count {n}")
    grp, n_c = pattern["grp"], pattern["n_c"]
    n_off = grp.size
    weights = np.asarray(weights, np.float64)[:n].copy()
    # sentinel/out-of-range pixels carry zero weight (the solver's rule)
    weights[pattern["bad"]] = 0.0

    sw_pix = np.bincount(pix, weights=weights, minlength=npix)
    inv_sw = np.where(sw_pix > 0, 1.0 / np.maximum(sw_pix, 1e-30), 0.0)
    sw_off = np.bincount(off_id, weights=weights, minlength=n_off)
    # (pixel, coarse) pair weights in one pass over the samples
    mw = np.bincount(pattern["inv"], weights=weights)
    mat = sp.coo_matrix((mw, (pattern["rows"], pattern["cols"])),
                        shape=(npix, n_c)).tocsr()
    d_c = np.bincount(grp, weights=sw_off, minlength=n_c)
    a_c = np.diag(d_c) - (mat.T @ sp.diags(inv_sw) @ mat).toarray()
    m = max(float(np.mean(np.diag(a_c))), 1e-30)
    a_c += m / n_c                      # rank-one null shift: m * 11^T/n_c
    a_c += np.eye(n_c) * ridge * m      # f32 round-off guard
    # Cholesky inverse: ~25 % faster than LU at production n_c and
    # certifies SPD (a non-SPD assembly would be a bug upstream);
    # measured at the production pointing (10.3M samples, n_c 3223):
    # pattern ~2 s once + ~5 s per band on this host, reused across the
    # whole CG — the price of reaching a threshold Jacobi never does
    try:
        import scipy.linalg as sl

        c_ = sl.cho_factor(a_c)
        inv = sl.cho_solve(c_, np.eye(n_c))
    except np.linalg.LinAlgError:
        # a ridged Galerkin A_c should ALWAYS be SPD — a Cholesky
        # failure means an assembly bug upstream; surface it loudly but
        # keep the solve alive with the LU inverse
        import logging

        logging.getLogger("comapreduce_tpu").warning(
            "coarse A_c failed Cholesky (not SPD?) — LU fallback; "
            "check the preconditioner assembly")
        inv = np.linalg.inv(a_c)
    inv = (inv + inv.T) / 2.0           # SPD to the last f32 bit
    return grp, inv.astype(np.float32)


def multigrid_levels(n_offsets: int, block: int = 8, levels: int = 2,
                     max_coarse: int = 4096) -> list[int]:
    """The offset-block ladder ``b_1 < b_2 < ... < b_L`` of the
    multigrid hierarchy (nested multiples, finest to coarsest).

    ``block`` is the finest coarsening factor; each level multiplies it
    by 8 (one V-cycle level per ~decade of offset drift wavelength —
    the MAPCUMBA-style offset hierarchy, astro-ph/0101112). The
    coarsest block doubles until its system fits ``max_coarse``
    unknowns (the dense-inverse budget of
    :func:`build_coarse_preconditioner`); doubling preserves the
    nesting, so restriction between adjacent levels stays an exact
    block sum. Levels that no longer strictly coarsen — or would leave
    fewer than 2 unknowns (a 1-block system is PURE null mode: its
    ridged inverse explodes and poisons the cycle) — are dropped, so on
    small problems the ladder degrades toward a two-grid hierarchy
    with a halving coarsest block, and to EMPTY (``[]``) when no >=
    2-unknown level exists at all (``n_offsets < 3``) — the builders
    then refuse and the config layer falls back to Jacobi rather than
    assemble a guaranteed-divergent cycle."""
    blocks = []
    b = max(int(block), 2)
    for _ in range(max(int(levels), 1)):
        blocks.append(b)
        b *= 8
    n_off = max(int(n_offsets), 1)
    while -(-n_off // blocks[-1]) > max_coarse:
        blocks[-1] *= 2
    # every surviving block divides every larger one (geometric x8 plus
    # power-of-two growth on the last), so dropping a level never
    # breaks the adjacent-level nesting
    out = []
    prev_n = n_off
    for bk in blocks:
        n_b = -(-n_off // bk)
        if 2 <= n_b < prev_n:
            out.append(bk)
            prev_n = n_b
    if out:
        return out
    # every candidate over-coarsened (block > n_off/2): the largest
    # block still leaving 2 unknowns, or no ladder at all
    half = -(-n_off // 2)
    return [half] if half >= 2 and -(-n_off // half) >= 2 else []


def multigrid_patterns(pixels, npix, offset_length: int, block: int = 8,
                       levels: int = 2, max_coarse: int = 4096) -> dict:
    """Weights-independent half of the multigrid build: one
    :func:`coarse_pattern` per ladder level. A multi-band joint solve
    shares ONE pattern set (pixels are band-invariant) and runs only
    the per-band weight bincounts through
    :func:`build_multigrid_hierarchy` — the same amortisation as the
    two-level ``coarse_pattern``/``build_coarse_preconditioner``
    split."""
    npix = resolve_npix(npix)
    pixels = np.asarray(pixels)
    n_off = (pixels.size // int(offset_length))
    blocks = multigrid_levels(n_off, block=block, levels=levels,
                              max_coarse=max_coarse)
    if not blocks:
        raise MultigridUnavailable(
            f"n_offsets={n_off} is too small for any multigrid level "
            "(every block leaves < 2 unknowns — the coarse system "
            "would be pure null mode); run jacobi/twolevel instead")
    # intermediate patterns must keep their EXACT block (no internal
    # doubling): pass a max_coarse no level can exceed
    pats = [coarse_pattern(pixels, npix, offset_length, block=bk,
                           max_coarse=max(n_off, 1))
            for bk in blocks[:-1]]
    pats.append(coarse_pattern(pixels, npix, offset_length,
                               block=blocks[-1],
                               max_coarse=max_coarse))
    return {"blocks": blocks, "patterns": pats}


def build_multigrid_hierarchy(pixels, weights, npix, offset_length: int,
                              block: int = 8, levels: int = 2,
                              max_coarse: int = 4096, ridge: float = 3e-3,
                              patterns: dict | None = None) -> tuple:
    """Galerkin offset-block hierarchy for the multigrid V-cycle —
    host side, f64 assembly (the true multi-grid upgrade of
    :func:`build_coarse_preconditioner`, which remains the coarsest
    level of this ladder).

    Per intermediate level ``k`` (block ``b_k``) the EXACT Galerkin
    coarse operator ``A_k = R_k A P_k`` (piecewise-constant
    prolongation over ``b_k`` consecutive offsets) is assembled from
    the level's (pixel, block) pair aggregates — the same algebra as
    the fine system one level up::

        A_k = diag(sum w per block) - Mat_k^T diag(1/sumw_pix) Mat_k

    — and kept SPARSE (COO triplets applied on device as one small
    scatter-add per V-cycle visit; these systems are ``n_off / b_k``
    sized, orders below the fine pair space). The coarsest level is the
    existing dense ridged inverse. Every level inherits the fine
    operator's two structural facts, which make the damped-Jacobi
    V-cycle provably safe: row sums are exactly zero (the global-
    constant null mode — Galerkin restriction of ``A 1 = 0``) and
    off-diagonal entries are non-positive, so by Gershgorin
    ``lambda(D_k^{-1} A_k) <= 2`` at EVERY level and any damping
    ``omega < 1`` yields a convergent (hence SPD-preserving) smoother —
    no spectral estimation needed.

    Returns a tuple of per-level dicts of ARRAYS ONLY (a jit-traceable
    pytree for ``destripe_planned(mg=...)``): intermediate levels carry
    ``{grp, rows, cols, vals, invd}`` (``grp`` maps the PREVIOUS
    level's index to this level's block — the restriction/prolongation
    stencil), the coarsest ``{grp, ac_inv}``. Build once per
    (pointing, weights); bands with their own weights build their own
    (sharing ``patterns``) and stack via :func:`stack_multigrid` for a
    multi-RHS solve.

    Method lineage: MAPCUMBA's multigrid map-making CG
    (astro-ph/0101112) and the two-level/deflation preconditioners of
    arXiv:1309.7473 / MAPPRAISER (arXiv:2112.03370); the pair-aggregate
    Galerkin assembly per level and the TPU-side V-cycle are this
    framework's own.
    """
    import scipy.sparse as sp

    npix = resolve_npix(npix)
    if patterns is None:
        patterns = multigrid_patterns(pixels, npix, offset_length,
                                      block=block, levels=levels,
                                      max_coarse=max_coarse)
    blocks, pats = patterns["blocks"], patterns["patterns"]
    p0 = pats[0]
    n, pix, off_id = p0["n"], p0["pix"], p0["off_id"]
    n_off = p0["grp"].size
    w = np.asarray(weights, np.float64)[:n].copy()
    w[p0["bad"]] = 0.0

    sw_pix = np.bincount(pix, weights=w, minlength=npix)
    inv_sw = np.where(sw_pix > 0, 1.0 / np.maximum(sw_pix, 1e-30), 0.0)
    sw_off = np.bincount(off_id, weights=w, minlength=n_off)

    out = []
    for k, pat in enumerate(pats[:-1]):
        n_c = pat["n_c"]
        mw = np.bincount(pat["inv"], weights=w)
        mat = sp.coo_matrix((mw, (pat["rows"], pat["cols"])),
                            shape=(npix, n_c)).tocsr()
        d_c = np.bincount(pat["grp"], weights=sw_off, minlength=n_c)
        a_k = (sp.diags(d_c) - mat.T @ sp.diags(inv_sw) @ mat).tocsr()
        diag = a_k.diagonal()
        # level Jacobi inverse, same degenerate-offset rule as
        # _jacobi_inverse: fall back to the plain block weight sum where
        # Z absorbs the block, identity on zero-weight padding blocks
        cut = 1e-6 * np.maximum(d_c, 1e-30)
        safe = np.where(diag > cut, diag, np.where(d_c > 0, d_c, 1.0))
        coo = a_k.tocoo()
        grp = (pat["grp"] if k == 0 else
               np.arange(-(-n_off // blocks[k - 1]), dtype=np.int64)
               // (blocks[k] // blocks[k - 1]))
        out.append({"grp": np.asarray(grp, np.int32),
                    "rows": coo.row.astype(np.int32),
                    "cols": coo.col.astype(np.int32),
                    "vals": coo.data.astype(np.float32),
                    "invd": (1.0 / safe).astype(np.float32)})
    # coarsest: the existing dense ridged inverse, restricted FROM the
    # last intermediate level (or from the fine offsets when the ladder
    # collapsed to one level)
    _, ac_inv = build_coarse_preconditioner(
        pixels, weights, npix, offset_length, block=blocks[-1],
        ridge=ridge, max_coarse=max_coarse, pattern=pats[-1])
    if len(blocks) == 1:
        grp_c = pats[-1]["grp"]
    else:
        n_prev = -(-n_off // blocks[-2])
        grp_c = np.arange(n_prev, dtype=np.int64) \
            // (blocks[-1] // blocks[-2])
    out.append({"grp": np.asarray(grp_c, np.int32), "ac_inv": ac_inv})
    return tuple(out)


def stack_multigrid(hierarchies: list) -> tuple:
    """Stack per-band hierarchies (shared ``patterns``) into ONE
    multi-RHS hierarchy: weight-dependent leaves (``vals``, ``invd``,
    ``ac_inv``) gain a leading band axis; the index stencils
    (``grp``/``rows``/``cols``) are band-invariant and taken from the
    first."""
    first = hierarchies[0]
    out = []
    for lv_i, lv in enumerate(first):
        stacked = {}
        for key, val in lv.items():
            if key in ("vals", "invd", "ac_inv"):
                stacked[key] = np.stack(
                    [np.asarray(h[lv_i][key]) for h in hierarchies])
            else:
                stacked[key] = val
        out.append(stacked)
    return tuple(out)


def destripe_planned(tod: jax.Array, weights: jax.Array, plan: PointingPlan,
                     n_iter: int = 100, threshold: float = 1e-6,
                     axis_name: str | tuple | None = None,
                     dense_maps: bool = True,
                     device_arrays: dict | None = None,
                     ground_off: jax.Array | None = None,
                     az: jax.Array | None = None,
                     n_groups: int = 0,
                     coarse: tuple | None = None,
                     mg: tuple | None = None,
                     mg_smooth: int = 1,
                     mg_omega: float = 2.0 / 3.0,
                     banded: tuple | None = None,
                     x0: jax.Array | None = None,
                     precond: str = "jacobi",
                     kernels: str = "auto",
                     kernels_platform: str | None = None,
                     cg_dot: str = "f32",
                     trace_iters: int = 0) -> DestriperResult:
    """Destripe with a precomputed :class:`PointingPlan` — the fast path.

    Mathematically identical to :func:`destripe` (same normal equations,
    same CG with breakdown guard), but every per-iteration binning runs in
    the coarse (pixel, offset)-pair space with MXU one-hot binning instead
    of per-sample scatter-adds (see ``pointing_plan`` module docstring) —
    measured >10x faster per CG iteration at production shape. Use when the
    pointing is fixed for the whole solve (always true per band); the
    scatter-based :func:`destripe` remains the general/oracle path.

    ``tod``/``weights``: f32[..., N] in natural sample order, N as the
    plan was built. A leading axis is a MULTI-RHS solve (e.g. all four
    bands against their shared pointing): every per-iteration one-hot is
    built once per chunk and contracted against all bands in the same
    MXU matmul, and the CG runs per-band alphas/convergence (equivalent
    to independent solves). ``offsets``, the destriped/naive/weight maps
    and ``residual`` gain the leading axis; ``hit_map`` and ``n_iter``
    stay shared (hits depend on pointing alone; the loop runs until the
    slowest band converges).

    ``ground_off``/``az``/``n_groups`` enable the joint az-linear ground
    template on this scatter-free path: ``ground_off`` is the PER-OFFSET
    group id (:func:`ground_ids_per_offset`), ``az`` the per-sample
    normalised azimuth. The ground couplings ride the same pair space —
    two extra aggregate rows (``sum w az``, ``sum w az^2`` per pair) and
    an (n_off -> n_groups) segment reduction per iteration. Works under
    ``shard_map`` too (group sums and the offsets' dot psum; the ground
    block is replicated). Single-RHS only (multi-RHS ground solves run
    per band).

    ``axis_name``: set when called inside ``shard_map`` with per-shard
    plans from ``build_sharded_plans`` — compact map sums and CG scalars
    are ``psum``-reduced across shards (the shared compact index space).
    ``dense_maps=False`` returns COMPACT maps of shape (n_rank,) over
    ``plan.uniq_pixels`` instead of materialising npix-sized vectors —
    required at HEALPix nside 4096 where the dense map (~200M px) must
    never exist on device (partial-map output, ``COMAPData.py:570-574``).
    ``device_arrays`` overrides ``plan.device()`` — used by the shard_map
    wrapper, which feeds each shard its own index arrays as traced inputs
    (``plan`` then only supplies the shared static geometry).

    ``coarse``: optional ``(grp, ac_inv)`` from
    :func:`build_coarse_preconditioner` — upgrades the Jacobi
    preconditioner to the additive two-level one (kills the long-drift
    modes Jacobi stalls on). ``ac_inv`` may carry a leading band axis
    matching a multi-RHS ``tod``. Traced inputs, so the memoized
    compiled program is reused across bands/weights. Under
    ``axis_name`` (shard_map), ``grp`` is the SHARD-LOCAL slice of the
    global offset->block map while ``ac_inv`` is replicated: the coarse
    vector is psum'd (blocks may span shards), the tiny dense solve is
    computed redundantly per shard, and each shard gathers its own
    offsets' correction.

    ``x0``: optional warm-start offsets (leading band axis allowed,
    matching ``tod``) — the divergence-fallback path restarts the
    Jacobi solve from the coarse solve's best iterate through this.
    When the CG divergence monitor trips, ``result.diverged`` is 1 for
    that system and ``offsets`` hold the best (lowest-residual)
    iterate seen, not a converged solution.

    ``precond``: ``"jacobi"`` (default) or ``"none"`` — the
    ``[Destriper] preconditioner`` knob's fast-path end. ``coarse``
    (the two-level upgrade) is additive over Jacobi and requires it.
    Same fixed point whichever is selected; only the CG path changes.

    ``mg``: optional hierarchy from :func:`build_multigrid_hierarchy`
    (or :func:`stack_multigrid` for multi-RHS) — replaces the additive
    two-level correction with a SYMMETRIC V(nu, nu)-cycle over the
    offset-block ladder: ``mg_smooth`` (= nu) damped-Jacobi smoothing
    steps at every level around an exact-Galerkin residual restriction,
    the coarsest level solved by the dense ridged inverse. The fine
    level's operator is this solve's own ``matvec`` (exact, including
    the map projection Z), so one preconditioner application costs
    ``2 nu`` extra fine matvecs — the trade that buys the iteration
    count (multiplicative V-cycle > additive two-level, MAPCUMBA
    astro-ph/0101112). ``mg_omega`` is the Jacobi damping: every level
    has exactly-zero row sums and non-positive off-diagonals, so
    Gershgorin bounds ``lambda(D^{-1}A) <= 2`` and ANY ``omega < 1``
    keeps the smoother convergent and the V-cycle SPD (see
    ``build_multigrid_hierarchy``). Traced arrays — the memoized
    compiled program is reused across bands/weights; ``mg_smooth`` /
    ``mg_omega`` are static. Mutually exclusive with ``coarse``;
    requires ``precond='jacobi'``. Ground solves apply the V-cycle to
    the offsets block (identity on the small ground block, like every
    other preconditioner here). Under ``axis_name`` (shard_map) the
    hierarchy must be built from the GLOBAL padded pixel/weight
    vectors: level 0's ``grp`` is then each shard's contiguous slice
    of the global offset->block map (whole offsets per shard, so the
    slice lines up) while every other leaf is replicated — the level-0
    restriction is psum-assembled exactly like the two-level coarse
    vector, the coarser levels run redundantly per shard on the
    replicated global vectors, and prolongation is each shard's own
    gather. The fine smoother's operator is the psum-threaded
    ``matvec`` already, so the cycle stays ONE SPD operator across the
    mesh.

    ``banded``: optional ``(c0, cs)`` from
    :func:`~comapreduce_tpu.mapmaking.noise_weight.
    build_banded_weight` — adds a symmetric banded offset-rate noise
    prior ``B`` to the normal operator (``A' = F^T W Z F + B``, the
    MADAM/MAPPRAISER destriping prior built from the quality ledger's
    measured 1/f fits): ``c0`` f32[..., n_off] is the diagonal,
    ``cs`` f32[..., q, n_off] the ``q`` upper off-diagonal bands
    (``cs[..., j-1, i] = B[i, i+j]``). Applied inside the CG matvec
    and the Jacobi diagonal; the RHS is unchanged (zero-mean prior).
    Couplings across (file, feed) group and shard boundaries are
    zeroed by the builder, so the sharded apply is purely local (no
    halo exchange). Leading band axes broadcast like every other
    multi-RHS operand. Not available on the joint ground solve.

    ``kernels``: the ``[Destriper] kernels`` knob — ``auto`` (default),
    ``xla``, ``pallas``, or ``interpret``. Resolved EAGERLY at trace
    time via ``pallas_binning.resolve_kernels``: ``auto`` keeps the
    historical XLA paths byte-identical on non-TPU backends (the Mosaic
    branch never enters the jaxpr) and routes every per-iteration
    binning — ``pair_sum``/``rank_sum``/``off_sum``, hence the CG
    matvec, the multigrid fine smoother (which closes over ``matvec``)
    and the multi-RHS path — plus the ground-path windowed gathers
    through the Pallas kernels on TPU. ``interpret`` runs the same
    kernels under the Pallas interpreter (CPU parity testing).
    ``kernels_platform`` overrides the backend the ``auto`` resolution
    consults (``pallas_supported(platform=...)``) so a mixed CPU+TPU
    host can trace CPU-placed programs without pulling Mosaic calls
    into them. Shapes the kernel VMEM gate rejects silently keep the
    XLA path (parity holds either way).

    ``cg_dot``: ``"f32"`` (default, byte-identical program) or
    ``"compensated"`` — the CG recurrence dots (alpha/beta/residual
    and the divergence monitor's ``|r|^2``) run through the
    float-float :func:`~comapreduce_tpu.ops.precision.precise_dot`
    (the ``[Precision] cg_dot`` knob, OPERATIONS.md §15). Works on
    every branch here: multi-RHS per-band dots contract the last axis;
    sharded dots compensate per shard and psum the few per-shard
    partials in f32.
    """
    _check_precond(precond, coarse, mg)
    _check_cg_dot(cg_dot)
    from comapreduce_tpu.mapmaking.pallas_binning import (
        pallas_binning_ok, resolve_kernels, windowed_gather_pallas)
    kern = resolve_kernels(kernels, platform=kernels_platform)
    # None (not "xla") when the knob resolves to XLA: the legacy env
    # dispatch (COMAP_BIN_IMPL included) stays byte-identical
    bin_impl = None if kern == "xla" else kern
    dv = device_arrays if device_arrays is not None else plan.device()
    with_ground = ground_off is not None
    if with_ground and tod.ndim != 1:
        raise ValueError("the planned ground solve is single-RHS; "
                         "use destripe() or per-band solves otherwise")
    if banded is not None and with_ground:
        raise ValueError("banded noise weighting composes with the "
                         "offsets-only solves; the joint ground solve "
                         "keeps the white-weight operator (run "
                         "noise_weight = white there)")
    # numerical tripwire (see destripe): non-finite samples -> (0, 0)
    tod, weights = scrub_tod(tod, weights)

    def _psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x
    f32 = tod.dtype
    n_off, n_rank = plan.n_offsets, plan.n_rank
    P_pad = int(dv["pair_rank"].shape[0])
    N_pad = int(dv["sample_perm"].shape[0])
    N = tod.shape[-1]

    # sorted sample values; padding slots (which alias sample 0) zeroed
    pad_mask = (jnp.arange(N_pad) < N).astype(f32)
    w_s = jnp.take(weights, dv["sample_perm"], axis=-1) * pad_mask
    wd_s = w_s * jnp.take(tod, dv["sample_perm"], axis=-1)

    def pair_sum(v):
        return binned_window_sum(v, dv["sample_pair"], dv["sample_base"],
                                 plan.sample_window, plan.sample_chunk,
                                 P_pad, impl=bin_impl)

    def rank_sum(pv):
        return binned_window_sum(pv, dv["pair_rank"], dv["rank_base"],
                                 plan.rank_window, plan.pair_chunk, n_rank,
                                 impl=bin_impl)

    # offset-order views. The matvec runs its first half in rank order and
    # its second half in offset order, reading from the SMALL domains
    # (offset vector / compact map) with one random gather each; the
    # 2.5M-scale pair-permutation gather per iteration this replaces
    # measured ~2x slower than a small-domain gather on a v5e, and the
    # permutations below now run once at setup.
    perm_off = dv["pair_perm_off"]
    po_off = dv["pair_offset"][perm_off]   # sorted -> windowed binning
    pr_off = dv["pair_rank"][perm_off]     # unsorted, read via gather_m

    def off_sum(pv_off):
        """Pair -> offset sums; input already in OFFSET order."""
        return binned_window_sum(pv_off, po_off,
                                 dv["off_base"], plan.off_window,
                                 plan.pair_chunk, n_off, impl=bin_impl)

    # local -> global rank-space bridge (sharded plans): shard-local
    # compact sums scatter into the global hit-pixel space (tiny static
    # scatter), psum, and gather back for the pair-space reads
    l2g = dv.get("rank_to_global")
    if l2g is not None:
        n_rank_out = plan.n_rank_global

        def to_global(s):
            # leading (band) dims derive from the operand: the hit-count
            # path stays unbatched while weight/map sums carry the bands
            g = jnp.zeros(s.shape[:-1] + (n_rank_out,),
                          f32).at[..., l2g].add(s, mode="drop")
            return _psum(g)

        def from_global(mg):
            # padding/sentinel local ranks read 0 — the scatter path's
            # invalid-sample semantics
            return jnp.where(l2g < n_rank_out,
                             jnp.take(mg, jnp.clip(l2g, 0, n_rank_out - 1),
                                      axis=-1), 0.0)
    else:
        n_rank_out = n_rank

        def to_global(s):
            return _psum(s)

        def from_global(mg):
            return mg

    # one-time aggregates (the offset-order copies cost one permutation
    # gather each, at setup only)
    pair_w = pair_sum(w_s)           # P^T-pair weights (rank order)
    pair_wd = pair_sum(wd_s)
    pair_cnt = pair_sum(pad_mask)
    pair_w_off = jnp.take(pair_w, perm_off, axis=-1)
    pair_wd_off = jnp.take(pair_wd, perm_off, axis=-1)
    sum_w = to_global(rank_sum(pair_w))  # compact weight map (global)
    diag = off_sum(pair_w_off)       # diagonal of F^T W F (shard-local)

    if with_ground:
        az_s = jnp.take(az, dv["sample_perm"], axis=-1)
        paz = pair_sum(w_s * az_s)           # sum w az   per pair
        pazaz = pair_sum(w_s * az_s * az_s)  # sum w az^2 per pair
        pazd = pair_sum(wd_s * az_s)         # sum w az d per pair
        paz_off = jnp.take(paz, perm_off, axis=-1)
        pazaz_off = jnp.take(pazaz, perm_off, axis=-1)
        pazd_off = jnp.take(pazd, perm_off, axis=-1)
        grp_off = jnp.asarray(ground_off, jnp.int32)
        # offset-order coefficient gather (rank order reuses gather_a).
        # po_off IS plan-windowed (off_base/off_window), so the Pallas
        # windowed gather applies: sentinel pairs read 0.0 instead of
        # the clamped c[n_off-1], and every use below multiplies them
        # by a zero pair aggregate — same contribution either way.
        po_off_clip = jnp.clip(po_off, 0, n_off - 1)
        if bin_impl is not None and pallas_binning_ok(
                plan.off_window, plan.pair_chunk,
                interpret=(bin_impl == "interpret")):
            def c_gather(c):
                return windowed_gather_pallas(
                    c, po_off, dv["off_base"], plan.off_window,
                    plan.pair_chunk, interpret=(bin_impl == "interpret"))
        else:
            def c_gather(c):
                return jnp.take(c, po_off_clip)

        def group_sum(v_off):
            # psum: under shard_map each shard owns whole offsets, so
            # the global per-group sums are the psum of local segments
            return _psum(jax.ops.segment_sum(v_off, grp_off,
                                             num_segments=n_groups))

    def to_map(pv):
        s = to_global(rank_sum(pv))
        return jnp.where(sum_w > 0, s / jnp.maximum(sum_w, 1e-30), 0.0)

    def gather_a(a):
        # padding pairs' sentinel offset clamps to a[-1]; their pair_w is 0
        return jnp.take(a, jnp.clip(dv["pair_offset"], 0, n_off - 1),
                        axis=-1)

    def gather_m(m):
        # invalid-pixel pairs (sentinel rank) read 0 from the map — the
        # scatter path's sample_map semantics; OFFSET-order output
        return jnp.where(pr_off < n_rank,
                         jnp.take(m, jnp.clip(pr_off, 0, n_rank - 1),
                                  axis=-1), 0.0)

    # banded offset-rate noise prior B (see the ``banded`` doc above):
    # symmetric application from the stored diagonal + upper bands via
    # shifted adds with zero fill — group/shard boundary couplings are
    # zeroed by the builder, so the shifts never need a halo exchange
    if banded is not None:
        b_c0 = jnp.asarray(banded[0], f32)
        b_cs = jnp.asarray(banded[1], f32)
        n_bw = int(b_cs.shape[-2])

        def banded_apply(a):
            out = b_c0 * a
            for j in range(1, n_bw + 1):
                cj = b_cs[..., j - 1, :]
                zj = jnp.zeros(a.shape[:-1] + (j,), f32)
                # upper band: row i adds cj[i] * a[i+j] ...
                out = out + cj * jnp.concatenate(
                    [a[..., j:], zj], axis=-1)
                # ... and its transpose: row i+j adds cj[i] * a[i]
                out = out + jnp.concatenate(
                    [zj, (cj * a)[..., :-j]], axis=-1)
            return out

    def matvec(a):
        pav = pair_w * gather_a(a)                 # rank order
        m = from_global(to_map(pav))
        out = diag * a - off_sum(pair_w_off * gather_m(m))
        if banded is not None:
            out = out + banded_apply(a)
        return out

    m_d = to_map(pair_wd)
    gm_md = gather_m(from_global(m_d))
    b = off_sum(pair_wd_off - pair_w_off * gm_md)

    # Jacobi preconditioner: exact diag(A) from the pair aggregates —
    # A_oo = diag_o - sum_{pairs (r,o)} w_po^2 / sumw_r
    if precond != "none":
        inv_sw = jnp.where(sum_w > 0,
                           1.0 / jnp.maximum(sum_w, 1e-30), 0.0)
        corr = off_sum(pair_w_off * pair_w_off
                       * gather_m(from_global(inv_sw)))
        if banded is not None:
            # diag(A + B): the prior's diagonal rides both the true
            # diagonal and the degenerate-offset fallback (B is SPD, so
            # an offset the projection Z absorbs is still pinned by it)
            inv_diag = _jacobi_inverse(diag - corr + b_c0, diag + b_c0)
        else:
            inv_diag = _jacobi_inverse(diag - corr, diag)

    if precond == "none":
        def apply_precond(v):
            return v
    elif mg is not None:
        mg_t = tuple(mg)
        nu = max(int(mg_smooth), 1)
        omega = float(mg_omega)
        if not 0.0 < omega < 1.0:
            raise ValueError(f"mg_omega must be in (0, 1) — the "
                             f"Gershgorin-safe damping range — got "
                             f"{omega}")

        def coo_apply(lv, x):
            """Sparse level operator A_k x: one small scatter-add over
            the level's COO triplets (n_off/b_k-sized — negligible next
            to the fine one-hot binnings; bands broadcast through)."""
            n_k = lv["invd"].shape[-1]
            contrib = lv["vals"] * jnp.take(x, lv["cols"], axis=-1)
            return jnp.zeros(x.shape[:-1] + (n_k,),
                             f32).at[..., lv["rows"]].add(contrib)

        def restrict(grp, res, n_next):
            return jnp.zeros(res.shape[:-1] + (n_next,),
                             f32).at[..., grp].add(res)

        def vcycle(idx, r, apply_a, invd):
            # pre-smooth from zero: the first damped-Jacobi step needs
            # no matvec (x = omega D^-1 r exactly)
            x = omega * invd * r
            for _ in range(nu - 1):
                x = x + omega * invd * (r - apply_a(x))
            lv = mg_t[idx]
            grp = lv["grp"]
            res = r - apply_a(x)
            if "ac_inv" in lv:          # coarsest: dense ridged inverse
                rc = restrict(grp, res, lv["ac_inv"].shape[-1])
                if idx == 0:
                    # sharded: each shard restricts its own offsets into
                    # the GLOBAL coarse vector; psum assembles it (blocks
                    # may span shards) — the two-level coarse idiom.
                    # Coarser levels already hold replicated globals.
                    rc = _psum(rc)
                ec = jnp.einsum("...ij,...j->...i", lv["ac_inv"], rc)
            else:
                invd_n = lv["invd"]
                rc = restrict(grp, res, invd_n.shape[-1])
                if idx == 0:
                    rc = _psum(rc)
                ec = vcycle(idx + 1, rc,
                            lambda v, lv=lv: coo_apply(lv, v), invd_n)
            x = x + jnp.take(ec, grp, axis=-1)
            for _ in range(nu):          # symmetric post-smooth
                x = x + omega * invd * (r - apply_a(x))
            return x

        def apply_precond(v):
            return vcycle(0, v, matvec, inv_diag)
    elif coarse is not None:
        c_grp, ac_inv = coarse
        c_grp = jnp.asarray(c_grp, jnp.int32)
        n_c = ac_inv.shape[-1]

        def apply_precond(v):
            # additive two-level: Jacobi + coarse-grid correction
            # (segment-sum to blocks, small dense solve-as-matmul, gather
            # back — negligible next to the matvec's one-hot binnings).
            # Sharded: psum assembles the global coarse vector (blocks
            # may span shards); the dense solve is replicated.
            rc = jnp.zeros(v.shape[:-1] + (n_c,),
                           f32).at[..., c_grp].add(v)
            rc = _psum(rc)
            cc = jnp.einsum("...ij,...j->...i", ac_inv, rc)
            return v * inv_diag + jnp.take(cc, c_grp, axis=-1)
    else:
        def apply_precond(v):
            return v * inv_diag

    if with_ground:
        # joint [offsets; ground] solve in the same pair space: the
        # per-pair template coefficients are c0 = a + g0, c1 = g1 read
        # through the small per-offset domain, so each matvec stays two
        # one-hot binnings + one rank/map gather pair + a tiny
        # (n_off -> n_groups) segment reduction
        def q_off_of(c0, c1):
            return (pair_w_off * c_gather(c0)
                    + paz_off * c_gather(c1))

        def matvec_g(x):
            a_, g = x
            c0 = a_ + g[:, 0][grp_off]
            c1 = g[:, 1][grp_off]
            q_rank = pair_w * gather_a(c0) + paz * gather_a(c1)
            m = from_global(to_map(q_rank))
            gm = gather_m(m)
            off_f = off_sum(q_off_of(c0, c1) - pair_w_off * gm)
            off_az = off_sum(paz_off * c_gather(c0)
                             + pazaz_off * c_gather(c1)
                             - paz_off * gm)
            return (off_f, jnp.stack([group_sum(off_f),
                                      group_sum(off_az)], -1))

        b_az = off_sum(pazd_off - paz_off * gm_md)
        b_g = (b, jnp.stack([group_sum(b), group_sum(b_az)], -1))
        if x0 is not None:
            raise ValueError("x0 warm start is offsets-only; the joint "
                             "ground solve restarts cold")
        if cg_dot == "compensated":
            from comapreduce_tpu.ops.precision import precise_dot

            def dot_g(u, v):
                return (_psum(precise_dot(u[0], v[0]))
                        + precise_dot(u[1], v[1]))
        else:
            # offsets are sharded (psum the partial dot); the ground
            # block is replicated (group sums already psum'd), so its
            # dot term must NOT be psum'd again
            def dot_g(u, v):
                return (_psum(jnp.sum(u[0] * v[0]))
                        + jnp.sum(u[1] * v[1]))
        x, rz, k, b_norm, diverged, cg_trace = _cg_loop(
            matvec_g, b_g, dot_g,
            n_iter, threshold,
            # identity on the ground block, as in the scatter path (see
            # destripe's precond comment)
            precond=lambda v: (apply_precond(v[0]), v[1]),
            trace_n=trace_iters)
        a, ground = x
        c0 = a + ground[:, 0][grp_off]
        c1 = ground[:, 1][grp_off]
        pair_res = pair_wd - (pair_w * gather_a(c0) + paz * gather_a(c1))
    else:
        # per-band inner products (last axis only): a multi-RHS solve
        # runs independent CGs in one program
        if cg_dot == "compensated":
            from comapreduce_tpu.ops.precision import precise_dot

            def dot_b(u, v):
                return _psum(precise_dot(u, v, axis=-1))
        else:
            def dot_b(u, v):
                return _psum(jnp.sum(u * v, axis=-1))
        a, rz, k, b_norm, diverged, cg_trace = _cg_loop(
            matvec, b, dot_b,
            n_iter, threshold, precond=apply_precond, x0=x0,
            trace_n=trace_iters)
        ground = jnp.zeros((0, 2), f32)
        pair_res = pair_wd - pair_w * gather_a(a)

    # final products in the compact rank space; optionally scattered once
    # to the full map (host-side partial-map writers take the compact form)
    uniq = dv["uniq_pixels"]

    def expand(cmp):
        if not dense_maps:
            return cmp
        if l2g is not None:
            raise ValueError("dense_maps is not supported with sharded "
                             "plans; write the compact maps over "
                             "plan.uniq_global instead")
        return jnp.zeros(cmp.shape[:-1] + (plan.npix,),
                         f32).at[..., uniq].set(
            cmp, mode="drop", unique_indices=True)

    m_destriped = expand(to_map(pair_res))
    m_naive = expand(m_d)
    w_map = expand(sum_w)
    h_map = expand(to_global(rank_sum(pair_cnt)))
    residual = jnp.sqrt(rz / jnp.maximum(b_norm, 1e-30))
    # histories + |b|^2 so the host can reconstruct relative residuals;
    # None when untraced (an empty pytree node — sharded out_specs and
    # the compiled program are unchanged, the sky_pixels precedent)
    trace = None if cg_trace is None else (cg_trace + (b_norm,))
    return DestriperResult(a, ground, m_destriped, m_naive,
                           w_map, h_map, k, residual, diverged,
                           trace=trace)
