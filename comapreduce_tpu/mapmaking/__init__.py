"""Map-making: pixelization, binning, and the CG destriper.

TPU-native re-design of the reference's ``MapMaking/`` package
(``MapMaking/Destriper.py``, ``MapMaking/COMAPData.py``,
``MapMaking/run_destriper.py``; see SURVEY.md §2.3):

- pixelization (WCS flat-sky projections + HEALPix) is host-side numpy,
  precomputed once per observation (the reference computes pixels per scan on
  read, ``COMAPData.py:383-469``);
- the pointing-matrix apply ``P`` is a gather and ``P^T`` is a
  ``jax.ops.segment_sum`` — the north-star kernel replacing the Cython
  scatter-add ``Tools/binFuncs.pyx``;
- the destriper normal equations are solved by a fully jitted CG whose map
  reduction is a ``psum`` over the device mesh (replacing the reference's
  MPI ``Gather+Bcast`` per matvec, ``Destriper.py:183-204``).
"""

from comapreduce_tpu.mapmaking import (  # noqa: F401
    binning,
    destriper,
    fits_io,
    healpix,
    pixel_space,
    wcs,
)
from comapreduce_tpu.mapmaking.binning import bin_map, bin_offset_map  # noqa: F401
from comapreduce_tpu.mapmaking.pixel_space import (  # noqa: F401
    PixelSpace,
    build_seen_pixel_space,
)
from comapreduce_tpu.mapmaking.destriper import (  # noqa: F401
    DestriperResult,
    destripe,
)
from comapreduce_tpu.mapmaking.wcs import WCS  # noqa: F401
