"""Seen-pixel dictionaries: the map vector's index space.

At the production sky-survey regime (HEALPix nside 4096, ~201M pixels)
a COMAP field hits well under 1% of the sky, so every dense
``f32[npix]`` map vector — each ``segment_sum`` target, each CG state
leaf — would waste >99% of its bytes and FLOPs. The reference pipeline
compacts seen pixels for exactly this reason (``COMAPData.py:570-574``);
this module makes that compaction a first-class object instead of an
ad-hoc ``np.unique`` inside the data layer:

- :class:`PixelSpace` is *dense* (identity: solver ids == sky ids) or
  *compacted* (a sorted seen-pixel dictionary: solver id ``i`` is sky
  pixel ``pixels[i]``). Everything downstream — binning segment counts,
  destriper CG state, Jacobi/coarse/multigrid builds, the sharded
  ``psum`` vectors — sizes itself to ``n_solve`` (= ``n_compact`` when
  compacted), and the writers scatter compacted values into the full
  map **only at write time**, host-side. ``npix``-sized vectors never
  exist on device.
- The dictionary is built host-side as the union of hit pixels across
  all files of a campaign (:func:`build_seen_pixel_space`) — one
  CAMPAIGN-level index, so every shard/rank that receives the same
  dictionary agrees on the compacted ids and compact partial maps
  psum/coadd without any re-indexing (the reference's allgather'd
  seen-pixel list). :meth:`PixelSpace.union` merges dictionaries for
  the coadd path.

The class is content-hashable (shape + sha1 digest of the dictionary),
so it can ride ``jax.jit`` static arguments and the CLI's plan memo the
same way a plain ``npix`` int does.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["PixelSpace", "build_seen_pixel_space", "resolve_npix"]


@dataclass(frozen=True)
class PixelSpace:
    """Dense or compacted pixel index space (see module docstring).

    ``npix_sky``: the full sky/field pixel count (``12 nside^2`` for
    HEALPix, ``nx*ny`` for a WCS field). ``pixels``: ``None`` for the
    dense space, else the sorted unique seen-pixel dictionary
    (i64[n_compact], strictly increasing, all in ``[0, npix_sky)``).
    """

    npix_sky: int
    pixels: np.ndarray | None = None
    _digest: str = field(default="", repr=False, compare=False)

    def __post_init__(self):
        object.__setattr__(self, "npix_sky", int(self.npix_sky))
        if self.pixels is not None:
            pix = np.ascontiguousarray(np.asarray(self.pixels, np.int64))
            if pix.ndim != 1:
                raise ValueError("pixel dictionary must be 1-D")
            if pix.size:
                if (np.diff(pix) <= 0).any():
                    raise ValueError("pixel dictionary must be sorted "
                                     "strictly increasing (use "
                                     "build_seen_pixel_space)")
                if pix[0] < 0 or pix[-1] >= self.npix_sky:
                    raise ValueError(
                        f"pixel dictionary ids outside [0, "
                        f"{self.npix_sky}): [{pix[0]}, {pix[-1]}]")
            object.__setattr__(self, "pixels", pix)
            object.__setattr__(
                self, "_digest", hashlib.sha1(pix.tobytes()).hexdigest())

    # -- construction -----------------------------------------------------

    @classmethod
    def dense(cls, npix: int) -> "PixelSpace":
        return cls(npix_sky=int(npix))

    @classmethod
    def from_dictionary(cls, pixels, npix_sky: int) -> "PixelSpace":
        """Wrap an ALREADY sorted-unique dictionary (validated)."""
        return cls(npix_sky=int(npix_sky), pixels=np.asarray(pixels))

    @classmethod
    def from_pixels(cls, pixels, npix_sky: int) -> "PixelSpace":
        """Compact from a raw pixel stream: sorted unique of the valid
        (``0 <= p < npix_sky``) ids. Invalid/sentinel ids drop out here
        and come back as the drop sentinel from :meth:`remap`."""
        pix = np.asarray(pixels, np.int64).ravel()
        valid = (pix >= 0) & (pix < int(npix_sky))
        return cls(npix_sky=int(npix_sky), pixels=np.unique(pix[valid]))

    def union(self, *others: "PixelSpace") -> "PixelSpace":
        """Merged dictionary over several spaces (the coadd rule). Any
        dense participant makes the union dense; sky sizes must agree
        (the caller's mixed-nside check fires first with a better
        message)."""
        spaces = (self,) + others
        npix = {s.npix_sky for s in spaces}
        if len(npix) != 1:
            raise ValueError(f"union over mixed sky sizes {sorted(npix)}")
        if any(not s.compacted for s in spaces):
            return PixelSpace.dense(self.npix_sky)
        merged = np.unique(np.concatenate([s.pixels for s in spaces]))
        return PixelSpace.from_dictionary(merged, self.npix_sky)

    # -- properties -------------------------------------------------------

    @property
    def compacted(self) -> bool:
        return self.pixels is not None

    @property
    def n_compact(self) -> int:
        if self.pixels is None:
            raise ValueError("dense PixelSpace has no compact size")
        return int(self.pixels.size)

    @property
    def n_solve(self) -> int:
        """Segment count the solver sees: ``n_compact`` when compacted,
        else the full ``npix_sky`` (dense)."""
        return int(self.pixels.size) if self.pixels is not None \
            else self.npix_sky

    # -- index maps -------------------------------------------------------

    def remap(self, global_pixels) -> np.ndarray:
        """Global sky ids -> solver ids (i32), ONCE per plan, host-side.

        Ids outside the dictionary (including negatives and
        ``>= npix_sky``) map to the drop sentinel ``n_solve`` — the
        binning layer's invalid-sample convention, so a remapped stream
        plugs into ``bin_map``/``build_pointing_plan`` unchanged. Dense
        spaces only sentinel-ise the out-of-range ids."""
        pix = np.asarray(global_pixels, np.int64)
        if self.pixels is None:
            return np.where((pix < 0) | (pix >= self.npix_sky),
                            self.npix_sky, pix).astype(np.int32)
        n = self.n_compact
        if n == 0:
            # empty dictionary (fully-flagged filelist): every sample
            # sentinel-ises, same as the pre-PixelSpace data layer
            return np.zeros(pix.shape, np.int32)
        idx = np.clip(np.searchsorted(self.pixels, np.clip(pix, 0, None)),
                      0, n - 1)
        hit = ((pix >= 0) & (pix < self.npix_sky)
               & (self.pixels[idx] == pix))
        return np.where(hit, idx, n).astype(np.int32)

    def to_global(self, solver_ids) -> np.ndarray:
        """Solver ids -> global sky ids (sentinels ride through as
        ``npix_sky``)."""
        ids = np.asarray(solver_ids, np.int64)
        if self.pixels is None:
            return ids
        out = np.full(ids.shape, self.npix_sky, np.int64)
        ok = (ids >= 0) & (ids < self.n_compact)
        out[ok] = self.pixels[ids[ok]]
        return out

    def expand(self, values: np.ndarray, fill: float = 0.0) -> np.ndarray:
        """Scatter a compact map into the FULL sky vector — write time
        only, host-side (this is the one place an ``npix_sky``-sized
        array may exist, and it never touches a device). Dense spaces
        pass values through. Leading axes (multi-RHS bands) ride."""
        vals = np.asarray(values)
        if self.pixels is None:
            return vals
        if vals.shape[-1] != self.n_compact:
            # exact — a longer input is as wrong as a shorter one
            # (e.g. an already-expanded dense map passed back in would
            # otherwise scatter sky-indexed values into dictionary
            # slots with no error)
            raise ValueError(f"compact map has {vals.shape[-1]} entries "
                             f"for a {self.n_compact}-pixel dictionary")
        full = np.full(vals.shape[:-1] + (self.npix_sky,), fill,
                       np.asarray(vals).dtype)
        full[..., self.pixels] = vals
        return full

    # -- hashing (jit static args / plan memo keys) -----------------------

    def __hash__(self):
        return hash((self.npix_sky, self._digest))

    def __eq__(self, other):
        return (isinstance(other, PixelSpace)
                and self.npix_sky == other.npix_sky
                and self._digest == other._digest)


def build_seen_pixel_space(pixel_streams, npix_sky: int) -> PixelSpace:
    """CAMPAIGN-level seen-pixel dictionary: the sorted union of hit
    pixels across all files/shards.

    ``pixel_streams``: an iterable of per-file (or per-shard) global
    pixel arrays — streamed, so the union never needs every file's
    pointing in memory at once. The result is deterministic in the
    stream CONTENT (sorted unique), not its order, so every rank that
    unions the same campaign's files computes the identical dictionary
    — the host-side analogue of the reference's allgather'd seen-pixel
    list, and the property that makes per-shard compact maps
    ``psum``-consistent and rank partial maps coadd-able without
    re-indexing."""
    seen: np.ndarray | None = None
    for pix in pixel_streams:
        part = PixelSpace.from_pixels(pix, npix_sky).pixels
        seen = part if seen is None else \
            np.union1d(seen, part)
    if seen is None:
        seen = np.empty(0, np.int64)
    return PixelSpace.from_dictionary(seen, npix_sky)


def resolve_npix(npix) -> int:
    """``npix | PixelSpace`` -> the solver's segment count. ONE home for
    the rule — every consumer of an ``npix``-like argument (binning,
    destriper, plans, sharded wrappers) resolves through here so a
    compacted space means ``n_compact`` everywhere at once."""
    if isinstance(npix, PixelSpace):
        return npix.n_solve
    return int(npix)
