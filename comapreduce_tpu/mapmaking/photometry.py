"""Map-space photometry and source fitting.

The reference drives this through an *external* ``mapext`` package
(``run_mapext.py:1-72``, absent upstream), so the capability was a
permanent gap there. Here it is native: aperture photometry with an
annulus background and a 2-D Gaussian source fit on a map cutout, built
on the WCS region queries (:mod:`comapreduce_tpu.mapmaking.wcs`) and the
batched LM fitter (:mod:`comapreduce_tpu.calibration.fitting`).

All functions take a FLAT map vector over ``wcs`` (the destriper's
output layout) in any unit; results come back in that unit (times
steradian-free pixel counts for fluxes — multiply by the pixel solid
angle for Jy-style integrals).
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.mapmaking.wcs import WCS, query_disc

__all__ = ["aperture_photometry", "fit_map_source"]


def aperture_photometry(map_flat, wcs: WCS, lon0: float, lat0: float,
                        r_aperture: float, r_in: float | None = None,
                        r_out: float | None = None,
                        weight_flat=None) -> dict:
    """Background-subtracted aperture sum around ``(lon0, lat0)``.

    ``r_aperture``/``r_in``/``r_out`` in degrees (annulus defaults:
    ``1.5x`` and ``2.5x`` the aperture). Background is the annulus
    MEDIAN (robust to nearby sources). The per-pixel noise comes from
    per-pixel weights (``1/variance``) when given, else from the annulus
    MAD scatter. NaN pixels are ignored.

    Returns ``{"flux", "flux_err", "background", "n_pixels"}`` with
    ``flux`` in map-unit * pixels.
    """
    from comapreduce_tpu.mapmaking.wcs import angular_separation

    m = np.asarray(map_flat, np.float64).reshape(-1)
    if r_in is None:
        r_in = 1.5 * r_aperture
    if r_out is None:
        r_out = 2.5 * r_aperture
    # one full-grid transform per source: disc and annulus both derive
    # from the same separation array (cached pixel centers)
    lon, lat = wcs.pixel_centers()
    r = angular_separation(lon0, lat0, lon.ravel(), lat.ravel())
    sel_ap = np.isfinite(r) & (r < r_aperture)
    sel_bg = np.isfinite(r) & (r >= r_in) & (r < r_out)
    ap_raw = m[sel_ap]
    bg_raw = m[sel_bg]
    fin_ap = np.isfinite(ap_raw)
    fin_bg = np.isfinite(bg_raw)
    ap = ap_raw[fin_ap]
    bg = bg_raw[fin_bg]
    n = ap.size
    if n == 0:
        return {"flux": np.nan, "flux_err": np.nan,
                "background": np.nan, "n_pixels": 0}
    background = float(np.median(bg)) if bg.size else 0.0
    flux = float(np.sum(ap - background))
    # per-pixel noise variances, APERTURE and ANNULUS separately: the
    # aperture-sum term uses the aperture pixels' depth, the
    # background-median term (n^2 * var_bg / n_bg) the annulus pixels' —
    # mixing them misestimates flux_err whenever the two depths differ
    if weight_flat is not None:
        w_all = np.asarray(weight_flat, np.float64).reshape(-1)
        w_ap = w_all[sel_ap][fin_ap]
        w_bg = w_all[sel_bg][fin_bg]
        var_ap = float(np.nanmedian(1.0 / np.maximum(w_ap, 1e-30)))
        var_bg = (float(np.nanmedian(1.0 / np.maximum(w_bg, 1e-30)))
                  if w_bg.size else var_ap)
    elif bg.size > 1:
        var_bg = (1.4826 * float(np.median(np.abs(bg - background)))) ** 2
        var_ap = var_bg
    else:
        var_ap = var_bg = float(np.var(ap))
    err = np.sqrt(n * var_ap + (n * n / max(bg.size, 1)) * var_bg)
    return {"flux": flux, "flux_err": float(err),
            "background": background, "n_pixels": int(n)}


def fit_map_source(map_flat, wcs: WCS, lon0: float, lat0: float,
                   radius: float, weight_flat=None,
                   fwhm_deg: float = 0.075) -> dict:
    """2-D Gaussian fit of a source in a map cutout.

    Pixels within ``radius`` degrees of ``(lon0, lat0)`` are fitted with
    the rotated-Gaussian + offset model in source-relative plane
    coordinates (degrees). Returns the parameter dict with 1-sigma
    errors from the LM covariance:
    ``amplitude, dx, sigma_x, dy, sigma_y, angle, offset`` (+``_err``),
    plus ``chi2`` and ``n_pixels``.
    """
    import jax.numpy as jnp

    from comapreduce_tpu.calibration.fitting import (fit_gauss2d,
                                                     initial_guess)

    m = np.asarray(map_flat, np.float64).reshape(-1)
    sel, lon, lat = query_disc(wcs, lon0, lat0, radius)
    vals = m[sel]
    good = np.isfinite(vals)
    vals = vals[good]
    lon, lat = lon[good], lat[good]
    if vals.size < 10:
        return {"n_pixels": int(vals.size)}
    # source-relative plane coords: flat-sky about the source position
    dx = ((lon - lon0 + 180.0) % 360.0 - 180.0) * np.cos(np.radians(lat0))
    dy = lat - lat0
    if weight_flat is not None:
        w = np.asarray(weight_flat, np.float64).reshape(-1)[sel][good]
        w = np.where(np.isfinite(w) & (w > 0), w, 0.0)
    else:
        w = np.ones_like(vals)
    img = jnp.asarray(vals, jnp.float32)
    xj = jnp.asarray(dx, jnp.float32)
    yj = jnp.asarray(dy, jnp.float32)
    wj = jnp.asarray(w, jnp.float32)
    p0 = initial_guess(img, xj, yj, wj, fwhm_deg=fwhm_deg)
    p, err, chi2 = fit_gauss2d(img, xj, yj, wj, p0)
    p, err = np.asarray(p, np.float64), np.asarray(err, np.float64)
    names = ("amplitude", "dx", "sigma_x", "dy", "sigma_y", "angle",
             "offset")
    out = {k: float(v) for k, v in zip(names, p)}
    out.update({f"{k}_err": float(e) for k, e in zip(names, err)})
    out["chi2"] = float(chi2)
    out["n_pixels"] = int(vals.size)
    # fitted centre back on the sky
    out["lon"] = float((lon0 + out["dx"]
                        / max(np.cos(np.radians(lat0)), 1e-9)) % 360.0)
    out["lat"] = float(lat0 + out["dy"])
    return out
