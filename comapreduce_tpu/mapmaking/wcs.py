"""Minimal flat-sky FITS-WCS: TAN (gnomonic) and CAR (plate carrée).

The reference pipeline builds its map geometry from ``astropy.wcs`` with
``CTYPE in {RA---TAN, RA---CAR, GLON-CAR, ...}`` (``MapMaking/
run_destriper.py:118-128``, ``Tools/WCS.py:211-244``). astropy is not a
dependency of this framework; map geometry is simple enough to own:

- **TAN**: full gnomonic projection about the reference point, including the
  spherical rotation to/from native coordinates (FITS WCS paper II), valid at
  any declination. Used for per-source calibrator maps and CO fields.
- **CAR**: plate carrée — linear in (lon, lat) about the reference point.
  This matches astropy's CAR for ``crval2 == 0`` (the reference's galactic
  survey geometry, ``ParameterFiles/parameters_GFields.ini:26-29``); nonzero
  ``crval2`` keeps the same linear convention (documented divergence from the
  FITS rotated-CAR corner case).

All angles in degrees. Pixel convention is 0-based (like
``astropy.wcs.wcs_world2pix(..., 0)``, which the reference uses:
``Tools/WCS.py:240``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["WCS", "udgrade_map", "angular_separation",
           "query_disc", "query_annulus", "query_slice"]

D2R = np.pi / 180.0


def _rotation_to_native(lon_pole_deg, alpha_p, delta_p):
    """Rows of the celestial->native rotation matrix (all degrees)."""
    ap, dp, lp = alpha_p * D2R, delta_p * D2R, lon_pole_deg * D2R
    # R = Rz(lonpole - pi) Rx(pi/2 - delta_p) Rz(alpha_p + pi/2) is the
    # standard Euler chain; written out explicitly for clarity.
    ca, sa = np.cos(ap), np.sin(ap)
    cd, sd = np.cos(dp), np.sin(dp)
    cl, sl = np.cos(lp), np.sin(lp)
    r11 = -sa * sl - ca * cl * sd
    r12 = ca * sl - sa * cl * sd
    r13 = cl * cd
    r21 = sa * cl - ca * sl * sd
    r22 = -ca * cl - sa * sl * sd
    r23 = sl * cd
    r31 = ca * cd
    r32 = sa * cd
    r33 = sd
    return np.array([[r11, r12, r13], [r21, r22, r23], [r31, r32, r33]])


@dataclass(frozen=True)
class WCS:
    """A 2-D celestial WCS.

    Parameters mirror the FITS keywords the reference feeds astropy
    (``run_destriper.py:118-128``): ``crval`` (deg), ``cdelt`` (deg/pix,
    cdelt[0] typically negative for RA), ``crpix`` (0-based reference pixel),
    ``ctype`` like ``("RA---TAN", "DEC--TAN")``, and image shape
    ``(nx, ny)``.
    """

    crval: tuple[float, float]
    cdelt: tuple[float, float]
    crpix: tuple[float, float]
    ctype: tuple[str, str] = ("RA---TAN", "DEC--TAN")
    shape: tuple[int, int] = (480, 480)  # (nx, ny)
    _rot: np.ndarray = field(init=False, repr=False, compare=False)

    def __post_init__(self):
        proj = self.projection
        if proj == "TAN":
            # zenithal: fiducial native lat 90deg, default LONPOLE=180
            object.__setattr__(
                self, "_rot",
                _rotation_to_native(180.0, self.crval[0], self.crval[1]))
        elif proj != "CAR":
            raise ValueError(f"unsupported projection {proj!r}")
        else:
            object.__setattr__(self, "_rot", np.eye(3))

    # -- properties ------------------------------------------------------
    @property
    def projection(self) -> str:
        return self.ctype[0][-3:]

    @property
    def nx(self) -> int:
        return self.shape[0]

    @property
    def ny(self) -> int:
        return self.shape[1]

    @property
    def npix(self) -> int:
        return self.nx * self.ny

    # -- core transforms -------------------------------------------------
    def world2plane(self, lon, lat):
        """Celestial (deg) -> intermediate plane coords (deg)."""
        lon = np.asarray(lon, dtype=np.float64)
        lat = np.asarray(lat, dtype=np.float64)
        if self.projection == "CAR":
            dlon = (lon - self.crval[0] + 180.0) % 360.0 - 180.0
            return dlon, lat - self.crval[1]
        # TAN: rotate to native, gnomonic project
        cl, sl = np.cos(lon * D2R), np.sin(lon * D2R)
        cb, sb = np.cos(lat * D2R), np.sin(lat * D2R)
        vec = np.stack([cb * cl, cb * sl, sb], axis=-1)
        R = self._rot
        nx = vec @ R[0]
        ny_ = vec @ R[1]
        nz = vec @ R[2]
        # with LONPOLE=180 the rows reduce to the classic standard
        # coordinates: xi = ny/nz (east), eta = -nx/nz (north)
        nz_safe = np.where(nz > 1e-12, nz, np.nan)  # behind tangent plane
        x = (ny_ / nz_safe) / D2R
        y = (-nx / nz_safe) / D2R
        return x, y

    def plane2world(self, x, y):
        """Intermediate plane coords (deg) -> celestial (deg)."""
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        if self.projection == "CAR":
            return (x + self.crval[0]) % 360.0, y + self.crval[1]
        R = self._rot
        xr, yr = x * D2R, y * D2R
        denom = np.sqrt(1.0 + xr * xr + yr * yr)
        nvec = np.stack([-yr / denom, xr / denom, 1.0 / denom], axis=-1)
        cel = nvec @ R  # R^T applied to native vector: R rows are native axes
        lon = (np.arctan2(cel[..., 1], cel[..., 0]) / D2R) % 360.0
        lat = np.arcsin(np.clip(cel[..., 2], -1.0, 1.0)) / D2R
        return lon, lat

    def world2pix(self, lon, lat):
        """Celestial (deg) -> continuous 0-based pixel coords (px, py)."""
        x, y = self.world2plane(lon, lat)
        px = x / self.cdelt[0] + self.crpix[0]
        py = y / self.cdelt[1] + self.crpix[1]
        return px, py

    def pix2world(self, px, py):
        x = (np.asarray(px, dtype=np.float64) - self.crpix[0]) * self.cdelt[0]
        y = (np.asarray(py, dtype=np.float64) - self.crpix[1]) * self.cdelt[1]
        return self.plane2world(x, y)

    def ang2pix(self, lon, lat):
        """Celestial (deg) -> flat pixel index ``iy * nx + ix``; -1 outside.

        Parity: ``Tools/WCS.py:234-249`` (``ang2pixWCS``), which also flattens
        as ``py * nx + px`` and marks out-of-range pixels invalid.
        """
        px, py = self.world2pix(lon, lat)
        with np.errstate(invalid="ignore"):
            ix = np.floor(px + 0.5).astype(np.int64)
            iy = np.floor(py + 0.5).astype(np.int64)
        bad = (~np.isfinite(px) | ~np.isfinite(py)
               | (ix < 0) | (ix >= self.nx) | (iy < 0) | (iy >= self.ny))
        return np.where(bad, -1, iy * self.nx + ix)

    def pixel_centers(self):
        """(lon, lat) of every pixel, each shaped (ny, nx).

        Cached: the geometry is immutable in practice, and the region
        queries / photometry call this repeatedly per source."""
        cached = getattr(self, "_centers", None)
        if cached is None:
            py, px = np.mgrid[0 : self.ny, 0 : self.nx]
            cached = self.pix2world(px, py)
            object.__setattr__(self, "_centers", cached)
        return cached

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_field(cls, crval, cdelt, shape, ctype=("RA---TAN", "DEC--TAN")):
        """Centered geometry like the reference's map params
        (``run_destriper.py:118-128``: crpix = shape/2)."""
        crpix = (shape[0] / 2.0, shape[1] / 2.0)
        return cls(tuple(crval), tuple(cdelt), crpix, tuple(ctype),
                   tuple(shape))

    def header_cards(self):
        """FITS header cards describing this WCS (1-based CRPIX)."""
        return {
            "CTYPE1": self.ctype[0], "CTYPE2": self.ctype[1],
            "CRVAL1": self.crval[0], "CRVAL2": self.crval[1],
            "CDELT1": self.cdelt[0], "CDELT2": self.cdelt[1],
            "CRPIX1": self.crpix[0] + 1, "CRPIX2": self.crpix[1] + 1,
        }


# -- map regridding and region queries (Tools/WCS.py capabilities) ----------

def _is_galactic(wcs: "WCS") -> bool:
    return str(wcs.ctype[0]).upper().startswith("GLON")


def _to_frame_of(lon, lat, wcs_from: "WCS", wcs_to: "WCS"):
    """Convert coordinates between the frames implied by two WCS ctypes
    (equatorial <-> galactic, ``udgrade_map_wcs`` behavior)."""
    if _is_galactic(wcs_from) == _is_galactic(wcs_to):
        return lon, lat
    from comapreduce_tpu.astro.coordinates import e2g, g2e

    return (g2e(lon, lat) if _is_galactic(wcs_from) else e2g(lon, lat))


def angular_separation(lon1, lat1, lon2, lat2):
    """Great-circle separation in degrees (haversine; stable at small
    angles, unlike the planar approximation)."""
    l1, b1 = np.asarray(lon1) * D2R, np.asarray(lat1) * D2R
    l2, b2 = np.asarray(lon2) * D2R, np.asarray(lat2) * D2R
    s = (np.sin((b2 - b1) / 2.0) ** 2
         + np.cos(b1) * np.cos(b2) * np.sin((l2 - l1) / 2.0) ** 2)
    return 2.0 * np.arcsin(np.minimum(np.sqrt(s), 1.0)) / D2R


def udgrade_map(map_in, wcs_in: "WCS", wcs_out: "WCS", variance=None):
    """Re-pixelise ``map_in`` onto ``wcs_out`` (the reference's
    ``udgrade_map_wcs``, ``Tools/WCS.py:275-350``): every input pixel's
    value is inverse-variance binned into the output pixel containing its
    centre, with automatic equatorial<->galactic conversion when the two
    geometries differ. Returns ``(map_out, var_out)`` with NaN where the
    output is unhit."""
    m = np.asarray(map_in, np.float64).reshape(-1)
    if m.size != wcs_in.npix:
        raise ValueError(f"map size {m.size} != wcs_in.npix {wcs_in.npix}")
    var = (np.ones_like(m) if variance is None
           else np.asarray(variance, np.float64).reshape(-1))
    lon, lat = wcs_in.pixel_centers()
    lon, lat = _to_frame_of(lon.ravel(), lat.ravel(), wcs_in, wcs_out)
    pix = wcs_out.ang2pix(lon, lat)
    good = (pix >= 0) & np.isfinite(m) & np.isfinite(var) & (var > 0)
    # bincount, not np.add.at: same scatter-add an order of magnitude
    # faster on survey-size maps
    num = np.bincount(pix[good], weights=m[good] / var[good],
                      minlength=wcs_out.npix).astype(np.float64)
    den = np.bincount(pix[good], weights=1.0 / var[good],
                      minlength=wcs_out.npix).astype(np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        map_out = np.where(den > 0, num / den, np.nan)
        var_out = np.where(den > 0, 1.0 / den, np.nan)
    return map_out, var_out


def query_disc(wcs: "WCS", lon0, lat0, radius_deg):
    """Flat-pixel mask + coordinates of pixels within ``radius_deg`` of
    ``(lon0, lat0)`` (``Tools/WCS.py:35-47``; true great-circle radius
    here). Returns ``(mask[npix], lon_sel, lat_sel)``."""
    lon, lat = wcs.pixel_centers()
    lon, lat = lon.ravel(), lat.ravel()
    r = angular_separation(lon0, lat0, lon, lat)
    sel = np.isfinite(r) & (r < radius_deg)
    return sel, lon[sel], lat[sel]


def query_annulus(wcs: "WCS", lon0, lat0, r_in, r_out):
    """Flat-pixel INDICES + coordinates within the annulus
    ``r_in <= r < r_out`` (``Tools/WCS.py:48-59``)."""
    lon, lat = wcs.pixel_centers()
    lon, lat = lon.ravel(), lat.ravel()
    r = angular_separation(lon0, lat0, lon, lat)
    idx = np.where(np.isfinite(r) & (r >= r_in) & (r < r_out))[0]
    return idx, lon[idx], lat[idx]


def query_slice(wcs: "WCS", lon0, lat0, lon1, lat1, width=None):
    """Pixels within ``width`` of the line (lon0,lat0)-(lon1,lat1) and
    inside its bounding segment (``Tools/WCS.py:61-86``; the reference
    thresholds the VERTICAL offset, which collapses for steep lines —
    here the true perpendicular distance is used, branch-free, in a
    lon-unwrapped local frame so RA 0/360 crossings work). Returns
    ``(mask[npix], lon_sel, lat_sel, dist_from_start)``."""
    lon, lat = wcs.pixel_centers()
    lon, lat = lon.ravel(), lat.ravel()
    if width is None:
        width = abs(wcs.cdelt[1])

    def unwrap(lo):
        return (np.asarray(lo, np.float64) - lon0 + 180.0) % 360.0 - 180.0

    # cos(lat) metric on the lon axis: a lon degree is smaller on the
    # sky, and without it the strip's true width depends on orientation
    clat = max(np.cos(np.radians((lat0 + lat1) / 2.0)), 1e-9)
    x, y = unwrap(lon) * clat, lat
    x0, y0 = 0.0, float(lat0)
    x1, y1 = float(unwrap(lon1)) * clat, float(lat1)
    dx, dy = x1 - x0, y1 - y0
    norm = max(np.hypot(dx, dy), 1e-12)
    off = np.abs(dx * (y0 - y) - (x0 - x) * dy) / norm
    x_mid, y_mid = (x0 + x1) / 2.0, (y0 + y1) / 2.0
    x_hw = abs(dx) / 2.0 or width
    y_hw = abs(dy) / 2.0 or width
    sel = ((off < width) & (np.abs(x - x_mid) < x_hw + width)
           & (np.abs(y - y_mid) < y_hw + width))
    dist = angular_separation(lon0, lat0, lon[sel], lat[sel])
    return sel, lon[sel], lat[sel], dist
