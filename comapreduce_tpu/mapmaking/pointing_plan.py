"""Static pointing plan: scatter-free destriper binning on TPU.

XLA lowers ``segment_sum``/scatter-add onto TPU as a serialized scatter —
measured ~75 ms per 10M-sample binning on a v5e, which made the destriper
CG (one bin per matvec, ``Destriper.py:217-263``) two orders of magnitude
slower than the memory bound. The pointing never changes across CG
iterations, so all data-dependent index structure can be computed ONCE on
host and the per-iteration work recast as dense MXU math:

1. **Compact ranks**: unique hit pixels -> rank space (the reference's
   seen-pixel compaction, ``COMAPData.py:43-70,570-574``), so map vectors
   are ~#hit-pixels, not npix.
2. **(rank, offset) pairs**: within one destriper offset (L consecutive
   samples) the telescope crosses only ~10-20 pixels, so the weighted
   pointing matrix ``P^T W F`` has one aggregate per (pixel, offset) pair —
   ~4x fewer entries than samples. The CG matvec runs entirely in pair
   space.
3. **Windowed one-hot binning**: pairs sorted by rank (or offset) are
   binned in fixed chunks; within a chunk every id lies in a static
   ``[base, base+window)`` range, so binning is an equality one-hot times
   values — an MXU matmul — plus one tiny (n_chunks*window) assembly
   scatter. No large scatter ever runs.

The plan is plain numpy (host, built once per pointing); ``device()``
uploads the index arrays. ``mapmaking.destriper.destripe_planned`` consumes
it.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.mapmaking.pixel_space import PixelSpace, resolve_npix

__all__ = ["PointingPlan", "build_pointing_plan", "build_sharded_plans",
           "binned_window_sum"]


def _resolve_pixel_space(pixels, npix, pixel_space):
    """Shared plan-entry rule: remap GLOBAL sky pixels through a
    compacted :class:`PixelSpace` ONCE per plan build (the sentinel
    ``n_solve`` rides the existing invalid-pixel path), or resolve a
    ``PixelSpace`` passed as ``npix`` (pixels then already solver
    ids). A mismatched ``npix``/``pixel_space`` pair raises (the data
    layer's rule) — remapping against a wrong-resolution dictionary
    would silently sentinel-ise or misplace most samples."""
    if pixel_space is not None:
        n = resolve_npix(npix)
        if n not in (pixel_space.npix_sky, pixel_space.n_solve):
            raise ValueError(
                f"npix {n} matches neither pixel_space.npix_sky "
                f"{pixel_space.npix_sky} nor its n_solve "
                f"{pixel_space.n_solve} — wrong-resolution dictionary?")
        return pixel_space.remap(pixels), pixel_space.n_solve
    return pixels, resolve_npix(npix)


def _round_up(x: int, q: int) -> int:
    return -(-int(x) // q) * q


@dataclass
class PointingPlan:
    """Static index structure for one pointing (see module docstring).

    Sample arrays are in *sorted* order (by (rank, offset)); device data
    enters through ``sample_perm``. Pair arrays come in two orders: rank
    order (for pair->map binning) and offset order (for pair->offset
    binning), linked by ``pair_perm_off``.
    """

    npix: int
    offset_length: int
    n_offsets: int
    n_rank: int                      # unique hit pixels
    uniq_pixels: np.ndarray          # i64[n_rank] rank -> global pixel
    # sample space (length N_pad, sorted by (rank, offset))
    sample_perm: np.ndarray          # i32[N_pad] gather: x_sorted = x[perm]
    sample_pair: np.ndarray          # i32[N_pad] pair id per sorted sample
    sample_chunk: int
    sample_window: int
    sample_base: np.ndarray          # i32[n_s_chunks] pair-id base per chunk
    # pair space, rank order (length P_pad)
    n_pairs: int                     # valid pairs (excludes trash/padding)
    pair_rank: np.ndarray            # i32[P_pad]
    pair_offset: np.ndarray          # i32[P_pad]
    pair_chunk: int
    rank_window: int
    rank_base: np.ndarray            # i32[n_p_chunks] rank base per chunk
    # pair space, offset order
    pair_perm_off: np.ndarray        # i32[P_pad]: x_off = x_rank[perm]
    off_window: int
    off_base: np.ndarray             # i32[n_p_chunks] offset base per chunk
    # chunks merged per binning step (pair_chunk above is the EFFECTIVE
    # chunk = base chunk x pair_batch; see build_pointing_plan)
    pair_batch: int = 1
    # sharded-plan extras (build_sharded_plans): the shard's LOCAL rank
    # space keeps binning windows dense; these map it into the global
    # compact space for the cross-shard psum
    rank_to_global: np.ndarray | None = None  # i32[n_rank] (global sentinel
    #                                           n_rank_global on padding)
    n_rank_global: int = 0
    uniq_global: np.ndarray | None = None     # i64[n_rank_global]
    _device: dict = field(default_factory=dict, repr=False)

    def device(self) -> dict:
        """Upload (and cache) the index arrays as device i32 arrays.

        Called both eagerly and under ``jit`` tracing. Under a trace the
        converted arrays are TRACERS of that trace — caching them would
        leak stale tracers into the next differently-shaped trace of the
        same (memoized) plan (observed: the single-band solver's trace
        poisoning a later multi-RHS retrace). Cache only concrete
        arrays."""
        if not self._device:
            arrs = {
                k: jnp.asarray(getattr(self, k), jnp.int32)
                for k in ("sample_perm", "sample_pair", "sample_base",
                          "pair_rank", "pair_offset", "rank_base",
                          "pair_perm_off", "off_base", "uniq_pixels")}
            if self.rank_to_global is not None:
                arrs["rank_to_global"] = jnp.asarray(
                    self.rank_to_global, jnp.int32)
            if any(isinstance(v, jax.core.Tracer) for v in arrs.values()):
                return arrs   # mid-trace: hand back, never cache
            self._device = arrs
        return self._device


def _window_layout(ids_sorted: np.ndarray, chunk: int, align: int = 128):
    """Per-chunk base ids and the window width covering every chunk's span.

    ``ids_sorted`` must be ascending; the caller pads its length to a chunk
    multiple beforehand.
    """
    n_chunks = len(ids_sorted) // chunk
    blocks = ids_sorted.reshape(n_chunks, chunk)
    base = blocks[:, 0].astype(np.int64)
    span = blocks[:, -1] - base + 1
    window = _round_up(max(int(span.max()), 1), align)
    return base.astype(np.int32), int(window)


def _resolve_pair_batch(pair_batch) -> int:
    """Normalise the knob: explicit int >= 1 pins it; None reads
    ``COMAP_PAIR_BATCH`` (int, or unset/0/"auto" = HBM-planner auto)."""
    if pair_batch is None:
        env = os.environ.get("COMAP_PAIR_BATCH", "").strip().lower()
        if env in ("", "auto", "0"):
            return 0
        return max(int(env), 1)
    return max(int(pair_batch), 0)


# one-hot budget of the auto-sizer: the merged chunk's (chunk, window)
# equality matrix is the per-step live block of binned_window_sum; cap it
# at a small HBM fraction so batching never eats the solve's headroom
_PAIR_BATCH_CANDIDATES = (8, 4, 2, 1)


def _auto_pair_batch_budget() -> int:
    from comapreduce_tpu.ops.reduce import device_hbm_bytes

    return max(device_hbm_bytes() // 64, 64 << 20)


def _mxu_backend() -> bool:
    """Auto pair-batching is an MXU trade: the merged chunk's one-hot
    window grows ~quadratically with the batch, which a systolic matmul
    unit absorbs while the trip-count/dispatch saving pays. Off-TPU the
    wider contraction is plain FLOPs — measured 4x SLOWER at batch 8 on
    CPU — so auto stays at 1 there; explicit knobs still pin any value
    (the CPU parity tests exercise the merged layout that way)."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def build_pointing_plan(pixels: np.ndarray, npix: int, offset_length: int,
                        sample_chunk: int = 8192,
                        pair_chunk: int = 4096,
                        min_pair_pad: int = 0,
                        min_windows: tuple = (0, 0, 0),
                        pair_batch: int | None = None,
                        pixel_space: PixelSpace | None = None
                        ) -> PointingPlan:
    """Build the static plan for one flat pointing vector.

    ``pixel_space``: a compacted seen-pixel dictionary — ``pixels`` are
    then GLOBAL sky ids, remapped here once per plan (the plan's
    ``npix`` becomes ``n_compact`` and ``uniq_pixels`` index the
    dictionary, not the sky). ``npix`` alone may also be a
    ``PixelSpace`` when the pixels are already solver ids.

    ``pixels``: integer pixel per sample (invalid = negative or >= npix);
    length must be a multiple of ``offset_length`` (sample t belongs to
    offset ``t // L``, ``OffsetTypes.py:11-54``). Invalid samples keep
    their true offset but carry the sentinel rank ``n_rank``: they
    participate in offset-domain sums (same semantics as the scatter path,
    where an invalid sample reads 0 from the map but its weight still
    enters ``F^T W``) while their map-domain sums land in a padding slot
    that is sliced away.

    ``pair_batch`` merges that many consecutive ``pair_chunk`` windows
    into ONE binning step: the plan's effective pair chunk becomes
    ``pair_chunk * pair_batch`` and every per-CG-iteration
    ``binned_window_sum`` contracts ``pair_batch`` windows in a single
    MXU matmul — the ``lax.map``/``fori`` trip count drops by the same
    factor (the round-3 "next lever (c)", raised per ISSUE 4). The
    window widens with the merged chunk's id span, so the one-hot grows
    ~quadratically with the batch; ``None`` (default) auto-sizes via the
    HBM planner — the largest candidate whose merged one-hot fits a
    small budget (``device_hbm_bytes()/64``, >= 64 MiB), on MXU
    backends only (auto = 1 off-TPU; see ``_mxu_backend``) — and
    ``COMAP_PAIR_BATCH`` pins it (1 = the pre-batching layout). Merged
    chunks change the f32 accumulation grouping, so results are equal to
    the unbatched plan only to rounding, not bit-for-bit.
    """
    pixels, npix = _resolve_pixel_space(pixels, npix, pixel_space)
    pixels = np.asarray(pixels).astype(np.int64).ravel()
    N = pixels.size
    if N % offset_length:
        raise ValueError(f"N={N} not a multiple of L={offset_length}")
    n_offsets = N // offset_length
    offs = np.arange(N, dtype=np.int64) // offset_length
    valid = (pixels >= 0) & (pixels < npix)

    uniq = np.unique(pixels[valid])
    n_rank = int(uniq.size)
    rank = np.full(N, n_rank, dtype=np.int64)
    rank[valid] = np.searchsorted(uniq, pixels[valid])

    # sort samples by (rank, offset); invalid (rank = n_rank) sort last
    # but keep their true offset so offset-domain sums see them
    key = rank * n_offsets + offs
    perm = np.argsort(key, kind="stable")
    skey = key[perm]

    new_pair = np.empty(N, dtype=bool)
    new_pair[0] = True
    np.not_equal(skey[1:], skey[:-1], out=new_pair[1:])
    pair_id = np.cumsum(new_pair) - 1
    n_pairs_all = int(pair_id[-1]) + 1
    n_pairs = n_pairs_all

    firsts = np.flatnonzero(new_pair)
    pair_rank = rank[perm][firsts]
    pair_offset = offs[perm][firsts]

    # ---- pad sample space to a chunk multiple ---------------------------
    N_pad = _round_up(max(N, 1), sample_chunk)
    sample_perm = np.concatenate(
        [perm, np.zeros(N_pad - N, np.int64)]).astype(np.int32)
    # padding samples point at slot 0's data but carry the sentinel pair id
    # n_pairs_all, whose sums land in the sliced-off padding region
    sample_pair = np.concatenate(
        [pair_id, np.full(N_pad - N, n_pairs_all, np.int64)])
    sample_base, sample_window = _window_layout(sample_pair, sample_chunk)
    sample_pair = sample_pair.astype(np.int32)

    # ---- pad pair space to a chunk multiple -----------------------------
    # (min_pair_pad / min_windows let per-shard plans share one compiled
    # program: every shard pads to the fleet maxima)
    def pair_layout(chunk_eff):
        P_pad = _round_up(max(n_pairs_all, 1, min_pair_pad), chunk_eff)
        pad = P_pad - n_pairs_all
        # padding pairs carry sentinel rank n_rank / offset n_offsets
        pr = np.concatenate([pair_rank, np.full(pad, n_rank, np.int64)])
        po = np.concatenate(
            [pair_offset, np.full(pad, n_offsets, np.int64)])
        rank_base, rank_window = _window_layout(pr, chunk_eff)
        # offset-order view (pairs sorted by (offset, rank))
        okey = po * (n_rank + 1) + pr
        perm_off = np.argsort(okey, kind="stable")
        off_base, off_window = _window_layout(po[perm_off], chunk_eff)
        return (pr, po, rank_base, rank_window, perm_off, off_base,
                off_window)

    pb = _resolve_pair_batch(pair_batch)
    if pb == 0:
        # [tuning]: a MEASURED winner for this (N, L) bucket outranks
        # both the MXU heuristic and the HBM budget walk below —
        # measurement is exactly what those two approximate. Disabled
        # (absent [tuning] table) this is one attribute check and the
        # auto path is byte-identical to the untuned planner.
        from comapreduce_tpu.tuning.cache import TUNING

        if TUNING.enabled:
            from comapreduce_tpu.tuning.space import plan_bucket

            win = TUNING.winner("plan", plan_bucket(N, offset_length))
            if win and win.get("pair_batch"):
                pb = max(int(win["pair_batch"]), 1)
    if pb == 0 and not _mxu_backend():
        pb = 1  # merged windows only pay on the MXU (see _mxu_backend)
    if pb == 0:  # auto: largest candidate whose merged one-hot fits
        budget = _auto_pair_batch_budget()
        for cand in _PAIR_BATCH_CANDIDATES:
            layout = pair_layout(pair_chunk * cand)
            onehot = pair_chunk * cand * max(layout[3], layout[6],
                                             int(min_windows[1]),
                                             int(min_windows[2])) * 4
            pb = cand
            if onehot <= budget:
                break
    else:
        layout = pair_layout(pair_chunk * pb)
    pair_chunk = pair_chunk * pb
    (pair_rank, pair_offset, rank_base, rank_window, pair_perm_off,
     off_base, off_window) = layout
    sample_window = max(sample_window, int(min_windows[0]))
    rank_window = max(rank_window, int(min_windows[1]))
    off_window = max(off_window, int(min_windows[2]))

    return PointingPlan(
        npix=int(npix), offset_length=int(offset_length),
        n_offsets=int(n_offsets), n_rank=n_rank,
        uniq_pixels=uniq,
        sample_perm=sample_perm, sample_pair=sample_pair,
        sample_chunk=int(sample_chunk), sample_window=sample_window,
        sample_base=sample_base,
        n_pairs=n_pairs, pair_rank=pair_rank.astype(np.int32),
        pair_offset=pair_offset.astype(np.int32),
        pair_chunk=int(pair_chunk),
        rank_window=rank_window, rank_base=rank_base,
        pair_perm_off=pair_perm_off.astype(np.int32),
        off_window=off_window, off_base=off_base, pair_batch=pb)


def build_sharded_plans(pixels: np.ndarray, npix: int, offset_length: int,
                        n_shards: int, sample_chunk: int = 8192,
                        pair_chunk: int = 4096,
                        pair_batch: int | None = None,
                        pixel_space: PixelSpace | None = None
                        ) -> list[PointingPlan]:
    """Per-shard plans over contiguous time shards with identical static
    shapes (one compiled SPMD program) and a shared GLOBAL compact space.

    Each shard compacts into its own LOCAL rank space — local ranks are
    dense, so the one-hot binning windows stay narrow (a shared global
    space would make a shard's pairs sparse in rank and blow the window to
    ~the whole hit set). ``rank_to_global`` then scatters the shard's
    compact sums into the global hit-pixel space for the cross-shard
    ``psum`` (the reference's allgather'd seen-pixel compaction,
    ``COMAPData.py:43-70,570-574``). Memory stays bounded by hit pixels,
    never ``npix`` (SURVEY hard part 3, nside-4096 HEALPix destriping).
    ``pixel_space`` (or a ``PixelSpace`` as ``npix``) remaps once here,
    exactly as in :func:`build_pointing_plan` — the global compact index
    space every shard psums over then IS the campaign seen-pixel
    dictionary, so every shard (and any other solve sharing the
    dictionary) agrees on the compacted ids.
    """
    pixels, npix = _resolve_pixel_space(pixels, npix, pixel_space)
    pixels = np.asarray(pixels).astype(np.int64).ravel()
    N = pixels.size
    quantum = n_shards * offset_length
    if N % quantum:
        raise ValueError(f"N={N} not a multiple of "
                         f"n_shards*L={quantum}; pad first")
    shard_n = N // n_shards
    valid = (pixels >= 0) & (pixels < npix)
    uniq_global = np.unique(pixels[valid])
    n_rank_global = int(uniq_global.size)
    shards = [pixels[i * shard_n:(i + 1) * shard_n]
              for i in range(n_shards)]

    def build_all(min_pair_pad=0, wins=(0, 0, 0), pb=pair_batch):
        return [build_pointing_plan(s, npix, offset_length,
                                    sample_chunk=sample_chunk,
                                    pair_chunk=pair_chunk,
                                    min_pair_pad=min_pair_pad,
                                    min_windows=wins,
                                    pair_batch=pb)
                for s in shards]

    plans = build_all()
    # the shared compiled program needs ONE static layout: auto
    # pair_batch may differ per shard — force the MINIMUM (the batch
    # every shard's one-hot budget accepted) before equalising windows,
    # so the window maxima are measured at the final merged chunk
    pb = min(p.pair_batch for p in plans)
    if any(p.pair_batch != pb for p in plans):
        plans = build_all(pb=pb)
    # second pass: equalise pair padding and window widths across shards
    p_max = max(p.pair_rank.shape[0] for p in plans)
    wins = (max(p.sample_window for p in plans),
            max(p.rank_window for p in plans),
            max(p.off_window for p in plans))
    if (any(p.pair_rank.shape[0] != p_max for p in plans)
            or any((p.sample_window, p.rank_window, p.off_window) != wins
                   for p in plans)):
        plans = build_all(min_pair_pad=p_max, wins=wins, pb=pb)

    # local -> global rank maps, local rank space padded to a common size.
    # A shard's pairs keep their local sentinel rank (= that shard's own
    # n_rank); after padding, slot n_rank_local maps to the global
    # sentinel, so invalid/trash sums still drop in the global scatter.
    n_rank_max = max(p.n_rank for p in plans)
    import dataclasses

    out = []
    for p in plans:
        l2g = np.full(n_rank_max, n_rank_global, np.int64)
        l2g[:p.n_rank] = np.searchsorted(uniq_global, p.uniq_pixels)
        uniq_pad = np.concatenate(
            [p.uniq_pixels,
             np.full(n_rank_max - p.n_rank, npix, np.int64)])
        out.append(dataclasses.replace(
            p, n_rank=n_rank_max, uniq_pixels=uniq_pad,
            rank_to_global=l2g, n_rank_global=n_rank_global,
            uniq_global=uniq_global, _device={}))
    return out


def binned_window_sum(values: jax.Array, ids: jax.Array, base: jax.Array,
                      window: int, chunk: int, out_size: int,
                      batch: int | None = None,
                      impl: str | None = None) -> jax.Array:
    """Sum ``values`` into ``out[..., id]`` for pre-sorted, chunk-windowed
    ids.

    ``values``: f32[..., M]; ``ids``: i32[M] with ``M % chunk == 0`` and
    every id of chunk c inside ``[base[c], base[c] + window)`` (ids
    outside — sentinels — are dropped). The inner product against the
    equality one-hot is an MXU matmul (f32-exact: one-hot entries are
    0/1). Two implementations, selected by ``COMAP_BIN_IMPL``:
    ``fori`` (default) streams chunks through one ordered
    ``fori_loop`` — dynamic-slice, contract, read-modify-write
    ``dynamic_update_slice`` assembly, no scatter at all; ``map`` is
    the older batched ``lax.map`` path whose only remaining scatter is
    the ``n_chunks * window`` window assembly.

    Leading axes of ``values`` (the multi-RHS destriper's band axis) ride
    through: the one-hot is built ONCE per chunk and contracted against
    every band's value row in the same matmul.

    ``batch=None`` reads the ``COMAP_BIN_BATCH`` env default (8) — the
    round-3 "next lever (c)" sweep knob, meaningful only under
    ``COMAP_BIN_IMPL=map``: larger batches amortise
    ``lax.map`` chunk streaming at the cost of a bigger live one-hot.
    The env value binds at FIRST TRACE per input shape: ``jax.jit``
    caches executables per shape, so a same-shape re-call never
    retraces and a changed env value is silently ignored in-process.
    To sweep it, either spawn a fresh process per point (what
    ``tools/onchip_sweep.py`` does), call ``jax.clear_caches()``
    between points, or pass ``batch`` explicitly as an argument.
    ``COMAP_BIN_IMPL`` binds the same way — an in-process impl A/B at
    one shape needs fresh processes or ``jax.clear_caches()``, or the
    cached executable silently keeps the first impl.

    ``impl`` (PR 11) overrides the env dispatch from code — the
    ``[Destriper] kernels`` knob resolves to it at trace time in
    ``destripe_planned``.  ``None`` keeps the env path byte-identical
    to before the knob existed; ``"xla"`` forces the fori path;
    ``"pallas"``/``"interpret"`` route to the Mosaic segment-scatter
    kernel (``mapmaking/pallas_binning.py``) when
    ``pallas_binning_ok`` accepts the shape, silently falling back to
    the fori path otherwise (the kernel's VMEM gate is shape-dependent
    and parity holds either way — see the kernel module docstring).
    """
    if batch is None:
        batch = int(os.environ.get("COMAP_BIN_BATCH", "8"))
    if impl in ("pallas", "interpret"):
        if values.dtype == jnp.float32:
            from comapreduce_tpu.mapmaking.pallas_binning import (
                binned_window_sum_pallas, pallas_binning_ok)
            rows = 1
            for d in values.shape[:-1]:
                rows *= int(d)
            if pallas_binning_ok(window, chunk, rows=rows,
                                 interpret=(impl == "interpret")):
                return binned_window_sum_pallas(
                    values, ids, base, window, chunk, out_size,
                    interpret=(impl == "interpret"))
        impl = "xla"
    if impl == "xla":
        return _binned_window_sum_fori(values, ids, base, window, chunk,
                                       out_size)
    # default impl: the ordered fori loop — measured on-chip (round 5)
    # at production multi-RHS shape it takes the destriper 2.09 s ->
    # 1.59 s (full bench wall 4.00 s -> 3.50 s) by eliminating the
    # chunk-major transpose, the lax.map slicing, and the serialized
    # assembly scatter. COMAP_BIN_IMPL=map restores the batched-map
    # path (where COMAP_BIN_BATCH applies) for A/B.
    impl = os.environ.get("COMAP_BIN_IMPL", "fori")
    if impl == "fori":
        return _binned_window_sum_fori(values, ids, base, window, chunk,
                                       out_size)
    M = values.shape[-1]
    lead = values.shape[:-1]
    n_chunks = M // chunk
    # chunk axis FIRST so lax.map streams it; bands stay minor
    v = jnp.moveaxis(values.reshape(lead + (n_chunks, chunk)), -2, 0)
    ids_c = ids.reshape(n_chunks, chunk)

    def body(args):
        v_c, id_c, b_c = args                      # (..., chunk), (chunk,)
        local = id_c - b_c
        oh = (local[:, None] == jnp.arange(window)[None, :])
        return jax.lax.dot_general(
            v_c, oh.astype(v_c.dtype),
            (((v_c.ndim - 1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)   # (..., window)

    part = jax.lax.map(body, (v, ids_c, base), batch_size=batch)
    part = jnp.moveaxis(part, 0, -2)               # (..., n_chunks, window)
    out = jnp.zeros(lead + (out_size + window,), values.dtype)
    idx = (base[:, None].astype(jnp.int32)
           + jnp.arange(window, dtype=jnp.int32)[None, :])
    out = out.at[..., idx.reshape(-1)].add(
        part.reshape(lead + (n_chunks * window,)), mode="drop")
    return out[..., :out_size]


def _binned_window_sum_fori(values: jax.Array, ids: jax.Array,
                            base: jax.Array, window: int, chunk: int,
                            out_size: int) -> jax.Array:
    """``binned_window_sum`` as ONE ordered ``fori_loop`` (A/B via
    ``COMAP_BIN_IMPL=fori``): per chunk, dynamic-slice the values (no
    chunk-major transpose of the whole pair space), contract against
    the equality one-hot on the MXU, and assemble by a read-modify-
    write ``dynamic_update_slice`` into the output window — overlap
    between consecutive chunks' windows is safe because the loop is
    ordered, and no serialized per-element scatter ever runs. Same
    result bit-for-bit (each output element is a sum of the same
    values in the same chunk order)."""
    M = values.shape[-1]
    lead = values.shape[:-1]
    n_chunks = M // chunk
    ids_c = ids.reshape(n_chunks, chunk)
    col = jnp.arange(window, dtype=jnp.int32)[None, :]
    out0 = jnp.zeros(lead + (out_size + window,), values.dtype)

    def step(c, out):
        v_c = jax.lax.dynamic_slice_in_dim(values, c * chunk, chunk,
                                           axis=-1)
        id_c = jax.lax.dynamic_index_in_dim(ids_c, c, keepdims=False)
        # clamp the window start BEFORE building the one-hot: landing
        # positions stay absolute (start + local == id) and ids whose
        # window falls outside [0, out_size] DROP via the one-hot,
        # matching the map path's mode="drop" — dynamic_update_slice
        # alone would clamp the start and silently shift such sums
        # into the last real bins
        b_c = jnp.clip(base[c], 0, out_size)
        oh = ((id_c - b_c)[:, None] == col)
        part = jax.lax.dot_general(
            v_c, oh.astype(v_c.dtype),
            (((v_c.ndim - 1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)   # (..., window)
        cur = jax.lax.dynamic_slice_in_dim(out, b_c, window, axis=-1)
        return jax.lax.dynamic_update_slice_in_dim(out, cur + part, b_c,
                                                   axis=-1)

    out = jax.lax.fori_loop(0, n_chunks, step, out0)
    return out[..., :out_size]
