"""HEALPix pixelization (RING and NESTED), vectorized numpy.

The reference depends on ``healpy`` for map-making at nside 4096
(``MapMaking/COMAPData.py:429-469`` ``read_pixels_healpix``,
``run_destriper.py:53-77`` partial-map output). healpy is not in this
image, and the subset the pipeline needs — ``ang2pix``/``pix2ang`` in both
orderings, ``ring2nest``/``nest2ring``, nside/npix helpers, and the
galactic rotation handled separately — is small enough to own. Algorithms
follow the standard HEALPix indexing equations (Górski et al. 2005); this
is an independent implementation, host-side (pixelization is precomputed
per observation, never device-resident).

Angles: ``theta`` colatitude [0, pi], ``phi`` longitude [0, 2pi), radians
(healpy convention); lon/lat-degree wrappers provided.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "nside2npix", "npix2nside", "nside2resol",
    "ang2pix", "pix2ang", "ang2pix_lonlat", "pix2ang_lonlat",
    "ring2nest", "nest2ring", "ang2vec", "vec2ang",
]


def nside2npix(nside: int) -> int:
    return 12 * nside * nside


def npix2nside(npix: int) -> int:
    nside = int(round(np.sqrt(npix / 12.0)))
    if 12 * nside * nside != npix:
        raise ValueError(f"{npix} is not a valid HEALPix map size")
    return nside


def nside2resol(nside: int) -> float:
    """Mean pixel spacing in radians (sqrt of pixel area)."""
    return np.sqrt(4.0 * np.pi / nside2npix(nside))


def _check_nside(nside: int):
    if nside < 1 or (nside & (nside - 1)):
        raise ValueError(f"nside must be a positive power of 2, got {nside}")


# ---------------------------------------------------------------------------
# RING scheme
# ---------------------------------------------------------------------------

def _ang2pix_ring(nside, theta, phi):
    z = np.cos(theta)
    za = np.abs(z)
    tt = np.mod(phi, 2.0 * np.pi) * (2.0 / np.pi)  # in [0, 4)

    # equatorial belt |z| <= 2/3
    temp1 = nside * (0.5 + tt)
    temp2 = nside * z * 0.75
    jp = np.floor(temp1 - temp2).astype(np.int64)  # ascending edge line
    jm = np.floor(temp1 + temp2).astype(np.int64)  # descending edge line
    ir = nside + 1 + jp - jm                       # ring counted from z=2/3
    kshift = 1 - (ir & 1)
    ip = (jp + jm - nside + kshift + 1) >> 1
    ip = np.mod(ip, 4 * nside)
    ncap = 2 * nside * (nside - 1)
    pix_eq = ncap + (ir - 1) * 4 * nside + ip

    # polar caps
    tp = tt - np.floor(tt)
    tmp = nside * np.sqrt(3.0 * (1.0 - za))
    jp_p = np.floor(tp * tmp).astype(np.int64)
    jm_p = np.floor((1.0 - tp) * tmp).astype(np.int64)
    ir_p = jp_p + jm_p + 1                         # ring from the pole
    ip_p = np.floor(tt * ir_p).astype(np.int64)
    ip_p = np.mod(ip_p, 4 * ir_p)
    npix = nside2npix(nside)
    pix_north = 2 * ir_p * (ir_p - 1) + ip_p
    pix_south = npix - 2 * ir_p * (ir_p + 1) + ip_p

    return np.where(za <= 2.0 / 3.0, pix_eq,
                    np.where(z > 0, pix_north, pix_south))


def _pix2ang_ring(nside, pix):
    pix = np.asarray(pix, dtype=np.int64)
    npix = nside2npix(nside)
    ncap = 2 * nside * (nside - 1)

    # north cap: rings 1..nside-1, 2 i (i-1) pixels before ring i
    iring_n = ((1.0 + np.sqrt(np.maximum(2.0 * pix + 1.0, 0.0))) / 2.0)
    iring_n = iring_n.astype(np.int64)
    # float-boundary fixup
    iring_n = np.where(2 * iring_n * (iring_n + 1) <= pix, iring_n + 1,
                       iring_n)
    iring_n = np.where(2 * iring_n * (iring_n - 1) > pix, iring_n - 1,
                       iring_n)
    iring_n = np.maximum(iring_n, 1)
    iphi_n = pix - 2 * iring_n * (iring_n - 1)
    z_n = 1.0 - iring_n**2 / (3.0 * nside**2)
    phi_n = (iphi_n + 0.5) * np.pi / (2.0 * np.maximum(iring_n, 1))

    # equatorial belt: odd (iring+nside) rings start at phi=0, even at
    # phi = pi/(4 nside) (Gorski et al. 2005 eq. 9)
    p_eq = pix - ncap
    iring_e = p_eq // (4 * nside) + nside
    iphi_e = np.mod(p_eq, 4 * nside)
    shift = 0.5 * (1 - np.mod(iring_e + nside, 2))
    z_e = (2 * nside - iring_e) * 2.0 / (3.0 * nside)
    phi_e = (iphi_e + shift) * np.pi / (2.0 * nside)

    # south cap (mirror of north)
    ps = npix - 1 - pix
    iring_s = ((1.0 + np.sqrt(np.maximum(2.0 * ps + 1.0, 0.0))) / 2.0)
    iring_s = iring_s.astype(np.int64)
    iring_s = np.where(2 * iring_s * (iring_s + 1) <= ps, iring_s + 1,
                       iring_s)
    iring_s = np.where(2 * iring_s * (iring_s - 1) > ps, iring_s - 1,
                       iring_s)
    iring_s = np.maximum(iring_s, 1)
    # index within the south ring, counted the same direction as north
    ipix_in_ring = pix - (npix - 2 * iring_s * (iring_s + 1))
    z_s = -1.0 + iring_s**2 / (3.0 * nside**2)
    phi_s = (ipix_in_ring + 0.5) * np.pi / (2.0 * np.maximum(iring_s, 1))

    north = pix < ncap
    south = pix >= npix - ncap
    z = np.where(north, z_n, np.where(south, z_s, z_e))
    phi = np.where(north, phi_n, np.where(south, phi_s, phi_e))
    return np.arccos(np.clip(z, -1.0, 1.0)), np.mod(phi, 2.0 * np.pi)


# ---------------------------------------------------------------------------
# NESTED scheme (via face/x/y coordinates and bit interleaving)
# ---------------------------------------------------------------------------

_JRLL = np.array([2, 2, 2, 2, 3, 3, 3, 3, 4, 4, 4, 4])
_JPLL = np.array([1, 3, 5, 7, 0, 2, 4, 6, 1, 3, 5, 7])


def _spread_bits(v):
    """Interleave zeros between the bits of v (v < 2^29)."""
    v = v.astype(np.int64)
    v = (v | (v << 16)) & 0x0000FFFF0000FFFF
    v = (v | (v << 8)) & 0x00FF00FF00FF00FF
    v = (v | (v << 4)) & 0x0F0F0F0F0F0F0F0F
    v = (v | (v << 2)) & 0x3333333333333333
    v = (v | (v << 1)) & 0x5555555555555555
    return v


def _compress_bits(v):
    v = v & 0x5555555555555555
    v = (v | (v >> 1)) & 0x3333333333333333
    v = (v | (v >> 2)) & 0x0F0F0F0F0F0F0F0F
    v = (v | (v >> 4)) & 0x00FF00FF00FF00FF
    v = (v | (v >> 8)) & 0x0000FFFF0000FFFF
    v = (v | (v >> 16)) & 0x00000000FFFFFFFF
    return v


def _xyf2nest(nside, ix, iy, face):
    return face * nside * nside + _spread_bits(ix) + (_spread_bits(iy) << 1)


def _nest2xyf(nside, pix):
    npface = nside * nside
    face = pix // npface
    p = pix & (npface - 1)
    return _compress_bits(p), _compress_bits(p >> 1), face


def _ang2xyf(nside, theta, phi):
    z = np.cos(theta)
    za = np.abs(z)
    tt = np.mod(phi, 2.0 * np.pi) * (2.0 / np.pi)

    # equatorial
    temp1 = nside * (0.5 + tt)
    temp2 = nside * z * 0.75
    jp = np.floor(temp1 - temp2).astype(np.int64)
    jm = np.floor(temp1 + temp2).astype(np.int64)
    ifp = jp // nside
    ifm = jm // nside
    face_eq = np.where(ifp == ifm, (ifp & 3) + 4,
                       np.where(ifp < ifm, ifp & 3, (ifm & 3) + 8))
    ix_eq = jm & (nside - 1)
    iy_eq = nside - (jp & (nside - 1)) - 1

    # polar
    ntt = np.minimum(tt.astype(np.int64), 3)
    tp = tt - ntt
    tmp = nside * np.sqrt(3.0 * (1.0 - za))
    jp_p = np.minimum(np.floor(tp * tmp).astype(np.int64), nside - 1)
    jm_p = np.minimum(np.floor((1.0 - tp) * tmp).astype(np.int64), nside - 1)
    north = z >= 0
    face_p = np.where(north, ntt, ntt + 8)
    ix_p = np.where(north, nside - jm_p - 1, jp_p)
    iy_p = np.where(north, nside - jp_p - 1, jm_p)

    eq = za <= 2.0 / 3.0
    return (np.where(eq, ix_eq, ix_p), np.where(eq, iy_eq, iy_p),
            np.where(eq, face_eq, face_p))


def _xyf2ang(nside, ix, iy, face):
    jr = _JRLL[face] * nside - ix - iy - 1  # ring index 1..4nside-1

    npolar = jr < nside
    spolar = jr > 3 * nside
    nr = np.where(npolar, jr, np.where(spolar, 4 * nside - jr, nside))
    z = np.where(
        npolar, 1.0 - nr**2 / (3.0 * nside**2),
        np.where(spolar, -1.0 + nr**2 / (3.0 * nside**2),
                 (2 * nside - jr) * 2.0 / (3.0 * nside)))
    kshift = np.where(npolar | spolar, 0, (jr - nside) & 1)

    jp = (_JPLL[face] * nr + ix - iy + 1 + kshift) // 2
    jp = np.where(jp > 4 * nr, jp - 4 * nr, jp)
    jp = np.where(jp < 1, jp + 4 * nr, jp)
    phi = (jp - (kshift + 1) * 0.5) * (np.pi / (2.0 * nr))
    return np.arccos(np.clip(z, -1.0, 1.0)), np.mod(phi, 2.0 * np.pi)


def _xyf2ring(nside, ix, iy, face):
    jr = _JRLL[face] * nside - ix - iy - 1
    npix = nside2npix(nside)
    ncap = 2 * nside * (nside - 1)

    npolar = jr < nside
    spolar = jr > 3 * nside
    nr = np.where(npolar, jr, np.where(spolar, 4 * nside - jr, nside))
    n_before = np.where(
        npolar, 2 * nr * (nr - 1),
        np.where(spolar, npix - 2 * nr * (nr + 1),
                 ncap + (jr - nside) * 4 * nside))
    kshift = np.where(npolar | spolar, 0, (jr - nside) & 1)

    jp = (_JPLL[face] * nr + ix - iy + 1 + kshift) // 2
    jp = np.where(jp > 4 * nr, jp - 4 * nr, jp)
    jp = np.where(jp < 1, jp + 4 * nr, jp)
    return n_before + jp - 1


def _isqrt(v):
    r = np.sqrt(v.astype(np.float64)).astype(np.int64)
    r = np.where((r + 1) * (r + 1) <= v, r + 1, r)
    return np.where(r * r > v, r - 1, r)


def _ring2xyf(nside, pix):
    """Exact integer RING -> (ix, iy, face), standard HEALPix indexing."""
    npix = nside2npix(nside)
    ncap = 2 * nside * (nside - 1)
    north = pix < ncap
    south = pix >= npix - ncap
    eq = ~(north | south)

    # north polar cap
    ir_n = (1 + _isqrt(1 + 2 * pix)) >> 1
    iphi_n = (pix + 1) - 2 * ir_n * (ir_n - 1)          # 1-based
    face_n = (iphi_n - 1) // np.maximum(ir_n, 1)

    # equatorial
    ip = pix - ncap
    tmp = ip // (4 * nside)
    ir_e = tmp + nside
    iphi_e = ip - tmp * 4 * nside + 1
    kshift_e = (ir_e + nside) & 1
    ire = ir_e - nside + 1
    irm = 2 * nside + 2 - ire
    ifm = (iphi_e - ire // 2 + nside - 1) // nside
    ifp = (iphi_e - irm // 2 + nside - 1) // nside
    face_e = np.where(ifp == ifm, (ifp & 3) + 4,
                      np.where(ifp < ifm, ifp, ifm + 8))

    # south polar cap
    ip_s = npix - pix
    ir_s = (1 + _isqrt(2 * ip_s - 1)) >> 1
    iphi_s = 4 * ir_s + 1 - (ip_s - 2 * ir_s * (ir_s - 1))
    face_s = 8 + (iphi_s - 1) // np.maximum(ir_s, 1)
    ir_s_n = 4 * nside - ir_s                            # from north

    iring = np.where(north, ir_n, np.where(eq, ir_e, ir_s_n))
    iphi = np.where(north, iphi_n, np.where(eq, iphi_e, iphi_s))
    face = np.where(north, face_n, np.where(eq, face_e, face_s))
    nr = np.where(eq, nside, np.where(north, ir_n, ir_s))
    kshift = np.where(eq, kshift_e, 0)

    irt = iring - _JRLL[face] * nside + 1
    ipt = 2 * iphi - _JPLL[face] * nr - kshift - 1
    ipt = np.where(ipt >= 2 * nside, ipt - 8 * nside, ipt)
    ix = (ipt - irt) >> 1
    iy = (-(ipt + irt)) >> 1
    return ix, iy, face


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def ang2pix(nside: int, theta, phi, nest: bool = False):
    """(theta, phi) radians -> pixel index."""
    _check_nside(nside)
    theta = np.asarray(theta, dtype=np.float64)
    phi = np.asarray(phi, dtype=np.float64)
    if nest:
        ix, iy, face = _ang2xyf(nside, theta, phi)
        return _xyf2nest(nside, ix, iy, face)
    return _ang2pix_ring(nside, theta, phi)


def pix2ang(nside: int, pix, nest: bool = False):
    """Pixel index -> (theta, phi) radians at pixel centers."""
    _check_nside(nside)
    pix = np.asarray(pix, dtype=np.int64)
    if nest:
        ix, iy, face = _nest2xyf(nside, pix)
        return _xyf2ang(nside, ix, iy, face)
    return _pix2ang_ring(nside, pix)


def ang2pix_lonlat(nside: int, lon_deg, lat_deg, nest: bool = False):
    """healpy's ``lonlat=True`` convention: longitude/latitude in degrees."""
    theta = np.radians(90.0 - np.asarray(lat_deg, dtype=np.float64))
    phi = np.radians(np.asarray(lon_deg, dtype=np.float64))
    return ang2pix(nside, theta, phi, nest=nest)


def pix2ang_lonlat(nside: int, pix, nest: bool = False):
    theta, phi = pix2ang(nside, pix, nest=nest)
    return np.degrees(phi), 90.0 - np.degrees(theta)


def ring2nest(nside: int, pix):
    _check_nside(nside)
    ix, iy, face = _ring2xyf(nside, np.asarray(pix, dtype=np.int64))
    return _xyf2nest(nside, ix, iy, face)


def nest2ring(nside: int, pix):
    _check_nside(nside)
    ix, iy, face = _nest2xyf(nside, np.asarray(pix, dtype=np.int64))
    return _xyf2ring(nside, ix, iy, face)


def ang2vec(theta, phi):
    st = np.sin(theta)
    return np.stack([st * np.cos(phi), st * np.sin(phi), np.cos(theta)],
                    axis=-1)


def vec2ang(vec):
    vec = np.asarray(vec, dtype=np.float64)
    r = np.linalg.norm(vec, axis=-1)
    theta = np.arccos(np.clip(vec[..., 2] / np.maximum(r, 1e-300), -1, 1))
    phi = np.mod(np.arctan2(vec[..., 1], vec[..., 0]), 2 * np.pi)
    return theta, phi
