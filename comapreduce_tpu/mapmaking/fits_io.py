"""Minimal FITS image I/O (no astropy dependency).

The reference writes maps as multi-extension FITS via ``astropy.io.fits``
(``MapMaking/run_destriper.py:19-50``) and HEALPix partial maps via
``healpy.write_map`` (:53-77). This module implements the subset of FITS
needed for those products: primary + IMAGE extensions of 2-D float32/float64
arrays with WCS header cards, and a reader sufficient to round-trip them.
HEALPix maps are stored as 1-D image extensions with ``PIXTYPE=HEALPIX``
cards plus an explicit pixel-index extension (partial-sky storage).
"""

from __future__ import annotations

import numpy as np

__all__ = ["write_fits_image", "read_fits_image", "write_healpix_map",
           "read_healpix_map"]

BLOCK = 2880


def _card(key: str, value, comment: str = "") -> bytes:
    if isinstance(value, bool):
        v = "T" if value else "F"
        body = f"{key:<8}= {v:>20}"
    elif isinstance(value, (int, np.integer)):
        body = f"{key:<8}= {value:>20d}"
    elif isinstance(value, (float, np.floating)):
        if not np.isfinite(value):
            # FITS headers have no representation for NaN/Inf; failing here
            # beats writing a card every reader rejects or misparses
            raise ValueError(f"non-finite FITS card value: {key}={value}")
        v = f"{value:.12G}"
        # FITS real values must carry a decimal point, or readers (including
        # ours) parse them back as integers
        if "." not in v and "E" not in v and "e" not in v:
            v += "."
        body = f"{key:<8}= {v:>20}"
    else:
        s = str(value).replace("'", "''")
        body = f"{key:<8}= '{s:<8}'"
    if comment:
        body = f"{body} / {comment}"
    return body[:80].ljust(80).encode("ascii")


def _header_bytes(cards: list[bytes]) -> bytes:
    raw = b"".join(cards) + b"END".ljust(80)
    pad = (-len(raw)) % BLOCK
    return raw + b" " * pad


def _data_bytes(data: np.ndarray) -> bytes:
    raw = data.astype(data.dtype.newbyteorder(">")).tobytes()
    pad = (-len(raw)) % BLOCK
    return raw + b"\x00" * pad


_BITPIX = {np.dtype(">f4"): -32, np.dtype(">f8"): -64,
           np.dtype(">i4"): 32, np.dtype(">i8"): 64, np.dtype(">i2"): 16}


def _image_hdu(data: np.ndarray, header: dict | None, primary: bool,
               name: str | None = None) -> bytes:
    if data.dtype.kind == "f" and data.dtype.itemsize not in (4, 8):
        data = data.astype(np.float32)
    be = data.dtype.newbyteorder(">")
    bitpix = _BITPIX[np.dtype(be)]
    cards = []
    if primary:
        cards.append(_card("SIMPLE", True, "conforms to FITS standard"))
    else:
        cards.append(_card("XTENSION", "IMAGE", "image extension"))
    cards.append(_card("BITPIX", bitpix))
    cards.append(_card("NAXIS", data.ndim))
    # FITS axis order is reversed w.r.t. numpy shape
    for i, n in enumerate(reversed(data.shape)):
        cards.append(_card(f"NAXIS{i + 1}", n))
    if not primary:
        cards.append(_card("PCOUNT", 0))
        cards.append(_card("GCOUNT", 1))
    if name:
        cards.append(_card("EXTNAME", name))
    for k, v in (header or {}).items():
        cards.append(_card(k, v))
    return _header_bytes(cards) + _data_bytes(data)


def write_fits_image(path: str, images: dict[str, np.ndarray],
                     header: dict | None = None):
    """Write named 2-D images: first as primary HDU, rest as extensions.

    Mirrors the reference's map file layout (``run_destriper.py:35-46``:
    primary + extensions named per product).
    """
    names = list(images.keys())
    out = b""
    for i, nm in enumerate(names):
        hdr = dict(header or {})
        if i == 0:
            hdr["EXTNAME"] = nm
        out += _image_hdu(np.asarray(images[nm]), hdr, primary=(i == 0),
                          name=None if i == 0 else nm)
    with open(path, "wb") as f:
        f.write(out)


def _parse_header(raw: bytes) -> dict:
    hdr = {}
    for i in range(0, len(raw), 80):
        card = raw[i:i + 80].decode("ascii", errors="replace")
        key = card[:8].strip()
        if key == "END":
            break
        if card[8:10] != "= ":
            continue
        raw_val = card[10:]
        if raw_val.lstrip().startswith("'"):
            # quoted string: scan to the closing quote ('' escapes one ')
            s = raw_val.lstrip()
            out = []
            i = 1
            while i < len(s):
                if s[i] == "'":
                    if i + 1 < len(s) and s[i + 1] == "'":
                        out.append("'")
                        i += 2
                        continue
                    break
                out.append(s[i])
                i += 1
            hdr[key] = "".join(out).rstrip()
            continue
        val = raw_val.split("/")[0].strip()
        if val == "T":
            hdr[key] = True
        elif val == "F":
            hdr[key] = False
        else:
            try:
                hdr[key] = int(val)
            except ValueError:
                try:
                    hdr[key] = float(val)
                except ValueError:
                    hdr[key] = val
    return hdr


_NP_DTYPE = {-32: ">f4", -64: ">f8", 16: ">i2", 32: ">i4", 64: ">i8", 8: "u1"}


def read_fits_image(path: str):
    """Read all image HDUs: returns list of (name, header, ndarray)."""
    with open(path, "rb") as f:
        buf = f.read()
    hdus = []
    pos = 0
    idx = 0
    while pos < len(buf):
        # read header blocks until END card
        hdr_raw = b""
        while True:
            block = buf[pos:pos + BLOCK]
            if len(block) < BLOCK:
                return hdus
            hdr_raw += block
            pos += BLOCK
            if _has_end(block):
                break
        hdr = _parse_header(hdr_raw)
        naxis = hdr.get("NAXIS", 0)
        shape = tuple(hdr[f"NAXIS{i + 1}"] for i in range(naxis))[::-1]
        count = int(np.prod(shape)) if shape else 0
        dtype = np.dtype(_NP_DTYPE[hdr["BITPIX"]])
        nbytes = count * dtype.itemsize
        data = np.frombuffer(buf[pos:pos + nbytes], dtype=dtype)
        data = data.reshape(shape) if count else data
        pos += nbytes + ((-nbytes) % BLOCK)
        name = hdr.get("EXTNAME", f"HDU{idx}")
        hdus.append((name, hdr, data.astype(dtype.newbyteorder("="))))
        idx += 1
    return hdus


def _has_end(block: bytes) -> bool:
    for i in range(0, len(block), 80):
        if block[i:i + 8].rstrip() == b"END":
            return True
    return False


def write_healpix_map(path: str, maps: dict[str, np.ndarray],
                      pixels, nside: int, nest: bool = False):
    """Partial-sky HEALPix maps: PIXELS index HDU + one HDU per product
    (the healpy ``write_map(..., partial=True)`` analogue,
    ``run_destriper.py:68-77``).

    ``pixels`` is the seen-pixel index — an array of sky ids, or a
    compacted ``mapmaking.pixel_space.PixelSpace`` whose dictionary is
    written directly: compacted map values align with it as-is, so the
    full-sky vector is never materialised anywhere on the write path.
    """
    from comapreduce_tpu.mapmaking.pixel_space import PixelSpace

    if isinstance(pixels, PixelSpace):
        if not pixels.compacted:
            raise ValueError("partial-map write needs a compacted "
                             "PixelSpace (a dense space has no "
                             "seen-pixel dictionary)")
        pixels = pixels.pixels
    hdr = {"PIXTYPE": "HEALPIX", "ORDERING": "NESTED" if nest else "RING",
           "NSIDE": nside, "OBJECT": "PARTIAL"}
    images: dict[str, np.ndarray] = {
        "PIXELS": np.asarray(pixels, dtype=np.int64)}
    for k, v in maps.items():
        images[k] = np.asarray(v, dtype=np.float32)
    write_fits_image(path, images, header=hdr)


def read_healpix_map(path: str):
    """Returns (maps dict, pixels, nside, nest)."""
    hdus = read_fits_image(path)
    hdr0 = hdus[0][1]
    nside = hdr0["NSIDE"]
    nest = hdr0.get("ORDERING", "RING") == "NESTED"
    pixels = None
    maps = {}
    for name, _, data in hdus:
        if name == "PIXELS":
            pixels = data
        else:
            maps[name] = data
    return maps, pixels, nside, nest
