"""``SkyModel``: sum of components + Level-1 TOD injection
(``Simulations/SkyModel.py:6-37`` parity).

``inject_level1`` adds the model signal into an existing Level-1 file's
raw TOD — scaled by the file's own per-channel gains would require the
truth, so the injection happens in power units using the per-channel
band-average response: ``counts += gain_estimate * T_model``. The
pipeline's vane calibration then recovers the injected temperature,
which is what makes this the backbone of signal-recovery tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from comapreduce_tpu.data.hdf5io import HDF5Store

__all__ = ["SkyModel", "inject_level1"]


@dataclass
class SkyModel:
    """Sum of sky components evaluated at (lon, lat) x freq."""

    components: list = field(default_factory=list)

    def add(self, component) -> "SkyModel":
        self.components.append(component)
        return self

    def __call__(self, lon_deg, lat_deg, freq_ghz):
        lon = np.asarray(lon_deg, np.float64)
        freq = np.asarray(freq_ghz, np.float64)
        out_shape = lon.shape + (freq.shape if freq.ndim else ())
        total = np.zeros(out_shape)
        for comp in self.components:
            total = total + comp(lon_deg, lat_deg, freq_ghz)
        return total


def inject_level1(filename: str, model: SkyModel,
                  gain_estimate: np.ndarray | None = None) -> None:
    """Add ``model``'s brightness [K RJ] into a Level-1 file's TOD.

    ``gain_estimate``: per-channel counts/K (F, B, C). When None, it is
    estimated from the file itself: median counts over time divided by a
    nominal 40 K system temperature (Trx ~ 20 K + atmosphere + CMB, the
    COMAP regime) — good to ~30%, fine for injection tests (the
    reference injects into simulated TOD where it knows the gain).
    """
    store = HDF5Store(name="inject")
    store.read(filename)
    tod = np.asarray(store["spectrometer/tod"], np.float64)  # (F, B, C, T)
    F, B, C, T = tod.shape
    ra = np.asarray(store["spectrometer/pixel_pointing/pixel_ra"])
    dec = np.asarray(store["spectrometer/pixel_pointing/pixel_dec"])
    freq = np.asarray(store["spectrometer/frequency"])       # (B, C) GHz
    if gain_estimate is None:
        gain_estimate = np.median(tod, axis=-1) / 40.0       # (F, B, C)
    for f in range(F):
        t_model = model(ra[f], dec[f], freq.ravel())         # (T, B*C)
        t_model = t_model.reshape(T, B, C).transpose(1, 2, 0)
        tod[f] += gain_estimate[f][..., None] * t_model
    store["spectrometer/tod"] = tod.astype(np.float32)
    store.write(filename)
