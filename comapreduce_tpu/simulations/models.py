"""Sky components evaluated at (lon, lat, freq)
(``Simulations/Models.py:11-100`` parity).

Each component returns RJ brightness temperature [K] with shape
``broadcast(lon/lat) x freq``. The reference's ``BasicSkyComponent``
wraps an analytic profile, ``HealpixSkyComponent`` interpolates a map;
here: Gaussian / point-source analytic components plus a HEALPix map
component backed by the framework's own pixelisation (nearest-pixel
lookup — the reference uses healpy ``get_interp_val``; COMAP beams are
much wider than the nside used, see ``Sim_SkyMaps.ini``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from comapreduce_tpu.mapmaking import healpix as hp

__all__ = ["GaussianComponent", "PointSourceComponent", "HealpixComponent"]


def _unity(freq_ghz):
    return np.ones_like(np.asarray(freq_ghz, np.float64))


@dataclass
class GaussianComponent:
    """Elliptical Gaussian blob: amplitude [K RJ] at ``freq0``."""

    lon0: float
    lat0: float
    amplitude_k: float
    fwhm_deg: float
    freq_law: Callable = field(default=_unity)

    def __call__(self, lon_deg, lat_deg, freq_ghz):
        sig = self.fwhm_deg / 2.355
        dx = ((np.asarray(lon_deg, np.float64) - self.lon0 + 180.0) % 360.0
              - 180.0) * np.cos(np.radians(np.asarray(lat_deg, np.float64)))
        dy = np.asarray(lat_deg, np.float64) - self.lat0
        spatial = self.amplitude_k * np.exp(-0.5 * (dx**2 + dy**2) / sig**2)
        law = np.asarray(self.freq_law(freq_ghz), np.float64)
        return spatial[..., None] * law[None, ...] if law.ndim else \
            spatial * law


@dataclass
class PointSourceComponent:
    """Point source smoothed by the instrument beam (delta x beam =
    Gaussian at the beam width)."""

    lon0: float
    lat0: float
    flux_jy: float
    beam_fwhm_deg: float = 0.075
    freq0_ghz: float = 30.0
    freq_law: Callable = field(default=_unity)

    def peak_k(self) -> float:
        from comapreduce_tpu.calibration.unitconv import (
            gaussian_solid_angle, jy_to_k)

        sig = self.beam_fwhm_deg / 2.355
        return float(jy_to_k(self.flux_jy, self.freq0_ghz,
                             gaussian_solid_angle(sig, sig)))

    def __call__(self, lon_deg, lat_deg, freq_ghz):
        g = GaussianComponent(self.lon0, self.lat0, self.peak_k(),
                              self.beam_fwhm_deg, self.freq_law)
        return g(lon_deg, lat_deg, freq_ghz)


@dataclass
class HealpixComponent:
    """A HEALPix map [K RJ] sampled by nearest pixel, with a frequency
    law (``HealpixSkyComponent``, ``Models.py:54-100``)."""

    sky_map: np.ndarray
    nest: bool = False
    freq_law: Callable = field(default=_unity)

    def __post_init__(self):
        self.nside = hp.npix2nside(len(self.sky_map))

    def __call__(self, lon_deg, lat_deg, freq_ghz):
        pix = np.asarray(hp.ang2pix_lonlat(self.nside, lon_deg, lat_deg,
                                           nest=self.nest))
        spatial = np.asarray(self.sky_map)[pix]
        law = np.asarray(self.freq_law(freq_ghz), np.float64)
        return spatial[..., None] * law[None, ...] if law.ndim else \
            spatial * law
