"""Frequency scaling laws for sky components
(``Simulations/FrequencyModels.py:7-35`` parity).

Each law maps ``freq_ghz -> multiplicative amplitude`` relative to a
reference frequency, in Rayleigh-Jeans temperature units.
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.calibration.unitconv import blackbody

__all__ = ["power_law", "lognormal_ame", "blackbody_law"]


def power_law(freq_ghz, freq0_ghz: float = 30.0, index: float = -3.0):
    """``(nu/nu0)^index`` — synchrotron-like RJ scaling."""
    return (np.asarray(freq_ghz, np.float64) / freq0_ghz) ** index


def lognormal_ame(freq_ghz, freq_peak_ghz: float = 25.0,
                  width: float = 0.5):
    """Log-normal bump peaking at ``freq_peak_ghz`` — the spinning-dust
    (AME) approximation the reference draws from its spdust tables."""
    nu = np.asarray(freq_ghz, np.float64)
    x = np.log(nu / freq_peak_ghz)
    return np.exp(-0.5 * (x / width) ** 2)


def blackbody_law(freq_ghz, freq0_ghz: float = 30.0, t_dust: float = 19.6,
                  beta: float = 1.6):
    """Modified-blackbody (thermal dust) RJ scaling relative to ``nu0``:
    ``(nu/nu0)^(beta) * B_nu(T)/B_nu0(T) * (nu0/nu)^2`` in RJ units."""
    nu = np.asarray(freq_ghz, np.float64)
    b_ratio = blackbody(nu, t_dust) / blackbody(freq0_ghz, t_dust)
    rj = (freq0_ghz / nu) ** 2  # intensity -> RJ temperature
    return (nu / freq0_ghz) ** beta * b_ratio * rj
