"""Generate a small synthetic field dataset in the current directory.

The zero-to-map quickstart companion of ``examples/configs/``::

    mkdir run && cd run
    python -m comapreduce_tpu.simulations.make_field [n_obs] [seed]
    comap-run-average  .../examples/configs/configuration.toml
    ls level2/Level2_*.hd5 > l2list.txt
    comap-run-destriper .../examples/configs/parameters.ini

Writes ``comap-<obsid>.hd5`` Level-1 files (4 bands, a 5 K point source
at the co2 field centre) plus ``filelist.txt``.
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    n_obs = int(argv[0]) if argv else 2
    seed = int(argv[1]) if len(argv) > 1 else 0
    from comapreduce_tpu.data.synthetic import (SyntheticObsParams,
                                                generate_level1_file)
    from comapreduce_tpu.mapmaking.filelist import write_filelist

    files = []
    for i in range(n_obs):
        params = SyntheticObsParams(
            obsid=1_000_000 + i, source="co2", n_feeds=2, n_bands=4,
            n_channels=32, n_scans=4, scan_samples=1200,
            vane_samples=250, seed=seed + i, source_amplitude_k=5.0,
            source_fwhm_deg=0.15, az_throw=2.0, fknee=1.0)
        path = f"comap-{1_000_000 + i}.hd5"
        generate_level1_file(path, params)
        files.append(path)
        print(f"wrote {path}")
    write_filelist("filelist.txt", files)
    print(f"wrote filelist.txt ({n_obs} files)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
