"""Sky-model simulations and TOD signal injection.

Parity with the reference ``Simulations/`` package (SURVEY.md §2.7):
frequency laws (``FrequencyModels.py:7-35``), sky components evaluated at
(lon, lat, freq) (``Models.py:11-100``), a summing ``SkyModel``
(``SkyModel.py:6-37``), and TOD injection into Level-1 files for
pipeline-level signal-recovery tests (the reference configures this via
``ParameterFiles/Sim_SkyMaps.ini``).
"""

from comapreduce_tpu.simulations.frequency_models import (blackbody_law,
                                                          lognormal_ame,
                                                          power_law)
from comapreduce_tpu.simulations.models import (GaussianComponent,
                                                HealpixComponent,
                                                PointSourceComponent)
from comapreduce_tpu.simulations.skymodel import SkyModel, inject_level1

__all__ = ["power_law", "lognormal_ame", "blackbody_law",
           "GaussianComponent", "PointSourceComponent", "HealpixComponent",
           "SkyModel", "inject_level1"]
