"""Tile grids: which sky pixels belong to which tile.

Two pixelisations, one rule each:

- **HEALPix** maps tile by NESTED parent pixel. A tile is one pixel of
  the coarser ``tile_nside`` grid; in NESTED ordering its children are
  the contiguous id range ``[t * k^2, (t+1) * k^2)`` with
  ``k = nside // tile_nside``, so the tile of a sky pixel is one shift:
  ``nest_id >> (2 * log2(k))``. The repo's partial maps store RING ids
  (``fits_io.write_healpix_map``), so the layer converts through
  ``healpix.ring2nest`` once per tiling — and because a compacted
  ``PixelSpace`` already holds the sorted seen-pixel dictionary, the
  set of non-empty tiles falls straight out of it
  (:func:`healpix_tile_ids`): a compacted epoch IS a sparse tile set.
- **WCS** maps tile on a fixed ``tile_px`` pixel grid over the field:
  tile ``(tx, ty)`` covers ``x in [tx*T, min(nx, (tx+1)*T))`` (same
  for y), id ``ty * ntx + tx``. Edge tiles are clipped, never padded —
  padding would make the tile bytes depend on the field size.

Both rules are pure index math (no jax, no I/O) so the byte-budget
gate in ``tools/check_perf.py`` can price a tile set from the
``PixelSpace`` alone, machine-independently
(:func:`expected_healpix_tiles`).
"""

from __future__ import annotations

import numpy as np

__all__ = ["healpix_tile_nside_auto", "healpix_tile_of",
           "healpix_tile_ids", "expected_healpix_tiles",
           "wcs_tile_grid", "wcs_tile_of", "wcs_tile_box"]

#: default children-per-side per tile: one HEALPix tile covers
#: ``DEFAULT_K^2`` sky pixels (64^2 = 4096 — a few-KB f32 payload,
#: the CDN sweet spot between request count and over-fetch)
DEFAULT_K = 64

#: default WCS tile edge in pixels
DEFAULT_WCS_TILE = 64


def _check_pow2(n: int, what: str) -> None:
    n = int(n)
    if n < 1 or (n & (n - 1)):
        raise ValueError(f"{what} must be a power of two, got {n}")


def healpix_tile_nside_auto(nside: int, k: int = DEFAULT_K) -> int:
    """The coarser tile grid for a map at ``nside``: ``nside // k``
    floored at 1 (small test nsides tile by base face)."""
    _check_pow2(nside, "nside")
    _check_pow2(k, "tile k")
    return max(1, int(nside) // int(k))


def healpix_tile_of(nest_ids, nside: int, tile_nside: int) -> np.ndarray:
    """NESTED sky ids -> tile ids (i64). Vectorised shift — the whole
    point of the NESTED ordering choice."""
    _check_pow2(nside, "nside")
    _check_pow2(tile_nside, "tile_nside")
    k = int(nside) // int(tile_nside)
    if k < 1:
        raise ValueError(f"tile_nside {tile_nside} finer than map "
                         f"nside {nside}")
    shift = 2 * (k.bit_length() - 1)
    return np.asarray(nest_ids, np.int64) >> shift


def healpix_tile_ids(ring_ids, nside: int, tile_nside: int):
    """Group RING-ordered sky ids by tile.

    Returns ``(tile_ids, nest_ids, order)``: the sorted-unique tile id
    per input pixel is ``tile_ids[...]``; ``order`` sorts the inputs by
    ``(tile, nest-within-tile)`` so each tile's pixels come out as one
    contiguous, deterministically-ordered slice (the blob layout).
    """
    from comapreduce_tpu.mapmaking.healpix import ring2nest

    ring = np.asarray(ring_ids, np.int64)
    nest = np.asarray(ring2nest(int(nside), ring), np.int64)
    tiles = healpix_tile_of(nest, nside, tile_nside)
    order = np.lexsort((nest, tiles))
    return tiles, nest, order


def expected_healpix_tiles(pixel_space, tile_nside: int) -> np.ndarray:
    """The exact non-empty tile ids of a compacted ``PixelSpace`` —
    the sparse tile set IS the seen-pixel dictionary, coarsened. Used
    by the machine-independent byte-budget gate."""
    from comapreduce_tpu.mapmaking.healpix import (npix2nside, ring2nest)

    if not pixel_space.compacted:
        raise ValueError("expected_healpix_tiles needs a compacted "
                         "PixelSpace (a dense space tiles everywhere)")
    nside = npix2nside(pixel_space.npix_sky)
    nest = np.asarray(ring2nest(nside, pixel_space.pixels), np.int64)
    return np.unique(healpix_tile_of(nest, nside, tile_nside))


def wcs_tile_grid(nx: int, ny: int, tile_px: int = DEFAULT_WCS_TILE):
    """``(ntx, nty)`` tile counts for an ``(nx, ny)`` field."""
    t = int(tile_px)
    if t < 1:
        raise ValueError(f"tile_px must be >= 1, got {t}")
    return (-(-int(nx) // t), -(-int(ny) // t))


def wcs_tile_of(x, y, nx: int, tile_px: int = DEFAULT_WCS_TILE):
    """Pixel coords -> tile id (``ty * ntx + tx``)."""
    t = int(tile_px)
    ntx = -(-int(nx) // t)
    return (np.asarray(y, np.int64) // t) * ntx + \
        (np.asarray(x, np.int64) // t)


def wcs_tile_box(tid: int, nx: int, ny: int,
                 tile_px: int = DEFAULT_WCS_TILE):
    """Tile id -> clipped pixel box ``(x0, y0, w, h)``."""
    t = int(tile_px)
    ntx, nty = wcs_tile_grid(nx, ny, t)
    tid = int(tid)
    if not 0 <= tid < ntx * nty:
        raise ValueError(f"tile id {tid} outside the {ntx}x{nty} grid")
    tx, ty = tid % ntx, tid // ntx
    x0, y0 = tx * t, ty * t
    return x0, y0, min(t, int(nx) - x0), min(t, int(ny) - y0)
