"""The canonical tile byte format.

One blob holds every map product of one (band, tile) — one fetch gets
everything a renderer needs. The encoding is DETERMINISTIC by
construction (sorted-key compact JSON header, little-endian contiguous
arrays, no timestamps), which is what makes the tier content-addressed:
identical tile content always serialises to identical bytes, so an
unchanged tile keeps its hash across epochs and every cache between the
store and the reader keeps hitting.

Layout::

    b"CMTL1\\n"                      magic + format version
    u32le header_len
    header JSON (ascii, sort_keys, compact separators)
    payload arrays, in header-declared order, little-endian, contiguous

Header fields: ``kind`` (``wcs``/``healpix``), ``tile`` id,
``products`` (array names, payload order), plus per-kind geometry —
WCS: ``x0``/``y0``/``w``/``h`` (the clipped pixel box; each product is
f32[h, w]); HEALPix: ``nside``/``tile_nside``/``n`` (a leading i32[n]
array of NESTED offsets *within the tile*, sorted ascending, then each
product as f32[n] — tiles are sparse like the partial maps they come
from).
"""

from __future__ import annotations

import json
import struct

import numpy as np

__all__ = ["encode_tile", "decode_tile", "MAGIC"]

MAGIC = b"CMTL1\n"


def _canon_json(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("ascii")


def _le(arr: np.ndarray, dtype: str) -> bytes:
    return np.ascontiguousarray(np.asarray(arr).astype(dtype,
                                                      copy=False)).tobytes()


def encode_tile(kind: str, tile: int, products: dict,
                **geometry) -> bytes:
    """Serialise one tile. ``products`` maps name -> array (f32 values;
    2-D ``(h, w)`` for WCS, 1-D ``(n,)`` for HEALPix); ``geometry`` is
    the per-kind header extras (see module docstring) — for HEALPix it
    must include ``local=`` the i32 within-tile NESTED offsets."""
    names = sorted(products)
    hdr = {"schema": 1, "kind": str(kind), "tile": int(tile),
           "products": names}
    local = geometry.pop("local", None)
    for k, v in geometry.items():
        hdr[k] = int(v)
    payload = b""
    if kind == "healpix":
        if local is None:
            raise ValueError("healpix tiles need local= offsets")
        local = np.asarray(local, np.int64)
        if local.ndim != 1 or (np.diff(local) <= 0).any():
            raise ValueError("tile offsets must be 1-D sorted strictly "
                             "increasing")
        hdr["n"] = int(local.size)
        payload += _le(local, "<i4")
        for nm in names:
            v = np.asarray(products[nm])
            if v.shape != local.shape:
                raise ValueError(f"product {nm} shape {v.shape} != "
                                 f"offsets {local.shape}")
            payload += _le(v, "<f4")
    elif kind == "wcs":
        h, w = int(hdr["h"]), int(hdr["w"])
        for nm in names:
            v = np.asarray(products[nm])
            if v.shape != (h, w):
                raise ValueError(f"product {nm} shape {v.shape} != "
                                 f"tile box ({h}, {w})")
            payload += _le(v, "<f4")
    else:
        raise ValueError(f"unknown tile kind {kind!r}")
    raw = _canon_json(hdr)
    return MAGIC + struct.pack("<I", len(raw)) + raw + payload


def decode_tile(blob: bytes) -> dict:
    """Parse a tile blob back to ``{"header": dict, "products":
    {name: f32 array}, "local": i64 offsets | None}``. Raises
    ``ValueError`` on a foreign or truncated blob — a torn object can
    never be mistaken for a short tile."""
    if not blob.startswith(MAGIC):
        raise ValueError("not a tile blob (bad magic)")
    off = len(MAGIC)
    if len(blob) < off + 4:
        raise ValueError("truncated tile blob (no header length)")
    (hlen,) = struct.unpack_from("<I", blob, off)
    off += 4
    try:
        hdr = json.loads(blob[off:off + hlen].decode("ascii"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise ValueError(f"torn tile header: {exc}") from exc
    off += hlen
    names = list(hdr.get("products", []))
    kind = hdr.get("kind")
    local = None
    if kind == "healpix":
        n = int(hdr["n"])
        need = 4 * n * (1 + len(names))
        if len(blob) - off != need:
            raise ValueError(f"tile payload is {len(blob) - off} bytes, "
                             f"expected {need}")
        local = np.frombuffer(blob, "<i4", n, off).astype(np.int64)
        off += 4 * n
        products = {}
        for nm in names:
            products[nm] = np.frombuffer(blob, "<f4", n,
                                         off).astype(np.float32)
            off += 4 * n
    elif kind == "wcs":
        h, w = int(hdr["h"]), int(hdr["w"])
        need = 4 * h * w * len(names)
        if len(blob) - off != need:
            raise ValueError(f"tile payload is {len(blob) - off} bytes, "
                             f"expected {need}")
        products = {}
        for nm in names:
            products[nm] = np.frombuffer(
                blob, "<f4", h * w, off).astype(np.float32).reshape(h, w)
            off += 4 * h * w
    else:
        raise ValueError(f"unknown tile kind {kind!r}")
    return {"header": hdr, "products": products, "local": local}
