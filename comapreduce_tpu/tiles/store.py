"""Content-addressed tile object store.

Objects live at ``objects/<hh>/<sha256>`` under the tiles root, named
by the sha256 of their bytes. The invariants the read tier leans on:

- **Immutable**: an object is never rewritten — its name IS its
  content, so ``Cache-Control: immutable`` and strong ``ETag``s are
  correct by construction.
- **Idempotent writes**: ``put`` of bytes that already exist is a
  no-op (the hash matches), so a tiler crashed mid-publish simply
  re-puts on resume; two tilers racing on one root converge on the
  same objects.
- **Never torn**: writes go through tmp + fsync + atomic rename
  (``data/durable.py``), so a SIGKILL leaves either the complete
  object or a dead ``.tmp`` sibling (swept by :meth:`cleanup_tmp`) —
  a reader can never fetch half a tile.

Garbage (objects no manifest references, e.g. after a crash between
object writes and the manifest rename) is bounded and harmless;
:meth:`sweep_unreferenced` reclaims it given the live hash set.
"""

from __future__ import annotations

import hashlib
import os

from comapreduce_tpu.data.durable import durable_replace

__all__ = ["TileStore"]

OBJECTS_DIR = "objects"


class TileStore:
    """The ``objects/`` half of a tiles root (see module docstring)."""

    def __init__(self, root: str):
        self.root = str(root)
        self.objects = os.path.join(self.root, OBJECTS_DIR)
        os.makedirs(self.objects, exist_ok=True)

    @staticmethod
    def digest(blob: bytes) -> str:
        return hashlib.sha256(blob).hexdigest()

    def path(self, digest: str) -> str:
        d = str(digest)
        return os.path.join(self.objects, d[:2], d)

    def has(self, digest: str) -> bool:
        return os.path.exists(self.path(digest))

    def put(self, blob: bytes) -> tuple[str, bool]:
        """Store ``blob``; returns ``(digest, was_new)``. Existing
        objects are trusted by name — content-addressing means a
        present object IS the bytes (rewriting it would only race
        readers for no change)."""
        digest = self.digest(blob)
        dest = self.path(digest)
        if os.path.exists(dest):
            return digest, False
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = dest + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        durable_replace(tmp, dest)
        return digest, True

    def get(self, digest: str) -> bytes:
        with open(self.path(digest), "rb") as f:
            return f.read()

    def size(self, digest: str) -> int:
        return os.stat(self.path(digest)).st_size

    # -- maintenance ------------------------------------------------------

    def cleanup_tmp(self) -> int:
        """Remove dead ``*.tmp*`` writes (writer killed before its
        rename); returns how many were removed."""
        n = 0
        for sub, _, names in os.walk(self.objects):
            for name in names:
                if ".tmp" in name:
                    try:
                        os.remove(os.path.join(sub, name))
                        n += 1
                    except OSError:
                        pass
        return n

    def sweep_unreferenced(self, live: set) -> int:
        """Remove objects whose digest is not in ``live`` (the union of
        every manifest's hashes — the caller computes it so rollback
        targets stay servable); returns how many were removed."""
        n = 0
        for sub, _, names in os.walk(self.objects):
            for name in names:
                if ".tmp" in name or name in live:
                    continue
                try:
                    os.remove(os.path.join(sub, name))
                    n += 1
                except OSError:
                    pass
        return n
