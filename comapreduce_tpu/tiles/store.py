"""Content-addressed tile object store.

Objects live at ``objects/<hh>/<sha256>`` under the tiles root, named
by the sha256 of their bytes. The invariants the read tier leans on:

- **Immutable**: an object is never rewritten — its name IS its
  content, so ``Cache-Control: immutable`` and strong ``ETag``s are
  correct by construction.
- **Idempotent writes**: ``put`` of bytes that already exist is a
  no-op (the hash matches), so a tiler crashed mid-publish simply
  re-puts on resume; two tilers racing on one root converge on the
  same objects.
- **Never torn**: writes go through tmp + fsync + atomic rename
  (``data/durable.py``), so a SIGKILL leaves either the complete
  object or a dead ``.tmp`` sibling (swept by :meth:`cleanup_tmp`) —
  a reader can never fetch half a tile.

Garbage (objects no manifest references, e.g. after a crash between
object writes and the manifest rename) is bounded and harmless;
:meth:`sweep_unreferenced` reclaims it given the live hash set.
"""

from __future__ import annotations

import hashlib
import logging
import os
import time

from comapreduce_tpu.data.durable import durable_replace
from comapreduce_tpu.resilience.integrity import (CorruptArtifactError,
                                                  verify_enabled)
from comapreduce_tpu.telemetry.core import TELEMETRY

__all__ = ["TileStore", "PUBLISH_MARKER_PREFIX"]

logger = logging.getLogger(__name__)

OBJECTS_DIR = "objects"

#: in-flight tile publish sentinel (``tiles-epoch-NNNNNN.tmp<pid>`` in
#: the tiles root) — created by ``tiles.tiler.tile_epoch`` before its
#: first object write, removed after the CURRENT swap. While a FRESH
#: one exists, :meth:`TileStore.sweep_unreferenced` refuses to run:
#: GC racing a publish must not delete an object the in-flight
#: manifest is about to reference.
PUBLISH_MARKER_PREFIX = "tiles-epoch-"

#: objects younger than this are never swept (seconds) — the window
#: between an object's ``put`` and its manifest's rename, with margin
DEFAULT_SWEEP_GRACE_S = 300.0


class TileStore:
    """The ``objects/`` half of a tiles root (see module docstring)."""

    def __init__(self, root: str):
        self.root = str(root)
        self.objects = os.path.join(self.root, OBJECTS_DIR)
        os.makedirs(self.objects, exist_ok=True)

    @staticmethod
    def digest(blob: bytes) -> str:
        return hashlib.sha256(blob).hexdigest()

    def path(self, digest: str) -> str:
        d = str(digest)
        return os.path.join(self.objects, d[:2], d)

    def has(self, digest: str) -> bool:
        return os.path.exists(self.path(digest))

    def put(self, blob: bytes) -> tuple[str, bool]:
        """Store ``blob``; returns ``(digest, was_new)``. Existing
        objects are trusted by name — content-addressing means a
        present object IS the bytes (rewriting it would only race
        readers for no change)."""
        digest = self.digest(blob)
        dest = self.path(digest)
        if os.path.exists(dest):
            return digest, False
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = dest + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        durable_replace(tmp, dest)
        return digest, True

    def get(self, digest: str) -> bytes:
        """Read an object, verifying content-addressing on the way
        out: the name IS the committed sha256, so a rehash mismatch is
        proof of in-place damage. A corrupt object is unlinked (an
        idempotent re-put repairs it — the bytes rebuild from the
        epoch FITS) and :class:`CorruptArtifactError` raised so the
        HTTP plane 404s instead of serving rot under an immutable
        cache header."""
        path = self.path(digest)
        with open(path, "rb") as f:
            blob = f.read()
        if verify_enabled() and self.digest(blob) != str(digest):
            TELEMETRY.counter("integrity.violations", kind="tile")
            try:
                os.remove(path)
            except OSError:
                pass
            logger.warning("tile object %s fails its content hash; "
                           "unlinked (re-put rebuilds it)", digest)
            raise CorruptArtifactError(path, kind="tile",
                                       expected=str(digest),
                                       actual=self.digest(blob))
        return blob

    def size(self, digest: str) -> int:
        return os.stat(self.path(digest)).st_size

    # -- maintenance ------------------------------------------------------

    def cleanup_tmp(self) -> int:
        """Remove dead ``*.tmp*`` writes (writer killed before its
        rename); returns how many were removed."""
        n = 0
        for sub, _, names in os.walk(self.objects):
            for name in names:
                if ".tmp" in name:
                    try:
                        os.remove(os.path.join(sub, name))
                        n += 1
                    except OSError:
                        pass
        return n

    def publish_in_flight(self, max_age_s: float = 3600.0) -> bool:
        """True while a fresh ``tiles-epoch-*.tmp*`` publish marker
        exists in the tiles root — a tiler is between its first object
        write and its CURRENT swap. Markers older than ``max_age_s``
        are a crashed publisher's litter and do not count (a killed
        tiler must not block GC forever)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return False
        now = time.time()
        for name in names:
            if not (name.startswith(PUBLISH_MARKER_PREFIX)
                    and ".tmp" in name):
                continue
            try:
                age = now - os.path.getmtime(
                    os.path.join(self.root, name))
            except OSError:
                continue
            if age < max_age_s:
                return True
        return False

    def sweep_unreferenced(self, live: set,
                           grace_s: float = DEFAULT_SWEEP_GRACE_S) -> int:
        """Remove objects whose digest is not in ``live`` (the union of
        every manifest's hashes — the caller computes it so rollback
        targets stay servable); returns how many were removed.

        Two guards against GC racing a concurrent publish: the sweep
        refuses outright while a fresh ``tiles-epoch-*`` publish marker
        exists (:meth:`publish_in_flight` — that tiler's manifest is
        not on disk yet, so ``live`` cannot include its objects), and
        objects younger than ``grace_s`` are always spared (a put whose
        manifest is still being written looks unreferenced for a few
        seconds even without a marker — e.g. a publisher on another
        host whose marker write raced this listing)."""
        if self.publish_in_flight():
            logger.info("tile sweep skipped: a tiles-epoch publish is "
                        "in flight in %s", self.root)
            return 0
        n = 0
        now = time.time()
        for sub, _, names in os.walk(self.objects):
            for name in names:
                if ".tmp" in name or name in live:
                    continue
                path = os.path.join(sub, name)
                if grace_s > 0:
                    try:
                        if now - os.path.getmtime(path) < grace_s:
                            continue
                    except OSError:
                        continue
                try:
                    os.remove(path)
                    n += 1
                except OSError:
                    pass
        return n
