"""Map tile tier: content-addressed tiles over published epochs.

The serving layer's versioned epochs (:mod:`comapreduce_tpu.serving`)
are batch artifacts — reading one means mounting the epochs root and
loading a whole FITS file. This package turns each published epoch into
a CDN-shaped read surface:

- :mod:`~comapreduce_tpu.tiles.layout` — the tile grid. HEALPix maps
  tile by NESTED parent pixel (tile ids fall straight out of the
  compacted ``PixelSpace``: a sparse seen-pixel dictionary IS a sparse
  tile set); WCS maps tile on a fixed pixel grid.
- :mod:`~comapreduce_tpu.tiles.blob` — the canonical tile byte format.
  Deterministic by construction, so identical tile CONTENT always
  hashes to identical bytes and unchanged tiles are cache hits across
  epochs for free.
- :mod:`~comapreduce_tpu.tiles.store` — the content-addressed object
  store (``objects/<hh>/<hash>``): writes are idempotent, objects are
  immutable, a re-tile after a crash re-derives the same names.
- :mod:`~comapreduce_tpu.tiles.tiler` — walks an epoch dir, emits the
  tile set plus a per-epoch manifest and a DELTA manifest against the
  previous tiled epoch (clients refresh only changed tiles). Empty
  tiles are never materialised.
- :mod:`~comapreduce_tpu.tiles.cutout` — reassembles rectangular sky
  cutouts (and whole map products, for ``coadd``) from tiles,
  bit-identical to slicing the expanded FITS.
- :mod:`~comapreduce_tpu.tiles.http` — the stdlib ``http.server``
  read tier: tiles, manifests, epoch metadata and cutouts with
  immutable-epoch ``Cache-Control``/``ETag`` headers so edge caches
  absorb the traffic, following the epochs root's ``current`` pointer
  atomically for freshness.

Operate it with ``tools/tile_server.py`` (serve/status); docs at
OPERATIONS.md §14.
"""

from comapreduce_tpu.tiles.blob import decode_tile, encode_tile
from comapreduce_tpu.tiles.cutout import assemble_cutout, reconstruct_hdus
from comapreduce_tpu.tiles.layout import (healpix_tile_ids,
                                          healpix_tile_nside_auto,
                                          wcs_tile_grid)
from comapreduce_tpu.tiles.store import TileStore
from comapreduce_tpu.tiles.tiler import (TileSet, is_tile_source,
                                         tile_budget_bytes, tile_epoch)

__all__ = ["encode_tile", "decode_tile", "assemble_cutout",
           "reconstruct_hdus", "healpix_tile_ids",
           "healpix_tile_nside_auto", "wcs_tile_grid", "TileStore",
           "TileSet", "is_tile_source", "tile_budget_bytes",
           "tile_epoch"]
