"""Reassemble sky from tiles: cutouts and whole map products.

The inverse of the tiler, with a bit-identity contract both ways:

- :func:`assemble_cutout` builds a rectangular WCS cutout
  ``f32[h, w]`` from exactly the tiles the box touches; missing
  (empty) tiles zero-fill, so the result is bit-identical to slicing
  the expanded full-field FITS — the acceptance drill's check.
- :func:`assemble_healpix` gathers a set of HEALPix tiles back into
  ``(ring_pixels, {product: values})`` — partial-sky, sorted by RING
  id, exactly the slice of the source partial map covered by those
  tiles.
- :func:`reconstruct_hdus` rebuilds a whole map product in the
  ``fits_io.read_fits_image`` HDU-tuple shape, which is what lets
  ``mapmaking.coadd`` accept a tile manifest as a map source without
  ever touching the original epoch dir.

Cutout/blob serialisation for the HTTP layer is deterministic
(:func:`cutout_blob` reuses the tile encoding with kind ``wcs``), so
cutout ``ETag``\\ s are content hashes like everything else in the tier.
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.tiles import layout
from comapreduce_tpu.tiles.blob import encode_tile
from comapreduce_tpu.tiles.tiler import TileSet, is_tile_source

__all__ = ["assemble_cutout", "assemble_healpix", "cutout_blob",
           "reconstruct_hdus", "resolve_tile_manifest"]


def resolve_tile_manifest(source: str) -> tuple[TileSet, dict]:
    """A tile source path (tiles root, or a manifest JSON under
    ``manifests/``) -> ``(TileSet, manifest)``. Roots resolve through
    the tiles ``CURRENT`` pointer, falling back to the newest tiled
    epoch."""
    import json
    import os

    p = str(source)
    if os.path.isdir(p):
        ts = TileSet(p)
        n = ts.current()
        if n is None:
            n = ts.latest()
        if n is None:
            raise ValueError(f"{p}: no complete tiled epoch")
        return ts, ts.manifest(n)
    if not is_tile_source(p):
        raise ValueError(f"{p} is not a tile manifest or tiles root")
    with open(p, encoding="utf-8") as f:
        man = json.load(f)
    if man.get("kind") != "tiles":
        raise ValueError(f"{p} is a {man.get('kind')!r} manifest, not "
                         "a full tile manifest")
    root = os.path.dirname(os.path.dirname(os.path.abspath(p)))
    return TileSet(root), man


def _wcs_geometry(man: dict) -> tuple[int, int, int]:
    pix = man.get("pixelization") or {}
    if pix.get("kind") != "wcs":
        raise ValueError("rectangular cutouts need a WCS tile set "
                         f"(this manifest is {pix.get('kind')!r}; use "
                         "assemble_healpix for HEALPix tiles)")
    return int(pix["nx"]), int(pix["ny"]), int(pix["tile_px"])


def assemble_cutout(ts: TileSet, man: dict, x0: int, y0: int,
                    w: int, h: int, band: int = 0,
                    product: str = "DESTRIPED") -> np.ndarray:
    """Rectangular WCS cutout ``f32[h, w]`` at field pixels
    ``[x0, x0+w) x [y0, y0+h)`` — bit-identical to slicing the
    expanded full-field product. Out-of-field boxes raise; empty tiles
    inside the box zero-fill."""
    nx, ny, tile_px = _wcs_geometry(man)
    x0, y0, w, h = int(x0), int(y0), int(w), int(h)
    if w < 1 or h < 1:
        raise ValueError(f"cutout box {w}x{h} is empty")
    if x0 < 0 or y0 < 0 or x0 + w > nx or y0 + h > ny:
        raise ValueError(f"cutout [{x0},{x0 + w})x[{y0},{y0 + h}) "
                         f"outside the {nx}x{ny} field")
    if product not in man.get("products", []):
        raise ValueError(f"product {product!r} not in this tile set "
                         f"{man.get('products')}")
    out = np.zeros((h, w), np.float32)
    ntx, _ = layout.wcs_tile_grid(nx, ny, tile_px)
    for ty in range(y0 // tile_px, (y0 + h - 1) // tile_px + 1):
        for tx in range(x0 // tile_px, (x0 + w - 1) // tile_px + 1):
            tile = ts.read_tile(man, band, ty * ntx + tx)
            if tile is None:
                continue
            hd = tile["header"]
            tx0, ty0 = int(hd["x0"]), int(hd["y0"])
            arr = tile["products"].get(product)
            if arr is None:
                continue
            # overlap of the tile box with the cutout box
            ax0, ay0 = max(tx0, x0), max(ty0, y0)
            ax1 = min(tx0 + int(hd["w"]), x0 + w)
            ay1 = min(ty0 + int(hd["h"]), y0 + h)
            if ax0 >= ax1 or ay0 >= ay1:
                continue
            out[ay0 - y0:ay1 - y0, ax0 - x0:ax1 - x0] = \
                arr[ay0 - ty0:ay1 - ty0, ax0 - tx0:ax1 - tx0]
    return out


def cutout_blob(ts: TileSet, man: dict, x0: int, y0: int, w: int,
                h: int, band: int = 0,
                products: list[str] | None = None) -> bytes:
    """Deterministic multi-product cutout bytes for the HTTP layer —
    the tile encoding with the cutout box as the geometry, so clients
    decode cutouts and tiles with the same parser."""
    names = list(products) if products else list(man.get("products", []))
    cut = {nm: assemble_cutout(ts, man, x0, y0, w, h, band=band,
                               product=nm) for nm in names}
    return encode_tile("wcs", -1, cut, x0=int(x0), y0=int(y0),
                       w=int(w), h=int(h))


def assemble_healpix(ts: TileSet, man: dict, tile_ids, band: int = 0):
    """Gather HEALPix tiles back to partial-sky: ``(ring_pixels,
    {product: f32 values})`` sorted by RING id — exactly the source
    partial map restricted to those tiles. Unknown/empty tile ids
    contribute nothing."""
    from comapreduce_tpu.mapmaking.healpix import nest2ring

    pix = man.get("pixelization") or {}
    if pix.get("kind") != "healpix":
        raise ValueError("assemble_healpix needs a HEALPix tile set")
    nside = int(pix["nside"])
    tile_nside = int(pix["tile_nside"])
    k = nside // tile_nside
    nests, parts = [], []
    for tid in sorted(int(t) for t in tile_ids):
        tile = ts.read_tile(man, band, tid)
        if tile is None:
            continue
        nests.append(np.int64(tid) * (k * k) + tile["local"])
        parts.append(tile["products"])
    if not nests:
        return (np.empty(0, np.int64),
                {nm: np.empty(0, np.float32)
                 for nm in man.get("products", [])})
    nest = np.concatenate(nests)
    ring = np.asarray(nest2ring(nside, nest), np.int64)
    order = np.argsort(ring, kind="stable")
    out = {}
    for nm in man.get("products", []):
        vals = np.concatenate([p[nm] for p in parts])
        out[nm] = vals[order]
    return ring[order], out


def reconstruct_hdus(source: str, band: int | None = None) -> list:
    """Rebuild the map product HDUs of a tile manifest in
    ``read_fits_image`` shape: ``[(name, header, array), ...]`` —
    the coadd adapter. WCS sets come back as the full field (empty
    tiles zero-filled, bit-identical to the original FITS); HEALPix
    sets as the partial map (PIXELS HDU first, RING-sorted)."""
    ts, man = resolve_tile_manifest(source)
    bands = man.get("bands", [0])
    if band is None:
        if len(bands) != 1:
            raise ValueError(f"tile set covers bands {bands}; pass "
                             "band= to pick one")
        band = int(bands[0])
    pix = man.get("pixelization") or {}
    products = list(man.get("products", []))
    if pix.get("kind") == "wcs":
        nx, ny = int(pix["nx"]), int(pix["ny"])
        hdr = dict(pix.get("cards") or {})
        out = []
        for nm in products:
            full = assemble_cutout(ts, man, 0, 0, nx, ny, band=band,
                                   product=nm)
            out.append((nm, dict(hdr, EXTNAME=nm), full))
        return out
    # healpix: every non-empty tile of this band
    prefix = f"b{int(band)}/"
    tids = [int(key[len(prefix):]) for key in man.get("tiles", {})
            if key.startswith(prefix)]
    ring, maps = assemble_healpix(ts, man, tids, band=band)
    hdr = {"PIXTYPE": "HEALPIX", "ORDERING": pix.get("ordering", "RING"),
           "NSIDE": int(pix["nside"]), "OBJECT": "PARTIAL"}
    out = [("PIXELS", dict(hdr, EXTNAME="PIXELS"), ring)]
    for nm in products:
        out.append((nm, dict(hdr, EXTNAME=nm), maps[nm]))
    return out
