"""Turn a published epoch into a content-addressed tile set.

``tile_epoch`` walks one ``epoch-NNNNNN`` dir (:mod:`serving.epochs`),
cuts every map product into tiles (:mod:`tiles.layout`), stores each
tile blob by content hash (:mod:`tiles.store`) and publishes two
manifests under ``<tiles_root>/manifests/``:

- ``epoch-NNNNNN.json`` — the FULL manifest: pixelisation, products,
  and ``tiles: {"b<band>/<tile>": [sha256, bytes, n_pix]}``. Empty
  tiles (every product zero over the tile) are never materialised;
  absence from the manifest IS the zero tile.
- ``delta-epoch-NNNNNN.json`` — the DELTA against the previous tiled
  epoch: only ``changed`` (new hash) and ``removed`` keys. Clients
  holding epoch P refresh to N by fetching the delta and only the
  changed tiles; unchanged tiles keep their content hash (the blob
  encoding is deterministic) so every cached copy stays valid.

Crash safety mirrors the epoch store: objects are idempotent
content-addressed writes, manifests land via tmp + fsync + atomic
rename, and the ``CURRENT`` pointer swaps last — a SIGKILL anywhere
leaves readers on the previous complete tile set (old-or-new, never
torn) and a resumed tiler re-derives identical objects and simply
re-publishes the manifest. The ``chaos`` hook injects the drill's
``kill_mid_publish`` between the object writes and the manifest
rename, the widest window.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time

import numpy as np

from comapreduce_tpu.data.durable import durable_replace
from comapreduce_tpu.resilience.integrity import check_json, seal_json
from comapreduce_tpu.serving.epochs import epoch_name, parse_epoch_name
from comapreduce_tpu.tiles import layout
from comapreduce_tpu.tiles.blob import encode_tile
from comapreduce_tpu.tiles.store import TileStore

__all__ = ["TileSet", "tile_epoch", "is_tile_source",
           "tile_budget_bytes", "MANIFESTS_DIR", "TILES_CURRENT"]

logger = logging.getLogger(__name__)

MANIFESTS_DIR = "manifests"
TILES_CURRENT = "CURRENT"
_BAND_RE = re.compile(r"band(\d+)")
_DELTA_PREFIX = "delta-"

#: per-tile fixed-cost bound for the machine-independent byte budget:
#: magic + header-length word + the canonical JSON header (all fields
#: are short ints/names; measured headers are ~160 B)
TILE_HEADER_BOUND = 512


def _write_json(path: str, obj: dict) -> bytes:
    raw = json.dumps(seal_json(obj), sort_keys=True,
                     indent=1).encode("utf-8")
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(raw)
    durable_replace(tmp, path)
    return raw


def _read_json(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(obj, dict):
        return None
    obj, verdict = check_json(obj)
    if verdict is False:
        # a manifest that parses but fails its seal rotted in place —
        # unusable exactly like a torn one (re-tiling rebuilds it)
        logger.warning("tile manifest %s fails its _sha256 seal; "
                       "ignoring it (re-tile the epoch or run "
                       "tools/campaign_fsck.py)", path)
        return None
    return obj


class TileSet:
    """Read/point-at side of a tiles root (manifests + CURRENT).

    The write side is :func:`tile_epoch`; this class never touches
    objects it did not come to read. Import-light (no jax) — status
    tools stay instant.
    """

    def __init__(self, root: str):
        self.root = str(root)
        self.manifests = os.path.join(self.root, MANIFESTS_DIR)
        self.store = TileStore(self.root)
        os.makedirs(self.manifests, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def manifest_path(self, n: int) -> str:
        return os.path.join(self.manifests, epoch_name(n) + ".json")

    def delta_path(self, n: int) -> str:
        return os.path.join(self.manifests,
                            _DELTA_PREFIX + epoch_name(n) + ".json")

    # -- queries ----------------------------------------------------------

    def manifest(self, n: int) -> dict | None:
        man = _read_json(self.manifest_path(n))
        if man is None or man.get("kind") != "tiles" or \
                int(man.get("schema", 0)) != 1:
            return None
        return man

    def delta(self, n: int) -> dict | None:
        d = _read_json(self.delta_path(n))
        if d is None or d.get("kind") != "tiles-delta":
            return None
        return d

    def list_tiled(self) -> list[int]:
        out = []
        try:
            names = os.listdir(self.manifests)
        except OSError:
            return out
        for name in names:
            if name.startswith(_DELTA_PREFIX) or \
                    not name.endswith(".json"):
                continue
            n = parse_epoch_name(name[:-len(".json")])
            if n is not None and self.manifest(n) is not None:
                out.append(n)
        return sorted(out)

    def latest(self) -> int | None:
        eps = self.list_tiled()
        return eps[-1] if eps else None

    def current(self) -> int | None:
        try:
            with open(os.path.join(self.manifests, TILES_CURRENT),
                      encoding="utf-8") as f:
                name = f.read().strip()
        except OSError:
            return None
        n = parse_epoch_name(name)
        if n is None or self.manifest(n) is None:
            return None
        return n

    def set_current(self, n: int, force: bool = False) -> None:
        """Atomic pointer swap, forward-only unless ``force`` (the
        rollback path) — same contract as ``EpochStore.set_current``."""
        if self.manifest(n) is None:
            raise ValueError(f"epoch {n} is not tiled in {self.root}")
        cur = self.current()
        if cur is not None and n < cur and not force:
            raise ValueError(f"tiles CURRENT is {epoch_name(cur)}; "
                             f"refusing a backwards swap to "
                             f"{epoch_name(n)} (use force/rollback)")
        tmp = os.path.join(self.manifests,
                           f".{TILES_CURRENT}.tmp{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(epoch_name(n) + "\n")
        durable_replace(tmp, os.path.join(self.manifests, TILES_CURRENT))

    # -- tile reads -------------------------------------------------------

    def tile_entry(self, man: dict, band: int, tid: int):
        """Manifest entry ``[hash, bytes, n_pix]`` or None (empty)."""
        return (man.get("tiles") or {}).get(f"b{int(band)}/{int(tid)}")

    def read_tile(self, man: dict, band: int, tid: int) -> dict | None:
        from comapreduce_tpu.tiles.blob import decode_tile

        entry = self.tile_entry(man, band, tid)
        if entry is None:
            return None
        return decode_tile(self.store.get(entry[0]))


# -- tiling one epoch -----------------------------------------------------


def _band_of(map_name: str) -> int:
    m = _BAND_RE.search(os.path.basename(map_name))
    return int(m.group(1)) if m else 0


def _tile_wcs(images: list, hdr0: dict, band: int, tile_px: int,
              store: TileStore, tiles: dict, stats: dict) -> dict:
    """Cut one WCS map's HDUs into dense tile blobs; empty (all-zero
    across every product) tiles are skipped — absence IS the zero
    tile, so reassembly zero-fills and stays bit-identical."""
    products = {name: np.asarray(data, np.float32)
                for name, _, data in images}
    ny, nx = next(iter(products.values())).shape
    for name, arr in products.items():
        if arr.shape != (ny, nx):
            raise ValueError(f"product {name} shape {arr.shape} != "
                             f"({ny}, {nx})")
    ntx, nty = layout.wcs_tile_grid(nx, ny, tile_px)
    for tid in range(ntx * nty):
        x0, y0, w, h = layout.wcs_tile_box(tid, nx, ny, tile_px)
        cut = {k: v[y0:y0 + h, x0:x0 + w] for k, v in products.items()}
        if not any(np.any(c) for c in cut.values()):
            stats["n_empty"] += 1
            continue
        blob = encode_tile("wcs", tid, cut, x0=x0, y0=y0, w=w, h=h)
        digest, new = store.put(blob)
        tiles[f"b{band}/{tid}"] = [digest, len(blob), int(w * h)]
        stats["total_bytes"] += len(blob)
        stats["n_new_objects"] += int(new)
    cards = {k: v for k, v in hdr0.items()
             if k.startswith(("CRVAL", "CRPIX", "CDELT", "CTYPE",
                              "CUNIT"))}
    return {"kind": "wcs", "nx": int(nx), "ny": int(ny),
            "tile_px": int(tile_px), "cards": cards}


def _tile_healpix(images: list, hdr0: dict, band: int,
                  tile_nside: int, store: TileStore, tiles: dict,
                  stats: dict) -> dict:
    """Cut one partial-sky HEALPix map into sparse tile blobs. The
    pixel list (RING ids, sorted — the PixelSpace dictionary) groups by
    NESTED parent: tile ids fall straight out of the seen-pixel set,
    and a compacted epoch is already the sparse tile set."""
    from comapreduce_tpu.mapmaking.healpix import nside2npix

    pixels = next(np.asarray(d, np.int64)
                  for n, _, d in images if n == "PIXELS")
    products = {n: np.asarray(d, np.float32)
                for n, _, d in images if n != "PIXELS"}
    nside = int(hdr0["NSIDE"])
    if hdr0.get("ORDERING", "RING") != "RING":
        raise ValueError("tiler expects RING-ordered partial maps "
                         "(the repo's write_healpix_map layout)")
    npix_sky = nside2npix(nside)
    if pixels.size and (pixels.min() < 0 or pixels.max() >= npix_sky):
        raise ValueError(f"PIXELS outside [0, {npix_sky}) for nside "
                         f"{nside} — corrupt partial map?")
    if tile_nside <= 0:
        tile_nside = layout.healpix_tile_nside_auto(nside)
    k = nside // tile_nside
    tids, nest, order = layout.healpix_tile_ids(pixels, nside,
                                                tile_nside)
    tids_s, nest_s = tids[order], nest[order]
    bounds = np.flatnonzero(np.diff(tids_s)) + 1
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [tids_s.size]])
    for s, e in zip(starts, ends):
        if s == e:
            continue
        tid = int(tids_s[s])
        local = nest_s[s:e] - np.int64(tid) * (k * k)
        sel = order[s:e]
        cut = {nm: arr[sel] for nm, arr in products.items()}
        blob = encode_tile("healpix", tid, cut, local=local,
                           nside=nside, tile_nside=tile_nside)
        digest, new = store.put(blob)
        tiles[f"b{band}/{tid}"] = [digest, len(blob), int(e - s)]
        stats["total_bytes"] += len(blob)
        stats["n_new_objects"] += int(new)
    return {"kind": "healpix", "nside": nside, "ordering": "RING",
            "tile_nside": int(tile_nside)}


def tile_epoch(epoch_dir: str, tiles_root: str, *,
               tile_px: int = layout.DEFAULT_WCS_TILE,
               tile_nside: int = 0, chaos=None,
               now=time.time) -> dict:
    """Tile one published epoch; returns the full manifest (already
    durable on disk, with its delta, and ``CURRENT`` rolled forward).

    ``tile_nside`` 0 = auto (``nside // 64``); ``chaos`` injects the
    ``kill_mid_publish`` drill fault between object writes and the
    manifest rename. Re-tiling an already-tiled epoch is idempotent:
    objects are content-addressed and the manifest is atomically
    replaced by an identical one.

    A ``tiles-epoch-NNNNNN.tmp<pid>`` publish marker sits in the tiles
    root from before the first object write until after the CURRENT
    swap: while it exists, ``TileStore.sweep_unreferenced`` refuses to
    GC — the in-flight manifest references objects no on-disk manifest
    does yet. A killed tiler's stale marker ages out
    (``TileStore.publish_in_flight``); the next re-tile removes it.

    The source epoch is verified against its ``integrity.json``
    first: tiling a bit-rotted FITS would launder the damage into
    content-addressed tiles that verify forever after.
    """
    from comapreduce_tpu.mapmaking.fits_io import read_fits_image
    from comapreduce_tpu.resilience.integrity import CorruptArtifactError
    from comapreduce_tpu.serving.epochs import (read_epoch_manifest,
                                                verify_epoch)

    epoch_dir = str(epoch_dir)
    man_src = read_epoch_manifest(epoch_dir)
    if man_src is None:
        raise ValueError(f"{epoch_dir} is not a complete epoch (no "
                         "readable manifest.json)")
    _, problems = verify_epoch(epoch_dir)
    if problems:
        name, detail = problems[0]
        raise CorruptArtifactError(os.path.join(epoch_dir, name),
                                   kind="epoch", detail=detail)
    n = int(man_src["epoch"])
    ts = TileSet(tiles_root)
    marker = os.path.join(str(tiles_root),
                          f"tiles-{epoch_name(n)}.tmp{os.getpid()}")
    t0 = time.perf_counter()
    tiles: dict[str, list] = {}
    stats = {"total_bytes": 0, "n_new_objects": 0, "n_empty": 0}
    bands, pixelization = set(), None
    with open(marker, "w") as f:
        f.write(f"{os.getpid()}\n")
    try:
        for map_name in man_src.get("maps", []):
            path = os.path.join(epoch_dir, str(map_name))
            images = read_fits_image(path)
            if not images:
                raise ValueError(f"{path}: no image HDUs")
            hdr0 = images[0][1]
            band = _band_of(map_name)
            bands.add(band)
            if hdr0.get("PIXTYPE") == "HEALPIX":
                pix = _tile_healpix(images, hdr0, band, tile_nside,
                                    ts.store, tiles, stats)
            else:
                pix = _tile_wcs(images, hdr0, band, tile_px, ts.store,
                                tiles, stats)
            if pixelization is not None and pixelization != pix:
                raise ValueError(f"epoch {n} mixes pixelisations "
                                 f"across bands: {pixelization} vs "
                                 f"{pix}")
            pixelization = pix
        if pixelization is None:
            raise ValueError(f"epoch {n} manifest lists no map "
                             "products")
        products = _product_names(ts, tiles)
        manifest = {
            "schema": 1, "kind": "tiles", "epoch": n,
            "pixelization": pixelization, "products": products,
            "bands": sorted(bands), "tiles": tiles,
            "n_tiles": len(tiles), "n_empty": stats["n_empty"],
            "total_bytes": stats["total_bytes"],
            "source": {"n_files": int(man_src.get("n_files", 0)),
                       "census_sha1": hashlib.sha1("\n".join(
                           man_src.get("census", [])
                       ).encode()).hexdigest()},
            "t_publish_unix": float(now()),
            "t_tile_s": round(time.perf_counter() - t0, 3),
        }
        prev = max((p for p in ts.list_tiled() if p < n), default=None)
        if chaos is not None:
            chaos.maybe_kill_publish(f"tiles-{epoch_name(n)}")
        _write_json(ts.manifest_path(n), manifest)
        delta = _build_delta(ts, n, manifest, prev)
        _write_json(ts.delta_path(n), delta)
        cur = ts.current()
        if cur is None or n >= cur:
            ts.set_current(n, force=True)
    finally:
        # the marker outlives a SIGKILL by design (it ages out /
        # the re-tile clears it) but never an ordinary exception —
        # GC must not stay blocked for an hour over a config error.
        # Stale same-epoch markers from a killed predecessor go too:
        # this (re-)tile just committed or failed; either way no
        # in-flight manifest references unreachable objects.
        for name in os.listdir(str(tiles_root)):
            if name.startswith(f"tiles-{epoch_name(n)}.tmp"):
                try:
                    os.unlink(os.path.join(str(tiles_root), name))
                except OSError:
                    pass
    logger.info("tiled %s: %d tiles (%d empty skipped), %d bytes, "
                "delta %d changed / %d removed vs %s", epoch_name(n),
                len(tiles), stats["n_empty"], stats["total_bytes"],
                len(delta["changed"]), len(delta["removed"]),
                "nothing" if prev is None else epoch_name(prev))
    return manifest


def _product_names(ts: TileSet, tiles: dict) -> list[str]:
    if not tiles:
        return []
    key = sorted(tiles)[0]
    from comapreduce_tpu.tiles.blob import decode_tile

    blob = decode_tile(ts.store.get(tiles[key][0]))
    return list(blob["header"].get("products", []))


def _build_delta(ts: TileSet, n: int, manifest: dict,
                 prev: int | None) -> dict:
    """Exact delta vs the previous tiled epoch: hash comparison over
    the two manifests — correct by the blob encoding's determinism
    (same content, same hash), so ``delta + prev == full re-tile``."""
    prev_tiles = {}
    if prev is not None:
        pman = ts.manifest(prev)
        prev_tiles = (pman or {}).get("tiles", {})
    tiles = manifest["tiles"]
    changed = {k: v for k, v in tiles.items()
               if prev_tiles.get(k, [None])[0] != v[0]}
    removed = sorted(k for k in prev_tiles if k not in tiles)
    return {
        "schema": 1, "kind": "tiles-delta", "epoch": n,
        "prev": prev, "changed": changed, "removed": removed,
        "n_changed": len(changed), "n_removed": len(removed),
        "n_unchanged": len(tiles) - len(changed),
        "changed_bytes": int(sum(v[1] for v in changed.values())),
    }


def is_tile_source(path: str) -> bool:
    """True when ``path`` names tile content: a tiles ROOT (contains
    ``manifests/``), a tile manifest JSON, or a delta's full sibling.
    Cheap — filename/dirname checks first, one small JSON parse only
    for unrecognised ``.json`` paths."""
    p = str(path)
    if os.path.isdir(p):
        return os.path.isdir(os.path.join(p, MANIFESTS_DIR))
    if not p.endswith(".json"):
        return False
    if os.path.basename(os.path.dirname(p)) == MANIFESTS_DIR:
        return parse_epoch_name(
            os.path.basename(p)[:-len(".json")]) is not None
    obj = _read_json(p)
    return bool(obj) and obj.get("kind") == "tiles"


def tile_budget_bytes(pixel_space, tile_nside: int,
                      n_products: int = 4) -> tuple[int, int]:
    """Machine-independent byte ceiling for a compacted HEALPix tile
    set: exact payload (4 B offset + 4 B per product per seen pixel)
    plus :data:`TILE_HEADER_BOUND` per non-empty tile. Returns
    ``(budget_bytes, n_tiles)`` — the perf gate asserts the tiler's
    ``total_bytes`` under the budget and its tile count EQUAL to the
    ``PixelSpace``-derived sparse count."""
    tiles = layout.expected_healpix_tiles(pixel_space, tile_nside)
    payload = 4 * (1 + int(n_products)) * pixel_space.n_compact
    return payload + tiles.size * TILE_HEADER_BOUND, int(tiles.size)
