"""The map read tier's HTTP front: epochs, manifests, tiles, cutouts.

A :class:`TileServer` wraps one tiles root (:class:`tiles.tiler.TileSet`)
in a stdlib ``ThreadingHTTPServer`` — no framework, no extra deps, and
the threading model is safe because everything it serves is immutable
content or an atomically-swapped pointer. The cache story IS the
architecture:

- ``/v1/tiles/<sha256>`` and ``/v1/epochs/<E>/...`` are **immutable**
  (``Cache-Control: public, max-age=31536000, immutable`` + strong
  ``ETag``): a tile object's name is its content hash and an epoch's
  manifest never changes after publish, so any number of HTTP caches /
  CDN edges between this process and millions of readers can hold them
  forever. Scaling the read tier is deploying caches, not servers.
- ``/v1/current`` is the ONE mutable URL (``no-cache`` + validator
  ``ETag``): it follows the tiles ``CURRENT`` pointer at request time,
  so a reader polls one tiny JSON, sees a new epoch, fetches that
  epoch's delta manifest, and refreshes only the changed tiles.
- Conditional requests (``If-None-Match``) short-circuit to ``304``
  everywhere, including across an operator **rollback**: the pointer
  swap changes ``/v1/current``'s ETag, while every epoch-addressed URL
  keeps validating — a reader pinned on a rolled-back-from epoch keeps
  its cache intact.

Rectangular sky cutouts (``/v1/epochs/<E>/cutout?x0=&y0=&w=&h=``) are
assembled server-side from exactly the tiles the box touches
(:mod:`tiles.cutout`) and encoded with the tile blob format — and
because that encoding is deterministic, a cutout's ETag is a content
hash too, making even computed responses CDN-cacheable.

Telemetry (when ``TELEMETRY`` is configured — the tile server runs on
its own serving-lane rank): request count / bytes / latency counters
per route class, plus registered gauges for the current epoch and its
freshness.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from comapreduce_tpu.serving.epochs import (epoch_name, parse_epoch_name,
                                            read_epoch_manifest)
from comapreduce_tpu.tiles.tiler import TileSet

__all__ = ["TileServer", "IMMUTABLE_CACHE", "MUTABLE_CACHE"]

logger = logging.getLogger(__name__)

IMMUTABLE_CACHE = "public, max-age=31536000, immutable"
MUTABLE_CACHE = "no-cache"

_JSON = "application/json"
_BLOB = "application/x-comap-tile"
_PROM = "text/plain; version=0.0.4; charset=utf-8"


class _HTTPError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


def _parse_epoch_spec(spec: str) -> int:
    """Path epoch component: ``epoch-000007`` or plain ``7``."""
    n = parse_epoch_name(spec)
    if n is None and spec.isdigit():
        n = int(spec)
    if n is None:
        raise _HTTPError(400, f"bad epoch {spec!r} (want N or "
                              "epoch-NNNNNN)")
    return n


def _int_param(q: dict, name: str) -> int:
    vals = q.get(name)
    if not vals:
        raise _HTTPError(400, f"missing cutout parameter {name!r}")
    try:
        return int(vals[0])
    except ValueError:
        raise _HTTPError(400, f"cutout parameter {name}={vals[0]!r} is "
                              "not an integer") from None


class _Reply:
    """One response: status + typed body + cache class."""

    __slots__ = ("status", "ctype", "body", "etag", "immutable")

    def __init__(self, body: bytes, ctype: str = _JSON, *,
                 status: int = 200, etag: str | None = None,
                 immutable: bool = False):
        self.status = status
        self.ctype = ctype
        self.body = body
        self.etag = etag
        self.immutable = immutable

    @classmethod
    def json(cls, obj, **kw) -> "_Reply":
        return cls(json.dumps(obj, sort_keys=True).encode("utf-8")
                   + b"\n", _JSON, **kw)


class TileServer:
    """Serve one tiles root over HTTP (see module docstring).

    ``port=0`` binds an ephemeral port (tests/drills); the bound port
    is ``self.port``. ``epochs_root`` optionally points at the source
    ``EpochStore`` so ``/v1/epochs/<E>/meta`` can serve the solve
    metadata (census size, CG residual) next to the tile manifest.
    Run with :meth:`serve_forever` (blocking) or :meth:`start` (a
    daemon thread — the in-process mode drills and tests use).
    """

    def __init__(self, tiles_root: str, host: str = "127.0.0.1",
                 port: int = 0, epochs_root: str | None = None):
        self.tiles = TileSet(tiles_root)
        self.epochs_root = str(epochs_root) if epochs_root else None
        self._lock = threading.Lock()
        self.stats = {"t_start_unix": time.time(), "n_requests": 0,
                      "n_304": 0, "n_errors": 0, "bytes_sent": 0,
                      "by_route": {}}
        # per-request latency histogram + route/status counters in the
        # live sidecar's exact /metrics schema (ISSUE 15)
        from comapreduce_tpu.telemetry.core import RequestMetrics

        self.request_metrics = RequestMetrics("tiles_http")
        self.httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.app = self
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None
        self._gauges_registered = self._register_gauges()

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self) -> None:
        logger.info("tile server on http://%s:%d/ (root %s)", self.host,
                    self.port, self.tiles.root)
        self.httpd.serve_forever(poll_interval=0.2)

    def start(self) -> "TileServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="tile-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- telemetry ---------------------------------------------------------

    def _register_gauges(self) -> bool:
        from comapreduce_tpu.telemetry import TELEMETRY

        if not TELEMETRY.enabled:
            # register_gauge no-ops while telemetry is disabled, so a
            # server built BEFORE TELEMETRY.configure would silently
            # never export its gauges — _account re-attempts on the
            # first request after telemetry comes up
            return False
        TELEMETRY.register_gauge("serving.tiles.current_epoch",
                                 lambda: self.tiles.current())
        TELEMETRY.register_gauge("serving.tiles.freshness_s",
                                 self._freshness_s)
        TELEMETRY.register_gauge(
            "serving.tiles.http.requests_total",
            lambda: self.stats["n_requests"])
        return True

    def _freshness_s(self) -> float | None:
        """Age of the CURRENT tile set — the staleness a reader who
        refreshes right now observes. None until something is tiled."""
        n = self.tiles.current()
        man = self.tiles.manifest(n) if n is not None else None
        if not man:
            return None
        return max(0.0, time.time() - float(man.get("t_publish_unix", 0)))

    def _account(self, route: str, status: int, n_bytes: int,
                 dur_s: float) -> None:
        from comapreduce_tpu.telemetry import TELEMETRY

        self.request_metrics.observe(route, status, dur_s)
        with self._lock:
            st = self.stats
            st["n_requests"] += 1
            st["bytes_sent"] += n_bytes
            if status == 304:
                st["n_304"] += 1
            elif status >= 400:
                st["n_errors"] += 1
            br = st["by_route"].setdefault(route, {"n": 0, "bytes": 0})
            br["n"] += 1
            br["bytes"] += n_bytes
        if TELEMETRY.enabled:
            if not self._gauges_registered:
                self._gauges_registered = self._register_gauges()
            TELEMETRY.counter("serving.tiles.http.requests",
                              route=route, status=int(status))
            if n_bytes:
                TELEMETRY.counter("serving.tiles.http.bytes", n_bytes,
                                  route=route)
            TELEMETRY.event_span("serving.tiles.http.request", dur_s,
                                 unit=route, status=int(status))

    def prom_text(self) -> str:
        """The /metrics page: request-latency histogram + per-route
        counters (``RequestMetrics``), then the serving gauges the
        register_gauge path exports when a campaign's telemetry is up —
        here they are scrapeable even for a standalone tile server."""
        out = list(self.request_metrics.prom_lines())

        def gauge(name, value):
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {value:g}")

        cur = self.tiles.current()
        if cur is not None:
            gauge("comap_tiles_current_epoch", int(cur))
        fresh = self._freshness_s()
        if fresh is not None:
            gauge("comap_tiles_freshness_seconds", fresh)
        with self._lock:
            sent = self.stats["bytes_sent"]
        out.append("# TYPE comap_tiles_http_bytes_sent_total counter")
        out.append(f"comap_tiles_http_bytes_sent_total {sent}")
        return "\n".join(out) + "\n"

    # -- routing -----------------------------------------------------------

    def handle(self, path: str, query: str) -> tuple[str, _Reply]:
        """Resolve one request to ``(route_class, reply)``; raises
        ``_HTTPError`` for client errors."""
        parts = [p for p in path.split("/") if p]
        if parts == ["metrics"]:
            # the tile tier self-surfaces its request telemetry in the
            # live sidecar's exact Prometheus schema (ISSUE 15)
            return "metrics", _Reply(
                self.prom_text().encode("utf-8"), _PROM)
        if parts == ["v1", "current"]:
            return "current", self._reply_current()
        if parts == ["v1", "status"]:
            return "status", _Reply.json(self.status())
        if parts == ["v1", "epochs"]:
            return "epochs", _Reply.json(
                {"epochs": self.tiles.list_tiled()})
        if len(parts) == 3 and parts[:2] == ["v1", "tiles"]:
            return "tile", self._reply_tile(parts[2])
        if len(parts) == 4 and parts[:2] == ["v1", "epochs"]:
            n = _parse_epoch_spec(parts[2])
            leaf = parts[3]
            if leaf == "manifest.json":
                return "manifest", self._reply_manifest_file(
                    self.tiles.manifest_path(n), n)
            if leaf == "delta.json":
                return "delta", self._reply_manifest_file(
                    self.tiles.delta_path(n), n)
            if leaf == "meta":
                return "meta", self._reply_meta(n)
            if leaf == "cutout":
                return "cutout", self._reply_cutout(n, query)
        raise _HTTPError(404, f"no route for {path}")

    def _reply_current(self) -> _Reply:
        cur = self.tiles.current()
        obj = {"epoch": cur,
               "name": epoch_name(cur) if cur is not None else None,
               "latest": self.tiles.latest()}
        # validator ETag: a poll after a publish or rollback misses,
        # everything else is a 304 — the pointer itself is tiny anyway
        return _Reply.json(obj, etag=f'W/"cur-{cur}"')

    def _reply_tile(self, digest: str) -> _Reply:
        d = digest.lower()
        if len(d) != 64 or any(c not in "0123456789abcdef" for c in d):
            raise _HTTPError(400, f"bad tile id {digest!r} (want a "
                                  "sha256 hex digest)")
        try:
            blob = self.tiles.store.get(d)
        except OSError:
            raise _HTTPError(404, f"no tile object {d}") from None
        return _Reply(blob, _BLOB, etag=f'"{d}"', immutable=True)

    def _reply_manifest_file(self, path: str, n: int) -> _Reply:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            raise _HTTPError(404, f"epoch {n} is not tiled") from None
        d = self.tiles.store.digest(raw)
        return _Reply(raw, _JSON, etag=f'"{d}"', immutable=True)

    def _reply_meta(self, n: int) -> _Reply:
        """Epoch metadata without the (possibly large) tile index: the
        tile manifest's summary fields plus, when the source epoch
        store is mounted, the solve manifest."""
        man = self.tiles.manifest(n)
        if man is None:
            raise _HTTPError(404, f"epoch {n} is not tiled")
        obj = {k: v for k, v in man.items() if k != "tiles"}
        if self.epochs_root:
            import os

            src = read_epoch_manifest(
                os.path.join(self.epochs_root, epoch_name(n)))
            if src is not None:
                obj["solve"] = {k: v for k, v in src.items()
                                if k != "census"}
        raw = json.dumps(obj, sort_keys=True).encode("utf-8") + b"\n"
        return _Reply(raw, _JSON,
                      etag=f'"{self.tiles.store.digest(raw)}"',
                      immutable=True)

    def _reply_cutout(self, n: int, query: str) -> _Reply:
        from comapreduce_tpu.tiles.cutout import cutout_blob

        man = self.tiles.manifest(n)
        if man is None:
            raise _HTTPError(404, f"epoch {n} is not tiled")
        q = parse_qs(query)
        x0, y0 = _int_param(q, "x0"), _int_param(q, "y0")
        w, h = _int_param(q, "w"), _int_param(q, "h")
        band = int(q.get("band", ["0"])[0])
        products = None
        if q.get("products"):
            products = [p for p in q["products"][0].split(",") if p]
        try:
            blob = cutout_blob(self.tiles, man, x0, y0, w, h,
                               band=band, products=products)
        except ValueError as exc:
            raise _HTTPError(400, str(exc)) from None
        # deterministic encoding -> the ETag is a true content hash,
        # identical across servers and epochs with the same sky
        return _Reply(blob, _BLOB,
                      etag=f'"{self.tiles.store.digest(blob)}"',
                      immutable=True)

    def status(self) -> dict:
        cur = self.tiles.current()
        man = self.tiles.manifest(cur) if cur is not None else None
        with self._lock:
            st = {k: (dict(v) if isinstance(v, dict) else v)
                  for k, v in self.stats.items()}
        return {
            "root": self.tiles.root, "current": cur,
            "latest": self.tiles.latest(),
            "tiled_epochs": len(self.tiles.list_tiled()),
            "current_tiles": (man or {}).get("n_tiles"),
            "current_bytes": (man or {}).get("total_bytes"),
            "freshness_s": self._freshness_s(),
            "uptime_s": round(time.time() - st["t_start_unix"], 3),
            "http": st,
        }


class _Handler(BaseHTTPRequestHandler):
    server_version = "comap-tiles/1"
    protocol_version = "HTTP/1.1"

    # stdlib logs every request to stderr by default; route to logging
    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        logger.debug("tile-server %s - %s", self.address_string(),
                     fmt % args)

    def do_GET(self):  # noqa: N802 - stdlib casing
        self._serve(send_body=True)

    def do_HEAD(self):  # noqa: N802 - stdlib casing
        self._serve(send_body=False)

    def _serve(self, send_body: bool) -> None:
        app: TileServer = self.server.app
        t0 = time.monotonic()
        url = urlsplit(self.path)
        route = "error"
        try:
            route, reply = app.handle(url.path, url.query)
        except _HTTPError as exc:
            reply = _Reply.json({"error": str(exc)}, status=exc.status)
        except Exception as exc:  # a bug must 500, not kill the thread
            logger.exception("tile-server error on %s", self.path)
            reply = _Reply.json({"error": f"internal: {exc}"},
                                status=500)
        sent = self._send(reply, send_body)
        app._account(route, reply.status if sent != 304 else 304,
                     sent if isinstance(sent, int) and sent != 304 else 0,
                     time.monotonic() - t0)

    def _send(self, reply: _Reply, send_body: bool):
        """Write one response; returns bytes sent, or 304."""
        inm = self.headers.get("If-None-Match")
        if reply.etag and inm and reply.status == 200 and \
                reply.etag in [t.strip() for t in inm.split(",")]:
            self.send_response(304)
            if reply.etag:
                self.send_header("ETag", reply.etag)
            self.send_header("Cache-Control",
                             IMMUTABLE_CACHE if reply.immutable
                             else MUTABLE_CACHE)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return 304
        try:
            self.send_response(reply.status)
            self.send_header("Content-Type", reply.ctype)
            self.send_header("Content-Length", str(len(reply.body)))
            if reply.etag:
                self.send_header("ETag", reply.etag)
            self.send_header("Cache-Control",
                             IMMUTABLE_CACHE if reply.immutable
                             else MUTABLE_CACHE)
            self.end_headers()
            if send_body:
                self.wfile.write(reply.body)
        except (BrokenPipeError, ConnectionResetError):
            return 0  # reader hung up mid-write; nothing to do
        return len(reply.body) if send_body else 0
