"""Fleet-level summaries across Level-2 files.

Covers two reference roles:

- ``Level2Timelines`` (``Analysis/Level2Data.py:142-223``): system
  temperature / gain / noise timelines over many observations;
- the ``Summary/`` package (``Summary/CalibrationFactors.py:19-165``):
  aggregation of calibration factors into a ``gains.hd5``-style product,
  read back with outlier-robust smoothing (``data/Data.py:13-98``
  ``read_gains``).
"""

from __future__ import annotations

import logging

import numpy as np

from comapreduce_tpu.data.hdf5io import HDF5Store
from comapreduce_tpu.data.level import COMAPLevel2
from comapreduce_tpu.database.obsdb import robust_smooth

__all__ = ["level2_timelines", "timeline_row", "assemble_timelines",
           "write_gains", "read_gains", "merge_gains"]

logger = logging.getLogger("comapreduce_tpu")


def timeline_row(fname):
    """One observation's timeline row ``(mjd, obsid, tsys, gain, rms)``
    from a Level-2 file, or ``None`` on a bad/unreadable file — the
    incremental unit of :func:`level2_timelines` (cache these to avoid
    re-reading the whole fleet per update)."""
    try:
        lvl2 = COMAPLevel2(filename=fname)
        mjd = float(np.mean(np.asarray(lvl2.mjd)))
        tsys = gain = rms = None
        if "vane/system_temperature" in lvl2:
            t = np.asarray(lvl2.system_temperature)  # (E, F, B, C)
            g = np.asarray(lvl2.system_gain)
            tsys = np.nanmedian(np.where(t > 0, t, np.nan), axis=(0, 3))
            gain = np.nanmedian(np.where(g > 0, g, np.nan), axis=(0, 3))
        if "fnoise_fits/auto_rms" in lvl2:
            rms = np.nanmedian(
                np.asarray(lvl2["fnoise_fits/auto_rms"]), axis=-1)
        return (mjd, lvl2.obsid, tsys, gain, rms)
    except (OSError, KeyError) as exc:
        logger.warning("level2_timelines: BAD FILE %s (%s)", fname, exc)
        return None


def level2_timelines(filenames) -> dict:
    """Per-observation median Tsys/gain/noise timelines.

    Returns dict of arrays sorted by MJD: ``mjd[T]``, ``obsid[T]``,
    ``tsys[T, F, B]``, ``gain[T, F, B]``, ``auto_rms[T, F, B]``
    (``Level2Timelines``, ``Level2Data.py:142-223``). Files missing a
    product contribute NaN rows.
    """
    rows = [r for r in (timeline_row(f) for f in filenames)
            if r is not None]
    return assemble_timelines(rows)


def assemble_timelines(rows) -> dict:
    """Stack :func:`timeline_row` tuples into the timelines dict."""
    if not rows:
        return {"mjd": np.zeros(0), "obsid": np.zeros(0, np.int64)}
    rows = sorted(rows, key=lambda r: r[0])
    # (F, B) from any product in any file — tsys may be absent everywhere
    # while auto_rms is present
    shapes = [r[i].shape for r in rows for i in (2, 3, 4)
              if r[i] is not None]
    fb = shapes[0] if shapes else (0, 0)

    def stack(idx):
        out = np.full((len(rows),) + fb, np.nan)
        for i, r in enumerate(rows):
            if r[idx] is None:
                continue
            if r[idx].shape != fb:
                logger.warning("level2_timelines: obsid %s has shape %s "
                               "!= %s; NaN-filled", rows[i][1],
                               r[idx].shape, fb)
                continue
            out[i] = r[idx]
        return out

    return {
        "mjd": np.array([r[0] for r in rows]),
        "obsid": np.array([r[1] for r in rows], np.int64),
        "tsys": stack(2),
        "gain": stack(3),
        "auto_rms": stack(4),
    }


def write_gains(path: str, timelines: dict) -> None:
    """Persist timelines as the ``gains.hd5`` analogue
    (``Summary/CalibrationFactors.py`` output role)."""
    store = HDF5Store(name="gains")
    for k, v in timelines.items():
        store[f"gains/{k}"] = np.asarray(v)
    # atomic: the Level2Timelines stage rewrites this product after every
    # processed file — a kill mid-write must not truncate it
    store.write(path, atomic=True)


def merge_gains(output_path: str, inputs=None) -> dict:
    """Merge per-rank gains products into ONE fleet-wide ``gains.hd5``.

    A multi-process ``Level2Timelines`` run writes ``{base}_rank{r}{ext}``
    shards (disjoint filelist shards per rank — ``pipeline/stages.py``);
    the reference builds the single fleet product in
    ``Summary/CalibrationFactors.py:19-165``. ``inputs`` is a list of
    shard paths; ``None`` discovers ``{output}_rank{N}{ext}`` next to
    ``output_path`` (non-numeric ``_rank*`` strays are ignored). Rows
    are concatenated, de-duplicated by obsid — the row with the LATEST
    MJD wins, so a reprocessed observation beats its stale copy in any
    shard — sorted by MJD, and written atomically to ``output_path``.
    Returns the merged timelines dict.
    """
    import glob
    import os
    import re

    if inputs is None:
        base, ext = os.path.splitext(output_path)
        numbered = []
        for p in glob.glob(f"{base}_rank*{ext}"):
            m = re.search(r"_rank(\d+)", os.path.basename(p))
            if m:
                numbered.append((int(m.group(1)), p))
            else:
                logger.warning("merge_gains: ignoring non-rank file %s", p)
        inputs = [p for _, p in sorted(numbered)]
    if not inputs:
        raise FileNotFoundError(
            f"merge_gains: no rank shards found for {output_path}")
    rows: dict = {}   # obsid -> row tuple; latest-MJD row wins
    for path in inputs:
        shard = read_gains(path, smooth_window_days=0.0)
        mjd = shard.get("mjd")
        if mjd is None or not len(mjd):
            logger.warning("merge_gains: %s is empty; skipped", path)
            continue
        for i in range(len(mjd)):
            def pick(key):
                arr = shard.get(key)
                # a product-less shard stores (T, 0, 0) NaN arrays;
                # treating those as data would poison the merged (F, B)
                return (arr[i] if arr is not None and arr.ndim == 3
                        and arr[i].size else None)
            obsid = int(shard["obsid"][i])
            row = (float(mjd[i]), obsid,
                   pick("tsys"), pick("gain"), pick("auto_rms"))
            old = rows.get(obsid)
            has_data = any(v is not None for v in row[2:])
            old_has_data = old is not None and any(
                v is not None for v in old[2:])
            # latest MJD wins — but data beats product-less regardless of
            # MJD or shard order, and a product-less row never displaces
            # real calibration data
            if old is None \
                    or (has_data and not old_has_data) \
                    or (row[0] >= old[0]
                        and (has_data or not old_has_data)):
                rows[obsid] = row
    merged = assemble_timelines(list(rows.values()))
    write_gains(output_path, merged)
    logger.info("merge_gains: %d observations from %d shards -> %s",
                len(rows), len(inputs), output_path)
    return merged


def read_gains(path: str, smooth_window_days: float = 30.0) -> dict:
    """Load a gains file; adds outlier-robust smoothed ``tsys_smooth`` /
    ``gain_smooth`` (``data/Data.py:57-98`` ``read_gains``)."""
    store = HDF5Store(name="gains")
    store.read(path)
    out = {k.split("/", 1)[1]: np.asarray(v) for k, v in store.items()}
    mjd = out.get("mjd")
    if smooth_window_days <= 0:   # raw read (e.g. the merge tool)
        return out
    for key in ("tsys", "gain"):
        arr = out.get(key)
        if arr is None or mjd is None or arr.ndim != 3 or not len(mjd):
            continue
        sm = np.empty_like(arr)
        for f in range(arr.shape[1]):
            for b in range(arr.shape[2]):
                v = arr[:, f, b]
                ok = np.isfinite(v)
                if ok.sum() < 2:
                    sm[:, f, b] = v
                    continue
                sm[ok, f, b] = robust_smooth(mjd[ok], v[ok],
                                             smooth_window_days)
                sm[~ok, f, b] = np.interp(mjd[~ok], mjd[ok], sm[ok, f, b])
        out[f"{key}_smooth"] = sm
    return out
