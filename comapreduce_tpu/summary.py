"""Fleet-level summaries across Level-2 files.

Covers two reference roles:

- ``Level2Timelines`` (``Analysis/Level2Data.py:142-223``): system
  temperature / gain / noise timelines over many observations;
- the ``Summary/`` package (``Summary/CalibrationFactors.py:19-165``):
  aggregation of calibration factors into a ``gains.hd5``-style product,
  read back with outlier-robust smoothing (``data/Data.py:13-98``
  ``read_gains``).
"""

from __future__ import annotations

import logging

import numpy as np

from comapreduce_tpu.data.hdf5io import HDF5Store
from comapreduce_tpu.data.level import COMAPLevel2
from comapreduce_tpu.database.obsdb import robust_smooth

__all__ = ["level2_timelines", "timeline_row", "assemble_timelines",
           "write_gains", "read_gains"]

logger = logging.getLogger("comapreduce_tpu")


def timeline_row(fname):
    """One observation's timeline row ``(mjd, obsid, tsys, gain, rms)``
    from a Level-2 file, or ``None`` on a bad/unreadable file — the
    incremental unit of :func:`level2_timelines` (cache these to avoid
    re-reading the whole fleet per update)."""
    try:
        lvl2 = COMAPLevel2(filename=fname)
        mjd = float(np.mean(np.asarray(lvl2.mjd)))
        tsys = gain = rms = None
        if "vane/system_temperature" in lvl2:
            t = np.asarray(lvl2.system_temperature)  # (E, F, B, C)
            g = np.asarray(lvl2.system_gain)
            tsys = np.nanmedian(np.where(t > 0, t, np.nan), axis=(0, 3))
            gain = np.nanmedian(np.where(g > 0, g, np.nan), axis=(0, 3))
        if "fnoise_fits/auto_rms" in lvl2:
            rms = np.nanmedian(
                np.asarray(lvl2["fnoise_fits/auto_rms"]), axis=-1)
        return (mjd, lvl2.obsid, tsys, gain, rms)
    except (OSError, KeyError) as exc:
        logger.warning("level2_timelines: BAD FILE %s (%s)", fname, exc)
        return None


def level2_timelines(filenames) -> dict:
    """Per-observation median Tsys/gain/noise timelines.

    Returns dict of arrays sorted by MJD: ``mjd[T]``, ``obsid[T]``,
    ``tsys[T, F, B]``, ``gain[T, F, B]``, ``auto_rms[T, F, B]``
    (``Level2Timelines``, ``Level2Data.py:142-223``). Files missing a
    product contribute NaN rows.
    """
    rows = [r for r in (timeline_row(f) for f in filenames)
            if r is not None]
    return assemble_timelines(rows)


def assemble_timelines(rows) -> dict:
    """Stack :func:`timeline_row` tuples into the timelines dict."""
    if not rows:
        return {"mjd": np.zeros(0), "obsid": np.zeros(0, np.int64)}
    rows = sorted(rows, key=lambda r: r[0])
    # (F, B) from any product in any file — tsys may be absent everywhere
    # while auto_rms is present
    shapes = [r[i].shape for r in rows for i in (2, 3, 4)
              if r[i] is not None]
    fb = shapes[0] if shapes else (0, 0)

    def stack(idx):
        out = np.full((len(rows),) + fb, np.nan)
        for i, r in enumerate(rows):
            if r[idx] is None:
                continue
            if r[idx].shape != fb:
                logger.warning("level2_timelines: obsid %s has shape %s "
                               "!= %s; NaN-filled", rows[i][1],
                               r[idx].shape, fb)
                continue
            out[i] = r[idx]
        return out

    return {
        "mjd": np.array([r[0] for r in rows]),
        "obsid": np.array([r[1] for r in rows], np.int64),
        "tsys": stack(2),
        "gain": stack(3),
        "auto_rms": stack(4),
    }


def write_gains(path: str, timelines: dict) -> None:
    """Persist timelines as the ``gains.hd5`` analogue
    (``Summary/CalibrationFactors.py`` output role)."""
    store = HDF5Store(name="gains")
    for k, v in timelines.items():
        store[f"gains/{k}"] = np.asarray(v)
    # atomic: the Level2Timelines stage rewrites this product after every
    # processed file — a kill mid-write must not truncate it
    store.write(path, atomic=True)


def read_gains(path: str, smooth_window_days: float = 30.0) -> dict:
    """Load a gains file; adds outlier-robust smoothed ``tsys_smooth`` /
    ``gain_smooth`` (``data/Data.py:57-98`` ``read_gains``)."""
    store = HDF5Store(name="gains")
    store.read(path)
    out = {k.split("/", 1)[1]: np.asarray(v) for k, v in store.items()}
    mjd = out.get("mjd")
    for key in ("tsys", "gain"):
        arr = out.get(key)
        if arr is None or mjd is None or arr.ndim != 3 or not len(mjd):
            continue
        sm = np.empty_like(arr)
        for f in range(arr.shape[1]):
            for b in range(arr.shape[2]):
                v = arr[:, f, b]
                ok = np.isfinite(v)
                if ok.sum() < 2:
                    sm[:, f, b] = v
                    continue
                sm[ok, f, b] = robust_smooth(mjd[ok], v[ok],
                                             smooth_window_days)
                sm[~ok, f, b] = np.interp(mjd[~ok], mjd[ok], sm[ok, f, b])
        out[f"{key}_smooth"] = sm
    return out
