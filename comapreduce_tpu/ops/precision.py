"""Explicit end-to-end precision policy (ISSUE 13).

The pipeline runs f32 everywhere by default, with two known pressure
points on opposite ends of the precision axis:

- **Ingest is memory-bound.** The TOD blocks dominate cache bytes,
  HBM residency, and the H2D traffic the ``ingest.h2d.bytes`` counter
  meters (OPERATIONS.md §13). Production map-makers stream TOD in the
  cheapest precision the science tolerates ("fast and precise
  map-making", arXiv 0912.2738; MAPPRAISER, arXiv 2112.03370) — bf16
  keeps f32's exponent range (NaN sentinels and the ``scrub_tod``
  tripwires survive the round-trip bit-exactly in their *finiteness*)
  while halving every byte count. The fused reduction upcasts to f32
  at the first arithmetic touch, so accumulators, band averages and
  gain solves keep f32 semantics; only storage and transport narrow.
- **CG recurrences are precision-bound.** The alpha/beta/residual dot
  products accumulate rounding at tight tolerances (the f32 stall
  edge ROOFLINE round 8 discusses; the block-8/16 twolevel divergence
  BENCH_r06 records). A compensated (float-float, effectively
  f64-emulated) dot restores the lost bits exactly where iteration
  counts are precision-limited, without widening any array state.

:class:`PrecisionPolicy` is the single config object for both knobs,
threaded like ``ShapeBuckets``: ``[Precision]`` in the destriper INI,
``[precision]`` in the runner TOML. The default policy is the identity
— byte-identical behaviour to a build without this module.

Products are NEVER narrowed: FITS maps, tile blobs (``CMTL1`` is LE
f32 by format) and coadds stay f32 regardless of policy (enforced in
``band_map_writer`` / ``fits_io`` / ``tiles.blob``).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "PrecisionPolicy",
    "TOD_PAYLOAD_KEYS",
    "tod_numpy_dtype",
    "cast_payload_tod",
    "two_sum",
    "two_prod",
    "precise_sum",
    "precise_dot",
    "precise_norm",
]

# bf16 as a *numpy* dtype comes from ml_dtypes (a jax dependency).
# Gated import: if the environment lacks it, requesting bf16 raises a
# clear error instead of an ImportError at module import time.
try:  # pragma: no cover - ml_dtypes ships with jax
    import ml_dtypes as _ml_dtypes

    _BF16 = np.dtype(_ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None


# The HDF5 dataset paths whose payload arrays a bf16 policy narrows.
# ONLY the TOD streams: weights, masks, pointing, and calibration
# tables stay f32 (narrowing a weight changes solve semantics; the TOD
# is re-widened at the first device-side reduce).
TOD_PAYLOAD_KEYS = frozenset({
    "spectrometer/tod",            # Level-1 raw counts
    "averaged_tod/tod",            # Level-2 band averages
    "averaged_tod/tod_original",   # Level-2, no gain subtraction
    "frequency_binned/tod",        # Level-2 frequency-binned variant
})

_TOD_DTYPE_ALIASES = {
    "f32": "f32", "float32": "f32", "fp32": "f32",
    "bf16": "bf16", "bfloat16": "bf16",
}
_CG_DOT_VALUES = ("f32", "compensated")


class PrecisionPolicy:
    """End-to-end precision knobs (value-hashable like ``ShapeBuckets``).

    - ``tod_dtype``: ``"f32"`` (default) or ``"bf16"`` — the dtype TOD
      payloads are *stored and shipped* in (``BlockCache``, the
      prefetcher queue, H2D transfers). Accumulators are always f32:
      the fused reduction and the destriper upcast at first touch.
    - ``cg_dot``: ``"f32"`` (default) or ``"compensated"`` — the dot
      product the CG recurrences (alpha/beta/residual and the
      divergence monitor) use. ``"compensated"`` swaps in
      :func:`precise_dot`, a float-float (two-sum/two-product) dot
      with ~2x f32's effective mantissa, so tight-tolerance solves
      stop stalling at the f32 rounding floor.

    The default instance is the identity policy: nothing changes dtype
    and no solver code path diverges (byte-identical to policy-off).
    """

    KNOBS = ("tod_dtype", "cg_dot")

    def __init__(self, tod_dtype: str = "f32", cg_dot: str = "f32"):
        td = _TOD_DTYPE_ALIASES.get(str(tod_dtype).strip().lower())
        if td is None:
            raise ValueError(
                f"[Precision] tod_dtype must be one of f32|bf16, "
                f"got {tod_dtype!r}")
        cd = str(cg_dot).strip().lower()
        if cd not in _CG_DOT_VALUES:
            raise ValueError(
                f"[Precision] cg_dot must be one of f32|compensated, "
                f"got {cg_dot!r}")
        if td == "bf16" and _BF16 is None:  # pragma: no cover
            raise ValueError(
                "tod_dtype=bf16 requires the ml_dtypes package "
                "(ships with jax); it is missing in this environment")
        self.tod_dtype = td
        self.cg_dot = cd

    def _key(self):
        return (self.tod_dtype, self.cg_dot)

    def __eq__(self, other):
        return (type(other) is PrecisionPolicy and
                self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return (f"PrecisionPolicy(tod_dtype={self.tod_dtype!r}, "
                f"cg_dot={self.cg_dot!r})")

    @property
    def enabled(self) -> bool:
        """True iff any knob departs from the identity policy."""
        return self.tod_dtype != "f32" or self.cg_dot != "f32"

    @classmethod
    def coerce(cls, value) -> "PrecisionPolicy":
        """None / dict / PrecisionPolicy -> PrecisionPolicy.

        A typo'd knob raises instead of silently running the default —
        the ``[Resilience]``/``[Destriper]`` section contract."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown precision keys: {sorted(unknown)} "
                    f"(knobs: {list(cls.KNOBS)})")
            return cls(**known)
        raise TypeError(f"cannot build PrecisionPolicy from {type(value)}")


def tod_numpy_dtype(tod_dtype: str):
    """The numpy dtype a ``tod_dtype`` knob value stores TOD in."""
    td = _TOD_DTYPE_ALIASES.get(str(tod_dtype).strip().lower())
    if td == "f32":
        return np.dtype(np.float32)
    if td == "bf16":
        if _BF16 is None:  # pragma: no cover
            raise ValueError("bf16 requires ml_dtypes (ships with jax)")
        return _BF16
    raise ValueError(f"unknown tod_dtype {tod_dtype!r}")


def cast_payload_tod(payload, tod_dtype: str):
    """Narrow the TOD datasets of an exported store payload in place.

    ``payload`` is the ``export_payload`` dict (``{"data": {path:
    array}, "attrs": ...}``); only the :data:`TOD_PAYLOAD_KEYS` arrays
    are cast — weights/masks/pointing stay f32. Runs on the
    prefetcher's WORKER thread so the ``BlockCache`` holds the
    narrowed bytes (the cache is dtype-homogeneous per run: its key is
    ``(path, mtime)``, so one run must not mix policies on one cache).
    Live (non-dict) payloads pass through untouched — a lazy Level-1
    handle is never cached, so there is nothing to narrow.
    """
    dtype = tod_numpy_dtype(tod_dtype)
    if dtype == np.float32:
        return payload
    if not (isinstance(payload, dict) and "data" in payload):
        return payload
    data = payload["data"]
    for key in TOD_PAYLOAD_KEYS:
        arr = data.get(key)
        if arr is not None and getattr(arr, "dtype", None) != dtype:
            data[key] = np.asarray(arr).astype(dtype)
    return payload


# ---------------------------------------------------------------------------
# Compensated (float-float) arithmetic for the CG recurrences.
#
# Classic error-free transformations (Knuth two-sum, Dekker split /
# two-product) carried through a pairwise tree reduction — the dot2
# algorithm of Ogita, Rump & Oishi (2005) in a vectorised, jittable
# form. Each value is an unevaluated (hi, lo) pair with |lo| <= ulp(hi)
# / 2, giving ~2x the f32 mantissa (~48 effective bits): effectively
# f64 accuracy without f64 hardware (jax_enable_x64 stays off).
# XLA does not reassociate floating-point ops by default, so the
# cancellation tricks below survive jit compilation.
# ---------------------------------------------------------------------------


def two_sum(a, b):
    """Knuth's error-free sum: returns ``(s, err)`` with
    ``s = fl(a + b)`` and ``a + b = s + err`` exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _split(a):
    # Dekker split for binary32: C = 2^12 + 1 halves the 24-bit
    # mantissa into two 12-bit pieces whose products are exact in f32.
    c = a * 4097.0
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    """Dekker/Veltkamp error-free product: ``(p, err)`` with
    ``p = fl(a * b)`` and ``a * b = p + err`` exactly (no FMA needed)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _ff_add(xh, xl, yh, yl):
    # add two float-float values, renormalised so |lo| <= ulp(hi)/2
    s, e = two_sum(xh, yh)
    e = e + (xl + yl)
    hi = s + e
    return hi, e - (hi - s)


def _ff_tree_sum(hi, lo):
    """Pairwise (log-depth) float-float sum over the LAST axis.

    Pads to a power of two with exact zeros and halves repeatedly —
    fully vectorised over any leading axes (the multi-RHS band axis of
    the planned solver rides along for free) and O(log n) rounding
    depth on top of the compensation."""
    import jax.numpy as jnp

    n = hi.shape[-1]
    p = 1
    while p < n:
        p *= 2
    if p != n:
        pad = [(0, 0)] * (hi.ndim - 1) + [(0, p - n)]
        hi = jnp.pad(hi, pad)
        lo = jnp.pad(lo, pad)
    while hi.shape[-1] > 1:
        h = hi.shape[-1] // 2
        hi, lo = _ff_add(hi[..., :h], lo[..., :h],
                         hi[..., h:], lo[..., h:])
    return hi[..., 0], lo[..., 0]


def precise_sum(x, axis=None):
    """Compensated sum of ``x`` (f32 in, f32 out, ~f64 internally).

    ``axis=None`` sums everything; otherwise the axis must be the last
    (the only shape the CG recurrences need)."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    if axis is None:
        x = x.reshape(-1)
    elif axis not in (-1, x.ndim - 1):
        raise ValueError(f"precise_sum supports axis=None|-1, got {axis}")
    hi, lo = _ff_tree_sum(x, jnp.zeros_like(x))
    return hi + lo


def precise_dot(x, y, axis=None):
    """Compensated dot product (Ogita–Rump–Oishi dot2, pairwise form).

    f32 inputs, f32 result, ~f64 internal accuracy: every elementwise
    product is split exactly (``two_prod``) and the (value, error)
    stream is tree-summed in float-float. ``axis=None`` contracts all
    axes (the scalar CG dots); ``axis=-1`` contracts the last axis
    only, vectorised over leading axes (the multi-RHS planned solver's
    per-band dots). Cost is ~10 flops/element of cheap elementwise
    math on data already resident for the plain dot — the recurrences
    it feeds are latency-, not throughput-, critical."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if axis is None:
        x = x.reshape(-1)
        y = y.reshape(-1)
    elif axis not in (-1, x.ndim - 1):
        raise ValueError(f"precise_dot supports axis=None|-1, got {axis}")
    p, e = two_prod(x, y)
    hi, lo = _ff_tree_sum(p, e)
    return hi + lo


def precise_norm(x, axis=None):
    """Compensated squared norm: ``precise_dot(x, x)``."""
    return precise_dot(x, x, axis=axis)
