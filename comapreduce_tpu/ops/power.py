"""Noise power-spectrum estimation and model fits.

Parity targets: ``Analysis/PowerSpectra.py`` (log-binned PSD :20-48, noise
models :50-72, log-chi^2 minimisation :137-159) and the per-scan PSD fit in
``Level1Averaging.fit_power_spectrum`` (:552-589). TPU-native: the binning
is a ``segment_sum`` over precomputed log-bin ids; the 3-parameter fits use
the jittable damped-Newton solver :func:`minimize_lm`, vmappable over
(feed, band, scan) so a whole observation's noise fits are one jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["psd", "log_bin_psd", "fit_noise_model", "knee_model",
           "red_noise_model"]


def psd(tod: jax.Array, sample_rate: float = 50.0):
    """One-sided power spectrum |rfft|^2 and its frequencies.

    Returns ``(freqs[n//2+1], ps[..., n//2+1])``; the DC bin is kept but
    callers exclude it via the bin mask.
    """
    n = tod.shape[-1]
    ps = jnp.abs(jnp.fft.rfft(tod, axis=-1)) ** 2 / n
    freqs = jnp.fft.rfftfreq(n, d=1.0 / sample_rate)
    return freqs, ps


@functools.partial(jax.jit, static_argnames=("nbins",))
def log_bin_psd(freqs: jax.Array, ps: jax.Array, nbins: int = 15):
    """Average the PSD in log-spaced frequency bins.

    Parity: ``bin_power_spectrum`` (``Level1Averaging.py:534-550``). Empty
    bins return 0 with ``counts`` 0 (the reference returns NaN and drops
    them; masks compose better on device). Batched over leading axes of
    ``ps``.
    """
    fmin = freqs[1]
    fmax = freqs[-1]
    edges = jnp.logspace(jnp.log10(fmin), jnp.log10(fmax), nbins + 1)
    # clip into [0, nbins-1]: the top-edge sample (and any float-rounding
    # overflow) belongs in the last bin, not a discarded overflow bucket
    ids = jnp.clip(jnp.searchsorted(edges, freqs, side="right") - 1,
                   0, nbins - 1)
    # drop DC (freq < fmin lands in bin 0 too; exclude exact DC sample)
    valid = (freqs >= fmin).astype(ps.dtype)

    # counts and frequency sums are batch-independent: compute once
    cnt = jax.ops.segment_sum(valid, ids, num_segments=nbins)
    fsum = jax.ops.segment_sum(freqs * valid, ids, num_segments=nbins)

    def bin_one(row):
        return jax.ops.segment_sum(row * valid, ids, num_segments=nbins)

    flat = ps.reshape((-1, ps.shape[-1]))
    tops = jax.vmap(bin_one)(flat)
    safe = jnp.maximum(cnt, 1.0)
    p_bin = (tops / safe).reshape(ps.shape[:-1] + (nbins,))
    nu_bin = fsum / safe
    return nu_bin, p_bin, cnt


def knee_model(params, nu):
    """``sigma_w^2 (1 + |nu/fknee|^alpha)`` — PowerSpectra.py:50-60."""
    sig2, fknee, alpha = params
    return sig2 * (1.0 + jnp.abs(nu / fknee) ** alpha)


def red_noise_model(params, nu):
    """``sigma_w^2 + sigma_r^2 |nu|^alpha`` — PowerSpectra.py:62-72."""
    sig2, red2, alpha = params
    return sig2 + red2 * jnp.abs(nu) ** alpha


@functools.partial(jax.jit, static_argnames=("model",))
def fit_noise_model(nu_bin: jax.Array, p_bin: jax.Array, counts: jax.Array,
                    p0: jax.Array, model=knee_model):
    """Fit a 3-parameter noise model to a binned PSD by log-chi^2 BFGS.

    Positivity is enforced by optimising log(sig2), log(fknee/red2) with the
    spectral index free — the reference uses L-BFGS-B bounds instead
    (``PowerSpectra.py:137-159``). Returns the fitted params in natural
    units. vmap over leading axes for batch fits.
    """
    good = (counts > 0) & (p_bin > 0) & (nu_bin > 0)
    logp = jnp.where(good, jnp.log(jnp.maximum(p_bin, 1e-30)), 0.0)

    def loss(q):
        params = (jnp.exp(q[0]), jnp.exp(q[1]), q[2])
        m = model(params, jnp.maximum(nu_bin, 1e-6))
        r = (logp - jnp.log(jnp.maximum(m, 1e-30))) * good
        return jnp.sum(r * r)

    q0 = jnp.array([jnp.log(jnp.maximum(p0[0], 1e-20)),
                    jnp.log(jnp.maximum(p0[1], 1e-20)), p0[2]])
    q = minimize_lm(loss, q0, n_iter=60)
    return jnp.array([jnp.exp(q[0]), jnp.exp(q[1]), q[2]])


def minimize_lm(loss, q0: jax.Array, n_iter: int = 60,
                lam0: float = 1e-2) -> jax.Array:
    """Damped-Newton (Levenberg-Marquardt style) minimiser for small
    parameter vectors, fully jittable/vmappable.

    jax removed ``jax.scipy.optimize`` in 0.9; for 3-parameter noise-model
    fits an explicit Hessian Newton step with multiplicative damping is
    simpler and faster than BFGS anyway (the Hessian is 3x3).
    """
    grad_fn = jax.grad(loss)
    hess_fn = jax.hessian(loss)
    n = q0.shape[0]
    eye = jnp.eye(n, dtype=q0.dtype)

    def step(_, state):
        q, lam, f = state
        g = grad_fn(q)
        H = hess_fn(q)
        H = jnp.where(jnp.all(jnp.isfinite(H)), H, eye)
        delta = jnp.linalg.solve(H + lam * eye, g)
        q_new = q - delta
        f_new = loss(q_new)
        better = jnp.isfinite(f_new) & (f_new < f)
        q = jnp.where(better, q_new, q)
        f = jnp.where(better, f_new, f)
        lam = jnp.where(better, lam * 0.3, lam * 10.0)
        lam = jnp.clip(lam, 1e-9, 1e9)
        return q, lam, f

    q, _, _ = jax.lax.fori_loop(
        0, n_iter, step, (q0, jnp.asarray(lam0, q0.dtype), loss(q0)))
    return q
