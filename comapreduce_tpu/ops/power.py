"""Noise power-spectrum estimation and model fits.

Parity targets: ``Analysis/PowerSpectra.py`` (log-binned PSD :20-48, noise
models :50-72, log-chi^2 minimisation :137-159) and the per-scan PSD fit in
``Level1Averaging.fit_power_spectrum`` (:552-589). TPU-native: the binning
is a ``segment_sum`` over precomputed log-bin ids; the 3-parameter fits use
the jittable damped-Newton solver :func:`minimize_lm`, vmappable over
(feed, band, scan) so a whole observation's noise fits are one jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["psd", "log_bin_psd", "psd_peak_mask", "fit_noise_model",
           "fit_observation_noise", "knee_model", "red_noise_model"]


def psd(tod: jax.Array, sample_rate: float = 50.0):
    """One-sided power spectrum |rfft|^2 and its frequencies.

    Returns ``(freqs[n//2+1], ps[..., n//2+1])``; the DC bin is kept but
    callers exclude it via the bin mask.
    """
    n = tod.shape[-1]
    ps = jnp.abs(jnp.fft.rfft(tod, axis=-1)) ** 2 / n
    freqs = jnp.fft.rfftfreq(n, d=1.0 / sample_rate)
    return freqs, ps


@functools.partial(jax.jit, static_argnames=("nbins",))
def log_bin_psd(freqs: jax.Array, ps: jax.Array, nbins: int = 15,
                sample_mask: jax.Array | None = None):
    """Average the PSD in log-spaced frequency bins.

    Parity: ``bin_power_spectrum`` (``Level1Averaging.py:534-550``). Empty
    bins return 0 with ``counts`` 0 (the reference returns NaN and drops
    them; masks compose better on device). Batched over leading axes of
    ``ps``. ``sample_mask`` (same shape as ``ps``, 1 = keep) excludes
    per-row frequency samples — the spike-masking path
    (``Level2Data.py:288-298``); ``counts`` then gains the batch axes.
    """
    fmin = freqs[1]
    fmax = freqs[-1]
    edges = jnp.logspace(jnp.log10(fmin), jnp.log10(fmax), nbins + 1)
    # clip into [0, nbins-1]: the top-edge sample (and any float-rounding
    # overflow) belongs in the last bin, not a discarded overflow bucket
    ids = jnp.clip(jnp.searchsorted(edges, freqs, side="right") - 1,
                   0, nbins - 1)
    # drop DC (freq < fmin lands in bin 0 too; exclude exact DC sample)
    valid = (freqs >= fmin).astype(ps.dtype)

    def bin_one(row):
        return jax.ops.segment_sum(row, ids, num_segments=nbins)

    fsum = bin_one(freqs * valid)
    valid_cnt = bin_one(valid)
    flat = ps.reshape((-1, ps.shape[-1]))
    if sample_mask is None:
        cnt = valid_cnt
        tops = jax.vmap(bin_one)(flat * valid)
        p_bin = tops / jnp.maximum(cnt, 1.0)
    else:
        m_flat = sample_mask.astype(ps.dtype).reshape(flat.shape) * valid
        cnt_rows = jax.vmap(bin_one)(m_flat)
        tops = jax.vmap(bin_one)(flat * m_flat)
        p_bin = tops / jnp.maximum(cnt_rows, 1.0)
        cnt = cnt_rows.reshape(ps.shape[:-1] + (nbins,))
    p_bin = p_bin.reshape(ps.shape[:-1] + (nbins,))
    # bin-centre frequencies from the unmasked grid (masking a few spike
    # samples must not shift the fit's frequency axis)
    nu_bin = fsum / jnp.maximum(valid_cnt, 1.0)
    return nu_bin, p_bin, cnt


@functools.partial(jax.jit,
                   static_argnames=("threshold", "min_freq", "halfwidth"))
def psd_peak_mask(freqs: jax.Array, ps: jax.Array, auto_rms2: jax.Array,
                  threshold: float = 100.0, min_freq: float = 0.5,
                  halfwidth: int = 4):
    """Mask (1 = keep) of PSD samples free of resonance spikes.

    Parity: the iterative ``find_peaks``/``peak_widths`` masking ahead of
    the Level-2 noise fits (``Level2Data.py:288-298``): peaks above
    ``threshold * auto_rms^2`` at ``freqs > min_freq`` are zapped. The
    reference widens each peak to 85% of its height with
    ``peak_widths``; here the candidate set is dilated by a fixed
    ``halfwidth`` bins (max-pool), the jittable formulation — resonance
    spikes in COMAP data are a few bins wide.

    ``ps``: f32[..., n]; ``auto_rms2``: f32[...] white-noise variance per
    row (the reference's ``auto_rms**2``).
    """
    cand = ((ps > threshold * auto_rms2[..., None])
            & (freqs > min_freq)).astype(ps.dtype)
    if halfwidth > 0:
        w = 2 * halfwidth + 1
        window = (1,) * (ps.ndim - 1) + (w,)
        cand = jax.lax.reduce_window(
            cand, -jnp.inf, jax.lax.max, window, (1,) * ps.ndim, "SAME")
    return 1.0 - jnp.clip(cand, 0.0, 1.0)


def knee_model(params, nu):
    """``sigma_w^2 (1 + |nu/fknee|^alpha)`` — PowerSpectra.py:50-60."""
    sig2, fknee, alpha = params
    return sig2 * (1.0 + jnp.abs(nu / fknee) ** alpha)


def red_noise_model(params, nu):
    """``sigma_w^2 + sigma_r^2 |nu|^alpha`` — PowerSpectra.py:62-72."""
    sig2, red2, alpha = params
    return sig2 + red2 * jnp.abs(nu) ** alpha


def _fit_noise_model_with_loss(nu_bin, p_bin, counts, p0, model):
    """One LM fit; returns ``(params_natural_units, final log-chi^2)``.
    The loss value is what multi-start selection compares."""
    good = (counts > 0) & (p_bin > 0) & (nu_bin > 0)
    logp = jnp.where(good, jnp.log(jnp.maximum(p_bin, 1e-30)), 0.0)
    # a bin averaging k exponentially-distributed PSD samples has
    # var(log) ~ 1/k: weight by sqrt(k) so single-sample low-frequency
    # bins cannot destabilise the fit (the reference fits unweighted,
    # PowerSpectra.py:137-159, and inherits that instability)
    wgt = jnp.sqrt(jnp.maximum(counts, 0.0)) * good

    def loss(q):
        params = (jnp.exp(q[0]), jnp.exp(q[1]), q[2])
        m = model(params, jnp.maximum(nu_bin, 1e-6))
        r = (logp - jnp.log(jnp.maximum(m, 1e-30))) * wgt
        return jnp.sum(r * r)

    q0 = jnp.array([jnp.log(jnp.maximum(p0[0], 1e-20)),
                    jnp.log(jnp.maximum(p0[1], 1e-20)), p0[2]])
    q = minimize_lm(loss, q0, n_iter=60)
    return jnp.array([jnp.exp(q[0]), jnp.exp(q[1]), q[2]]), loss(q)


@functools.partial(jax.jit, static_argnames=("model",))
def fit_noise_model(nu_bin: jax.Array, p_bin: jax.Array, counts: jax.Array,
                    p0: jax.Array, model=knee_model):
    """Fit a 3-parameter noise model to a binned PSD by log-chi^2 BFGS.

    Positivity is enforced by optimising log(sig2), log(fknee/red2) with the
    spectral index free — the reference uses L-BFGS-B bounds instead
    (``PowerSpectra.py:137-159``). Returns the fitted params in natural
    units. vmap over leading axes for batch fits.
    """
    return _fit_noise_model_with_loss(nu_bin, p_bin, counts, p0, model)[0]


def minimize_lm(loss, q0: jax.Array, n_iter: int = 60,
                lam0: float = 1e-2) -> jax.Array:
    """Damped-Newton (Levenberg-Marquardt style) minimiser for small
    parameter vectors, fully jittable/vmappable.

    jax removed ``jax.scipy.optimize`` in 0.9; for 3-parameter noise-model
    fits an explicit Hessian Newton step with multiplicative damping is
    simpler and faster than BFGS anyway (the Hessian is 3x3).
    """
    grad_fn = jax.grad(loss)
    hess_fn = jax.hessian(loss)
    n = q0.shape[0]
    eye = jnp.eye(n, dtype=q0.dtype)

    def step(_, state):
        q, lam, f = state
        g = grad_fn(q)
        H = hess_fn(q)
        H = jnp.where(jnp.all(jnp.isfinite(H)), H, eye)
        delta = jnp.linalg.solve(H + lam * eye, g)
        q_new = q - delta
        f_new = loss(q_new)
        better = jnp.isfinite(f_new) & (f_new < f)
        q = jnp.where(better, q_new, q)
        f = jnp.where(better, f_new, f)
        lam = jnp.where(better, lam * 0.3, lam * 10.0)
        lam = jnp.clip(lam, 1e-9, 1e9)
        return q, lam, f

    q, _, _ = jax.lax.fori_loop(
        0, n_iter, step, (q0, jnp.asarray(lam0, q0.dtype), loss(q0)))
    return q


@functools.partial(jax.jit,
                   static_argnames=("sample_rate", "nbins", "model_name",
                                    "mask_peaks"))
def fit_observation_noise(blocks: jax.Array, sample_rate: float = 50.0,
                          nbins: int = 30, model_name: str = "red_noise",
                          mask_peaks: bool = True):
    """Whole-observation noise fits: PSD -> peak mask -> log bin -> p0 ->
    LM, one jit.

    ``blocks``: f32[..., Lmin] per-(feed, band, scan) TOD blocks. With
    ``mask_peaks`` (default, reference behavior ``Level2Data.py:288-298``)
    resonance spikes above 100x the white level are excluded from the
    binned PSD before fitting, so they cannot bias the fnoise parameters.
    The initial guess mirrors the host heuristic the pipeline stage used
    to assemble in numpy (white level from the top half of the binned PSD;
    the second parameter from the lowest usable bin's excess power) but
    runs on device so the stage stays host-loop-free. Returns f32[..., 3].
    """
    model = red_noise_model if model_name == "red_noise" else knee_model
    freqs, ps = psd(blocks, sample_rate)
    if mask_peaks:
        d = blocks[..., 1:] - blocks[..., :-1]
        auto_rms2 = jnp.var(d, axis=-1) / 2.0
        smask = psd_peak_mask(freqs, ps, auto_rms2)
        nu, pb, cnt = log_bin_psd(freqs, ps, nbins=nbins,
                                  sample_mask=smask)
        cnt = cnt.reshape(-1, nbins)
    else:
        nu, pb, cnt = log_bin_psd(freqs, ps, nbins=nbins)
    pb_flat = pb.reshape(-1, nbins)
    good_hi = (nu > 0.5 * nu.max()).astype(pb.dtype)
    n_hi = jnp.maximum(good_hi.sum(), 1.0)
    sig2 = jnp.maximum((pb_flat * good_hi).sum(-1) / n_hi, 1e-20)
    p_low = jnp.maximum(pb_flat[:, 1], sig2 * 1.01)
    nu_low = jnp.maximum(nu[1], 1e-3)
    alpha0 = -1.5
    if model_name == "red_noise":
        # second parameter: red-noise power amplitude sigma_r^2
        p1 = jnp.maximum((p_low - sig2) * nu_low ** (-alpha0), sig2 * 1e-3)
    else:
        # knee model: fknee where the 1/f power equals the white level
        excess = jnp.maximum(p_low / sig2 - 1.0, 1e-3)
        p1 = jnp.clip(nu_low * excess ** (-1.0 / alpha0),
                      nu_low, 0.5 * sample_rate)
    p0 = jnp.stack([sig2, p1, jnp.full_like(sig2, alpha0)], axis=-1)
    if model_name == "red_noise":
        # the red-noise log-chi^2 surface is bistable (documented in
        # OPERATIONS.md §16): a too-small sigma_r^2 start can settle in
        # a white-only local minimum with alpha pinned near its start,
        # making alpha recovery seed-lucky. Multi-start: also fit the
        # KNEE model (whose fknee parametrisation does not share the
        # degeneracy), convert its optimum algebraically —
        # ``sig2 (1 + |nu/fknee|^a) = sig2 + (sig2 fknee^-a) |nu|^a``,
        # i.e. red2 = sig2 * fknee^(-alpha) — into a second red-noise
        # start, and keep whichever optimum fits better.
        excess_k = jnp.maximum(p_low / sig2 - 1.0, 1e-3)
        p1_k = jnp.clip(nu_low * excess_k ** (-1.0 / alpha0),
                        nu_low, 0.5 * sample_rate)
        p0_k = jnp.stack([sig2, p1_k, jnp.full_like(sig2, alpha0)],
                         axis=-1)

        def fit_row(pbr, cntr, p0r, p0kr):
            pk, _ = _fit_noise_model_with_loss(nu, pbr, cntr, p0kr,
                                               knee_model)
            red2_k = pk[0] * jnp.maximum(pk[1], 1e-6) ** (-pk[2])
            start_k = jnp.stack([pk[0], jnp.maximum(red2_k, 1e-20),
                                 pk[2]])
            pa, la = _fit_noise_model_with_loss(nu, pbr, cntr, p0r,
                                                red_noise_model)
            pb2, lb = _fit_noise_model_with_loss(nu, pbr, cntr, start_k,
                                                 red_noise_model)
            return jnp.where(jnp.isfinite(lb) & (lb < la), pb2, pa)

        if mask_peaks:
            fit = jax.vmap(fit_row)(pb_flat, cnt, p0, p0_k)
        else:
            fit = jax.vmap(lambda pbr, p0r, p0kr: fit_row(
                pbr, cnt, p0r, p0kr))(pb_flat, p0, p0_k)
    elif mask_peaks:
        fit = jax.vmap(lambda pbr, cntr, p0r: fit_noise_model(
            nu, pbr, cntr, p0r, model=model))(pb_flat, cnt, p0)
    else:
        fit = jax.vmap(lambda pbr, p0r: fit_noise_model(
            nu, pbr, cnt, p0r, model=model))(pb_flat, p0)
    return fit.reshape(blocks.shape[:-1] + (3,))
