"""Vane (ambient hot-load) system-temperature calibration.

TPU-native re-design of the reference ``Analysis/VaneCalibration.py:21-198``
(``MeasureSystemTemperature``). The reference finds hot/cold samples with a
data-dependent index search per (feed, band) (``find_hot_cold_from_tod``
:86-141); here the same selection becomes fixed-shape boolean masks so a
whole vane event is one jitted kernel over ``(F, B, C, t)``:

  hot  = (x - mid) > 15*rms   and |grad x| < 2e-3        (x range-normalised)
  cold = (x - mid) < 15*rms   and |grad x| < 2e-3  and  t > last hot sample

then per channel ``gain = (<hot> - <cold>) / (T_vane - T_cmb)``,
``tsys = <cold> / gain`` (``VaneCalibration.py:67-82``).

Vane event windows are found on host (they gate host-side lazy HDF5 reads);
the per-event kernel is jit + vmap over feeds/bands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.ops.stats import auto_rms, masked_mean

__all__ = ["find_vane_events", "hot_cold_masks", "tsys_gain_from_event",
           "measure_system_temperature"]

VANE_COLD_TEMP = 2.73  # K, reference VaneCalibration.py:33
GRADIENT_LIMIT = 2e-3  # reference VaneCalibration.py:116
SIGMA_FACTOR = 15.0    # reference VaneCalibration.py:116


def find_vane_events(vane_flag: np.ndarray) -> np.ndarray:
    """Half-open [start, end) windows of contiguous vane-in-beam samples.

    Host-side (drives lazy TOD slicing). Equivalent of
    ``find_vane_samples`` (``VaneCalibration.py:56-65``) but robust to events
    touching the array ends.
    """
    flag = np.asarray(vane_flag).astype(np.int8)
    d = np.diff(np.concatenate(([0], flag, [0])))
    starts = np.where(d == 1)[0]
    ends = np.where(d == -1)[0]
    return np.stack([starts, ends], axis=1).astype(np.int64)


def _gradient(x: jax.Array) -> jax.Array:
    """Central differences matching ``np.gradient`` along the last axis."""
    left = x[..., 1:2] - x[..., 0:1]
    right = x[..., -1:] - x[..., -2:-1]
    mid = (x[..., 2:] - x[..., :-2]) / 2.0
    return jnp.concatenate([left, mid, right], axis=-1)


def hot_cold_masks(band_avg: jax.Array):
    """Hot/cold sample masks from the band-average TOD of one vane event.

    ``band_avg``: f32[..., t] — batch axes vmap over (feed, band).
    Returns ``(hot, cold)`` f32 masks of the same shape.

    Mirrors ``find_hot_cold_from_tod`` (``VaneCalibration.py:86-141``): the
    TOD is normalised by its range; samples well above the midpoint with a
    flat gradient are hot (vane fully in); samples below the hot threshold
    with flat gradient *after the last hot sample* are cold (vane fully out,
    looking at sky).
    """
    rms = auto_rms(band_avg)[..., None]
    rng = (jnp.max(band_avg, axis=-1) - jnp.min(band_avg, axis=-1))[..., None]
    rng = jnp.maximum(rng, 1e-30)
    x = band_avg / rng
    rms_n = rms / rng
    mid = ((jnp.max(x, axis=-1) + jnp.min(x, axis=-1)) / 2.0)[..., None]
    flat = jnp.abs(_gradient(x)) < GRADIENT_LIMIT
    hot = ((x - mid) > SIGMA_FACTOR * rms_n) & flat
    cold = ((x - mid) < SIGMA_FACTOR * rms_n) & flat

    t = jnp.arange(x.shape[-1])
    # last hot sample index; -1 when no hot samples at all
    last_hot = jnp.max(jnp.where(hot, t, -1), axis=-1, keepdims=True)
    cold = cold & (t > last_hot)

    has_both = (jnp.any(hot, axis=-1) & jnp.any(cold, axis=-1))[..., None]
    hot = hot & has_both
    cold = cold & has_both
    return hot.astype(band_avg.dtype), cold.astype(band_avg.dtype)


def tsys_gain_from_event(tod: jax.Array, hot: jax.Array, cold: jax.Array,
                         vane_temperature: float):
    """Per-channel Tsys and gain for one vane event.

    ``tod``: f32[..., C, t]; ``hot``/``cold``: f32[..., t] masks broadcast
    over channels. Returns ``(tsys, gain)`` f32[..., C]. Channels of events
    with no valid hot/cold samples return 0 (flagged downstream by zero
    weights). Parity: ``system_temperature_from_tod``
    (``VaneCalibration.py:67-82``).
    """
    hot_b = hot[..., None, :]
    cold_b = cold[..., None, :]
    p_hot = masked_mean(tod, jnp.broadcast_to(hot_b, tod.shape), axis=-1)
    p_cold = masked_mean(tod, jnp.broadcast_to(cold_b, tod.shape), axis=-1)
    gain = (p_hot - p_cold) / (vane_temperature - VANE_COLD_TEMP)
    ok = (jnp.sum(hot, axis=-1) > 0) & (jnp.sum(cold, axis=-1) > 0)
    ok = ok[..., None] & (gain > 0)
    gain = jnp.where(ok, gain, 0.0)
    tsys = jnp.where(ok, p_cold / jnp.where(ok, gain, 1.0), 0.0)
    return tsys, gain


@jax.jit
def _event_kernel(tod_event: jax.Array, vane_temperature: jax.Array):
    """(F, B, C, t) event window -> per-channel (tsys, gain), each (F, B, C)."""
    band_avg = jnp.mean(tod_event, axis=2)  # (F, B, t)
    hot, cold = hot_cold_masks(band_avg)
    return tsys_gain_from_event(tod_event, hot, cold, vane_temperature)


def measure_system_temperature(tod_reader, vane_flag: np.ndarray,
                               vane_temperature: float,
                               pad: int = 50):
    """All vane events of one observation -> ``(tsys, gain)`` of shape
    ``(n_events, F, B, C)``.

    ``tod_reader(start, end)`` returns the raw TOD slice ``(F, B, C, end-start)``
    (lazy HDF5 read or in-memory slice). ``pad`` widens each event window so
    the cold (sky) samples after vane retraction are included — the reference
    relies on the feature flag staying set past the mechanical motion.
    """
    events = find_vane_events(vane_flag)
    n = len(vane_flag)
    out_t, out_g = [], []
    for start, end in events:
        s, e = max(0, int(start) - pad), min(n, int(end) + pad)
        tod_event = jnp.asarray(np.asarray(tod_reader(s, e), dtype=np.float32))
        tsys, gain = _event_kernel(tod_event, jnp.float32(vane_temperature))
        out_t.append(tsys)
        out_g.append(gain)
    if not out_t:
        return None, None
    return jnp.stack(out_t), jnp.stack(out_g)
