"""Atmosphere removal: per-channel regression against airmass.

The reference fits ``tod(c, t) ~ offset(c) + atmos(c) * A(t)`` per (scan,
feed, band, channel) by assembling a sparse block-diagonal system and calling
``scipy.sparse.linalg.spsolve`` (``Analysis/Level1Averaging.py:197-227``).
That system is exactly C independent 2x2 normal-equation solves, so the
TPU-native form is: accumulate the five moments (1, A, A^2, d, A*d) per scan
with one ``segment_sum`` over the time axis and solve the 2x2 closed form —
no sparse algebra, no Python scan loop, vmappable over (F, B, C).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["fit_airmass_block", "fit_atmosphere_segments",
           "subtract_atmosphere"]


def fit_airmass_block(tod: jax.Array, airmass: jax.Array, mask: jax.Array):
    """Masked per-channel linear fit ``tod ~ offset + slope * airmass`` over
    one contiguous block (the per-scan form used inside the reduction).

    ``tod``/``mask``: f32[..., C, L]; ``airmass``: f32[L]. Returns
    ``(offset, slope)`` each f32[..., C]. Centered moments — the raw normal
    equations cancel catastrophically in f32 at raw-count scales.
    Degenerate blocks (under 2 valid samples or no airmass variance) return
    slope 0 and offset = masked mean.
    """
    cnt = jnp.sum(mask, -1)
    s1 = jnp.maximum(cnt, 1.0)
    a_mean = jnp.sum(mask * airmass, -1) / s1
    d_mean = jnp.sum(mask * tod, -1) / s1
    da = airmass - a_mean[..., None]
    dd = tod - d_mean[..., None]
    saa = jnp.sum(mask * da * da, -1)
    sad = jnp.sum(mask * da * dd, -1)
    ok = (cnt >= 2.0) & (saa > 1e-12)
    slope = jnp.where(ok, sad / jnp.maximum(saa, 1e-12), 0.0)
    offset = d_mean - slope * a_mean
    return offset, slope


def fit_atmosphere_segments(tod: jax.Array, airmass: jax.Array,
                            scan_ids: jax.Array, mask: jax.Array,
                            n_scans: int):
    """Per-scan, per-channel linear fit of TOD against airmass.

    Parameters
    ----------
    tod:      f32[..., C, T]
    airmass:  f32[T] (per-feed airmass is passed per vmapped feed)
    scan_ids: i32[T], -1 outside scans
    mask:     f32[..., C, T] validity
    n_scans:  static number of scans

    Returns ``(offset, atmos)`` each f32[..., C, n_scans]: the per-scan
    regression coefficients. Degenerate scans (fewer than 2 valid samples or
    zero airmass variance) return offset = weighted mean, atmos = 0 — same
    effect as the reference's NaN fits + downstream masking, but mask-clean.
    Parity: ``AtmosphereRemoval.fit_atmosphere``
    (``Level1Averaging.py:197-227``).
    """
    seg = jnp.where(scan_ids < 0, n_scans, scan_ids)  # junk bucket at n_scans

    def moments(x):
        # x: f32[..., T] -> f32[..., n_scans]
        return jax.vmap(
            lambda row: jax.ops.segment_sum(row, seg, num_segments=n_scans + 1)
        )(x.reshape((-1, x.shape[-1]))).reshape(x.shape[:-1] + (n_scans + 1,))[
            ..., :n_scans
        ]

    m = mask
    a = airmass  # broadcast over leading axes below
    cnt = moments(m)
    s1 = jnp.maximum(cnt, 1.0)
    a_mean = moments(m * a) / s1
    d_mean = moments(m * tod) / s1

    # second pass with per-scan centered values (f32-stable: the raw normal
    # equations cancel catastrophically at count scales)
    n_sc = a_mean.shape[-1]
    seg_c = jnp.clip(scan_ids, 0, n_sc - 1)
    am_t = jnp.take_along_axis(
        a_mean, jnp.broadcast_to(seg_c, a_mean.shape[:-1] + seg_c.shape), -1)
    dm_t = jnp.take_along_axis(
        d_mean, jnp.broadcast_to(seg_c, d_mean.shape[:-1] + seg_c.shape), -1)
    da = a - am_t
    dd = tod - dm_t
    saa = moments(m * da * da)
    sad = moments(m * da * dd)
    ok = (cnt >= 2.0) & (saa > 1e-12)
    atmos = jnp.where(ok, sad / jnp.maximum(saa, 1e-12), 0.0)
    offset = d_mean - atmos * a_mean
    return offset, atmos


def subtract_atmosphere(tod: jax.Array, airmass: jax.Array,
                        scan_ids: jax.Array, offset: jax.Array,
                        atmos: jax.Array):
    """Subtract the fitted per-scan atmosphere model from the TOD.

    ``offset``/``atmos``: f32[..., C, n_scans] from
    :func:`fit_atmosphere_segments`. Samples outside any scan are left
    unchanged (their mask is 0 anyway). Parity:
    ``AtmosphereRemoval.subtract_fitted_atmosphere``
    (``Level1Averaging.py:188-195``).
    """
    n_scans = offset.shape[-1]
    seg = jnp.clip(scan_ids, 0, n_scans - 1)
    off_t = jnp.take_along_axis(
        offset, jnp.broadcast_to(seg, offset.shape[:-1] + seg.shape[-1:]),
        axis=-1)
    atm_t = jnp.take_along_axis(
        atmos, jnp.broadcast_to(seg, atmos.shape[:-1] + seg.shape[-1:]),
        axis=-1)
    model = off_t + atm_t * airmass
    return jnp.where(scan_ids >= 0, tod - model, tod)
