"""Basic TOD statistics as JAX kernels.

Capability parity with the reference's ``Tools/stats.py`` (auto_rms :59-72,
MAD :50-57, TsysRMS :74-80, weighted mean/var :82-97, norm :99-106), but with
one deliberate design change for TPU: **validity masks instead of NaNs**.
The reference marks bad samples with NaN and uses ``np.nan*`` reductions;
XLA handles NaN fine but masked arithmetic fuses better, keeps bf16 an option
and makes downstream ``segment_sum`` weights exact. Every op therefore takes
an optional ``mask`` (1.0 = good, 0.0 = bad); NaN inputs can be converted once
at ingest with :func:`nan_to_mask`.

All functions operate on the trailing (time) axis and broadcast over any
leading batch axes, so they vmap/shard cleanly over (feed, band, channel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "SELECT_MEDIAN_MIN_WINDOW",
    "nan_to_mask",
    "masked_mean",
    "masked_std",
    "masked_median",
    "median_lastaxis",
    "mad",
    "auto_rms",
    "tsys_rms",
    "weighted_mean",
    "weighted_var",
    "normalise",
]

_EPS = 1e-30

# Rows at least this wide take the radix-bisection median (32 counting
# passes, fixed per-pass overhead) over the bitonic sort (~log^2 n full
# passes). Measured crossover on the v5e: sort wins whole-program at
# 500-wide rows, radix wins ~20x at ~3400 — single shared knob for every
# median dispatch site.
SELECT_MEDIAN_MIN_WINDOW = 1024


def nan_to_mask(x: jax.Array, mask: jax.Array | None = None):
    """Convert NaN samples to (0, mask=0); returns ``(x_clean, mask)``."""
    good = jnp.isfinite(x)
    if mask is not None:
        good = good & (mask > 0)
    good_f = good.astype(x.dtype)
    return jnp.where(good, x, 0.0), good_f


def masked_mean(x: jax.Array, mask: jax.Array | None = None, axis=-1):
    """Mean over ``axis`` counting only samples with ``mask > 0``."""
    if mask is None:
        return jnp.mean(x, axis=axis)
    m = mask.astype(x.dtype)
    return jnp.sum(x * m, axis=axis) / jnp.maximum(jnp.sum(m, axis=axis), 1.0)


def masked_std(x: jax.Array, mask: jax.Array | None = None, axis=-1):
    """Standard deviation over ``axis`` counting only masked-in samples."""
    mu = masked_mean(x, mask, axis=axis)
    d = x - (mu if axis is None else jnp.expand_dims(mu, axis))
    var = masked_mean(d * d, mask, axis=axis)
    return jnp.sqrt(jnp.maximum(var, 0.0))


def _f32_sortable_u32(x: jax.Array) -> jax.Array:
    """Monotone f32 -> u32 key: total order matches float comparison."""
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    neg = (u >> 31) == 1
    return jnp.where(neg, ~u, u | jnp.uint32(0x80000000))


def _u32_sortable_f32(u: jax.Array) -> jax.Array:
    """Inverse of :func:`_f32_sortable_u32`."""
    was_neg = (u >> 31) == 0
    v = jnp.where(was_neg, ~u, u & jnp.uint32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(v, jnp.float32)


def _kth_smallest_u32(u: jax.Array, k: jax.Array) -> jax.Array:
    """Exact k-th smallest (0-based) per row of u32 keys, by 32-step value
    bisection: each step counts ``u <= mid`` — a fused compare+reduce pass.

    On TPU this replaces a row sort: XLA lowers a length-n sort to a
    bitonic network of ~log^2(n) full passes (measured ~20x slower than the
    32 counting passes at the production row length of ~3400)."""
    lo = jnp.zeros(u.shape[:-1], jnp.uint32)
    hi = jnp.full(u.shape[:-1], 0xFFFFFFFF, jnp.uint32)

    def body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2
        c = jnp.sum((u <= mid[..., None]).astype(jnp.int32), axis=-1)
        take = c >= (k + 1)
        return (jnp.where(take, lo, mid + 1), jnp.where(take, mid, hi))

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo


def _median_mid(f_lo, f_hi):
    """Midpoint of the lower/upper median with the equal-middles guard:
    equal middles return the ELEMENT — 0.5*(v+v) rounds the minimum
    subnormal to zero (hypothesis-found edge). One home for the formula;
    the Pallas kernel (ops/pallas_median.py) calls it too."""
    return jnp.where(f_lo == f_hi, f_lo, 0.5 * (f_lo + f_hi))


def median_lastaxis(x: jax.Array) -> jax.Array:
    """Exact median over the last axis, no mask — radix bisection.

    Drop-in for ``jnp.median(x, axis=-1)`` on TPU for wide f32 rows, where
    the sort-based median pays ~log^2(n) bitonic passes vs 32 counting
    passes here (plus 2 for the upper median on even lengths). Matches
    ``jnp.median`` semantics: NaN inputs propagate to a NaN result; non-f32
    dtypes fall back to the sort path rather than silently truncating.
    """
    if x.dtype != jnp.float32:
        return jnp.median(x, axis=-1)
    n = x.shape[-1]
    u = _f32_sortable_u32(x)
    k_lo = jnp.full(x.shape[:-1], (n - 1) // 2, jnp.int32)
    v_lo = _kth_smallest_u32(u, k_lo)
    if n % 2 == 1:
        med = _u32_sortable_f32(v_lo)
    else:
        c_le = jnp.sum((u <= v_lo[..., None]).astype(jnp.int32), axis=-1)
        above = jnp.where(u > v_lo[..., None], u, jnp.uint32(0xFFFFFFFF))
        v_next = jnp.min(above, axis=-1)
        v_hi = jnp.where(c_le >= n // 2 + 1, v_lo, v_next)
        med = _median_mid(_u32_sortable_f32(v_lo),
                          _u32_sortable_f32(v_hi))
    return jnp.where(jnp.any(jnp.isnan(x), axis=-1), jnp.nan, med)


def masked_median(x: jax.Array, mask: jax.Array | None = None, axis: int = -1):
    """Median over ``axis`` ignoring masked-out samples.

    Exact (equals the sort-based definition: mean of the lower and upper
    median), but computed by radix bisection on sortable u32 keys — O(32)
    vectorised counting passes instead of a bitonic sort, the TPU-fast
    formulation for the long rows of the NaN-fill path
    (``Level1Averaging.py:658-665``).
    """
    axis = axis % x.ndim
    x = jnp.moveaxis(x, axis, -1)
    if mask is None:
        return (median_lastaxis(x)
                if x.shape[-1] >= SELECT_MEDIAN_MIN_WINDOW
                and x.dtype == jnp.float32 else jnp.median(x, axis=-1))
    m = jnp.broadcast_to(mask.astype(bool), x.shape) if mask.ndim != x.ndim else (
        jnp.moveaxis(mask, axis, -1) > 0
    )
    if x.dtype != jnp.float32 or x.shape[-1] < SELECT_MEDIAN_MIN_WINDOW:
        # non-f32 (no u32 key truncation) and narrow rows (sort wins
        # below the measured crossover): sort-based definition
        big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
        xs = jnp.sort(jnp.where(m, x, big), axis=-1)
        cnt = jnp.sum(m, axis=-1)
        n = x.shape[-1]
        lo = jnp.clip((jnp.maximum(cnt, 1) - 1) // 2, 0, n - 1)
        hi = jnp.clip(jnp.maximum(cnt, 1) // 2, 0, n - 1)
        vlo = jnp.take_along_axis(xs, lo[..., None], axis=-1)[..., 0]
        vhi = jnp.take_along_axis(xs, hi[..., None], axis=-1)[..., 0]
        mid = _median_mid(vlo, vhi)
        return jnp.where(cnt > 0, mid, 0.0)
    u = jnp.where(m, _f32_sortable_u32(x), jnp.uint32(0xFFFFFFFF))
    cnt = jnp.sum(m, axis=-1)
    k_lo = (jnp.maximum(cnt, 1) - 1) // 2
    k_hi = jnp.maximum(cnt, 1) // 2
    v_lo = _kth_smallest_u32(u, k_lo)
    # upper median from two more fused passes: the smallest key above v_lo,
    # used only when the k_hi-th order statistic really exceeds v_lo
    # (duplicates can make them equal even for even counts)
    c_le = jnp.sum((u <= v_lo[..., None]).astype(jnp.int32), axis=-1)
    above = jnp.where(u > v_lo[..., None], u, jnp.uint32(0xFFFFFFFF))
    v_next = jnp.min(above, axis=-1)
    v_hi = jnp.where(c_le >= k_hi + 1, v_lo, v_next)
    med = _median_mid(_u32_sortable_f32(v_lo),
                      _u32_sortable_f32(v_hi))
    return jnp.where(cnt > 0, med, 0.0)


def mad(x: jax.Array, mask: jax.Array | None = None, axis: int = -1):
    """Median absolute deviation scaled to a Gaussian sigma (x1.48).

    Parity: ``Tools/stats.py:50-57`` (which actually computes
    ``1.48*sqrt(median((d-med)^2))`` — same thing for the absolute value).
    """
    med = masked_median(x, mask, axis=axis)
    d = x - jnp.expand_dims(med, axis % x.ndim)
    return 1.48 * jnp.sqrt(masked_median(d * d, mask, axis=axis))


def auto_rms(tod: jax.Array, mask: jax.Array | None = None):
    """White-noise rms from adjacent-pair differences along the last axis.

    Parity: ``Tools/stats.py:59-72`` — pair samples (2i, 2i+1), difference,
    take the std over pairs, divide by sqrt(2). A pair is valid only if both
    of its samples are valid.
    """
    n = (tod.shape[-1] // 2) * 2
    a = tod[..., 0:n:2]
    b = tod[..., 1:n:2]
    diff = b - a
    pair_mask = None
    if mask is not None:
        pair_mask = mask[..., 0:n:2] * mask[..., 1:n:2]
    return masked_std(diff, pair_mask, axis=-1) / jnp.sqrt(2.0).astype(tod.dtype)


def tsys_rms(tod: jax.Array, sample_rate: float, bandwidth: float,
             mask: jax.Array | None = None):
    """System temperature implied by the radiometer equation from the rms.

    Parity: ``Tools/stats.py:74-80``: ``Tsys = rms * sqrt(bandwidth/sample_rate)``.
    """
    return auto_rms(tod, mask) * jnp.sqrt(bandwidth / sample_rate)


def weighted_mean(x: jax.Array, e: jax.Array, axis=None):
    """Inverse-variance weighted mean; ``e`` are 1-sigma errors.

    Parity: ``Tools/stats.py:82-87``.
    """
    w = 1.0 / jnp.maximum(e * e, _EPS)
    return jnp.sum(x * w, axis=axis) / jnp.maximum(jnp.sum(w, axis=axis), _EPS)


def weighted_var(x: jax.Array, e: jax.Array, axis=None):
    """Inverse-variance weighted variance about the weighted mean.

    Parity: ``Tools/stats.py:89-97``.
    """
    w = 1.0 / jnp.maximum(e * e, _EPS)
    m = weighted_mean(x, e, axis=axis)
    if axis is not None:
        m = jnp.expand_dims(m, axis)
    return jnp.sum((x - m) ** 2 * w, axis=axis) / jnp.maximum(
        jnp.sum(w, axis=axis), _EPS
    )


def normalise(tod: jax.Array, mask: jax.Array | None = None):
    """Zero-mean, unit-rms normalisation along the time axis.

    Parity: ``Tools/stats.py:99-106`` (per-band normalisation).
    """
    mu = masked_mean(tod, mask, axis=-1)[..., None]
    sd = masked_std(tod, mask, axis=-1)[..., None]
    out = jnp.where(sd > 0, (tod - mu) / jnp.where(sd > 0, sd, 1.0), 0.0)
    return out if mask is None else out * mask


def downsample(tod: jax.Array, factor: int = 50):
    """Block-average along time: f32[..., T] -> f32[..., T//factor]
    (the reference's 1-second downsample, ``Tools/stats.py:104-117``)."""
    n = tod.shape[-1] // factor * factor
    blocks = tod[..., :n].reshape(tod.shape[:-1] + (n // factor, factor))
    return jnp.mean(blocks, axis=-1)


def correlation_matrix(tod: jax.Array, factor: int = 50):
    """Channel-channel correlation of the downsampled TOD
    (``Tools/stats.py:104-139``): ``tod`` f32[C, T] -> f32[C, C]."""
    d = downsample(tod, factor)
    d = d - jnp.mean(d, axis=-1, keepdims=True)
    sd = jnp.sqrt(jnp.mean(d * d, axis=-1))
    cov = d @ d.T / d.shape[-1]
    denom = jnp.maximum(sd[:, None] * sd[None, :], _EPS)
    return cov / denom
