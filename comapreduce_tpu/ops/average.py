"""Normalisation and frequency averaging kernels.

Parity targets: ``Level1AveragingGainCorrection.normalise_data``
(``Analysis/Level1Averaging.py:667-679``), ``weighted_average_over_band``
(:592-599), and the generic frequency binner ``Level1Averaging.average_tod``
(:292-321). All are masked reductions over the channel axis — pure VPU work
that XLA fuses with neighbours.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from comapreduce_tpu.ops.stats import masked_std

__all__ = ["normalise_by_rms", "weighted_band_average", "frequency_bin",
           "edge_channel_mask"]

_EPS = 1e-30


def normalise_by_rms(tod: jax.Array, mask: jax.Array | None = None,
                     bandwidth: float = 2e9 / 1024.0, tau: float = 1.0 / 50.0):
    """Divide each channel by its stride-4 difference rms x sqrt(dnu*tau).

    The reference differences samples (0,4,8,...) - (2,6,10,...) — pairs two
    samples apart on a stride-4 grid — to estimate the white level immune to
    slow drifts, then scales by sqrt(bandwidth x integration time) so the
    normalised TOD is in units of the radiometer noise
    (``Level1Averaging.py:667-679``). Returns ``(tod_norm, rms)`` with
    ``rms``: f32[..., 1] broadcastable back.
    """
    n4 = tod.shape[-1] // 4 * 4
    a = tod[..., 0:n4:4]
    b = tod[..., 2:n4:4]
    pm = None
    if mask is not None:
        pm = mask[..., 0:n4:4] * mask[..., 2:n4:4]
    diff = a - b
    rms = masked_std(diff, pm, axis=-1) / jnp.sqrt(2.0)
    rms = rms * jnp.sqrt(bandwidth * tau)
    rms = rms[..., None]
    safe = jnp.maximum(rms, _EPS)
    out = jnp.where(rms > 0, tod / safe, 0.0)
    return out, rms


def edge_channel_mask(n_channels: int, edge: int = 10, centre_below: int = 0,
                      centre_above: int = 0, dtype=jnp.float32) -> jax.Array:
    """1 everywhere except ``edge`` channels at each end and
    ``[c-centre_below, c+centre_above)`` around the band centre ``c = C//2``
    — the reference's recurring channel cuts (``Level1Averaging.py:843-845``
    uses edge=10 + centre [510:515]; the gain templates use edge=20 +
    centre 512±5; the band average uses edge=50 + centre {512})."""
    m = jnp.ones((n_channels,), dtype=dtype)
    if edge > 0:
        m = m.at[:edge].set(0.0)
        m = m.at[-edge:].set(0.0)
    if centre_below or centre_above:
        c = n_channels // 2
        m = m.at[max(c - centre_below, 0):min(c + centre_above, n_channels)
                 ].set(0.0)
    return m


def weighted_band_average(tod: jax.Array, weights: jax.Array):
    """Collapse channels: ``sum_c w(c) x(c,t) / sum_c w(c)``.

    ``tod``: f32[..., C, T]; ``weights``: f32[..., C] (zero = excluded).
    Parity: ``weighted_average_over_band`` (``Level1Averaging.py:592-599``)
    minus its in-place weight mutations, which the caller expresses through
    the weight mask instead.
    """
    num = jnp.einsum("...ct,...c->...t", tod, weights)
    den = jnp.sum(weights, axis=-1)[..., None]
    return num / jnp.maximum(den, _EPS)


def frequency_bin(tod: jax.Array, weights: jax.Array, bin_size: int,
                  valid: jax.Array | None = None):
    """Weighted binning of C channels into C//bin_size coarse channels.

    ``tod``: f32[..., C, T]; ``weights``: f32[..., C] per-channel.
    ``valid``: optional bool[..., C, T] per-sample validity — invalid
    (NaN-flagged) samples leave the in-bin mean entirely (zero weight)
    instead of averaging in as zeros. Kept as a SEPARATE bool operand
    (not a pre-multiplied f32[..., C, T] weight tensor): each
    elementwise product below has a single reduce consumer, so XLA
    fuses it into the reduction and the raw-TOD-sized f32 weight array
    never lives in HBM (~2.2 GB/feed at production shape). Returns
    ``(binned, stddev)`` each f32[..., C//bin_size, T]. Parity:
    ``Level1Averaging.average_tod`` (``Level1Averaging.py:292-321``),
    which also records the in-bin standard deviation.
    """
    c = tod.shape[-2]
    nb = c // bin_size
    shape = tod.shape[:-2] + (nb, bin_size, tod.shape[-1])
    w = weights[..., : nb * bin_size].reshape(
        weights.shape[:-1] + (nb, bin_size))[..., None]
    if valid is None:
        x = tod[..., : nb * bin_size, :].reshape(shape)
        den = jnp.maximum(jnp.sum(w, axis=-2), _EPS)
        avg = jnp.sum(x * w, axis=-2) / den
        d = x - avg[..., None, :]
        var = jnp.sum(d * d * w, axis=-2) / den
    else:
        v = valid[..., : nb * bin_size, :].reshape(shape)
        # NaNs at invalid slots must not poison 0*NaN products
        x = jnp.where(v, tod[..., : nb * bin_size, :].reshape(shape), 0.0)
        den = jnp.maximum(jnp.sum(w * v, axis=-2), _EPS)
        avg = jnp.sum(x * w, axis=-2) / den
        # centered second pass: E[x^2] - E[x]^2 cancels catastrophically
        # in f32 when the in-bin scatter is far below the mean
        d = jnp.where(v, x - avg[..., None, :], 0.0)
        var = jnp.sum(d * d * w, axis=-2) / den
    return avg, jnp.sqrt(jnp.maximum(var, 0.0))
