"""The Level-1 -> Level-2 reduction: one jitted program per observation.

TPU-native re-design of ``Level1AveragingGainCorrection.average_tod``
(``Analysis/Level1Averaging.py:792-872``), the reference's hot loop. Where
the reference iterates Python loops over 19 feeds x ~10 scans, slicing numpy
arrays, this module:

  1. extracts all scans into one padded block ``(S, B, C, L)`` per feed
     (static shapes; short scans are masked),
  2. runs the whole chain — NaN fill, atmosphere subtraction, radiometer
     normalisation, median-filter high-pass, closed-form gain solve,
     Tsys-weighted band averaging — as masked array ops ``vmap``-ed over
     scans and feeds,
  3. scatters the per-scan results back onto the time axis.

Every step is elementwise / reduction / matmul math; XLA fuses the chain and
``shard_map`` distributes feeds across a device mesh (the reference's
MPI-over-files analogue, SURVEY.md §2.5).
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.ops import gain as gain_ops
from comapreduce_tpu.ops.atmosphere import fit_airmass_block
from comapreduce_tpu.ops.average import edge_channel_mask
from comapreduce_tpu.ops.median_filter import medfilt_highpass
from comapreduce_tpu.ops.stats import masked_median, masked_std

logger = logging.getLogger("comapreduce_tpu")

__all__ = ["scan_starts_lengths", "extract_scan_blocks",
           "scatter_scan_blocks", "reduce_feed_scans", "ReduceConfig",
           "estimate_reduce_hbm", "plan_reduce_memory", "device_hbm_bytes",
           "plan_stage_feed_batch", "stage_feed_batches", "ShapeBuckets",
           "pad_time_axis", "pad_scan_geometry"]


def scan_starts_lengths(edges: np.ndarray, pad_to: int = 128):
    """Static scan geometry from host edges: (starts, lengths, L_max)."""
    edges = np.asarray(edges, dtype=np.int64)
    starts = edges[:, 0]
    lengths = edges[:, 1] - edges[:, 0]
    L = int(lengths.max()) if len(lengths) else pad_to
    L = -(-L // pad_to) * pad_to
    return starts, lengths, L


def extract_scan_blocks(x: jax.Array, starts: jax.Array, L: int,
                        lengths: jax.Array | None = None):
    """Gather scans into a padded block: f32[..., T] -> f32[S, ..., L].

    With ``lengths`` given, the padded tail of each scan repeats that scan's
    own last sample (edge replication — what the median filter wants);
    otherwise out-of-range indices clamp to T-1.
    """
    T = x.shape[-1]
    idx = starts[:, None] + jnp.arange(L)[None, :]       # (S, L)
    if lengths is not None:
        last = starts + jnp.maximum(lengths, 1) - 1
        idx = jnp.minimum(idx, last[:, None])
    idx = jnp.clip(idx, 0, T - 1)
    out = x[..., idx]                                    # (..., S, L)
    return jnp.moveaxis(out, -2, 0)                      # (S, ..., L)


def extract_one_scan(x: jax.Array, start, L: int, length=None):
    """One scan's padded block: f32[..., T] -> f32[..., L].

    Same edge-replication clamp semantics as :func:`extract_scan_blocks`
    (one source of truth would be ideal, but the shapes differ for a
    reason): the 1-D ``take`` keeps the scan batch dim LEADING in the
    gather output when vmapped (``lax.map`` over scans), so XLA emits
    the (batch, B, C, L) layout directly instead of gathering
    (B, C, batch, L) and paying a full transposed copy per scan batch
    (measured 0.13 s of the production bench before this existed).
    """
    T = x.shape[-1]
    idx = start + jnp.arange(L)
    if length is not None:
        idx = jnp.minimum(idx, start + jnp.maximum(length, 1) - 1)
    idx = jnp.clip(idx, 0, T - 1)
    return jnp.take(x, idx, axis=-1)


def scatter_scan_blocks(blocks: jax.Array, starts: jax.Array,
                        lengths: jax.Array, T: int):
    """Inverse of :func:`extract_scan_blocks`: f32[S, ..., L] -> f32[..., T].

    Padded samples are dropped; samples outside every scan stay 0.
    """
    S, L = blocks.shape[0], blocks.shape[-1]
    idx = starts[:, None] + jnp.arange(L)[None, :]       # (S, L)
    valid = (jnp.arange(L)[None, :] < lengths[:, None])
    idx = jnp.where(valid, idx, T)                       # junk slot at T
    flat_idx = idx.reshape(-1)
    moved = jnp.moveaxis(blocks, 0, -2)                  # (..., S, L)
    flat = moved.reshape(moved.shape[:-2] + (S * L,))
    out = jnp.zeros(moved.shape[:-2] + (T + 1,), blocks.dtype)
    out = out.at[..., flat_idx].set(flat, mode="drop")
    return out[..., :T]


class ReduceConfig:
    """Static knobs of the reduction (mirrors the reference's constants).

    Value-hashable: it is a ``jit`` static argument, and identity hashing
    would recompile the flagship kernel once per file in a filelist run.
    """

    def _key(self):
        return (self.n_channels, self.medfilt_window, self.medfilt_stride,
                self.is_calibrator, self.bandwidth, self.tau,
                self.scan_batch)

    def __eq__(self, other):
        return (type(other) is ReduceConfig and self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __init__(self, n_channels: int, medfilt_window: int = 6000,
                 is_calibrator: bool = False,
                 bandwidth: float | None = None, tau: float = 1.0 / 50.0,
                 medfilt_stride: int | None = None,
                 scan_batch: int | None = None):
        c = n_channels
        # channel cuts scale with C so small test configs behave like 1024
        def s(n):
            return max(int(round(n * c / 1024.0)), 1)
        self.n_channels = c
        self.medfilt_window = medfilt_window
        # None = subsample windows beyond MAX_EXACT_WINDOW (fast path);
        # 1 = exact rolling median at any window (the reference's filter)
        self.medfilt_stride = medfilt_stride
        # None = vmap every scan at once (fastest, peak memory ~ S copies
        # of a (B, C, L) block); k = stream scans through the chain k at a
        # time, bounding peak memory for production-length observations
        # (~45-60 min of 50 Hz data does not fit 16 GB HBM all at once)
        self.scan_batch = scan_batch
        self.is_calibrator = is_calibrator
        self.bandwidth = bandwidth if bandwidth is not None else 2e9 / c
        self.tau = tau
        # reference cuts (Level1Averaging.py:843-845, 592-595;
        # GainSubtraction.py:185-201; median_filter :688-690)
        self.mask_weights = edge_channel_mask(c, s(10), s(2), s(3))
        self.mask_band_avg = edge_channel_mask(c, s(50), 0, s(1))
        self.mask_medfilt = edge_channel_mask(c, s(10), s(5), s(6))
        self.mask_templates = edge_channel_mask(c, s(20), s(5), s(5))


# Simultaneous (B, C, L)-sized working blocks the per-scan chain holds at
# peak (gathered counts, NaN-filled copy, normalised, filtered, gain
# residual, plus fusion slack) — the envelope behind the HBM planner. The
# round-3 bench (scan_batch=2 at production shape, ~4 GB resident) sits
# comfortably inside this estimate; it is deliberately conservative so the
# planner errs toward smaller batches rather than a device OOM.
REDUCE_CHAIN_BLOCKS = 6


def estimate_reduce_hbm(feed_batch: int, B: int, C: int, T: int,
                        n_scans: int, L: int, scan_batch: int | None = None,
                        dense_mask: bool = False) -> int:
    """Estimated peak HBM bytes of one feed-batched reduction program.

    Inputs resident per feed: the raw f32[B, C, T] counts (plus a dense
    mask of the same size when ``dense_mask`` — the ``mask=None`` ingest
    path avoids it). Working set per feed: ``REDUCE_CHAIN_BLOCKS`` scan
    blocks of f32[B, C, L], times the number of scans materialised at once
    (``scan_batch`` when streaming, else all ``n_scans``).
    """
    unit_T = B * C * T * 4
    blk = B * C * L * 4
    k = n_scans if (scan_batch is None or scan_batch >= n_scans) \
        else max(int(scan_batch), 1)
    inputs = unit_T * (2 if dense_mask else 1)
    return int(feed_batch) * (inputs + REDUCE_CHAIN_BLOCKS * k * blk)


# [tuning] device_hbm_mb, installed by TUNING.configure (0 = unset):
# the declared-capacity override for backends whose memory_stats is
# unsupported, so the auto-sizers stop guessing
_HBM_OVERRIDE_BYTES = 0
_HBM_DEFAULT_WARNED = False


def set_device_hbm_override(n_bytes: int) -> None:
    """Install (or clear, with 0) the ``[tuning] device_hbm_mb``
    declared-capacity override consulted by :func:`device_hbm_bytes`.
    Clearing also re-arms the silent-default warning so the next run
    in this process warns again."""
    global _HBM_OVERRIDE_BYTES, _HBM_DEFAULT_WARNED
    _HBM_OVERRIDE_BYTES = max(int(n_bytes), 0)
    if not _HBM_OVERRIDE_BYTES:
        _HBM_DEFAULT_WARNED = False


def device_hbm_bytes(default: int = 16 << 30) -> int:
    """Accelerator memory of local device 0, or ``default`` (16 GB — the
    v5e/v5p-class floor this framework budgets for) when the backend does
    not report it (CPU meshes, GPU runtimes without ``memory_stats``,
    older runtimes). Override with ``COMAP_HBM_BYTES`` for planning
    against a different part, or declare the capacity once with
    ``[tuning] device_hbm_mb``. Falling back to the default is WARNED
    once per process — every HBM auto-sizer in the pipeline inherits a
    guess at that point, and a GPU whose real memory is smaller would
    OOM where the planner promised fit."""
    global _HBM_DEFAULT_WARNED
    env = os.environ.get("COMAP_HBM_BYTES", "")
    if env:
        return int(env)
    if _HBM_OVERRIDE_BYTES:
        return _HBM_OVERRIDE_BYTES
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # CPU backend: memory_stats is None/unsupported
        pass
    if not _HBM_DEFAULT_WARNED:
        _HBM_DEFAULT_WARNED = True
        logger.warning(
            "device_hbm_bytes: backend does not report memory "
            "(memory_stats unsupported); assuming the %.0f GiB "
            "default for every HBM auto-sizer. Set [tuning] "
            "device_hbm_mb (or COMAP_HBM_BYTES) to plan against the "
            "real part.", default / 2**30)
    return default


def plan_reduce_memory(feed_batch: int, B: int, C: int, T: int,
                       n_scans: int, L: int, scan_batch: int | None,
                       hbm_bytes: int | None = None,
                       dense_mask: bool = False,
                       headroom: float = 0.9,
                       suggest_scale: int = 1) -> int | None:
    """Validate (and auto-pick) the reduction's streaming knobs against the
    device HBM budget, BEFORE the device OOMs mid-observation.

    ``feed_batch`` here is the PER-DEVICE feed count; ``suggest_scale``
    (the feed-mesh size) converts the error message's suggestion back to
    the stage's total-feeds option. Returns the ``scan_batch`` to use —
    possibly smaller than requested (an explicit value acts as an upper
    bound; ``None`` = all scans at once when that fits). Raises
    ``ValueError`` naming a ``feed_batch`` that fits when no scan
    streaming can rescue the requested one. Scan-batch candidates prefer
    divisors of ``n_scans``: a trailing partial chunk makes ``lax.map``
    compile its body twice.
    """
    budget = int((hbm_bytes or device_hbm_bytes()) * headroom)

    def fits(k):
        return estimate_reduce_hbm(feed_batch, B, C, T, n_scans, L,
                                   scan_batch=k,
                                   dense_mask=dense_mask) <= budget

    if fits(scan_batch):
        return scan_batch
    # shrink below the requested (or full) chunk size, largest fitting
    # divisor of n_scans first; k=1 is always a divisor, and the estimate
    # is monotone in k, so non-divisors can never fit when no divisor does
    top = n_scans if scan_batch is None else min(int(scan_batch), n_scans)
    for k in (k for k in range(top - 1, 0, -1) if n_scans % k == 0):
        if fits(k):
            return k
    # no scan streaming rescues this feed_batch: suggest one that fits
    # with single-scan streaming
    per_feed = estimate_reduce_hbm(1, B, C, T, n_scans, L, scan_batch=1,
                                   dense_mask=dense_mask)
    fb_ok = max(budget // max(per_feed, 1), 0)
    raise ValueError(
        f"reduction batch does not fit device memory: feed_batch="
        f"{feed_batch} feeds/device at shape (B={B}, C={C}, T={T}, "
        f"S={n_scans}, L={L}) needs "
        f"~{estimate_reduce_hbm(feed_batch, B, C, T, n_scans, L, 1, dense_mask) / 2**30:.1f} GiB "
        f"even streaming one scan at a time; budget is "
        f"{budget / 2**30:.1f} GiB. Set feed_batch="
        f"{max(fb_ok, 1) * max(suggest_scale, 1)}"
        + ("" if fb_ok else " and a smaller medfilt/scan geometry")
        + " (stage option feed_batch=, see docs/OPERATIONS.md §2).")


# per-feed working blocks of the lax.map-streamed stage programs
# (atmosphere fit / frequency bin): the mapped body holds the NaN-filled
# copy plus fusion slack for ONE feed while the raw counts of the whole
# chunk stay resident. Conservative, like REDUCE_CHAIN_BLOCKS.
STAGE_CHAIN_BLOCKS = 3


def plan_stage_feed_batch(F: int, B: int, C: int, T: int,
                          requested: int = 0, n_arrays: int = 1,
                          hbm_bytes: int | None = None,
                          headroom: float = 0.9) -> int:
    """ONE sizing policy for the feed-batched stage programs
    (SkyDip / AtmosphereRemoval / Level1Averaging — ISSUE 4 satellite:
    no more hard-coded ``fb`` copies).

    The stage programs ``lax.map`` over the feed axis, so their working
    set is ONE feed's ``STAGE_CHAIN_BLOCKS`` raw-sized blocks on top of
    the chunk's resident inputs (``n_arrays`` f32[B, C, T] device arrays
    per feed — the raw counts, plus e.g. a dense per-feed mask where a
    stage ships one). Returns the largest feed chunk that fits the HBM
    budget; ``requested`` > 0 acts as an upper bound (the stage knob),
    0/None means auto — and on the auto path a measured ``[tuning]``
    winner for this (F, B, C, T) bucket, when one is cached, becomes
    the bound instead of "as many as fit" (the HBM fit still caps it:
    a tuned winner can shrink the chunk, never blow the budget).
    Always >= 1: a single feed that cannot fit is a geometry problem
    the downstream OOM reports better than a zero batch would."""
    budget = int((hbm_bytes or device_hbm_bytes()) * headroom)
    unit = B * C * T * 4 * max(int(n_arrays), 1)
    work = STAGE_CHAIN_BLOCKS * B * C * T * 4
    fit = max((budget - work) // max(unit, 1), 1)
    if not requested:
        # [tuning]: consult the winners cache on the auto path only —
        # an explicit stage knob always wins. Lazy import, and a no-op
        # attribute check when the table is absent (TUNING disabled):
        # byte-identical to the untuned planner.
        from comapreduce_tpu.tuning.cache import TUNING

        if TUNING.enabled:
            from comapreduce_tpu.tuning.space import stage_bucket

            win = TUNING.winner("stage",
                                stage_bucket(F, B, C, T, n_arrays))
            if win and win.get("feed_batch"):
                requested = int(win["feed_batch"])
    fb = F if not requested else min(int(requested), F)
    return int(max(min(fb, fit), 1))


def stage_feed_batches(F: int, B: int, C: int, T: int,
                       requested: int = 0, n_arrays: int = 1,
                       hbm_bytes: int | None = None) -> list[list[int]]:
    """Feed-index chunks for one whole-observation stage pass, sized by
    :func:`plan_stage_feed_batch` (each chunk = ONE jitted dispatch)."""
    fb = plan_stage_feed_batch(F, B, C, T, requested=requested,
                               n_arrays=n_arrays, hbm_bytes=hbm_bytes)
    return [list(range(i, min(i + fb, F))) for i in range(0, F, fb)]


class ShapeBuckets:
    """Campaign-level shape canonicalisation policy (ISSUE 5 tentpole 1).

    Every distinct ``(T, S, L)`` observation geometry is its own XLA
    compile of the flagship programs; a production filelist (hundreds
    of obsIDs with second-level duration jitter) would recompile them
    per file. This policy rounds each axis UP to a quantum grid so the
    whole campaign lands in a small set of canonical buckets — programs
    compile once per bucket and are reused across every file:

    - ``t_quantum``   rounds the time axis ``T`` (padded tail shipped
      as NaN -> zero validity on device; outputs sliced back to ``T``);
    - ``scan_quantum`` rounds the scan count ``S`` (padding scans have
      ``length == 0``: their ``t_valid`` row is all-zero, and
      ``scatter_scan_blocks`` routes every one of their samples to the
      dropped junk slot);
    - ``l_quantum``   rounds the padded scan-block length ``L`` on top
      of ``scan_starts_lengths``'s ``pad_to`` grid (the masked-tail
      semantics of ``extract_scan_blocks`` already carry any ``L`` >=
      the longest scan).

    A quantum of 0 leaves that axis untouched (the per-file exact
    shape — zero behaviour change for existing configs). The padding
    overhead is bounded: at most ``quantum - 1`` extra samples per
    axis, i.e. a fractional compute/memory overhead under
    ``quantum / axis_length`` per padded axis (see
    :meth:`overhead_bound`). Value-hashable like :class:`ReduceConfig`.
    """

    def __init__(self, t_quantum: int = 0, scan_quantum: int = 0,
                 l_quantum: int = 0):
        self.t_quantum = max(int(t_quantum or 0), 0)
        self.scan_quantum = max(int(scan_quantum or 0), 0)
        self.l_quantum = max(int(l_quantum or 0), 0)

    def _key(self):
        return (self.t_quantum, self.scan_quantum, self.l_quantum)

    def __eq__(self, other):
        return (type(other) is ShapeBuckets and
                self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return (f"ShapeBuckets(t_quantum={self.t_quantum}, "
                f"scan_quantum={self.scan_quantum}, "
                f"l_quantum={self.l_quantum})")

    @property
    def enabled(self) -> bool:
        return bool(self.t_quantum or self.scan_quantum or self.l_quantum)

    @staticmethod
    def _up(n: int, q: int) -> int:
        return int(n) if q <= 0 or n <= 0 else -(-int(n) // q) * q

    def round_T(self, T: int) -> int:
        return self._up(T, self.t_quantum)

    def round_S(self, S: int) -> int:
        return self._up(S, self.scan_quantum)

    def round_L(self, L: int) -> int:
        return self._up(L, self.l_quantum)

    def canonical(self, T: int, S: int, L: int) -> tuple:
        """The bucket ``(T, S, L)`` falls in."""
        return (self.round_T(T), self.round_S(S), self.round_L(L))

    def overhead_bound(self, T: int, S: int, L: int) -> float:
        """Upper bound on the fractional sample-count overhead of
        padding ``(T, S, L)`` to its bucket — the documented cost of
        the policy (docs/OPERATIONS.md §9)."""
        Tb, Sb, Lb = self.canonical(T, S, L)
        raw = max(T, 1) * max(S, 1) * max(L, 1)
        return (Tb * max(Sb, 1) * max(Lb, 1)) / raw - 1.0

    @classmethod
    def coerce(cls, value) -> "ShapeBuckets":
        """None / dict / ShapeBuckets -> ShapeBuckets (config plumbing;
        ``None`` is the disabled identity policy)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in
                     ("t_quantum", "scan_quantum", "l_quantum")
                     if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown shape-bucket keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build ShapeBuckets from {type(value)}")


def pad_time_axis(x: np.ndarray, n_to: int,
                  fill: str = "nan") -> np.ndarray:
    """Pad a host array's trailing (time) axis up to ``n_to`` samples.

    ``fill='nan'`` marks the tail INVALID for the ``mask=None`` device
    path (``isfinite`` -> zero weight); ``'edge'`` repeats the last
    sample — for operands that must stay finite because they multiply
    into masked sums (``0 * NaN`` is NaN, so a NaN airmass tail would
    poison a zero-weight reduction); ``'zero'`` for masks."""
    n = int(x.shape[-1])
    n_to = int(n_to)
    if n_to <= n:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, n_to - n)]
    if fill == "edge":
        return np.pad(x, pad, mode="edge")
    if fill == "zero":
        return np.pad(x, pad)
    return np.pad(x, pad, constant_values=np.nan)


def pad_scan_geometry(starts: np.ndarray, lengths: np.ndarray,
                      n_to: int):
    """Pad scan ``starts``/``lengths`` up to ``n_to`` scans with
    zero-length scans at start 0 (all-masked: ``t_valid`` rows are
    all-zero and the scatter drops every sample)."""
    S = len(starts)
    n_to = int(n_to)
    if n_to <= S:
        return starts, lengths
    z = np.zeros(n_to - S, dtype=np.asarray(starts).dtype)
    return (np.concatenate([np.asarray(starts), z]),
            np.concatenate([np.asarray(lengths),
                            np.zeros(n_to - S,
                                     np.asarray(lengths).dtype)]))


def _fill_bad_xla(tod, mask):
    """XLA branch of :func:`_fill_bad` — the reference semantics every
    other implementation must match bit-for-bit."""
    med = masked_median(tod[..., ::4], mask[..., ::4], axis=-1)
    sub_cnt = jnp.sum(mask[..., ::4], axis=-1)
    cnt = jnp.sum(mask, axis=-1)
    mean = jnp.sum(tod * mask, axis=-1) / jnp.maximum(cnt, 1.0)
    fill = jnp.where(sub_cnt > 0, med, mean)[..., None]
    return jnp.where(mask > 0, tod, fill)


def _fill_bad(tod, mask, impl: str = "auto"):
    """Replace masked samples with the per-channel masked median
    (``fill_bad_data``, ``Level1Averaging.py:658-665``).

    The median runs on a stride-4 subsample: it only supplies fill values
    for already-masked samples, and the full-length per-channel sort is
    one of the costliest ops in the reduction. When a channel's valid
    samples all fall off the stride-4 grid the subsampled median is
    undefined — ``masked_median`` on an empty subsample returns its
    float32-max sort sentinel (~3.4e38), so fall back to the full-length
    masked mean (cheap reduction) instead of filling with the sentinel.

    The XLA formulation is the reduction pre-filter's measured floor
    (~34 logical HBM passes: the median selection re-reads the block
    once per radix/sort step). On TPU backends the fused Mosaic kernel
    (``ops/pallas_median.masked_fill_pallas``) computes the identical
    fill in 3 passes, gated exactly like ``rolling_median``'s kernel:
    ``pallas_supported()``/``pallas_fill_ok()`` keep the Mosaic body
    out of the jaxpr at TRACE time on CPU-only hosts, and
    ``platform_dependent`` picks the branch per lowering platform on
    TPU hosts. CPU-default behaviour is byte-identical by construction
    (the gate leaves this function exactly `_fill_bad_xla` there).

    ``impl`` overrides the gate for tests and benches: ``"xla"`` forces
    the reference, ``"pallas"`` traces the kernel unconditionally (the
    compile-inspection budget test inspects that jaxpr), ``"interpret"``
    runs the kernel under the Pallas interpreter (CPU parity suite),
    and ``"none"`` skips the fill entirely — test-only, so the budget
    test can compile-inspect the rest of the pre-filter chain and add
    the kernel's accounted passes on top."""
    if impl == "none":
        return tod
    if impl == "xla":
        return _fill_bad_xla(tod, mask)
    from comapreduce_tpu.ops.pallas_median import (masked_fill_pallas,
                                                   pallas_fill_ok,
                                                   pallas_supported)
    if impl == "pallas":
        return masked_fill_pallas(tod, mask)
    if impl == "interpret":
        return masked_fill_pallas(tod, mask, interpret=True)
    if impl != "auto":
        raise ValueError(f"unknown _fill_bad impl {impl!r}")
    if tod.dtype == jnp.float32 and pallas_fill_ok(tod.shape[-1]) \
            and pallas_supported():
        return jax.lax.platform_dependent(
            tod, mask,
            tpu=masked_fill_pallas, axon=masked_fill_pallas,
            default=_fill_bad_xla)
    return _fill_bad_xla(tod, mask)


def _prefilter_chain(d_s, m_s, a_s, cfg: ReduceConfig, fill_impl="auto"):
    """Fused PRE-FILTER segment of the per-scan chain: NaN fill ->
    atmosphere (field) or median (calibrator) removal -> radiometer
    normalisation.

    One module-level home so the compile-inspection pass-count test
    (``tests/test_reduce.py::test_prefilter_pass_budget``) measures
    exactly the segment the reduction runs: every step is elementwise /
    reduction math over one ``(B, C, L)`` scan block and XLA fuses the
    chain into a handful of logical HBM passes. Returns
    ``(clean_norm, norm, atmos_fit)``; ``m_s`` must already carry the
    time-validity mask (the caller's ``tv``).

    ``fill_impl`` routes the NaN fill (see :func:`_fill_bad`): the
    ``"auto"`` default keeps CPU behaviour byte-identical while TPU
    lowerings take the fused Mosaic kernel — the pre-filter's measured
    ~34-pass floor is almost entirely the XLA fill's median selection,
    so the kernel is what moves this chain toward the post-filter's
    ~3-pass budget (ROOFLINE round 8).

    Precision contract (OPERATIONS.md §15): a bf16 TOD policy narrows
    storage and transport only — this chain widens the scan block to
    f32 HERE, before the first arithmetic touch, so every reduction
    (median, airmass fit, rms) accumulates in f32. The guard is a
    trace-time no-op for f32 inputs (default path byte-identical; the
    pass-budget test sees the same program)."""
    if d_s.dtype != jnp.float32:
        d_s = d_s.astype(jnp.float32)
    B, C, L = d_s.shape
    # NaN fill is per-scan independent; doing it here (not on the full
    # block) lets scan_batch streaming bound its memory too
    d_s = _fill_bad(d_s, m_s, impl=fill_impl)
    if cfg.is_calibrator:
        med = masked_median(d_s, m_s, axis=-1)
        base, slope = med, jnp.zeros_like(med)
        atmos_fit = jnp.concatenate(
            [med[:, None, :], jnp.zeros((B, 1, C))], axis=1)
    else:
        base, slope = fit_airmass_block(d_s, a_s, m_s)
        atmos_fit = jnp.stack([base, slope], axis=1)  # (B, 2, C)
    # radiometer rms straight from the FILLED block on the stride-4
    # grid: diff(clean) == diff(d) - slope * diff(airmass) (the per-
    # channel baseline cancels in the pair difference), so the
    # detrended block is written ONCE — already normalised — instead
    # of a detrended pass plus a normalising pass
    # (``normalise_by_rms`` semantics, ``Level1Averaging.py:667-679``)
    n4 = L // 4 * 4
    am_d = (a_s[0:n4:4] - a_s[2:n4:4])[None, None, :]
    diff = (d_s[..., 0:n4:4] - d_s[..., 2:n4:4]) - slope[..., None] * am_d
    pm = m_s[..., 0:n4:4] * m_s[..., 2:n4:4]
    rms = masked_std(diff, pm, axis=-1) / jnp.sqrt(2.0)
    norm = (rms * jnp.sqrt(cfg.bandwidth * cfg.tau))[..., None]
    safe = jnp.maximum(norm, 1e-30)
    model = base[..., None] + slope[..., None] * a_s[None, None, :]
    clean = jnp.where(norm > 0, (d_s - model) / safe, 0.0)
    return clean, norm, atmos_fit


def _postfilter_chain(filtered, m_s, tv, norm, tsys, sys_gain,
                      freq_scaled, cfg: ReduceConfig):
    """Fused POST-FILTER segment: gain solve + counts->kelvin band
    averages in ONE traversal of the filtered block.

    The unfused chain materialised ``sub = filtered - p dg`` and
    ``in_kelvin = filtered * norm / gain`` as full ``(B, C, L)`` blocks
    and band-averaged each — three extra logical HBM passes at
    production shape. With ``kelvin = norm / gain`` per channel the
    gain template's contribution to the band average is RANK-1::

        wba((filtered - p dg) kelvin, w)
            = wba(filtered kelvin, w) - (sum_c w p kelvin / sum_c w) dg

    so ``tod_clean`` is ``tod_orig`` minus a per-band coefficient times
    ``dg`` — no second traversal, no intermediate blocks. Returns
    ``(tod_clean, tod_orig, weights, dg)`` (each already tv-masked).

    Like :func:`_prefilter_chain`, the block is widened to f32 before
    the gain solve / band average (trace-time no-op for f32 inputs)."""
    if filtered.dtype != jnp.float32:
        filtered = filtered.astype(jnp.float32)
    B, C, L = filtered.shape
    T2, p = gain_ops.build_templates(
        tsys, freq_scaled, cfg.mask_templates[None, :] * jnp.ones((B, 1)))
    if cfg.is_calibrator:
        dg = jnp.zeros((L,), filtered.dtype)
    else:
        # natural (B, C, L) block: solve_gain contracts the channel
        # axes in place (a (B*C, L) reshape costs a layout copy)
        dg = gain_ops.solve_gain(filtered * m_s, T2, p, time_mask=tv)

    w_tsys = jnp.where(tsys > 0, 1.0 / jnp.maximum(tsys, 1e-10) ** 2, 0.0)
    w = w_tsys * cfg.mask_weights[None, :] * cfg.mask_band_avg[None, :]
    safe_gain = jnp.where(sys_gain > 0, sys_gain, 1.0)
    # tod_original: same exact counts->kelvin reconstruction
    # (norm/gain), just without the gain-fluctuation subtraction.
    # Scaling by tsys instead would distort whenever the auto-rms is
    # contaminated (e.g. by a bright calibrator transit): norm/gain
    # cancels the normalisation exactly, tsys only approximates it.
    kelvin = norm[..., 0] / safe_gain                       # (B, C)
    wk = w * kelvin
    den = jnp.maximum(jnp.sum(w, axis=-1), 1e-30)[..., None]  # (B, 1)
    tod_orig = jnp.einsum("...ct,...c->...t", filtered, wk) / den
    coef = jnp.sum(wk * p.reshape(B, C), axis=-1)[..., None] / den
    tod_clean = tod_orig - coef * dg[None, :]               # (B, L)

    # per-band weights from the residual's auto-rms
    n2 = L // 2 * 2
    diff = (tod_clean[..., 1:n2:2] - tod_clean[..., 0:n2:2])
    pm = tv[1:n2:2] * tv[0:n2:2]
    var = jnp.sum(diff * diff * pm, -1) / jnp.maximum(jnp.sum(pm, -1), 1.0)
    rms2 = var / 2.0
    w_t = jnp.where(rms2 > 0, 1.0 / jnp.maximum(rms2, 1e-30), 0.0)
    weights = jnp.broadcast_to(w_t[:, None], (B, L)) * tv[None, :]
    return (tod_clean * tv[None, :], tod_orig * tv[None, :], weights, dg)


@functools.partial(jax.jit, static_argnames=("cfg", "n_scans", "L"))
def reduce_feed_scans(tod, mask, airmass, starts, lengths,
                      tsys, sys_gain, freq_scaled, cfg: ReduceConfig,
                      n_scans: int, L: int, fold_len=None):
    """Full reduction of one feed's observation.

    Parameters
    ----------
    tod:        f32[B, C, T] raw counts. With ``mask=None`` the counts may
                carry NaNs: validity is derived on device.
    mask:       f32 validity mask, any shape broadcastable to [B, C, T]
                (e.g. a plain time mask f32[T]); a pre-broadcast dense
                mask forces an extra full-size gather + materialisation,
                so pass the smallest true shape. ``None`` derives the mask
                as ``isfinite(tod)`` and NaN-fills ``tod`` on device —
                the HDF5 ingest path uses this so the host never ships a
                dense mask (halves transfer bytes and HBM residency; the
                ``isfinite`` fuses into the scan gather's consumers).
    airmass:    f32[T].
    starts, lengths: i32[S] scan geometry (host-derived, static count).
    tsys, sys_gain:  f32[B, C] from the vane calibration.
    freq_scaled:     f32[B, C] ``(nu-nu0)/nu0`` for the gain templates.
    fold_len:   optional DYNAMIC i32 scalar: the per-file scan-block
                length the median filter reflects at. A campaign shape
                policy (``ShapeBuckets``) pads ``L`` up to a bucket; the
                filter's symmetric boundary must stay at the UNPADDED
                length or windows near a scan's end would mirror
                different samples and break bucketed-vs-exact parity
                (docs/OPERATIONS.md §9). ``None`` reflects at the static
                ``L`` (the pre-campaign behaviour, exact when ``L`` is
                the per-file length).

    Returns dict with ``tod`` (gain-subtracted, calibrated, band-averaged,
    f32[B, T]), ``tod_original`` (no gain subtraction), ``weights``
    (f32[B, T]), ``dg`` (f32[S, L] gain solutions),
    ``atmos_fits`` (f32[S, B, 2, C]).

    vmap over feeds; shard_map the feed axis over the mesh.
    """
    B, C, T = tod.shape
    if tod.dtype != jnp.float32:
        # bf16 TOD policy (OPERATIONS.md §15): payloads may arrive
        # narrowed — widen at the first device touch. bf16 shares
        # f32's exponent field, so the NaN sentinels the mask=None
        # path keys on survive the round-trip; validity is identical.
        tod = tod.astype(jnp.float32)
    if mask is None:
        mask = jnp.isfinite(tod).astype(tod.dtype)
        tod = jnp.nan_to_num(tod)
    t_valid = (jnp.arange(L)[None, :] < lengths[:, None]).astype(tod.dtype)

    def per_scan(d_s, m_s, a_s, tv):
        # masks arrive in their natural (possibly broadcast) shape; the
        # lazy broadcast here fuses into consumers instead of
        # materialising a (B, C, L) block. Padding samples are masked by
        # tv here — the one place both call paths share.
        m_s = jnp.broadcast_to(m_s, d_s.shape) * tv
        # two fused elementwise segments around the median filter (the
        # only stage that genuinely needs its own passes); their pass
        # budgets are pinned by compile inspection in tests/test_reduce
        clean, norm, atmos_fit = _prefilter_chain(d_s, m_s, a_s, cfg)
        filtered, _ = medfilt_highpass(clean, cfg.mask_medfilt[None, :]
                                       * jnp.ones((B, 1)), cfg.medfilt_window,
                                       time_mask=tv,
                                       stride=cfg.medfilt_stride,
                                       fold_len=fold_len)
        tod_clean, tod_orig, weights, dg = _postfilter_chain(
            filtered, m_s, tv, norm, tsys, sys_gain, freq_scaled, cfg)
        return tod_clean, tod_orig, weights, dg, atmos_fit

    if cfg.scan_batch is not None and cfg.scan_batch < n_scans:
        # stream scans in fixed-size chunks, EXTRACTING inside the loop:
        # peak memory ~= scan_batch (B, C, L) working sets on top of the
        # raw (B, C, T) input — the full (S, B, C, L) block pair (2x the
        # observation) never materialises. NOTE lax.map compiles the body
        # a second time for a trailing partial chunk — prefer scan_batch
        # values dividing n_scans to avoid doubling compile time.
        def per_scan_slice(args):
            # single-scan takes (NOT extract_scan_blocks with a size-1
            # batch): under lax.map's vmap the 1-D take keeps the scan
            # batch leading in the gather output, where the blocked
            # extract gathered (B, C, batch, L) and paid a transposed
            # copy per batch (see extract_one_scan)
            start, length, tv = args
            d_s = extract_one_scan(tod, start, L, length)
            m_s = extract_one_scan(mask, start, L)
            a_s = extract_one_scan(airmass, start, L, length)
            return per_scan(d_s, m_s, a_s, tv)  # m_s broadcast/tv'd there

        tod_c, tod_o, wts, dgs, atm = jax.lax.map(
            per_scan_slice, (starts, lengths, t_valid),
            batch_size=cfg.scan_batch)
    else:
        # (S, ..., L) scan blocks in one gather each
        d = extract_scan_blocks(tod, starts, L, lengths)
        m = extract_scan_blocks(mask, starts, L)
        a = extract_scan_blocks(airmass, starts, L, lengths)  # (S, L)
        tod_c, tod_o, wts, dgs, atm = jax.vmap(per_scan)(d, m, a, t_valid)

    return {
        "tod": scatter_scan_blocks(tod_c, starts, lengths, T),
        "tod_original": scatter_scan_blocks(tod_o, starts, lengths, T),
        "weights": scatter_scan_blocks(wts, starts, lengths, T),
        "dg": dgs,
        "atmos_fits": atm,
    }
