"""Spike detection: median-filter high-pass + thresholded run dilation.

Parity target: ``Analysis/Statistics.py:30-104`` (``Spikes``) — high-pass
the averaged TOD with a rolling median, flag samples beyond
``threshold * auto_rms``, and pad each flagged run by ±``pad`` samples.
The reference dilates with a Python loop over flagged indices; here the
dilation is a max-pool (``lax.reduce_window``) so the whole (F, B, T) cube
is one jitted kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from comapreduce_tpu.ops.median_filter import rolling_median
from comapreduce_tpu.ops.stats import auto_rms

__all__ = ["dilate_mask", "spike_mask"]

DEFAULT_WINDOW = 501
DEFAULT_THRESHOLD = 10.0  # Statistics.py: |tod| > 10 * rms
DEFAULT_PAD = 100         # ±100-sample padding around each spike run


@functools.partial(jax.jit, static_argnames=("pad",))
def dilate_mask(mask: jax.Array, pad: int) -> jax.Array:
    """Dilate a boolean/0-1 mask by ±``pad`` samples along the last axis."""
    if pad <= 0:
        return mask
    m = mask.astype(jnp.float32)
    flat = m.reshape((-1, m.shape[-1]))
    out = lax.reduce_window(flat, -jnp.inf, lax.max,
                            window_dimensions=(1, 2 * pad + 1),
                            window_strides=(1, 1), padding="SAME")
    return (out > 0).reshape(mask.shape)


@functools.partial(jax.jit,
                   static_argnames=("window", "pad"))
def spike_mask(tod: jax.Array, window: int = DEFAULT_WINDOW,
               threshold: float = DEFAULT_THRESHOLD, pad: int = DEFAULT_PAD,
               valid: jax.Array | None = None) -> jax.Array:
    """Boolean spike mask (True = spike) for ``tod`` f32[..., T].

    ``valid``: optional f32[..., T]; invalid samples never flag. The rms is
    the adjacent-pair ``auto_rms`` of the high-passed stream, so slow drifts
    don't inflate the threshold.
    """
    hp = tod - rolling_median(tod, window)
    rms = auto_rms(hp, valid)[..., None]
    hits = jnp.abs(hp) > threshold * jnp.maximum(rms, 1e-30)
    if valid is not None:
        hits = hits & (valid > 0)
    return dilate_mask(hits, pad)
