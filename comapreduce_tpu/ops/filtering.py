"""TOD filtering utilities (``Tools/Filtering.py`` parity).

Source-aware background estimation (mask + interpolate across the source,
then Butterworth low-pass, ``Filtering.py:6-47``), airmass-template
atmosphere estimation (``:49-89``), and rms estimation (``calcRMS``).
All jittable jnp; the low-pass is an FFT multiply (device-friendly,
unlike the reference's scipy filtfilt).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from comapreduce_tpu.ops.atmosphere import fit_airmass_block
from comapreduce_tpu.ops.stats import auto_rms

__all__ = ["butterworth_lowpass", "background_estimate",
           "atmosphere_estimate", "calc_rms"]


@functools.partial(jax.jit, static_argnames=("order",))
def butterworth_lowpass(x: jax.Array, cutoff: float, sample_rate: float = 50.0,
                        order: int = 3) -> jax.Array:
    """Zero-phase Butterworth low-pass via an rFFT gain multiply.

    ``|H(f)|^2 = 1 / (1 + (f/fc)^(2*order))`` — the squared magnitude of
    the reference's forward-backward ``filtfilt`` Butterworth
    (``Filtering.py:30-38``), applied spectrally so it stays one fused
    device op. Operates along the last axis.
    """
    n = x.shape[-1]
    f = jnp.fft.rfftfreq(n, d=1.0 / sample_rate)
    gain = 1.0 / (1.0 + (f / cutoff) ** (2 * order))
    return jnp.fft.irfft(jnp.fft.rfft(x, axis=-1) * gain, n=n, axis=-1)


def _linear_fill(x: jax.Array, keep: jax.Array) -> jax.Array:
    """Replace masked samples by linear interpolation between kept
    neighbours (edge samples extend)."""
    t = jnp.arange(x.shape[-1], dtype=x.dtype)
    big = jnp.asarray(x.shape[-1] * 2, x.dtype)
    # previous kept index per sample
    idx = jnp.arange(x.shape[-1])
    ax = keep.ndim - 1  # lax.cummax rejects negative axes
    prev = jax.lax.cummax(jnp.where(keep > 0, idx, -1), axis=ax)
    nxt_rev = jax.lax.cummax(jnp.where(jnp.flip(keep, -1) > 0,
                                       idx, -1), axis=ax)
    nxt = x.shape[-1] - 1 - jnp.flip(nxt_rev, -1)
    has_prev = prev >= 0
    has_next = nxt <= x.shape[-1] - 1
    p = jnp.clip(prev, 0, x.shape[-1] - 1)
    q = jnp.clip(nxt, 0, x.shape[-1] - 1)
    xp = jnp.take_along_axis(x, p, axis=-1)
    xq = jnp.take_along_axis(x, q, axis=-1)
    tp = t[p].astype(x.dtype)
    tq = t[q].astype(x.dtype)
    dt = jnp.where(has_prev & has_next, jnp.maximum(tq - tp, 1.0), big)
    w = jnp.clip((t - tp) / dt, 0.0, 1.0)
    filled = jnp.where(has_prev, jnp.where(has_next,
                                           xp + (xq - xp) * w, xp),
                       xq)
    return jnp.where(keep > 0, x, filled)


@jax.jit
def background_estimate(tod: jax.Array, source_mask: jax.Array,
                        cutoff: float = 0.1,
                        sample_rate: float = 50.0) -> jax.Array:
    """Slowly-varying background under a masked source
    (``Filtering.py:6-47``): interpolate across ``source_mask`` (1 =
    source, excluded), then low-pass. Last axis is time."""
    keep = 1.0 - source_mask
    filled = _linear_fill(tod, keep)
    return butterworth_lowpass(filled, cutoff, sample_rate)


def atmosphere_estimate(tod: jax.Array, airmass: jax.Array,
                        mask: jax.Array | None = None) -> jax.Array:
    """Airmass-template atmosphere estimate: the fitted
    ``offset + slope * A(t)`` (``Filtering.py:49-89``)."""
    if mask is None:
        mask = jnp.ones_like(tod)
    off, slope = fit_airmass_block(tod, airmass, mask)
    return off[..., None] + slope[..., None] * airmass


def calc_rms(tod: jax.Array) -> jax.Array:
    """Adjacent-pair white-noise rms (``Filtering.calcRMS`` role)."""
    return auto_rms(tod)
