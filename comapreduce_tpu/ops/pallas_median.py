"""Pallas TPU kernel: exact rolling median by in-VMEM radix bisection.

The XLA formulation of the windowed median (gather the (chunk, window)
mat, select per row — ``ops/median_filter.py``) round-trips every window
matrix through HBM and, under the reduction's scan-batch ``vmap``, picks
layouts that put the small batch dims in the vector lanes (profiled ~7x
over its bandwidth bound). This kernel keeps the whole selection on-chip:

1. DMA an overlapping ``(8, chunk + Wpad)`` row segment from ANY memory
   (dynamic *lane* slicing is not lowerable on this Mosaic version, but
   DMA offsets are address-based and free of that restriction);
2. build the window matrix in VMEM scratch with ``pltpu.roll`` (dynamic
   roll IS supported) + a static slice + a sublane-dynamic store;
3. run the 32-pass radix bisection (``ops/stats._kth_smallest_u32``
   semantics, mapped to signed i32 keys because Mosaic lacks unsigned
   reductions) entirely in VMEM, plus two passes for the upper median.

Exact: bit-identical to ``sort -> middle`` selection, with full
``jnp.median`` NaN semantics — any NaN inside a window yields NaN (the
wrapper counts windowed NaNs by cumsum difference and overwrites those
outputs; the kernel itself only orders finite keys). Handles any window;
VMEM bounds the padded window at ``MAX_PALLAS_WINDOW``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["rolling_median_windows_pallas", "MAX_PALLAS_WINDOW",
           "pallas_supported", "pallas_window_ok"]

_ROWS = 8          # f32 sublane tile
MAX_PALLAS_WINDOW = 2048   # padded-window cap: mat scratch = Wpad*8*chunk*4B


def _w_pad(window: int) -> int:
    return -(-max(int(window), 2) // 128) * 128


def pallas_window_ok(window: int) -> bool:
    """True when ``window`` fits the kernel's VMEM scratch budget — the
    single predicate dispatch gates must use (keeps the padding rule in
    one place)."""
    return _w_pad(window) <= MAX_PALLAS_WINDOW


def pallas_supported() -> bool:
    """True when the PROCESS-DEFAULT backend can run the Mosaic
    (TPU-only) kernel; 'axon' is the tunnelled TPU platform.

    ``rolling_median`` uses this as its TRACE-time gate: current jax
    lowers every ``platform_dependent`` branch, so the Mosaic kernel
    must stay out of the jaxpr entirely on CPU-only hosts. On a
    TPU-default host the ``platform_dependent`` lowering-time selection
    still applies to TPU placements (CPU placements there cannot lower
    the embedded kernel — pre-existing limitation)."""
    backend = jax.default_backend()
    return backend.startswith("tpu") or backend == "axon"


def _kernel(x_hbm, o_ref, seg_ref, mat_ref, sem, *, window, w_pad, chunk):
    IMAX = jnp.int32(0x7FFFFFFF)
    i = pl.program_id(0)
    j = pl.program_id(1)
    cp = pltpu.make_async_copy(
        x_hbm.at[pl.ds(i * _ROWS, _ROWS), pl.ds(j * chunk, chunk + w_pad)],
        seg_ref, sem)
    cp.start()
    cp.wait()
    # monotone f32 -> signed i32 keys (same total order as the floats;
    # NaN windows are overwritten by the wrapper, so NaN keys just need
    # a consistent slot in the order)
    u = jax.lax.bitcast_convert_type(seg_ref[...], jnp.uint32)
    neg = (u >> 31) == 1
    key_u = jnp.where(neg, ~u, u | jnp.uint32(0x80000000))
    keys = jax.lax.bitcast_convert_type(
        key_u ^ jnp.uint32(0x80000000), jnp.int32)

    def build(jj, _):
        # positive shift: pltpu.roll miscomputes NEGATIVE dynamic shifts
        # at non-power-of-two widths (observed off-by-(width-256) at 640)
        rolled = pltpu.roll(keys, (chunk + w_pad) - jj, 1)[:, :chunk]
        mat_ref[pl.ds(jj * _ROWS, _ROWS), :] = jnp.where(
            jj < window, rolled, IMAX)
        return 0

    jax.lax.fori_loop(0, w_pad, build, 0)
    mat = mat_ref[...].reshape(w_pad, _ROWS, chunk)

    k_lo = (window - 1) // 2
    k_hi = window // 2
    lo = jnp.full((_ROWS, chunk), -0x80000000, jnp.int32)
    hi = jnp.full((_ROWS, chunk), 0x7FFFFFFF, jnp.int32)

    def bis(_, lohi):
        lo, hi = lohi
        # overflow-safe midpoint of the full i32 range
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        c = jnp.sum((mat <= mid[None, :, :]).astype(jnp.int32), axis=0)
        take = c >= (k_lo + 1)
        return (jnp.where(take, lo, mid + 1), jnp.where(take, mid, hi))

    v_lo, _ = jax.lax.fori_loop(0, 32, bis, (lo, hi))
    # upper median: smallest key strictly above v_lo unless the k_hi-th
    # order statistic equals v_lo (duplicates)
    c_le = jnp.sum((mat <= v_lo[None, :, :]).astype(jnp.int32), axis=0)
    above = jnp.where(mat > v_lo[None, :, :], mat, IMAX)
    v_next = jnp.min(above, axis=0)
    v_hi = jnp.where(c_le >= k_hi + 1, v_lo, v_next)

    def tof(v_s):
        v = (jax.lax.bitcast_convert_type(v_s, jnp.uint32)
             ^ jnp.uint32(0x80000000))
        was_neg = (v >> 31) == 0
        return jax.lax.bitcast_convert_type(
            jnp.where(was_neg, ~v, v & jnp.uint32(0x7FFFFFFF)), jnp.float32)

    from comapreduce_tpu.ops.stats import _median_mid

    o_ref[...] = _median_mid(tof(v_lo), tof(v_hi))


@functools.partial(jax.jit,
                   static_argnames=("window", "chunk", "interpret"))
def rolling_median_windows_pallas(padded: jax.Array, window: int,
                                  chunk: int = 256,
                                  interpret: bool = False) -> jax.Array:
    """``out[..., i] = median(padded[..., i : i + window])`` — exact.

    ``padded``: f32[..., P] with ``P >= T + window - 1`` for the desired
    ``T = P - window + 1`` outputs (callers do their own edge padding,
    exactly like the XLA path in ``ops/median_filter.rolling_median``).
    ``jnp.median`` NaN semantics: any NaN inside a window yields NaN.
    ``interpret=True`` runs the Pallas interpreter — the CPU parity path
    for tests.
    """
    P = padded.shape[-1]
    T = P - window + 1
    if T <= 0:
        raise ValueError(f"padded length {P} shorter than window {window}")
    if not pallas_window_ok(window):
        raise ValueError(f"window {window} beyond MAX_PALLAS_WINDOW")
    w_pad = _w_pad(window)

    def call2d_raw(x):
        R = x.shape[0]
        r_pad = -(-R // _ROWS) * _ROWS
        n_chunks = -(-T // chunk)
        p_need = n_chunks * chunk + w_pad
        x = jnp.pad(x, ((0, r_pad - R), (0, max(p_need - P, 0))))
        out = pl.pallas_call(
            functools.partial(_kernel, window=window, w_pad=w_pad,
                              chunk=chunk),
            grid=(r_pad // _ROWS, n_chunks),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((_ROWS, chunk), lambda i, j: (i, j),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((r_pad, n_chunks * chunk),
                                           jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((_ROWS, chunk + w_pad), jnp.float32),
                pltpu.VMEM((w_pad * _ROWS, chunk), jnp.int32),
                pltpu.SemaphoreType.DMA,
            ],
            interpret=interpret,
        )(x)[:R, :T]
        # jnp.median NaN semantics, outside the kernel: windowed NaN
        # counts by cumsum difference (two cheap XLA passes) instead of
        # an extra roll+add per kernel build step
        cs = jnp.cumsum(jnp.isnan(x[:R]).astype(jnp.int32), axis=-1)
        cnt = (cs[:, window - 1:window - 1 + T]
               - jnp.pad(cs, ((0, 0), (1, 0)))[:, :T])
        return jnp.where(cnt > 0, jnp.float32(jnp.nan), out)

    # vmapping a pallas_call with an ANY-space input is not lowerable
    # (Mosaic requires whole-array blocks with trivial index maps there);
    # rows are embarrassingly parallel, so batching folds into the row
    # axis instead — this is exactly what the reduction's scan-batch
    # vmap needs
    call2d = jax.custom_batching.custom_vmap(call2d_raw)

    @call2d.def_vmap
    def _rule(axis_size, in_batched, xb):  # noqa: ANN001
        del axis_size
        out = call2d(xb.reshape((-1, xb.shape[-1])))
        return out.reshape(xb.shape[:-1] + (T,)), True

    lead = padded.shape[:-1]
    out = call2d(padded.reshape((-1, P)))
    return out.reshape(lead + (T,))
