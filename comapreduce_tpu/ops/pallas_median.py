"""Pallas TPU kernels: exact rolling median and fused masked fill.

The XLA formulation of the windowed median (gather the (chunk, window)
mat, select per row — ``ops/median_filter.py``) round-trips every window
matrix through HBM and, under the reduction's scan-batch ``vmap``, picks
layouts that put the small batch dims in the vector lanes (profiled ~7x
over its bandwidth bound). This kernel keeps the whole selection on-chip:

1. DMA an overlapping ``(8, chunk + Wpad)`` row segment from ANY memory
   (dynamic *lane* slicing is not lowerable on this Mosaic version, but
   DMA offsets are address-based and free of that restriction);
2. build the window matrix in VMEM scratch with ``pltpu.roll`` (dynamic
   roll IS supported) + a static slice + a sublane-dynamic store;
3. run the 32-pass radix bisection (``ops/stats._kth_smallest_u32``
   semantics, mapped to signed i32 keys because Mosaic lacks unsigned
   reductions) entirely in VMEM, plus two passes for the upper median.

Exact: bit-identical to ``sort -> middle`` selection, with full
``jnp.median`` NaN semantics — any NaN inside a window yields NaN. NaN
keys map to the IMAX padding sentinel, so the per-window NaN test is one
VMEM count over the already-built window matrix (``count(IMAX) >
padding rows``) — no extra roll per build step and no XLA cumsum passes
in the wrapper. Handles any window; VMEM bounds the padded window at
``MAX_PALLAS_WINDOW``.

:func:`masked_fill_pallas` (ISSUE 11) is the second kernel of the
family: the reduction pre-filter's ``_fill_bad`` NaN fill (masked
stride-4 median with masked-mean fallback) in ONE HBM read of the raw
TOD + mask per row block. The XLA formulation is floored at ~34 logical
passes because the masked-median selection re-reads the (stride-4)
block once per radix step; here the whole bisection runs on the
VMEM-resident rows, so the kernel's HBM traffic is exactly
read(tod) + read(mask) + write(out) — 3 logical passes
(:func:`masked_fill_logical_passes` is the accounting the
compile-inspection budget test and ``tools/check_perf.py`` pin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["rolling_median_windows_pallas", "masked_fill_pallas",
           "MAX_PALLAS_WINDOW", "MAX_PALLAS_FILL_LEN",
           "pallas_supported", "pallas_window_ok", "pallas_fill_ok",
           "masked_fill_logical_passes"]

_ROWS = 8          # f32 sublane tile
MAX_PALLAS_WINDOW = 2048   # padded-window cap: mat scratch = Wpad*8*chunk*4B
#: row-length cap for the fused fill kernel: the whole (8, Lpad) row
#: block plus its i32 key image stays VMEM-resident (3 x 8 x Lpad x 4 B
#: plus bisection temporaries — ~1.6 MB at the cap, far under VMEM)
MAX_PALLAS_FILL_LEN = 65536


def _w_pad(window: int) -> int:
    return -(-max(int(window), 2) // 128) * 128


def pallas_window_ok(window: int) -> bool:
    """True when ``window`` fits the kernel's VMEM scratch budget — the
    single predicate dispatch gates must use (keeps the padding rule in
    one place)."""
    return _w_pad(window) <= MAX_PALLAS_WINDOW


def pallas_fill_ok(length: int) -> bool:
    """True when a time-axis row of ``length`` samples fits the fused
    fill kernel's whole-row VMEM residency (the analogue of
    :func:`pallas_window_ok` for :func:`masked_fill_pallas`)."""
    return 0 < int(length) <= MAX_PALLAS_FILL_LEN


def pallas_supported(platform: str | None = None) -> bool:
    """True when ``platform`` (default: the PROCESS-DEFAULT backend) can
    run the Mosaic (TPU-only) kernels; 'axon' is the tunnelled TPU
    platform.

    ``rolling_median`` uses this as its TRACE-time gate: current jax
    lowers every ``platform_dependent`` branch, so the Mosaic kernel
    must stay out of the jaxpr entirely on CPU-only hosts. On a
    TPU-default host the ``platform_dependent`` lowering-time selection
    still applies to TPU placements (CPU placements there cannot lower
    the embedded kernel — pre-existing limitation).

    ``platform=`` is the mixed-host override (ISSUE 11 satellite): a
    host whose default backend is TPU but which places some programs on
    CPU (or vice versa) passes the placement's platform explicitly —
    e.g. ``destripe_planned(..., kernels_platform='cpu')`` — so the
    trace for that placement never embeds an unlowerable kernel."""
    backend = platform if platform is not None else jax.default_backend()
    return backend.startswith("tpu") or backend == "axon"


def _kernel(x_hbm, o_ref, seg_ref, mat_ref, sem, *, window, w_pad, chunk):
    IMAX = jnp.int32(0x7FFFFFFF)
    i = pl.program_id(0)
    j = pl.program_id(1)
    cp = pltpu.make_async_copy(
        x_hbm.at[pl.ds(i * _ROWS, _ROWS), pl.ds(j * chunk, chunk + w_pad)],
        seg_ref, sem)
    cp.start()
    cp.wait()
    # monotone f32 -> signed i32 keys (same total order as the floats).
    # NaNs of EITHER sign map to the IMAX padding sentinel: their
    # windows are overwritten with NaN below, so they need no slot in
    # the order, and sharing the sentinel makes the per-window NaN test
    # one count over the already-built mat (no extra roll per build
    # step, no XLA cumsum passes in the wrapper). No finite f32 key
    # collides with IMAX (its preimage is a NaN bit pattern).
    seg = seg_ref[...]
    u = jax.lax.bitcast_convert_type(seg, jnp.uint32)
    neg = (u >> 31) == 1
    key_u = jnp.where(neg, ~u, u | jnp.uint32(0x80000000))
    keys = jax.lax.bitcast_convert_type(
        key_u ^ jnp.uint32(0x80000000), jnp.int32)
    keys = jnp.where(seg != seg, IMAX, keys)

    def build(jj, _):
        # positive shift: pltpu.roll miscomputes NEGATIVE dynamic shifts
        # at non-power-of-two widths (observed off-by-(width-256) at 640)
        rolled = pltpu.roll(keys, (chunk + w_pad) - jj, 1)[:, :chunk]
        mat_ref[pl.ds(jj * _ROWS, _ROWS), :] = jnp.where(
            jj < window, rolled, IMAX)
        return 0

    jax.lax.fori_loop(0, w_pad, build, 0)
    mat = mat_ref[...].reshape(w_pad, _ROWS, chunk)

    k_lo = (window - 1) // 2
    k_hi = window // 2
    lo = jnp.full((_ROWS, chunk), -0x80000000, jnp.int32)
    hi = jnp.full((_ROWS, chunk), 0x7FFFFFFF, jnp.int32)

    def bis(_, lohi):
        lo, hi = lohi
        # overflow-safe midpoint of the full i32 range
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        c = jnp.sum((mat <= mid[None, :, :]).astype(jnp.int32), axis=0)
        take = c >= (k_lo + 1)
        return (jnp.where(take, lo, mid + 1), jnp.where(take, mid, hi))

    v_lo, _ = jax.lax.fori_loop(0, 32, bis, (lo, hi))
    # upper median: smallest key strictly above v_lo unless the k_hi-th
    # order statistic equals v_lo (duplicates)
    c_le = jnp.sum((mat <= v_lo[None, :, :]).astype(jnp.int32), axis=0)
    above = jnp.where(mat > v_lo[None, :, :], mat, IMAX)
    v_next = jnp.min(above, axis=0)
    v_hi = jnp.where(c_le >= k_hi + 1, v_lo, v_next)
    # jnp.median NaN semantics, fused: every window with a NaN shows
    # more IMAX entries than the (w_pad - window) padding rows alone
    c_max = jnp.sum((mat == IMAX).astype(jnp.int32), axis=0)
    has_nan = c_max > (w_pad - window)

    def tof(v_s):
        v = (jax.lax.bitcast_convert_type(v_s, jnp.uint32)
             ^ jnp.uint32(0x80000000))
        was_neg = (v >> 31) == 0
        return jax.lax.bitcast_convert_type(
            jnp.where(was_neg, ~v, v & jnp.uint32(0x7FFFFFFF)), jnp.float32)

    from comapreduce_tpu.ops.stats import _median_mid

    o_ref[...] = jnp.where(has_nan, jnp.float32(jnp.nan),
                           _median_mid(tof(v_lo), tof(v_hi)))


@functools.partial(jax.jit,
                   static_argnames=("window", "chunk", "interpret"))
def rolling_median_windows_pallas(padded: jax.Array, window: int,
                                  chunk: int = 256,
                                  interpret: bool = False) -> jax.Array:
    """``out[..., i] = median(padded[..., i : i + window])`` — exact.

    ``padded``: f32[..., P] with ``P >= T + window - 1`` for the desired
    ``T = P - window + 1`` outputs (callers do their own edge padding,
    exactly like the XLA path in ``ops/median_filter.rolling_median``).
    ``jnp.median`` NaN semantics: any NaN inside a window yields NaN.
    ``interpret=True`` runs the Pallas interpreter — the CPU parity path
    for tests.
    """
    P = padded.shape[-1]
    T = P - window + 1
    if T <= 0:
        raise ValueError(f"padded length {P} shorter than window {window}")
    if not pallas_window_ok(window):
        raise ValueError(f"window {window} beyond MAX_PALLAS_WINDOW")
    w_pad = _w_pad(window)

    def call2d_raw(x):
        R = x.shape[0]
        r_pad = -(-R // _ROWS) * _ROWS
        n_chunks = -(-T // chunk)
        p_need = n_chunks * chunk + w_pad
        x = jnp.pad(x, ((0, r_pad - R), (0, max(p_need - P, 0))))
        out = pl.pallas_call(
            functools.partial(_kernel, window=window, w_pad=w_pad,
                              chunk=chunk),
            grid=(r_pad // _ROWS, n_chunks),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((_ROWS, chunk), lambda i, j: (i, j),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((r_pad, n_chunks * chunk),
                                           jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((_ROWS, chunk + w_pad), jnp.float32),
                pltpu.VMEM((w_pad * _ROWS, chunk), jnp.int32),
                pltpu.SemaphoreType.DMA,
            ],
            interpret=interpret,
        )(x)[:R, :T]
        # jnp.median NaN semantics live INSIDE the kernel (ISSUE 11):
        # NaN keys share the IMAX padding sentinel, so the per-window
        # NaN test is one VMEM count over the window matrix — the two
        # XLA cumsum passes this wrapper used to spend are gone
        return out

    # vmapping a pallas_call with an ANY-space input is not lowerable
    # (Mosaic requires whole-array blocks with trivial index maps there);
    # rows are embarrassingly parallel, so batching folds into the row
    # axis instead — this is exactly what the reduction's scan-batch
    # vmap needs
    call2d = jax.custom_batching.custom_vmap(call2d_raw)

    @call2d.def_vmap
    def _rule(axis_size, in_batched, xb):  # noqa: ANN001
        del axis_size
        out = call2d(xb.reshape((-1, xb.shape[-1])))
        return out.reshape(xb.shape[:-1] + (T,)), True

    lead = padded.shape[:-1]
    out = call2d(padded.reshape((-1, P)))
    return out.reshape(lead + (T,))


def _fill_kernel(t_ref, m_ref, o_ref, *, L, Lp):
    """Fused ``_fill_bad`` row block: masked stride-4 median (radix
    bisection, VMEM-resident) + masked-mean fallback + select, in one
    traversal of the (8, Lp) rows."""
    IMAX = jnp.int32(0x7FFFFFFF)
    t = t_ref[...]
    m = m_ref[...]
    valid = m > 0
    lane = jax.lax.broadcasted_iota(jnp.int32, (_ROWS, Lp), 1)
    # the stride-4 subsample as a mask over the full row: same valid
    # multiset as tod[..., ::4] / mask[..., ::4], so the selected order
    # statistics (and hence the median) are bit-identical; lane < L
    # also retires the zero-padded tail
    on_grid = (lane % 4 == 0) & (lane < L)
    sub = valid & on_grid
    # monotone f32 -> signed i32 keys; invalid slots take the IMAX
    # sentinel exactly like masked_median's u32 0xFFFFFFFF (same order)
    u = jax.lax.bitcast_convert_type(t, jnp.uint32)
    neg = (u >> 31) == 1
    key_u = jnp.where(neg, ~u, u | jnp.uint32(0x80000000))
    keys = jnp.where(sub, jax.lax.bitcast_convert_type(
        key_u ^ jnp.uint32(0x80000000), jnp.int32), IMAX)
    cnt_sub = jnp.sum(sub.astype(jnp.int32), axis=1, keepdims=True)
    k_lo = (jnp.maximum(cnt_sub, 1) - 1) // 2
    k_hi = jnp.maximum(cnt_sub, 1) // 2
    lo = jnp.full((_ROWS, 1), -0x80000000, jnp.int32)
    hi = jnp.full((_ROWS, 1), 0x7FFFFFFF, jnp.int32)

    def bis(_, lohi):
        lo, hi = lohi
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        c = jnp.sum((keys <= mid).astype(jnp.int32), axis=1,
                    keepdims=True)
        take = c >= (k_lo + 1)
        return (jnp.where(take, lo, mid + 1), jnp.where(take, mid, hi))

    v_lo, _ = jax.lax.fori_loop(0, 32, bis, (lo, hi))
    c_le = jnp.sum((keys <= v_lo).astype(jnp.int32), axis=1,
                   keepdims=True)
    above = jnp.where(keys > v_lo, keys, IMAX)
    v_next = jnp.min(above, axis=1, keepdims=True)
    v_hi = jnp.where(c_le >= k_hi + 1, v_lo, v_next)

    def tof(v_s):
        v = (jax.lax.bitcast_convert_type(v_s, jnp.uint32)
             ^ jnp.uint32(0x80000000))
        was_neg = (v >> 31) == 0
        return jax.lax.bitcast_convert_type(
            jnp.where(was_neg, ~v, v & jnp.uint32(0x7FFFFFFF)),
            jnp.float32)

    from comapreduce_tpu.ops.stats import _median_mid

    med = jnp.where(cnt_sub > 0, _median_mid(tof(v_lo), tof(v_hi)), 0.0)
    # _fill_bad's fallback test is the FLOAT mask sum on the stride
    # grid (not the >0 count) and the full-length masked mean — both
    # formulas verbatim so the fallback branch is taken identically
    sub_f = jnp.sum(jnp.where(on_grid, m, 0.0), axis=1, keepdims=True)
    cnt_f = jnp.sum(m, axis=1, keepdims=True)
    mean = (jnp.sum(t * m, axis=1, keepdims=True)
            / jnp.maximum(cnt_f, 1.0))
    fill = jnp.where(sub_f > 0, med, mean)
    o_ref[...] = jnp.where(valid, t, fill)


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_fill_pallas(tod: jax.Array, mask: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """``ops/reduce._fill_bad`` fused into one Mosaic kernel (ISSUE 11):
    ``where(mask > 0, tod, fill)`` with ``fill`` the masked median of
    the stride-4 subsample (masked-mean fallback when that subsample is
    empty), one HBM read of tod + mask per row block.

    Semantics are those of the XLA ``_fill_bad``: the median is an
    exact order-statistic selection (radix bisection on monotone keys —
    the same multiset, so bit-identically the same f32 element), the
    fallback test and masked mean use the identical formulas, masked-in
    samples (including NaN) pass through untouched and masked-out NaNs
    are replaced by the fill. Two documented divergences: (1) the
    masked-MEAN fallback (stride-4 subsample empty, mask non-empty)
    sums over the kernel's zero-padded 128-lane rows, so at unaligned
    ``L`` its f32 sum may reassociate a couple of ulp away from the
    unpadded XLA reduce — the median path, which every realistic row
    takes, stays bitwise; (2) a masked-IN **negative** NaN orders below
    -inf here (monotone-key order) while the narrow-row XLA sort branch
    sorts every NaN last — upstream ``nan_to_mask`` makes that
    configuration unreachable.

    ``interpret=True`` runs the Pallas interpreter — the CPU parity
    path for tests and the ``bench.py --config kernels`` A/B.
    """
    lead = tod.shape[:-1]
    L = tod.shape[-1]
    if not pallas_fill_ok(L):
        raise ValueError(f"row length {L} beyond MAX_PALLAS_FILL_LEN")
    Lp = -(-L // 128) * 128

    def call2d_raw(t2, m2):
        R = t2.shape[0]
        r_pad = -(-max(R, 1) // _ROWS) * _ROWS
        t2 = jnp.pad(t2, ((0, r_pad - R), (0, Lp - L)))
        m2 = jnp.pad(m2, ((0, r_pad - R), (0, Lp - L)))
        spec = pl.BlockSpec((_ROWS, Lp), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            functools.partial(_fill_kernel, L=L, Lp=Lp),
            grid=(r_pad // _ROWS,),
            in_specs=[spec, spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((r_pad, Lp), jnp.float32),
            interpret=interpret,
        )(t2, m2)
        return out[:R, :L]

    # batching folds into the row axis (same rationale as the rolling
    # median above: rows are embarrassingly parallel and the scan-batch
    # vmap must not try to vmap the pallas_call itself)
    call2d = jax.custom_batching.custom_vmap(call2d_raw)

    @call2d.def_vmap
    def _rule(axis_size, in_batched, tb, mb):  # noqa: ANN001
        del axis_size, in_batched
        out = call2d(tb.reshape((-1, tb.shape[-1])),
                     mb.reshape((-1, mb.shape[-1])))
        return out.reshape(tb.shape), True

    t = tod.astype(jnp.float32).reshape((-1, L))
    m = mask.astype(jnp.float32).reshape((-1, L))
    return call2d(t, m).reshape(lead + (L,))


def masked_fill_logical_passes(shape: tuple[int, ...]) -> float:
    """Logical-HBM-pass accounting for :func:`masked_fill_pallas` on a
    ``shape`` TOD block, in units of the block's own bytes — the
    machine-independent number the compile-inspection budget test and
    the ``check_perf.py`` kernel gate pin.

    The kernel's HBM traffic is read(tod) + read(mask) + write(out) on
    the (row, lane)-padded image; when padding is needed the XLA-side
    pad copies (read + padded write per input, padded read + write for
    the output slice) are charged too. No measurement is involved: the
    count follows from the kernel's block plan by construction, which
    is what makes it pinnable on a CPU-only CI host where the Mosaic
    body cannot be compiled."""
    L = int(shape[-1])
    R = 1
    for d in shape[:-1]:
        R *= int(d)
    Lp = -(-L // 128) * 128
    r_pad = -(-max(R, 1) // _ROWS) * _ROWS
    ratio = (r_pad * Lp) / float(max(R * L, 1))
    passes = 3.0 * ratio
    if ratio != 1.0:
        # two input pad copies (read unpadded + write padded) and the
        # output slice (read padded + write unpadded)
        passes += 2.0 * (1.0 + ratio) + (ratio + 1.0)
    return passes
