"""Exact rolling median on TPU.

The reference's sliding median is a sequential dual-heap C++ ``Mediator``
(``Tools/median_filter/Mediator.h:36-60``, ``medianFilter.cpp:4-30``) — an
inherently serial O(T log w) algorithm that cannot map to the MXU/VPU. The
TPU-native formulation trades FLOPs for parallelism: materialise windows in
fixed-size output chunks via gather and take a vectorised median (sort) per
window, streamed with ``lax.map`` so peak memory stays bounded at
``chunk * window`` floats per batch row. Exact (same values as an exact
rolling median), fully jittable, and fast because sort is vectorised 8x128.

Window alignment matches the reference pipeline's use: a *centered* window
with edge handling done by the caller (the gain path reflect-pads 3x and
keeps the centre third, ``Level1Averaging.py:696-700``), so the pad mode
here (edge-replicate) only affects standalone use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# sort-vs-radix median crossover: one shared knob (see stats.py). At the
# two-level filter's 500-sample block window the sort path measured
# 3.01 s -> 2.75 s whole-program vs radix.
from comapreduce_tpu.ops.stats import (
    SELECT_MEDIAN_MIN_WINDOW as _SELECT_MEDIAN_MIN_WINDOW,
    median_lastaxis)

__all__ = ["rolling_median", "medfilt_highpass"]

# windows at least this wide use the Pallas in-VMEM selection kernel on
# TPU backends (ops/pallas_median.py); narrower windows keep the XLA
# sort, whose mats are small enough not to matter
_SELECT_MEDIAN_MIN_PALLAS = 65


# Windows above this switch to the two-level block-median filter (see
# rolling_median): block medians of ``stride = ceil(window/512)`` samples,
# then an exact rolling median over the block series. Measured error at the
# production 6000-sample window is ~2.5% rms of the local white noise
# (tests/test_medfilt_parity.py), while the windowed sort — the
# reduction's costliest op — does ~stride x less work than exact.
MAX_EXACT_WINDOW = 512


@functools.partial(jax.jit,
                   static_argnames=("window", "chunk", "stride", "pad_mode"))
def rolling_median(x: jax.Array, window: int, chunk: int = 256,
                   stride: int | None = None,
                   pad_mode: str = "edge",
                   fold_len: jax.Array | None = None) -> jax.Array:
    """Centered rolling median along the last axis, edge-replicate padded.

    ``x``: f32[..., T]; ``window`` static. Output[..., i] is the median of
    ``x[..., i-(w-1)//2 : i+w//2]`` with out-of-range samples replaced by the
    edge value — the streaming equivalent of the C++ ``Mediator`` filter's
    interior behavior.

    ``fold_len``: optional DYNAMIC (traced i32 scalar) boundary for the
    symmetric reflection — window samples are gathered through a
    symmetric fold into ``[0, fold_len)`` instead of reflecting at the
    static block end ``T``. This is the campaign shape-canonicalisation
    hook (docs/OPERATIONS.md §9): a scan block padded from its per-file
    length ``L_raw`` up to a bucket length ``Lb`` filters bit-identically
    to the unpadded block when ``fold_len = L_raw``, because the fold is
    a VALUE, not a shape — one compiled program serves every file in the
    bucket. Requires ``pad_mode='symmetric'``; equals the static pad
    exactly when ``fold_len == T``.

    ``stride``: approximation/performance knob. ``stride=1`` is exact;
    ``None`` picks ``ceil(window / MAX_EXACT_WINDOW)`` — exact up to
    ``MAX_EXACT_WINDOW`` (512) window samples. Beyond that the filter runs
    two-level: per-block medians of ``stride`` consecutive samples
    (vectorised reshape + sort), then an EXACT rolling median over the
    block-median series, upsampled back to per-sample outputs. Unlike a
    strided subsample this uses every window sample, so at the production
    6000-sample window the error vs the exact filter is a couple of
    percent of the local white noise (quantified in
    ``tests/test_medfilt_parity.py``), while the sort work *drops* by
    ~stride x: (T/stride) outputs x (window/stride) block medians. The
    output is piecewise-constant over runs of ``stride`` samples — a
    sub-sample quantisation of a 2-minute baseline estimator.

    ``pad_mode``: boundary handling, 'edge' (replicate) or 'symmetric'
    (mirror). 'symmetric' equals the reference gain path's explicit
    [reversed | x | reversed] 3x padding (``Level1Averaging.py:696-700``)
    without computing the discarded two thirds.
    """
    if window <= 1:
        return x
    if stride is None:
        stride = -(-window // MAX_EXACT_WINDOW)
    stride = max(int(stride), 1)
    T = x.shape[-1]
    left = (window - 1) // 2
    right = window - 1 - left
    if fold_len is not None:
        if pad_mode != "symmetric":
            raise ValueError("fold_len requires pad_mode='symmetric'")
        # symmetric reflection at the DYNAMIC boundary: position i of the
        # padded series reads x[fold(i)] with the period-2n fold
        # (..., x1, x0 | x0, x1, ..., x_{n-1} | x_{n-1}, ...) — numpy's
        # 'symmetric' rule at n = fold_len, multi-reflection included
        n = jnp.asarray(fold_len, jnp.int32)
        pos = jnp.arange(T + window - 1, dtype=jnp.int32) - left
        m = jnp.mod(pos, 2 * n)
        src = jnp.clip(jnp.where(m < n, m, 2 * n - 1 - m), 0, T - 1)
        padded = jnp.take(x, src, axis=-1, mode="clip")
    else:
        pad_width = [(0, 0)] * (x.ndim - 1) + [(left, right)]
        padded = jnp.pad(x, pad_width, mode=pad_mode)

    if stride > 1:
        # two-level median: decimate by block medians, exact rolling
        # median over the block series, upsample by gather
        P0 = T + window - 1
        nblocks = -(-P0 // stride)
        padded = jnp.pad(padded, [(0, 0)] * (x.ndim - 1)
                         + [(0, nblocks * stride - P0)], mode="edge")
        # flatten batch x blocks into one big row axis for the sort: tiny
        # trailing batch dims otherwise end up in the vector lanes
        bm = jnp.median(
            padded.reshape((-1, stride)), axis=-1
        ).reshape(x.shape[:-1] + (nblocks,))
        # recurse with stride=None so an explicitly oversized stride (e.g.
        # stride=2 at window=6000 -> block window 3000) re-splits instead
        # of running an exact rolling median far above MAX_EXACT_WINDOW;
        # for the default stride the block window is <= MAX_EXACT_WINDOW
        # and this resolves to the exact filter either way
        wb = max(window // stride, 1)
        rm_b = rolling_median(bm, wb, chunk=chunk, stride=None,
                              pad_mode="edge")
        # sample i's window is padded[i : i+window]; its centre block
        j = jnp.clip((jnp.arange(T) + left) // stride, 0, nblocks - 1)
        return rm_b[..., j]

    if window >= _SELECT_MEDIAN_MIN_PALLAS and x.dtype == jnp.float32:
        from comapreduce_tpu.ops.pallas_median import (
            pallas_supported, pallas_window_ok,
            rolling_median_windows_pallas)
        if pallas_window_ok(window) and pallas_supported():
            # windowed selection entirely in VMEM (Mosaic kernel): no
            # HBM window mats, no layout copies — bit-identical output
            # (including NaN-in-window -> NaN). ``pallas_supported()``
            # gates at TRACE time: current jax lowers EVERY
            # ``platform_dependent`` branch, so on a CPU-only host an
            # unlowerable Mosaic kernel in the unselected branch still
            # breaks CPU lowering — keep it out of the jaxpr entirely.
            # Residual limitation: on a TPU-default host a CPU-placed
            # trace of this window still embeds the kernel and fails to
            # lower (pre-existing; per-placement selection needs a
            # lowering-time gate jax no longer offers). 'axon' is the
            # tunnelled-TPU platform name.
            def _pallas(p):
                return rolling_median_windows_pallas(
                    p, window, chunk=-(-max(chunk, 128) // 128) * 128)

            return jax.lax.platform_dependent(
                padded, tpu=_pallas, axon=_pallas,
                default=functools.partial(_rolling_median_xla,
                                          window=window, chunk=chunk, T=T))

    return _rolling_median_xla(padded, window=window, chunk=chunk, T=T)


def _rolling_median_xla(padded: jax.Array, *, window: int, chunk: int,
                        T: int) -> jax.Array:
    """Generic XLA rolling-median path over pre-padded input (window mats
    per chunk + radix/sort median) — the non-Mosaic branch of
    :func:`rolling_median`."""
    n_chunks = -(-T // chunk)
    total = n_chunks * chunk
    seg_len = chunk + window - 1
    # pad tail so every chunk slice is full-size (values unused past T)
    padded = jnp.pad(padded, [(0, 0)] * (padded.ndim - 1)
                     + [(0, total - T)], mode="edge")
    win_idx = (jnp.arange(chunk)[:, None] + jnp.arange(window)[None, :])

    med_fn = (median_lastaxis if window >= _SELECT_MEDIAN_MIN_WINDOW
              else functools.partial(jnp.median, axis=-1))

    def body(ci):
        seg = lax.dynamic_slice_in_dim(padded, ci * chunk, seg_len,
                                       axis=-1)
        mat = seg[..., win_idx]            # (..., chunk, window)
        lead = mat.shape[:-1]
        # flatten every leading dim: the radix/sort passes then tile as
        # (rows, window) with both dims large — small batch dims (e.g.
        # (scans, bands) under vmap) in the minor positions otherwise
        # waste most of each 8x128 vector tile (profiled ~2x op time)
        return med_fn(mat.reshape((-1, window))).reshape(lead)

    out = lax.map(body, jnp.arange(n_chunks))  # (n_chunks, ..., chunk)
    out = jnp.moveaxis(out, 0, -2)             # (..., n_chunks, chunk)
    out = out.reshape(padded.shape[:-1] + (total,))
    return out[..., :T]


def _reflect3(x: jax.Array) -> jax.Array:
    """[x reversed | x | x reversed] along the last axis
    (``Level1Averaging.py:696-699``)."""
    rev = jnp.flip(x, axis=-1)
    return jnp.concatenate([rev, x, rev], axis=-1)


@functools.partial(jax.jit, static_argnames=("window", "chunk", "stride"))
def medfilt_highpass(tod: jax.Array, channel_mask: jax.Array, window: int,
                     chunk: int = 256, time_mask: jax.Array | None = None,
                     stride: int | None = None,
                     fold_len: jax.Array | None = None):
    """Median-filter high-pass of a (B, C, T) block, reference semantics.

    Per band (``Level1Averaging.py:681-708``):
      1. mean over the selected channels -> mean_tod(T);
      2. reflect-pad 3x, rolling median of ``window``, keep centre third;
      3. per channel, least-squares fit ``tod_c ~ a + b * medfilt`` and
       subtract the fitted affine model.

    ``channel_mask``: f32[B, C] (1 = channel used; edges/centre excluded by
    the caller). ``time_mask``: optional f32[T] — padded/invalid samples are
    excluded from the regression moments so short scan blocks aren't biased
    by their padding. ``stride``: forwarded to :func:`rolling_median` —
    ``1`` forces the exact filter at any window, ``None`` uses the
    two-level block-median filter beyond ``MAX_EXACT_WINDOW``.
    ``fold_len``: optional dynamic reflection boundary (traced i32
    scalar) forwarded to :func:`rolling_median` — the campaign padding
    hook: a block padded past its per-file length filters identically to
    the unpadded block when ``fold_len`` carries that length.

    Returns ``(filtered, medfilt_tod)`` where ``filtered`` is (B, C, T)
    with excluded channels zeroed and ``medfilt_tod`` is (B, T). Batch
    axes may precede B.
    """
    cm = channel_mask[..., :, :, None]  # (B, C, 1)
    nch = jnp.maximum(jnp.sum(channel_mask, axis=-1), 1.0)[..., :, None]
    mean_tod = jnp.sum(tod * cm, axis=-2) / nch  # (..., B, T)

    T = tod.shape[-1]
    if window < 2 * T:
        # symmetric boundary = the reference's 3x reflect padding without
        # computing the discarded outer thirds (3x less sort work)
        med = rolling_median(mean_tod, window, chunk=chunk,
                             stride=stride, pad_mode="symmetric",
                             fold_len=fold_len)
    else:
        if fold_len is not None:
            raise NotImplementedError(
                "fold_len with window >= 2T (the 3x-reflect branch) is "
                "unused: the reduction clamps its window to the unpadded "
                "block length")
        padded = _reflect3(mean_tod)
        med = rolling_median(padded, window, chunk=chunk,
                             stride=stride)[..., T:2 * T]

    # per-channel affine regression against the filter output, centered for
    # f32 stability; masked in time when a validity mask is supplied
    mt = med
    if time_mask is None:
        tm = jnp.ones(tod.shape[-1:], tod.dtype)
    else:
        tm = time_mask
    n_t = jnp.maximum(jnp.sum(tm, axis=-1), 1.0)
    m_mean = (jnp.sum(mt * tm, axis=-1) / n_t)[..., None]   # (..., B, 1)
    d_mean = jnp.sum(tod * tm, axis=-1) / n_t[..., None]    # (..., B, C)
    dm = (mt - m_mean) * tm
    smm = jnp.sum(dm * dm, axis=-1)                         # (..., B)
    smd = jnp.einsum("...bt,...bct->...bc", dm, tod)  # dm is masked &
    # zero-mean over the mask, so centering tod as well would be a no-op
    safe = jnp.where(smm > 1e-20, smm, 1.0)
    b = jnp.where(smm[..., None] > 1e-20, smd / safe[..., None], 0.0)
    a = d_mean - b * m_mean[..., 0][..., None]
    model = a[..., None] + b[..., None] * mt[..., None, :]
    filtered = (tod - model) * cm
    return filtered, med
