"""Gain-fluctuation solver: the Level-1 -> Level-2 hot kernel.

Model (reference ``Analysis/GainSubtraction.py``): the normalised TOD
``y(c, t)`` over the stacked band-channel axis ``c in [0, BC)`` contains a
common-mode relative gain fluctuation ``dg(t)`` plus sky/atmosphere drifts
that project onto per-channel templates. With

  T = [1/Tsys(c), nu_scaled(c)/Tsys(c)]   (the "signal" templates, BC x 2)
  p = 1(c) (masked)                       (the gain template, BC)

the estimator solves the normal equations ``(P^T Z P) g = P^T Z y`` where
``Z = I - T (T^T T)^{-1} T^T`` projects the signal templates out of each
time step and ``P`` stretches ``g(t)`` across channels by ``p``
(``GainSubtraction.py:27-78,129-168``).

TPU-native formulation: every operator application is a (BC x k) matmul
batched over time — pure MXU work. ``Z P g`` collapses algebraically:

  A g = (p^T Z p) * g     —  because Z is a fixed projector and P acts
                             per-time-step, A is DIAGONAL with the scalar
                             ``zpp = p^T Z p`` on valid samples.

The reference solves this diagonal system with scipy CG without exploiting
the structure; we compute the closed form directly (one pass, no iterations)
and keep a CG fallback (`solve_gain_cg`) for the optional circulant 1/f
prior, where A = diag + C^{-1} is genuinely non-diagonal
(``GainSubtraction.py:97-113``). With the prior, the matvec is an FFT scale
— also ideal TPU work.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["build_templates", "gain_projector", "solve_gain",
           "solve_gain_cg", "subtract_gain"]


def build_templates(system_temperature: jax.Array, frequency_scaled: jax.Array,
                    channel_mask: jax.Array):
    """Templates (T, p) from per-channel Tsys.

    ``system_temperature``: f32[B, C]; ``frequency_scaled``: f32[B, C]
    ((nu - nu0)/nu0); ``channel_mask``: f32[B, C] with edge/centre channels
    zeroed (``GainSubtraction.py:185-201``). Returns ``(T2, p)`` with
    ``T2``: f32[BC, 2] and ``p``: f32[BC].
    """
    tsys = system_temperature
    ok = (tsys > 0) & (channel_mask > 0) & jnp.isfinite(tsys)
    inv_t = jnp.where(ok, 1.0 / jnp.where(ok, tsys, 1.0), 0.0)
    t0 = inv_t
    t1 = frequency_scaled * inv_t
    p = ok.astype(tsys.dtype)
    T2 = jnp.stack([t0.reshape(-1), t1.reshape(-1)], axis=-1)
    return T2, p.reshape(-1)


def gain_projector(T2: jax.Array, p: jax.Array):
    """Precompute Z-projection pieces: returns ``(G_inv, zp, zpp)`` where
    ``G_inv = (T^T T)^{-1}`` (2x2), ``zp = Z p`` (BC), ``zpp = p^T Z p``."""
    G = T2.T @ T2  # (2, 2)
    # guard singular Gram (all-masked): fall back to identity
    det = G[0, 0] * G[1, 1] - G[0, 1] * G[1, 0]
    ok = jnp.abs(det) > 1e-30
    G = jnp.where(ok, G, jnp.eye(2, dtype=T2.dtype))
    G_inv = jnp.linalg.inv(G)
    zp = p - T2 @ (G_inv @ (T2.T @ p))
    zpp = p @ zp
    return G_inv, zp, zpp


def solve_gain(y: jax.Array, T2: jax.Array, p: jax.Array,
               time_mask: jax.Array | None = None):
    """Closed-form solve of ``(P^T Z P) g = P^T Z y``.

    ``y``: f32[BC, t] — or unflattened f32[B, C, t]; passing the natural
    (B, C, t) block avoids a full-size layout-changing reshape copy (the
    channel axes are contracted in place). Returns ``dg``: f32[t]. Exact
    solution of the reference's CG system (diagonal A), at one matmul's
    cost.
    """
    G_inv, zp, zpp = gain_projector(T2, p)
    if y.ndim > 2:
        # p^T Z y contracting every leading (channel) axis in place
        lead = list(range(y.ndim - 1))
        b = jnp.einsum(zp.reshape(y.shape[:-1]), lead, y,
                       lead + [y.ndim - 1], [y.ndim - 1])
    else:
        b = zp @ y  # (t,) == p^T Z y since Z is symmetric idempotent
    dg = b / jnp.maximum(zpp, 1e-20)
    if time_mask is not None:
        dg = dg * time_mask
    return dg


def _prior_inv_ps(n: int, white_noise, fknee, alpha, sample_rate=50.0):
    """1/PSD of the 1/f prior on the rfft grid
    (``GainSubtraction.py:80-95``)."""
    freqs = jnp.fft.rfftfreq(n, d=1.0 / sample_rate)
    f1 = freqs.at[0].set(freqs[1])
    ps = white_noise**2 * jnp.abs(f1 / fknee) ** alpha
    return 1.0 / jnp.maximum(ps, 1e-30)


@functools.partial(jax.jit, static_argnames=("n_iter", "use_prior"))
def solve_gain_cg(y: jax.Array, T2: jax.Array, p: jax.Array,
                  white_noise=1.0, fknee=1.0, alpha=-1.0,
                  time_mask: jax.Array | None = None,
                  n_iter: int = 50, use_prior: bool = True):
    """CG solve of ``(P^T Z P + C^{-1}) g = P^T Z y`` with the circulant 1/f
    prior applied in rfft space (``GainSubtraction.py:97-142``).

    Matvec = diagonal term + irfft(rfft(g)/PSD): O(t log t), XLA-fused.
    """
    G_inv, zp, zpp = gain_projector(T2, p)
    n = y.shape[-1]
    b = zp @ y
    if time_mask is not None:
        b = b * time_mask

    inv_ps = _prior_inv_ps(n, white_noise, fknee, alpha)

    def matvec(g):
        out = zpp * g
        if use_prior:
            out = out + jnp.fft.irfft(jnp.fft.rfft(g) * inv_ps, n=n)
        if time_mask is not None:
            out = out * time_mask
        return out

    dg, _ = jax.scipy.sparse.linalg.cg(matvec, b, maxiter=n_iter)
    if time_mask is not None:
        dg = dg * time_mask
    return dg


def subtract_gain(y: jax.Array, dg: jax.Array, p: jax.Array):
    """Remove the common-mode gain: ``y - p(c) dg(t)``.

    The reference subtracts ``dg`` from every channel unweighted
    (``Level1Averaging.py:850``); using the masked gain template ``p`` keeps
    excluded channels untouched (they are zeroed anyway).
    """
    return y - p[:, None] * dg[None, :]
