"""Device-side TOD kernels (JAX/XLA/Pallas).

Each module here is the TPU-native re-design of one hot-path component of the
reference pipeline (see SURVEY.md §2.2/§2.4). The ops are pure functions over
dense arrays + validity masks — no data-dependent Python control flow — so
everything composes under ``jax.jit``/``vmap``/``shard_map``.
"""

from comapreduce_tpu.ops import (  # noqa: F401
    atmosphere,
    average,
    gain,
    median_filter,
    power,
    reduce,
    stats,
    vane,
)
from comapreduce_tpu.ops.stats import (  # noqa: F401
    auto_rms,
    mad,
    masked_mean,
    masked_median,
    masked_std,
    nan_to_mask,
    normalise,
    tsys_rms,
    weighted_mean,
    weighted_var,
)
