"""The closed-loop control plane: sensors to actions.

Everything below the pipeline is a sensor — live ``/metrics``
(:mod:`~comapreduce_tpu.telemetry.live`), per-rank heartbeats, the
quarantine and data-quality ledgers with their SLO rules, per-solve
convergence traces, and the lease-based elastic work queue. This
package is the actuator side: three independent control loops, each
drillable on its own with :class:`~comapreduce_tpu.resilience.chaos
.ChaosMonkey`, each auditable through ``control.decision`` telemetry
events and the ``decisions.*.jsonl`` ledger.

- :mod:`~comapreduce_tpu.control.supervisor` /
  :mod:`~comapreduce_tpu.control.autoscaler` — the campaign
  supervisor: watches queue depth (``queue.json`` + lease states),
  rank liveness (the CHANGE-based
  :class:`~comapreduce_tpu.resilience.heartbeat.HeartbeatWatch` rule)
  and measured throughput, and decides when to spawn replacement or
  additional elastic ranks (:mod:`~comapreduce_tpu.control.manager`
  actually forks and reaps them) and when to retire idle ones.
- :mod:`~comapreduce_tpu.control.admission` — SLO-pressure admission
  control: sheds quality-flagged files while the queue backlog sits
  above the high-water mark, every shed ledgered ``deferred`` and
  re-admitted when pressure clears — shed, never dropped (the
  automatic version of the manual ``[slo] exclude_flagged`` knob).
- :mod:`~comapreduce_tpu.control.policy` — the solver policy engine:
  picks ``preconditioner``/``mg_block``/``pair_batch`` per shape
  bucket from the solver traces, ``solver_report --registry`` deltas
  and the ``programs.jsonl`` cost model instead of static config.

All three loops are OFF by default: ``[control]`` absent is
byte-for-byte the uncontrolled pipeline (docs/OPERATIONS.md §19).
"""

from comapreduce_tpu.control.config import ControlConfig
from comapreduce_tpu.control.decisions import (DECISION_SCHEMA,
                                               decisions_paths,
                                               read_decisions,
                                               record_decision)

__all__ = ["ControlConfig", "DECISION_SCHEMA", "decisions_paths",
           "read_decisions", "record_decision"]
