"""SLO-driven admission control: shed flagged files under pressure.

The manual knob already exists: ``[slo] exclude_flagged`` drops
quality-flagged files from a destriper filelist up front. This loop is
its automatic, reversible form for live campaigns: while the queue
backlog sits above ``shed_high_water``, files whose latest
data-quality record is FLAGGED (``telemetry/quality.py`` SLO rules)
are deferred — claim released, one ``deferred`` line in the
quarantine ledger, one ``defer`` decision event — so the healthy bulk
of the queue drains first. When backlog falls to ``shed_low_water``
(hysteresis against flapping) or nothing but deferred work remains,
the scheduler re-admits every shed unit (``readmitted`` ledger line).
A shed file is therefore delayed, never dropped: the final map sees
every unit exactly once either way, and turning the loop off
reproduces the uncontrolled schedule byte-for-byte.

The controller is consumed by
:class:`~comapreduce_tpu.pipeline.scheduler.Scheduler` through two
duck-typed calls — ``should_defer(filename, backlog)`` on every
just-claimed unit and ``pressure_cleared(backlog)`` before each
re-admission pass — so the scheduler never imports this package.
"""

from __future__ import annotations

import logging
import os
import time

from comapreduce_tpu.control.config import ControlConfig
from comapreduce_tpu.control.decisions import record_decision

__all__ = ["AdmissionController"]

logger = logging.getLogger("comapreduce_tpu")

# re-scan the quality ledger for newly-flagged files at most this
# often: flags arrive at file-completion rate, not claim rate
_FLAGGED_REFRESH_S = 2.0


class AdmissionController:
    """One rank's admission gate (state in memory, evidence on disk).

    ``flagged`` (optional) pins the flagged set for tests; the default
    reads :func:`~comapreduce_tpu.telemetry.quality.flagged_files`
    from the state directory's quality ledgers, refreshed at most
    every couple of seconds.
    """

    def __init__(self, config: ControlConfig, state_dir: str,
                 rank: int = 0, flagged=None, clock=time.monotonic):
        self.cfg = ControlConfig.coerce(config)
        self.state_dir = state_dir or "."
        self.rank = int(rank)
        self.clock = clock
        self._writer = f"rank{self.rank}"
        self._pinned = frozenset(os.path.basename(f) for f in flagged) \
            if flagged is not None else None
        self._flagged: frozenset = self._pinned or frozenset()
        self._flagged_t: float | None = None
        self.shedding = False

    # -- sensors -------------------------------------------------------------
    def flagged_files(self) -> frozenset:
        if self._pinned is not None:
            return self._pinned
        now = self.clock()
        if self._flagged_t is None \
                or now - self._flagged_t >= _FLAGGED_REFRESH_S:
            from comapreduce_tpu.telemetry.quality import flagged_files

            try:
                self._flagged = frozenset(flagged_files(self.state_dir))
            except Exception:  # a torn ledger must not stop admission
                logger.exception("admission: flagged-file scan failed")
            self._flagged_t = now
        return self._flagged

    def _update_pressure(self, backlog: int) -> None:
        cfg = self.cfg
        if not self.shedding and backlog >= cfg.shed_high_water:
            self.shedding = True
            record_decision(
                self.state_dir, "admission", "shed_on",
                f"backlog {backlog} >= shed_high_water="
                f"{cfg.shed_high_water}; deferring flagged files",
                writer=self._writer, rank=self.rank, backlog=backlog)
        elif self.shedding and backlog <= cfg.shed_low_water:
            self.shedding = False
            record_decision(
                self.state_dir, "admission", "shed_off",
                f"backlog {backlog} <= shed_low_water="
                f"{cfg.shed_low_water}; re-admitting deferred files",
                writer=self._writer, rank=self.rank, backlog=backlog)

    # -- the scheduler-facing gate -------------------------------------------
    def should_defer(self, filename: str, backlog: int) -> str | None:
        """Defer reason for a just-claimed unit, or None to admit.
        Only quality-FLAGGED files are ever shed — admission pressure
        never touches healthy data."""
        self._update_pressure(int(backlog))
        if not self.shedding:
            return None
        base = os.path.basename(filename)
        if base not in self.flagged_files():
            return None
        reason = (f"backlog {backlog} above shed water mark and "
                  f"{base} is SLO-flagged; deferred until pressure "
                  f"clears")
        record_decision(self.state_dir, "admission", "defer", reason,
                        writer=self._writer, rank=self.rank,
                        file=base, backlog=int(backlog))
        return reason

    def pressure_cleared(self, backlog: int) -> bool:
        """True when deferred units may re-enter the queue."""
        self._update_pressure(int(backlog))
        return not self.shedding
