"""The solver policy engine: evidence-driven solver knobs.

Static config picks ``[Destriper] preconditioner`` / ``mg_block`` /
``pair_batch`` once, for every shape the campaign will ever see. This
loop picks them from evidence instead:

- the run's own **solver traces** (``solver.rank*.jsonl``, the same
  records ``tools/solver_report.py`` renders): per-preconditioner-rung
  iteration counts and convergence/stall/divergence verdicts;
- the **run registry delta** (what ``solver_report --registry``
  prints): this run's mean iterations against the trailing-window
  median of the ``*cg_iters*`` registry metrics — a rung suddenly
  needing ``ESCALATE_RATIO`` times its historical iterations gets
  escalated one rung up the ladder before it shows up in wall clocks;
- the **program cost model** (``programs.jsonl``): XLA's per-shape-
  bucket temp-memory counts — a bucket whose pair-reduce scratch
  blows the HBM budget halves ``pair_batch`` for the next solves.

Every override is recorded as an auditable ``control.decision`` event
(loop ``solver``, action ``override``, carrying the knob, old and new
values, and the evidence in the reason). No evidence → no overrides:
the static config stands, byte-for-byte.
"""

from __future__ import annotations

import logging

from comapreduce_tpu.control.decisions import record_decision

__all__ = ["ESCALATE_RATIO", "PAIR_TEMP_BUDGET", "RUNG_ORDER",
           "choose_solver", "rung_health"]

logger = logging.getLogger("comapreduce_tpu")

#: the preconditioner ladder, weakest to strongest — mirrors
#: mapmaking.destriper.CONFIG_PRECONDITIONERS (asserted in tests so
#: the two homes cannot drift)
RUNG_ORDER = ("none", "jacobi", "twolevel", "multigrid")

#: registry-delta ratio at which a rung is escalated one step
ESCALATE_RATIO = 1.5

#: per-program temp-bytes budget beyond which pair_batch halves (XLA
#: buffer-assignment scratch for one pair-reduce bucket; ~a quarter of
#: a v4 chip's HBM — past this the batch risks an OOM retrace spiral)
PAIR_TEMP_BUDGET = 2 << 30


def rung_health(records: list, bucket: str = "") -> dict:
    """Fold solver-trace records into per-preconditioner-rung health:
    ``{rung: {"solves", "iters", "converged", "stalled", "diverged"}}``
    — the same rung key ``tools/solver_report.py`` aggregates by (the
    first ``|`` segment of ``precond_id``).

    ``bucket`` restricts the fold to solves whose stamped shape-bucket
    id starts with the given prefix (ISSUE 20: per-shape-bucket rungs —
    ``"L=50"`` matches every ``"L=50|N=..."`` stamp). Records without
    a stamp only count under the unrestricted fold, so evidence from
    one geometry never argues a rung for another."""
    out: dict = {}
    for rec in records:
        if rec.get("kind") != "solve":
            continue
        if bucket and not str(rec.get("bucket") or "").startswith(
                str(bucket)):
            continue
        rung = str(rec.get("precond_id") or "").split("|")[0]
        if not rung:
            continue
        agg = out.setdefault(rung, {"solves": 0, "iters": 0,
                                    "converged": 0, "stalled": 0,
                                    "diverged": 0})
        agg["solves"] += 1
        agg["iters"] += int(rec.get("n_iter") or 0)
        agg["converged"] += int(bool(rec.get("converged")))
        agg["stalled"] += int(bool(rec.get("stalled")))
        agg["diverged"] += int(bool(rec.get("diverged")))
    return out


def _registry_worst_ratio(records: list, registry_path: str,
                          window: int) -> float | None:
    """max over ``*cg_iters*`` registry metrics of (this run's mean
    solve iterations) / (trailing-window median) — the
    ``solver_report --registry`` delta, as one number."""
    from comapreduce_tpu.telemetry.registry import read_runs

    solves = [r for r in records if r.get("kind") == "solve"]
    if not solves:
        return None
    cur = sum(int(r.get("n_iter") or 0) for r in solves) / len(solves)
    hist: dict = {}
    for run in read_runs(registry_path)[-window:]:
        for k, v in (run.get("metrics") or {}).items():
            if "cg_iters" in k and isinstance(v, (int, float)):
                hist.setdefault(k, []).append(float(v))
    worst = None
    for vals in hist.values():
        vals = sorted(vals)
        med = vals[len(vals) // 2]
        if med:
            ratio = cur / med
            worst = ratio if worst is None else max(worst, ratio)
    return worst


def _escalate(rung: str) -> str | None:
    try:
        i = RUNG_ORDER.index(rung)
    except ValueError:
        return None
    return RUNG_ORDER[i + 1] if i + 1 < len(RUNG_ORDER) else None


def choose_solver(state_dir: str, static: dict | None = None,
                  registry_path: str = "", window: int = 5,
                  record: bool = True, bucket: str = "") -> dict:
    """Evidence-driven overrides for the destriper's solver knobs.

    ``static`` carries the configured values (``preconditioner``,
    ``mg_block``, ``pair_batch``) the decisions are measured against.
    Returns only the knobs the evidence argues to CHANGE, plus a
    ``reasons`` list; an empty dict (modulo ``reasons``) means the
    static config stands. ``record=False`` suppresses the decision
    ledger (dry-run / report use).

    ``bucket`` (ISSUE 20) restricts the rung-health evidence to solves
    stamped with that shape-bucket prefix — one rung PER BUCKET instead
    of one per run, so a calibrator geometry's easy converges can never
    argue the survey geometry down a rung. When no stamped record
    matches the bucket, the fold falls back to all records (the
    pre-bucket behaviour — old traces stay actionable). When the
    ``[tuning]`` winners cache is enabled and holds a measured
    ``mg_block`` for this bucket, escalations into multigrid use it
    instead of the documented default of 8."""
    static = dict(static or {})
    out: dict = {"reasons": []}

    def decide(knob: str, old, new, reason: str) -> None:
        out[knob] = new
        out["reasons"].append(f"{knob}: {old!r} -> {new!r} ({reason})")
        if record:
            record_decision(state_dir, "solver", "override", reason,
                            writer="solver", knob=knob, old=old,
                            new=new)

    try:
        from comapreduce_tpu.telemetry.solver_trace import read_solver

        records = read_solver(state_dir)
    except Exception:
        logger.exception("solver policy: trace read failed; static "
                         "config stands")
        return out
    if not records:
        return out
    rungs = rung_health(records, bucket=bucket)
    if bucket and not rungs:
        # no stamped evidence for THIS bucket yet: fall back to the
        # whole-run fold rather than flying blind
        rungs = rung_health(records)

    # 1. pick the cheapest HEALTHY rung: converged solves, no stall or
    # divergence on the rung, fewest iterations per solve
    healthy = {r: a for r, a in rungs.items()
               if a["solves"] > 0 and a["converged"] > 0
               and not a["stalled"] and not a["diverged"]}

    def cost(agg) -> float:
        return agg["iters"] / max(agg["solves"], 1)

    chosen = min(healthy, key=lambda r: cost(healthy[r])) \
        if healthy else None
    current = str(static.get("preconditioner") or "")
    if chosen and current and chosen != current \
            and chosen in RUNG_ORDER:
        cur_agg = rungs.get(current)
        sick = bool(cur_agg and (cur_agg["stalled"]
                                 or cur_agg["diverged"]))
        better = (cur_agg is None or not cur_agg["converged"]
                  or cost(healthy[chosen]) < cost(cur_agg))
        if sick or better:
            why = (f"rung '{chosen}' converged at "
                   f"{cost(healthy[chosen]):.1f} iters/solve vs "
                   f"'{current}' at "
                   + (f"{cost(cur_agg):.1f}"
                      if cur_agg and cur_agg["solves"]
                      else "no evidence")
                   + ("; and the configured rung stalled/diverged"
                      if sick else ""))
            decide("preconditioner", current, chosen, why)
            current = chosen

    # 2. registry delta: this run suddenly needs ESCALATE_RATIO x the
    # trailing-window iterations -> escalate one rung up the ladder
    if registry_path:
        try:
            worst = _registry_worst_ratio(records, registry_path,
                                          window)
        except Exception:
            logger.exception("solver policy: registry delta failed")
            worst = None
        if worst is not None and worst >= ESCALATE_RATIO:
            base = str(out.get("preconditioner", current))
            up = _escalate(base)
            if up is not None:
                decide("preconditioner", base, up,
                       f"iteration count at {worst:.2f}x the "
                       f"trailing-{window}-run registry median "
                       f"(escalation threshold {ESCALATE_RATIO:g})")

    # 3. program cost model: a shape bucket whose scratch blows the
    # HBM budget halves pair_batch for the next solves
    pair_batch = static.get("pair_batch")
    if pair_batch and int(pair_batch) > 1:
        try:
            from comapreduce_tpu.telemetry.programs import read_programs

            progs = read_programs(state_dir)
        except Exception:
            progs = []
        worst_rec = None
        for rec in progs:
            temp = rec.get("temp_bytes") or 0
            if temp > PAIR_TEMP_BUDGET and \
                    (worst_rec is None
                     or temp > (worst_rec.get("temp_bytes") or 0)):
                worst_rec = rec
        if worst_rec is not None:
            decide("pair_batch", int(pair_batch),
                   max(int(pair_batch) // 2, 1),
                   f"program {worst_rec.get('name')!r} bucket "
                   f"{worst_rec.get('shape_bucket')!r} assigns "
                   f"{worst_rec.get('temp_bytes')} temp bytes, over "
                   f"the {PAIR_TEMP_BUDGET} budget")

    # 4. mg_block: escalating INTO multigrid with no block configured
    # gets the measured [tuning] winner for this bucket when the cache
    # holds one, else the documented default so the ladder builds
    if out.get("preconditioner") == "multigrid" \
            and not static.get("mg_block"):
        block, source = 8, "the documented default block of 8"
        try:
            from comapreduce_tpu.tuning.cache import TUNING
            from comapreduce_tpu.tuning.space import solver_bucket

            if TUNING.enabled:
                win = TUNING.winner(
                    "solver",
                    solver_bucket(int(static.get("offset_length")
                                      or 0)))
                if win and win.get("mg_block"):
                    block = int(win["mg_block"])
                    source = (f"the measured [tuning] winner "
                              f"(mg_block={block})")
        except Exception:
            logger.exception("solver policy: tuning cache consult "
                             "failed; using the default block")
        decide("mg_block", static.get("mg_block"), block,
               "multigrid selected with no mg_block configured; "
               f"using {source}")
    return out
