"""The process-manager backend: actually fork reducer ranks, reap them.

The lease queue makes rank join/leave free (a fresh rank just starts
claiming; a dead rank's leases expire and get stolen), so this layer
is deliberately dumb: spawn a child process for a rank id, poll for
exits, terminate on shutdown. All POLICY — when to spawn, which rank
ids, how many — lives in :mod:`~comapreduce_tpu.control.autoscaler`;
all protocol — how a rank proves liveness, how work moves — lives in
``resilience/``. Keeping the manager mechanism-only is what lets the
control drill swap in a tiny worker entrypoint while production
supervises full ``run_destriper``/``loadgen`` ranks with the same
supervisor.
"""

from __future__ import annotations

import logging
import os
import subprocess
import time

__all__ = ["RankManager"]

logger = logging.getLogger("comapreduce_tpu")


class RankManager:
    """Spawn/reap child processes, one per elastic rank.

    ``argv_for_rank(rank) -> list[str]`` builds the child's command
    line — the supervisor's only coupling to WHAT a rank runs.
    ``log_dir`` (optional) captures each child's stdout+stderr in
    ``rank{r}.out``; without it output is discarded (children keep
    their own per-rank logfiles regardless).
    """

    def __init__(self, argv_for_rank, env: dict | None = None,
                 cwd: str | None = None, log_dir: str = ""):
        self.argv_for_rank = argv_for_rank
        self.env = dict(env) if env is not None else None
        self.cwd = cwd
        self.log_dir = log_dir
        self._procs: dict[int, subprocess.Popen] = {}
        self._logs: dict[int, object] = {}
        # (rank, returncode) history of every reaped child
        self.exited: list = []

    def spawn(self, rank: int) -> int:
        """Fork a child for ``rank``; returns its pid. A rank id with
        a live child is a no-op (its pid is returned) — the supervisor
        never races itself into double-spawning one rank."""
        rank = int(rank)
        proc = self._procs.get(rank)
        if proc is not None and proc.poll() is None:
            return proc.pid
        out = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            out = open(os.path.join(self.log_dir, f"rank{rank}.out"),
                       "ab")
            self._logs[rank] = out
        argv = list(self.argv_for_rank(rank))
        proc = subprocess.Popen(argv, stdout=out,
                                stderr=subprocess.STDOUT,
                                env=self.env, cwd=self.cwd)
        self._procs[rank] = proc
        logger.info("rank manager: spawned rank %d (pid %d): %s",
                    rank, proc.pid, " ".join(argv))
        return proc.pid

    def reap(self) -> list:
        """Collect finished children; returns ``[(rank, returncode)]``
        for the ones that exited since the last call."""
        done = []
        for rank, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            done.append((rank, rc))
            self.exited.append((rank, rc))
            self._procs.pop(rank, None)
            log = self._logs.pop(rank, None)
            if log is not None:
                try:
                    log.close()
                except OSError:
                    pass
            logger.info("rank manager: rank %d exited rc=%d", rank, rc)
        return done

    def live_ranks(self) -> list:
        """Ranks with a currently-running child, sorted."""
        return sorted(r for r, p in self._procs.items()
                      if p.poll() is None)

    def all_ranks(self) -> list:
        """Every rank id this manager has ever spawned, live or
        exited — the id-allocation floor for fresh spawns."""
        return sorted(set(self._procs)
                      | {r for r, _ in self.exited})

    def terminate_all(self, timeout_s: float = 5.0) -> None:
        """SIGTERM every live child, SIGKILL stragglers past the
        grace period, close log handles — the shutdown path."""
        for proc in self._procs.values():
            if proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + max(timeout_s, 0.0)
        for proc in self._procs.values():
            left = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(left, 0.05))
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                    proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self.reap()
        for log in self._logs.values():
            try:
                log.close()
            except OSError:
                pass
        self._logs.clear()
