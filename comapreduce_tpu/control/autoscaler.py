"""The autoscale policy: sensors in, spawn/retire decisions out.

Pure decision logic — no filesystem, no subprocesses — so every rule
is unit-testable with plain numbers and the supervisor stays a thin
sense→decide→act shell. Rules, in priority order:

1. **Replace the dead.** A rank judged dead (the CHANGE-based
   :class:`~comapreduce_tpu.resilience.heartbeat.HeartbeatWatch`
   rule) while work remains gets a replacement immediately — a crash
   never waits out the cooldown (the queue's lease TTL already spent
   the detection latency).
2. **Fill to the floor.** Fewer live ranks than ``min_ranks`` while
   work remains spawns up to the floor, also cooldown-exempt.
3. **Scale up under pressure.** Backlog above ``2 x live`` ranks, or
   a measured commit rate below ``target_files_per_hour`` with
   backlog remaining, adds ONE rank per cooldown window — the
   hysteresis that keeps one slow rank from causing spawn thrashing.
4. **Retire the idle.** No backlog and more live ranks than the floor
   yields a ``retire`` decision; elastic ranks drain and exit on
   their own when the queue empties, so retirement is advisory
   bookkeeping (the reap), never a kill — a rank mid-solve finishes.

Every rule is capped at ``max_ranks`` live children.
"""

from __future__ import annotations

import time
from typing import NamedTuple

from comapreduce_tpu.control.config import ControlConfig

__all__ = ["AutoscalePolicy", "ScaleDecision"]


class ScaleDecision(NamedTuple):
    """One policy verdict: ``action`` is ``spawn`` / ``retire``,
    ``ranks`` the rank ids it applies to, ``reason`` the audit
    line."""

    action: str
    ranks: tuple
    reason: str


class AutoscalePolicy:
    """See the module docstring for the rule set."""

    def __init__(self, config: ControlConfig, clock=time.monotonic):
        self.cfg = ControlConfig.coerce(config)
        self.clock = clock
        self._last_scale_up: float | None = None
        self._retired = False

    def _next_ranks(self, live, dead, reserved, n: int) -> tuple:
        """``n`` fresh rank ids past everything ever seen — a
        replacement never reuses a dead rank's id, so its stale
        heartbeat/lease files cannot masquerade as the newcomer's."""
        used = {int(r) for r in live} | {int(r) for r in dead} \
            | {int(r) for r in reserved}
        start = max(used, default=-1) + 1
        return tuple(range(start, start + n))

    def decide(self, *, backlog: int, live_ranks, dead_ranks=(),
               reserved_ranks=(),
               files_per_hour: float | None = None
               ) -> ScaleDecision | None:
        """One sense cycle in, at most one decision out.

        ``backlog`` counts units not yet done anywhere; ``live_ranks``
        / ``dead_ranks`` are the HeartbeatWatch verdicts (dead ranks
        already replaced must be filtered by the caller);
        ``reserved_ranks`` are ids ever used by ANY rank, live or not
        — fresh spawns allocate past them; ``files_per_hour`` is the
        measured commit rate (None = not yet measurable)."""
        cfg = self.cfg
        live = sorted(int(r) for r in live_ranks)
        dead = sorted(int(r) for r in dead_ranks)
        reserved = set(reserved_ranks)
        now = self.clock()
        room = cfg.max_ranks - len(live)

        if backlog > 0 and dead and room > 0:
            n = min(len(dead), room)
            ranks = self._next_ranks(live, dead, reserved, n)
            self._retired = False
            return ScaleDecision(
                "spawn", ranks,
                f"rank(s) {dead} dead (heartbeat unchanged past the "
                f"liveness TTL) with {backlog} unit(s) outstanding; "
                f"spawning {n} replacement(s)")

        if backlog > 0 and len(live) < cfg.min_ranks:
            n = min(cfg.min_ranks - len(live), room)
            if n > 0:
                ranks = self._next_ranks(live, dead, reserved, n)
                self._retired = False
                return ScaleDecision(
                    "spawn", ranks,
                    f"{len(live)} live rank(s) below min_ranks="
                    f"{cfg.min_ranks} with {backlog} unit(s) "
                    f"outstanding")

        if backlog > 0 and room > 0:
            slow = (cfg.target_files_per_hour > 0
                    and files_per_hour is not None
                    and files_per_hour < cfg.target_files_per_hour)
            deep = backlog > 2 * max(len(live), 1)
            cooled = (self._last_scale_up is None
                      or now - self._last_scale_up >= cfg.cooldown_s)
            if (slow or deep) and cooled:
                self._last_scale_up = now
                ranks = self._next_ranks(live, dead, reserved, 1)
                self._retired = False
                why = (f"measured {files_per_hour:.1f} files/h below "
                       f"target {cfg.target_files_per_hour:g}" if slow
                       else f"backlog {backlog} > 2 x {len(live)} "
                            f"live rank(s)")
                return ScaleDecision("spawn", ranks,
                                     why + "; adding one rank")

        if backlog == 0 and len(live) > cfg.min_ranks \
                and not self._retired:
            # advisory: elastic ranks drain and exit on their own —
            # emitted once per idle episode so the ledger shows WHEN
            # the fleet went idle, without a retire line per poll
            self._retired = True
            extra = tuple(live[cfg.min_ranks:])
            return ScaleDecision(
                "retire", extra,
                f"queue drained with {len(live)} live rank(s) above "
                f"min_ranks={cfg.min_ranks}; idle ranks drain and "
                f"exit on their own")
        if backlog > 0:
            self._retired = False
        return None

    def note_spawned(self) -> None:
        """Record an out-of-band spawn (replacement / fill-to-floor)
        so rule 3's cooldown also spaces off it."""
        self._last_scale_up = self.clock()
