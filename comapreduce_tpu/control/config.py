"""The ``[control]`` config table (TOML; ``[Control]`` in legacy INI).

One strict-coerce table for all three control loops, following the
``[resilience]`` discipline: a typo'd knob raises at load, every loop
defaults OFF, and ``coerce(None)`` — no table at all — yields the
identity config, byte-for-byte the uncontrolled pipeline.

Autoscaler knobs (docs/OPERATIONS.md §19):

- ``autoscale``              bool, default False — the supervisor loop
- ``min_ranks``              int, default 1 — spawn up to this floor
- ``max_ranks``              int, default 8 — never scale past this
- ``target_files_per_hour``  float, default 0 (off) — scale up while
  the measured commit rate sits below this target and backlog remains
- ``cooldown_s``             float, default 30 — minimum spacing
  between *scale-up* actions (replacing a dead rank and filling to
  ``min_ranks`` bypass the cooldown: a crashed rank must not wait out
  a timer); the anti-thrash hysteresis
- ``poll_s``                 float, default 1.0 — supervisor sense
  period
- ``liveness_ttl_s``         float, default 0 — seconds without a
  heartbeat CHANGE before a rank is judged dead (0 derives
  ``2 x lease_ttl_s`` at runtime)

Admission knobs:

- ``admission``              bool, default False — the shed/defer loop
- ``shed_high_water``        int, default 16 — backlog (not-yet-done,
  non-deferred units) at or above which shedding switches ON
- ``shed_low_water``         int, default 4 — backlog at or below
  which shedding switches OFF (hysteresis band against flapping)

Solver-policy knob:

- ``solver_policy``          bool, default False — pick
  ``preconditioner``/``mg_block``/``pair_batch`` from solver traces,
  registry deltas and the program cost model instead of static config
"""

from __future__ import annotations

__all__ = ["ControlConfig"]


def _bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


class ControlConfig:
    """See the module docstring for knob semantics; ``enabled`` is
    True when ANY loop is on — the cheap gate callers check before
    importing anything heavier."""

    KNOBS = ("autoscale", "min_ranks", "max_ranks",
             "target_files_per_hour", "cooldown_s", "poll_s",
             "liveness_ttl_s", "admission", "shed_high_water",
             "shed_low_water", "solver_policy")

    __slots__ = KNOBS

    def __init__(self, autoscale: bool = False, min_ranks: int = 1,
                 max_ranks: int = 8,
                 target_files_per_hour: float = 0.0,
                 cooldown_s: float = 30.0, poll_s: float = 1.0,
                 liveness_ttl_s: float = 0.0, admission: bool = False,
                 shed_high_water: int = 16, shed_low_water: int = 4,
                 solver_policy: bool = False):
        self.autoscale = _bool(autoscale)
        self.min_ranks = int(min_ranks)
        self.max_ranks = int(max_ranks)
        self.target_files_per_hour = float(target_files_per_hour)
        self.cooldown_s = float(cooldown_s)
        self.poll_s = float(poll_s)
        self.liveness_ttl_s = float(liveness_ttl_s)
        self.admission = _bool(admission)
        self.shed_high_water = int(shed_high_water)
        self.shed_low_water = int(shed_low_water)
        self.solver_policy = _bool(solver_policy)
        if self.min_ranks < 1:
            raise ValueError(
                f"[control] min_ranks must be >= 1, got {self.min_ranks}")
        if self.max_ranks < self.min_ranks:
            raise ValueError(
                f"[control] max_ranks ({self.max_ranks}) must be >= "
                f"min_ranks ({self.min_ranks})")
        if self.shed_low_water > self.shed_high_water:
            raise ValueError(
                f"[control] shed_low_water ({self.shed_low_water}) must "
                f"be <= shed_high_water ({self.shed_high_water})")
        if self.cooldown_s < 0 or self.poll_s <= 0:
            raise ValueError(
                "[control] cooldown_s must be >= 0 and poll_s > 0")

    @property
    def enabled(self) -> bool:
        return self.autoscale or self.admission or self.solver_policy

    @classmethod
    def coerce(cls, value) -> "ControlConfig":
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        unknown = set(value) - set(cls.KNOBS)
        if unknown:
            raise ValueError(
                f"unknown [control] option(s) {sorted(unknown)}; "
                f"valid: {list(cls.KNOBS)}")
        return cls(**dict(value))

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={getattr(self, k)}" for k in self.KNOBS)
        return f"ControlConfig({body})"
