"""The campaign supervisor: the autoscaler loop, closed.

One poll cycle = **sense → decide → act → publish**:

- **sense** — queue depth from ``queue.json`` + the lease board (done
  / claimed / outstanding counts, commit timestamps for the measured
  files-per-hour rate and its ETA), rank liveness from the
  CHANGE-based :class:`~comapreduce_tpu.resilience.heartbeat
  .HeartbeatWatch` (a crashed rank's final beat never reads alive —
  the file stops changing), child exits from the
  :class:`~comapreduce_tpu.control.manager.RankManager` reap, and the
  shed backlog from the quarantine ledger's ``deferred`` lines;
- **decide** — :class:`~comapreduce_tpu.control.autoscaler
  .AutoscalePolicy` (replace the dead, fill to the floor, scale up
  under cooldown, retire the idle);
- **act** — spawn through the manager; every action is recorded as a
  ``control.decision`` event whether or not it changes anything;
- **publish** — ``supervisor.json`` in the state directory (durable
  replace): desired vs live ranks, backlog, shed backlog, last
  decision, poll period. ``tools/watchdog_report.py`` renders it as
  its schema-3 supervisor columns and exits 1 on a stuck loop (the
  file's age tells on a supervisor that died mid-campaign).

The supervisor is a *sidecar*: it holds no leases, reduces nothing,
and a campaign runs identically without it — minus the self-healing.
Run it in-process (the control drill) or as the operator CLI::

    python -m comapreduce_tpu.control.supervisor STATE_DIR \\
        --spawn-cmd 'python -m comapreduce_tpu.cli.run_destriper \\
        cfg.ini --rank {rank}' --min-ranks 4 --max-ranks 8
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import logging
import os
import shlex
import time

from comapreduce_tpu.control.autoscaler import AutoscalePolicy
from comapreduce_tpu.control.config import ControlConfig
from comapreduce_tpu.control.decisions import record_decision
from comapreduce_tpu.control.manager import RankManager
from comapreduce_tpu.data.durable import durable_replace
from comapreduce_tpu.resilience.heartbeat import (HeartbeatWatch,
                                                  read_heartbeats)
from comapreduce_tpu.resilience.lease import read_lease

__all__ = ["SUPERVISOR_FILE", "Supervisor", "read_supervisor",
           "shed_backlog", "supervisor_stuck"]

logger = logging.getLogger("comapreduce_tpu")

SUPERVISOR_FILE = "supervisor.json"
SUPERVISOR_SCHEMA = 1

#: measured-rate window: commits older than this do not count toward
#: the current files-per-hour estimate
_RATE_WINDOW_S = 300.0


def read_supervisor(state_dir: str) -> dict | None:
    """The latest supervisor snapshot; None when missing/torn (= no
    supervisor ran here — the watchdog stays schema 2)."""
    try:
        with open(os.path.join(state_dir or ".", SUPERVISOR_FILE),
                  "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def supervisor_stuck(snap: dict | None, now: float | None = None,
                     grace: float = 10.0) -> bool:
    """True when a supervisor snapshot exists but has not been
    republished for 5 poll periods (+ ``grace``) with the queue still
    undrained — a control loop that died mid-campaign. A DRAINED
    campaign's supervisor legitimately stops publishing."""
    if snap is None:
        return False
    if snap.get("drained"):
        return False
    now = time.time() if now is None else now
    age = now - float(snap.get("t_unix") or 0.0)
    poll = float(snap.get("poll_s") or 1.0)
    return age > 5.0 * poll + grace


def shed_backlog(state_dir: str) -> int:
    """Units whose LATEST quarantine-ledger line says ``deferred`` —
    shed by admission control and not yet re-admitted."""
    from comapreduce_tpu.resilience.ledger import QuarantineLedger

    ledgers = sorted(_glob.glob(os.path.join(state_dir or ".",
                                             "quarantine*.jsonl")))
    if not ledgers:
        return 0
    led = QuarantineLedger(ledgers[0], read_paths=tuple(ledgers[1:]))
    return sum(n for k, n in led.summary().items()
               if k.endswith(":deferred"))


class Supervisor:
    """See the module docstring. ``manager=None`` runs the loop
    sensors-and-decisions only (decisions are recorded but nothing is
    spawned) — the dry-run / observe mode."""

    def __init__(self, state_dir: str, config: ControlConfig,
                 manager: RankManager | None = None,
                 lease_ttl_s: float = 60.0, clock=time.monotonic,
                 sleep=time.sleep):
        self.state_dir = state_dir or "."
        self.cfg = ControlConfig.coerce(config)
        self.manager = manager
        self.clock = clock
        self.sleep = sleep
        ttl = self.cfg.liveness_ttl_s or 2.0 * float(lease_ttl_s)
        self.watch = HeartbeatWatch(ttl_s=ttl, clock=clock)
        self.policy = AutoscalePolicy(self.cfg, clock=clock)
        self.desired = self.cfg.min_ranks
        self.last_decision: dict | None = None
        self.n_decisions = 0
        # dead ranks already replaced (or judged not worth replacing):
        # a rank is replaced at most once
        self._replaced: set = set()
        self._crashed: set = set()

    # -- sense ---------------------------------------------------------------
    def _queue_sense(self) -> dict:
        from comapreduce_tpu.pipeline.scheduler import read_manifest

        man = read_manifest(self.state_dir) or {}
        n_files = len(man.get("files", []))
        n_done = n_claimed = 0
        now_unix = time.time()
        recent = 0
        for p in _glob.glob(os.path.join(self.state_dir,
                                         "lease.*.json")):
            st = read_lease(p)
            if st is None:
                continue
            if st.get("state") == "done":
                n_done += 1
                t_done = st.get("t_done_unix")
                if t_done and now_unix - float(t_done) <= _RATE_WINDOW_S:
                    recent += 1
            elif st.get("state") == "claimed":
                n_claimed += 1
        backlog = max(n_files - n_done, 0)
        rate = (recent * 3600.0 / _RATE_WINDOW_S) if recent else None
        return {"n_files": n_files, "n_done": n_done,
                "n_claimed": n_claimed, "backlog": backlog,
                "files_per_hour": rate,
                "eta_s": (backlog * 3600.0 / rate
                          if rate and backlog else None)}

    def sense(self) -> dict:
        crashed = set()
        if self.manager is not None:
            for rank, rc in self.manager.reap():
                if rc != 0:
                    crashed.add(rank)
                    self._crashed.add(rank)
        q = self._queue_sense()
        beats = read_heartbeats(self.state_dir)
        self.watch.observe(beats)
        live = set(self.watch.alive_ranks())
        if self.manager is not None:
            # a just-spawned child that has not written its first beat
            # yet is STARTING, not dead — count it live, or the
            # fill-to-the-floor rule refires every poll of the startup
            # window; once it has a heartbeat file the CHANGE-based
            # verdict governs (a zombie child is still judged dead)
            live |= {r for r in self.manager.live_ranks()
                     if r not in beats}
        # a reaped child is NOT alive, however fresh its final beats
        # still look to the heartbeat watch — the reap outruns the TTL
        live -= self._crashed
        live = sorted(live)
        # dead = heartbeat unchanged past the TTL, plus children the
        # manager just reaped with a non-zero exit (faster than the
        # TTL — the reap is immediate); each replaced at most once
        dead = sorted((set(self.watch.dead_ranks()) | crashed
                       | self._crashed) - self._replaced
                      - set(live))
        q.update({"live_ranks": live, "dead_ranks": dead,
                  "shed_backlog": shed_backlog(self.state_dir)})
        return q

    # -- decide + act --------------------------------------------------------
    def step(self) -> dict:
        """One full cycle; returns the published snapshot."""
        s = self.sense()
        decision = None
        if self.cfg.autoscale:
            reserved = self._replaced | self._crashed
            if self.manager is not None:
                reserved |= set(self.manager.all_ranks())
            decision = self.policy.decide(
                backlog=s["backlog"], live_ranks=s["live_ranks"],
                dead_ranks=s["dead_ranks"], reserved_ranks=reserved,
                files_per_hour=s["files_per_hour"])
        if decision is not None:
            entry = record_decision(
                self.state_dir, "autoscaler", decision.action,
                decision.reason, ranks=list(decision.ranks),
                backlog=s["backlog"], live=list(s["live_ranks"]),
                dead=list(s["dead_ranks"]))
            self.last_decision = entry
            self.n_decisions += 1
            if decision.action == "spawn":
                self._replaced.update(int(r) for r in s["dead_ranks"])
                self.desired = min(
                    max(self.desired,
                        len(s["live_ranks"]) + len(decision.ranks)),
                    self.cfg.max_ranks)
                self.policy.note_spawned()
                if self.manager is not None:
                    for r in decision.ranks:
                        self.manager.spawn(r)
            elif decision.action == "retire":
                self.desired = self.cfg.min_ranks
        return self._publish(s)

    def _publish(self, s: dict) -> dict:
        snap = {"schema": SUPERVISOR_SCHEMA, "t_unix": time.time(),
                "poll_s": self.cfg.poll_s,
                "autoscale": self.cfg.autoscale,
                "desired_ranks": self.desired,
                "live_ranks": s["live_ranks"],
                "dead_ranks": sorted(self._crashed
                                     | set(s["dead_ranks"])
                                     | self._replaced),
                "n_files": s["n_files"], "n_done": s["n_done"],
                "n_claimed": s["n_claimed"], "backlog": s["backlog"],
                "shed_backlog": s["shed_backlog"],
                "files_per_hour": s["files_per_hour"],
                "eta_s": s["eta_s"],
                "drained": bool(s["n_files"]
                                and s["n_done"] >= s["n_files"]),
                "n_decisions": self.n_decisions,
                "last_decision": self.last_decision}
        tmp = os.path.join(self.state_dir,
                           f".{SUPERVISOR_FILE}.{os.getpid()}.tmp")
        try:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f)
            durable_replace(tmp, os.path.join(self.state_dir,
                                              SUPERVISOR_FILE))
        except OSError as exc:
            logger.warning("supervisor snapshot write failed (%s: %s)",
                           type(exc).__name__, exc)
        return snap

    def run(self, max_s: float = 0.0) -> dict:
        """Poll until the campaign drains (manifest known and every
        unit done, no live children) or ``max_s`` elapses (0 = no
        limit); returns the final snapshot."""
        t0 = self.clock()
        snap = self.step()
        while True:
            children = (self.manager.live_ranks()
                        if self.manager is not None else [])
            if snap["drained"] and not children:
                return snap
            if max_s and self.clock() - t0 >= max_s:
                logger.warning("supervisor: max_s=%.0f reached with "
                               "backlog %d", max_s, snap["backlog"])
                return snap
            self.sleep(self.cfg.poll_s)
            snap = self.step()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="campaign supervisor: autoscale elastic reducer "
                    "ranks over a lease-queue state directory")
    ap.add_argument("state_dir", help="the campaign's state directory "
                                      "(queue.json / heartbeats / "
                                      "leases)")
    ap.add_argument("--spawn-cmd", default="",
                    help="command template for one rank; '{rank}' is "
                         "substituted (omit to observe without "
                         "acting)")
    ap.add_argument("--min-ranks", type=int, default=1)
    ap.add_argument("--max-ranks", type=int, default=8)
    ap.add_argument("--target-files-per-hour", type=float, default=0.0)
    ap.add_argument("--cooldown-s", type=float, default=30.0)
    ap.add_argument("--poll-s", type=float, default=1.0)
    ap.add_argument("--lease-ttl-s", type=float, default=60.0,
                    help="the campaign's [resilience] lease_ttl_s "
                         "(liveness TTL derives 2x this unless "
                         "--liveness-ttl-s is set)")
    ap.add_argument("--liveness-ttl-s", type=float, default=0.0)
    ap.add_argument("--max-s", type=float, default=0.0,
                    help="stop after this many seconds (0 = until "
                         "the queue drains)")
    ap.add_argument("--json", action="store_true",
                    help="print the final snapshot as JSON")
    args = ap.parse_args(argv)

    cfg = ControlConfig(
        autoscale=True, min_ranks=args.min_ranks,
        max_ranks=args.max_ranks,
        target_files_per_hour=args.target_files_per_hour,
        cooldown_s=args.cooldown_s, poll_s=args.poll_s,
        liveness_ttl_s=args.liveness_ttl_s)
    manager = None
    if args.spawn_cmd:
        template = args.spawn_cmd

        def argv_for_rank(rank: int, _t=template) -> list:
            return [a.replace("{rank}", str(rank))
                    for a in shlex.split(_t)]

        manager = RankManager(argv_for_rank,
                              log_dir=os.path.join(args.state_dir,
                                                   "supervisor_logs"))
    sup = Supervisor(args.state_dir, cfg, manager=manager,
                     lease_ttl_s=args.lease_ttl_s)
    try:
        snap = sup.run(max_s=args.max_s)
    finally:
        if manager is not None:
            manager.terminate_all()
    if args.json:
        print(json.dumps(snap))
    else:
        print(f"supervisor: drained={snap['drained']} "
              f"done={snap['n_done']}/{snap['n_files']} "
              f"live={snap['live_ranks']} decisions="
              f"{snap['n_decisions']}")
    return 0 if snap["drained"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
