"""The control drill: all three control loops closed, under chaos.

``run_control_drill`` stages the campaign ISSUE criterion the control
plane exists for — **kill 2 of 4 ranks mid-campaign, fire a
``load_spike`` burst of SLO-flagged files, and prove the supervised
campaign still finishes exactly-once with every shed unit re-admitted
and the final map byte-identical to an undisturbed run**:

- 12 base Level-2 files are queued for 4 elastic worker ranks
  (``python -m comapreduce_tpu.control.drill --worker``, spawned by
  the :class:`~comapreduce_tpu.control.supervisor.Supervisor` through
  its :class:`~comapreduce_tpu.control.manager.RankManager` — the
  fill-to-the-floor rule performs the initial rollout);
- ranks 0 and 1 draw ``rank_kill`` on their third rotation unit:
  SIGKILLed mid-claim, leases leaked, heartbeats frozen — the
  supervisor's reap + CHANGE-based liveness must spawn fresh
  replacement ranks (never reusing the dead ids) within the policy
  cooldown, recorded as auditable ``control.decision`` events;
- rank 2 draws ``load_spike`` on its first commit: 3 extra files land
  in the shared ``queue.json`` mid-run. All 3 are pre-flagged in the
  data-quality ledger, so every rank's admission gate (shed water
  marks low enough that a mid-campaign backlog means pressure) defers
  them — ``deferred`` quarantine-ledger lines — until the base queue
  drains and pressure clears, when they are re-admitted
  (``readmitted`` lines) and reduced: shed, never dropped;
- a :class:`~comapreduce_tpu.telemetry.live.LiveServer` watches
  throughout; the drill audits ``/metrics``
  ``comap_scheduler_committed_total`` against the lease board's done
  count (workers flush telemetry after every commit, so even a
  SIGKILLed rank's commits are all on disk).

Asserts, in order: the supervisor drained the campaign; every one of
the 15 units has a ``done`` lease (exactly once — the fence makes a
double commit impossible, the count makes a lost unit visible); the
survivors' result manifests cover exactly the units the dead ranks
did not finish; spawn decisions replace ranks {0, 1} with fresh ids;
every spike file has a ``deferred`` AND a later ``readmitted`` ledger
line; ``/metrics`` agrees with the lease board; and the destriped map
over the committed set equals a clean in-process run over the same
15 files to the last byte.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import sys
import time

import numpy as np

__all__ = ["run_control_drill"]

logger = logging.getLogger("comapreduce_tpu")


def run_control_drill(workdir: str, seed: int = 0, ttl_s: float = 1.5,
                      hold_s: float = 0.4,
                      timeout_s: float = 120.0) -> dict:
    """Run the full control drill in ``workdir``; returns the evidence
    dict (see the module docstring for the scenario and asserts)."""
    from urllib.request import urlopen

    from comapreduce_tpu.control.config import ControlConfig
    from comapreduce_tpu.control.decisions import read_decisions
    from comapreduce_tpu.control.manager import RankManager
    from comapreduce_tpu.control.supervisor import Supervisor
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience.drill import (_child_env, _read,
                                                  _solve, _write_level2)
    from comapreduce_tpu.resilience.ledger import QuarantineLedger
    from comapreduce_tpu.resilience.lease import (lease_key, lease_path,
                                                  read_lease)
    from comapreduce_tpu.telemetry.live import LiveServer

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    base, spikes = [], []
    for i in range(12):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 100 + i)
        base.append(os.path.abspath(path))
    for i in range(3):
        path = os.path.join(workdir, f"Level2_spike-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=2000 + seed * 100 + i)
        spikes.append(os.path.abspath(path))
    everything = sorted(base + spikes)

    state = os.path.join(workdir, "control")
    shutil.rmtree(state, ignore_errors=True)
    os.makedirs(state)
    flist = os.path.join(state, "filelist.txt")
    with open(flist, "w", encoding="utf-8") as f:
        f.write("\n".join(base) + "\n")
    spike_list = os.path.join(state, "spikes.txt")
    with open(spike_list, "w", encoding="utf-8") as f:
        f.write("\n".join(spikes) + "\n")
    # the spike files arrive already SLO-flagged (a bad-weather session
    # being backfilled): the admission gate's flagged-file sensor reads
    # this data-quality ledger
    t_iso = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(state, "quality.rank99.jsonl"), "w",
              encoding="utf-8") as f:
        for s in spikes:
            f.write(json.dumps({
                "schema": 1, "file": os.path.basename(s), "feed": 0,
                "band": 0, "t": t_iso, "t_unix": time.time(),
                "flagged": True,
                "flags": ["drill: pre-flagged spike file"]}) + "\n")
    # pre-publish the queue manifest (what a campaign's rank 0 would
    # write) so the supervisor's very first sense sees the backlog and
    # fill-to-the-floor performs the initial 4-rank rollout
    with open(os.path.join(state, "queue.json"), "w",
              encoding="utf-8") as f:
        names = [os.path.basename(p) for p in base]
        json.dump({"schema": 1, "n": len(names), "files": names,
                   "t_wall": t_iso}, f)

    # faults: ranks 0/1 die claiming their THIRD rotation unit (files
    # 8/9 under 4-rank rotation) — two commits in, work outstanding,
    # the worst moment; rank 2 spikes at its FIRST commit (file 2)
    kill0 = os.path.basename(base[8])
    kill1 = os.path.basename(base[9])
    spike_at = os.path.basename(base[2])

    def argv_for_rank(rank: int) -> list:
        cmd = [sys.executable, "-m", "comapreduce_tpu.control.drill",
               "--worker", f"--rank={rank}", "--n-ranks=4",
               f"--state-dir={state}", f"--filelist={flist}",
               f"--ttl={ttl_s}", f"--seed={seed}",
               f"--hold-s={hold_s}", "--shed-high=2", "--shed-low=0",
               "--telemetry"]
        if rank == 0:
            cmd.append(f"--chaos=rank_kill@{kill0}")
        elif rank == 1:
            cmd.append(f"--chaos=rank_kill@{kill1}")
        elif rank == 2:
            cmd += [f"--chaos=load_spike@{spike_at}",
                    f"--spike-list={spike_list}"]
        return cmd

    manager = RankManager(argv_for_rank, env=_child_env(),
                          log_dir=os.path.join(state,
                                               "supervisor_logs"))
    cfg = ControlConfig(autoscale=True, min_ranks=4, max_ranks=6,
                        cooldown_s=30.0, poll_s=0.3,
                        liveness_ttl_s=3.0)
    sup = Supervisor(state, cfg, manager=manager, lease_ttl_s=ttl_s)
    srv = LiveServer(state, port=0, stale_s=2.0 * ttl_s,
                     n_ranks=4).start()
    try:
        snap = sup.run(max_s=timeout_s)
        assert snap["drained"], \
            f"control drill: campaign did not drain within " \
            f"{timeout_s:.0f} s: {snap}"
        with urlopen(f"http://{srv.host}:{srv.port}/metrics",
                     timeout=10) as r:
            assert r.status == 200
            prom = r.read().decode("utf-8")
    finally:
        manager.terminate_all()
        srv.stop()

    # -- exactly once: the lease board is the ground truth ----------------
    names_all = sorted(os.path.basename(p) for p in everything)
    done_by = {}
    for p in everything:
        st = read_lease(lease_path(state, lease_key(p)))
        assert st is not None and st.get("state") == "done", \
            f"control drill: lease for {os.path.basename(p)} not " \
            f"done: {st}"
        done_by[os.path.basename(p)] = int(st.get("done_by", -1))
    results = {}
    for fn in os.listdir(state):
        if fn.startswith("result.rank") and fn.endswith(".json"):
            with open(os.path.join(state, fn), encoding="utf-8") as f:
                rec = json.load(f)
            results[rec["rank"]] = rec
    assert 0 not in results and 1 not in results, \
        "control drill: a SIGKILLed rank wrote a result manifest"
    committed = sorted(n for r in results.values()
                       for n in r["committed"])
    finished_by_dead = sorted(n for n, r in done_by.items()
                              if r in (0, 1))
    # multiset equality: the survivors committed exactly the units the
    # dead ranks did not — nothing lost, nothing committed twice
    assert committed == sorted(set(names_all)
                               - set(finished_by_dead)), \
        f"control drill: survivors committed {committed}, expected " \
        f"everything but {finished_by_dead}"
    n_spiked = sum(r["stats"]["spiked"] for r in results.values())
    assert n_spiked == len(spikes), \
        f"control drill: load_spike queued {n_spiked} unit(s), " \
        f"expected {len(spikes)}"

    # -- the autoscaler: dead ranks replaced with FRESH ids ---------------
    decisions = read_decisions(state)
    spawns = [d for d in decisions if d["loop"] == "autoscaler"
              and d["action"] == "spawn"]
    replaced = set()
    spawned = set()
    for d in spawns:
        if d.get("dead"):
            replaced.update(int(r) for r in d["dead"])
            spawned.update(int(r) for r in d.get("ranks", ()))
    assert replaced >= {0, 1}, \
        f"control drill: spawn decisions replaced {sorted(replaced)}," \
        f" expected ranks 0 and 1: {spawns}"
    assert len(spawned) >= 2 and not spawned & {0, 1, 2, 3}, \
        f"control drill: replacement ids {sorted(spawned)} must be " \
        f">= 2 fresh ranks (never a reused id)"
    for r in sorted(spawned):
        assert r in results and results[r]["stats"]["claimed"] >= 0, \
            f"control drill: replacement rank {r} left no result " \
            f"manifest (never ran?)"

    # -- admission: every spike file shed AND re-admitted -----------------
    import glob as _glob

    ledgers = sorted(_glob.glob(os.path.join(state,
                                             "quarantine*.jsonl")))
    led = QuarantineLedger(ledgers[0], read_paths=tuple(ledgers[1:]))
    dispositions: dict = {}
    for e in led.entries:
        b = os.path.basename(e.unit["file"])
        dispositions.setdefault(b, []).append(e.disposition)
    for s in spikes:
        b = os.path.basename(s)
        disp = dispositions.get(b, [])
        assert "deferred" in disp, \
            f"control drill: spike file {b} was never ledgered " \
            f"deferred: {disp}"
        assert "readmitted" in disp, \
            f"control drill: spike file {b} shed but never ledgered " \
            f"readmitted — a shed unit must come back: {disp}"
    admission = [d for d in decisions if d["loop"] == "admission"]
    acts = {d["action"] for d in admission}
    assert {"shed_on", "defer", "shed_off"} <= acts, \
        f"control drill: admission decisions incomplete: {acts}"
    assert snap["shed_backlog"] == 0, \
        f"control drill: {snap['shed_backlog']} unit(s) still shed " \
        f"after the drain — deferred work was dropped"

    # -- /metrics audit: every commit emitted exactly one counter ---------
    committed_metric = 0.0
    for ln in prom.splitlines():
        if ln.startswith("comap_scheduler_committed_total{"):
            committed_metric += float(ln.rsplit(" ", 1)[1])
    assert committed_metric == len(everything), \
        f"control drill: /metrics committed {committed_metric} != " \
        f"{len(everything)} done leases"
    assert "comap_control_decision_total{" in prom, \
        "control drill: /metrics lacks comap_control_decision_total"

    # -- the map: chaos + control changed WHO reduced, never WHAT ---------
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60),
                         (64, 64))
    by_name = {os.path.basename(p): p for p in everything}
    map_ctl = np.asarray(_solve(_read(
        [by_name[n] for n in names_all], wcs)).destriped_map)
    map_clean = np.asarray(_solve(_read(everything, wcs)).destriped_map)
    identical = bool(np.array_equal(map_ctl, map_clean))
    assert identical, \
        "control drill: supervised-campaign map != clean run over " \
        "the same 15 files"

    return {
        "control_drained": snap["drained"],
        "control_n_done": snap["n_done"],
        "control_replaced": sorted(replaced),
        "control_spawned": sorted(spawned),
        "control_n_decisions": len(decisions),
        "control_shed": sorted(os.path.basename(s) for s in spikes),
        "control_committed_metric": committed_metric,
        "control_map_byte_identical": identical,
        "control_supervisor_snapshot": {
            k: snap[k] for k in ("desired_ranks", "live_ranks",
                                 "dead_ranks", "shed_backlog",
                                 "n_decisions")},
        "control_wall_s": round(time.perf_counter() - t0, 3),
    }


def _worker_main(argv=None) -> int:
    """One supervised drill rank: heartbeat + admission gate +
    scheduler over the shared state dir. Spawned (and reaped) by the
    supervisor's RankManager; chaos makes rank 0/1 the kill victims
    and rank 2 the load-spike source. Results land in
    ``result.rank<r>.json`` exactly like the elastic drill's."""
    import argparse

    from comapreduce_tpu.control.admission import AdmissionController
    from comapreduce_tpu.control.config import ControlConfig
    from comapreduce_tpu.pipeline.scheduler import Scheduler
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.heartbeat import Heartbeat
    from comapreduce_tpu.resilience.ledger import QuarantineLedger
    from comapreduce_tpu.telemetry import TELEMETRY

    p = argparse.ArgumentParser(prog="control-drill-worker")
    p.add_argument("--worker", action="store_true")
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--n-ranks", type=int, required=True)
    p.add_argument("--state-dir", required=True)
    p.add_argument("--filelist", required=True)
    p.add_argument("--ttl", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chaos", default="")
    p.add_argument("--spike-list", default="")
    p.add_argument("--hold-s", type=float, default=0.0)
    p.add_argument("--shed-high", type=int, default=16)
    p.add_argument("--shed-low", type=int, default=4)
    p.add_argument("--telemetry", action="store_true")
    a = p.parse_args(argv)
    with open(a.filelist, encoding="utf-8") as f:
        files = [ln.strip() for ln in f if ln.strip()]
    if a.telemetry:
        TELEMETRY.configure(a.state_dir, rank=a.rank, flush_s=0.2)
    hb = Heartbeat(a.state_dir, rank=a.rank,
                   period_s=max(a.ttl / 5.0, 0.05))
    hb.start()
    monkey = None
    if a.chaos:
        monkey = ChaosMonkey(a.chaos, seed=a.seed)
        if a.spike_list:
            with open(a.spike_list, encoding="utf-8") as f:
                monkey.spike_files = [ln.strip() for ln in f
                                      if ln.strip()]
    cfg = ControlConfig(admission=True, shed_high_water=a.shed_high,
                        shed_low_water=a.shed_low)
    gate = AdmissionController(cfg, a.state_dir, rank=a.rank)
    ledger = QuarantineLedger(os.path.join(
        a.state_dir, f"quarantine.rank{a.rank}.jsonl"))
    sched = Scheduler(files, a.state_dir, rank=a.rank,
                      n_ranks=a.n_ranks, lease_ttl_s=a.ttl,
                      poll_s=min(a.ttl / 5.0, 0.25), ledger=ledger,
                      chaos=monkey, heartbeat=hb, admission=gate)
    processed, committed = [], []
    for f in sched.claim_iter():
        processed.append(os.path.basename(f))
        if a.hold_s:
            time.sleep(a.hold_s)
        if sched.commit(f):
            committed.append(os.path.basename(f))
        if a.telemetry:
            # a SIGKILL between this commit and the next claim must
            # not lose the commit's counter — the drill's /metrics
            # audit is EXACT
            TELEMETRY.flush()
    out = {"rank": a.rank, "processed": processed,
           "committed": committed, "stats": sched.stats}
    tmp = os.path.join(a.state_dir, f".result.rank{a.rank}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f)
    os.replace(tmp, os.path.join(a.state_dir,
                                 f"result.rank{a.rank}.json"))
    if a.telemetry:
        TELEMETRY.close()
    hb.stop(final_stage="drill.control.done")
    return 0


if __name__ == "__main__":
    _argv = sys.argv[1:]
    if "--worker" in _argv:
        raise SystemExit(_worker_main(_argv))
    import argparse as _ap

    _p = _ap.ArgumentParser(prog="control-drill")
    _p.add_argument("workdir")
    _p.add_argument("--seed", type=int, default=0)
    _p.add_argument("--timeout-s", type=float, default=120.0)
    _a = _p.parse_args(_argv)
    _ev = run_control_drill(_a.workdir, seed=_a.seed,
                            timeout_s=_a.timeout_s)
    print(json.dumps(_ev, indent=2))
