"""The control-plane decision ledger: every action, auditable.

A control loop that acts silently is indistinguishable from a bug, so
every decision any loop takes — spawn a rank, shed a file, switch a
preconditioner — lands twice:

- one line in ``decisions.{writer}.jsonl`` in the run's state
  directory (per-writer files: JSONL appends only interleave safely
  with one writer per file, the quarantine-ledger discipline — the
  supervisor writes ``decisions.supervisor.jsonl``, rank ``r``'s
  admission gate ``decisions.rank{r}.jsonl``);
- one ``control.decision`` telemetry counter with ``loop``/``action``
  attributes, which the live plane exports generically as
  ``comap_control_decision_total`` and ``tools/campaign_watch.py``
  surfaces in its live view.

Entry schema (one JSON object per line)::

    {"schema": 1, "t": "2026-08-07T07:00:00Z", "t_unix": 1786…,
     "loop": "autoscaler" | "admission" | "solver",
     "action": "spawn" | "retire" | "shed_on" | "shed_off" | "defer"
               | "readmit" | "override" | ...,
     "reason": "...", ...loop-specific attributes...}

Reading is merge-all-writers sorted by ``t_unix``, torn lines dropped
— the same tolerance as every JSONL reader here.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import time

from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["DECISION_SCHEMA", "decisions_path", "decisions_paths",
           "read_decisions", "record_decision"]

logger = logging.getLogger("comapreduce_tpu")

DECISION_SCHEMA = 1


def decisions_path(state_dir: str, writer: str = "supervisor") -> str:
    return os.path.join(state_dir or ".", f"decisions.{writer}.jsonl")


def decisions_paths(state_dir: str) -> list:
    return sorted(_glob.glob(os.path.join(state_dir or ".",
                                          "decisions.*.jsonl")))


def record_decision(state_dir: str, loop: str, action: str,
                    reason: str, writer: str = "supervisor",
                    **attrs) -> dict:
    """Append one decision (torn-line-safe) + fire the telemetry
    counter + log it. I/O failures are logged and swallowed — the
    decision was already TAKEN; bookkeeping must not undo it."""
    entry = {"schema": DECISION_SCHEMA,
             "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "t_unix": time.time(), "loop": str(loop),
             "action": str(action), "reason": str(reason)}
    entry.update(attrs)
    logger.warning("control decision [%s] %s: %s", loop, action, reason)
    TELEMETRY.counter("control.decision", 1, loop=str(loop),
                      action=str(action))
    path = decisions_path(state_dir, writer)
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        needs_nl = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
        with open(path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_nl else "")
                    + json.dumps(entry, separators=(",", ":"),
                                 default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as exc:
        logger.warning("decision ledger append to %s failed (%s: %s)",
                       path, type(exc).__name__, exc)
    return entry


def read_decisions(source) -> list:
    """All decisions merged across writers, sorted by ``t_unix``.
    ``source``: a state directory, one path, or a list of paths.
    Torn/garbled lines are dropped, never fatal."""
    if isinstance(source, (list, tuple)):
        paths = [str(p) for p in source]
    elif os.path.isdir(source):
        paths = decisions_paths(source)
    else:
        paths = [str(source)]
    out = []
    for path in paths:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if isinstance(rec, dict) and "loop" in rec:
                out.append(rec)
    out.sort(key=lambda r: r.get("t_unix") or 0.0)
    return out
