"""The autotuner's durable winners ledger + the ``TUNING`` singleton.

One measurement campaign per (backend platform, device kind, shape
bucket, precision policy, knob-space version) is enough: the winning
knob values are a property of the hardware and the compiled program,
not of the run that happened to measure them. This module makes the
winners durable — ``tuning.jsonl`` under ``[Global] log_dir``, one
sealed JSON object per line with the quarantine/served ledgers'
torn-line append discipline — so a second campaign run (or a second
*rank*) re-measures nothing.

Record schema (one JSON object per line; ``_sha256`` is the PR 18
embedded line seal)::

    {"schema": 1, "kind": "tuning", "key": "9f2c...", "group": "plan",
     "platform": "cpu", "device_kind": "cpu", "bucket": {"N": 36864,
     "L": 50}, "precision_id": "tod=f32|cgdot=f32",
     "space_version": 1, "winner": {"pair_batch": 4},
     "default": {"pair_batch": 1}, "best_ms": 8.1, "default_ms": 11.9,
     "candidates": 4, "measurements": 9,
     "t": "2026-08-07T07:00:00Z", "_sha256": "..."}

``key`` is a CONTENT hash — sha256 over the canonical (sorted-keys,
tight-separators) JSON of the identity tuple — so two processes
building the key from differently-ordered bucket dicts agree, and a
knob-space revision (``space.SPACE_VERSION``) invalidates every stale
winner at once instead of silently applying measurements of a space
that no longer exists. Reads are latest-wins per key with torn and
seal-violating lines dropped (``COMAP_VERIFY_READS`` honoured like
every other ledger).

The process-wide :data:`TUNING` singleton is the integration surface:
``plan_stage_feed_batch``, ``build_pointing_plan`` and the destriper
config layer ask it for winners behind the strict ``[tuning]`` config
table. Disabled (the default — absent table) every lookup is None and
the callers' behaviour is byte-identical to the untuned pipeline.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

__all__ = ["TUNING", "TuningCache", "TuningConfig", "content_key",
           "read_tuning", "tuning_path"]

logger = logging.getLogger("comapreduce_tpu")

TUNING_SCHEMA = 1


def tuning_path(directory: str) -> str:
    return os.path.join(directory or ".", "tuning.jsonl")


def content_key(platform: str, device_kind: str, bucket,
                precision_id: str = "", space_version: int = 1,
                group: str = "") -> str:
    """Content hash of one winner's identity.

    ``bucket`` may be a dict, tuple/list, or scalar — it is embedded
    in canonical sorted-keys JSON, so two callers passing the same
    bucket with different dict insertion orders produce the SAME key
    (asserted in tests). Changing any identity field — including the
    knob-space version — changes the key, which is how a space
    revision retires every old winner without a migration."""
    ident = {"platform": str(platform), "device_kind": str(device_kind),
             "bucket": bucket, "precision_id": str(precision_id),
             "space_version": int(space_version), "group": str(group)}
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def read_tuning(source) -> dict:
    """``{key: record}`` from a directory (its ``tuning.jsonl``) or one
    path — latest-wins per key; torn, unparseable and seal-violating
    lines dropped (the house JSONL reader contract)."""
    from comapreduce_tpu.resilience.integrity import check_line

    path = tuning_path(source) if os.path.isdir(str(source)) \
        else str(source)
    latest: dict = {}
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return latest
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            body, verdict = check_line(line.decode("utf-8", "replace"))
        except Exception:
            continue
        if body is None or verdict is False:
            continue
        if not isinstance(body, dict) or body.get("kind") != "tuning":
            continue
        key = body.get("key")
        if key:
            latest[str(key)] = body
    return latest


class TuningCache:
    """The winners ledger: latest-wins reads, sealed torn-line-safe
    appends, and hit/miss accounting (the check_perf warm-cache gate
    asserts a warm second run is ALL hits and ZERO measurements)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._records: dict | None = None
        self.hits = 0
        self.misses = 0

    def load(self) -> dict:
        with self._lock:
            if self._records is None:
                self._records = read_tuning(self.path)
            return self._records

    def get(self, key: str) -> dict | None:
        rec = self.load().get(str(key))
        with self._lock:
            if rec is None:
                self.misses += 1
            else:
                self.hits += 1
        return rec

    def put(self, record: dict) -> dict:
        """Seal and append one winner record (and serve it to this
        process's later gets without a re-read). I/O failure is logged
        and swallowed — a read-only log_dir costs durability, never
        the sweep's result."""
        from comapreduce_tpu.resilience.integrity import seal_line

        rec = dict(record)
        rec.setdefault("schema", TUNING_SCHEMA)
        rec.setdefault("kind", "tuning")
        rec.setdefault("t", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()))
        line = seal_line(rec)
        with self._lock:
            if self._records is None:
                self._records = read_tuning(self.path)
            self._records[str(rec.get("key"))] = rec
        try:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            needs_nl = False
            try:
                with open(self.path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    needs_nl = f.read(1) != b"\n"
            except OSError:
                pass
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(("\n" if needs_nl else "") + line + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as exc:
            logger.warning("tuning cache append to %s failed (%s: %s)",
                           self.path, type(exc).__name__, exc)
        return rec


class TuningConfig:
    """The strict ``[tuning]`` table (TOML) / ``[Tuning]`` section
    (INI). Absent table = disabled = byte-identical pipeline; a typo'd
    knob raises at config load (the ``[Destriper]``/``[Resilience]``
    contract).

    - ``enabled``         consult (and, for the sweep tools, write)
      the winners cache. Default False.
    - ``device_hbm_mb``   declared accelerator memory for the HBM
      auto-sizers when the backend cannot report it (GPU backends
      without ``memory_stats``); 0 = ask the backend. Feeds
      ``ops.reduce.device_hbm_bytes`` (satellite: no more silent
      16 GB guess).
    - ``max_candidates``  grid cap per sweep after the cost-prior
      prune (default 8).
    - ``repeats``         repetitions the successive-halving schedule
      grows to for the surviving candidates (default 3).
    - ``min_improvement`` the noise floor: a measured winner must beat
      the default by this fraction or the default is kept (default
      0.05 — tuned knobs can then never be slower than defaults
      beyond noise, which check_perf gates).
    """

    KNOBS = ("enabled", "device_hbm_mb", "max_candidates", "repeats",
             "min_improvement")

    def __init__(self, enabled: bool = False, device_hbm_mb: int = 0,
                 max_candidates: int = 8, repeats: int = 3,
                 min_improvement: float = 0.05):
        self.enabled = bool(enabled)
        self.device_hbm_mb = int(device_hbm_mb)
        self.max_candidates = int(max_candidates)
        self.repeats = int(repeats)
        self.min_improvement = float(min_improvement)
        if self.device_hbm_mb < 0:
            raise ValueError(f"[tuning] device_hbm_mb must be >= 0 "
                             f"(0 = ask the backend), got "
                             f"{device_hbm_mb!r}")
        if self.max_candidates < 1:
            raise ValueError(f"[tuning] max_candidates must be >= 1, "
                             f"got {max_candidates!r}")
        if self.repeats < 1:
            raise ValueError(f"[tuning] repeats must be >= 1, got "
                             f"{repeats!r}")
        if not 0.0 <= self.min_improvement < 1.0:
            raise ValueError(f"[tuning] min_improvement must be in "
                             f"[0, 1), got {min_improvement!r}")

    @classmethod
    def coerce(cls, value) -> "TuningConfig":
        """None / dict / TuningConfig -> TuningConfig; unknown keys
        raise (fail at config load, before any campaign-scale work).
        A non-empty dict without an explicit ``enabled`` knob means
        the operator wrote the table to turn the tuner on."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown tuning keys: {sorted(unknown)} "
                    f"(knobs: {list(cls.KNOBS)})")
            if known and "enabled" not in known:
                known["enabled"] = True
            if "enabled" in known:
                known["enabled"] = _as_bool(known["enabled"])
            return cls(**known)
        raise TypeError(f"cannot build TuningConfig from {type(value)}")


def _as_bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def _backend_identity() -> tuple:
    """(platform, device_kind) of local device 0, best-effort — the
    cache key's hardware axes. '' fields mean "unknown backend" and
    still key consistently within a process."""
    try:
        import jax

        dev = jax.local_devices()[0]
        return (str(jax.default_backend()),
                str(getattr(dev, "device_kind", "")))
    except Exception:
        return ("", "")


class TuningRuntime:
    """Process-wide tuned-knob lookup (the TELEMETRY/PROGRAMS shape:
    disabled it costs one attribute check; ``configure`` binds it to a
    run's log_dir, ``close`` resets for the next run/test)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._cache: TuningCache | None = None
        self._config = TuningConfig()
        self._identity: tuple | None = None

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def config(self) -> TuningConfig:
        return self._config

    @property
    def cache(self) -> TuningCache | None:
        return self._cache

    def configure(self, log_dir: str,
                  config: TuningConfig | dict | None = None
                  ) -> "TuningRuntime":
        from comapreduce_tpu.ops.reduce import set_device_hbm_override

        cfg = TuningConfig.coerce(config)
        with self._lock:
            self._config = cfg
            self._cache = TuningCache(tuning_path(log_dir))
            self._enabled = cfg.enabled
            self._identity = None
        set_device_hbm_override(cfg.device_hbm_mb << 20
                                if cfg.device_hbm_mb else 0)
        return self

    def close(self) -> None:
        from comapreduce_tpu.ops.reduce import set_device_hbm_override

        with self._lock:
            self._enabled = False
            self._cache = None
            self._config = TuningConfig()
            self._identity = None
        set_device_hbm_override(0)

    def identity(self) -> tuple:
        with self._lock:
            if self._identity is None:
                self._identity = _backend_identity()
            return self._identity

    def winner(self, group: str, bucket, precision_id: str = ""
               ) -> dict | None:
        """The cached winning knob dict for one (group, bucket) on this
        process's backend, or None (disabled / never measured). The
        cache counts the hit either way — the warm-cache gate's
        observable."""
        if not self._enabled or self._cache is None:
            return None
        from comapreduce_tpu.tuning.space import SPACE_VERSION

        platform, device_kind = self.identity()
        key = content_key(platform, device_kind, bucket,
                          precision_id=precision_id,
                          space_version=SPACE_VERSION, group=group)
        rec = self._cache.get(key)
        if rec is None:
            return None
        win = rec.get("winner")
        return dict(win) if isinstance(win, dict) else None


TUNING = TuningRuntime()
