"""The measurement loop: compile, wall-time, halve, memoise.

One :meth:`Tuner.tune` call answers "which knob values win for THIS
(platform, device kind, shape bucket, precision)?" by measuring the
caller's *actual* programs — the ``build(combo)`` hook returns a
zero-arg thunk that runs the real jitted/compiled program once (the
campaign warm-up's ``warm_programs`` / ``lower().compile()`` products,
never a proxy kernel) — and writes the winner to the durable cache so
the question is never asked twice.

Sweep cost is bounded three ways:

- **validity first**: only combos the knob space's validators accept
  are measured (``space.enumerate_group``; the tuner re-validates and
  counts ``invalid_proposed``, gated == 0 by check_perf);
- **cost prior**: an optional ``prior(combo) -> float | None`` (the
  PR 15 program registry's ``cost_analysis``/``memory_analysis``
  numbers, per name x bucket x precision) orders the grid
  cheapest-predicted-first and caps it at ``max_candidates`` — the
  pruned tail is reported, never silently dropped;
- **successive halving**: every survivor gets 1 timed repetition,
  the better half survives to 2, then 4, ... up to ``repeats`` — so
  the full repeat budget is only ever spent on the final contenders
  (total measurements <= n + 2*ceil(n/2) + ... ~ O(n + r log n),
  instead of n*r for the flat grid).

The measured winner must beat the default combo by
``min_improvement`` (the noise floor) or the default is kept — and a
challenger that crosses the floor on sweep walls must HOLD it on a
fresh interleaved paired re-measurement against the default (paired
reps cancel drift; a min-of-few sweep wall can overfit a transient
quiet moment). Tuned knobs can never be slower than defaults beyond
noise, by construction — the property the check_perf autotune gate
asserts.
"""

from __future__ import annotations

import logging
import math
import time

from comapreduce_tpu.tuning.cache import TuningCache, content_key
from comapreduce_tpu.tuning.space import (SPACE_VERSION, SpaceContext,
                                          enumerate_group,
                                          validate_combo)

__all__ = ["Tuner", "registry_prior"]

logger = logging.getLogger("comapreduce_tpu")


def _combo_id(combo: dict) -> str:
    return "|".join(f"{k}={combo[k]}" for k in sorted(combo))


def registry_prior(records: list, name: str = "") -> "callable":
    """A grid-pruning prior from PR 15 program-registry records: the
    predicted relative cost of a combo is the matching program's
    ``bytes_accessed`` (falling back to ``flops``), scaled by any
    knob that multiplies the per-dispatch working set. Returns a
    ``prior(combo) -> float | None`` for :meth:`Tuner.tune`; combos
    the registry knows nothing about rank None (measured, never
    assumed cheap)."""
    base = None
    for rec in records:
        if name and rec.get("name") != name:
            continue
        cost = rec.get("bytes_accessed") or rec.get("flops")
        if cost:
            base = min(base, float(cost)) if base else float(cost)

    def prior(combo: dict) -> float | None:
        if base is None:
            return None
        scale = 1.0
        for k in ("pair_batch", "feed_batch", "mg_smooth"):
            if k in combo:
                scale *= max(int(combo[k]), 1)
        return base * scale

    return prior


class Tuner:
    """Per-bucket knob sweeps against a durable winners cache.

    Counters (the autotune gate's observables): ``measurements`` —
    timed program runs this tuner performed; ``cache_hits`` /
    ``cache_misses`` — sweeps answered from / missing in the cache;
    ``invalid_proposed`` — combos that reached the measurement stage
    without passing validation (always 0 by construction);
    ``pruned`` — grid points dropped by the cost prior / candidate
    cap (reported per sweep record too)."""

    def __init__(self, cache: TuningCache, platform: str = "",
                 device_kind: str = "", max_candidates: int = 8,
                 repeats: int = 3, min_improvement: float = 0.05):
        self.cache = cache
        self.platform = str(platform)
        self.device_kind = str(device_kind)
        self.max_candidates = max(int(max_candidates), 1)
        self.repeats = max(int(repeats), 1)
        self.min_improvement = float(min_improvement)
        self.measurements = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.invalid_proposed = 0
        self.pruned = 0

    # -- measurement --------------------------------------------------

    def _time_once(self, thunk) -> float:
        t0 = time.perf_counter()
        thunk()
        self.measurements += 1
        return time.perf_counter() - t0

    def _best_of(self, thunk, reps: int) -> float:
        """Min-of-reps wall seconds — min, not mean: scheduling noise
        only ever adds time, so the minimum is the least-noisy
        estimate of the program's true cost."""
        return min(self._time_once(thunk) for _ in range(reps))

    # -- the sweep ----------------------------------------------------

    def tune(self, group: str, bucket, ctx: SpaceContext, build,
             default: dict, precision_id: str = "",
             candidates: list | None = None, prior=None) -> dict:
        """Measure (or recall) the winning combo for one group/bucket.

        ``build(combo)`` -> zero-arg thunk running the actual program
        once (compile cost lands outside the timed reps: the thunk is
        called once untimed as warm-up). ``default`` is the pipeline's
        untuned combo — always measured, and kept unless a candidate
        beats it beyond the noise floor. Returns the full cache
        record; its ``winner`` field is the knob dict to apply."""
        key = content_key(self.platform, self.device_kind, bucket,
                          precision_id=precision_id,
                          space_version=SPACE_VERSION, group=group)
        cached = self.cache.get(key)
        if cached is not None:
            self.cache_hits += 1
            return cached
        self.cache_misses += 1
        sweep_start = self.measurements

        if candidates is None:
            candidates = enumerate_group(group, ctx).combos
        combos = []
        for combo in candidates:
            if not validate_combo(group, combo, ctx):
                # never measured: validation is the wall the gate
                # asserts holds (invalid_proposed == 0)
                self.invalid_proposed += 1
                continue
            combos.append(dict(combo))
        if not any(c == default for c in combos):
            combos.insert(0, dict(default))

        if prior is not None and len(combos) > 1:
            ranked = sorted(combos,
                            key=lambda c: (prior(c) is None,
                                           prior(c) or 0.0))
            kept = ranked[:self.max_candidates]
            # the default must survive any prune: the noise-floor
            # comparison below is against its measured wall
            if not any(c == default for c in kept):
                kept[-1] = dict(default)
            self.pruned += len(combos) - len(kept)
            combos = kept
        elif len(combos) > self.max_candidates:
            kept = combos[:self.max_candidates]
            if not any(c == default for c in kept):
                kept[-1] = dict(default)
            self.pruned += len(combos) - len(kept)
            combos = kept

        # successive halving: 1 rep for everyone, the faster half
        # advances to doubled reps, until one combo (or the rep budget)
        # remains
        walls = {}
        pool = []
        thunks = {}
        for combo in combos:
            try:
                thunk = build(combo)
                thunk()  # warm-up: compile cost stays untimed
            except Exception as exc:
                logger.warning("tuning: candidate %s failed to "
                               "build/warm (%s: %s) — dropped",
                               _combo_id(combo), type(exc).__name__,
                               exc)
                continue
            pool.append((combo, thunk))
            thunks[_combo_id(combo)] = thunk
        reps = 1
        while pool:
            timed = []
            for combo, thunk in pool:
                wall = self._best_of(thunk, reps)
                cid = _combo_id(combo)
                walls[cid] = min(walls.get(cid, math.inf), wall)
                timed.append((wall, combo, thunk))
            timed.sort(key=lambda t: t[0])
            if len(pool) == 1 or reps >= self.repeats:
                break
            pool = [(c, th) for _, c, th in
                    timed[:max(len(timed) // 2, 1)]]
            reps = min(reps * 2, self.repeats)

        default_id = _combo_id(default)
        default_ms = walls.get(default_id)
        best_id, best_wall = None, math.inf
        best_combo = dict(default)
        for combo in combos:
            cid = _combo_id(combo)
            if cid in walls and walls[cid] < best_wall:
                best_id, best_wall = cid, walls[cid]
                best_combo = dict(combo)
        if (default_ms is not None and best_id is not None
                and best_id != default_id
                and best_wall < default_ms * (1.0
                                              - self.min_improvement)):
            # paired confirmation: a challenger that crossed the floor
            # on sweep walls must hold it on fresh INTERLEAVED reps
            # against the default — min-of-few walls overfit transient
            # scheduler noise, and a noise winner taxes every later
            # campaign that consults the cache
            for _ in range(max((self.repeats + 1) // 2, 1)):
                walls[default_id] = min(
                    walls[default_id],
                    self._time_once(thunks[default_id]))
                walls[best_id] = min(
                    walls[best_id], self._time_once(thunks[best_id]))
            default_ms = walls[default_id]
            best_wall = walls[best_id]
        winner = dict(default)
        if (default_ms is not None and best_id is not None
                and best_id != default_id
                and best_wall < default_ms * (1.0
                                              - self.min_improvement)):
            winner = best_combo

        record = {
            "key": key, "group": str(group),
            "platform": self.platform,
            "device_kind": self.device_kind, "bucket": bucket,
            "precision_id": str(precision_id),
            "space_version": SPACE_VERSION,
            "winner": winner, "default": dict(default),
            "best_ms": round(best_wall * 1e3, 4)
            if best_wall < math.inf else None,
            "default_ms": round(default_ms * 1e3, 4)
            if default_ms is not None else None,
            "candidates": len(combos),
            "measurements": self.measurements - sweep_start,
            "walls_ms": {cid: round(w * 1e3, 4)
                         for cid, w in sorted(walls.items())},
        }
        return self.cache.put(record)
