"""Shape-bucket autotuning (ISSUE 20): measurement-driven knob
selection, memoised into the campaign plan.

- :mod:`~comapreduce_tpu.tuning.space` — the declarative knob space,
  with validity rules reusing the pipeline's own validators so a
  sweep can never propose an invalid combo;
- :mod:`~comapreduce_tpu.tuning.tuner` — per-(platform, device kind,
  shape bucket, precision) sweeps over the *actual* compiled
  programs, pruned by the program-registry cost prior and bounded by
  successive halving;
- :mod:`~comapreduce_tpu.tuning.cache` — the durable ``tuning.jsonl``
  winners ledger (sealed lines, torn-line-safe appends, content-hash
  keys) plus the process-wide :data:`TUNING` lookup the integration
  points consult behind the strict ``[tuning]`` config table.

Absent ``[tuning]`` table = TUNING disabled = byte-identical pipeline.
"""

from comapreduce_tpu.tuning.cache import (TUNING, TuningCache,
                                          TuningConfig, content_key,
                                          read_tuning, tuning_path)
from comapreduce_tpu.tuning.space import (SPACE_VERSION, SpaceContext,
                                          enumerate_group, plan_bucket,
                                          solver_bucket, stage_bucket,
                                          validate_combo)
from comapreduce_tpu.tuning.tuner import Tuner, registry_prior

__all__ = ["SPACE_VERSION", "SpaceContext", "TUNING", "Tuner",
           "TuningCache", "TuningConfig", "content_key",
           "enumerate_group", "plan_bucket", "read_tuning",
           "registry_prior", "solver_bucket", "stage_bucket",
           "tuning_path", "validate_combo"]
