"""The declarative knob space the autotuner sweeps.

Every hot path's free performance knob, with its candidate values and
— the part that keeps sweeps safe — its VALIDITY rule, expressed by
calling the same validators the pipeline itself trusts at run time:

- ``feed_batch``  (group ``stage``): stage feed chunks, validated by
  :func:`~comapreduce_tpu.ops.reduce.plan_stage_feed_batch` /
  :func:`~comapreduce_tpu.ops.reduce.plan_reduce_memory` — a candidate
  the HBM planner would shrink or reject is never proposed;
- ``pair_batch``  (group ``plan``): merged one-hot binning windows,
  validated by the planner's own budget rule (the merged one-hot must
  fit ``device_hbm_bytes()/64``, exactly ``build_pointing_plan``'s
  auto rule) and, for the Pallas kernels, by
  :func:`~comapreduce_tpu.mapmaking.pallas_binning.pallas_binning_ok`;
- ``mg_block`` / ``mg_smooth`` (group ``solver``): the multigrid
  ladder's geometry, validated by
  ``destriper._check_precond`` plus the config layer's range rules
  (``mg_block >= 2``, ``mg_smooth >= 1`` — ``parse_destriper_section``)
  and the ladder-buildability rule (a block larger than the offset
  count has no level to build);
- ``kernels``     (group ``solver``): the binning/gather
  implementation — ``pallas`` is only proposed where
  ``pallas_binning_ok`` accepts the bucket's window geometry (and the
  backend is TPU).

:func:`enumerate_group` returns only combos that pass every rule and
counts what it filtered (``SpaceResult.invalid_filtered``) — the
check_perf autotune gate asserts the tuner never *measured* an
invalid combo (``invalid_proposed == 0``), which this module makes
true by construction.

``SPACE_VERSION`` is part of every cache key (``cache.content_key``):
revising the candidate grid or a validity rule bumps it and retires
every stale winner at once.
"""

from __future__ import annotations

import dataclasses

__all__ = ["SPACE_VERSION", "SpaceContext", "SpaceResult",
           "enumerate_group", "plan_bucket", "solver_bucket",
           "stage_bucket", "validate_combo"]

SPACE_VERSION = 1

#: candidate grids per knob — the measured ROOFLINE levers (pair_batch
#: mirrors pointing_plan._PAIR_BATCH_CANDIDATES; mg_block spans the
#: r10 sweep where 32 converged and 8/16 diverged on spread weights)
CANDIDATES = {
    "feed_batch": (1, 2, 4, 8, 19),
    "pair_batch": (1, 2, 4, 8),
    "mg_block": (8, 16, 32),
    "mg_smooth": (1, 2),
    "kernels": ("xla", "pallas"),
}

GROUPS = ("stage", "plan", "solver")


@dataclasses.dataclass(frozen=True)
class SpaceContext:
    """The shape/backend facts validity is judged against — one bucket
    of one campaign. Axes default to 0 = "not constrained" so each
    group only needs its own geometry filled in."""

    F: int = 0           # feeds
    B: int = 0           # bands
    C: int = 0           # channels
    T: int = 0           # samples per scan axis (stage group)
    S: int = 0           # scans
    L: int = 0           # padded scan-block / offset length
    n_samples: int = 0   # flat destriper sample count (plan group)
    offset_length: int = 0
    n_arrays: int = 1
    platform: str = ""
    hbm_bytes: int = 0   # 0 = ask device_hbm_bytes()


@dataclasses.dataclass
class SpaceResult:
    combos: list
    invalid_filtered: int


def stage_bucket(F: int, B: int, C: int, T: int,
                 n_arrays: int = 1) -> dict:
    """The stage group's cache-key bucket (the feed-batched program's
    shape identity)."""
    return {"group": "stage", "F": int(F), "B": int(B), "C": int(C),
            "T": int(T), "n_arrays": int(n_arrays)}


def plan_bucket(n_samples: int, offset_length: int) -> dict:
    """The plan group's cache-key bucket: the pointing plan's flat
    sample count and offset length (the two axes the merged one-hot
    geometry depends on)."""
    return {"group": "plan", "N": int(n_samples),
            "L": int(offset_length)}


def solver_bucket(offset_length: int, n_samples: int = 0) -> dict:
    """The solver group's cache-key bucket. ``n_samples`` may be 0 at
    config time (the destriper CLI keys on offset length before any
    file is read); sweeps that know the flat length include it."""
    out = {"group": "solver", "L": int(offset_length)}
    if n_samples:
        out["N"] = int(n_samples)
    return out


def _hbm(ctx: SpaceContext) -> int:
    from comapreduce_tpu.ops.reduce import device_hbm_bytes

    return int(ctx.hbm_bytes) or device_hbm_bytes()


def _valid_feed_batch(fb: int, ctx: SpaceContext) -> bool:
    """A feed_batch candidate is valid iff the stage HBM planner keeps
    it as-is (would neither shrink nor reject it) and the reduce-chain
    planner accepts it with some scan streaming."""
    from comapreduce_tpu.ops.reduce import (plan_reduce_memory,
                                            plan_stage_feed_batch)

    if ctx.F and fb > ctx.F:
        return False
    hbm = _hbm(ctx)
    kept = plan_stage_feed_batch(ctx.F or fb, ctx.B, ctx.C, ctx.T,
                                 requested=fb, n_arrays=ctx.n_arrays,
                                 hbm_bytes=hbm)
    if kept != fb:
        return False
    if ctx.S and ctx.L:
        try:
            plan_reduce_memory(fb, ctx.B, ctx.C, ctx.T, ctx.S, ctx.L,
                               scan_batch=None, hbm_bytes=hbm)
        except ValueError:
            return False
    return True


def _valid_pair_batch(pb: int, ctx: SpaceContext,
                      pair_chunk: int = 4096) -> bool:
    """``build_pointing_plan``'s auto budget rule, applied to a
    candidate: the merged chunk's one-hot block (chunk x window, f32)
    must fit the planner's budget. The true window needs the built
    plan; the conservative bound here is the merged chunk's own id
    span (window <= chunk_eff rounded to the 128 alignment), which is
    exact for dense rank spaces — the regime batching targets."""
    from comapreduce_tpu.mapmaking.pointing_plan import _round_up

    budget = max(_hbm(ctx) // 64, 64 << 20)
    chunk_eff = pair_chunk * pb
    window = _round_up(min(chunk_eff,
                           max(ctx.n_samples // max(ctx.offset_length
                                                    or 1, 1), 1)),
                       128)
    return chunk_eff * window * 4 <= budget


def _valid_solver(combo: dict, ctx: SpaceContext) -> bool:
    """The destriper's own preconditioner rule plus the config layer's
    mg ranges and the ladder-buildability bound."""
    from comapreduce_tpu.mapmaking.destriper import _check_precond

    mg_block = int(combo.get("mg_block", 8))
    mg_smooth = int(combo.get("mg_smooth", 1))
    if mg_block < 2 or mg_smooth < 1:
        return False
    mg = {"levels": 2, "smooth": mg_smooth, "block": mg_block}
    try:
        _check_precond("jacobi", coarse=None, mg=mg)
    except ValueError:
        return False
    if ctx.n_samples and ctx.offset_length:
        n_offsets = ctx.n_samples // max(ctx.offset_length, 1)
        if mg_block >= max(n_offsets, 2):
            return False  # no coarse level to build
    kern = str(combo.get("kernels", "xla"))
    if kern == "pallas":
        if ctx.platform and ctx.platform != "tpu":
            return False
        from comapreduce_tpu.mapmaking.pallas_binning import \
            pallas_binning_ok

        window = 128 * max(int(combo.get("pair_batch", 1)), 1)
        if not pallas_binning_ok(window, 4096):
            return False
    return True


def validate_combo(group: str, combo: dict, ctx: SpaceContext) -> bool:
    """True iff ``combo`` passes the group's validity rules — the rule
    the tuner re-checks before measuring anything (belt and braces:
    enumerate_group only yields valid combos in the first place)."""
    if group == "stage":
        return _valid_feed_batch(int(combo.get("feed_batch", 1)), ctx)
    if group == "plan":
        return _valid_pair_batch(int(combo.get("pair_batch", 1)), ctx)
    if group == "solver":
        return _valid_solver(combo, ctx)
    raise ValueError(f"unknown tuning group {group!r} "
                     f"(groups: {list(GROUPS)})")


def enumerate_group(group: str, ctx: SpaceContext) -> SpaceResult:
    """All VALID candidate combos for one group at one bucket, plus
    the count of grid points the validity rules filtered out."""
    if group == "stage":
        grid = [{"feed_batch": fb} for fb in CANDIDATES["feed_batch"]]
    elif group == "plan":
        grid = [{"pair_batch": pb} for pb in CANDIDATES["pair_batch"]]
    elif group == "solver":
        grid = [{"mg_block": b, "mg_smooth": s}
                for b in CANDIDATES["mg_block"]
                for s in CANDIDATES["mg_smooth"]]
        if ctx.platform == "tpu":
            grid = [dict(g, kernels=k) for g in grid
                    for k in CANDIDATES["kernels"]]
    else:
        raise ValueError(f"unknown tuning group {group!r} "
                         f"(groups: {list(GROUPS)})")
    combos = [g for g in grid if validate_combo(group, g, ctx)]
    return SpaceResult(combos=combos,
                       invalid_filtered=len(grid) - len(combos))
