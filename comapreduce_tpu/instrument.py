"""Instrument constants: feed focal-plane layout and beam widths.

The reference ships these as packaged data files
(``data/COMAP_FEEDS.dat``: per-feed focal-plane offsets;
``data/AverageBeamWidths.dat``: per-feed beam FWHM) with loaders in
``data/Data.py``. The actual COMAP tables are observatory data and not in
this repository; this module provides (a) parsers for the same
whitespace-column file format, and (b) a documented synthetic default —
the 19-feed hexagonal close-packed layout the real array approximates —
so every pipeline path runs without the proprietary files.
"""

from __future__ import annotations

import numpy as np

__all__ = ["feed_positions", "beam_widths", "load_feed_positions",
           "load_beam_widths", "N_FEEDS", "NOMINAL_BEAM_FWHM_DEG"]

N_FEEDS = 19
NOMINAL_BEAM_FWHM_DEG = 4.5 / 60.0  # 4.5 arcmin at 30 GHz
_HEX_SPACING_DEG = 0.2              # ~12 arcmin feed separation


def feed_positions(n_feeds: int = N_FEEDS,
                   spacing_deg: float = _HEX_SPACING_DEG) -> np.ndarray:
    """(n_feeds, 2) focal-plane offsets [deg]: hexagonal rings around the
    boresight (feed 1 at centre, 6 in ring 1, 12 in ring 2)."""
    pts = [(0.0, 0.0)]
    ring = 1
    while len(pts) < n_feeds:
        for k in range(6 * ring):
            ang = 2 * np.pi * k / (6 * ring) + (0 if ring % 2 else
                                                np.pi / (6 * ring))
            pts.append((ring * spacing_deg * np.cos(ang),
                        ring * spacing_deg * np.sin(ang)))
            if len(pts) == n_feeds:
                break
        ring += 1
    return np.asarray(pts[:n_feeds])


def beam_widths(n_feeds: int = N_FEEDS,
                fwhm_deg: float = NOMINAL_BEAM_FWHM_DEG) -> np.ndarray:
    """(n_feeds,) beam FWHM [deg] — nominal uniform beam."""
    return np.full(n_feeds, fwhm_deg)


def load_feed_positions(path: str) -> np.ndarray:
    """Parse a ``COMAP_FEEDS.dat``-format file: whitespace columns
    ``feed x y``; returns (n_feeds, 2) [deg] ordered by feed number."""
    rows = []
    with open(path) as f:
        for line in f:
            s = line.split("#", 1)[0].split()
            if len(s) >= 3:
                rows.append((int(float(s[0])), float(s[1]), float(s[2])))
    rows.sort()
    return np.asarray([(x, y) for _, x, y in rows])


def load_beam_widths(path: str) -> np.ndarray:
    """Parse an ``AverageBeamWidths.dat``-format file: ``feed fwhm``
    (arcmin); returns (n_feeds,) FWHM [deg]."""
    rows = []
    with open(path) as f:
        for line in f:
            s = line.split("#", 1)[0].split()
            if len(s) >= 2:
                rows.append((int(float(s[0])), float(s[1])))
    rows.sort()
    return np.asarray([w for _, w in rows]) / 60.0
