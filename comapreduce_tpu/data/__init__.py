"""Host-side data model and I/O.

Mirrors the capability of the reference's ``Analysis/DataHandling.py``:
an in-memory dict view of a COMAP HDF5 file (Level-1 raw TOD, Level-2
reduced products) with lazy handling of the large raw TOD dataset, plus
the device-side ``TODBlock`` pytree that the JAX kernels consume.
"""

from comapreduce_tpu.data.hdf5io import HDF5Store  # noqa: F401
from comapreduce_tpu.data.level import COMAPLevel1, COMAPLevel2  # noqa: F401
from comapreduce_tpu.data.blocks import TODBlock, Level2Block  # noqa: F401
from comapreduce_tpu.data import scan_edges  # noqa: F401
from comapreduce_tpu.data.synthetic import (  # noqa: F401
    SyntheticObsParams,
    generate_level1_file,
)
