"""Scan segmentation (host side).

Capability parity with the reference ``RepointEdges``
(``Analysis/DataHandling.py:183-245``): a scan is a contiguous stretch where
the telescope is actually scanning (drive-tracker lissajous/CES status == 1,
interpolated onto the spectrometer time grid). Calibrator observations use
the min/max extent of the on-source feature flags instead; if the tracker
status is flat zero, fall back to feature bit 9.

Output convention: ``(n_scans, 2)`` int array of [start, end) sample indices
— note the reference treats edges as inclusive starts of consecutive runs;
we produce half-open intervals, which is what the padded device blocks and
``segment_sum`` want.
"""

from __future__ import annotations

import numpy as np

__all__ = ["previous_interp", "edges_from_status", "scan_edges_source",
           "scan_edges_calibrator", "segment_ids_from_edges"]


def previous_interp(x_new: np.ndarray, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Piecewise-previous interpolation with end extrapolation.

    Equivalent of ``scipy.interpolate.interp1d(kind='previous',
    fill_value='extrapolate')`` used at ``DataHandling.py:216-217`` — kept
    dependency-free and O(n log n).
    """
    idx = np.searchsorted(x, x_new, side="right") - 1
    idx = np.clip(idx, 0, len(x) - 1)
    return y[idx]


def edges_from_status(status: np.ndarray, code: int = 1) -> np.ndarray:
    """Half-open [start, end) runs where ``status == code``."""
    on = (status == code).astype(np.int8)
    d = np.diff(np.concatenate(([0], on, [0])))
    starts = np.where(d == 1)[0]
    ends = np.where(d == -1)[0]
    return np.stack([starts, ends], axis=1).astype(np.int64)


def scan_edges_source(scan_status: np.ndarray, scan_utc: np.ndarray,
                      mjd: np.ndarray, features: np.ndarray,
                      status_code: int = 1) -> np.ndarray:
    """Scan edges for field observations.

    Interpolate the drive tracker status onto the spectrometer MJD grid and
    take contiguous runs of ``status_code``. If the tracker never reports
    scanning, fall back to the span of feature bit 9
    (``DataHandling.py:218-226``).
    """
    if np.sum(scan_status) == 0:
        sel = np.where(features == 9)[0]
        if sel.size == 0:
            return np.zeros((0, 2), dtype=np.int64)
        return np.array([[sel[0], sel[-1] + 1]], dtype=np.int64)
    status = previous_interp(mjd, scan_utc, scan_status)
    return edges_from_status(status, status_code)


def scan_edges_calibrator(on_source: np.ndarray) -> np.ndarray:
    """Single scan spanning the on-source extent (``DataHandling.py:231-245``)."""
    idx = np.where(on_source)[0]
    if idx.size == 0:
        return np.zeros((0, 2), dtype=np.int64)
    return np.array([[idx.min(), idx.max() + 1]], dtype=np.int64)


def segment_ids_from_edges(edges: np.ndarray, n_samples: int) -> np.ndarray:
    """Per-sample scan id; -1 outside any scan.

    This is the bridge from ragged host-side scans to fixed-shape device
    arrays: kernels consume ``(tod, scan_ids, mask)`` and use segment
    reductions instead of Python scan loops.
    """
    ids = np.full(n_samples, -1, dtype=np.int32)
    for i, (s, e) in enumerate(np.asarray(edges, dtype=np.int64)):
        ids[s:e] = i
    return ids
