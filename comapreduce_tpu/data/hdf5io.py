"""Dict-backed HDF5 store with lazy large datasets.

Capability parity with the reference ``Analysis/DataHandling.py:40-179``
(``HDF5Data``): read a whole HDF5 file into a ``{path: array}`` mapping,
keeping designated large datasets (the raw TOD) as lazy h5py handles; write
appends/overwrites datasets and attributes into an existing file, which is
what makes the Level-2 file double as the pipeline checkpoint.

Differences by design (not omissions):

- reading collects datasets *and* attributes in one traversal, but attributes
  of groups that hold no dataset are kept too (the reference loses per-file
  root attrs unless visited);
- ``write`` never deletes unrelated paths, so concurrent stages appending
  disjoint groups compose;
- no global mutable singleton; stores are cheap value objects.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Iterable, Iterator

import h5py
import numpy as np

__all__ = ["HDF5Store", "safe_hdf5_open"]

logger = logging.getLogger("comapreduce_tpu")


def safe_hdf5_open(filename: str, mode: str = "r", retries: int = 10,
                   delay: float = 1.0, backoff: float = 1.5) -> h5py.File:
    """Open an HDF5 file, retrying while another writer holds the lock.

    Parity: ``Tools/FileTools.py:40-52`` ``safe_hdf5_open`` — on shared
    filesystems a Level-2 file may be mid-checkpoint by another rank; HDF5
    then raises ``BlockingIOError``/``OSError`` ("unable to lock file").
    Retries with exponential backoff, re-raising after ``retries``
    attempts. Non-locking errors (missing file, not an HDF5 file) raise
    immediately.
    """
    attempt = 0
    while True:
        try:
            return h5py.File(filename, mode)
        except (BlockingIOError, OSError) as err:
            msg = str(err).lower()
            locked = (isinstance(err, BlockingIOError)
                      or "lock" in msg
                      or "resource temporarily unavailable" in msg)
            if not locked or not os.path.exists(filename):
                raise
            attempt += 1
            if attempt > retries:
                raise
            logger.warning("safe_hdf5_open: %s locked, retry %d/%d in "
                           "%.1f s", filename, attempt, retries, delay)
            time.sleep(delay)
            delay *= backoff


@dataclass
class HDF5Store:
    """In-memory mirror of an HDF5 file: ``{path: ndarray | h5py.Dataset}``.

    ``lazy_paths`` entries stay as h5py dataset handles on read (sliceable,
    never fully materialised); everything else is read eagerly.
    """

    name: str = "HDF5Store"
    lazy_paths: tuple = ()
    _data: dict = field(default_factory=dict)
    _attrs: dict = field(default_factory=dict)
    _file: h5py.File | None = field(default=None, repr=False)
    # abspath of the file this store mirrors (set by read(); also set by a
    # from-scratch write) — gates the atomic-write fast path
    _mirrors: str = field(default="", repr=False)

    # -- mapping protocol ---------------------------------------------------
    def __getitem__(self, path: str):
        return self._data[path]

    def __setitem__(self, path: str, value) -> None:
        self._data[path] = value

    def __contains__(self, path: str) -> bool:
        return path in self._data

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def get(self, path: str, default=None):
        return self._data.get(path, default)

    # -- attributes ---------------------------------------------------------
    def attrs(self, path: str, key: str | None = None):
        """Attributes dict of ``path``, or a single attribute if ``key``."""
        if key is None:
            return self._attrs.get(path, {})
        return self._attrs[path][key]

    def set_attrs(self, path: str, key: str, value) -> None:
        self._attrs.setdefault(path, {})[key] = value

    def attr_items(self):
        return self._attrs.items()

    @property
    def groups(self) -> list[str]:
        """Unique top-level group names present in the store."""
        return sorted({p.split("/")[0] for p in self._data})

    def contains_groups(self, groups: Iterable[str]) -> bool:
        """True if every top-level group in ``groups`` is present.

        This is the resume test the runner uses to skip completed stages
        (reference ``DataHandling.py:432-437`` ``COMAPLevel2.contains``).
        """
        have = set(self.groups)
        return all(g.split("/")[0] in have for g in groups)

    # -- file I/O -----------------------------------------------------------
    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            finally:
                self._file = None

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.close()
        except Exception:
            # h5py module state may already be torn down at interpreter exit.
            pass

    @property
    def source_filename(self) -> str:
        """Absolute path this store mirrors: the file last ``read``, or —
        for a store that has only ever been written — the last full
        ``write`` target ('' before either)."""
        return self._mirrors

    def read(self, filename: str) -> "HDF5Store":
        """Read every dataset and attribute in ``filename`` into the store.

        Resets any previously-read content — a store mirrors exactly one file.
        """
        self.close()
        self._data = {}
        self._attrs = {}
        self._mirrors = os.path.abspath(filename)
        # verify-on-read: when this file was committed with an
        # integrity sidecar (atomic checkpoint writes do that), prove
        # the bytes still match before handing them to h5py — a
        # flipped bit in a checkpoint must raise CorruptArtifactError
        # (failure class "corrupt": unlink-and-rebuild), not decode
        # into a silently wrong map. Files without a sidecar (Level-1
        # inputs
        # staged outside the pipeline) read unverified, as ever.
        from comapreduce_tpu.resilience.integrity import verify_file

        verify_file(filename, kind="checkpoint")
        f = safe_hdf5_open(filename, "r")
        self._file = f
        # root attributes
        for k, v in f.attrs.items():
            self.set_attrs("", k, v)

        keep_open = False

        def visit(name: str, node) -> None:
            nonlocal keep_open
            for k, v in node.attrs.items():
                self.set_attrs(name, k, v)
            if isinstance(node, h5py.Dataset):
                if name in self.lazy_paths:
                    self._data[name] = node  # lazy handle; file stays open
                    keep_open = True
                else:
                    self._data[name] = node[...]

        f.visititems(visit)
        if not keep_open:
            # Don't hold a read lock when nothing stayed lazy — another store
            # must be able to append to this file (stage checkpointing).
            f.close()
            self._file = None
        return self

    def write(self, filename: str, atomic: bool = False,
              durable: bool = True) -> None:
        """Append/overwrite the store's datasets + attrs into ``filename``.

        Lazy (still-on-disk) datasets are skipped — they belong to the source
        file. An existing output file is opened in append mode so repeated
        stage checkpoints accumulate (reference ``DataHandling.py:110-139``).

        ``atomic=True`` stages the update in a temp copy and ``os.replace``s
        it into place, so a run killed mid-write never leaves a
        partially-written checkpoint — a resume would otherwise see a
        stage's group present but incomplete and skip it forever.
        ``durable=True`` (default) additionally fsyncs the temp file
        before the rename (and the directory after, on POSIX): without
        it a POWER CUT — unlike a mere kill — can commit the rename
        ahead of the data blocks and leave a zero-length "checkpoint"
        under the final name, defeating the corrupt-checkpoint recovery
        that trusts atomically-named files. ``durable=False`` trades
        that guarantee for write latency (scratch/throwaway outputs).
        """
        # If we hold an open read handle on this same path, release it first.
        if self._file is not None and os.path.abspath(
            getattr(self._file, "filename", "")
        ) == os.path.abspath(filename):
            self.close()

        if atomic:
            import shutil
            import tempfile

            d = os.path.dirname(os.path.abspath(filename))
            fd, tmp = tempfile.mkstemp(suffix=".hd5.tmp", dir=d)
            os.close(fd)
            # When the store fully mirrors the target (it read this very
            # file, or the file doesn't exist yet) and holds no lazy
            # handles, a fresh write is equivalent to copy+append and
            # skips copying the whole file every stage. A store that
            # never read an existing target must copy+append — rewriting
            # would delete datasets it doesn't hold.
            target = os.path.abspath(filename)
            fresh = (not any(isinstance(v, h5py.Dataset)
                             for v in self._data.values())
                     and (not os.path.exists(filename)
                          or self._mirrors == target))
            try:
                if os.path.exists(filename) and not fresh:
                    shutil.copy2(filename, tmp)
                    self._write_into(tmp, "a")
                else:
                    self._write_into(tmp, "w")
                    # the file now equals this store's content exactly
                    self._mirrors = target
                from comapreduce_tpu.resilience.integrity import (
                    committed_replace)

                # sidecar-first commit: the .s256 manifest lands before
                # the payload rename, so a kill between the two leaves
                # old-payload-under-new-sidecar — still verifiable via
                # the sidecar's digest history, never condemnable
                committed_replace(tmp, filename, kind="checkpoint",
                                  durable=durable)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            return

        mode = "a" if os.path.exists(filename) else "w"
        self._write_into(filename, mode)
        # an in-place append honestly mutated the bytes: re-seal an
        # existing sidecar so the stale manifest can't condemn them
        from comapreduce_tpu.resilience.integrity import refresh_sidecar

        refresh_sidecar(filename, kind="checkpoint", durable=durable)

    def _write_into(self, filename: str, mode: str) -> None:
        with safe_hdf5_open(filename, mode) as out:
            for path, value in self._data.items():
                if isinstance(value, h5py.Dataset):
                    continue
                if path in out:
                    del out[path]
                arr = np.asarray(value)
                out.create_dataset(path, data=arr)
            for path, kv in self._attrs.items():
                if path == "":
                    target = out
                elif path in out:
                    target = out[path]
                elif isinstance(self._data.get(path), h5py.Dataset):
                    # attrs of a still-lazy source dataset: creating a group
                    # at a dataset path would corrupt the schema — skip.
                    continue
                else:
                    target = out.require_group(path)
                for k, v in kv.items():
                    target.attrs[k] = v

    # -- ingest payloads ----------------------------------------------------
    def export_payload(self) -> dict:
        """Decoded-content snapshot for the ingest cache: ``{'data',
        'attrs', 'source'}`` with the dict *structure* copied (arrays
        shared). Lazy datasets must be materialised first — an open
        h5py handle is neither cacheable nor picklable."""
        for path, v in self._data.items():
            if isinstance(v, h5py.Dataset):
                raise ValueError(
                    f"export_payload: {path!r} is still a lazy h5py "
                    "handle; materialise it first")
        return {"data": dict(self._data),
                "attrs": {k: dict(v) for k, v in self._attrs.items()},
                "source": self._mirrors}

    def adopt_payload(self, payload: dict) -> "HDF5Store":
        """Rebuild this store from an :meth:`export_payload` snapshot.

        Dict structure is copied again on adoption, so two stores
        rebuilt from one cached payload never alias each other's
        mutable state (the arrays themselves are shared read-only).
        """
        self.close()
        self._data = dict(payload["data"])
        self._attrs = {k: dict(v) for k, v in payload["attrs"].items()}
        self._mirrors = payload.get("source", "")
        return self

    def materialise(self, path: str) -> np.ndarray:
        """Force a lazy dataset into memory and return it."""
        v = self._data[path]
        if isinstance(v, h5py.Dataset):
            v = v[...]
            self._data[path] = v
        return v
