"""Synthetic COMAP Level-1 observation generator.

The reference repo ships no data and no test suite; its only end-to-end test
is the destriper's inline simulation (``MapMaking/Destriper.py:505-612``:
1/f noise + power-law sky + Lissajous scan, eyeballed). This module is the
framework's stand-in for real data *and* the backbone of the asserted test
suite (SURVEY.md §4): it writes a physically-motivated Level-1 HDF5 file in
the real COMAP schema and returns the ground truth used to assert recovery.

Physical model per (feed, band, channel, sample):

    P = G * T_total * (1 + dg(t)),   T_total =
        vane in beam:  T_rx + T_vane
        sky:           T_rx + T_cmb + T_atm * airmass(t) + T_sky(ra, dec)

    noise: radiometer white noise with rms = G*T_total/sqrt(dnu/fs),
    dg(t): 1/f gain fluctuation with PSD (sigma_g^2/fs)*(f_knee/f)^alpha.

Scan pattern: constant-elevation (CES) azimuth triangle sweeps between vane
events at the start and end of the observation, mirroring a COMAP field obs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from comapreduce_tpu.data.hdf5io import HDF5Store

__all__ = ["SyntheticObsParams", "generate_level1_file",
           "generate_level1_store", "one_over_f_noise",
           "gaussian_source_sky"]

SAMPLE_RATE = 50.0  # Hz, reference Level1Averaging.py:808
FEATURE_VANE = 13
FEATURE_SCAN = 5


def one_over_f_noise(rng: np.random.Generator, n: int, sigma: float,
                     fknee: float, alpha: float, fs: float = SAMPLE_RATE,
                     size: tuple = ()) -> np.ndarray:
    """Generate noise with PSD ``sigma^2/fs * (1 + (fknee/f)^alpha)``.

    Shaping white Gaussian noise in rFFT space — same construction as the
    reference's destriper self-test noise (``Destriper.py:361-370``), with an
    explicit knee frequency.
    """
    freqs = np.fft.rfftfreq(n, d=1.0 / fs)
    shape = np.ones_like(freqs)
    shape[1:] = np.sqrt(1.0 + (fknee / freqs[1:]) ** alpha)
    shape[0] = 0.0  # zero-mean
    w = rng.normal(size=size + (n,))
    W = np.fft.rfft(w, axis=-1)
    return np.fft.irfft(W * shape, n=n, axis=-1) * sigma


def gaussian_source_sky(ra, dec, ra0, dec0, amplitude, fwhm_deg):
    """Elliptically-symmetric Gaussian source brightness in K at (ra, dec)."""
    sig = fwhm_deg / 2.355
    dx = (np.asarray(ra) - ra0) * np.cos(np.radians(np.asarray(dec)))
    dy = np.asarray(dec) - dec0
    return amplitude * np.exp(-0.5 * (dx**2 + dy**2) / sig**2)


@dataclass
class SyntheticObsParams:
    """Knobs for one synthetic observation. Defaults are COMAP-plausible but
    sized for tests; scale n_* up for benchmarks."""

    obsid: int = 1_000_001
    source: str = "co2"           # field name; use 'TauA' for calibrator obs
    n_feeds: int = 2
    n_bands: int = 4
    n_channels: int = 64          # 1024 in production
    n_scans: int = 4
    scan_samples: int = 2_000     # per scan
    vane_samples: int = 300       # per vane event
    gap_samples: int = 100        # slew between scans
    mjd_start: float = 59620.0    # after the vane-thermometry epoch switch
    # physics
    t_rx: float = 20.0            # receiver temperature, K
    t_atm_zenith: float = 10.0    # zenith atmosphere, K
    t_cmb: float = 2.73
    t_vane: float = 290.0         # hot-load physical temperature, K
    gain_mean: float = 2.0e7      # counts per K
    gain_spread: float = 0.2      # fractional per-channel gain scatter
    passband_curvature: float = 0.3  # fractional Trx rise at band edges
    t_rx_scatter: float = 0.05    # per-channel receiver temp scatter
    fknee: float = 1.0            # gain-fluctuation knee, Hz
    alpha: float = 1.5
    sigma_g: float = 5.0e-4       # per-sample rms of dg at f >> fknee
    elevation: float = 55.0       # deg
    # peak-to-peak elevation drift across the observation (deg) — >0
    # simulates a sky-nod / sky-dip elevation sweep
    el_sweep: float = 0.0
    comment: str = "synthetic observation"
    az_centre: float = 180.0
    az_throw: float = 4.0         # deg, peak-to-peak/2
    ra0: float = 170.0
    dec0: float = 52.0
    source_amplitude_k: float = 0.0   # K; >0 injects a Gaussian source
    source_fwhm_deg: float = 0.075    # ~4.5 arcmin COMAP beam
    # additive per-feed atmospheric 1/f temperature fluctuation (K). Unlike
    # the multiplicative dg(t) gain stream — which gain correction removes —
    # this survives reduction and is what the destriper (and the quality
    # ledger's noise fits) actually see. >0 enables it.
    t_atm_sigma: float = 0.0
    t_atm_fknee: float = 0.1          # Hz
    t_atm_alpha: float = 1.5
    # fault mix: fraction of (feed, band, channel, sample) cells hit
    spike_rate: float = 0.0           # multiplied 100x (cosmic-ray spikes)
    nan_rate: float = 0.0             # set to NaN (dropped packets)
    # optional sky model callable (lon_deg, lat_deg, freq_GHz) -> K,
    # e.g. ``simulations.skymodel.SkyModel``; evaluated per (feed, band)
    # at the band-centre frequency and added to t_sky.
    sky_model: object = None
    seed: int = 1234
    truth: dict = field(default_factory=dict, repr=False)

    @property
    def n_samples(self) -> int:
        return (2 * self.vane_samples
                + self.n_scans * self.scan_samples
                + (self.n_scans + 1) * self.gap_samples)


def _band_frequencies(n_bands: int, n_channels: int) -> np.ndarray:
    """COMAP band plan: 26-34 GHz in four 2 GHz bands (B, C) in GHz."""
    edges = 26.0 + 2.0 * np.arange(n_bands + 1)
    freq = np.zeros((n_bands, n_channels))
    for b in range(n_bands):
        df = (edges[b + 1] - edges[b]) / n_channels
        freq[b] = edges[b] + df * (0.5 + np.arange(n_channels))
    return freq


def generate_level1_store(params: SyntheticObsParams | None = None
                          ) -> tuple[SyntheticObsParams, HDF5Store]:
    """Build a synthetic Level-1 observation as an in-memory ``HDF5Store``.

    Returns ``(params, store)`` with ``params.truth`` filled in (per-channel
    gain/tsys, dg time stream, scan edges, sky). The store can be written to
    disk (``generate_level1_file``) or served directly through the ingest
    payload path (``comapreduce_tpu.synthetic.memsource``) — both see the
    same arrays, so campaigns are identical with or without disk.

    Determinism contract: all randomness derives from ``params.seed``. The
    base observation draws from ``default_rng(seed)`` in a fixed order; the
    optional scenario extensions (atmospheric 1/f, faults) draw from
    *separate* ``default_rng([seed, k])`` streams so enabling them never
    perturbs the base draws, and files generated with default knobs are
    byte-identical across versions.
    """
    p = params or SyntheticObsParams()
    rng = np.random.default_rng(p.seed)
    F, B, C, T = p.n_feeds, p.n_bands, p.n_channels, p.n_samples
    fs = SAMPLE_RATE

    # -- timeline: [vane][gap][scan gap]*n_scans [vane] --------------------
    features = np.zeros(T, dtype=np.int64)
    scan_flag = np.zeros(T, dtype=bool)
    t = 0
    features[t:t + p.vane_samples] = 2 ** FEATURE_VANE
    t += p.vane_samples
    scan_edges = []
    for _ in range(p.n_scans):
        t += p.gap_samples
        scan_edges.append((t, t + p.scan_samples))
        scan_flag[t:t + p.scan_samples] = True
        features[t:t + p.scan_samples] = 2 ** FEATURE_SCAN
        t += p.scan_samples
    t += p.gap_samples
    features[t:t + p.vane_samples] = 2 ** FEATURE_VANE
    scan_edges = np.asarray(scan_edges, dtype=np.int64).reshape(-1, 2)
    vane_flag = features == 2 ** FEATURE_VANE

    mjd = p.mjd_start + np.arange(T) / fs / 86400.0

    # -- pointing: CES triangle az sweeps at fixed elevation ----------------
    phase = np.cumsum(scan_flag) / fs  # seconds of scanning
    # triangle sweep: full period covers 4 x az_throw of azimuth travel,
    # so the az rate is 4*throw/period = 0.5 deg/s
    sweep_period = 4 * p.az_throw / 0.5
    tri = 2.0 * np.abs((phase / sweep_period) % 1.0 - 0.5) * 2.0 - 1.0
    az = p.az_centre + tri * p.az_throw * scan_flag
    el = p.elevation + p.el_sweep * (np.arange(T) / T - 0.5)
    # small per-feed focal-plane offsets
    feed_dx = 0.05 * rng.normal(size=F)
    feed_dy = 0.05 * rng.normal(size=F)
    az_f = az[None, :] + feed_dx[:, None]
    el_f = el[None, :] + feed_dy[:, None]
    # simple sky mapping: the az sweep scans RA, slow drift scans Dec.
    drift = 0.4 * (np.arange(T) / T - 0.5)
    dec_f = p.dec0 + (el_f - p.elevation) + drift[None, :]
    ra_f = p.ra0 + (az_f - p.az_centre) / np.cos(np.radians(dec_f))

    airmass = 1.0 / np.sin(np.radians(el_f))  # (F, T)

    # -- per-channel instrument truth --------------------------------------
    freq = _band_frequencies(B, C)  # GHz
    gain = p.gain_mean * (1.0 + p.gain_spread * rng.normal(size=(F, B, C)))
    gain = np.abs(gain).astype(np.float64)
    # receiver temperature: band-edge rise + per-channel scatter (the real
    # instrument's Tsys varies strongly across a band, which is what makes
    # the gain templates 1/Tsys distinguishable from the constant mode)
    chan = np.linspace(-1, 1, C)
    t_rx = p.t_rx * (1.0 + p.passband_curvature * chan[None, None, :] ** 2
                     + p.t_rx_scatter * rng.normal(size=(F, B, C)))
    t_rx = np.maximum(t_rx, 0.2 * p.t_rx)

    # -- time streams -------------------------------------------------------
    dg = one_over_f_noise(rng, T, p.sigma_g, p.fknee, p.alpha, fs, size=(F,))
    sky = np.zeros((F, T))
    if p.source_amplitude_k > 0:
        sky = gaussian_source_sky(ra_f, dec_f, p.ra0, p.dec0,
                                  p.source_amplitude_k, p.source_fwhm_deg)

    # additive atmospheric 1/f: per-feed, common-mode across (band, channel),
    # present only on sky (not the vane load). Separate RNG stream keeps the
    # base observation bit-identical when disabled.
    t_atm = np.zeros((F, T))
    if p.t_atm_sigma > 0:
        rng_atm = np.random.default_rng([p.seed, 101])
        t_atm = one_over_f_noise(rng_atm, T, p.t_atm_sigma, p.t_atm_fknee,
                                 p.t_atm_alpha, fs, size=(F,))

    t_sky = (p.t_cmb + p.t_atm_zenith * airmass + sky + t_atm)  # (F, T)
    t_sky_b = t_sky[:, None, :]  # (F, B, T) broadcast slot
    if p.sky_model is not None:
        # per-band sky from the model at band-centre frequency
        nu_c = freq.mean(axis=1)  # (B,) GHz
        model = np.stack([np.asarray(p.sky_model(ra_f, dec_f, nu))
                          for nu in nu_c], axis=1)  # (F, B, T)
        t_sky_b = t_sky_b + model
    t_total = t_rx[..., None] + np.where(vane_flag[None, None, None, :],
                                         p.t_vane,
                                         t_sky_b[:, :, None, :])  # (F,B,C,T)
    dnu = 2.0e9 / C  # Hz per channel
    rms_frac = 1.0 / np.sqrt(dnu / fs)
    tod = gain[..., None] * t_total * (1.0 + dg[:, None, None, :])
    tod = tod * (1.0 + rms_frac * rng.normal(size=(F, B, C, T)))
    tod = tod.astype(np.float32)

    # fault mix: spikes (x100 cosmic-ray hits) and NaN cells (dropped
    # packets), confined to scan samples so vane calibration stays clean.
    n_spikes = n_nans = 0
    if p.spike_rate > 0 or p.nan_rate > 0:
        rng_fault = np.random.default_rng([p.seed, 202])
        scan_idx = np.flatnonzero(scan_flag)
        n_cells = F * B * C * scan_idx.size
        n_spikes = int(round(p.spike_rate * n_cells))
        n_nans = int(round(p.nan_rate * n_cells))
        for count, op in ((n_spikes, "spike"), (n_nans, "nan")):
            if count <= 0 or scan_idx.size == 0:
                continue
            ff = rng_fault.integers(0, F, size=count)
            bb = rng_fault.integers(0, B, size=count)
            cc = rng_fault.integers(0, C, size=count)
            tt = scan_idx[rng_fault.integers(0, scan_idx.size, size=count)]
            if op == "spike":
                tod[ff, bb, cc, tt] *= 100.0
            else:
                tod[ff, bb, cc, tt] = np.nan

    # -- housekeeping -------------------------------------------------------
    hk_n = max(T // 5, 2)  # ~10 Hz housekeeping
    hk_idx = np.linspace(0, T - 1, hk_n).astype(int)
    hk_utc = mjd[hk_idx]
    lissajous = scan_flag[hk_idx].astype(np.int64)
    # sensors store centi-Kelvin above 0 C (DataHandling.py:322-325)
    tvane_raw = np.full(hk_n, (p.t_vane - 273.15) * 100.0)
    tshroud_c = ((p.t_vane - 213.0) / 0.2702) - 273.15
    tshroud_raw = np.full(hk_n, tshroud_c * 100.0)

    store = HDF5Store(name="synthetic_level1")
    store["spectrometer/tod"] = tod
    store["spectrometer/MJD"] = mjd
    store["spectrometer/features"] = features
    store["spectrometer/feeds"] = np.arange(1, F + 1, dtype=np.int64)
    store["spectrometer/bands"] = np.arange(B, dtype=np.int64)
    store["spectrometer/frequency"] = freq
    store["spectrometer/pixel_pointing/pixel_ra"] = ra_f
    store["spectrometer/pixel_pointing/pixel_dec"] = dec_f
    store["spectrometer/pixel_pointing/pixel_az"] = az_f
    store["spectrometer/pixel_pointing/pixel_el"] = el_f
    store["hk/antenna0/deTracker/lissajous_status"] = lissajous
    store["hk/antenna0/deTracker/utc"] = hk_utc
    store["hk/antenna0/vane/Tvane"] = tvane_raw
    store["hk/antenna0/vane/Tshroud"] = tshroud_raw
    store.set_attrs("comap", "obsid", p.obsid)
    store.set_attrs("comap", "source", f"{p.source},sky")
    store.set_attrs("comap", "comment", p.comment)

    tsys_truth = t_rx + p.t_cmb + p.t_atm_zenith * np.mean(airmass)
    p.truth = dict(
        gain=gain,
        tsys=np.broadcast_to(tsys_truth, (F, B, C)).copy(),
        dg=dg,
        scan_edges=scan_edges,
        vane_flag=vane_flag,
        frequency=freq,
        ra=ra_f, dec=dec_f,
        sky=sky,
        t_vane=p.t_vane,
        t_atm=t_atm,
        noise=dict(rms_frac=rms_frac, sigma_g=p.sigma_g, fknee=p.fknee,
                   alpha=p.alpha, t_atm_sigma=p.t_atm_sigma,
                   t_atm_fknee=p.t_atm_fknee, t_atm_alpha=p.t_atm_alpha),
        n_spikes=n_spikes, n_nans=n_nans,
    )
    return p, store


def generate_level1_file(filename: str, params: SyntheticObsParams | None = None
                         ) -> SyntheticObsParams:
    """Write a synthetic Level-1 HDF5 file; returns params with ``truth``
    filled in (per-channel gain/tsys, dg time stream, scan edges, sky)."""
    p, store = generate_level1_store(params)
    store.write(filename)
    return p
