"""Crash-durable atomic file replacement.

``os.replace`` makes a rename atomic against CONCURRENT readers, but
not against POWER LOSS: without an ``fsync`` of the temp file first,
the rename can be journalled to disk before the file's data blocks
are, and a crash then leaves a fully-committed name pointing at a
zero-length (or partially-written) file — exactly the torn checkpoint
the atomic write existed to prevent, resurfacing after the one failure
mode it was sold against. Syncing the *directory* afterwards makes the
rename itself durable (POSIX leaves directory-entry durability to an
explicit fsync of the directory fd; on platforms where directories
cannot be opened, that step is skipped — the data-blocks fsync is the
part that prevents torn content).

:func:`durable_replace` is the one home for the rule, used by the
Level-2 checkpoint writer (``data.hdf5io.HDF5Store.write(atomic=True)``)
and the ingest cache's disk spill (``ingest.cache.BlockCache``).
``durable=False`` restores the plain (fast, crash-torn-able) replace
for advisory files where a lost update costs one tick, not data.
"""

from __future__ import annotations

import os

__all__ = ["durable_replace", "fsync_path"]


def fsync_path(path: str) -> None:
    """fsync ``path``'s data blocks (opened read-only; the file must
    already be closed/flushed by the writer)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(path or ".", flags)
    except OSError:
        return  # non-POSIX: directory fds unsupported; rename
        # durability is then the filesystem's problem, torn content
        # is still prevented by the data fsync
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_replace(tmp: str, dst: str, durable: bool = True) -> None:
    """``os.replace(tmp, dst)`` with fsync-before-rename (and a POSIX
    directory fsync after), so a power cut leaves either the complete
    old file or the complete new one — never a committed name over
    unwritten blocks."""
    if durable:
        fsync_path(tmp)
    os.replace(tmp, dst)
    if durable:
        _fsync_dir(os.path.dirname(os.path.abspath(dst)))
