"""Device-side data currency: dense TOD blocks as pytrees.

The reference iterates Python loops over (feed, band, scan) slices of the raw
HDF5 TOD (``DataHandling.py:403-415`` ``tod_loop``). The TPU-native design
replaces every such loop with one dense block

    ``tod  : f32[F, B, C, T]``  + ``mask : f32[...]`` + ``scan_ids : i32[T]``

so kernels are single jitted array programs; feeds shard over the device
mesh, scans are segment ids, bad samples are mask zeros. These dataclasses
are registered pytrees (flax.struct), so they flow through ``jit``, ``vmap``
and ``shard_map`` unchanged.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp
import numpy as np

__all__ = ["TODBlock", "Level2Block"]


@flax.struct.dataclass
class TODBlock:
    """One observation's Level-1 data, padded to static shapes.

    Attributes
    ----------
    tod:       f32[F, B, C, T] raw power.
    mask:      f32[F, B, C, T] 1 = good sample (off-scan, vane and flagged
               samples are 0 for science ops; the vane kernel uses vane_flag).
    scan_ids:  i32[T] scan index per sample, -1 outside scans.
    vane_flag: bool[T] vane (hot load) in the beam.
    time_s:    f32[T] seconds since observation start (device timebase; f32
               holds sub-ms resolution over a multi-hour obs).
    az, el:    f32[F, T] telescope pointing per feed.
    ra, dec:   f32[F, T] sky pointing per feed.
    frequency: f32[B, C] channel frequencies (GHz).
    feeds:     i32[F] physical feed numbers.
    mjd0:      python float, MJD of sample 0 (pytree aux data, hashable — a
               full f32[T] MJD array would destroy the 0.02 s sample spacing:
               the f32 ulp at MJD~59620 is ~11 minutes).
    """

    tod: jnp.ndarray
    mask: jnp.ndarray
    scan_ids: jnp.ndarray
    vane_flag: jnp.ndarray
    time_s: jnp.ndarray
    az: jnp.ndarray
    el: jnp.ndarray
    ra: jnp.ndarray
    dec: jnp.ndarray
    frequency: jnp.ndarray
    feeds: jnp.ndarray
    mjd0: float = flax.struct.field(pytree_node=False, default=0.0)

    @property
    def mjd(self) -> np.ndarray:
        """MJD timestamps reconstructed at f64 on host (sub-ms accurate)."""
        return self.mjd0 + np.asarray(self.time_s, dtype=np.float64) / 86400.0

    @property
    def n_feeds(self) -> int:
        return self.tod.shape[0]

    @property
    def n_bands(self) -> int:
        return self.tod.shape[1]

    @property
    def n_channels(self) -> int:
        return self.tod.shape[2]

    @property
    def n_samples(self) -> int:
        return self.tod.shape[3]

    @property
    def n_scans(self) -> int:
        # static upper bound: max id + 1 cannot be traced; callers pass it.
        return int(np.max(np.asarray(self.scan_ids)) + 1)

    @property
    def airmass(self) -> jnp.ndarray:
        """1/sin(el), f32[F, T]."""
        return 1.0 / jnp.sin(jnp.radians(self.el))

    @classmethod
    def from_level1(cls, l1, ifeeds=None) -> "TODBlock":
        """Build a device block from a :class:`COMAPLevel1` view (host copy).

        ``ifeeds`` selects a subset of feed indices (defaults to all).
        """
        from comapreduce_tpu.data import scan_edges as se

        tod = l1["spectrometer/tod"]
        if ifeeds is None:
            ifeeds = np.arange(tod.shape[0])
        ifeeds = np.asarray(ifeeds)
        tod = np.asarray(tod[ifeeds.tolist()], dtype=np.float32)
        nT = tod.shape[-1]
        edges = l1.scan_edges
        ids = se.segment_ids_from_edges(edges, nT)
        vane = l1.vane_flag
        good = np.isfinite(tod) & (ids >= 0)[None, None, None, :]
        mjd = np.asarray(l1.mjd, dtype=np.float64)
        time_s = ((mjd - mjd[0]) * 86400.0).astype(np.float32)
        return cls(
            tod=jnp.asarray(np.nan_to_num(tod)),
            mask=jnp.asarray(good.astype(np.float32)),
            scan_ids=jnp.asarray(ids),
            vane_flag=jnp.asarray(vane),
            time_s=jnp.asarray(time_s),
            mjd0=float(mjd[0]),
            az=jnp.asarray(np.asarray(l1.az)[ifeeds], dtype=jnp.float32),
            el=jnp.asarray(np.asarray(l1.el)[ifeeds], dtype=jnp.float32),
            ra=jnp.asarray(np.asarray(l1.ra)[ifeeds], dtype=jnp.float32),
            dec=jnp.asarray(np.asarray(l1.dec)[ifeeds], dtype=jnp.float32),
            frequency=jnp.asarray(l1.frequency, dtype=jnp.float32),
            feeds=jnp.asarray(np.asarray(l1.feeds)[ifeeds], dtype=jnp.int32),
        )


@flax.struct.dataclass
class Level2Block:
    """Band-averaged Level-2 products on device.

    tod:      f32[F, B, T] calibrated, gain-filtered, band-averaged TOD.
    weights:  f32[F, B, T] per-sample inverse-variance weights.
    mask:     f32[F, B, T].
    scan_ids: i32[T].
    """

    tod: jnp.ndarray
    weights: jnp.ndarray
    mask: jnp.ndarray
    scan_ids: jnp.ndarray
    ra: jnp.ndarray
    dec: jnp.ndarray
    time_s: jnp.ndarray
    mjd0: float = flax.struct.field(pytree_node=False, default=0.0)

    @property
    def mjd(self) -> np.ndarray:
        return self.mjd0 + np.asarray(self.time_s, dtype=np.float64) / 86400.0
