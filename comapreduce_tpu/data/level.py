"""COMAP Level-1 / Level-2 file views.

Domain-aware wrappers over :class:`HDF5Store`, with the same observable
behavior as the reference's ``COMAPLevel1``/``COMAPLevel2``
(``Analysis/DataHandling.py:248-609``): feature-bit decoding, vane flags and
vane load temperature model, scan edges, pointing accessors, airmass, and the
``contains``/``update`` resume contract used by the pipeline runner.

HDF5 paths follow the real COMAP data format (they are the on-disk schema,
shared with the reference by necessity, not by code translation).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from comapreduce_tpu.data import scan_edges as se
from comapreduce_tpu.data.hdf5io import HDF5Store

__all__ = ["COMAPLevel1", "COMAPLevel2", "CALIBRATOR_NAMES",
           "decode_features", "find_level1_by_obsid"]

# Calibrator source names recognised by the pipeline
# (reference Tools/Coordinates.py:7-15 CalibratorList).
CALIBRATOR_NAMES = ("TauA", "CasA", "CygA", "jupiter", "Jupiter", "mars",
                    "venus", "moon")

# MJD of 2022-02-01 00:00 UTC: vane thermometry epoch switch
# (DataHandling.py:320-326). Time('2022-02-01').mjd == 59611.0.
_VANE_EPOCH_MJD = 59611.0
_KELVIN_OFFSET = 273.15


def find_level1_by_obsid(data_dir: str, obsid: int) -> str | None:
    """Path of the Level-1 file for ``obsid`` in ``data_dir``, or None.

    Matches the COMAP naming scheme ``comap-{obsid:07d}-*.hd5`` first,
    then any ``*.hd5`` whose LEADING filename token (optionally after a
    ``comap``/``comp`` prefix) is the obsid — a timestamp later in the
    name that merely contains the digits (e.g. ``-010000.`` vs obsid
    10000) can never match (parity: ``read_data_file_by_obsid``,
    ``Analysis/DataHandling.py`` — the prior-observation lookup the
    SkyDip stage uses)."""
    import glob
    import os
    import re

    hits = sorted(glob.glob(os.path.join(data_dir,
                                         f"comap-{int(obsid):07d}-*.hd5")))
    if hits:
        return hits[0]
    token = re.compile(rf"^(?:[A-Za-z]+[-_])?0*{int(obsid)}(?=[-_.])")
    for path in sorted(glob.glob(os.path.join(data_dir, "*.hd5"))):
        if token.match(os.path.basename(path)):
            return path
    return None


def decode_features(features: np.ndarray) -> np.ndarray:
    """Decode the feature register into bit indices: ``f -> log2(f)``.

    The telescope writes a one-hot feature word per sample; the pipeline works
    with the bit *index* (``DataHandling.py:342-349``). Zero stays zero.
    """
    f = np.asarray(features).astype(np.float64).copy()
    good = f > 0
    f[good] = np.log2(f[good])
    return f.astype(np.int64)


@dataclass
class _COMAPCommon(HDF5Store):
    """Shared Level-1/Level-2 accessors."""

    vane_bit: int = 13
    bad_keywords: tuple = ()

    @property
    def obsid(self) -> int:
        try:
            return int(self.attrs("comap", "obsid"))
        except KeyError:
            return -1

    @property
    def comment(self) -> str:
        try:
            return str(self.attrs("comap", "comment"))
        except KeyError:
            return ""

    @property
    def source_name(self) -> str:
        """First source token that is not a bad keyword (e.g. 'co2,sky')."""
        try:
            raw = str(self.attrs("comap", "source"))
        except KeyError:
            return ""
        parts = raw.split(",")
        if len(parts) == 1:
            return parts[0]
        keep = [s for s in parts if s not in self.bad_keywords]
        return keep[0] if keep else ""

    @property
    def is_calibrator(self) -> bool:
        return self.source_name in CALIBRATOR_NAMES

    @property
    def features(self) -> np.ndarray:
        if "spectrometer/features" not in self:
            raise KeyError("file contains no spectrometer/features")
        return decode_features(self.materialise("spectrometer/features"))

    @property
    def vane_flag(self) -> np.ndarray:
        return self.features == self.vane_bit

    @property
    def on_source(self) -> np.ndarray:
        """13 = vane, 0 = idle, 16 = source stare (ignored)."""
        f = self.features
        return (f != self.vane_bit) & (f != 0) & (f != 16)

    @property
    def mjd(self) -> np.ndarray:
        return self.materialise("spectrometer/MJD")

    @property
    def feeds(self) -> np.ndarray:
        return self.materialise("spectrometer/feeds")

    # pointing --------------------------------------------------------------
    @property
    def ra(self):
        return self["spectrometer/pixel_pointing/pixel_ra"]

    @ra.setter
    def ra(self, v):
        self["spectrometer/pixel_pointing/pixel_ra"] = v

    @property
    def dec(self):
        return self["spectrometer/pixel_pointing/pixel_dec"]

    @dec.setter
    def dec(self, v):
        self["spectrometer/pixel_pointing/pixel_dec"] = v

    @property
    def az(self):
        return self["spectrometer/pixel_pointing/pixel_az"]

    @az.setter
    def az(self, v):
        self["spectrometer/pixel_pointing/pixel_az"] = v

    @property
    def el(self):
        return self["spectrometer/pixel_pointing/pixel_el"]

    @el.setter
    def el(self, v):
        self["spectrometer/pixel_pointing/pixel_el"] = v

    @property
    def airmass(self) -> np.ndarray:
        """Plane-parallel airmass 1/sin(el) (``DataHandling.py:398-401``)."""
        return 1.0 / np.sin(np.radians(np.asarray(self.el)))

    def _scan_edges_from_features(self) -> np.ndarray:
        if self.is_calibrator:
            return se.scan_edges_calibrator(self.on_source)
        return se.scan_edges_source(
            self.materialise("hk/antenna0/deTracker/lissajous_status"),
            self.materialise("hk/antenna0/deTracker/utc"),
            self.mjd,
            self.features,
        )


@dataclass
class COMAPLevel1(_COMAPCommon):
    """Level-1 raw-data view; TOD stays lazy (`spectrometer/tod` ~GBs)."""

    name: str = "COMAPLevel1"
    lazy_paths: tuple = ("spectrometer/tod",)

    @property
    def tod_shape(self) -> tuple:
        return self["spectrometer/tod"].shape  # (F, B, C, T)

    @property
    def frequency(self) -> np.ndarray:
        """Channel frequencies in GHz, shape (B, C)."""
        return self.materialise("spectrometer/frequency")

    @property
    def vane_temperature(self) -> float:
        """Hot-load temperature in K.

        Before 2022-02-01 the vane thermometer is trusted directly; after,
        it is predicted from the shroud temperature with the linear model
        fitted on pre-2022 data (``DataHandling.py:316-326``). Sensor values
        are stored in centi-Kelvin-above-Celsius units (/100 + 273.15).
        """
        if float(self.mjd[0]) < _VANE_EPOCH_MJD:
            t = np.nanmean(self.materialise("hk/antenna0/vane/Tvane"))
            return float(t) / 100.0 + _KELVIN_OFFSET
        t = np.nanmean(self.materialise("hk/antenna0/vane/Tshroud"))
        tshroud = float(t) / 100.0 + _KELVIN_OFFSET
        return 0.2702 * tshroud + 213.0

    @property
    def scan_edges(self) -> np.ndarray:
        return self._scan_edges_from_features()

    def read_tod_feed(self, ifeed: int) -> np.ndarray:
        """Read one feed's raw TOD (B, C, T) from the lazy dataset."""
        return np.asarray(self["spectrometer/tod"][ifeed])


@dataclass
class COMAPLevel2(_COMAPCommon):
    """Level-2 reduced-data view. The file itself is the pipeline checkpoint.

    ``contains``/``update`` implement the resume contract: a stage is skipped
    when all its output groups are already present, and stages deposit their
    outputs via ``update`` (``DataHandling.py:417-448``).
    """

    name: str = "COMAPLevel2"
    filename: str = "pipeline_output.hd5"

    def __post_init__(self):
        import os

        if self.filename and os.path.exists(self.filename):
            self.read(self.filename)

    def contains(self, stage) -> bool:
        return self.contains_groups(getattr(stage, "groups", ()))

    def update(self, stage) -> None:
        data, attrs = stage.save_data
        for k, v in data.items():
            if v is not None:
                self[k] = v
        for path, kv in attrs.items():
            for k, v in kv.items():
                self.set_attrs(path, k, v)

    @property
    def tod(self):
        return self["averaged_tod/tod"]  # (F, B, T)

    @tod.setter
    def tod(self, v):
        self["averaged_tod/tod"] = v

    @property
    def tod_shape(self) -> tuple:
        return self["averaged_tod/tod"].shape

    @property
    def nbands(self) -> int:
        return self.tod_shape[1]

    @property
    def scan_edges(self) -> np.ndarray:
        if "averaged_tod/scan_edges" in self:
            return np.asarray(self["averaged_tod/scan_edges"])
        if "frequency_binned/scan_edges" in self:
            return np.asarray(self["frequency_binned/scan_edges"])
        return self._scan_edges_from_features()

    @property
    def system_temperature(self):
        return self["vane/system_temperature"]

    @system_temperature.setter
    def system_temperature(self, v):
        self["vane/system_temperature"] = v

    @property
    def system_gain(self):
        return self["vane/system_gain"]

    @system_gain.setter
    def system_gain(self, v):
        self["vane/system_gain"] = v

    def tod_auto_rms(self, ifeed: int, iband: int) -> float:
        """Adjacent-pair rms of the nonzero samples
        (``DataHandling.py:591-597``)."""
        tod = np.asarray(self["averaged_tod/tod"][ifeed, iband])
        tod = tod[tod != 0]
        n = tod.size // 2 * 2
        diff = tod[0:n:2] - tod[1:n:2]
        return float(np.nanstd(diff) / np.sqrt(2.0))
