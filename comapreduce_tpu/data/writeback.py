"""Ordered asynchronous Level-2 writeback.

The Runner's checkpoint-after-every-stage contract (the Level-2 file IS
the checkpoint, ``Running.py:152-153``) serialises device compute
behind host HDF5 writes: at production shape the ``averaged_tod`` group
alone is hundreds of MB, and the synchronous atomic write blocks the
stage chain while the accelerator idles. MAPPRAISER treats exactly this
whole-campaign write overlap as a first-class throughput concern; this
module is the mirror of the ingest :class:`~comapreduce_tpu.ingest
.prefetcher.Prefetcher` for the OUTPUT side — one background writer
thread, a bounded queue, per-file error capture, poisoning on hang.

Contract (what makes the async path safe to substitute for the sync
one):

- **Ordering.** One FIFO worker commits jobs in submission order.
  Each :meth:`submit_store` snapshot is the *cumulative* Level-2 state,
  so a later commit always supersedes an earlier one for the same
  path. A generation guard (``os.replace`` runs under a lock, gated on
  the submission counter) means a write that was hang-cancelled and
  later limps to completion on its abandoned worker thread can NEVER
  clobber a newer committed checkpoint — late commits are skipped, and
  counted in ``stats['late_skips']``.
- **Durability.** Store writes stage into a temp file in the target
  directory and commit through :func:`~comapreduce_tpu.data.durable
  .durable_replace` — fsync-before-rename (+ POSIX directory fsync)
  when ``durable=True`` (default), so a SIGKILL or power cut mid-async-
  write leaves either the complete old checkpoint or the complete new
  one, never a torn file (same guarantee as the synchronous
  ``HDF5Store.write(atomic=True)``).
- **Per-file flush barrier.** :meth:`flush` blocks until every queued
  job for a path committed and re-raises the first captured error for
  it — the Runner calls it at the end of each file's stage chain, so a
  failed/hung write surfaces inside the SAME per-file retry/quarantine
  net the synchronous write error would have hit, and by the time a
  file's result slot exists its checkpoint is on disk (resume,
  quarantine and kill-mid-write semantics are unchanged; only the
  *intra-file* stage writes overlap compute).
- **Failure isolation.** After a job for a path fails (or hangs), later
  queued jobs for that SAME path are dropped (their content is stale
  relative to the failure and committing one could reorder around the
  abandoned write); other paths are unaffected. ``flush`` clears the
  error it raises, so a chain re-run (the Runner's retry policy) can
  resubmit cleanly.

Supervision: with a ``resilience.Watchdog`` each write runs cancellably
under the ``writeback.write`` deadline — a writer stuck in HDF5/NFS C
code is abandoned at the hard deadline (``HangError``, the PR 3
``hang`` failure class: retried like a transient by the chain retry,
ledgered ``rejected`` on exhaustion, never quarantining the input).
A ``resilience.ChaosMonkey`` with a ``write_stall`` fault stalls the
write *inside* the supervised region, so drills exercise the cancel
path end to end (``resilience/drill.py`` criterion 6).
"""

from __future__ import annotations

import logging
import os
import queue
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["Writeback", "snapshot_store"]

logger = logging.getLogger("comapreduce_tpu")

_POLL_S = 0.1  # stop-event poll period (the Prefetcher's constant)


def snapshot_store(store) -> dict:
    """Host snapshot of an :class:`~comapreduce_tpu.data.hdf5io
    .HDF5Store` for asynchronous writing: lazy datasets materialised,
    dict structure copied (arrays shared — stages deposit fresh arrays
    and never mutate in place, the ingest payload contract)."""
    for path in list(store.keys()):
        store.materialise(path)
    return store.export_payload()


@dataclass
class _Job:
    path: str
    gen: int
    fn: Callable[[], None]
    cancelled: threading.Event = field(default_factory=threading.Event)


class Writeback:
    """Background writer with per-path ordering, flush barriers and
    durable commits (module docstring has the full contract).

    Parameters
    ----------
    depth:
        Queue bound — at most ``depth`` snapshots wait in the queue
        (plus the one being written). Size host memory accordingly:
        each Level-2 snapshot holds the file's full reduced content.
    durable:
        Default commit durability (fsync-before-rename through
        ``data.durable.durable_replace``); per-submit override wins.
    watchdog / chaos:
        Optional ``resilience`` hooks: the watchdog supervises each
        write under the ``writeback.write`` deadline (hard deadline ->
        cancel + ``HangError`` captured for the path); the chaos monkey
        injects ``write_stall`` faults inside the supervised region.
    on_hang:
        Called with the in-flight path when :meth:`close` abandons a
        writer that never returned (mirror of the Prefetcher's hook).
    """

    def __init__(self, depth: int = 2, durable: bool = True,
                 watchdog=None, chaos=None, on_hang=None,
                 name: str = "level2-writeback"):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.durable = bool(durable)
        self._watchdog = watchdog
        self._chaos = chaos
        self._on_hang = on_hang
        self._queue: queue.Queue = queue.Queue(maxsize=int(depth))
        self._stop = threading.Event()
        self._poisoned = False
        self._inflight: str | None = None
        self._lock = threading.Lock()          # errors/stats/cond
        self._done = threading.Condition(self._lock)
        # the commit gate gets its OWN lock: a durable commit fsyncs
        # the whole checkpoint (seconds on slow storage), and holding
        # the main lock through it would block submit_store — i.e. the
        # stage chain — exactly the serialisation this module removes.
        # Only an abandoned (hang-cancelled) writer limping to its own
        # commit ever contends here
        self._commit_lock = threading.Lock()   # committed_gen + replace
        self._gen = 0
        self._pending: dict[str, int] = {}     # path -> queued job count
        self._errors: dict[str, BaseException] = {}
        self._committed_gen: dict[str, int] = {}
        self.stats = {"writes": 0, "write_s": 0.0, "flush_wait_s": 0.0,
                      "bytes": 0, "dropped": 0, "late_skips": 0}
        self._thread = threading.Thread(target=self._work, name=name,
                                        daemon=True)
        self._thread.start()

    # -- submission ----------------------------------------------------------
    def submit_store(self, path: str, payload: dict,
                     durable: bool | None = None) -> None:
        """Queue one durable atomic write of ``payload`` (a
        :func:`snapshot_store` dict) to ``path``."""
        durable = self.durable if durable is None else bool(durable)
        job = self._make_job(path)
        job.fn = self._store_writer(payload, path, durable, job)
        self._enqueue(job)

    def submit(self, path: str, fn: Callable[[], None]) -> None:
        """Queue an arbitrary write callable (e.g. a FITS map write).
        The callable owns its own atomicity; the generation guard of
        :meth:`submit_store` does not apply — use this only for
        terminal, written-once outputs."""
        job = self._make_job(path)
        job.fn = fn
        self._enqueue(job)

    def _make_job(self, path: str) -> _Job:
        if self._poisoned:
            raise RuntimeError(
                "Writeback is poisoned (its worker hung and was "
                "abandoned); build a fresh Writeback")
        with self._lock:
            # a path that already failed fails fast at the NEXT submit
            # (the synchronous path would have raised at the earlier
            # write; surfacing here keeps the chain from burning more
            # stages on a dead output) — flush() is the other exit
            err = self._errors.pop(path, None)
            if err is not None:
                raise err
            self._gen += 1
            return _Job(path=path, gen=self._gen, fn=lambda: None)

    def _enqueue(self, job: _Job) -> None:
        with self._lock:
            self._pending[job.path] = self._pending.get(job.path, 0) + 1
        while not self._stop.is_set():
            try:
                self._queue.put(job, timeout=_POLL_S)
                # depth pinned at the bound = the writer is the
                # bottleneck; 0 = writes are fully hidden
                TELEMETRY.gauge("writeback.queue_depth",
                                self._queue.qsize())
                return
            except queue.Full:
                continue
        with self._lock:   # closed under the submitter's feet
            self._pending[job.path] -= 1
        raise RuntimeError("Writeback is closed")

    # -- the store write (durable, generation-guarded) -----------------------
    def _store_writer(self, payload: dict, path: str, durable: bool,
                      job: _Job) -> Callable[[], None]:
        def write() -> None:
            from comapreduce_tpu.data.hdf5io import HDF5Store
            from comapreduce_tpu.resilience.integrity import (
                committed_replace)

            store = HDF5Store(name="writeback")
            store.adopt_payload(payload)
            d = os.path.dirname(os.path.abspath(path)) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(suffix=".hd5.tmp", dir=d)
            os.close(fd)
            try:
                store._write_into(tmp, "w")
                n_bytes = os.path.getsize(tmp)
                with self._commit_lock:
                    # the commit gate: a hang-cancelled write finishing
                    # late on its abandoned worker thread must never
                    # replace a newer committed checkpoint — and a
                    # cancelled job must not commit at all (its path
                    # already failed over it)
                    stale = (job.cancelled.is_set()
                             or self._committed_gen.get(path, -1)
                             > job.gen)
                    if not stale:
                        # sidecar-first inside the same commit gate: the
                        # .s256 manifest and the payload rename share the
                        # generation fence, so a late writer can't land a
                        # stale sidecar over a newer checkpoint either
                        committed_replace(tmp, path, kind="checkpoint",
                                          durable=durable,
                                          chaos=self._chaos)
                        self._committed_gen[path] = job.gen
                if stale:
                    os.unlink(tmp)
                    with self._lock:
                        self.stats["late_skips"] += 1
                    logger.warning(
                        "writeback: stale/cancelled write of %s "
                        "(gen %d) skipped at commit", path, job.gen)
                else:
                    with self._lock:
                        self.stats["bytes"] += n_bytes
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise

        return write

    # -- worker --------------------------------------------------------------
    def _work(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=_POLL_S)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if job is None:   # close() sentinel after drain
                return
            self._inflight = job.path
            try:
                with self._lock:
                    failed = job.path in self._errors
                if failed:
                    # a later snapshot of a failed path is dropped: the
                    # failure (possibly an abandoned in-flight write)
                    # makes any commit after it a potential reorder
                    with self._lock:
                        self.stats["dropped"] += 1
                else:
                    self._run_job(job)
            except BaseException as exc:  # noqa: BLE001 — per-path net
                job.cancelled.set()
                with self._lock:
                    self._errors.setdefault(job.path, exc)
                logger.error("writeback: write of %s failed: %s: %s",
                             job.path, type(exc).__name__, exc)
            finally:
                self._inflight = None
                with self._lock:
                    self._pending[job.path] -= 1
                    self._done.notify_all()

    def _run_job(self, job: _Job) -> None:
        fn = job.fn
        if self._chaos is not None:
            chaos, inner = self._chaos, fn

            def fn(path=job.path, inner=inner):
                # the stall sits INSIDE the supervised region so the
                # watchdog's hard deadline cancels it like a real
                # stuck-in-C-code write would be
                chaos.stall_write(path)
                inner()
        t0 = time.perf_counter()
        ok = False
        try:
            if self._watchdog is not None:
                self._watchdog.call(fn, "writeback.write", unit=job.path)
            else:
                fn()
            ok = True
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.stats["write_s"] += dt
            # commit latency on the writer thread — true intervals for
            # campaign_report's write/compute overlap track
            TELEMETRY.event_span("writeback.write", dt, unit=job.path,
                                 skipped=not ok)
        with self._lock:
            self.stats["writes"] += 1

    # -- barriers ------------------------------------------------------------
    def flush(self, path: str | None = None,
              timeout: float | None = None) -> None:
        """Block until every queued job (for ``path``, or for every
        path) has committed or failed; re-raise (and clear) the first
        captured error. The Runner's per-file barrier."""
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        t0 = time.perf_counter()
        try:
            with self._done:
                def drained():
                    if path is None:
                        return not any(self._pending.values())
                    return self._pending.get(path, 0) == 0

                while not drained():
                    if self._poisoned:
                        break
                    if not self._thread.is_alive():
                        raise RuntimeError(
                            "Writeback worker died with writes pending")
                    remaining = _POLL_S if deadline is None else \
                        min(_POLL_S, deadline - time.monotonic())
                    if remaining <= 0:
                        raise TimeoutError(
                            f"writeback flush timed out "
                            f"({timeout:.1f} s) with writes pending")
                    self._done.wait(timeout=remaining)
                if self._poisoned and not drained():
                    # an abandoned worker means these writes never
                    # committed: the caller must see a failure, never a
                    # silent "flushed" (its file would look checkpointed
                    # while the bytes are in limbo)
                    err = (self._errors.pop(path, None) if path is not None
                           else None)
                    raise err or RuntimeError(
                        "Writeback is poisoned (worker hung) with "
                        "writes pending"
                        + (f" for {path}" if path else ""))
                if path is None:
                    errs = list(self._errors.items())
                    self._errors.clear()
                    if errs:
                        raise errs[0][1]
                else:
                    err = self._errors.pop(path, None)
                    if err is not None:
                        raise err
        finally:
            with self._lock:
                self.stats["flush_wait_s"] += time.perf_counter() - t0

    def close(self, timeout: float = 60.0) -> None:
        """Drain the queue, stop the worker and join it. Idempotent.
        Captured errors are NOT raised here (close runs in ``finally``
        blocks) — callers that care flush first. A worker that does not
        stop (stuck in C code past any watchdog budget) is abandoned:
        the writeback is poisoned and ``on_hang`` reports the in-flight
        path."""
        if not self._thread.is_alive():
            self._stop.set()
            return
        try:
            self._queue.put(None, timeout=timeout)
        except queue.Full:
            pass
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            inflight = self._inflight
            self._poisoned = True
            with self._done:
                self._done.notify_all()
            logger.warning(
                "Writeback: worker did not stop within %.1f s "
                "(writer stuck in C code?); abandoning it%s and "
                "poisoning the writeback", timeout,
                f" mid-write of {inflight}" if inflight else "")
            if inflight and self._on_hang is not None:
                try:
                    self._on_hang(inflight)
                except Exception:  # pragma: no cover - ledger I/O
                    logger.exception(
                        "Writeback: on_hang callback failed for %s",
                        inflight)
        with self._lock:
            for p, err in self._errors.items():
                logger.error("writeback: unflushed error for %s: %s: %s",
                             p, type(err).__name__, err)

    def __enter__(self) -> "Writeback":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
