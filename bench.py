"""Benchmark: full 19-feed CES observation -> Level-2 -> destriped map.

Production shape (BASELINE.md configs 3/5): 19 feeds x 4 bands x 1024
channels x ~45 min of 50 Hz data (T ~ 136k over 10 scans), median-filter
window 6000, destriped onto the production 480x480 field with a realistic
raster sweep. Prints ONE JSON line::

    {"metric": "tod_samples_per_sec", "value": ..., "unit": "samples/s",
     "vs_baseline": ...}

``value`` counts raw Level-1 samples (F*B*C*T) reduced per second of wall
time (per-feed reduction stream + destriper CG, like the real pipeline).

``vs_baseline`` is measured, not assumed: the denominator wall time comes
from a line-faithful single-core port of the reference's per-(feed, scan)
hot chain (``Level1Averaging.py:792-872``) run at the SAME scan length and
window — NaN fill via ``np.nanmedian``, per-channel atmosphere regression,
auto-rms normalisation, the reference's own C++ dual-heap ``Mediator``
median filter (compiled from ``/root/reference`` sources at runtime) with
its 3x reflect padding, the scipy ``cg``/``LinearOperator`` gain solve over
the flattened (time*4096) f64 vector, and the Tsys^2-weighted band average
— timed on one unit in a single-threaded subprocess and scaled by the
reference's production deployment of 16 MPI ranks
(``scripts/general/pbs.script:27``). The baseline excludes the reference's
HDF5 reads and its destriper (both would make it slower), so the ratio is
conservative.

QUIET HOST REQUIRED for any run that measures a baseline (no env
override): the reference unit is CPU-pinned single-core, and ambient
load (a concurrent test suite, a build) slows the pinned child — a
contaminated baseline inflates ``vs_baseline`` (observed: config 2's
calibrator unit 5.85 s under load vs 2.835 s quiet, a phantom 2x).
Device walls are unaffected (stable to ~0.1% across all round-5 runs).

Env knobs: ``BENCH_SCALE`` (float, default 1.0) scales the per-scan sample
count; ``BENCH_SMALL=1`` runs a tiny config (CI smoke);
``BENCH_BASELINE_S`` overrides the measured FLAGSHIP baseline unit
seconds — configs 1/2 use ``BENCH_BASELINE_CAL_S`` for their calibrator
unit instead, so a flagship override cannot inflate them — (skips
the ~60 s single-core measurement, e.g. for quick re-runs);
``BENCH_NO_PROBE=1`` skips the wedged-relay pre-flight probe.
"""

from __future__ import annotations

import ctypes
import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_RANKS = 16  # mpirun -n 16, scripts/general/pbs.script:27
_REF_MEDFILT_DIR = "/root/reference/comancpipeline/Tools/median_filter"
_SHIM_DIR = "/tmp/comap_bench_ref"


# --------------------------------------------------------------------------
# Reference baseline: line-faithful single-core port of the hot chain
# --------------------------------------------------------------------------

def _build_reference_medfilt():
    """Compile the reference's own C++ median filter to a ctypes lib.

    Builds ``medianFilter.cpp`` (the dual-heap ``Mediator``) from the
    read-only reference tree into /tmp with a tiny extern-C shim; nothing is
    copied into this repo. Returns a callable ``medfilt(x_f64, window)`` or
    None when the toolchain/sources are unavailable.
    """
    so = os.path.join(_SHIM_DIR, "refmedfilt.so")
    if not os.path.exists(so):
        if not os.path.isdir(_REF_MEDFILT_DIR):
            return None
        os.makedirs(_SHIM_DIR, exist_ok=True)
        shim = os.path.join(_SHIM_DIR, "shim.cpp")
        with open(shim, "w") as f:
            f.write('#include "medianFilter.h"\n'
                    'extern "C" void ref_filter(double* a, int n, int w)'
                    '{ filter(a, n, w); }\n')
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-I", _REF_MEDFILT_DIR,
               shim, os.path.join(_REF_MEDFILT_DIR, "medianFilter.cpp"),
               "-o", so]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            return None
    lib = ctypes.CDLL(so)
    lib.ref_filter.argtypes = [ctypes.POINTER(ctypes.c_double),
                               ctypes.c_int, ctypes.c_int]

    def medfilt(x, window):
        buf = np.ascontiguousarray(x, dtype=np.float64)
        lib.ref_filter(buf.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), buf.size, int(window))
        return buf

    return medfilt


def _insort_medfilt(x, window):
    """Pure-python fallback sliding median (same work class as the C++
    dual-heap: ordered-window maintenance), used only if g++ or the
    reference sources are missing."""
    import bisect

    half = window // 2
    out = np.empty_like(x)
    win = sorted(x[:half + 1].tolist())
    out[0] = win[len(win) // 2]
    for i in range(1, len(x)):
        hi = i + half
        if hi < len(x):
            bisect.insort(win, x[hi])
        lo = i - half - 1
        if lo >= 0:
            del win[bisect.bisect_left(win, x[lo])]
        out[i] = win[len(win) // 2]
    return out


def reference_unit_seconds(L: int, window: int, B: int = 4,
                           C: int = 1024, seed: int = 0,
                           calibrator: bool = False) -> float:
    """Wall seconds for ONE (feed, scan) of the reference hot chain.

    Mirrors the per-scan body of ``average_tod`` (``Level1Averaging.py:
    792-872``) step by step in f64 numpy/scipy, calling the reference's own
    compiled median filter. Run this single-threaded (see
    ``measure_baseline``).

    ``calibrator=True`` mirrors the reference's ``use_gain_filter=False``
    calibrator path instead (TauA/CasA/CygA/Jupiter,
    ``COMAPData.py:255-258`` / ``Level1Averaging.py:826-831``): the
    median-filter regression and the scipy-cg gain solve are SKIPPED and
    a per-channel median baseline is removed — the conservative
    denominator for BASELINE configs 1/2.
    """
    from scipy.sparse.linalg import LinearOperator, cg

    medfilt = _build_reference_medfilt() or _insort_medfilt
    rng = np.random.default_rng(seed)
    # raw counts with a common-mode gain drift so the chain sees
    # realistically correlated data
    drift = 1.0 + 1e-3 * np.cumsum(rng.normal(size=L)) / np.sqrt(L)
    tod = (1000.0 + rng.normal(0, 1.0, size=(B, C, L))) * drift
    airmass = 1.2 + 0.01 * rng.normal(size=L)
    tsys = 45.0 * (1.0 + 0.2 * rng.random(size=(B, C)))
    gains = 1e6 * np.ones((B, C))
    atmos_fits = rng.normal(0, 0.1, size=(B, 2, C))
    atmos_fits[:, 0, :] += 1000.0

    t0 = time.perf_counter()
    # fill_bad_data (:658-665): per-channel nanmedian fill
    dr = tod.reshape(B * C, L)
    nan_tod = np.isnan(dr)
    ones = np.ones(dr.shape) * np.nanmedian(dr, axis=1)[:, None]
    dr[nan_tod] = ones[nan_tod]
    tod = dr.reshape(B, C, L)
    # remove_atmosphere (:642-656): per-band [offset, slope] model
    clean = np.zeros((B, C, L))
    A = np.stack([np.ones(L), airmass])  # (2, L)
    for ib in range(B):
        clean[ib] = tod[ib] - atmos_fits[ib].T @ A
    # normalise_data (:667-679): stride-4 pair differences
    N4 = L // 4 * 4
    diff = clean[..., np.arange(0, N4, 4)] - clean[..., np.arange(2, N4, 4)]
    rms = np.nanstd(diff, axis=-1) / np.sqrt(2) * np.sqrt(
        (2e9 / 1024.0) * (1 / 50.0))
    clean = clean / rms[..., None]
    mid = C // 2
    if calibrator:
        # calibrator path (use_gain_filter=False): per-channel median
        # baseline instead of the filter+gain solve
        filt = clean - np.median(clean, axis=-1, keepdims=True)
        dG = np.zeros(L)
    else:
        # median_filter (:681-708): band mean -> 3x reflect pad -> C++
        # filter -> per-channel affine regression
        filt = np.zeros((B, C, L))
        index = np.arange(1024, dtype=int)[10:-10]
        index = index[(index < 512 - 5) | (index > 512 + 5)]
        index = index[index < C]
        for ib in range(B):
            masked = clean[ib, index, :]
            mean_tod = np.nanmean(masked, axis=0)
            pad = np.concatenate([mean_tod[::-1], mean_tod,
                                  mean_tod[::-1]])
            med = medfilt(pad, window)[L:2 * L]
            A2 = np.ones((L, 2))
            A2[:, 1] = med
            x = np.linalg.solve(A2.T @ A2, A2.T @ masked.T)
            filt[ib, index] = masked - (A2 @ x).T
        # gain_subtraction (:710, GainSubtraction.py:144-209): band-mean
        # PS prerequisite + scipy cg over the flattened (time*4096)
        # f64 vector
        for ib in range(B):
            _ = np.abs(np.fft.fft(np.nanmean(filt[ib], axis=0))) ** 2
        templates = np.ones((B, C, 3))
        v = np.linspace(-1, 1, B * C).reshape((B, C))
        templates[..., 0] = 1.0 / tsys
        templates[..., 1] = v / tsys
        templates[:, :20, :] = 0
        templates[:, -20:, :] = 0
        templates[:, mid - 5:mid + 5, :] = 0
        d = filt.copy()
        d[:, :20, :] = 0
        d[:, -20:, :] = 0
        d[:, mid - 5:mid + 5, :] = 0
        tmpl = templates.reshape(B * C, 3)
        dflat = d.reshape(B * C, L).T.flatten()

        def z_op(dd, tm):
            data = dd.reshape((L, tm.shape[0])).T
            TT = np.linalg.inv(tm.T @ tm)
            d_sub = tm @ (TT @ (tm.T @ data))
            return dd - d_sub.T.flatten()

        def p_op(g, tm):
            return np.repeat(g, tm.size) * np.tile(tm, g.size)

        def pt_op(dd, tm):
            return np.sum(dd.reshape((L, tm.size)) * tm[None, :], axis=1)

        def matvec(g):
            return pt_op(z_op(p_op(g, tmpl[:, 2]), tmpl[:, :2]),
                         tmpl[:, 2])

        Aop = LinearOperator((L, L), matvec=matvec, dtype=np.float64)
        b = pt_op(z_op(dflat, tmpl[:, :2]), tmpl[:, 2])
        dG, _info = cg(Aop, b)
    # weights + residual + band averages + auto-rms weights (:843-867)
    weights = 1.0 / tsys ** 2
    weights[:, :10] = 0
    weights[:, -10:] = 0
    weights[:, mid - 2:mid + 3] = 0
    residual = (filt - dG[None, None, :]) * rms[..., None] / gains[..., None]
    wsum = np.nansum(weights, axis=1)[:, None]
    avg = np.nansum(residual * weights[..., None], axis=1) / wsum
    clean_k = filt * tsys[..., None]
    _avg2 = np.nansum(clean_k * weights[..., None], axis=1) / wsum
    n2 = L // 2
    ar = np.nanstd(avg[:, 0:2 * n2:2] - avg[:, 1:2 * n2:2],
                   axis=1) / np.sqrt(2)
    _ = 1.0 / np.maximum(ar, 1e-30)[:, None] ** 2
    return time.perf_counter() - t0


N_BASELINE_REPS = 2   # unit reps; the minimum is the denominator


def measure_baseline(L: int, window: int,
                     n_rep: int = N_BASELINE_REPS,
                     calibrator: bool = False,
                     B: int = 4, C: int = 1024) -> float:
    """Single-threaded wall seconds of one reference (feed, scan) unit.

    Spawns a subprocess with BLAS/OpenMP pinned to one thread — the
    per-rank budget the production `mpirun -n 16` on a 32-core node gives
    the reference (2 cores/rank; 1 thread is generous to nobody and
    reproducible).

    The unit is measured ``n_rep`` times and the MINIMUM is returned,
    with the subprocess pinned to one CPU (``sched_setaffinity``): host
    load can only make the reference look slower, never faster, so the
    minimum is the defensible denominator (round-3 review observed a
    1.7x swing in ``vs_baseline`` from host load alone). The per-rep
    values are printed to stderr for the record.
    """
    env = dict(os.environ)
    for k in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS", "MKL_NUM_THREADS",
              "NUMEXPR_NUM_THREADS"):
        env[k] = "1"
    env.pop("JAX_PLATFORMS", None)
    # pin the child to one core inside the child itself (portable across
    # the taskset-less bench image); errors are non-fatal
    code = ("import os\n"
            "try: os.sched_setaffinity(0, {0})\n"
            "except (AttributeError, OSError): pass\n"
            "import bench\n"
            f"print(bench.reference_unit_seconds({L}, {window}, "
            f"B={B}, C={C}, calibrator={calibrator}))")
    units = []
    for rep in range(max(int(n_rep), 1)):
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, cwd=os.path.dirname(os.path.abspath(__file__)))
        if out.returncode != 0:
            raise RuntimeError(
                f"baseline subprocess failed (rc={out.returncode}):\n"
                f"{out.stderr}")
        units.append(float(out.stdout.strip().splitlines()[-1]))
    print(f"bench: baseline unit reps {['%.1f' % u for u in units]} s "
          f"-> min {min(units):.1f} s", file=sys.stderr)
    return min(units)


# --------------------------------------------------------------------------
# TPU pipeline at production shape
# --------------------------------------------------------------------------

def ces_pixels(T: int, nx: int, ny: int, feed: int, n_feeds: int):
    """Raster-scan pixel stream over an (ny, nx) field.

    Constant-elevation sweep: azimuth triangles across the field ~10 px/s
    while the field drifts through elevation rows over the observation —
    every row is crossed many times and most of the map is hit, so the
    destriper CG does production work. Feeds are offset across the focal
    plane.
    """
    t = np.arange(T, dtype=np.float64)
    period = 2.0 * nx / 10.0 * 50.0  # full sweep and back at 10 px/s, 50 Hz
    phase = (t / period + feed / max(n_feeds, 1)) % 1.0
    x = np.where(phase < 0.5, phase * 2, 2 - 2 * phase) * (nx - 1)
    y = (t / T) * (ny - 1 - 8) + (feed * 8) / max(n_feeds, 1)
    pix = np.round(y) * nx + np.round(x)
    return pix.astype(np.int32)


def weight_spread_raster(seed=0, T=12_000, nx=32, L=50):
    """THE weight-spread raster fixture: ces raster + 1/f offsets + two
    decades of weight spread. ONE home (used by ``--config destriper``,
    ``tests/test_multigrid.py``, ``tests/test_pixel_space.py`` and
    ``tests/test_precond_knob.py``) so the acceptance tests and the
    perf gate's bench cannot silently drift onto different problem
    classes. Returns ``(pix, tod, w, npix, L)`` with ``len(pix)``
    truncated to an offset multiple."""
    rng = np.random.default_rng(seed)
    pix = ces_pixels(T, nx, nx, 0, 1).astype(np.int64)
    n = (pix.size // L) * L
    pix = pix[:n]
    true_off = np.cumsum(rng.normal(0, 0.3, n // L)).astype(np.float32)
    sky = rng.normal(0, 1.0, nx * nx).astype(np.float32)
    tod = (sky[pix] + np.repeat(true_off, L)
           + rng.normal(0, 1.0, n).astype(np.float32)).astype(np.float32)
    w = (10.0 ** rng.uniform(-1, 1, n)).astype(np.float32)
    return pix, tod, w, nx * nx, L


def raster_to_healpix(pix, nx, nside):
    """Walk the raster's (x, y) cells over a small HEALPix patch —
    shared by the survey-smoke bench and the HEALPix parity tests."""
    from comapreduce_tpu.mapmaking import healpix as hp

    lon = 40.0 + (np.asarray(pix) % nx) * 0.05
    lat = 10.0 + (np.asarray(pix) // nx) * 0.05
    return np.asarray(hp.ang2pix_lonlat(nside, lon, lat), np.int64)


def _probe_device(timeout_s: float = 600.0) -> None:
    """Fail fast (with a clear message) when the TPU relay is wedged.

    A wedged axon remote-compile relay hangs EVERY jit indefinitely —
    including this bench, which would otherwise sit silent until the
    caller's timeout. Probe with a tiny jit in a subprocess first;
    ``BENCH_NO_PROBE=1`` skips."""
    if os.environ.get("BENCH_NO_PROBE", "") == "1":
        return
    code = ("import jax, jax.numpy as jnp;"
            "print(float(jax.jit(lambda x: (x + 1).sum())(jnp.ones(8))))")
    # NEVER signal the child on timeout: killing a process mid-TPU-compile
    # is itself the wedge trigger (SKILL.md gotcha) — on timeout the child
    # is left running (it either finishes harmlessly or was already hung).
    # stderr goes to a temp FILE, not a pipe: if the parent exited holding
    # a pipe, a slow-but-healthy child would be SIGPIPE-killed on its next
    # stderr write — mid-compile, the very thing this code avoids.
    import tempfile

    with tempfile.NamedTemporaryFile("w+b", suffix=".probe.log",
                                     delete=False) as errf:
        child = subprocess.Popen([sys.executable, "-c", code],
                                 stdout=subprocess.DEVNULL, stderr=errf)
        try:
            child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            # the hung child may still write the log: leave both alone
            print("bench: device probe hung for "
                  f"{timeout_s:.0f}s — the TPU compile relay appears "
                  "wedged (see .claude/skills/verify/SKILL.md gotchas); "
                  "aborting instead of hanging (probe child left "
                  "untouched). The last verified on-chip measurement is "
                  "recorded in ROOFLINE.md.", file=sys.stderr)
            raise SystemExit(3)
        errf.seek(0)
        err_tail = errf.read().decode(errors="replace")[-2000:]
    os.unlink(errf.name)
    if child.returncode != 0:
        print(f"bench: device probe failed:\n{err_tail}", file=sys.stderr)
        raise SystemExit(3)


def main():
    _probe_device()
    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.mapmaking.destriper import destripe_planned
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.ops.reduce import (ReduceConfig, reduce_feed_scans,
                                            scan_starts_lengths)
    from comapreduce_tpu.ops.vane import _event_kernel

    small = os.environ.get("BENCH_SMALL", "") == "1"
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))

    if small:
        F, B, C, scan_samples, n_scans, window = 2, 2, 64, 1000, 2, 101
        nx = ny = 8
        vane_samples, scan_batch = 128, None
    else:
        F, B, C, n_scans, window = 19, 4, 1024, 10, 6000
        scan_samples = max(int(13500 * scale), 1000)
        nx = ny = 480
        vane_samples, scan_batch = 256, 2

    npix = nx * ny
    gap = 64
    edges, t = [], gap
    for _ in range(n_scans):
        edges.append((t, t + scan_samples))
        t += scan_samples + gap
    T = t
    edges = np.asarray(edges, dtype=np.int64)
    scan_mask = np.zeros(T, np.float32)
    for s, e in edges:
        scan_mask[s:e] = 1.0

    starts, lengths, L = scan_starts_lengths(edges)
    starts_j = jnp.asarray(starts, jnp.int32)
    lengths_j = jnp.asarray(lengths, jnp.int32)
    cfg = ReduceConfig(C, medfilt_window=window, scan_batch=scan_batch)
    freq = np.broadcast_to(np.linspace(-0.1, 0.1, C), (B, C))
    freq_j = jnp.asarray(freq, jnp.float32)
    mask_j = jnp.asarray(scan_mask)

    def feed_step(key):
        """One feed: generate raw counts on device, vane-calibrate, reduce.

        Generation runs on device because the production-shape per-feed TOD
        (~2.2 GB) would otherwise bottleneck on the host link; the reference
        equally excludes data simulation from its runtime (its analogue, the
        HDF5 read, is excluded from the baseline too).
        """
        k = jax.random.split(key, 4)
        gain = 1e6 * (1.0 + 0.1 * jax.random.normal(k[0], (B, C)))
        tsys = 45.0 * (1.0 + 0.2 * jax.random.uniform(k[1], (B, C)))
        tod = gain[..., None] * tsys[..., None] * (
            1.0 + 0.01 * jax.random.normal(k[2], (B, C, T)))
        mask = mask_j  # (T,): reduce broadcasts lazily; a dense (B, C, T)
        # mask would cost a full-size gather + materialisation per feed
        vane_step = jnp.where(jnp.arange(vane_samples) < vane_samples // 2,
                              290.0, 0.0)
        vane_tod = gain[..., None] * (tsys[..., None] + vane_step) * (
            1.0 + 1e-3 * jax.random.normal(k[3], (B, C, vane_samples)))
        airmass = jnp.full((T,), 1.2, jnp.float32)
        # _event_kernel expects a leading feed axis: add a singleton
        tsys_cal, gain_cal = _event_kernel(vane_tod[None], jnp.float32(290.0))
        tsys_cal, gain_cal = tsys_cal[0], gain_cal[0]
        red = reduce_feed_scans(tod, mask, airmass, starts_j, lengths_j,
                                tsys_cal, gain_cal, freq_j,
                                cfg=cfg, n_scans=len(starts), L=L)
        return red["tod"], red["weights"]

    @jax.jit
    def all_feeds(keys):
        """Every feed through one program: lax.map streams feeds so the
        working set stays one feed's, and the per-call dispatch overhead
        (~65 ms through the tunnelled chip) is paid once, not F times."""
        return jax.lax.map(feed_step, keys)

    all_pix = np.stack([ces_pixels(T, nx, ny, f, F) for f in range(F)])

    offset_length, n_iter = 50, 100
    # CG preconditioner selection (the [Destriper] knob's bench end):
    # jacobi (default) | none | twolevel. The CG terminates on the 1e-6
    # tolerance, so cg_iters_to_tol in the detail line reports iterations
    # ACTUALLY run — None when the budget expired unconverged.
    from comapreduce_tpu.mapmaking.destriper import CONFIG_PRECONDITIONERS
    precond_name = os.environ.get("BENCH_PRECOND", "jacobi")
    if precond_name not in CONFIG_PRECONDITIONERS:
        raise SystemExit(
            f"BENCH_PRECOND must be {'|'.join(CONFIG_PRECONDITIONERS)}, "
            f"got {precond_name!r}")
    # static pointing -> plan built once (host), reused every run. The
    # four bands share the feed pointing exactly (one telescope
    # direction), so the destriper solves them as ONE multi-RHS CG over
    # the (F, T)-flat pixel stream — producing the four per-band maps
    # the reference's per-band loop makes (``run_destriper.py:146``).
    # Measured on-chip (SWEEP_r05 multi-rhs): joint 2.14 s vs 5.79 s
    # serial at this pointing — the index stream (and its gather-bound
    # per-iteration cost) is paid once, not per band.
    pix_feed = all_pix.reshape(-1)
    n_pad = (-pix_feed.size) % offset_length
    pix_feed = np.concatenate([pix_feed, np.full(n_pad, npix, np.int64)])
    # pair_batch auto-sized by the HBM planner (COMAP_PAIR_BATCH pins it)
    plan = build_pointing_plan(pix_feed, npix, offset_length)
    jitted_destripe = jax.jit(functools.partial(
        destripe_planned, plan=plan, n_iter=n_iter, threshold=1e-6,
        precond="none" if precond_name == "none" else "jacobi"))

    def make_bands(tods, weis):
        """(F, B, T) feed outputs -> padded (B, F*T) multi-RHS inputs.
        ONE home for the band assembly: the headline pipeline and the
        diagnostic stage split below must measure the same layout."""
        band_tod = jnp.moveaxis(tods, 1, 0).reshape(B, -1)   # (B, F*T)
        band_w = jnp.moveaxis(weis, 1, 0).reshape(B, -1)
        if n_pad:
            band_tod = jnp.concatenate(
                [band_tod, jnp.zeros((B, n_pad), band_tod.dtype)], axis=-1)
            band_w = jnp.concatenate(
                [band_w, jnp.zeros((B, n_pad), band_w.dtype)], axis=-1)
        return band_tod, band_w

    # dispatch accounting, COUNTED AT CALL TIME: the timed pipeline only
    # ever launches programs through the _counted wrappers below, so a
    # regression back to per-feed/per-band Python-loop dispatch inside
    # run_pipeline (e.g. `for f in range(F): feeds(keys[f:f+1])`) raises
    # the count by construction — no hand-maintained increment to forget.
    # Scope: this counts the BENCH pipeline's dispatches (reduction =
    # ONE lax.map-over-feeds program, destriper = ONE multi-RHS CG);
    # the library stage programs' chunking policy is pinned separately
    # (ops.reduce.plan_stage_feed_batch unit tests). tools/check_perf.py
    # gates on ANY increase.
    dispatch_n = {"reduce": 0, "destripe": 0}

    def _counted(fn, which):
        def call(*a, **k):
            dispatch_n[which] += 1
            return fn(*a, **k)
        return call

    all_feeds_counted = _counted(all_feeds, "reduce")
    destripe_counted = _counted(jitted_destripe, "destripe")

    coarse_kwargs = {}
    if precond_name in ("twolevel", "multigrid"):
        # both knobs need the post-reduction weights on host; pointing
        # and weights are run-invariant, so build once here (per band,
        # sharing one pattern set) — the same amortisation the CLI's
        # per-(pointing, weights) build relies on. The measurement must
        # time the SELECTED preconditioner, never silently Jacobi (the
        # PR 4 twolevel lesson).
        keys_w = jax.random.split(jax.random.key(7, impl="rbg"), F)
        tods_w, weis_w = all_feeds(keys_w)
        _, band_w0 = make_bands(tods_w, weis_w)
        band_w_host = np.asarray(band_w0)
    if precond_name == "multigrid":
        from comapreduce_tpu.mapmaking.destriper import (
            build_multigrid_hierarchy, multigrid_patterns,
            stack_multigrid)

        pats_mg = multigrid_patterns(pix_feed, npix, offset_length,
                                     block=8, levels=2)
        # device-convert ONCE, like the twolevel branch's jnp.asarray:
        # numpy kwargs would re-upload the whole hierarchy (incl. the
        # per-band dense ac_inv) on every timed dispatch and bias the
        # A/B against multigrid
        coarse_kwargs["mg"] = jax.tree_util.tree_map(
            jnp.asarray, stack_multigrid(
                [build_multigrid_hierarchy(pix_feed, band_w_host[i],
                                           npix, offset_length,
                                           patterns=pats_mg)
                 for i in range(B)]))
    if precond_name == "twolevel":
        from comapreduce_tpu.mapmaking.destriper import (
            build_coarse_preconditioner, coarse_pattern)

        pat = coarse_pattern(pix_feed, npix, offset_length, block=8)
        pre = [build_coarse_preconditioner(pix_feed, band_w_host[i],
                                           npix, offset_length, block=8,
                                           pattern=pat)
               for i in range(B)]
        coarse_kwargs["coarse"] = (
            jnp.asarray(pre[0][0]),
            jnp.stack([jnp.asarray(p[1]) for p in pre]))

    def run_pipeline():
        # hardware RNG (rbg): synthetic-data generation is bench scaffolding,
        # not pipeline work, and threefry costs ~35 ms/feed of the wall
        keys = jax.random.split(jax.random.key(7, impl="rbg"), F)
        tods, weis = all_feeds_counted(keys)   # (F, B, T) each
        return destripe_counted(*make_bands(tods, weis), **coarse_kwargs)

    def finish(res):
        """Force completion through the axon tunnel with a HOST FETCH —
        ``block_until_ready`` alone once reported ready at 2.5 ms wall
        on a 3.4 s computation (stale local ready-state; the sweep
        scripts learned this first). A fetched scalar cannot exist
        before the chain that produces it ran."""
        return float(jnp.sum(res.destriped_map))

    # warm-up: compile + first run
    result = run_pipeline()
    finish(result)

    n_rep = 2 if not small else 1
    best = float("inf")
    for _ in range(n_rep):
        dispatch_n["reduce"] = dispatch_n["destripe"] = 0
        t0 = time.perf_counter()
        result = run_pipeline()
        finish(result)
        dt = time.perf_counter() - t0
        best = min(best, dt)
    dispatch_count = dispatch_n["reduce"] + dispatch_n["destripe"]
    if not small and best < 0.05:
        # a sub-50 ms "measurement" of a production-shape chain is a
        # tunnel artifact, never a real wall — refuse to print it
        print(f"bench: implausible wall {best:.4f}s (tunnel ready-state "
              "artifact?); rerun", file=sys.stderr)
        raise SystemExit(4)

    n_raw = F * B * C * T
    throughput = n_raw / best
    cg_iters_per_sec = float(result.n_iter) / best
    # iterations ACTUALLY used: the CG exits on the 1e-6 tolerance, so
    # n_iter < budget means converged-to-tol; an unconverged run reports
    # None rather than pretending the budget was the requirement
    resid = np.asarray(result.residual)
    cg_converged = bool((resid <= 1e-6).all())
    cg_iters_to_tol = int(result.n_iter) if cg_converged else None

    # diagnostic stage split (NOT the headline wall, which times the
    # chained end-to-end pipeline): one extra rep of each half, so the
    # roofline attribution is measured instead of inferred
    keys_d = jax.random.split(jax.random.key(7, impl="rbg"), F)
    # warm pass FIRST: the fetch's sum over (F, B, T) is its own little
    # program, and its remote compile (~seconds through the relay) must
    # not land inside the timed region (observed: reduce_wall 7.4 s on
    # a 1.96 s stage the first time the fetch compiled there)
    tods_d, weis_d = all_feeds(keys_d)
    float(jnp.sum(tods_d) + jnp.sum(weis_d))
    t0 = time.perf_counter()
    tods_d, weis_d = all_feeds(keys_d)
    float(jnp.sum(tods_d) + jnp.sum(weis_d))   # host fetch, see finish()
    reduce_wall = time.perf_counter() - t0
    band_tod_d, band_w_d = make_bands(tods_d, weis_d)
    float(jnp.sum(band_w_d))
    t0 = time.perf_counter()
    # same coarse_kwargs as run_pipeline: under BENCH_PRECOND=twolevel
    # the split must time the SELECTED solver path (omitting the coarse
    # operand would measure plain Jacobi — and compile a second program)
    r_d = jitted_destripe(band_tod_d, band_w_d, **coarse_kwargs)
    finish(r_d)
    destripe_wall = time.perf_counter() - t0

    # ---- measured reference baseline ------------------------------------
    env_unit = os.environ.get("BENCH_BASELINE_S", "")
    if env_unit:
        unit_s = float(env_unit)
    else:
        unit_s = measure_baseline(L=int(L), window=window)
    # full job single-core = one unit per (feed, scan); production = 16 ranks
    baseline_wall = unit_s * F * n_scans / REFERENCE_RANKS
    vs_baseline = baseline_wall / best

    line = {
        "metric": "tod_samples_per_sec",
        "value": round(throughput, 1),
        "unit": "samples/s",
        "vs_baseline": round(vs_baseline, 2),
        "detail": {
            "shape": [F, B, C, T],
            "medfilt_window": window,
            "wall_s": round(best, 4),
            "cg_iters": int(result.n_iter),
            "cg_iters_to_tol": cg_iters_to_tol,
            "cg_residual": [round(float(r), 9) for r in resid.ravel()],
            "cg_iters_per_sec": round(cg_iters_per_sec, 1),
            "preconditioner": precond_name,
            "pair_batch": int(plan.pair_batch),
            "dispatch_count": int(dispatch_count),
            "reduce_dispatches": int(dispatch_n["reduce"]),
            "reduce_wall_s": round(reduce_wall, 4),
            "destripe_wall_s": round(destripe_wall, 4),
            "map_hit_fraction": None,
            "baseline_unit_s": round(unit_s, 3),
            "baseline_unit_policy": (
                "env-override" if env_unit
                else f"min-of-{N_BASELINE_REPS}, cpu-pinned"),
            "baseline_wall_s_16rank": round(baseline_wall, 2),
            "baseline_ranks": REFERENCE_RANKS,
            "device": str(jax.devices()[0].platform),
        },
    }
    hits = np.asarray(result.hit_map)
    line["detail"]["map_hit_fraction"] = round(float((hits > 0).mean()), 3)
    print(json.dumps(line))

    # relay-independent artifacts for the benched tree (VERDICT r4 #1b):
    # op table + compiled-HLO fingerprint, written AFTER the result line
    # (stderr only) so the driver's one-JSON-line contract holds
    N_flat = F * T + n_pad

    def _ev_run():
        r = run_pipeline()
        finish(r)

    sds = jax.ShapeDtypeStruct((B, N_flat), jnp.float32)
    # a thunk, NOT the compiled object: jax Compiled executables are
    # callable, so write_evidence's callable() dispatch would invoke one
    # with zero args (the pytree TypeError the round-5 cpu artifact
    # recorded) — and the AOT lower must run inside its guard anyway
    write_evidence("config35", _ev_run,
                   compile_fn=lambda: jitted_destripe.lower(
                       sds, sds, **coarse_kwargs).compile(),
                   extra=line["detail"])


# --------------------------------------------------------------------------
# Relay-independent evidence: every successful bench leaves artifacts
# --------------------------------------------------------------------------

def gviz_rows(table) -> list:
    """xprof tool data -> ``[header, *rows]``.

    Current xprof returns a gviz-style ``{"cols": [...], "rows": [...]}``
    mapping (each row ``{"c": [{"v": ...}, ...]}``); older versions
    returned a plain list of rows. Anything else -> []."""
    if isinstance(table, dict) and isinstance(table.get("cols"), list):
        hdr = [(c.get("label") or c.get("id", ""))
               if isinstance(c, dict) else str(c) for c in table["cols"]]
        body = [[cell.get("v") if isinstance(cell, dict) else cell
                 for cell in (row.get("c") or [])]
                for row in (table.get("rows") or [])
                if isinstance(row, dict)]
        return [hdr] + body
    if isinstance(table, list):
        return [r for r in table if isinstance(r, (list, dict))]
    return []


def write_evidence(tag: str, run_once, compile_fn=None, extra=None,
                   host_only: bool = False) -> str:
    """Record op-level evidence for a successful bench run (VERDICT r4
    #1b): one extra profiled repetition -> xprof ``hlo_stats`` top ops,
    plus the compiled program's HLO sha256 fingerprint and XLA cost
    analysis. Written to ``<BENCH_EVIDENCE_DIR or repo>/evidence/
    bench_<tag>_<platform>.json`` so a later relay outage leaves
    artifacts for the benched tree, not prose.

    ``compile_fn``: a ZERO-ARG THUNK returning the compiled program —
    never the compiled object itself (jax ``Compiled`` is callable, so
    a callable() dispatch would invoke it argless and record a pytree
    TypeError instead of the fingerprint). The thunk runs inside this
    guard, after the skip check, so a relay-sensitive AOT compile can
    never turn an already-printed successful measurement into a
    failure. ``host_only=True`` (config 1) records provenance WITHOUT
    importing jax at all — the host-only config must stay
    relay-independent end to end (its dispatch path skips the probe,
    and ``jax.devices()`` through a wedged relay hangs).
    ``BENCH_EVIDENCE=0`` skips. Returns the path ('' when skipped)."""
    if os.environ.get("BENCH_EVIDENCE", "1") == "0":
        return ""
    import glob
    import hashlib
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    out_root = os.environ.get("BENCH_EVIDENCE_DIR", "") or repo
    if host_only:
        rec: dict = {"tag": tag, "platform": "host"}
    else:
        import jax

        platform = jax.devices()[0].platform
        rec = {"tag": tag, "platform": platform, "jax": jax.__version__}
    try:
        rev = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                             capture_output=True, text=True)
        rec["git_rev"] = rev.stdout.strip()
        st = subprocess.run(["git", "status", "--porcelain"], cwd=repo,
                            capture_output=True, text=True)
        # evidence from a dirty tree must say so: a bare rev would
        # attribute the measurement to code that cannot reproduce it.
        # Measurement OUTPUTS (evidence artifacts, sweep logs) are not
        # dirt — a session writes them between runs, and without this
        # filter every artifact after the first marks itself dirty
        # against code identical to HEAD
        ev_rel = os.path.relpath(os.path.join(out_root, "evidence"),
                                 repo)
        skip = ("SWEEP_",) if ev_rel.startswith("..") else (
            ev_rel + os.sep, "SWEEP_")
        dirt = [ln for ln in st.stdout.splitlines()
                if ln[3:] and not ln[3:].startswith(skip)]
        if dirt:
            rec["git_rev"] += "-dirty"
    except OSError:
        rec["git_rev"] = ""
    if compile_fn is not None:
        try:
            compiled = compile_fn()
            txt = compiled.as_text()
            rec["hlo_sha256"] = hashlib.sha256(txt.encode()).hexdigest()
            rec["hlo_bytes"] = len(txt)
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            rec["cost_analysis"] = {k: float(v) for k, v in
                                    sorted(dict(cost).items())[:40]}
        except Exception as exc:   # noqa: BLE001 — evidence is best-effort
            rec["compiled_error"] = repr(exc)
    if not host_only:
        prof_dir = tempfile.mkdtemp(prefix=f"bench_ev_{tag}_")
        try:
            with jax.profiler.trace(prof_dir):
                run_once()
            planes = glob.glob(prof_dir + "/**/*.xplane.pb",
                               recursive=True)
            from xprof.convert import raw_to_tool_data as rtd

            data, _ = rtd.xspace_to_tool_data(planes, "hlo_stats", {})
            table = (json.loads(data) if isinstance(data, (str, bytes))
                     else data)
            rows = gviz_rows(table)
            # keep the header + top rows; drop 'while' rows (dbl counts)
            if len(rows) > 1 and isinstance(rows[0], list):
                hdr, body = rows[0], rows[1:]
                cat = next((i for i, label in enumerate(hdr)
                            if "category" in str(label).lower()), None)
                if cat is not None:
                    # short rows (gviz may omit trailing cells) pass
                    # through rather than IndexError the whole table
                    body = [r for r in body
                            if not (isinstance(r, list) and len(r) > cat
                                    and r[cat] == "while")]
                rec["hlo_stats"] = [hdr] + body[:60]
            elif rows and not isinstance(table, dict):
                # legacy list-shaped tables stored verbatim; a gviz
                # header with no body rows is the empty case below
                rec["hlo_stats"] = rows[:60]
            else:
                # an artifact whose primary payload is missing must say
                # so, not record success with an empty table
                rec["profile_error"] = (
                    f"empty hlo_stats table (shape {type(table).__name__})")
        except Exception as exc:   # noqa: BLE001
            rec["profile_error"] = repr(exc)
    if extra:
        rec["detail"] = extra
    os.makedirs(os.path.join(out_root, "evidence"), exist_ok=True)
    path = os.path.join(out_root, "evidence",
                        f"bench_{tag}_{rec['platform']}.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"bench: evidence -> {path}", file=sys.stderr)
    return path


# --------------------------------------------------------------------------
# BASELINE.md configs 1 / 2 / 4 (VERDICT r4 #7)
# --------------------------------------------------------------------------

class _pin_one_cpu:
    """Pin the current process to one CPU for a timed region (the
    measure_baseline child policy, applied in-process); restores the
    previous affinity on exit. No-op where unsupported."""

    def __enter__(self):
        try:
            self._prev = os.sched_getaffinity(0)
            os.sched_setaffinity(0, {next(iter(self._prev))})
        except (AttributeError, OSError):
            self._prev = None
        return self

    def __exit__(self, *exc):
        if self._prev is not None:
            try:
                os.sched_setaffinity(0, self._prev)
            except OSError:
                pass
        return False


def bench_config1():
    """Config 1: single TauA calibrator scan, 1 feed, 1 band, NumPy
    backend — the f64 host oracle against the reference's own
    single-core calibrator chain (both single-threaded on this host)."""
    from comapreduce_tpu.backends.numpy_ops import reduce_feed_scans_np
    from comapreduce_tpu.ops.reduce import ReduceConfig, scan_starts_lengths

    small = os.environ.get("BENCH_SMALL", "") == "1"
    B, C = 1, (64 if small else 1024)
    scan_samples, n_scans, gap = (1000 if small else 6000), 4, 64
    edges, t = [], gap
    for _ in range(n_scans):
        edges.append((t, t + scan_samples))
        t += scan_samples + gap
    T = t
    edges = np.asarray(edges, np.int64)
    rng = np.random.default_rng(11)
    tod = 1e6 * 45.0 * (1.0 + 0.01 * rng.normal(size=(B, C, T)))
    mask = np.zeros((B, C, T), np.float64)
    for s, e in edges:
        mask[..., s:e] = 1.0
    airmass = np.full(T, 1.3)
    tsys = 45.0 * (1.0 + 0.2 * rng.random((B, C)))
    gain = 1e6 * np.ones((B, C))
    freq = np.broadcast_to(np.linspace(-0.1, 0.1, C), (B, C))
    cfg = ReduceConfig(C, medfilt_window=501, is_calibrator=True)

    # pin like the baseline child: single core vs single core
    with _pin_one_cpu():
        t0 = time.perf_counter()
        out = reduce_feed_scans_np(tod, mask, airmass, edges, tsys, gain,
                                   freq, cfg)
        wall = time.perf_counter() - t0
    assert np.isfinite(out["tod"]).any()

    _, _, L = scan_starts_lengths(edges)
    # BENCH_BASELINE_S names the FLAGSHIP unit (medfilt-6000 gain chain)
    # and must not leak in here: the calibrator unit is a different,
    # much cheaper quantity (median baseline, no medfilt/cg) — the
    # round-5 sweep briefly inflated configs 1/2 ~66x/16x through
    # exactly that leak. BENCH_BASELINE_CAL_S is this mode's override.
    env_unit = os.environ.get("BENCH_BASELINE_CAL_S", "")
    # the reference unit must match the workload: ONE band, same C
    unit_s = (float(env_unit) if env_unit else
              measure_baseline(L=int(L), window=501, calibrator=True,
                               B=B, C=C))
    # single feed: the reference cannot spread one feed's scans across
    # ranks inside average_tod (serial per-feed loop) -> 1 rank
    baseline_wall = unit_s * n_scans
    line = {
        "metric": "calibrator_numpy_samples_per_sec",
        "value": round(B * C * T / wall, 1),
        "unit": "samples/s",
        "vs_baseline": round(baseline_wall / wall, 2),
        "detail": {"config": 1, "shape": [1, B, C, T],
                   "wall_s": round(wall, 3),
                   "baseline_unit_s": round(unit_s, 3),
                   "baseline_wall_s_1rank": round(baseline_wall, 2),
                   "backend": "numpy(f64, host)"},
    }
    print(json.dumps(line))
    # provenance artifact, host_only: this config must never touch jax
    # (its dispatch path skips the relay probe, and a wedged relay
    # hangs jax.devices() — relay-independence is the point)
    write_evidence("config1", lambda: None, extra=line["detail"],
                   host_only=True)
    return 0


def bench_config2():
    """Config 2: full 19-feed TauA scan, all 4 bands, gain+bandpass
    chain only (no destriper) on device — calibrator reduction path."""
    _probe_device()
    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.ops.reduce import (ReduceConfig, reduce_feed_scans,
                                            scan_starts_lengths)
    from comapreduce_tpu.ops.vane import _event_kernel

    small = os.environ.get("BENCH_SMALL", "") == "1"
    if small:
        F, B, C, scan_samples, n_scans = 2, 2, 64, 1000, 2
        vane_samples, scan_batch = 128, None
    else:
        F, B, C, scan_samples, n_scans = 19, 4, 1024, 6000, 8
        vane_samples, scan_batch = 256, 2
    gap = 64
    edges, t = [], gap
    for _ in range(n_scans):
        edges.append((t, t + scan_samples))
        t += scan_samples + gap
    T = t
    edges = np.asarray(edges, np.int64)
    scan_mask = np.zeros(T, np.float32)
    for s, e in edges:
        scan_mask[s:e] = 1.0
    starts, lengths, L = scan_starts_lengths(edges)
    starts_j = jnp.asarray(starts, jnp.int32)
    lengths_j = jnp.asarray(lengths, jnp.int32)
    cfg = ReduceConfig(C, medfilt_window=501, is_calibrator=True,
                       scan_batch=scan_batch)
    freq_j = jnp.asarray(
        np.broadcast_to(np.linspace(-0.1, 0.1, C), (B, C)), jnp.float32)
    mask_j = jnp.asarray(scan_mask)

    def feed_step(key):
        k = jax.random.split(key, 4)
        gain = 1e6 * (1.0 + 0.1 * jax.random.normal(k[0], (B, C)))
        tsys = 45.0 * (1.0 + 0.2 * jax.random.uniform(k[1], (B, C)))
        tod = gain[..., None] * tsys[..., None] * (
            1.0 + 0.01 * jax.random.normal(k[2], (B, C, T)))
        vane_step = jnp.where(jnp.arange(vane_samples) < vane_samples // 2,
                              290.0, 0.0)
        vane_tod = gain[..., None] * (tsys[..., None] + vane_step) * (
            1.0 + 1e-3 * jax.random.normal(k[3], (B, C, vane_samples)))
        airmass = jnp.full((T,), 1.2, jnp.float32)
        tsys_cal, gain_cal = _event_kernel(vane_tod[None],
                                           jnp.float32(290.0))
        red = reduce_feed_scans(tod, mask_j, airmass, starts_j, lengths_j,
                                tsys_cal[0], gain_cal[0], freq_j,
                                cfg=cfg, n_scans=len(starts), L=L)
        return red["tod"], red["weights"]

    @jax.jit
    def all_feeds(keys):
        return jax.lax.map(feed_step, keys)

    def run_once():
        keys = jax.random.split(jax.random.key(5, impl="rbg"), F)
        tods, weis = all_feeds(keys)
        # force a host fetch: block_until_ready is not reliable through
        # the axon tunnel (memory: tpu-bench-timing-pitfalls)
        return float(jnp.sum(tods)) + float(jnp.sum(weis))

    run_once()                                  # compile + warm
    best = float("inf")
    for _ in range(1 if small else 2):
        t0 = time.perf_counter()
        run_once()
        best = min(best, time.perf_counter() - t0)

    # see config 1: the flagship BENCH_BASELINE_S must not leak into
    # the calibrator-unit denominator
    env_unit = os.environ.get("BENCH_BASELINE_CAL_S", "")
    unit_s = (float(env_unit) if env_unit else
              measure_baseline(L=int(L), window=501, calibrator=True,
                               B=B, C=C))
    baseline_wall = unit_s * F * n_scans / REFERENCE_RANKS
    line = {
        "metric": "calibrator_chain_samples_per_sec",
        "value": round(F * B * C * T / best, 1),
        "unit": "samples/s",
        "vs_baseline": round(baseline_wall / best, 2),
        "detail": {"config": 2, "shape": [F, B, C, T],
                   "wall_s": round(best, 4),
                   "baseline_unit_s": round(unit_s, 3),
                   "baseline_wall_s_16rank": round(baseline_wall, 2),
                   "device": str(jax.devices()[0].platform)},
    }
    print(json.dumps(line))
    write_evidence("config2", run_once,
                   compile_fn=lambda: all_feeds.lower(jax.random.split(
                       jax.random.key(5, impl="rbg"), F)).compile(),
                   extra=line["detail"])
    return 0


def bench_config4():
    """Config 4: ~50-obsid filelist -> naive binned HEALPix map (no
    destripe) — the foreground-survey co-add. ang2pix + weighted
    segment-sum binning on device, obs streamed through ``lax.map``;
    baseline: the same binning as single-core ``np.add.at`` scaled to
    16 ranks (conservative: the reference's Cython ``binFuncs`` also
    pays its coordinate conversion, excluded here)."""
    _probe_device()
    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.mapmaking import healpix as hp

    small = os.environ.get("BENCH_SMALL", "") == "1"
    if small:
        n_obs, F, T, nside = 4, 2, 4000, 256
    else:
        n_obs, F, T, nside = 50, 19, 54_000, 1024
    npix = 12 * nside * nside

    # per-obs pointing: drifting raster in a ~10x10 deg patch (ra0
    # varies per obs so the co-add covers a band of sky like the fg
    # survey). Pixels come from the host HEALPix path (f64,
    # healpy-exact) as in the reference's healpy+binFuncs flow; the
    # device does the weighted co-add binning.
    rng = np.random.default_rng(9)
    t_h = np.arange(T, dtype=np.float64)
    sweep = 10.0 * np.abs(((t_h / 500.0) % 2.0) - 1.0)
    pix_all = np.empty((n_obs, F * T), np.int32)
    for i in range(n_obs):
        ra0 = 40.0 + 80.0 * rng.random()
        ra = ra0 + sweep[None, :] + 0.3 * np.arange(F)[:, None]
        dec = 30.0 + (t_h / T * 8.0)[None, :] \
            + 0.2 * np.arange(F)[:, None]
        pix_all[i] = np.asarray(hp.ang2pix_lonlat(
            nside, ra.reshape(-1), dec.reshape(-1)), np.int32)
    tod_all = (1.0 + 0.01 * rng.standard_normal(
        (n_obs, F * T))).astype(np.float32)

    def bin_obs(carry, x):
        sig, wei = carry
        pix, tod = x
        sig = sig.at[pix].add(tod)
        wei = wei.at[pix].add(1.0)
        return (sig, wei), 0

    @jax.jit
    def coadd(pix, tod):
        z = jnp.zeros(npix, jnp.float32)
        # unit weights: the hit map IS the weight map (no third scatter
        # — the host baseline pays exactly the same two passes).
        # Measured-and-dropped (SWEEP_r05 follow-up): fusing sig+wei
        # into one (npix, 2) scatter with an (M, 2) payload is 2.2x
        # SLOWER on-chip (2.32 s vs 1.05 s) — the windowed-update
        # scatter lowers worse than two flat f32 scatters, unlike the
        # gather case where the multi-RHS payload rides free.
        (sig, wei), _ = jax.lax.scan(bin_obs, (z, z), (pix, tod))
        return sig, wei

    pix_j = jnp.asarray(pix_all)
    tod_j = jnp.asarray(tod_all)

    def run_once():
        sig, wei = coadd(pix_j, tod_j)
        return float(jnp.sum(wei))   # host fetch forces execution

    run_once()
    best = float("inf")
    for _ in range(1 if small else 2):
        t0 = time.perf_counter()
        run_once()
        best = min(best, time.perf_counter() - t0)

    n_samples = n_obs * F * T
    # single-core np.add.at binning of the SAME pointing and values the
    # device binned (clustered raster, not random indices — random pixels
    # would cache-miss their way to an inflated denominator), CPU-pinned,
    # min of 2 reps (the measure_baseline policy)
    unit = float("inf")
    with _pin_one_cpu():
        for _ in range(2):
            sig_h = np.zeros(npix)
            wei_h = np.zeros(npix)
            t0 = time.perf_counter()
            for i in range(n_obs):
                np.add.at(sig_h, pix_all[i], tod_all[i])
                np.add.at(wei_h, pix_all[i], 1.0)
            unit = min(unit, time.perf_counter() - t0)
    baseline_wall = unit / REFERENCE_RANKS
    line = {
        "metric": "naive_healpix_samples_per_sec",
        "value": round(n_samples / best, 1),
        "unit": "samples/s",
        "vs_baseline": round(baseline_wall / best, 2),
        "detail": {"config": 4, "n_obs": n_obs, "nside": nside,
                   "n_samples": n_samples, "wall_s": round(best, 4),
                   "baseline_wall_s_16rank": round(baseline_wall, 3),
                   "baseline_policy": "np.add.at same pointing, "
                                      "cpu-pinned min-of-2, /16 ranks, "
                                      "pixels precomputed both sides",
                   "device": str(jax.devices()[0].platform)},
    }
    print(json.dumps(line))
    write_evidence("config4", run_once,
                   compile_fn=lambda: coadd.lower(pix_j, tod_j).compile(),
                   extra=line["detail"])
    return 0


def bench_ingest():
    """Ingest mode: streaming-ingest subsystem A/B on real HDF5 files.

    Writes a few synthetic Level-1 observations, then runs the SAME
    read+compute workload three ways over them — serial (read inline,
    the pre-ingest ``run_tod`` behaviour), prefetched (``ingest.
    Prefetcher``, bounded queue, reads overlap compute), and prefetched
    again with a warm ``BlockCache`` — and reports MB/s, queue depth
    over time, and the overlap fraction. Host-only (no jax import):
    relay-independent by construction, like config 1.

    Per-file compute = a host-side statistic over the decoded TOD plus
    a *device window*: a GIL-releasing block sized to the file's bytes
    at ``BENCH_INGEST_DEVICE_MBPS`` (default 400), standing in for the
    accelerator compute the reads overlap with in the real pipeline
    (during ``jit`` dispatch the host thread blocks exactly like this).
    A pure host-compute stand-in cannot show overlap at all on a
    1-core CI box — reads from page cache are memcpy, i.e. CPU work —
    and would mis-measure the subsystem rather than the host.

    Env: ``BENCH_SMALL=1`` tiny shapes; ``BENCH_INGEST_FILES``,
    ``BENCH_INGEST_DEPTH``, ``BENCH_INGEST_DEVICE_MBPS`` override the
    file count / queue depth / emulated device throughput.
    """
    import shutil
    import tempfile

    from comapreduce_tpu.data.synthetic import (SyntheticObsParams,
                                                generate_level1_file)
    from comapreduce_tpu.ingest import (BlockCache, Prefetcher,
                                        iter_serial, load_level1)

    small = os.environ.get("BENCH_SMALL", "") == "1"
    n_files = int(os.environ.get("BENCH_INGEST_FILES",
                                 "3" if small else "6"))
    depth = int(os.environ.get("BENCH_INGEST_DEPTH", "2"))
    shape = (dict(n_feeds=2, n_bands=2, n_channels=16, n_scans=2,
                  scan_samples=400, vane_samples=128) if small else
             dict(n_feeds=2, n_bands=4, n_channels=256, n_scans=4,
                  scan_samples=4000, vane_samples=256))

    tmp = tempfile.mkdtemp(prefix="bench_ingest_")
    try:
        files = []
        for i in range(n_files):
            path = os.path.join(tmp, f"comap-{1000 + i:07d}-synth.hd5")
            generate_level1_file(path, SyntheticObsParams(
                obsid=1000 + i, seed=100 + i, **shape))
            files.append(path)
        bytes_total = sum(os.path.getsize(f) for f in files)

        def loader(path):
            return load_level1(path, eager_tod=True)

        device_mbps = float(os.environ.get("BENCH_INGEST_DEVICE_MBPS",
                                           "400"))

        def compute(payload):
            # host-side stat touches the decoded data once, then the
            # device window (see docstring): the consumer thread blocks
            # GIL-free for bytes/device_mbps, the way it blocks on a
            # fetched device result in the real pipeline
            tod = payload["data"]["spectrometer/tod"]
            stat = float(np.abs(tod[..., ::64]).mean())
            time.sleep(tod.nbytes / (device_mbps * 1e6))
            return stat

        def run(items):
            t_read = t_compute = 0.0
            t0 = time.perf_counter()
            for item in items:
                item.result()  # re-raise per-file errors (none expected)
                t_read += item.read_s
                tc = time.perf_counter()
                compute(item.payload)
                t_compute += time.perf_counter() - tc
            return time.perf_counter() - t0, t_read, t_compute

        # warm the OS page cache so serial vs prefetch see the same
        # file-read cost (the A/B measures overlap, not cold disks)
        for f in files:
            with open(f, "rb") as fh:
                while fh.read(1 << 22):
                    pass

        serial_wall, read_s, compute_s = run(iter_serial(files, loader))

        pre = Prefetcher(files, loader, depth=depth)
        prefetch_wall, _, _ = run(pre)
        depth_log = [(round(t, 4), q) for t, q in pre.depth_log]

        cache = BlockCache(max_bytes=2 * bytes_total)
        with Prefetcher(files, loader, depth=depth, cache=cache) as p1:
            run(p1)  # populate
        with Prefetcher(files, loader, depth=depth, cache=cache) as p2:
            cached_wall, _, _ = run(p2)

        # the read you can hide is at most the compute you hide it
        # behind (and vice versa): normalise the measured saving by that
        ideal_saving = min(read_s, compute_s)
        overlap = (serial_wall - prefetch_wall) / ideal_saving \
            if ideal_saving > 0 else 0.0
        line = {
            "metric": "ingest_mb_per_sec",
            "value": round(bytes_total / 1e6 / prefetch_wall, 2),
            "unit": "MB/s",
            "vs_baseline": round(serial_wall / prefetch_wall, 3),
            "detail": {
                "config": "ingest",
                "n_files": n_files,
                "bytes_total": int(bytes_total),
                "queue_depth": depth,
                "serial_wall_s": round(serial_wall, 4),
                "prefetch_wall_s": round(prefetch_wall, 4),
                "cached_wall_s": round(cached_wall, 4),
                "read_s_total": round(read_s, 4),
                "compute_s_total": round(compute_s, 4),
                "overlap_fraction": round(max(min(overlap, 1.0), -1.0), 3),
                "queue_depth_log": depth_log[:200],
                "cache_stats": dict(cache.stats),
            },
        }
        print(json.dumps(line))
        write_evidence("ingest", lambda: None, extra=line["detail"],
                       host_only=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_resilience():
    """Resilience mode: the chaos drill as a benchmark config.

    Runs ``resilience.drill.run_drill`` (the ``tools/check_resilience``
    contract: every injected fault handled + ledgered — including a
    hanging read cancelled at the watchdog's hard deadline within
    ``hard + grace`` — chaos map byte-identical to the zero-weighted
    clean map, quarantine skip and re-admit correct across runs) and
    reports faults handled per second of drill wall time. Any broken
    promise raises — this config FAILING is the signal, the throughput
    number is just the trend line. The evidence line carries the
    measured per-attempt hang cancel latencies (``hang_cancel_s``).
    """
    import shutil
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from comapreduce_tpu.resilience.drill import run_drill

    tmp = tempfile.mkdtemp(prefix="bench_resilience_")
    try:
        evidence = run_drill(tmp, seed=0)
        n_faults = len(evidence["injected"])
        line = {
            "metric": "resilience_faults_per_sec",
            "value": round(n_faults / max(evidence["wall_s"], 1e-9), 3),
            "unit": "faults/s",
            # the contract is binary: 1.0 iff every promise held (the
            # drill raises otherwise, so reaching here IS the pass)
            "vs_baseline": 1.0,
            "detail": {"config": "resilience", **evidence},
        }
        print(json.dumps(line))
        write_evidence("resilience", lambda: None, extra=line["detail"],
                       host_only=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def _campaign_telemetry_check(log_dir, window, steady_wall, timings):
    """Close the campaign run's telemetry stream and cross-check the
    merged timeline against the bench's own bookkeeping: steady-state
    backend compiles recomputed from ``jax.compile`` spans (must match
    the CompileCounter exactly) and the read/compute overlap fraction
    integrated from span intersections (must track the bench's
    timings+wall estimate). ``tools/check_perf.py`` gates both."""
    from comapreduce_tpu.telemetry import TELEMETRY, merge_streams
    from comapreduce_tpu.telemetry.report import (chrome_trace,
                                                  overlap_seconds)

    TELEMETRY.close()
    merged = merge_streams(log_dir)
    w0, w1 = window
    compile_spans = sum(1 for s in merged.spans_named("jax.compile")
                        if w0 <= s["t"] + s["dur"] <= w1)
    # overlap, both ways, normalised by the steady wall: telemetry
    # integrates actual span intersections; the bench only knows
    # per-file busy totals, where busy beyond wall = overlapped time
    inter = overlap_seconds(merged, "ingest.read", "ingest.compute",
                            t0=w0, t1=w1)
    tele_frac = inter / (w1 - w0) if w1 > w0 else 0.0
    read_s = sum(timings.get("ingest.read", [])[1:])
    comp_s = sum(timings.get("ingest.compute", [])[1:])
    bench_frac = (max(read_s + comp_s - steady_wall, 0.0) / steady_wall
                  if steady_wall > 0 else 0.0)
    try:
        trace = json.loads(json.dumps(chrome_trace(merged)))
        trace_valid = bool(trace.get("traceEvents"))
    except (TypeError, ValueError):
        trace_valid = False
    return {
        "trace_valid": trace_valid,
        "steady_compile_spans": int(compile_spans),
        "overlap_read_compute": round(tele_frac, 4),
        "overlap_read_compute_bench": round(bench_frac, 4),
        "spans": len(merged.spans),
        "truncated_spans": sum(1 for s in merged.spans
                               if s["truncated"]),
        "dropped_lines": merged.dropped_lines,
    }


def bench_campaign():
    """Campaign mode: whole-filelist executor A/B (ISSUE 5).

    Generates N synthetic Level-1 files with realistic shape jitter
    (per-file scan-sample counts differ, so every file is a distinct
    ``(T, S, L)`` geometry) and runs the reduction chain over them two
    ways:

    - **campaign**: shape canonicalisation (one bucket for the whole
      filelist), persistent compile cache + background AOT warm-up, and
      async Level-2 writeback — the PR 5 executor;
    - **baseline**: the pre-campaign executor (per-file exact shapes,
      synchronous checkpoint writes) — run SECOND so any geometry-
      independent program it shares with the campaign run is already
      compiled, biasing the A/B *against* the campaign.

    The first file of each run absorbs cold compiles; the timed segment
    is files[1:] — the steady state. Reported: steady-state files/hour,
    backend compiles in the steady segment for both runs (the campaign
    number gated ``<= bucket_count`` by ``tools/check_perf.py``),
    persistent-cache hits, and the write-overlap fraction (share of
    async write seconds hidden behind stage compute).

    The campaign run also exercises the telemetry pipeline end to end
    (ISSUE 10): spans stream to ``events.rank0.jsonl`` in the campaign
    outdir, and after the run the merged timeline must (a) export valid
    Chrome trace JSON, (b) recompute the steady-state backend-compile
    count exactly from ``jax.compile`` spans, and (c) reproduce the
    read/compute overlap fraction the bench derives from its own
    timings+wall bookkeeping — both gated by ``tools/check_perf.py``.
    ``BENCH_TELEMETRY=0`` disables (used by the overhead A/B).

    Env: ``BENCH_SMALL=1`` tiny shapes; ``BENCH_CAMPAIGN_FILES``
    overrides the file count; ``BENCH_TELEMETRY=0`` turns telemetry
    off.
    """
    import shutil
    import tempfile

    from comapreduce_tpu.data.synthetic import (SyntheticObsParams,
                                                generate_level1_file)
    from comapreduce_tpu.ops.reduce import ShapeBuckets
    from comapreduce_tpu.pipeline import Runner
    from comapreduce_tpu.pipeline.campaign import (CompileCounter,
                                                   campaign_bucket_set,
                                                   probe_observation)
    from comapreduce_tpu.pipeline.stages import (
        AssignLevel1Data, AtmosphereRemoval, CheckLevel1File,
        Level1Averaging, Level1AveragingGainCorrection,
        MeasureSystemTemperature, SkyDip)

    small = os.environ.get("BENCH_SMALL", "") == "1"
    n_files = int(os.environ.get("BENCH_CAMPAIGN_FILES",
                                 "3" if small else "8"))
    base_samples = 400 if small else 800
    shape = (dict(n_feeds=2, n_bands=1, n_channels=16, n_scans=3,
                  vane_samples=120) if small else
             dict(n_feeds=2, n_bands=1, n_channels=32, n_scans=3,
                  vane_samples=128))
    quanta = (dict(t_quantum=2048, scan_quantum=4, l_quantum=512)
              if small else
              dict(t_quantum=4096, scan_quantum=4, l_quantum=1024))

    def chain():
        return [CheckLevel1File(min_duration_seconds=0.0),
                AssignLevel1Data(), MeasureSystemTemperature(),
                SkyDip(), AtmosphereRemoval(),
                Level1Averaging(frequency_bin_size=8),
                Level1AveragingGainCorrection(medfilt_window=301)]

    tmp = tempfile.mkdtemp(prefix="bench_campaign_")
    try:
        files = []
        for i in range(n_files):
            # deterministic second-level duration jitter: every file a
            # distinct T (and a mix of L buckets) — the adversarial
            # filelist for a per-exact-shape compile cache
            samples = base_samples + ((i * 29) % 97) - 48
            path = os.path.join(tmp, f"comap-{2000 + i:07d}-synth.hd5")
            generate_level1_file(path, SyntheticObsParams(
                obsid=2000 + i, seed=200 + i,
                scan_samples=samples, **shape))
            files.append(path)

        buckets = ShapeBuckets(**quanta)
        shapes = [probe_observation(f) for f in files]
        bucket_count = len(campaign_bucket_set(shapes, buckets))

        telemetry_on = os.environ.get("BENCH_TELEMETRY", "1") != "0"

        def timed_run(tag, campaign, ingest, telemetry=None):
            outdir = os.path.join(tmp, tag)
            runner = Runner(processes=chain(), output_dir=outdir,
                            campaign=campaign, ingest=ingest,
                            telemetry=telemetry,
                            resilience={"quarantine": "off",
                                        "heartbeat_s": 0})
            with CompileCounter() as c:
                runner.run_tod(files[:1])      # absorb cold compiles
                c_first = c.snapshot()
                w0 = time.time()               # steady window in the
                t0 = time.perf_counter()       # reader's wall domain
                runner.run_tod(files[1:])
                steady_wall = time.perf_counter() - t0
                w1 = time.time()
                c_end = c.snapshot()
            steady = {k: c_end[k] - c_first[k] for k in c_end}
            return (steady_wall, steady, c_end,
                    dict(runner.writeback_stats), (w0, w1), runner)

        cache_dir = os.path.join(tmp, "jaxcache")
        camp_wall, camp_steady, camp_full, wb, camp_win, camp_runner = \
            timed_run(
                "campaign",
                campaign={**quanta, "warm_compile": True},
                ingest={"compile_cache_dir": cache_dir, "writeback": 2,
                        "prefetch": 2},
                telemetry=({"enabled": True, "flush_s": 0.2}
                           if telemetry_on else None))

        # program-registry cross-check: snapshot BEFORE the telemetry
        # close below (PROGRAMS rides TELEMETRY's lifecycle) — every
        # steady-state warmup program must carry a cost/memory record,
        # and the registry can never have recorded more programs than
        # the CompileCounter saw compile requests
        progs = []
        if telemetry_on:
            from comapreduce_tpu.telemetry.programs import PROGRAMS

            progs = PROGRAMS.snapshot()
        programs_info = {
            "recorded": len(progs),
            "names": sorted({p["name"] for p in progs}),
            "compile_requests_full_run": camp_full["backend_compiles"],
            "within_compile_budget":
                len(progs) <= camp_full["backend_compiles"],
        }

        # telemetry cross-check BEFORE the baseline run: TELEMETRY is
        # process-global, so close it here or the baseline would keep
        # appending to the campaign's stream
        tele = {}
        if telemetry_on:
            tele = _campaign_telemetry_check(
                os.path.join(tmp, "campaign"), camp_win, camp_wall,
                camp_runner.timings)

        # baseline AFTER the campaign run (see docstring) with the
        # persistent cache off — the pre-PR executor had neither
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
        base_wall, base_steady, _, _, _, _ = timed_run(
            "baseline", None, None)

        write_s = wb.get("write_s", 0.0)
        flush_wait = wb.get("flush_wait_s", 0.0)
        overlap = (1.0 - flush_wait / write_s) if write_s > 0 else 0.0
        n_steady = max(n_files - 1, 1)
        line = {
            "metric": "campaign_files_per_hour",
            "value": round(3600.0 * n_steady / camp_wall, 2),
            "unit": "files/h",
            "vs_baseline": round(base_wall / camp_wall, 3),
            "detail": {
                "config": "campaign",
                "n_files": n_files,
                "bucket_count": bucket_count,
                "quanta": quanta,
                "raw_shapes": [[s["T"], s["S"], s["L"]] for s in shapes],
                "steady_wall_s": round(camp_wall, 4),
                "baseline_steady_wall_s": round(base_wall, 4),
                # backend_compiles counts compile REQUESTS; with the
                # persistent cache on, a request can be a fast disk hit
                # (cache_hits) — cache_misses is the true XLA-compile
                # count of the steady segment
                "compiles_campaign_steady":
                    camp_steady["backend_compiles"],
                "compiles_baseline_steady":
                    base_steady["backend_compiles"],
                "cache_hits": camp_steady["cache_hits"],
                "cache_misses": camp_steady["cache_misses"],
                "writeback": {k: round(v, 4) if isinstance(v, float)
                              else v for k, v in wb.items()},
                "write_overlap_fraction":
                    round(max(min(overlap, 1.0), 0.0), 3),
                # {} when BENCH_TELEMETRY=0 — check_perf's telemetry
                # gate skips on absence
                "telemetry": tele,
                "programs": programs_info,
            },
        }
        print(json.dumps(line))
        write_evidence("campaign", lambda: None, extra=line["detail"],
                       host_only=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_serving():
    """Serving mode: the incremental map server as a benchmark config
    (ISSUE 9).

    Replays a jittered arrival schedule over the serving drill's 1/f
    fixture (8 Level-2 files, three commit waves of 6+1+1) against an
    in-process :class:`~comapreduce_tpu.serving.server.MapServer`, then
    solves the full census cold into a twin epochs root. Reported:

    - **freshness**: per-epoch commit-to-published latency (the
      manifest's ``freshness_s`` — wall time from the newest folded
      file's lease commit to the epoch's atomic publish), the headline
      value being the final, warm epoch's;
    - **warm-start savings**: CG iterations of the final warm epoch vs
      the cold solve of the SAME census — ``vs_baseline`` is
      cold/warm (> 1 means warm starts pay). ``tools/check_perf.py``
      gates warm strictly below cold; machine-independent (an ordering
      of two iteration counts on one deterministic fixture).

    The fixture is the drill's exact, seed-verified configuration in
    both normal and ``BENCH_SMALL`` modes — the warm-vs-cold margin is
    a property of the 1/f realisation, so the bench does not scale it.
    """
    import shutil
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience.drill import (_commit_done,
                                                  _write_level2)
    from comapreduce_tpu.serving.server import MapServer

    seed = int(os.environ.get("BENCH_SERVING_SEED", "0"))
    tmp = tempfile.mkdtemp(prefix="bench_serving_")
    try:
        files = []
        for i in range(8):
            path = os.path.join(tmp, f"Level2_serving-{i:04d}.hd5")
            _write_level2(path, seed=1000 + seed * 10 + i, drift=6.0,
                          rw=0.3, raster=True)
            files.append(path)
        waves = [files[:6], files[6:7], files[7:8]]
        state = os.path.join(tmp, "state")
        solver = dict(
            wcs=WCS.from_field((170.25, 52.25), (1 / 60, 1 / 60),
                               (64, 64)),
            band=0, offset_length=50, n_iter=300, threshold=1e-8,
            medfilt_window=201, use_calibration=False)

        server = MapServer(state, os.path.join(tmp, "epochs"), **solver)
        epochs = []
        for wave in waves:
            _commit_done(state, wave)
            n = server.poll_once(force=True)
            man = server.store.manifest(n) or {}
            epochs.append({
                "epoch": n, "n_files": man.get("n_files"),
                "n_new": man.get("n_new"),
                "cg_iters": (man.get("cg") or {}).get("n_iter"),
                "x0": (man.get("cg") or {}).get("x0"),
                "freshness_s": round(float(man.get("freshness_s",
                                                   0.0)), 3),
                "t_solve_s": round(float(man.get("t_solve_s", 0.0)), 3),
            })
        warm_iters = epochs[-1]["cg_iters"]

        cold = MapServer(state, os.path.join(tmp, "epochs-cold"),
                         warm_start=False, **solver)
        n = cold.poll_once(force=True)
        cold_man = cold.store.manifest(n) or {}
        cold_iters = (cold_man.get("cg") or {}).get("n_iter")

        line = {
            "metric": "serving_freshness_s",
            "value": epochs[-1]["freshness_s"],
            "unit": "s",
            # warm-start payoff on the same census: cold/warm CG
            # iterations (> 1 means incremental epochs solve cheaper)
            "vs_baseline": (round(cold_iters / warm_iters, 3)
                            if warm_iters and cold_iters else None),
            "detail": {
                "config": "serving",
                "n_files": len(files),
                "waves": [len(w) for w in waves],
                "epochs": epochs,
                "warm_iters": warm_iters,
                "cold_iters": cold_iters,
                "cold_x0": (cold_man.get("cg") or {}).get("x0"),
            },
        }
        print(json.dumps(line))
        write_evidence("serving", lambda: None, extra=line["detail"],
                       host_only=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_destriper():
    """Destriper mode: survey-scale compaction + preconditioner ladder
    (ISSUE 6).

    Three measurements on the weight-spread raster fixture (two decades
    of weight spread, 1/f offsets — the class where preconditioning
    works for its living):

    - **preconditioner ladder**: iterations-to-1e-6 and ms/iter for
      ``none | jacobi | twolevel | multigrid`` — the acceptance bound
      is multigrid < twolevel in ITERATIONS (the V-cycle's 2 extra fine
      matvecs per application are reported honestly in ms/iter, not
      hidden);
    - **compacted vs dense**: the same jacobi solve through a
      ``PixelSpace`` seen-pixel dictionary vs the dense map space —
      ms/iter for both plus the device map-vector bytes (the planned
      matvec already runs in rank space, so compaction should cost ~0
      per iteration and shrink the map products to coverage);
    - **nside-4096 survey smoke**: the raster walked over a HEALPix
      nside-4096 patch (~201M sky pixels), destriped compacted on THIS
      container — recorded map-vector bytes are ``O(n_compact)``;
      the dense equivalent (printed for scale) would be ~3.2 GB of map
      products and is never allocated.

    The result line's ``detail.compacted``/``detail.survey4096`` carry
    ``map_vector_bytes``/``n_compact`` for the machine-independent
    memory gate in ``tools/check_perf.py`` (bytes <= 2x the exact
    ``4 B x (3 n_bands + 1) x n_compact`` budget). ``BENCH_SMALL=1``
    shrinks the fixture (CI smoke). Unless ``BENCH_EVIDENCE=0``, the
    line is also written to ``BENCH_r06.json`` (the round-7 ROOFLINE
    artifact).
    """
    import functools

    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.mapmaking import healpix as hp
    from comapreduce_tpu.mapmaking.destriper import (
        build_coarse_preconditioner, build_multigrid_hierarchy,
        destripe_planned)
    from comapreduce_tpu.mapmaking.pixel_space import PixelSpace
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.telemetry import solver_trace
    from comapreduce_tpu.telemetry.programs import PROGRAMS, shape_bucket

    small = os.environ.get("BENCH_SMALL", "") == "1"
    T = 12_000 if small else 120_000
    nx = 32 if small else 64
    L, n_iter = 50, 2000
    pix, tod, w, npix, _ = weight_spread_raster(T=T, nx=nx, L=L)
    n = pix.size
    tod_j, w_j = jnp.asarray(tod), jnp.asarray(w)

    # program cost/memory registry + solver trace land next to the
    # evidence artifacts (programs.jsonl / solver.rank0.jsonl) — the
    # check_perf HBM gate and the trace cross-check read them back.
    # With evidence writing off and no explicit dir (the perf gate's
    # children), they go to a temp dir: no artifact churn in the repo
    out_root = os.environ.get("BENCH_EVIDENCE_DIR", "")
    if not out_root:
        if os.environ.get("BENCH_EVIDENCE", "1") == "0":
            import tempfile

            out_root = tempfile.mkdtemp(prefix="bench_destriper_")
        else:
            out_root = os.path.dirname(os.path.abspath(__file__))
    if not PROGRAMS.enabled:
        PROGRAMS.configure(out_root)

    # the registry key carries the RESOLVED binning implementation
    # (ISSUE 19): 'auto' compiles genuinely different programs on TPU
    # (pallas) vs everywhere else (xla), and one shared key would let
    # whichever ran last corrupt the HBM gate baseline
    from comapreduce_tpu.mapmaking.pallas_binning import resolve_kernels
    resolved_impl = resolve_kernels("auto")

    def run(pixv, npixv, name, call_kwargs=None, **partial_kwargs):
        """AOT-compile one planned solve (feeding the compiled
        executable's cost/memory analysis to the program registry —
        the SAME compile the timed run dispatches, zero double
        compiles), warm it, then time a repeat run. Returns
        (result, wall_s of the timed run)."""
        plan = build_pointing_plan(pixv, npixv, L)
        fn = jax.jit(functools.partial(destripe_planned, plan=plan,
                                       n_iter=n_iter, threshold=1e-6,
                                       **partial_kwargs))
        kw = call_kwargs or {}
        compiled = fn.lower(tod_j, w_j, **kw).compile()
        PROGRAMS.record(f"destriper.{name}", compiled,
                        shape_bucket=shape_bucket(tod_j, w_j),
                        precision_id="tod=f32|cgdot=f32",
                        kernels=resolved_impl)
        r = compiled(tod_j, w_j, **kw)
        float(jnp.sum(r.destriped_map))          # warm + device sync
        t0 = time.perf_counter()
        r = compiled(tod_j, w_j, **kw)
        float(jnp.sum(r.destriped_map))          # host fetch (see finish)
        return r, time.perf_counter() - t0

    def stats(r, wall):
        resid = float(np.max(np.asarray(r.residual)))
        iters = int(r.n_iter)
        return {"iters_to_tol": iters if resid <= 1e-6 else None,
                "residual": round(resid, 9),
                "wall_s": round(wall, 4),
                "ms_per_iter": round(1e3 * wall / max(iters, 1), 3)}

    def map_bytes(r):
        return int(sum(leaf.nbytes for leaf in
                       (r.destriped_map, r.naive_map, r.weight_map,
                        r.hit_map)))

    # ---- preconditioner ladder (dense map space) ------------------------
    ladder = {}
    r_mg = None
    for name in ("none", "jacobi", "twolevel", "multigrid"):
        call_kw, part_kw, extra = {}, {}, {}
        if name == "none":
            part_kw["precond"] = "none"
        elif name == "twolevel":
            # the default block (8) can trip the divergence monitor on
            # some raster geometries (f32 SPD loss in the coarse
            # inverse — the documented failure the CLI falls back
            # from); escalate the block like an operator would and
            # record every diverged attempt rather than hiding it
            diverged_blocks = []
            for blk in (8, 16, 32):
                grp, aci = build_coarse_preconditioner(pix, w, npix, L,
                                                       block=blk)
                call_kw["coarse"] = (jnp.asarray(grp), jnp.asarray(aci))
                r, wall = run(pix, npix, f"twolevel_b{blk}",
                              call_kwargs=call_kw)
                if not np.any(np.asarray(r.diverged)):
                    break
                diverged_blocks.append(blk)
            extra = {"coarse_block": blk,
                     "diverged_blocks": diverged_blocks}
            ladder[name] = {**stats(r, wall), **extra}
            continue
        elif name == "multigrid":
            call_kw["mg"] = jax.tree_util.tree_map(
                jnp.asarray,
                build_multigrid_hierarchy(pix, w, npix, L, block=8,
                                          levels=2))
            # the acceptance rung carries the per-iteration solver
            # trace (3 scalar scatters/iteration — noise next to the
            # V-cycle's matvecs, and reported honestly either way)
            part_kw["trace_iters"] = n_iter
        r, wall = run(pix, npix, name, call_kwargs=call_kw, **part_kw)
        ladder[name] = stats(r, wall)
        if name == "multigrid":
            r_mg = r

    # ---- solver trace cross-check: the recorded per-iteration residual
    # records must match the solve's reported iteration count EXACTLY
    # (both come from the same dispatch — the traced multigrid rung) ------
    trace_path = os.path.join(out_root, "solver.rank0.jsonl")
    try:
        os.unlink(trace_path)        # count THIS run's records only
    except OSError:
        pass
    solver_trace.record_solve(
        r_mg, band="multigrid", path=trace_path,
        precond_id=f"multigrid|L{L}", precision_id="tod=f32|cgdot=f32",
        threshold=1e-6)
    trace_recs = [rec for rec in solver_trace.read_solver(trace_path)
                  if rec.get("kind") == "iteration"]
    trace_info = {
        "path": trace_path,
        "iteration_records": len(trace_recs),
        "reported_iters": int(r_mg.n_iter),
        "match": len(trace_recs) == int(r_mg.n_iter),
    }

    # ---- compacted vs dense (jacobi) ------------------------------------
    space = PixelSpace.from_pixels(pix, npix)
    r_dense, wall_dense = run(pix, npix, "compact_dense")
    r_comp, wall_comp = run(space.remap(pix), space, "compact")
    compacted = {
        "dense": {**stats(r_dense, wall_dense),
                  "map_vector_bytes": map_bytes(r_dense)},
        **stats(r_comp, wall_comp),
        "map_vector_bytes": map_bytes(r_comp),
        "n_compact": space.n_compact, "npix_dense": npix,
        "n_bands": 1,
    }

    # ---- nside-4096 survey smoke (compacted only — dense would be
    # ~3.2 GB of map products and must never be allocated) ----------------
    nside = 4096
    hpix = raster_to_healpix(pix, nx, nside)
    npix_sky = hp.nside2npix(nside)
    sp4096 = PixelSpace.from_pixels(hpix, npix_sky)
    r_s, wall_s = run(sp4096.remap(hpix), sp4096, "survey4096")
    survey = {**stats(r_s, wall_s),
              "nside": nside, "npix_sky": npix_sky,
              "n_compact": sp4096.n_compact,
              "coverage_fraction": round(sp4096.n_compact / npix_sky, 8),
              "map_vector_bytes": map_bytes(r_s),
              "dense_equiv_bytes": 4 * 4 * npix_sky,
              "n_bands": 1}

    line = {
        "metric": "destriper_cg_iters_to_tol",
        "value": ladder["multigrid"]["iters_to_tol"],
        "unit": "iterations",
        # the acceptance ratio: multigrid vs twolevel iterations (None
        # when either burned its budget unconverged — never pretend)
        "vs_baseline": (round(ladder["twolevel"]["iters_to_tol"]
                              / ladder["multigrid"]["iters_to_tol"], 3)
                        if ladder["multigrid"]["iters_to_tol"]
                        and ladder["twolevel"]["iters_to_tol"] else None),
        "detail": {
            "config": "destriper",
            "fixture": {"T": int(n), "nx": nx, "offset_length": L,
                        "n_offsets": n // L, "threshold": 1e-6},
            "preconditioners": ladder,
            "compacted": compacted,
            "survey4096": survey,
            "solver_trace": trace_info,
            "programs": PROGRAMS.snapshot(),
            "device": str(jax.devices()[0].platform),
        },
    }
    print(json.dumps(line))
    if os.environ.get("BENCH_EVIDENCE", "1") != "0":
        out_root = (os.environ.get("BENCH_EVIDENCE_DIR", "")
                    or os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(out_root, "BENCH_r06.json"), "w") as f:
            json.dump(line, f, indent=1)
    write_evidence("destriper", lambda: None, extra=line["detail"],
                   host_only=True)
    return 0


def bench_destriper_sharded():
    """Sharded-solver mode (ISSUE 19): the campaign solver path's
    iteration ladder UNDER SHARDING, plus measured-noise banded
    weighting — the two moves that stop the 1.65x iteration tax.

    Measurements (``BENCH_r09.json``, the round-10 ROOFLINE artifact):

    - **sharded preconditioner ladder**: iterations-to-tol for the
      single-device multigrid reference, sharded twolevel, and the
      native sharded MULTIGRID program (``with_mg=True`` — the rung
      that used to fall back to twolevel with a warning) on the
      weight-spread raster. Acceptance: sharded multigrid matches the
      single-device iteration count (same operator, psum-assembled
      coarse residual) and strictly beats sharded twolevel;
    - **offsets parity**: sharded-vs-single multigrid solutions agree;
    - **solver-trace cross-check**: the traced sharded rung's
      per-iteration records match its reported count EXACTLY;
    - **banded noise weighting**: on a 1/f fixture whose noise is drawn
      from the same PSD the quality fit reports (``sigma^2
      (f/fknee)^alpha``) with inverse-variance weights, map RMS error
      and iterations for white vs banded (single device), plus
      sharded-banded vs single-banded offsets parity (the no-halo
      boundary-zeroing contract).

    Needs >= 2 devices: when the host would expose one CPU device the
    conftest idiom (``--xla_force_host_platform_device_count``) forces
    a multi-device CPU mesh — set BEFORE jax imports, so this config
    must run in a fresh process (the ``BENCH_CONFIG`` contract).
    ``BENCH_SHARDS`` overrides the forced count (default 4).
    ``BENCH_SMALL=1`` shrinks both fixtures (CI smoke).
    """
    n_want = max(int(os.environ.get("BENCH_SHARDS", "4")), 2)
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{n_want}").strip()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from comapreduce_tpu.mapmaking.destriper import (
        build_coarse_preconditioner, build_multigrid_hierarchy,
        destripe_planned)
    from comapreduce_tpu.mapmaking.noise_weight import build_banded_weight
    from comapreduce_tpu.mapmaking.pointing_plan import (
        build_pointing_plan, build_sharded_plans)
    from comapreduce_tpu.parallel.sharded import (
        make_destripe_sharded_planned)
    from comapreduce_tpu.telemetry import solver_trace

    devices = jax.devices()
    if len(devices) < 2:
        print("bench: destriper-sharded needs >= 2 devices; got "
              f"{len(devices)} ({devices[0].platform}). Run in a fresh "
              "process (the XLA device-count flag cannot apply after "
              "jax import).", file=sys.stderr)
        return 3
    n_shards = len(devices)
    mesh = Mesh(np.array(devices), ("time",))

    small = os.environ.get("BENCH_SMALL", "") == "1"
    T = 12_000 if small else 120_000
    nx = 32 if small else 64
    L, n_iter, threshold = 50, 2000, 1e-6
    pix, tod, w, npix, _ = weight_spread_raster(T=T, nx=nx, L=L)

    # every shard owns whole offsets: pad to the shard quantum with the
    # zero-weight npix sentinel (the CLI's _pad_pixels rule), and run
    # the single-device reference on the SAME padded vectors so the
    # iteration counts compare the sharding alone
    n_pad = (-pix.size) % (n_shards * L)
    if n_pad:
        pix = np.concatenate([pix, np.full(n_pad, npix, pix.dtype)])
        tod = np.concatenate([tod, np.zeros(n_pad, tod.dtype)])
        w = np.concatenate([w, np.zeros(n_pad, w.dtype)])
    tod_j, w_j = jnp.asarray(tod), jnp.asarray(w)

    out_root = os.environ.get("BENCH_EVIDENCE_DIR", "")
    if not out_root:
        if os.environ.get("BENCH_EVIDENCE", "1") == "0":
            import tempfile

            out_root = tempfile.mkdtemp(prefix="bench_sharded_")
        else:
            out_root = os.path.dirname(os.path.abspath(__file__))

    def stats(r, wall):
        resid = float(np.max(np.asarray(r.residual)))
        iters = int(r.n_iter)
        return {"iters_to_tol": iters if resid <= threshold else None,
                "residual": round(resid, 9),
                "diverged": bool(np.any(np.asarray(r.diverged))),
                "wall_s": round(wall, 4),
                "ms_per_iter": round(1e3 * wall / max(iters, 1), 3)}

    def timed(fn, *args, **kw):
        r = fn(*args, **kw)
        int(r.n_iter)                          # warm + device sync
        t0 = time.perf_counter()
        r = fn(*args, **kw)
        int(r.n_iter)
        return r, time.perf_counter() - t0

    # ---- sharded preconditioner ladder ----------------------------------
    hier = build_multigrid_hierarchy(pix, w, npix, L, block=8, levels=2)
    plan = build_pointing_plan(pix, npix, L)
    single = jax.jit(functools.partial(destripe_planned, plan=plan,
                                       n_iter=n_iter,
                                       threshold=threshold))
    r_single, wall_single = timed(single, tod_j, w_j, mg=hier)

    plans = build_sharded_plans(pix, npix, L, n_shards)
    run_mg = make_destripe_sharded_planned(
        mesh, plans, n_iter=n_iter, threshold=threshold, with_mg=True,
        trace_iters=n_iter)
    r_mg, wall_mg = timed(run_mg, tod_j, w_j, mg=hier)
    run_tw = make_destripe_sharded_planned(
        mesh, plans, n_iter=n_iter, threshold=threshold,
        with_coarse=True)
    # the default block (8) can lose SPD in the f32 coarse inverse on
    # some raster geometries (the same documented failure the
    # single-device ladder escalates through) — escalate identically
    # and record every diverged attempt rather than hiding it
    diverged_blocks = []
    for blk in (8, 16, 32):
        coarse = build_coarse_preconditioner(pix, w, npix, L, block=blk)
        r_tw, wall_tw = timed(run_tw, tod_j, w_j, coarse=coarse)
        if not np.any(np.asarray(r_tw.diverged)):
            break
        diverged_blocks.append(blk)

    ladder = {"single_multigrid": stats(r_single, wall_single),
              "sharded_multigrid": stats(r_mg, wall_mg),
              "sharded_twolevel": {**stats(r_tw, wall_tw),
                                   "coarse_block": blk,
                                   "diverged_blocks": diverged_blocks}}
    parity = {
        "max_offset_diff": round(float(np.abs(
            np.asarray(r_single.offsets)
            - np.asarray(r_mg.offsets)).max()), 9),
        "iters_single": int(r_single.n_iter),
        "iters_sharded": int(r_mg.n_iter),
    }

    # ---- solver trace cross-check on the traced sharded rung ------------
    trace_path = os.path.join(out_root, "solver.rank0.jsonl")
    try:
        os.unlink(trace_path)
    except OSError:
        pass
    solver_trace.record_solve(
        r_mg, band="multigrid-sharded", path=trace_path,
        precond_id=f"multigrid|L{L}", precision_id="tod=f32|cgdot=f32",
        threshold=threshold)
    trace_recs = [rec for rec in solver_trace.read_solver(trace_path)
                  if rec.get("kind") == "iteration"]
    trace_info = {"path": trace_path,
                  "iteration_records": len(trace_recs),
                  "reported_iters": int(r_mg.n_iter),
                  "match": len(trace_recs) == int(r_mg.n_iter)}

    # ---- banded noise weighting on a matched 1/f fixture ----------------
    # noise drawn from the SAME per-sample PSD the quality fit reports,
    # inverse-variance weights — the regime the prior's normalisation
    # balances against (w = 1/sigma^2, so b0/A_diag stays O(0.1))
    rng = np.random.default_rng(7)
    Tb = 8_000 if small else 40_000
    Lb, nxb = 10, 16
    npix_b = nxb * nxb
    pix_b = ((np.arange(Tb) * 7) % npix_b).astype(np.int64)
    sky = rng.normal(0, 1.0, npix_b).astype(np.float32)
    sigma, fknee, alpha, fs = 0.05, 1.0, -1.5, 50.0
    freqs = np.fft.rfftfreq(Tb, d=1.0 / fs)
    psd = np.zeros_like(freqs)
    psd[1:] = sigma ** 2 * (freqs[1:] / fknee) ** alpha
    amp = np.sqrt(psd * Tb * fs / 2.0) / np.sqrt(fs)
    ph = rng.normal(size=freqs.size) + 1j * rng.normal(size=freqs.size)
    corr = np.fft.irfft(amp * ph, n=Tb).astype(np.float32)
    tod_b = (sky[pix_b] + corr
             + sigma * rng.normal(size=Tb).astype(np.float32)
             ).astype(np.float32)
    w_b = np.full(Tb, 1.0 / sigma ** 2, np.float32)
    n_off_b = Tb // Lb

    groups = [{"file": "synthetic.h5", "feed": 0, "sample_rate": fs,
               "n_samples": Tb}]
    quality = [{"file": "synthetic.h5", "feed": 0, "band": 0,
                "white_sigma": sigma, "fknee_hz": fknee, "alpha": alpha,
                "flagged": False}]
    banded1, report = build_banded_weight(groups, quality, n_off_b, Lb,
                                          n_shards=1)
    plan_b = build_pointing_plan(pix_b, npix_b, Lb)
    solve_b = jax.jit(functools.partial(destripe_planned, plan=plan_b,
                                        n_iter=n_iter, threshold=1e-8))
    r_white = solve_b(jnp.asarray(tod_b), jnp.asarray(w_b))
    r_band = solve_b(jnp.asarray(tod_b), jnp.asarray(w_b),
                     banded=(jnp.asarray(banded1[0]),
                             jnp.asarray(banded1[1])))
    hit = np.asarray(r_white.hit_map) > 0

    def map_err(r):
        d = np.asarray(r.destriped_map)[hit] - sky[hit]
        d -= d.mean()
        return round(float(np.sqrt((d * d).mean())), 6)

    # sharded banded parity: the shard-aware prior through the sharded
    # program vs the same prior on one device (boundary couplings
    # zeroed identically in both)
    banded_s, _ = build_banded_weight(groups, quality, n_off_b, Lb,
                                      n_shards=n_shards)
    plans_b = build_sharded_plans(pix_b, npix_b, Lb, n_shards)
    run_banded = make_destripe_sharded_planned(
        mesh, plans_b, n_iter=n_iter, threshold=1e-8, with_banded=True)
    r_band_sh = run_banded(jnp.asarray(tod_b), jnp.asarray(w_b),
                           banded=banded_s)
    r_band_1 = solve_b(jnp.asarray(tod_b), jnp.asarray(w_b),
                       banded=(jnp.asarray(banded_s[0]),
                               jnp.asarray(banded_s[1])))
    banded_detail = {
        "fixture": {"T": Tb, "offset_length": Lb, "white_sigma": sigma,
                    "fknee_hz": fknee, "alpha": alpha,
                    "sample_rate": fs, "threshold": 1e-8},
        "white": {"iters": int(r_white.n_iter),
                  "map_rms_err": map_err(r_white)},
        "banded": {"iters": int(r_band.n_iter),
                   "map_rms_err": map_err(r_band),
                   "diverged": bool(np.any(np.asarray(r_band.diverged)))},
        "report": report,
        "sharded_parity_max_diff": round(float(np.abs(
            np.asarray(r_band_sh.offsets)
            - np.asarray(r_band_1.offsets)).max()), 9),
    }

    line = {
        "metric": "destriper_sharded_mg_iters_to_tol",
        "value": ladder["sharded_multigrid"]["iters_to_tol"],
        "unit": "iterations",
        # the acceptance ratio: sharded twolevel vs sharded multigrid
        # iterations (the 1.65x the fallback used to cost; None when
        # either burned its budget unconverged — never pretend)
        "vs_baseline": (round(ladder["sharded_twolevel"]["iters_to_tol"]
                              / ladder["sharded_multigrid"]
                                      ["iters_to_tol"], 3)
                        if ladder["sharded_multigrid"]["iters_to_tol"]
                        and ladder["sharded_twolevel"]["iters_to_tol"]
                        else None),
        "detail": {
            "config": "destriper-sharded",
            "n_shards": n_shards,
            "fixture": {"T": int(pix.size), "nx": nx,
                        "offset_length": L,
                        "n_offsets": pix.size // L,
                        "threshold": threshold, "pad": int(n_pad)},
            "ladder": ladder,
            "parity": parity,
            "solver_trace": trace_info,
            "banded": banded_detail,
            "device": str(devices[0].platform),
        },
    }
    print(json.dumps(line))
    if os.environ.get("BENCH_EVIDENCE", "1") != "0":
        ev_root = (os.environ.get("BENCH_EVIDENCE_DIR", "")
                   or os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(ev_root, "BENCH_r09.json"), "w") as f:
            json.dump(line, f, indent=1)
    write_evidence("destriper-sharded", lambda: None,
                   extra=line["detail"], host_only=True)
    return 0


def bench_kernels():
    """Kernels mode (ISSUE 11): XLA vs Pallas A/B for the two measured
    roofline floors — the fused masked-fill pre-filter and the
    scatter/gather binning matvec.

    Three measurements:

    - **fused fill**: the accounted pre-filter cost at the canonical
      round-7 shape (XLA cost model over the chain with the fill
      elided + ``masked_fill_logical_passes``) against the LIVE
      measured XLA floor (~34.3 passes field / ~37.0 calib), plus wall
      ms for both fill paths at a bench-sized shape;
    - **binning matvec**: ms/iter for ``destripe_planned`` under
      ``kernels=xla`` vs the kernel path on the weight-spread raster
      (multigrid — its fine smoother rides the same kernels), the
      accounted HBM bytes of one offset-scatter
      (``binning_logical_bytes``), and the cg_iters-unchanged
      cross-check: same fixture, same threshold, so a kernel that
      perturbs the math beyond f32 accumulation order shows up as a
      different iteration count;
    - **parity**: max |diff| of the fill outputs and of the converged
      offsets between the two paths.

    HONESTY CONTRACT off-TPU: the kernel rows run the Pallas
    INTERPRETER — a correctness A/B whose timings are interpreter
    overhead, not kernel speed — and ``detail.tpu_rows`` says so; the
    compiled-Mosaic numbers exist only on a TPU host, where
    ``kernel_impl`` flips to ``pallas``. ``BENCH_SMALL=1`` shrinks the
    fixtures (CI smoke). Unless ``BENCH_EVIDENCE=0`` the line is also
    written to ``BENCH_r07.json`` (the round-8 ROOFLINE artifact).
    """
    import functools

    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.mapmaking.destriper import (
        build_multigrid_hierarchy, destripe_planned)
    from comapreduce_tpu.mapmaking.pallas_binning import (
        binning_logical_bytes, resolve_kernels)
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.ops.pallas_median import masked_fill_logical_passes
    from comapreduce_tpu.ops.reduce import (ReduceConfig, _fill_bad,
                                            _prefilter_chain)

    small = os.environ.get("BENCH_SMALL", "") == "1"
    on_tpu = jax.default_backend() == "tpu"
    kern_impl = resolve_kernels("auto")          # pallas on TPU
    if kern_impl == "xla":
        kern_impl = "interpret"                  # correctness A/B off-TPU

    def timeit(fn, *a):
        r = jax.block_until_ready(fn(*a))        # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = jax.block_until_ready(fn(*a))
            best = min(best, time.perf_counter() - t0)
        return r, best

    # ---- fused fill: accounted passes at the canonical shape ------------
    Bc, Cc, Lc = 2, 64, 1024
    blockc = Bc * Cc * Lc * 4

    def passes(fn, shapes):
        from comapreduce_tpu.telemetry.programs import analyze

        args = [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
        cost = analyze(jax.jit(fn).lower(*args).compile())
        return cost.get("bytes_accessed", 0.0) / blockc

    fill_acct = float(masked_fill_logical_passes((Bc, Cc, Lc)))
    acct = {}
    for calib in (False, True):
        cfg = ReduceConfig(Cc, medfilt_window=101, is_calibrator=calib)
        shp = [(Bc, Cc, Lc), (Bc, Cc, Lc), (Lc,)]
        rest = passes(functools.partial(_prefilter_chain, cfg=cfg,
                                        fill_impl="none"), shp)
        xla_floor = passes(functools.partial(_prefilter_chain, cfg=cfg,
                                             fill_impl="xla"), shp)
        acct["calib" if calib else "field"] = {
            "xla_passes": round(xla_floor, 2),
            "fused_passes": round(rest + fill_acct, 2)}

    # ---- fused fill: wall + parity at a bench-sized shape ----------------
    B, C, L = (2, 16, 1024) if small else (4, 64, 8192)
    rng = np.random.default_rng(0)
    tod = jnp.asarray(rng.normal(size=(B, C, L)).astype(np.float32))
    mask = jnp.asarray((rng.random((B, C, L)) > 0.2).astype(np.float32))
    f_x, wall_x = timeit(jax.jit(functools.partial(_fill_bad, impl="xla")),
                         tod, mask)
    f_k, wall_k = timeit(jax.jit(functools.partial(_fill_bad,
                                                   impl=kern_impl)),
                         tod, mask)
    fill = {
        "shape": [B, C, L],
        "accounted": {**acct, "fill_kernel_passes": fill_acct},
        "xla_ms": round(1e3 * wall_x, 3),
        f"{kern_impl}_ms": round(1e3 * wall_k, 3),
        "parity_maxdiff": float(np.max(np.abs(
            np.nan_to_num(np.asarray(f_x), nan=-1.25)
            - np.nan_to_num(np.asarray(f_k), nan=-1.25)))),
    }

    # ---- binning matvec: destripe A/B + accounted bytes ------------------
    T = 12_000 if small else 60_000
    pix, btod, bw, npix, L2 = weight_spread_raster(T=T, nx=32 if small
                                                   else 64, L=50)
    plan = build_pointing_plan(pix, npix, L2)
    mg = build_multigrid_hierarchy(pix, bw, npix, L2, block=8, levels=2)
    tod_j, w_j = jnp.asarray(btod), jnp.asarray(bw)

    def solve(kern):
        fn = jax.jit(functools.partial(destripe_planned, plan=plan,
                                       n_iter=400, threshold=1e-6,
                                       mg=mg, kernels=kern))
        return timeit(fn, tod_j, w_j)

    r_x, bwall_x = solve("xla")
    r_k, bwall_k = solve(kern_impl)
    n_off = btod.size // L2
    bytes_off = binning_logical_bytes(
        rows=1, M=int(plan.pair_rank.shape[0]),
        window=int(plan.off_window), chunk=int(plan.pair_chunk),
        out_size=n_off)
    binning = {
        "fixture": {"T": int(btod.size), "n_offsets": n_off,
                    "pair_chunk": int(plan.pair_chunk),
                    "off_window": int(plan.off_window)},
        "cg_iters": {"xla": int(r_x.n_iter), kern_impl: int(r_k.n_iter)},
        "ms_per_iter": {
            "xla": round(1e3 * bwall_x / max(int(r_x.n_iter), 1), 3),
            kern_impl: round(1e3 * bwall_k / max(int(r_k.n_iter), 1), 3)},
        "offset_scatter_bytes": bytes_off,
        "parity_offsets_maxdiff": float(np.max(np.abs(
            np.asarray(r_x.offsets) - np.asarray(r_k.offsets)))),
    }

    line = {
        "metric": "kernels_prefilter_accounted_passes",
        "value": acct["field"]["fused_passes"],
        "unit": "hbm_passes",
        # the roofline ratio: live-measured XLA floor over the fused
        # budget at the same canonical shape
        "vs_baseline": round(acct["field"]["xla_passes"]
                             / acct["field"]["fused_passes"], 3),
        "detail": {
            "config": "kernels",
            "device": str(jax.devices()[0].platform),
            "kernel_impl": kern_impl,
            "fill": fill,
            "binning": binning,
            "tpu_rows": None if on_tpu else (
                "deferred: compiled-Mosaic timings require a TPU host; "
                "the kernel rows above ran the Pallas INTERPRETER "
                "(correctness A/B only — interpreter wall time is NOT "
                "kernel speed)"),
        },
    }
    print(json.dumps(line))
    if os.environ.get("BENCH_EVIDENCE", "1") != "0":
        out_root = (os.environ.get("BENCH_EVIDENCE_DIR", "")
                    or os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(out_root, "BENCH_r07.json"), "w") as f:
            json.dump(line, f, indent=1)
    write_evidence("kernels", lambda: None, extra=line["detail"],
                   host_only=True)
    return 0


def bench_precision():
    """Precision mode (ISSUE 13): the precision-portfolio A/B.

    Three measurements, all counter/solver-measured (no estimates):

    - **H2D bytes**: the same synthetic Level-1 filelist streamed twice
      through ``level1_stream`` + ``prefetch_to_device`` — once at
      ``tod_dtype=f32``, once at ``bf16`` — with telemetry on, summing
      the ``ingest.h2d.bytes`` counter each way. The ratio is what the
      bus actually shipped (TOD halves; non-TOD payload arrays keep
      their width, so the ratio lands between 0.5 and the TOD fraction
      of the payload, gated at <= 0.55 by ``tools/check_perf.py``);
    - **CG iters-to-tol ladder**: ``destripe_planned`` on the shared
      weight-spread raster at a descending threshold ladder, ``cg_dot=
      f32`` vs ``compensated`` — per rung the iteration count, final
      residual, and whether the rung was reached. The *stall edge* (a
      rung f32 cannot reach that compensated dots do) is reported if it
      exists and reported ABSENT if both reach every rung: this fixture
      is measured either way, never assumed;
    - **bf16 parity**: the same solve with the TOD round-tripped
      through bf16 (storage narrowing only — the solve still runs f32,
      exactly the streaming contract), max |offset diff| reported
      against the bf16 eps 7.8e-3 context.

    ``BENCH_SMALL=1`` shrinks both fixtures (CI smoke). Unless
    ``BENCH_EVIDENCE=0`` the line is also written to ``BENCH_r08.json``
    (the round-9 ROOFLINE artifact).
    """
    import functools
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.data.synthetic import (SyntheticObsParams,
                                                generate_level1_file)
    from comapreduce_tpu.ingest import level1_stream, prefetch_to_device
    from comapreduce_tpu.mapmaking.destriper import destripe_planned
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.telemetry import TELEMETRY
    from comapreduce_tpu.telemetry.reader import read_events

    small = os.environ.get("BENCH_SMALL", "") == "1"

    # ---- H2D bytes A/B: counter-measured, same files both ways ----------
    n_files = 2 if small else 3
    shape = (dict(n_feeds=2, n_bands=2, n_channels=16, n_scans=2,
                  scan_samples=400, vane_samples=128) if small else
             dict(n_feeds=2, n_bands=4, n_channels=64, n_scans=2,
                  scan_samples=2000, vane_samples=256))
    tmp = tempfile.mkdtemp(prefix="bench_precision_")
    h2d = {}
    try:
        files = []
        for i in range(n_files):
            path = os.path.join(tmp, f"comap-{2000 + i:07d}-synth.hd5")
            generate_level1_file(path, SyntheticObsParams(
                obsid=2000 + i, seed=200 + i, **shape))
            files.append(path)
        for dtype in ("f32", "bf16"):
            tdir = os.path.join(tmp, f"telemetry_{dtype}")
            TELEMETRY.configure(tdir, rank=0, flush_s=0.1)
            try:
                def payloads():
                    # ship the whole decoded payload, the way run_tod's
                    # device path does — the A/B then includes the
                    # non-TOD arrays that do NOT narrow, so the ratio
                    # is the honest whole-payload number
                    for item in level1_stream(files, prefetch=1,
                                              tod_dtype=dtype):
                        item.result()
                        yield {k: item.payload[k]
                               for k in ("spectrometer/tod",
                                         "spectrometer/MJD")
                               if k in item.payload}
                for blk in prefetch_to_device(payloads(), size=2):
                    jax.block_until_ready(blk)
            finally:
                TELEMETRY.close()
            events, _ = read_events(
                os.path.join(tdir, "events.rank0.jsonl"))
            h2d[dtype] = int(sum(
                ev.get("value", 0) for ev in events
                if ev.get("kind") == "counter"
                and ev.get("name") == "ingest.h2d.bytes"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    h2d_ratio = h2d["bf16"] / max(h2d["f32"], 1)

    # ---- CG iters-to-tol ladder: f32 vs compensated dots ----------------
    T = 12_000 if small else 60_000
    pix, btod, bw, npix, L2 = weight_spread_raster(
        T=T, nx=32 if small else 64, L=50)
    plan = build_pointing_plan(pix, npix, L2)
    tod_j, w_j = jnp.asarray(btod), jnp.asarray(bw)
    # the cap must sit well above the fixture's iters-to-1e-7 so the
    # ladder probes convergence, not the cap (the nx=64 full fixture
    # needs ~3x the small one's iteration count)
    n_iter = 200 if small else 1800
    rungs = [1e-5, 1e-6, 1e-7, 1e-8]
    ladder = {}
    for mode in ("f32", "compensated"):
        rows = []
        for thr in rungs:
            fn = jax.jit(functools.partial(
                destripe_planned, plan=plan, n_iter=n_iter,
                threshold=thr, cg_dot=mode))
            r = jax.block_until_ready(fn(tod_j, w_j))
            rows.append({"threshold": thr, "n_iter": int(r.n_iter),
                         "residual": float(r.residual),
                         "reached": bool(float(r.residual) <= thr)})
        ladder[mode] = rows
    stall_edge = None
    for i, thr in enumerate(rungs):
        if (not ladder["f32"][i]["reached"]
                and ladder["compensated"][i]["reached"]):
            stall_edge = thr
            break

    # ---- bf16 storage parity on the same solve --------------------------
    tod_bf = jnp.asarray(btod).astype(jnp.bfloat16).astype(jnp.float32)
    base = functools.partial(destripe_planned, plan=plan, n_iter=n_iter,
                             threshold=1e-6)
    r_f = jax.block_until_ready(jax.jit(base)(tod_j, w_j))
    r_b = jax.block_until_ready(jax.jit(base)(tod_bf, w_j))
    parity = {
        "offsets_maxdiff": float(np.max(np.abs(
            np.asarray(r_f.offsets) - np.asarray(r_b.offsets)))),
        "offsets_scale": float(np.max(np.abs(np.asarray(r_f.offsets)))),
        "bf16_eps": 7.8125e-3,
        "n_iter": {"f32": int(r_f.n_iter), "bf16": int(r_b.n_iter)},
    }

    line = {
        "metric": "precision_h2d_bytes_ratio",
        "value": round(h2d_ratio, 4),
        "unit": "bf16_bytes/f32_bytes",
        # the headline saving: f32 bytes over bf16 bytes (2.0 would be
        # a pure-TOD payload; the MJD axis keeps its width)
        "vs_baseline": round(1.0 / max(h2d_ratio, 1e-9), 3),
        "detail": {
            "config": "precision",
            "device": str(jax.devices()[0].platform),
            "h2d_bytes": h2d,
            "h2d_files": n_files,
            "cg_ladder": ladder,
            "cg_fixture": {"T": int(btod.size), "L": int(L2),
                           "npix": int(npix), "n_iter_cap": n_iter},
            "stall_edge": stall_edge if stall_edge is not None else (
                "absent: no rung measured where f32 dots stalled while "
                "compensated converged on this fixture (documented-"
                "absent per the gate contract)"),
            "bf16_parity": parity,
        },
    }
    print(json.dumps(line))
    if os.environ.get("BENCH_EVIDENCE", "1") != "0":
        out_root = (os.environ.get("BENCH_EVIDENCE_DIR", "")
                    or os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(out_root, "BENCH_r08.json"), "w") as f:
            json.dump(line, f, indent=1)
    write_evidence("precision", lambda: None, extra=line["detail"],
                   host_only=True)
    return 0


def bench_synthetic():
    """Synthetic campaign mode (ISSUE 16): the scale drill as a
    benchmark config plus one transfer-function closure.

    Runs ``synthetic.loadgen.run_synthetic_drill`` — a generated
    ``synth://`` campaign through three real elastic reduce ranks, the
    map server, and the tile tier, with a mid-run SIGKILL/rejoin — and
    reports campaign files per hour of drill wall time. Every drill
    promise (exactly-once commits, healthz flip/recovery, fresh
    epochs, exact /metrics counters) raises on violation, so this
    config FAILING is the signal; the throughput number is the trend
    line. One ``synthetic.transfer.run_transfer`` campaign then closes
    the loop against the injected truth (``check_transfer``).

    ``BENCH_SMALL=1`` runs 48 files (the CI shape); the full shape is
    200. ``BENCH_SYNTH_FILES`` overrides either.
    """
    import shutil
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from comapreduce_tpu.synthetic.loadgen import run_synthetic_drill
    from comapreduce_tpu.synthetic.transfer import (check_transfer,
                                                    run_transfer)

    small = os.environ.get("BENCH_SMALL", "0") == "1"
    n_files = int(os.environ.get("BENCH_SYNTH_FILES",
                                 "48" if small else "200"))
    tmp = tempfile.mkdtemp(prefix="bench_synthetic_")
    try:
        evidence = run_synthetic_drill(os.path.join(tmp, "drill"),
                                       seed=0, n_files=n_files)
        artifact = run_transfer(os.path.join(tmp, "transfer"), seed=0)
        check_transfer(artifact)
        line = {
            "metric": "synthetic_files_per_hour",
            "value": round(3600.0 * n_files
                           / max(evidence["wall_s"], 1e-9), 1),
            "unit": "files/h",
            # contract-style: reaching here IS the pass (the drill and
            # the transfer gate both raise on any broken promise)
            "vs_baseline": 1.0,
            "detail": {
                "config": "synthetic",
                **evidence,
                "transfer": {
                    "map_gain": [b.get("map_gain")
                                 for b in artifact["bands"]],
                    "low_k_transfer": [
                        list(b.get("transfer", [])[:2])
                        for b in artifact["bands"]],
                    "quality": artifact.get("quality"),
                },
            },
        }
        print(json.dumps(line))
        write_evidence("synthetic", lambda: None, extra=line["detail"],
                       host_only=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def bench_tune():
    """The shape-bucket autotuner A/B (ISSUE 20): sweep cost, tuned-vs-
    default campaign throughput, and the warm-cache promise, on real
    jitted destriper programs.

    Three legs (``BENCH_r10.json``, the round-11 ROOFLINE artifact):

    - **cold sweep**: for two distinct (N, L) shape buckets, tune the
      ``plan`` group (pair_batch) and the ``solver`` group (mg_block x
      mg_smooth) by wall-timing the ACTUAL jitted ``destripe_planned``
      programs under successive halving. Every proposed combo passed the
      validity rules (``invalid_proposed`` must stay 0 — check_perf
      gates it);
    - **campaign A/B**: the same solves run default-config and
      tuned-config (winners consulted through the REAL plumbing:
      ``TUNING`` configured + ``build_pointing_plan(pair_batch=None)``
      + ``TUNING.winner("solver", ...)`` — the run_destriper consult).
      Tuned throughput must be >= default beyond the noise floor, BY
      CONSTRUCTION: a winner only replaces the default when it measured
      ``min_improvement`` faster;
    - **warm re-run**: a fresh Tuner against the same ``tuning.jsonl``
      re-tunes every bucket — zero new measurements, one cache hit per
      bucket (the memoisation promise, also gated).

    The amortization curve prices the sweep: cumulative campaign
    seconds for n runs, default vs sweep + tuned.

    ``BENCH_SMALL=1`` shrinks the fixtures (CI smoke / the check_perf
    child). The winners cache lives in a temp dir — the bench never
    writes ``tuning.jsonl`` into the repo.
    """
    import math
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from comapreduce_tpu.mapmaking.destriper import (
        build_multigrid_hierarchy, destripe_planned)
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.ops.reduce import device_hbm_bytes
    from comapreduce_tpu.tuning.cache import (TUNING, TuningConfig,
                                              TuningCache, tuning_path,
                                              _backend_identity)
    from comapreduce_tpu.tuning.space import (SpaceContext, plan_bucket,
                                              solver_bucket)
    from comapreduce_tpu.tuning.tuner import Tuner

    small = os.environ.get("BENCH_SMALL", "") == "1"
    n_iter, threshold = 60, 1e-6
    n_runs = 2 if small else 4        # timed campaign passes per leg
    fixtures = []
    for seed, T, nx, L in ((0, 8_000 if small else 60_000,
                            24 if small else 48, 50),
                           (1, 6_400 if small else 40_000,
                            16 if small else 32, 64)):
        pix, tod, w, npix, _ = weight_spread_raster(seed=seed, T=T,
                                                    nx=nx, L=L)
        fixtures.append({"pix": pix, "npix": npix, "L": int(L),
                         "N": int(pix.size),
                         "tod": jnp.asarray(tod), "w": jnp.asarray(w),
                         "w_np": w})

    platform, device_kind = _backend_identity()
    hbm = device_hbm_bytes()
    tmp = tempfile.mkdtemp(prefix="bench_tune_")
    cache = TuningCache(tuning_path(tmp))
    cfg = TuningConfig(enabled=True, max_candidates=6,
                       repeats=2 if small else 3)
    tuner = Tuner(cache, platform, device_kind,
                  max_candidates=cfg.max_candidates, repeats=cfg.repeats,
                  min_improvement=cfg.min_improvement)

    def solve_thunk(fx, pair_batch, mg_block, mg_smooth):
        """One jitted solve of this fixture under the given knobs; the
        returned thunk blocks (the wall time is the program's)."""
        plan = build_pointing_plan(fx["pix"], fx["npix"], fx["L"],
                                   pair_batch=int(pair_batch))
        hier = build_multigrid_hierarchy(fx["pix"], fx["w_np"],
                                         fx["npix"], fx["L"],
                                         block=int(mg_block), levels=2)
        fn = jax.jit(functools.partial(destripe_planned, plan=plan,
                                       n_iter=n_iter,
                                       threshold=threshold,
                                       mg_smooth=int(mg_smooth)))

        def thunk():
            jax.block_until_ready(fn(fx["tod"], fx["w"], mg=hier).offsets)

        return thunk

    # ---- cold sweep: 2 groups x 2 buckets, real programs ----------------
    t_sweep = time.perf_counter()
    winners: dict = {}
    for fx in fixtures:
        ctx = SpaceContext(F=1, B=1, C=1, T=fx["N"], S=1, L=fx["L"],
                           n_samples=fx["N"], offset_length=fx["L"],
                           platform=platform, hbm_bytes=hbm)
        rec_p = tuner.tune(
            "plan", plan_bucket(fx["N"], fx["L"]), ctx,
            lambda combo, fx=fx: solve_thunk(fx, combo["pair_batch"],
                                             8, 1),
            {"pair_batch": 1})
        rec_s = tuner.tune(
            "solver", solver_bucket(fx["L"]), ctx,
            lambda combo, fx=fx, rec_p=rec_p: solve_thunk(
                fx, rec_p["winner"]["pair_batch"], combo["mg_block"],
                combo["mg_smooth"]),
            {"mg_block": 8, "mg_smooth": 1})
        winners[f"L={fx['L']}|N={fx['N']}"] = {
            "plan": rec_p["winner"], "solver": rec_s["winner"]}
    sweep = {"wall_s": round(time.perf_counter() - t_sweep, 3),
             "measurements": tuner.measurements,
             "invalid_proposed": tuner.invalid_proposed,
             "pruned": tuner.pruned, "winners": winners}

    # ---- campaign A/B: default config vs tuned-consult plumbing ---------
    def campaign_leg() -> float:
        """One full campaign pass over both buckets through the REAL
        consult path: auto pair_batch (build_pointing_plan asks TUNING
        when enabled) + the destriper CLI's solver-winner consult."""
        fns = []
        for fx in fixtures:
            plan = build_pointing_plan(fx["pix"], fx["npix"], fx["L"],
                                       pair_batch=None)
            win = TUNING.winner("solver", solver_bucket(fx["L"])) or {}
            hier = build_multigrid_hierarchy(
                fx["pix"], fx["w_np"], fx["npix"], fx["L"],
                block=int(win.get("mg_block", 8)), levels=2)
            fns.append((jax.jit(functools.partial(
                destripe_planned, plan=plan, n_iter=n_iter,
                threshold=threshold,
                mg_smooth=int(win.get("mg_smooth", 1)))), fx, hier))
        for fn, fx, hier in fns:                  # absorb compiles
            jax.block_until_ready(fn(fx["tod"], fx["w"],
                                     mg=hier).offsets)
        t0 = time.perf_counter()
        for _ in range(n_runs):
            for fn, fx, hier in fns:
                jax.block_until_ready(fn(fx["tod"], fx["w"],
                                         mg=hier).offsets)
        return time.perf_counter() - t0

    total_samples = n_runs * sum(fx["N"] for fx in fixtures)
    TUNING.close()
    wall_default = campaign_leg()
    TUNING.configure(tmp, cfg)
    try:
        wall_tuned = campaign_leg()
        # ---- warm re-run: the memoisation promise -----------------------
        warm_cache = TuningCache(tuning_path(tmp))
        warm = Tuner(warm_cache, platform, device_kind,
                     max_candidates=cfg.max_candidates,
                     repeats=cfg.repeats)
        for fx in fixtures:
            ctx = SpaceContext(F=1, B=1, C=1, T=fx["N"], S=1,
                               L=fx["L"], n_samples=fx["N"],
                               offset_length=fx["L"],
                               platform=platform, hbm_bytes=hbm)
            warm.tune("plan", plan_bucket(fx["N"], fx["L"]), ctx,
                      lambda combo: (lambda: None), {"pair_batch": 1})
            warm.tune("solver", solver_bucket(fx["L"]), ctx,
                      lambda combo: (lambda: None),
                      {"mg_block": 8, "mg_smooth": 1})
    finally:
        TUNING.close()
    bucket_count = 2 * len(fixtures)

    saving = wall_default - wall_tuned
    amortization = {
        "sweep_wall_s": sweep["wall_s"],
        "per_campaign_saving_s": round(saving, 3),
        "campaigns_to_amortize": (math.ceil(sweep["wall_s"] / saving)
                                  if saving > 1e-9 else None),
        "curve": [{"campaigns": n,
                   "default_s": round(n * wall_default, 3),
                   "swept_s": round(sweep["wall_s"] + n * wall_tuned, 3)}
                  for n in (1, 2, 5, 10, 20, 50)],
    }
    line = {
        "metric": "tune_campaign_samples_per_s",
        "value": round(total_samples / max(wall_tuned, 1e-9), 1),
        "unit": "samples/s",
        "vs_baseline": round(wall_default / max(wall_tuned, 1e-9), 3),
        "detail": {
            "config": "tune",
            "fixtures": [{"N": fx["N"], "L": fx["L"]}
                         for fx in fixtures],
            "bucket_count": bucket_count,
            "sweep": sweep,
            "warm": {"measurements": warm.measurements,
                     "cache_hits": warm.cache_hits,
                     "buckets_hit": warm.cache_hits},
            "campaign": {
                "runs": n_runs, "total_samples": total_samples,
                "default": {"wall_s": round(wall_default, 3),
                            "samples_per_s": round(
                                total_samples
                                / max(wall_default, 1e-9), 1)},
                "tuned": {"wall_s": round(wall_tuned, 3),
                          "samples_per_s": round(
                              total_samples
                              / max(wall_tuned, 1e-9), 1)},
            },
            "amortization": amortization,
            "device": platform,
        },
    }
    print(json.dumps(line))
    if os.environ.get("BENCH_EVIDENCE", "1") != "0":
        out_root = (os.environ.get("BENCH_EVIDENCE_DIR", "")
                    or os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(out_root, "BENCH_r10.json"), "w") as f:
            json.dump(line, f, indent=1)
    write_evidence("tune", lambda: None, extra=line["detail"],
                   host_only=True)
    shutil.rmtree(tmp, ignore_errors=True)
    return 0


_CONFIGS = {"1": bench_config1, "2": bench_config2, "4": bench_config4,
            "ingest": bench_ingest, "resilience": bench_resilience,
            "campaign": bench_campaign, "destriper": bench_destriper,
            "destriper-sharded": bench_destriper_sharded,
            "serving": bench_serving, "kernels": bench_kernels,
            "precision": bench_precision, "synthetic": bench_synthetic,
            "tune": bench_tune}


if __name__ == "__main__":
    argv = sys.argv[1:]
    cfg = os.environ.get("BENCH_CONFIG", "")
    if len(argv) >= 2 and argv[0] == "--config":
        cfg = argv[1]
    # default (the driver's contract): configs 3+5, the flagship chain
    sys.exit(_CONFIGS.get(cfg, main)())
