"""Benchmark: full 19-feed CES observation -> Level-2 -> destriped map.

Times the flagship jitted program (``parallel/step.py``: vane calibration +
Level-1 -> Level-2 reduction + destriper CG) on one chip at production shape
(19 feeds x 4 bands x 1024 channels, BASELINE.md config 3/5), and prints ONE
JSON line::

    {"metric": "tod_samples_per_sec", "value": ..., "unit": "samples/s",
     "vs_baseline": ...}

``value`` counts raw Level-1 samples (F*B*C*T) reduced per second of device
time. ``vs_baseline`` is the ratio to the reference-equivalent throughput:
a measured single-core NumPy implementation of the same hot chain (atmosphere
fit, normalisation, rolling-median high-pass regression, gain solve, band
average — the per-scan loop of ``Level1Averaging.py:792-872``) scaled by the
reference's production scale of 16 MPI ranks (``scripts/general/pbs.script``).

Env knobs: ``BENCH_SCALE`` (float, default 1.0) scales the sample count;
``BENCH_SMALL=1`` runs a tiny config (CI smoke).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REFERENCE_RANKS = 16  # mpirun -n 16, scripts/general/pbs.script:27


def _sliding_median_sorted(x: np.ndarray, window: int) -> np.ndarray:
    """Sliding median via a maintained sorted window (bisect insort/remove).

    The same work class as the reference's C++ dual-heap ``Mediator``
    (``Tools/median_filter/Mediator.h``): O(T) inserts/deletes into an
    ordered structure, O(1) median reads. Python-level loop, C-speed
    memmoves — the honest single-process stand-in for the Cython-wrapped
    reference filter.
    """
    import bisect

    half = window // 2
    out = np.empty_like(x)
    win = sorted(x[:half + 1].tolist())  # window of i=0: x[0 : half+1]
    out[0] = win[len(win) // 2]
    for i in range(1, len(x)):
        hi = i + half
        if hi < len(x):
            bisect.insort(win, x[hi])
        lo = i - half - 1
        if lo >= 0:
            del win[bisect.bisect_left(win, x[lo])]
        out[i] = win[len(win) // 2]
    return out


def numpy_oracle_throughput(n_channels=1024, n_samples=2000, window=600,
                            n_bands=1) -> float:
    """Single-core NumPy samples/sec on the reduction hot chain.

    Small slice, extrapolated per-sample: the chain is linear in T per
    channel.
    """
    rng = np.random.default_rng(0)
    C, T, B = n_channels, n_samples, n_bands
    tod = rng.normal(1000.0, 1.0, size=(B, C, T))
    airmass = 1.2 + 0.01 * rng.normal(size=T)

    t0 = time.perf_counter()
    # atmosphere: per-channel [1, A] regression
    A = np.stack([np.ones(T), airmass])          # (2, T)
    G = A @ A.T
    coef = np.linalg.solve(G, A @ tod.reshape(B * C, T).T).T
    clean = tod - (coef[:, 0:1] + coef[:, 1:2] * airmass).reshape(B, C, T)
    # normalisation by auto-rms
    d = clean[..., 1::2][..., :T // 2 * 2 // 2] - clean[..., ::2][..., :T // 2]
    rms = np.sqrt(np.mean(d * d, axis=-1) / 2.0)
    clean = clean / np.maximum(rms[..., None], 1e-30)
    # rolling median of the band average (reference medfilt window ~ T/3)
    mean_tod = clean.mean(axis=1)                # (B, T)
    med = np.stack([_sliding_median_sorted(mean_tod[b], window)
                    for b in range(B)])
    # per-channel regression vs filter + gain solve + band average
    dm = med - med.mean(axis=-1, keepdims=True)
    smm = np.sum(dm * dm, axis=-1, keepdims=True)
    slope = (clean @ dm[..., None] / np.maximum(smm, 1e-30)[..., None])
    filtered = clean - slope * dm[:, None, :]
    p = np.ones(B * C)
    y = filtered.reshape(B * C, T)
    dg = (p @ y) / (p @ p)
    resid = y - p[:, None] * dg[None, :]
    w = 1.0 / np.maximum(rms.reshape(B * C, 1) ** 2, 1e-30)
    _ = (resid * w).reshape(B, C, T).sum(axis=1) / w.reshape(B, C, 1).sum(1)
    dt = time.perf_counter() - t0
    return (B * C * T) / dt


def device_inputs(F, B, C, T, scan_mask, vane_samples, npix, seed=7):
    """Generate the observation arrays ON DEVICE (jax.random inside jit).

    The production-shape TOD is ~GBs; generating on host and pushing it
    through the host->device link would dominate the benchmark setup (and
    the reference equally excludes data simulation from its runtime).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen(key):
        k = jax.random.split(key, 6)
        gain = 1e6 * (1.0 + 0.1 * jax.random.normal(k[0], (F, B, C)))
        tsys = 45.0 * (1.0 + 0.2 * jax.random.uniform(k[1], (F, B, C)))
        tod = gain[..., None] * tsys[..., None] * (
            1.0 + 0.01 * jax.random.normal(k[2], (F, B, C, T)))
        mask = jnp.broadcast_to(jnp.asarray(scan_mask), (F, B, C, T))
        tv = vane_samples
        vane_step = jnp.where(jnp.arange(tv) < tv // 2, 290.0, 0.0)
        vane_tod = gain[..., None] * (tsys[..., None] + vane_step) * (
            1.0 + 1e-3 * jax.random.normal(k[3], (F, B, C, tv)))
        airmass = jnp.full((F, T), 1.2, jnp.float32)
        sweep = (jnp.arange(T) * 7) % npix
        pixels = jnp.broadcast_to(sweep, (F, T)).astype(jnp.int32)
        freq = jnp.broadcast_to(jnp.linspace(-0.1, 0.1, C), (B, C))
        return dict(tod=tod.astype(jnp.float32), mask=mask,
                    vane_tod=vane_tod.astype(jnp.float32), airmass=airmass,
                    pixels=pixels, freq_scaled=freq.astype(jnp.float32))

    out = gen(jax.random.key(seed))
    jax.block_until_ready(out["tod"])
    return out


def main():
    import jax

    from comapreduce_tpu.parallel.mesh import local_mesh
    from comapreduce_tpu.parallel.step import ObservationStep

    small = os.environ.get("BENCH_SMALL", "") == "1"
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))

    if small:
        F, B, C, scan_samples, n_scans, window = 2, 2, 64, 1000, 2, 101
        npix, vane_samples = 64, 128
    else:
        F, B, C, n_scans, window = 19, 4, 1024, 2, 6001
        scan_samples = max(int(2000 * scale), 500)
        npix, vane_samples = 480 * 480, 256

    gap = 64
    edges, t = [], gap
    for _ in range(n_scans):
        edges.append((t, t + scan_samples))
        t += scan_samples + gap
    T = t
    edges = np.asarray(edges, dtype=np.int64)
    scan_mask = np.zeros(T, np.float32)
    for s, e in edges:
        scan_mask[s:e] = 1.0

    arrays = device_inputs(F, B, C, T, scan_mask, vane_samples, npix)
    n_raw = F * B * C * T

    mesh = local_mesh()
    step = ObservationStep(mesh, scan_edges=edges, n_samples=T, npix=npix,
                           offset_length=50, n_iter=50, n_channels=C,
                           medfilt_window=window)

    # warm-up: compile + first run
    level2, result = step(**arrays)
    jax.block_until_ready((level2["tod"], result.destriped_map))

    n_rep = 3
    best = float("inf")
    for _ in range(n_rep):
        t0 = time.perf_counter()
        level2, result = step(**arrays)
        jax.block_until_ready((level2["tod"], result.destriped_map))
        best = min(best, time.perf_counter() - t0)

    throughput = n_raw / best
    cg_iters_per_sec = float(result.n_iter) / best

    oracle = numpy_oracle_throughput(
        n_channels=min(C, 256), n_samples=1500,
        window=min(window, 301), n_bands=1)
    baseline = oracle * REFERENCE_RANKS
    line = {
        "metric": "tod_samples_per_sec",
        "value": round(throughput, 1),
        "unit": "samples/s",
        "vs_baseline": round(throughput / baseline, 2),
        "detail": {
            "shape": [F, B, C, T],
            "wall_s": round(best, 4),
            "cg_iters_per_sec": round(cg_iters_per_sec, 1),
            "numpy_1core_samples_per_sec": round(oracle, 1),
            "baseline_ranks": REFERENCE_RANKS,
            "device": str(jax.devices()[0].platform),
        },
    }
    print(json.dumps(line))


if __name__ == "__main__":
    sys.exit(main())
