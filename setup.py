"""Build hooks for the native astrometry library.

Role parity: the reference's ``setup.py:14-63`` builds its Cython/C++/F90
extensions at install time. Here the single native component
(``csrc/astrometry.cpp``, C ABI + ctypes — no pybind11 dependency) is
compiled best-effort into the package as ``astro/_astrometry.so`` and the
source is copied in as package data, so an installed (non-editable)
package can still rebuild on demand (``astro/native.py``). A missing
compiler is NOT an error: the NumPy astrometry oracle serves alone.
"""

import logging
import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "csrc", "astrometry.cpp")
PKG_ASTRO = os.path.join("comapreduce_tpu", "astro")

log = logging.getLogger(__name__)


def _stage_native() -> None:
    """Copy the C++ source into the package and try to compile it."""
    dst_src = os.path.join(HERE, PKG_ASTRO, "astrometry.cpp")
    if os.path.exists(SRC):
        shutil.copyfile(SRC, dst_src)
    so = os.path.join(HERE, PKG_ASTRO, "_astrometry.so")
    cc = shutil.which("g++") or shutil.which("c++")
    if cc is None or not os.path.exists(SRC):
        return
    try:
        subprocess.run([cc, "-O3", "-shared", "-fPIC", "-o", so, SRC],
                       check=True, capture_output=True, timeout=300)
    except (OSError, subprocess.SubprocessError) as exc:
        log.info("native astrometry build skipped: %s", exc)


class build_py_with_native(build_py):
    def run(self):
        _stage_native()
        super().run()


setup(cmdclass={"build_py": build_py_with_native})
