#!/usr/bin/env python
"""Merge a campaign's per-rank telemetry streams into operator views.

    python tools/campaign_report.py LOG_DIR [--trace out.json]
        [--prom out.prom] [--json] [--no-summary]
    python tools/campaign_report.py --selftest

Reads every ``events.rank*.jsonl`` under LOG_DIR (the run's
``[Global] log_dir`` — requires ``[telemetry] enabled = true``) and
writes:

- ``--trace`` (default ``LOG_DIR/trace.json``): Chrome trace-event
  JSON. Open in https://ui.perfetto.dev or ``chrome://tracing`` —
  ranks as processes (serving-lane streams, rank >= 1000, are named
  ``serving lane N`` rather than raw rank numbers), writer threads as
  tracks, counters as counter tracks, crash-truncated spans flagged.
- ``--prom``  (default ``LOG_DIR/metrics.prom``): a Prometheus
  textfile-exporter snapshot (point node_exporter's textfile
  collector at it).
- stdout: the terminal summary — per-stage p50/p95, read/compute and
  write/compute overlap fractions integrated from span intersections,
  per-rank load imbalance (``--json`` for machine-readable form).

``--quality`` folds the data-quality ledger (``quality.rank*.jsonl``,
docs/OPERATIONS.md §16) into the summary: flag counts per SLO rule and
the worst-N feeds by fitted 1/f knee frequency (``--worst N``,
default 5). Works even when telemetry was off — the quality ledger is
always written.

``--selftest`` builds a synthetic two-rank campaign (interleaved
streams, a torn trailing line, a span left open by a "SIGKILLed"
rank, skewed monotonic clocks), round-trips it through the full
merge/export path and validates the trace JSON — the CI smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def quality_summary(log_dir: str, worst_n: int = 5) -> dict:
    """Fold ``quality.rank*.jsonl`` into one summary dict: record and
    flag totals, flag counts per SLO rule, and the worst-N (file, feed,
    band) rows by fitted 1/f knee frequency."""
    from comapreduce_tpu.telemetry.quality import (flag_counts,
                                                   read_quality,
                                                   worst_feeds)

    records = read_quality(log_dir)
    return {
        "n_records": len(records),
        "n_flagged": sum(1 for r in records if r.get("flagged")),
        "n_files": len({r.get("file") for r in records}),
        "flag_counts": flag_counts(records),
        "worst_feeds": [
            {k: r.get(k) for k in ("file", "feed", "band", "fknee_hz",
                                   "white_sigma", "alpha", "tsys_k",
                                   "flags")}
            for r in worst_feeds(records, n=worst_n)],
    }


def format_quality(q: dict) -> str:
    lines = [f"quality: {q['n_records']} record(s) over "
             f"{q['n_files']} file(s), {q['n_flagged']} flagged"]
    for rule, n in sorted(q["flag_counts"].items()):
        lines.append(f"  flag {rule}: {n}")
    if q["worst_feeds"]:
        def g(v):  # absent signals are None fields, never errors
            return "-" if v is None else format(float(v), ".3g")

        lines.append(f"  worst {len(q['worst_feeds'])} by 1/f knee:")
        for r in q["worst_feeds"]:
            flags = ",".join(r.get("flags") or ()) or "-"
            lines.append(
                f"    {r['file']} feed {r['feed']} band {r['band']}: "
                f"fknee {g(r['fknee_hz'])} Hz  "
                f"sigma {g(r['white_sigma'])}  "
                f"alpha {g(r['alpha'])}  flags {flags}")
    return "\n".join(lines)


def run_report(log_dir: str, trace_path: str = "", prom_path: str = "",
               summary: bool = True, as_json: bool = False,
               quality: bool = False, worst_n: int = 5) -> int:
    from comapreduce_tpu.telemetry import merge_streams
    from comapreduce_tpu.telemetry.report import (format_summary,
                                                  summarize,
                                                  write_prom,
                                                  write_trace)

    qual = quality_summary(log_dir, worst_n) if quality else None
    merged = merge_streams(log_dir)
    if not (merged.spans or merged.counters or merged.gauges):
        # the quality ledger is written even with telemetry off, so
        # --quality still reports; without it this stays an error
        if qual is not None and qual["n_records"]:
            print(json.dumps({"quality": qual}) if as_json
                  else format_quality(qual))
            return 0
        print(f"no telemetry events under {log_dir} (is [telemetry] "
              f"enabled = true?)", file=sys.stderr)
        return 1
    trace_path = trace_path or os.path.join(log_dir, "trace.json")
    prom_path = prom_path or os.path.join(log_dir, "metrics.prom")
    write_trace(merged, trace_path)
    write_prom(merged, prom_path)
    if summary:
        s = summarize(merged)
        if as_json:
            blob = {"summary": s, "trace": trace_path,
                    "prom": prom_path}
            if qual is not None:
                blob["quality"] = qual
            print(json.dumps(blob))
        else:
            print(format_summary(s))
            if qual is not None:
                print(format_quality(qual))
            print(f"trace: {trace_path}\nprom:  {prom_path}")
    return 0


def _selftest() -> int:
    """Synthesise a 2-rank stream set and validate the full path."""
    from comapreduce_tpu.telemetry import TELEMETRY, merge_streams
    from comapreduce_tpu.telemetry.report import chrome_trace, summarize

    with tempfile.TemporaryDirectory() as tmp:
        # rank 0: a normal little campaign written through the real
        # registry (exercises the writer discipline end to end)
        TELEMETRY.configure(tmp, rank=0, flush_s=60.0)
        with TELEMETRY.span("ingest.compute", unit="obs1.hd5"):
            TELEMETRY.event_span("stage.fit", 0.02, unit="obs1.hd5")
        TELEMETRY.event_span("ingest.read", 0.01, unit="obs2.hd5")
        TELEMETRY.counter("scheduler.claimed", 2)
        TELEMETRY.gauge("ingest.queue_depth", 1)
        TELEMETRY.close()
        # rank 1: hand-written with a skewed mono clock, an open span
        # (the SIGKILL case) and a torn trailing line
        lines = [
            {"kind": "meta", "schema": 1, "rank": 1, "pid": 9,
             "host": "b", "wall0": 1000.0, "mono0": 500.0},
            {"kind": "span", "id": 1, "name": "ingest.compute",
             "mono": 501.0, "dur": 0.5, "tid": "MainThread"},
            {"kind": "begin", "id": 2, "name": "ingest.compute",
             "mono": 502.0, "tid": "MainThread"},
        ]
        p1 = os.path.join(tmp, "events.rank1.jsonl")
        with open(p1, "w") as f:
            for ev in lines:
                f.write(json.dumps(ev) + "\n")
            f.write('{"kind": "span", "id": 3, "na')  # torn tail
        merged = merge_streams(tmp)
        trace = chrome_trace(merged)
        blob = json.loads(json.dumps(trace))  # valid JSON round-trip
        evs = blob["traceEvents"]
        ok = (merged.ranks == [0, 1]
              and merged.dropped_lines == 1
              and any(s["truncated"] for s in merged.spans)
              and any(e.get("ph") == "X" and e["args"].get("truncated")
                      for e in evs)
              and any(e.get("ph") == "C" for e in evs)
              and all("ts" in e for e in evs if e.get("ph") != "M")
              and summarize(merged)["stages"])
        print(json.dumps({"selftest_ok": bool(ok),
                          "events": len(evs),
                          "dropped_lines": merged.dropped_lines}))
        return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log_dir", nargs="?", default="",
                    help="run log directory holding events.rank*.jsonl")
    ap.add_argument("--trace", default="", help="Chrome trace output "
                    "path (default LOG_DIR/trace.json)")
    ap.add_argument("--prom", default="", help=".prom snapshot path "
                    "(default LOG_DIR/metrics.prom)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    ap.add_argument("--no-summary", action="store_true")
    ap.add_argument("--quality", action="store_true",
                    help="fold quality.rank*.jsonl into the summary "
                    "(flag counts per rule, worst feeds by 1/f knee)")
    ap.add_argument("--worst", type=int, default=5,
                    help="rows in the --quality worst-feeds table")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic round-trip (the CI smoke)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.log_dir:
        ap.error("log_dir is required (or use --selftest)")
    return run_report(args.log_dir, args.trace, args.prom,
                      summary=not args.no_summary, as_json=args.json,
                      quality=args.quality, worst_n=args.worst)


if __name__ == "__main__":
    raise SystemExit(main())
