#!/usr/bin/env python
"""Operate the incremental map server (``comapreduce_tpu.serving``).

Subcommands::

    serve     run the long-lived server: tail a campaign's committed
              units, fold new files, publish versioned map epochs
    status    one-line health: current epoch, census size, staleness
    epochs    list every complete epoch with its CG/freshness metrics
    rollback  point the ``current`` read path at an older epoch

Examples::

    python tools/map_server.py serve --state-dir run/logs \\
        --epochs-dir run/epochs --crval 170.25 52.25 \\
        --cdelt 0.0166667 0.0166667 --shape 64 64 \\
        --medfilt-window 201 --idle-exit-s 600
    python tools/map_server.py status --epochs-dir run/epochs
    python tools/map_server.py rollback --epochs-dir run/epochs 4

``status``/``epochs``/``rollback`` import no jax and return instantly;
``serve`` owns the epochs root exclusively (one server per root — the
admission ledger is single-writer).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _add_epochs_dir(ap):
    ap.add_argument("--epochs-dir", required=True,
                    help="epochs root (ledger + epoch-NNNNNN dirs)")


def _fmt_age(seconds: float) -> str:
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def cmd_serve(args) -> int:
    from comapreduce_tpu.serving.server import MapServer
    from comapreduce_tpu.telemetry import TELEMETRY

    if args.telemetry:
        from comapreduce_tpu.telemetry import serving_lane_rank

        # the server shares the campaign's state dir, so its epoch
        # spans land next to the reducer ranks' streams and merge into
        # one timeline under tools/campaign_report.py; ranks >= 1000
        # are the serving lane, and each serving process (map server,
        # tile server, restarts of either) takes the next free stream
        # — two writers on one stream would interleave span ids
        rank = args.telemetry_rank
        if rank is None:
            rank = serving_lane_rank(args.state_dir)
        TELEMETRY.configure(args.state_dir, rank=rank)
    wcs = None
    if args.nside is None:
        if not (args.crval and args.cdelt and args.shape):
            print("serve: pass --nside or all of --crval/--cdelt/"
                  "--shape", file=sys.stderr)
            return 2
        from comapreduce_tpu.mapmaking.wcs import WCS

        wcs = WCS.from_field(tuple(args.crval), tuple(args.cdelt),
                             (int(args.shape[0]), int(args.shape[1])))
    mg = {"block": args.mg_block} if args.mg_block else None
    server = MapServer(
        args.state_dir, args.epochs_dir, wcs=wcs, nside=args.nside,
        band=args.band, level2_dir=args.level2_dir,
        level2_prefix=args.level2_prefix,
        offset_length=args.offset_length, n_iter=args.n_iter,
        threshold=args.threshold, precond=args.precond,
        coarse_block=args.coarse_block, mg=mg, galactic=args.galactic,
        medfilt_window=args.medfilt_window,
        use_calibration=not args.no_calibration,
        tod_variant=args.tod_variant, warm_start=not args.cold,
        checkpoint_every=args.checkpoint_every,
        min_new_files=args.min_new_files, poll_s=args.poll_s,
        tiles_root=args.tiles_dir)
    live = None
    if args.live_port is not None:
        # live observability sidecar over the campaign's state dir
        # (docs/OPERATIONS.md §16); stats_path points the serving
        # freshness gauges at the stats file THIS server maintains
        from comapreduce_tpu.telemetry.live import LiveServer

        live = LiveServer(args.state_dir, port=args.live_port,
                          stats_path=server.stats_path).start()
        print(f"live plane: http://{live.host}:{live.port}/metrics")
    published = server.serve(
        max_epochs=args.max_epochs, idle_exit_s=args.idle_exit_s,
        max_wall_s=args.max_wall_s)
    print(f"serve: published {published} epoch(s); stats at "
          f"{server.stats_path}")
    if live is not None:
        live.stop()
    return 0


def cmd_status(args) -> int:
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.serving.server import STATS_JSON

    store = EpochStore(args.epochs_dir)
    cur = store.current()
    if cur is None:
        print(f"{args.epochs_dir}: no epoch published yet")
        return 1
    man = store.manifest(cur) or {}
    stale = time.time() - float(man.get("t_publish_unix", 0.0))
    line = (f"current epoch-{cur:06d}: {man.get('n_files', '?')} files, "
            f"published {_fmt_age(stale)} ago")
    cg = man.get("cg") or {}
    if cg:
        line += (f", {cg.get('n_iter', '?')} CG iters "
                 f"({cg.get('x0', '?')} start)")
    if man.get("freshness_s") is not None:
        line += f", freshness {_fmt_age(float(man['freshness_s']))}"
    print(line)
    stats = os.path.join(args.epochs_dir, STATS_JSON)
    if args.json and os.path.exists(stats):
        with open(stats, encoding="utf-8") as f:
            print(json.dumps(json.load(f), indent=1, sort_keys=True))
    return 0


def cmd_epochs(args) -> int:
    from comapreduce_tpu.serving.epochs import EpochStore

    store = EpochStore(args.epochs_dir)
    cur = store.current()
    rows = store.list_epochs()
    if not rows:
        print(f"{args.epochs_dir}: no complete epochs")
        return 1
    for n in rows:
        man = store.manifest(n) or {}
        cg = man.get("cg") or {}
        mark = "*" if n == cur else " "
        print(f"{mark} epoch-{n:06d}  files={man.get('n_files', '?'):>4}"
              f"  new={man.get('n_new', '?'):>3}"
              f"  cg={cg.get('n_iter', '?'):>4}"
              f"  x0={cg.get('x0', '?')}"
              f"  t_solve={man.get('t_solve_s', 0.0):.1f}s")
    return 0


def cmd_rollback(args) -> int:
    from comapreduce_tpu.serving.epochs import EpochStore

    store = EpochStore(args.epochs_dir)
    was = store.current()
    store.rollback(args.epoch)
    print(f"current: epoch-{was:06d} -> epoch-{args.epoch:06d}"
          if was is not None else
          f"current: epoch-{args.epoch:06d}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="run the incremental map server")
    s.add_argument("--state-dir", required=True,
                   help="campaign lease/commit dir ([Global] log_dir)")
    _add_epochs_dir(s)
    s.add_argument("--crval", nargs=2, type=float)
    s.add_argument("--cdelt", nargs=2, type=float)
    s.add_argument("--shape", nargs=2, type=int)
    s.add_argument("--nside", type=int)
    s.add_argument("--band", type=int, default=0)
    s.add_argument("--level2-dir", default="",
                   help="map committed names to Level-2 checkpoints "
                   "(empty: the lease's file path is servable as-is)")
    s.add_argument("--level2-prefix", default="Level2")
    s.add_argument("--offset-length", type=int, default=50)
    s.add_argument("--n-iter", type=int, default=100)
    s.add_argument("--threshold", type=float, default=1e-6)
    s.add_argument("--precond", default="jacobi")
    s.add_argument("--coarse-block", type=int, default=0)
    s.add_argument("--mg-block", type=int, default=0)
    s.add_argument("--galactic", action="store_true")
    s.add_argument("--medfilt-window", type=int, default=400)
    s.add_argument("--no-calibration", action="store_true")
    s.add_argument("--tod-variant", default="auto")
    s.add_argument("--cold", action="store_true",
                   help="disable warm starts (every epoch solves cold)")
    s.add_argument("--checkpoint-every", type=int, default=0)
    s.add_argument("--min-new-files", type=int, default=1)
    s.add_argument("--poll-s", type=float, default=2.0)
    s.add_argument("--max-epochs", type=int, default=None)
    s.add_argument("--idle-exit-s", type=float, default=None,
                   help="exit after this long with nothing new "
                   "(default: run forever)")
    s.add_argument("--max-wall-s", type=float, default=None)
    s.add_argument("--telemetry", action="store_true",
                   help="emit serving.epoch spans into the campaign's "
                   "state dir (merge with tools/campaign_report.py)")
    s.add_argument("--telemetry-rank", type=int, default=None,
                   help="serving-lane telemetry rank (default: next "
                   "free stream >= 1000 in the state dir)")
    s.add_argument("--tiles-dir", default="",
                   help="also tile every published epoch into this "
                   "tiles root (the HTTP read tier's content store)")
    s.add_argument("--live-port", type=int, default=None,
                   help="serve the live observability plane (/metrics, "
                   "/healthz, /v1/campaign) on this port")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("status", help="current epoch + staleness")
    _add_epochs_dir(s)
    s.add_argument("--json", action="store_true",
                   help="also dump the full server stats JSON")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("epochs", help="list complete epochs")
    _add_epochs_dir(s)
    s.set_defaults(fn=cmd_epochs)

    s = sub.add_parser("rollback",
                       help="swap current back to an older epoch")
    _add_epochs_dir(s)
    s.add_argument("epoch", type=int)
    s.set_defaults(fn=cmd_rollback)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
