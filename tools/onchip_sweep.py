"""One-command on-chip measurement session for when the TPU relay is up.

Runs, in order, against the real chip:

1. ``bench.py`` (full production-shape benchmark, measured baseline) —
   the BENCH_r{N} evidence (also writes ``evidence/`` artifacts);
2. BASELINE.md configs 1/2/4 (``bench.py --config N``);
3. the on-chip pytest tier (``COMAP_ONCHIP=1 -m onchip``: real-Mosaic
   Pallas parity, on-device planned-vs-scatter destriper, fused step);
4. a ``COMAP_BIN_IMPL`` fori-vs-map A/B of the destriper's one-hot
   binning (fori has been the default since round 5; map is the
   retained reference path, where ``COMAP_BIN_BATCH`` applies),
   reusing the measured baseline so each point only pays TPU wall;
5. a joint multi-RHS vs per-band destriper timing at production pointing
   (the round-4 multi-RHS lever);
6. a shape-bucket autotuner session (``bench.py --config tune``,
   ISSUE 20): the cold sweep + tuned-vs-default campaign A/B + warm
   cache verification ON THE CHIP — the on-TPU winners (pair_batch,
   mg_block x mg_smooth, and the pallas-vs-xla kernel axis that only
   exists on TPU) land in the session log for the committed-evidence
   discussion.

Appends one JSON line per measurement to ``SWEEP_r05.jsonl`` (repo root)
so a wedge mid-session loses nothing. Never signals a child process (a
signal landing mid-remote-compile wedges the relay — see
.claude/skills/verify/SKILL.md).

Usage: ``python tools/onchip_sweep.py [--skip-bench]``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "SWEEP_r05.jsonl")


def log_line(obj: dict) -> None:
    obj["t"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(OUT, "a") as f:
        f.write(json.dumps(obj) + "\n")
    print(json.dumps(obj), flush=True)


def run_bench(env_extra: dict, label: str, argv=()) -> dict | None:
    env = dict(os.environ, **env_extra)
    proc = subprocess.run([sys.executable, "bench.py", *argv], cwd=REPO,
                          env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        log_line({"kind": "bench-failed", "label": label,
                  "rc": proc.returncode,
                  "err": proc.stderr.strip()[-400:]})
        return None
    line = _last_json(proc.stdout)
    if line is None:
        log_line({"kind": "bench-noparse", "label": label,
                  "out": proc.stdout.strip()[-400:]})
        return None
    log_line({"kind": "bench", "label": label, **line})
    return line


def _last_json(stdout: str) -> dict | None:
    """Last parseable JSON line of a child's stdout, or None — a stray
    warning line must not abort the whole sweep session."""
    for raw in reversed(stdout.strip().splitlines()):
        try:
            return json.loads(raw)
        except (json.JSONDecodeError, ValueError):
            continue
    return None


def main() -> int:
    skip_bench = "--skip-bench" in sys.argv
    baseline_s = os.environ.get("BENCH_BASELINE_S", "")

    first = None
    if not skip_bench:
        first = run_bench({}, "bench-default")
        if first is None:
            return 3
        baseline_s = str(first["detail"]["baseline_unit_s"])

    # BASELINE.md configs 1/2/4 (VERDICT r4 #7) — each writes its own
    # evidence artifact too
    for cfg in ("1", "2", "4"):
        run_bench({}, f"config-{cfg}", argv=("--config", cfg))

    # on-chip pytest tier (VERDICT r4 #3): Mosaic Pallas parity,
    # on-device planned-vs-scatter, fused SPMD step
    env = dict(os.environ, COMAP_ONCHIP="1")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_onchip.py",
         "-m", "onchip", "-q"], cwd=REPO, env=env,
        capture_output=True, text=True)
    log_line({"kind": "onchip-tier", "rc": proc.returncode,
              "tail": proc.stdout.strip()[-300:]})

    # binning impl A/B (fori is the default since round 5; map retained
    # as the reference path — COMAP_BIN_BATCH only applies under map)
    for impl in ("fori", "map"):
        run_bench({"COMAP_BIN_IMPL": impl,
                   **({"BENCH_BASELINE_S": baseline_s} if baseline_s
                      else {})},
                  f"bin-impl-{impl}")

    # two-level preconditioner A/B at production pointing: iterations
    # and wall to reach the 1e-6 spec (Jacobi expected to hit the cap)
    code_pre = r"""
import json, time, functools, os
import numpy as np, jax, jax.numpy as jnp
from bench import ces_pixels
from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
from comapreduce_tpu.mapmaking.destriper import (destripe_planned,
                                                 build_coarse_preconditioner)

small = os.environ.get("SWEEP_SMALL", "") == "1"
F, T, nx = (2, 4000, 32) if small else (19, 135704, 480)
L, n_iter = (25, 50) if small else (50, 400)
rng = np.random.default_rng(1)
pix = np.concatenate([ces_pixels(T, nx, nx, f, F) for f in range(F)])
n = (pix.size // L) * L
pix = pix[:n]
toff = np.cumsum(rng.normal(0, 0.3, n // L)).astype(np.float32)
tod = (rng.normal(0, 1, n).astype(np.float32) + np.repeat(toff, L))
w = np.ones(n, np.float32)
plan = build_pointing_plan(pix, nx * nx, L)
grp, aci = build_coarse_preconditioner(pix, w, nx * nx, L, block=8)
out = {}
for name, kw in (("jacobi", {}),
                 ("coarse", {"coarse": (grp, jnp.asarray(aci))})):
    fn = jax.jit(functools.partial(destripe_planned, plan=plan,
                                   n_iter=n_iter, threshold=1e-6))
    r = fn(jnp.asarray(tod), jnp.asarray(w), **kw)
    float(jnp.sum(r.destriped_map))          # warm + host fetch
    t0 = time.perf_counter()
    r = fn(jnp.asarray(tod), jnp.asarray(w), **kw)
    float(jnp.sum(r.destriped_map))
    out[name] = {"iters": int(r.n_iter),
                 "residual": float(r.residual),
                 "wall_s": round(time.perf_counter() - t0, 3)}
print(json.dumps(out))
"""
    proc = subprocess.run([sys.executable, "-c", code_pre], cwd=REPO,
                          capture_output=True, text=True)
    parsed = _last_json(proc.stdout) if proc.returncode == 0 else None
    if parsed is not None:
        log_line({"kind": "coarse-precond", **parsed})
    else:
        log_line({"kind": "coarse-precond-failed", "rc": proc.returncode,
                  "err": proc.stderr.strip()[-400:]})

    # multi-RHS destriper: 4 bands jointly vs serially on one pointing
    code = r"""
import json, time
import numpy as np, jax, jax.numpy as jnp
import functools
from bench import ces_pixels
from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
from comapreduce_tpu.mapmaking.destriper import destripe_planned

import os
small = os.environ.get("SWEEP_SMALL", "") == "1"   # CPU smoke of this code
F, B, T, nx = (2, 2, 4000, 32) if small else (19, 4, 135704, 480)
L, n_iter = (25, 20) if small else (50, 100)
pix = np.concatenate([ces_pixels(T, nx, nx, f, F) for f in range(F)])
n = (pix.size // L) * L
pix = pix[:n]
plan = build_pointing_plan(pix, nx * nx, L)
key = jax.random.key(3, impl="rbg")
tod = jax.random.normal(key, (B, n), jnp.float32)
w = jnp.ones((B, n), jnp.float32)
# one jitted fn serves both shapes (jit caches per input shape)
solve = jax.jit(functools.partial(destripe_planned, plan=plan,
                                  n_iter=n_iter, threshold=1e-8))

def timed(fn, *a):
    r = fn(*a); jax.block_until_ready(r.destriped_map)
    float(jnp.sum(r.destriped_map))  # force host fetch (tunnel quirk)
    t0 = time.perf_counter()
    r = fn(*a); jax.block_until_ready(r.destriped_map)
    float(jnp.sum(r.destriped_map))
    return time.perf_counter() - t0

tj = timed(solve, tod, w)
ts = sum(timed(solve, tod[b], w[b]) for b in range(B))
print(json.dumps({"joint_4band_s": round(tj, 3),
                  "serial_4band_s": round(ts, 3),
                  "speedup": round(ts / tj, 2)}))
"""
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True)
    parsed = _last_json(proc.stdout) if proc.returncode == 0 else None
    if parsed is not None:
        log_line({"kind": "multi-rhs", **parsed})
    else:
        log_line({"kind": "multi-rhs-failed", "rc": proc.returncode,
                  "err": proc.stderr.strip()[-400:]})

    # autotuner session (ISSUE 20): the sweep measures REAL on-chip
    # programs, so its winners (including the TPU-only pallas kernel
    # axis) are the production numbers; the bench asserts the warm
    # cache promise itself and its JSON line carries the amortization
    # curve — log_line preserves all of it
    tune = run_bench({"BENCH_EVIDENCE": "0"}, "tune",
                     argv=("--config", "tune"))
    if tune is not None:
        det = tune.get("detail") or {}
        log_line({"kind": "tune-winners",
                  "winners": (det.get("sweep") or {}).get("winners"),
                  "warm": det.get("warm"),
                  "amortization": det.get("amortization")})
    return 0


if __name__ == "__main__":
    sys.exit(main())
