#!/usr/bin/env python
"""Perf gate: quick-shape bench vs the last committed evidence.

Usage::

    python tools/check_perf.py [--update] [--reps N] [--tolerance F]
                               [--dispatch-only]

Runs ``bench.py`` at the quick CI shape (``BENCH_SMALL=1``, baseline
measurement skipped — this gate compares the framework against ITSELF,
never against the reference) and compares the result to the committed
reference ``evidence/perf_quick_<platform>.json``:

- ``tod_samples_per_sec`` more than ``--tolerance`` (default 15%) below
  the reference -> exit 1 (throughput regression);
- ``dispatch_count`` above the reference -> exit 1 (dispatch-
  amortisation regression: someone reintroduced per-feed / per-band
  Python-loop dispatch — the ISSUE 4 fused-execution contract).

The current run takes the MAX of ``--reps`` (default 2) repetitions:
like ``measure_baseline``'s minimum rule in reverse, ambient load can
only make this process slower, so the max is the defensible sample of
the tree's real speed. ``--update`` (re)writes the reference JSON —
commit it whenever a deliberate change moves the quick-shape numbers.
Wired next to ``tools/check_resilience.py`` in CI.

The throughput half assumes a SAME-CLASS host as the committed
reference (the key is platform only, not machine): on a slower box the
absolute samples/s comparison fails spuriously with zero code change —
run ``--update`` once on that host, or pass ``--dispatch-only`` to keep
the machine-independent halves of the gate and skip the throughput
check.

The campaign no-recompile gate (ISSUE 5) also runs by default: one
``bench.py --config campaign`` smoke (shape-jittered filelist, compile
warm-up, async writeback) must show steady-state backend compiles
``<= bucket_count`` — a recompile-per-file regression in the shape
canonicalisation or warm-up fails here. Machine-independent (it is a
count, not a throughput); ``--no-campaign`` skips it.

The telemetry gates (ISSUE 10) also run by default with the campaign
gate: the campaign bench runs with telemetry enabled and its merged
event stream must (a) export valid Chrome trace JSON, (b) recompute
the steady-state backend-compile count EXACTLY from ``jax.compile``
spans, and (c) reproduce the bench's own read/compute overlap fraction
within 0.05 — all machine-independent (one run cross-checked against
itself). A second campaign run with ``BENCH_TELEMETRY=0`` then gates
the enabled-vs-disabled steady wall within 3% (+0.25 s floor);
``--no-telemetry-overhead`` skips that A/B.

The fused-kernel gate (ISSUE 11) also runs by default: one ``bench.py
--config kernels`` smoke must show (a) the fused pre-filter's accounted
pass budget at the canonical (2, 64, 1024) shape at or under 28 passes
AND below the live-measured XLA floor (~34.3), (b) bit-level masked-fill
parity between the XLA and kernel paths, and (c) the destriper's CG
iteration count UNCHANGED under the kernel binning matvec — all
machine-independent (cost-model accounting and same-process parity
checks, never wall clocks). Off-TPU the kernel side runs the Pallas
interpreter; ``--no-kernels`` skips.

The serving warm-start gate (ISSUE 9) also runs by default: one
``bench.py --config serving`` smoke (incremental map server folding
three commit waves) must show the final WARM epoch converging in
strictly fewer CG iterations than a cold solve of the same census.
Machine-independent (an ordering of two iteration counts on one
deterministic fixture); ``--no-serving`` skips it.

The tile-tier gate (ISSUE 12) also runs by default, in-process (no
bench child — tiling is pure index math + file I/O): two synthetic
epochs differing on one tile are cut into a tiles root, and (a) a
reader refreshing via the delta must fetch strictly fewer tiles and
strictly fewer bytes than a full re-download (delta manifest smaller
than the full manifest too — refresh cost scales with the CHANGE, not
the field), and (b) a sparse HEALPix epoch's tile bytes must stay
under ``tile_budget_bytes``'s exact-payload + header-bound ceiling
with the tile count EQUAL to the ``PixelSpace``-derived sparse count
(empty sky must cost nothing). Both halves are byte/count comparisons
of one deterministic fixture against itself — machine-independent;
``--no-tiles`` skips.

The precision gate (ISSUE 13) also runs by default: one ``bench.py
--config precision`` smoke must show (a) the bf16 run's
``ingest.h2d.bytes`` counter at or under 0.55x the f32 run's on the
SAME filelist (the streaming policy actually halves what crosses the
bus — a counter ratio of one run against itself, never a wall clock),
(b) the CG iters-to-tol ladder ordered: every rung the f32 dots reach,
the compensated dots reach too (and the bench must report the stall
edge, measured-present or documented-absent), and (c) bf16 storage
parity: converged offsets within a bf16-eps-scaled envelope of the f32
stream. All machine-independent; ``--no-precision`` skips.

The quality-ledger gate (ISSUE 14) also runs by default, in-process
(no jax, no bench child): three drill fixtures are read through a
``nan_burst`` chaos loader and their quality records evaluated against
the default SLO table — the poisoned file must be the ONLY flagged one
(rule ``masked_high``, one alert per flagged record) and every clean
file must stay unflagged. Set/count comparisons of one deterministic
fixture against itself — machine-independent; ``--no-quality`` skips.

The program HBM gate (ISSUE 15) rides the destriper bench: every
compiled program the bench registers (``telemetry/programs.py``)
carries XLA's exact ``temp_bytes + output_bytes``, compared per
program x shape bucket x precision against the committed baseline
``evidence/programs_<platform>.json`` with 1.25x slack. Byte GROWTH on
a program both sides know fails; new/vanished programs are reported
informationally, never failures (a renamed rung must not page anyone).
Machine-independent — XLA buffer assignment is deterministic for a
fixed backend. ``--update`` (re)writes the baseline from the current
run; ``--no-programs`` skips the gate. The destriper section also
cross-checks the solver trace: the per-iteration records written to
``solver.rank0.jsonl`` must match the solve's reported iteration count
EXACTLY (both come from the same dispatch).

The sharded-solver gates (ISSUE 19) also run by default: one
``bench.py --config destriper-sharded`` child (forced multi-device CPU
mesh) must show (a) the NATIVE sharded multigrid program converging in
strictly fewer iterations than sharded twolevel and within 10% of the
single-device count on the same fixture (the rung that used to fall
back with a warning), with its per-iteration solver-trace records
matching the reported count exactly, and (b) measured-noise banded
weighting beating white on both iterations and map RMS on a matched
1/f fixture with sharded-vs-single offset parity under 1e-5. An
in-process builder check then pins EXACT white parity: a
white-noise-only scenario must yield no banded operand at all (kwarg
omitted -> byte-identical compiled program), every fallback ledgered
with its reason. All iteration/count/parity comparisons of
deterministic fixtures — machine-independent; the iteration rungs are
recorded to the run registry (``*_cg_iters`` — the series
``solver_report.py --registry`` deltas against). ``--no-sharded``
skips.

The transfer-function gate (ISSUE 16) also runs by default,
in-process: for each of ``--transfer-seeds`` seeds (default 3) a
synthetic calibrator campaign with a KNOWN injected sky is generated
in memory (``synth://``), pushed through the real reduce -> destripe
-> map chain, and the recovered map compared against the injected
truth. ``check_transfer`` gates the signal-carrying low-k transfer
bins, the map-domain regression gain, and the quality ledger's
recovery of the scenario's KNOWN noise parameters on a blind reference
file — all physics ratios of one deterministic campaign against its
own truth, machine-independent; ``--no-transfer`` skips.

The autotune gate (ISSUE 20) also runs by default: one ``bench.py
--config tune`` child (shape-bucket autotuner A/B on real jitted
destriper programs) must show (a) the tuned campaign leg's throughput
at or above the default leg's beyond a noise floor — true BY
CONSTRUCTION (a winner only replaces the default when it measured
``min_improvement`` faster), so a violation means the consult plumbing
applies something the sweep never picked; (b) the warm re-run
re-measuring NOTHING with one cache hit per shape bucket (the
``tuning.jsonl`` memoisation promise); and (c) ``invalid_proposed``
at 0 — the knob space's validity rules must filter every combo before
the tuner times it. All ratios/counts of one run against itself —
machine-independent; ``--no-tune`` skips.

Unless ``--no-registry``, the gate appends one ``perf_gate`` summary
record to ``evidence/runs.jsonl`` (``telemetry/registry.py``) so
``tools/campaign_watch.py trend`` can alert on a regression against
the trailing window.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # the tiles/quality gates run in-process


def run_quick_bench() -> dict:
    """One quick-shape bench child -> its parsed JSON result line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_BASELINE_S": "1",   # skip the reference measurement
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",     # no artifact churn from the gate
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py failed (rc={out.returncode}):\n"
                           f"{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "tod_samples_per_sec":
            return rec
    raise RuntimeError("no bench result line found in bench.py output")


def run_campaign_bench(telemetry: bool = True) -> dict:
    """One small-shape campaign bench child -> its parsed JSON line.
    ``telemetry=False`` is the overhead A/B's control run."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
        "BENCH_TELEMETRY": "1" if telemetry else "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "campaign"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config campaign failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "campaign_files_per_hour":
            return rec
    raise RuntimeError("no campaign result line in bench.py output")


def run_destriper_bench() -> dict:
    """One small-shape destriper bench child -> its parsed JSON line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "destriper"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config destriper failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "destriper_cg_iters_to_tol":
            return rec
    raise RuntimeError("no destriper result line in bench.py output")


def run_sharded_bench() -> dict | None:
    """One small-shape sharded-destriper bench child -> its parsed JSON
    line, or None when the host cannot present >= 2 devices (the bench
    exits 3 then — a single-accelerator box without a CPU fallback; the
    gate records the skip instead of failing a box that cannot run the
    program class)."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    # the bench forces a multi-device CPU mesh pre-jax-import when the
    # platform is CPU; pin CPU here so the gate's iteration ORDERING
    # stays machine-independent (counts, never wall clocks)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "destriper-sharded"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode == 3:
        return None
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config destriper-sharded failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "destriper_sharded_mg_iters_to_tol":
            return rec
    raise RuntimeError("no destriper-sharded result line in bench.py "
                       "output")


def banded_white_parity_check() -> dict:
    """In-process half of the banded gate (ISSUE 19): on a
    white-noise-only scenario — quality fits with no usable correlated
    power — ``build_banded_weight`` must return ``None`` with every
    fallback ledgered, so the solve omits the kwarg and runs the
    byte-identical white program (exact parity by construction, no
    tolerance). Pure numpy; no jax, no bench child."""
    from comapreduce_tpu.mapmaking.noise_weight import build_banded_weight

    groups = [{"file": "white_a.h5", "feed": 0, "sample_rate": 50.0,
               "n_samples": 1000},
              {"file": "white_b.h5", "feed": 1, "sample_rate": 50.0,
               "n_samples": 1000}]
    # one fit with the knee below the resolvable offset-rate band, one
    # file with no fit at all — the two ways a white-noise campaign
    # presents to the builder
    quality = [{"file": "white_a.h5", "feed": 0, "band": 0,
                "white_sigma": 0.05, "fknee_hz": 1e-6, "alpha": -1.5,
                "flagged": False}]
    banded, report = build_banded_weight(groups, quality, 200, 10,
                                         band=0)
    return {"banded_is_none": banded is None,
            "reasons": sorted(f["reason"]
                              for f in report["fallbacks"]),
            "report": report}


def run_kernels_bench() -> dict:
    """One small-shape kernels bench child -> its parsed JSON line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "kernels"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config kernels failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "kernels_prefilter_accounted_passes":
            return rec
    raise RuntimeError("no kernels result line in bench.py output")


#: ISSUE 11 pass budget for the fused pre-filter at the canonical
#: (2, 64, 1024) shape: measured 25.2 (field) / 26.9 (calib) accounted
#: passes vs the 34.3-pass XLA floor; the gate allows headroom to 28
#: before failing. Machine-independent — XLA cost model + the kernel's
#: logical-pass accounting, never a wall clock.
FUSED_FILL_PASS_BUDGET = 28.0


def run_serving_bench() -> dict:
    """One serving bench child -> its parsed JSON result line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "serving"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config serving failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "serving_freshness_s":
            return rec
    raise RuntimeError("no serving result line in bench.py output")


def run_tiles_gate() -> dict:
    """The ISSUE 12 tile-tier numbers, computed in-process on a
    deterministic synthetic fixture (no jax, no subprocess)."""
    import shutil
    import tempfile

    import numpy as np

    from comapreduce_tpu.mapmaking.fits_io import (write_fits_image,
                                                   write_healpix_map)
    from comapreduce_tpu.mapmaking.pixel_space import PixelSpace
    from comapreduce_tpu.tiles.tiler import (TileSet, tile_budget_bytes,
                                             tile_epoch)

    work = tempfile.mkdtemp(prefix="check_perf_tiles_")
    try:
        def publish(n, products, kind, **hp):
            d = os.path.join(work, kind, f"epoch-{n:06d}")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, "map_band0.fits")
            if kind == "wcs":
                write_fits_image(path, products,
                                 header={"CRVAL1": 170.25,
                                         "CDELT1": 1.0 / 60})
            else:
                write_healpix_map(path, products, hp["pixels"],
                                  hp["nside"])
            with open(os.path.join(d, "manifest.json"), "w") as f:
                json.dump({"schema": 1, "epoch": n,
                           "census": [f"f{i}" for i in range(n)],
                           "n_files": n,
                           "maps": ["map_band0.fits"]}, f)
            return d

        # -- WCS: two epochs differing on ONE 64px tile of a 256^2
        # field — the delta side of the gate
        rng = np.random.default_rng(12)
        base = {nm: rng.normal(size=(256, 256)).astype(np.float32)
                for nm in ("DESTRIPED", "WEIGHTS", "HITS")}
        ep1 = publish(1, base, "wcs")
        bumped = {k: v.copy() for k, v in base.items()}
        bumped["DESTRIPED"][:32, :32] += 1.0  # inside tile 0 only
        ep2 = publish(2, bumped, "wcs")
        root = os.path.join(work, "tiles-wcs")
        tile_epoch(ep1, root, tile_px=64)
        man2 = tile_epoch(ep2, root, tile_px=64)
        ts = TileSet(root)
        delta = ts.delta(2)
        wcs = {
            "n_tiles": int(man2["n_tiles"]),
            "total_bytes": int(man2["total_bytes"]),
            "delta_changed": int(delta["n_changed"]),
            "delta_removed": int(delta["n_removed"]),
            "delta_bytes": int(delta["changed_bytes"]),
            "full_manifest_bytes": os.path.getsize(ts.manifest_path(2)),
            "delta_manifest_bytes": os.path.getsize(ts.delta_path(2)),
        }

        # -- HEALPix: a sparse partial map — the byte-budget side
        nside = 64
        npix = 12 * nside * nside
        ring = np.sort(rng.choice(npix, 2000, replace=False))
        maps = {nm: rng.normal(size=ring.size).astype(np.float32)
                for nm in ("DESTRIPED", "NAIVE", "WEIGHTS", "HITS")}
        eph = publish(1, maps, "healpix", pixels=ring, nside=nside)
        manh = tile_epoch(eph, os.path.join(work, "tiles-hp"),
                          tile_nside=8)
        space = PixelSpace.from_pixels(ring, npix)
        budget, n_expected = tile_budget_bytes(space, 8,
                                               n_products=len(maps))
        hp = {
            "n_tiles": int(manh["n_tiles"]),
            "n_expected": int(n_expected),
            "total_bytes": int(manh["total_bytes"]),
            "budget_bytes": int(budget),
            "n_compact": int(space.n_compact),
        }
        return {"wcs": wcs, "healpix": hp}
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_transfer_gate(seeds) -> tuple[dict, list]:
    """The ISSUE 16 transfer-function closure, in-process: one
    end-to-end synthetic campaign per seed, each gated by
    ``check_transfer`` against the scenario's own injected truth."""
    import shutil
    import tempfile

    from comapreduce_tpu.synthetic.transfer import (check_transfer,
                                                    run_transfer)

    failures, per_seed = [], {}
    for seed in seeds:
        work = tempfile.mkdtemp(prefix=f"check_perf_transfer_s{seed}_")
        try:
            artifact = run_transfer(work, seed=seed)
            bands = artifact.get("bands") or []
            q = artifact.get("quality") or {}
            per_seed[str(seed)] = {
                "map_gain": [b.get("map_gain") for b in bands],
                "low_k_transfer": [list(b.get("transfer", [])[:2])
                                   for b in bands],
                "alpha_median": q.get("alpha_median"),
                "fknee_ratio": (
                    q["fknee_median"] / q["fknee_expected"]
                    if q.get("fknee_median") and q.get("fknee_expected")
                    else None),
            }
            check_transfer(artifact)
        except AssertionError as exc:
            failures.append(f"transfer (seed {seed}): {exc}")
        except Exception as exc:  # a broken stage, not a closure miss
            failures.append(f"transfer (seed {seed}): campaign raised "
                            f"{type(exc).__name__}: {exc}")
        finally:
            shutil.rmtree(work, ignore_errors=True)
    return per_seed, failures


def run_quality_gate() -> dict:
    """The ISSUE 14 data-quality gate, in-process on the chaos drill's
    own Level-2 fixtures (no jax, no subprocess): a ``nan_burst``-
    poisoned read must land in the quality ledger flagged
    ``masked_high`` with an SLO alert fired, while every clean file's
    records stay unflagged."""
    import shutil
    import tempfile

    from comapreduce_tpu.data.level import COMAPLevel2
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.drill import _write_level2
    from comapreduce_tpu.telemetry import quality as q

    work = tempfile.mkdtemp(prefix="check_perf_quality_")
    monkey = ChaosMonkey("nan_burst@0001", seed=7, burst_frac=0.1)
    try:
        files = []
        for i in range(3):
            path = os.path.join(work, f"Level2_comap-{i:04d}.hd5")
            _write_level2(path, seed=500 + i)
            files.append(path)
        loader = monkey.wrap_loader(lambda p: COMAPLevel2(filename=p))
        slo = q.SloConfig()  # defaults: only masked_high is armed
        state = os.path.join(work, "state")
        n_alerts = 0
        for path in files:
            records = q.assemble_quality_records(
                loader(path), path,
                precision_id="tod=float32|cgdot=plain")
            for rec in records:
                rec["flags"] = q.evaluate_record(rec, slo)
                rec["flagged"] = bool(rec["flags"])
            q.append_quality(q.quality_path(state, 0), records)
            n_alerts += q.emit_alerts(records)
        latest = q.read_quality(state)
        return {
            "n_files": len(files),
            "poisoned": os.path.basename(files[1]),
            "flagged": sorted(q.flagged_files(state)),
            "flag_counts": q.flag_counts(latest),
            "n_records": len(latest),
            "n_flagged_records": sum(1 for r in latest
                                     if r.get("flagged")),
            "n_alerts": n_alerts,
            "max_nonfinite_fraction": max(
                float(r.get("nonfinite_fraction") or 0.0)
                for r in latest),
            "masked_threshold": slo.max_masked_fraction,
        }
    finally:
        monkey.release()
        shutil.rmtree(work, ignore_errors=True)


def run_precision_bench() -> dict:
    """One small-shape precision bench child -> its parsed JSON line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "precision"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config precision failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "precision_h2d_bytes_ratio":
            return rec
    raise RuntimeError("no precision result line in bench.py output")


def run_tune_bench() -> dict:
    """One small-shape autotuner bench child -> its parsed JSON line."""
    env = dict(os.environ)
    env.update({
        "BENCH_SMALL": "1",
        "BENCH_NO_PROBE": env.get("BENCH_NO_PROBE", "1"),
        "BENCH_EVIDENCE": "0",
    })
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                          "--config", "tune"],
                         env=env, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py --config tune failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == "tune_campaign_samples_per_s":
            return rec
    raise RuntimeError("no tune result line in bench.py output")


#: ISSUE 20 noise floor for the tuned-vs-default campaign A/B: the
#: tuner only replaces a default when the candidate measured
#: ``min_improvement`` (5%) faster, so tuned throughput below
#: (1 - floor) x default means the CONSULT plumbing applied knobs the
#: sweep never picked. 10% absorbs run-to-run scheduler noise on the
#: quick shape — the gate is an ordering of one process's two legs,
#: never a committed-reference throughput.
TUNE_NOISE_FLOOR = 0.10


#: ISSUE 13 H2D ceiling: with ``tod_dtype=bf16`` the counter-measured
#: bytes must be at or under 0.55x the f32 run's — 0.5 is a pure-TOD
#: payload; the 0.05 headroom covers the non-TOD arrays (MJD etc.) that
#: keep their width. Machine-independent: a ratio of one process's
#: ``ingest.h2d.bytes`` counter against itself, never a wall clock.
H2D_BYTES_RATIO_MAX = 0.55

#: bf16 parity envelope multiplier: converged offsets from a
#: bf16-round-tripped stream must land within this many bf16 epsilons
#: (7.8e-3, scaled by the offset magnitude) of the f32 stream's.
BF16_PARITY_EPS_MULT = 4.0


#: compacted-path memory budget multiplier: the exact device footprint
#: of the four map products is 4 B x (3 n_bands + 1) x n_compact
#: (per-band destriped/naive/weight + shared hits); the gate allows 2x
#: for dtype/padding slack. Machine-independent — it is a byte count
#: against the run's own coverage, not a throughput.
MEM_SLACK = 2.0


def check_map_vector_bytes(section: dict, tag: str) -> str | None:
    """The ISSUE 6 memory gate: a compacted destriper's device
    map-vector bytes must stay O(n_compact)."""
    nb = int(section.get("n_bands", 1))
    budget = MEM_SLACK * 4 * (3 * nb + 1) * int(section["n_compact"])
    got = int(section["map_vector_bytes"])
    if got > budget:
        return (f"{tag}: device map-vector bytes {got} exceed "
                f"{MEM_SLACK:g}x the compacted budget {budget:.0f} "
                f"(= {MEM_SLACK:g} x 4 B x (3x{nb}+1) x "
                f"{section['n_compact']} hit pixels) — an npix-sized "
                "vector leaked back onto the device?")
    return None


def reference_path(platform: str) -> str:
    return os.path.join(REPO, "evidence", f"perf_quick_{platform}.json")


def programs_reference_path(platform: str) -> str:
    # anchored to reference_path so a test/env redirect of the quick
    # reference moves BOTH baselines together — --update must never
    # write the repo's committed HBM baseline from a redirected run
    return os.path.join(os.path.dirname(reference_path(platform)),
                        f"programs_{platform}.json")


def programs_baseline(records: list) -> dict:
    """``{program key: temp+output HBM bytes}`` from bench program
    records — the committed shape of the HBM gate baseline."""
    from comapreduce_tpu.telemetry.programs import program_key

    out = {}
    for rec in records:
        hbm = ((rec.get("temp_bytes") or 0)
               + (rec.get("output_bytes") or 0))
        if hbm > 0:
            out[program_key(rec.get("name", ""),
                            rec.get("shape_bucket", ""),
                            rec.get("precision_id", ""),
                            rec.get("kernels", ""))] = int(hbm)
    return out


def write_programs_reference(platform: str, records: list,
                             git_rev: str = "") -> str:
    path = programs_reference_path(platform)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"schema": 1, "platform": platform,
                   "git_rev": git_rev,
                   "programs": programs_baseline(records)}, f, indent=1,
                  sort_keys=True)
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update", action="store_true",
                    help="write the current run as the new reference")
    ap.add_argument("--reps", type=int, default=2,
                    help="bench repetitions; the MAX samples/s is used")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="allowed fractional samples/s regression")
    ap.add_argument("--dispatch-only", action="store_true",
                    help="skip the throughput comparison (foreign host: "
                         "the committed reference is another machine's "
                         "samples/s); the dispatch_count and campaign "
                         "no-recompile gates still run")
    ap.add_argument("--no-campaign", action="store_true",
                    help="skip the campaign no-recompile gate")
    ap.add_argument("--no-telemetry-overhead", action="store_true",
                    help="skip the telemetry disabled-overhead A/B "
                         "(a second campaign bench run)")
    ap.add_argument("--no-destriper", action="store_true",
                    help="skip the destriper memory/iteration gate")
    ap.add_argument("--no-serving", action="store_true",
                    help="skip the serving warm-start gate")
    ap.add_argument("--no-kernels", action="store_true",
                    help="skip the fused-kernel pass-budget/parity gate")
    ap.add_argument("--no-tiles", action="store_true",
                    help="skip the tile-tier delta/byte-budget gate")
    ap.add_argument("--no-precision", action="store_true",
                    help="skip the precision H2D/CG-ladder/parity gate")
    ap.add_argument("--no-quality", action="store_true",
                    help="skip the quality-ledger nan_burst gate")
    ap.add_argument("--no-transfer", action="store_true",
                    help="skip the synthetic transfer-function gate")
    ap.add_argument("--transfer-seeds", type=int, default=3,
                    help="number of seeds for the transfer gate "
                         "(default 3)")
    ap.add_argument("--no-programs", action="store_true",
                    help="skip the compiled-program HBM gate (rides "
                         "the destriper bench; --no-destriper also "
                         "skips it)")
    ap.add_argument("--no-sharded", action="store_true",
                    help="skip the sharded-solver gates (sharded "
                         "multigrid iteration ordering + banded-weight "
                         "white parity)")
    ap.add_argument("--no-tune", action="store_true",
                    help="skip the shape-bucket autotuner gate "
                         "(tuned>=default A/B + warm-cache memoisation)")
    ap.add_argument("--no-registry", action="store_true",
                    help="do not append this gate run to the run "
                         "registry (evidence/runs.jsonl)")
    args = ap.parse_args(argv)

    best: dict | None = None
    for _ in range(max(args.reps, 1)):
        rec = run_quick_bench()
        if best is None or rec["value"] > best["value"]:
            best = rec
    platform = best["detail"].get("device", "cpu")
    cur = {
        "metric": best["metric"],
        "value": best["value"],
        "dispatch_count": best["detail"].get("dispatch_count"),
        "reduce_dispatches": best["detail"].get("reduce_dispatches"),
        "cg_iters_to_tol": best["detail"].get("cg_iters_to_tol"),
        "platform": platform,
        "shape": best["detail"].get("shape"),
    }

    path = reference_path(platform)
    if args.update:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            rev = subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                                 capture_output=True, text=True)
            cur["git_rev"] = rev.stdout.strip()
        except OSError:
            pass
        with open(path, "w") as f:
            json.dump(cur, f, indent=1)
        updated = [path]
        if not (args.no_programs or args.no_destriper):
            # the HBM baseline comes from the same quick destriper
            # bench the gate will run — commit both references together
            d = run_destriper_bench()["detail"]
            updated.append(write_programs_reference(
                platform, d.get("programs") or [],
                git_rev=cur.get("git_rev", "")))
        print(json.dumps({"ok": True, "updated": updated, **cur}))
        return 0

    if not os.path.exists(path):
        print(json.dumps({"ok": False,
                          "error": f"no committed reference {path}; run "
                                   "tools/check_perf.py --update first"}))
        return 2

    with open(path) as f:
        ref = json.load(f)
    failures = []
    floor = ref["value"] * (1.0 - args.tolerance)
    if not args.dispatch_only and cur["value"] < floor:
        failures.append(
            f"samples/s regression: {cur['value']:.3g} < "
            f"{floor:.3g} ({(1 - cur['value'] / ref['value']) * 100:.1f}% "
            f"below reference {ref['value']:.3g})")
    ref_disp = ref.get("dispatch_count")
    if ref_disp is not None and cur["dispatch_count"] is not None \
            and cur["dispatch_count"] > ref_disp:
        failures.append(
            f"dispatch_count increased: {cur['dispatch_count']} > "
            f"{ref_disp} (per-batch Python-loop dispatch reintroduced?)")

    campaign = None
    if not args.no_campaign:
        # the no-recompile gate is ABSOLUTE (a count against the
        # filelist's own bucket set, not a throughput vs a committed
        # reference), so it needs no --update baseline and holds on any
        # host class
        camp = run_campaign_bench()["detail"]
        campaign = {k: camp.get(k) for k in
                    ("bucket_count", "compiles_campaign_steady",
                     "compiles_baseline_steady", "cache_hits",
                     "cache_misses", "write_overlap_fraction")}
        if camp["compiles_campaign_steady"] > camp["bucket_count"]:
            failures.append(
                f"campaign steady-state recompiles: "
                f"{camp['compiles_campaign_steady']} backend compiles > "
                f"bucket count {camp['bucket_count']} (shape "
                f"canonicalisation or compile warm-up regressed?)")
        # the telemetry cross-check gate (ISSUE 10): both halves are
        # machine-independent — the span-recomputed compile count is an
        # exact equality against the CompileCounter on the SAME run,
        # and the overlap comparison is two measurements of one run's
        # own timeline (never a throughput vs a committed reference)
        tele = camp.get("telemetry") or {}
        campaign["telemetry"] = tele or None
        if tele:
            if not tele.get("trace_valid"):
                failures.append(
                    "telemetry: the campaign event stream did not "
                    "export valid Chrome trace JSON")
            if tele.get("steady_compile_spans") \
                    != camp["compiles_campaign_steady"]:
                failures.append(
                    f"telemetry compile-span mismatch: "
                    f"{tele.get('steady_compile_spans')} jax.compile "
                    f"span(s) in the steady window but the "
                    f"CompileCounter saw "
                    f"{camp['compiles_campaign_steady']} — span "
                    "emission and the monitoring hooks disagree")
            d_ov = abs(tele.get("overlap_read_compute", 0.0)
                       - tele.get("overlap_read_compute_bench", 0.0))
            if d_ov > 0.05:
                failures.append(
                    f"telemetry overlap drift: span-integrated "
                    f"read/compute overlap "
                    f"{tele.get('overlap_read_compute')} vs the "
                    f"bench's timings+wall estimate "
                    f"{tele.get('overlap_read_compute_bench')} "
                    f"(|diff| = {d_ov:.3f} > 0.05)")
        if tele and not args.no_telemetry_overhead:
            # enabled-vs-disabled wall A/B: telemetry ON must cost
            # under 3% steady wall (+0.25 s absolute floor so a tiny
            # quick-shape wall is not hostage to scheduler noise);
            # skipped when the bench ran without telemetry (canned or
            # BENCH_TELEMETRY=0 runs have no instrumented side to A/B)
            off = run_campaign_bench(telemetry=False)["detail"]
            on_wall = float(camp["steady_wall_s"])
            off_wall = float(off["steady_wall_s"])
            campaign["telemetry_overhead"] = {
                "enabled_wall_s": on_wall, "disabled_wall_s": off_wall}
            if on_wall > off_wall * 1.03 + 0.25:
                failures.append(
                    f"telemetry overhead: steady wall {on_wall:.3f} s "
                    f"enabled vs {off_wall:.3f} s disabled — more than "
                    "3% (+0.25 s floor); the hot path is doing real "
                    "work with telemetry on")
    destriper = None
    if not args.no_destriper:
        # both halves machine-independent: the memory gate is a byte
        # count against the run's own coverage (ISSUE 6 — an npix-sized
        # device vector on the compacted path fails absolutely), the
        # iteration gate an ordering of two counts on one fixture
        d = run_destriper_bench()["detail"]
        destriper = {
            "iters": {k: v.get("iters_to_tol")
                      for k, v in d["preconditioners"].items()},
            "compacted_bytes": d["compacted"]["map_vector_bytes"],
            "survey4096_bytes": d["survey4096"]["map_vector_bytes"],
            "survey4096_n_compact": d["survey4096"]["n_compact"],
        }
        for section, tag in ((d["compacted"], "compacted"),
                             (d["survey4096"], "survey4096")):
            bad = check_map_vector_bytes(section, tag)
            if bad:
                failures.append(bad)
        it = destriper["iters"]
        if it.get("multigrid") is None:
            failures.append("destriper: multigrid did not reach "
                            "tolerance within the iteration budget")
        elif it.get("twolevel") is not None \
                and it["multigrid"] >= it["twolevel"]:
            failures.append(
                f"destriper: multigrid iterations ({it['multigrid']}) "
                f"not below twolevel ({it['twolevel']}) — the V-cycle "
                "regressed to (or below) the additive two-level "
                "preconditioner")
        # solver-trace exactness (ISSUE 15): the per-iteration records
        # and the reported count come from the SAME traced dispatch —
        # any mismatch means the trace scatter or the host decode
        # broke. A detail with NO solver_trace key is a canned fixture
        # (the live bench always emits one): skip, don't fail.
        if "solver_trace" in d:
            trace = d.get("solver_trace") or {}
            destriper["solver_trace"] = {k: trace.get(k) for k in
                                         ("iteration_records",
                                          "reported_iters", "match")}
            if not trace.get("match"):
                failures.append(
                    f"destriper: solver trace wrote "
                    f"{trace.get('iteration_records')} iteration "
                    f"record(s) but the solve reported "
                    f"{trace.get('reported_iters')} CG iteration(s) — "
                    "the per-iteration trace no longer mirrors the "
                    "solve")
        else:
            destriper["solver_trace"] = {"skipped": "canned bench "
                                         "detail has no solver_trace"}
        if not args.no_programs:
            # the HBM gate (ISSUE 15): machine-independent byte counts
            # from XLA's buffer assignment vs the committed baseline;
            # growth on a shared key fails, new/vanished programs are
            # informational
            from comapreduce_tpu.telemetry.programs import (
                hbm_regressions, program_key)

            progs = d.get("programs") or []
            pref = programs_reference_path(platform)
            if os.path.exists(pref):
                with open(pref) as f:
                    base = (json.load(f) or {}).get("programs", {})
                cur_keys = {program_key(r.get("name", ""),
                                        r.get("shape_bucket", ""),
                                        r.get("precision_id", ""),
                                        r.get("kernels", ""))
                            for r in progs}
                hbm_fails = hbm_regressions(progs, base)
                failures.extend(hbm_fails)
                destriper["programs_gate"] = {
                    "checked": len(cur_keys & set(base)),
                    "regressions": len(hbm_fails),
                    "new_programs": sorted(cur_keys - set(base)),
                    "vanished_programs": sorted(set(base) - cur_keys),
                }
            else:
                destriper["programs_gate"] = {
                    "skipped": f"no committed baseline {pref}; run "
                               "tools/check_perf.py --update"}
    sharded = None
    if not args.no_sharded:
        # both halves machine-independent (ISSUE 19): iteration-count
        # orderings of solves on one deterministic fixture, and an
        # exact-parity-by-construction builder check — never wall clocks
        s = run_sharded_bench()
        if s is None:
            sharded = {"skipped": "host cannot present >= 2 devices"}
        else:
            d = s["detail"]
            ladder = d["ladder"]
            banded = d["banded"]
            sharded = {
                "n_shards": d.get("n_shards"),
                "iters": {k: v.get("iters_to_tol")
                          for k, v in ladder.items()},
                "parity_max_offset_diff":
                    d["parity"]["max_offset_diff"],
                "solver_trace": {k: (d.get("solver_trace") or {}).get(k)
                                 for k in ("iteration_records",
                                           "reported_iters", "match")},
                "banded": {"white_iters": banded["white"]["iters"],
                           "banded_iters": banded["banded"]["iters"],
                           "white_err": banded["white"]["map_rms_err"],
                           "banded_err": banded["banded"]["map_rms_err"],
                           "sharded_parity_max_diff":
                               banded["sharded_parity_max_diff"]},
            }
            it = sharded["iters"]
            if it.get("sharded_multigrid") is None:
                failures.append(
                    "sharded: the native sharded multigrid program did "
                    "not reach tolerance within the iteration budget — "
                    "the rung the fallback deletion promised is broken")
            else:
                if it.get("sharded_twolevel") is not None \
                        and it["sharded_multigrid"] \
                        >= it["sharded_twolevel"]:
                    failures.append(
                        f"sharded: multigrid iterations "
                        f"({it['sharded_multigrid']}) not strictly below "
                        f"sharded twolevel ({it['sharded_twolevel']}) — "
                        "the psum-threaded V-cycle stopped out-earning "
                        "the rung it replaced as the fallback")
                single = it.get("single_multigrid")
                if single and it["sharded_multigrid"] > 1.1 * single:
                    failures.append(
                        f"sharded: multigrid took "
                        f"{it['sharded_multigrid']} iterations sharded "
                        f"vs {single} on one device (> 10% — the "
                        "level-0 psum no longer assembles the same "
                        "coarse operator)")
            if not (d.get("solver_trace") or {}).get("match"):
                failures.append(
                    "sharded: the traced sharded solve's per-iteration "
                    "records do not match its reported count — the "
                    "psum'd trace dots broke under shard_map")
            b = sharded["banded"]
            if b["banded_iters"] >= b["white_iters"] \
                    or b["banded_err"] >= b["white_err"]:
                failures.append(
                    f"sharded: banded weighting on the matched 1/f "
                    f"fixture — {b['banded_iters']} iters / "
                    f"{b['banded_err']} map RMS vs white's "
                    f"{b['white_iters']} / {b['white_err']} — the "
                    "measured-noise prior stopped earning its band")
            if b["sharded_parity_max_diff"] > 1e-5:
                failures.append(
                    f"sharded: banded sharded-vs-single offset drift "
                    f"{b['sharded_parity_max_diff']:.3g} > 1e-5 — a "
                    "prior coupling crossed a shard boundary (the "
                    "no-halo zeroing contract broke)")
        # white-noise parity half: a campaign with no usable correlated
        # power must yield NO banded operand at all (kwarg omitted ->
        # byte-identical white program), with every fallback ledgered
        wp = banded_white_parity_check()
        sharded["white_parity"] = wp
        if not wp["banded_is_none"]:
            failures.append(
                "sharded: build_banded_weight returned a banded operand "
                "on a white-noise-only scenario — exact white parity by "
                "kwarg omission is broken")
        if wp["reasons"] != ["absent", "fknee_low"]:
            failures.append(
                f"sharded: white-noise fallbacks ledgered as "
                f"{wp['reasons']}, expected ['absent', 'fknee_low'] — "
                "the per-file fallback reasons drifted")

    serving = None
    if not args.no_serving:
        # machine-independent like the campaign gate: the warm epoch's
        # CG iteration count must order strictly below the cold solve
        # of the same census on the bench's deterministic 1/f fixture —
        # a warm-start regression (x0 dropped, offsets misaligned, sky
        # estimate broken) erases the ordering, not just the margin
        s = run_serving_bench()["detail"]
        serving = {k: s.get(k) for k in
                   ("warm_iters", "cold_iters", "cold_x0", "waves")}
        serving["final_x0"] = s["epochs"][-1]["x0"] if s.get("epochs") \
            else None
        if not serving["warm_iters"] or not serving["cold_iters"]:
            failures.append("serving: bench reported no CG iteration "
                            f"counts ({serving})")
        elif serving["final_x0"] in (None, "cold"):
            failures.append(
                "serving: the final epoch solved COLD "
                f"(x0={serving['final_x0']}) — warm start never "
                "engaged, so the iteration ordering is vacuous")
        elif serving["warm_iters"] >= serving["cold_iters"]:
            failures.append(
                f"serving warm-start regression: warm epoch took "
                f"{serving['warm_iters']} CG iterations, not below the "
                f"cold solve's {serving['cold_iters']} on the same "
                "census (epoch offsets/sky estimate no longer reused?)")
    kernels = None
    if not args.no_kernels:
        # every half machine-independent (ISSUE 11): the pass budget is
        # XLA's own cost model + logical-pass accounting, the parity
        # halves are max|diff| and an iteration-count equality of two
        # solves of one deterministic fixture in the same process
        k = run_kernels_bench()["detail"]
        impl = k["kernel_impl"]
        acct = k["fill"]["accounted"]
        kernels = {
            "kernel_impl": impl,
            "accounted": acct,
            "fill_parity_maxdiff": k["fill"]["parity_maxdiff"],
            "cg_iters": k["binning"]["cg_iters"],
            "offsets_parity_maxdiff":
                k["binning"]["parity_offsets_maxdiff"],
            "tpu_rows": k.get("tpu_rows"),
        }
        for kind in ("field", "calib"):
            fused = acct[kind]["fused_passes"]
            floor = acct[kind]["xla_passes"]
            budget = FUSED_FILL_PASS_BUDGET + (0.0 if kind == "field"
                                               else 2.0)
            if fused > budget or fused >= floor:
                failures.append(
                    f"kernels: fused pre-filter accounted passes "
                    f"({kind}) = {fused} — must stay <= {budget:g} and "
                    f"below the live XLA floor {floor} (the fused "
                    "masked-fill stopped paying for itself?)")
        if k["fill"]["parity_maxdiff"] > 1e-5:
            failures.append(
                f"kernels: masked-fill parity drift "
                f"{k['fill']['parity_maxdiff']:.3g} > 1e-5 between the "
                f"XLA fill and the {impl} kernel — exact fill/NaN "
                "semantics broke")
        it = kernels["cg_iters"]
        if it.get("xla") != it.get(impl):
            failures.append(
                f"kernels: cg_iters changed under kernels={impl}: "
                f"{it.get(impl)} vs xla's {it.get('xla')} on the same "
                "fixture — the binning kernel perturbs the solve "
                "beyond f32 accumulation order")
        if kernels["offsets_parity_maxdiff"] > 5e-3:
            failures.append(
                f"kernels: converged-offset drift "
                f"{kernels['offsets_parity_maxdiff']:.3g} > 5e-3 "
                f"between kernels=xla and kernels={impl}")
    tiles = None
    if not args.no_tiles:
        # machine-independent on both sides (ISSUE 12): byte and count
        # comparisons of one deterministic tile fixture against itself
        tiles = run_tiles_gate()
        w, hp = tiles["wcs"], tiles["healpix"]
        if not (w["delta_changed"] < w["n_tiles"]
                and w["delta_bytes"] < w["total_bytes"]):
            failures.append(
                f"tiles: a one-tile change produced a delta of "
                f"{w['delta_changed']}/{w['n_tiles']} tiles "
                f"({w['delta_bytes']}/{w['total_bytes']} bytes) — "
                "refresh cost no longer scales with the change (blob "
                "encoding picked up nondeterminism?)")
        if w["delta_manifest_bytes"] >= w["full_manifest_bytes"]:
            failures.append(
                f"tiles: the delta manifest ({w['delta_manifest_bytes']}"
                f" B) is not smaller than the full manifest "
                f"({w['full_manifest_bytes']} B) — incremental refresh "
                "pays the full index anyway")
        if hp["n_tiles"] != hp["n_expected"]:
            failures.append(
                f"tiles: {hp['n_tiles']} HEALPix tiles materialised but "
                f"the PixelSpace dictionary implies {hp['n_expected']} "
                "— empty sky is being tiled (or coverage dropped)")
        if hp["total_bytes"] > hp["budget_bytes"]:
            failures.append(
                f"tiles: sparse tile set costs {hp['total_bytes']} B > "
                f"the exact-payload + header budget "
                f"{hp['budget_bytes']} B for {hp['n_compact']} seen "
                "pixels — tile bytes stopped scaling with coverage")
    precision = None
    if not args.no_precision:
        # every half machine-independent (ISSUE 13): a bytes-counter
        # ratio of one run against itself, an ordering of iteration
        # counts on one deterministic fixture, and an eps-scaled
        # max|diff| of two solves in the same process
        p = run_precision_bench()
        det = p["detail"]
        par = det["bf16_parity"]
        precision = {
            "h2d_bytes": det["h2d_bytes"],
            "h2d_ratio": p["value"],
            "stall_edge": det.get("stall_edge"),
            "parity_offsets_maxdiff": par["offsets_maxdiff"],
            "cg_iters": {m: [r["n_iter"] for r in rows]
                         for m, rows in det["cg_ladder"].items()},
        }
        if p["value"] > H2D_BYTES_RATIO_MAX:
            failures.append(
                f"precision: bf16 H2D bytes ratio {p['value']:.3f} > "
                f"{H2D_BYTES_RATIO_MAX} of the f32 run "
                f"({det['h2d_bytes']}) — the streaming policy stopped "
                "narrowing what actually crosses the bus (a silent "
                "upcast before device_put?)")
        ladder = det["cg_ladder"]
        for i, f32_row in enumerate(ladder["f32"]):
            comp_row = ladder["compensated"][i]
            if f32_row["reached"] and not comp_row["reached"]:
                failures.append(
                    f"precision: compensated CG dots failed the "
                    f"{f32_row['threshold']:g} rung that plain f32 dots "
                    f"reach (residual {comp_row['residual']:.3g} after "
                    f"{comp_row['n_iter']} iters) — the two-sum "
                    "recurrences are hurting, not helping")
        if det.get("stall_edge") in (None, ""):
            failures.append(
                "precision: bench reported no stall_edge field — the "
                "ladder contract requires the f32 stall tolerance to be "
                "measured-present or documented-absent, never omitted")
        envelope = (BF16_PARITY_EPS_MULT * par["bf16_eps"]
                    * max(par["offsets_scale"], 1.0))
        if par["offsets_maxdiff"] > envelope:
            failures.append(
                f"precision: bf16-stream converged offsets drift "
                f"{par['offsets_maxdiff']:.3g} > the "
                f"{BF16_PARITY_EPS_MULT:g}x bf16-eps envelope "
                f"{envelope:.3g} — storage narrowing is leaking into "
                "the solve beyond representation error (an accumulator "
                "went bf16?)")
    quality = None
    if not args.no_quality:
        # machine-independent (ISSUE 14): set/count comparisons of one
        # deterministic chaos fixture's quality ledger against itself —
        # the nan_burst file must be the ONLY flagged one, every flag
        # must be the masked_high rule, and each flagged record must
        # have fired exactly one alert
        quality = run_quality_gate()
        if quality["flagged"] != [quality["poisoned"]]:
            failures.append(
                f"quality: flagged files {quality['flagged']} != "
                f"[{quality['poisoned']!r}] — the nan_burst file must "
                "be flagged and every clean file left alone (the SLO "
                "evaluation drifted or the burst went undetected)")
        counts = quality["flag_counts"]
        if set(counts) != {"masked_high"} or counts["masked_high"] < 1:
            failures.append(
                f"quality: flag counts {counts} — expected only "
                "masked_high firings from a NaN burst under the "
                "default SLO table")
        if quality["n_alerts"] != quality["n_flagged_records"]:
            failures.append(
                f"quality: {quality['n_alerts']} alert(s) fired for "
                f"{quality['n_flagged_records']} flagged record(s) — "
                "emit_alerts and the flags disagree")
        if quality["max_nonfinite_fraction"] \
                <= quality["masked_threshold"]:
            failures.append(
                f"quality: peak nonfinite fraction "
                f"{quality['max_nonfinite_fraction']:.3g} not above "
                f"the {quality['masked_threshold']:g} threshold — the "
                "fixture no longer exercises the rule")

    tune = None
    if not args.no_tune:
        # every half machine-independent (ISSUE 20): a throughput
        # ordering of one process's two legs (tuned vs default, where
        # tuned>=default holds by the tuner's min_improvement rule),
        # and exact counts of the warm re-run's measurements and cache
        # hits — never a committed-reference wall clock
        t = run_tune_bench()
        det = t["detail"]
        tune = {
            "vs_default": t["value"] and t.get("vs_baseline"),
            "bucket_count": det.get("bucket_count"),
            "sweep_measurements": (det.get("sweep") or {}).get(
                "measurements"),
            "invalid_proposed": (det.get("sweep") or {}).get(
                "invalid_proposed"),
            "warm": det.get("warm"),
            "winners": (det.get("sweep") or {}).get("winners"),
        }
        if "sweep" not in det:
            # a canned fixture (the live bench always emits the sweep
            # section): record the skip, don't fail
            tune = {"skipped": "canned bench detail has no sweep"}
        else:
            ratio = float(t.get("vs_baseline") or 0.0)
            if ratio < 1.0 - TUNE_NOISE_FLOOR:
                failures.append(
                    f"tune: tuned campaign leg at {ratio:.3f}x the "
                    f"default leg's throughput (< {1 - TUNE_NOISE_FLOOR:g}"
                    ") — the consult plumbing applies knobs the sweep "
                    "never picked as winners")
            warm = det.get("warm") or {}
            if int(warm.get("measurements") or 0) != 0:
                failures.append(
                    f"tune: warm re-run took "
                    f"{warm.get('measurements')} new measurement(s) — "
                    "the tuning.jsonl memoisation broke (key drift "
                    "between write and read?)")
            if int(warm.get("buckets_hit") or 0) \
                    != int(det.get("bucket_count") or -1):
                failures.append(
                    f"tune: warm re-run hit {warm.get('buckets_hit')} "
                    f"cache entr(ies) for {det.get('bucket_count')} "
                    "bucket(s) — a bucket re-swept or vanished")
            if int((det.get("sweep") or {}).get("invalid_proposed")
                   or 0) != 0:
                failures.append(
                    f"tune: the sweep proposed "
                    f"{det['sweep']['invalid_proposed']} invalid "
                    "combo(s) — the knob space's validity rules must "
                    "filter every candidate before it is timed")

    transfer = None
    if not args.no_transfer:
        # machine-independent (ISSUE 16): closure of the end-to-end
        # pipeline against a synthetic campaign's OWN injected truth —
        # physics ratios with ~2x headroom over the cross-seed scatter,
        # never a wall clock or a committed reference
        transfer, t_fails = run_transfer_gate(
            range(max(args.transfer_seeds, 1)))
        failures.extend(t_fails)

    if not args.no_registry:
        # one summary record per gate run (ISSUE 14): the registry is
        # what campaign_watch.py trend compares against, so the gate
        # feeds it even when it fails — ok:false is itself a signal
        from comapreduce_tpu.telemetry.registry import record_run

        metrics = {
            "tod_samples_per_s": cur["value"],
            "dispatch_count": cur["dispatch_count"] or 0,
            "gate_failures": len(failures),
        }
        if sharded and "iters" in sharded:
            # *cg_iters* keys feed solver_report.py --registry's
            # trailing-window deltas — the sharded rungs become part of
            # the same trend series campaign_watch alerts on
            it = sharded["iters"]
            metrics["sharded_mg_cg_iters"] = it.get(
                "sharded_multigrid") or 0
            metrics["sharded_twolevel_cg_iters"] = it.get(
                "sharded_twolevel") or 0
            metrics["banded_cg_iters"] = \
                sharded["banded"]["banded_iters"]
            metrics["banded_white_cg_iters"] = \
                sharded["banded"]["white_iters"]
        record_run("perf_gate", metrics, ok=not failures,
                   extra={"platform": platform})

    print(json.dumps({"ok": not failures, "failures": failures,
                      "current": cur, "campaign": campaign,
                      "destriper": destriper, "sharded": sharded,
                      "serving": serving,
                      "kernels": kernels, "tiles": tiles,
                      "precision": precision, "quality": quality,
                      "tune": tune, "transfer": transfer,
                      "reference": {k: ref.get(k) for k in
                                    ("value", "dispatch_count",
                                     "git_rev")}}))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
