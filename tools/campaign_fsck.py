#!/usr/bin/env python
"""Offline integrity audit + repair for a campaign run directory.

Usage::

    python tools/campaign_fsck.py RUN_DIR [--repair] [--json]
    python tools/campaign_fsck.py --selftest

Walks every durable artifact the campaign writes (the integrity plane,
docs/OPERATIONS.md §20) and verifies it with the SAME primitives the
online readers use (:mod:`comapreduce_tpu.resilience.integrity`):

- ``*.s256`` **sidecars** — payload hashed against the digest history;
  a sidecar with no payload is an orphan (crash between the two
  renames of a committed_replace), a payload hashing outside the
  history is corrupt.
- **JSONL ledgers** (``quarantine*.jsonl``, ``quality.rank*.jsonl``,
  any other ``*.jsonl``) — per-line embedded ``_sha256`` seals; torn
  trailing lines are tolerated (append-crash), seal failures are
  corruption.
- **Sealed JSON state** (``queue.json``, ``heartbeat.rank*.json``) —
  embedded seal on the whole document.
- **Epoch dirs** (``epoch-NNNNNN/``) — every product re-hashed against
  the epoch's ``integrity.json`` (:func:`serving.epochs.verify_epoch`).
- **Tile roots** (``objects/`` + ``manifests/``) — every CAS object
  re-hashed against its name; every sealed tile manifest cross-checked
  (referenced object missing = problem; unreferenced object = orphan,
  reported but not an error — ``sweep_unreferenced`` owns GC).
- **Torn stumps** — ``*.tmp*`` files and ``.tmp-epoch.*`` dirs left by
  a killed writer (informational; ``--repair`` removes them).

``--repair`` triages by artifact class: re-derivable state (Level-2
checkpoints, spill, solver snapshots, epochs, tiles, control JSON) is
unlinked so the next run rebuilds it; corrupt ledger lines are dropped
by an atomic rewrite; a corrupt epoch is demoted (CURRENT rolled back
to the newest clean epoch, the dir removed); anything NOT re-derivable
(kind ``level1`` or unknown) is moved to ``<run>/fsck-quarantine/``
with a ``.evidence.json`` recording the expected and actual digests.
Repair iterates until stable (unlinking a corrupt tile object exposes
a dangling manifest reference, which demotes that manifest on the next
pass).

Exit code: 0 when no corruption remains (orphans/stumps/unverified
artifacts alone never fail); 1 otherwise. ``--selftest`` builds a
throwaway run dir with one artifact of every class, bit-flips each,
and asserts detect → repair → clean (exit 0/1) — CI runs it next to
``check_resilience.py --integrity-only``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from comapreduce_tpu.resilience.integrity import (  # noqa: E402
    SEAL_KEY, SIDECAR_SUFFIX, check_json, check_line, read_sidecar,
    seal_json, seal_line, sha256_path)
from comapreduce_tpu.serving.epochs import (  # noqa: E402
    CURRENT_FILE, CURRENT_LINK, INTEGRITY, MANIFEST, epoch_name,
    parse_epoch_name, verify_epoch)
from comapreduce_tpu.tiles.store import OBJECTS_DIR  # noqa: E402

#: artifact kinds fsck may destroy and let the pipeline rebuild.  An
#: empty kind ("" — pre-plane sidecar or unknown writer) is treated as
#: re-derivable only when the payload lives inside the run dir the
#: campaign owns; ``level1`` (and any unrecognised kind) is evidence,
#: not rebuild fodder.
REBUILDABLE_KINDS = frozenset(
    {"checkpoint", "spill", "solver", "epoch", "tile", "json", ""})

QUARANTINE_DIR = "fsck-quarantine"

#: JSON documents verified (and repaired) whole-document
_SEALED_JSON = ("queue.json",)


def _is_heartbeat(name: str) -> bool:
    return name.startswith("heartbeat.rank") and name.endswith(".json")


def _problem(path, cls, problem, detail="", kind=""):
    return {"path": path, "class": cls, "kind": kind,
            "problem": problem, "detail": detail, "repaired": False}


def scan(run_dir: str) -> dict:
    """One full audit pass; returns the report dict (see --json)."""
    run_dir = os.path.abspath(run_dir)
    problems, stumps, orphans = [], [], []
    n_verified = n_unverified = 0
    tile_roots, epoch_dirs = [], []

    for dirpath, dirnames, filenames in os.walk(run_dir):
        # never audit our own quarantine (it holds known-bad bytes)
        dirnames[:] = [d for d in dirnames if d != QUARANTINE_DIR]
        for d in list(dirnames):
            if d.startswith(".tmp-epoch."):
                stumps.append(os.path.join(dirpath, d))
                dirnames.remove(d)
            elif parse_epoch_name(d) is not None:
                epoch_dirs.append(os.path.join(dirpath, d))
        if OBJECTS_DIR in dirnames and "manifests" in dirnames:
            tile_roots.append(dirpath)
            # the tile pass owns these two subtrees
            dirnames[:] = [d for d in dirnames
                           if d not in (OBJECTS_DIR, "manifests")]
        inside_epoch = parse_epoch_name(
            os.path.basename(dirpath)) is not None
        for name in filenames:
            path = os.path.join(dirpath, name)
            if ".tmp" in name and not name.endswith(SIDECAR_SUFFIX):
                stumps.append(path)
                continue
            if name.endswith(SIDECAR_SUFFIX):
                res = _check_sidecar(path)
                if res is None:
                    n_verified += 1
                else:
                    problems.append(res)
            elif name.endswith(".jsonl"):
                ok, res = _check_jsonl(path)
                n_verified += ok
                problems.extend(res)
            elif name in _SEALED_JSON or _is_heartbeat(name):
                res = _check_sealed_json(path)
                if res is None:
                    n_verified += 1
                elif res == "unverified":
                    n_unverified += 1
                else:
                    problems.append(res)
            elif inside_epoch or name in (MANIFEST, INTEGRITY):
                continue  # the epoch pass owns these
            elif not os.path.exists(path + SIDECAR_SUFFIX) \
                    and name not in (CURRENT_FILE, CURRENT_LINK):
                n_unverified += 1

    for ed in epoch_dirs:
        ok, probs = verify_epoch(ed)
        n_verified += ok
        if not probs and ok == 0:
            n_unverified += 1  # pre-plane epoch: no integrity.json
        for name, detail in probs:
            problems.append(_problem(os.path.join(ed, name), "epoch",
                                     "corrupt", detail, kind="epoch"))

    for tr in tile_roots:
        v, probs, orph = _check_tiles(tr)
        n_verified += v
        problems.extend(probs)
        orphans.extend(orph)

    corrupt = [p for p in problems if p["problem"] == "corrupt"]
    return {
        "run_dir": run_dir,
        "n_verified": n_verified,
        "n_unverified": n_unverified,
        "problems": problems,
        "n_corrupt": len(corrupt),
        "stumps": sorted(stumps),
        "orphan_objects": sorted(orphans),
        "ok": not problems,
    }


def _check_sidecar(scpath: str):
    payload = scpath[:-len(SIDECAR_SUFFIX)]
    sc = read_sidecar(payload)
    if not os.path.exists(payload):
        return _problem(scpath, "sidecar", "orphan-sidecar",
                        "sidecar with no payload (crash between the "
                        "sidecar and payload renames)")
    if sc is None:
        return _problem(scpath, "sidecar", "torn-sidecar",
                        "sidecar unreadable — payload unverifiable")
    actual = sha256_path(payload)
    if actual not in sc.get("digests", []):
        return _problem(payload, "sidecar", "corrupt",
                        f"sha256 {actual[:12]} not in committed "
                        f"history", kind=str(sc.get("kind", "")))
    return None


def _check_jsonl(path: str):
    problems = []
    n_ok = torn = 0
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        return 0, [_problem(path, "jsonl", "unreadable", str(exc))]
    for i, line in enumerate(raw.split(b"\n")):
        if not line.strip():
            continue
        try:
            text = line.decode("utf-8")
        except UnicodeDecodeError:
            torn += 1
            continue
        body, verdict = check_line(text)
        if body is None:
            if verdict is False and SEAL_KEY.encode() in line:
                problems.append(_problem(
                    path, "jsonl", "corrupt",
                    f"line {i + 1} fails its embedded seal",
                    kind="ledger-line"))
            else:
                torn += 1
        elif verdict:
            n_ok += 1
    if torn:
        problems.append(_problem(path, "jsonl", "torn-lines",
                                 f"{torn} unparseable line(s)"))
    return n_ok, problems


def _check_sealed_json(path: str):
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        return _problem(path, "json", "corrupt",
                        f"unparseable: {exc}", kind="json")
    if not isinstance(doc, dict):
        return _problem(path, "json", "corrupt", "not an object",
                        kind="json")
    _, verdict = check_json(doc)
    if verdict is False:
        return _problem(path, "json", "corrupt",
                        "document fails its embedded seal",
                        kind="json")
    return None if verdict else "unverified"


def _check_tiles(tiles_root: str):
    problems, orphans = [], []
    n_verified = 0
    objects = os.path.join(tiles_root, OBJECTS_DIR)
    on_disk = set()
    for sub, _, names in os.walk(objects):
        for name in names:
            path = os.path.join(sub, name)
            if ".tmp" in name:
                problems.append(_problem(path, "tile", "torn-stump",
                                         "torn object write"))
                continue
            try:
                actual = sha256_path(path)
            except OSError as exc:
                problems.append(_problem(path, "tile", "corrupt",
                                         f"unreadable: {exc}",
                                         kind="tile"))
                continue
            if actual != name:
                problems.append(_problem(
                    path, "tile", "corrupt",
                    f"content hashes to {actual[:12]}, named "
                    f"{name[:12]}", kind="tile"))
            else:
                on_disk.add(name)
                n_verified += 1
    referenced = set()
    mandir = os.path.join(tiles_root, "manifests")
    try:
        mannames = sorted(os.listdir(mandir))
    except OSError:
        mannames = []
    for name in mannames:
        if not name.endswith(".json") or ".tmp" in name:
            continue
        mpath = os.path.join(mandir, name)
        res = _check_sealed_json(mpath)
        if isinstance(res, dict):
            res["class"], res["kind"] = "tile-manifest", "tile"
            problems.append(res)
            continue
        n_verified += 1
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError):
            continue
        for key, entry in (man.get("tiles") or {}).items():
            digest = entry[0] if isinstance(entry, list) else None
            if not digest:
                continue
            referenced.add(digest)
            if digest not in on_disk and not man.get("prev"):
                # deltas reference only what changed; the FULL
                # manifest must resolve every tile
                problems.append(_problem(
                    mpath, "tile-manifest", "missing-object",
                    f"{key} references absent object "
                    f"{digest[:12]}", kind="tile"))
    orphans.extend(sorted(on_disk - referenced))
    return n_verified, problems, orphans


# -- repair ---------------------------------------------------------------


def repair(run_dir: str, report: dict) -> list:
    """One repair pass over ``report['problems']`` + stumps; returns
    human-readable action lines. Caller rescans afterwards."""
    actions = []

    def act(msg):
        actions.append(msg)

    for p in report["problems"]:
        path, prob, kind = p["path"], p["problem"], p["kind"]
        if prob in ("orphan-sidecar", "torn-sidecar"):
            _unlink(path)
            act(f"unlinked {prob}: {path}")
        elif prob == "torn-lines" or (prob == "corrupt"
                                      and p["class"] == "jsonl"):
            if _rewrite_jsonl(path):
                act(f"rewrote {path} without corrupt/torn lines")
        elif prob == "corrupt" and p["class"] == "epoch":
            ed = path if os.path.isdir(path) else os.path.dirname(path)
            _demote_epoch(ed)
            act(f"demoted corrupt epoch {ed} (CURRENT rolled back, "
                "dir removed — republish rebuilds it)")
        elif prob == "corrupt" and kind == "tile":
            _unlink(path)
            act(f"unlinked corrupt tile object {path} (re-tile "
                "re-puts it)")
        elif prob == "missing-object":
            _demote_tile_manifest(path)
            act(f"removed tile manifest {path} with dangling "
                "references (re-tile rebuilds it)")
        elif prob == "corrupt" and kind in REBUILDABLE_KINDS:
            _unlink(path)
            _unlink(path + SIDECAR_SUFFIX)
            act(f"unlinked corrupt {kind or 'artifact'}: {path} "
                "(re-derivable — the next run rebuilds it)")
        elif prob == "corrupt":
            dst = _quarantine(run_dir, path, p)
            act(f"quarantined NON-derivable corrupt artifact "
                f"{path} -> {dst} (evidence alongside)")
        elif prob == "unreadable":
            act(f"NOT repaired (unreadable, fix permissions): {path}")
    for s in report["stumps"]:
        if os.path.isdir(s):
            shutil.rmtree(s, ignore_errors=True)
        else:
            _unlink(s)
        act(f"removed torn stump {s}")
    return actions


def _unlink(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _rewrite_jsonl(path: str) -> bool:
    """Atomically rewrite ``path`` keeping only lines that parse and
    pass (or predate) their seal."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return False
    kept = []
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            body, verdict = check_line(line.decode("utf-8"))
        except UnicodeDecodeError:
            continue
        if body is not None and verdict is not False:
            kept.append(seal_line(body) if verdict else
                        json.dumps(body, separators=(",", ":"),
                                   default=str))
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write("".join(k + "\n" for k in kept))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return True


def _demote_epoch(epoch_dir: str) -> None:
    """Remove a corrupt epoch; if CURRENT pointed at it, roll back to
    the newest remaining clean epoch (or clear the pointer)."""
    root = os.path.dirname(epoch_dir)
    victim = os.path.basename(epoch_dir)
    shutil.rmtree(epoch_dir, ignore_errors=True)
    cur_path = os.path.join(root, CURRENT_FILE)
    try:
        with open(cur_path, "r", encoding="utf-8") as f:
            cur = f.read().strip()
    except OSError:
        cur = None
    if cur != victim:
        return
    clean = sorted((n for n in os.listdir(root)
                    if parse_epoch_name(n) is not None
                    and not verify_epoch(os.path.join(root, n))[1]),
                   key=lambda n: parse_epoch_name(n))
    link = os.path.join(root, CURRENT_LINK)
    if not clean:
        _unlink(cur_path)
        _unlink(link)
        return
    target = clean[-1]
    tmp = cur_path + f".tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(target + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, cur_path)
    try:
        ltmp = link + f".tmp{os.getpid()}"
        _unlink(ltmp)
        os.symlink(target, ltmp)
        os.replace(ltmp, link)
    except OSError:
        pass


def _demote_tile_manifest(mpath: str) -> None:
    """Remove a tile manifest (and its delta / CURRENT reference) so a
    re-tile rebuilds the epoch's tiles from the source FITS."""
    mandir = os.path.dirname(mpath)
    name = os.path.basename(mpath)
    _unlink(mpath)
    _unlink(os.path.join(mandir, "delta-" + name))
    root = os.path.dirname(mandir)
    cur_path = os.path.join(root, CURRENT_FILE)
    try:
        with open(cur_path, "r", encoding="utf-8") as f:
            cur = f.read().strip()
    except OSError:
        return
    if cur + ".json" != name:
        return
    remaining = sorted(n for n in os.listdir(mandir)
                       if n.endswith(".json")
                       and not n.startswith("delta-")
                       and parse_epoch_name(n[:-5]) is not None)
    if remaining:
        with open(cur_path, "w", encoding="utf-8") as f:
            f.write(remaining[-1][:-5] + "\n")
    else:
        _unlink(cur_path)


def _quarantine(run_dir: str, path: str, p: dict) -> str:
    qdir = os.path.join(run_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dst = os.path.join(qdir, os.path.basename(path))
    i = 0
    while os.path.exists(dst):
        i += 1
        dst = os.path.join(qdir, f"{os.path.basename(path)}.{i}")
    try:
        shutil.move(path, dst)
    except OSError:
        return path
    sc = read_sidecar(path)
    evidence = {"original_path": path, "kind": p["kind"],
                "detail": p["detail"],
                "actual_sha256": _safe_hash(dst),
                "committed_digests": (sc or {}).get("digests", [])}
    with open(dst + ".evidence.json", "w", encoding="utf-8") as f:
        json.dump(seal_json(evidence), f, indent=1, default=str)
    _unlink(path + SIDECAR_SUFFIX)
    return dst


def _safe_hash(path: str):
    try:
        return sha256_path(path)
    except OSError:
        return None


# -- selftest -------------------------------------------------------------


def selftest() -> int:
    """Build one artifact per class in a temp dir, bit-flip each,
    assert fsck detects all and --repair converges to clean."""
    import tempfile

    from comapreduce_tpu.resilience.chaos import flip_byte
    from comapreduce_tpu.resilience.integrity import (committed_replace,
                                                      write_sidecar)

    td = tempfile.mkdtemp(prefix="fsck-selftest-")
    try:
        # sidecar'd binary payload (stands in for checkpoint/spill/npz)
        ck = os.path.join(td, "fixture1_Level2.hd5")
        tmp = ck + ".tmp1"
        with open(tmp, "wb") as f:
            f.write(b"\x89HDF\r\n" + b"payload" * 64)
        committed_replace(tmp, ck, kind="checkpoint")

        # non-derivable payload -> quarantine path
        lv1 = os.path.join(td, "raw_input.h5")
        with open(lv1, "wb") as f:
            f.write(b"level1-bytes" * 32)
        write_sidecar(lv1, lv1, kind="level1")

        # sealed JSONL ledger
        led = os.path.join(td, "quarantine.jsonl")
        with open(led, "w", encoding="utf-8") as f:
            for i in range(3):
                f.write(seal_line({"i": i, "disposition": "ok"}) + "\n")

        # sealed whole-document JSON
        qj = os.path.join(td, "queue.json")
        with open(qj, "w", encoding="utf-8") as f:
            json.dump(seal_json({"schema": 1, "files": ["a", "b"]}), f)

        # epoch dir with integrity manifest
        ed = os.path.join(td, "epochs", epoch_name(1))
        os.makedirs(ed)
        fits = os.path.join(ed, "map_band0.fits")
        with open(fits, "wb") as f:
            f.write(b"SIMPLE  =                    T" + b"\x00" * 64)
        with open(os.path.join(ed, INTEGRITY), "w",
                  encoding="utf-8") as f:
            json.dump(seal_json({"schema": 1, "products": {
                "map_band0.fits": sha256_path(fits)}}), f)
        with open(os.path.join(ed, MANIFEST), "w",
                  encoding="utf-8") as f:
            json.dump(seal_json({"schema": 2, "epoch": 1,
                                 "maps": ["map_band0.fits"],
                                 "census": []}), f)
        with open(os.path.join(td, "epochs", CURRENT_FILE), "w",
                  encoding="utf-8") as f:
            f.write(epoch_name(1) + "\n")

        # tile root: one object + a sealed manifest referencing it
        troot = os.path.join(td, "tiles")
        blob = b"tile-blob-bytes" * 16
        import hashlib as _h
        digest = _h.sha256(blob).hexdigest()
        obj = os.path.join(troot, OBJECTS_DIR, digest[:2], digest)
        os.makedirs(os.path.dirname(obj))
        with open(obj, "wb") as f:
            f.write(blob)
        os.makedirs(os.path.join(troot, "manifests"))
        with open(os.path.join(troot, "manifests",
                               epoch_name(1) + ".json"), "w",
                  encoding="utf-8") as f:
            json.dump(seal_json({"schema": 1, "kind": "tiles",
                                 "epoch": 1,
                                 "tiles": {"b0/0": [digest,
                                                    len(blob), 16]}}),
                      f)

        rep = scan(td)
        if rep["problems"] or rep["n_verified"] < 6:
            print(f"selftest: clean scan not clean: {rep}")
            return 1

        victims = [ck, lv1, fits, obj]
        for v in victims:
            flip_byte(v, seed=7)
        # corrupt one ledger line + the sealed queue doc in place
        with open(led, "r+", encoding="utf-8") as f:
            lines = f.read().splitlines()
            lines[1] = lines[1].replace('"disposition":"ok"',
                                        '"disposition":"no"')
            f.seek(0)
            f.truncate()
            f.write("\n".join(lines) + "\n")
        with open(qj, "r+", encoding="utf-8") as f:
            doc = f.read().replace('"a"', '"z"')
            f.seek(0)
            f.truncate()
            f.write(doc)

        rep = scan(td)
        ncorrupt = sum(1 for p in rep["problems"]
                       if p["problem"] == "corrupt")
        if ncorrupt != 6:
            print("selftest: expected 6 corrupt artifacts, found "
                  f"{ncorrupt}:")
            for p in rep["problems"]:
                print(f"  {p['problem']:<14} {p['class']:<13} "
                      f"{p['path']}")
            return 1

        for _ in range(4):
            repair(td, rep)
            rep = scan(td)
            if rep["ok"]:
                break
        if not rep["ok"]:
            print(f"selftest: repair did not converge: "
                  f"{rep['problems']}")
            return 1
        qn = os.path.join(td, QUARANTINE_DIR, "raw_input.h5")
        if not os.path.exists(qn) or \
                not os.path.exists(qn + ".evidence.json"):
            print("selftest: level1 victim not quarantined with "
                  "evidence")
            return 1
        if os.path.exists(ck) or os.path.exists(obj) \
                or os.path.exists(ed):
            print("selftest: re-derivable victims not removed")
            return 1
        print("selftest: ok — 6/6 corruptions detected, repair "
              "converged, level1 quarantined with evidence")
        return 0
    finally:
        shutil.rmtree(td, ignore_errors=True)


# -- CLI ------------------------------------------------------------------


def render(rep: dict, actions: list) -> str:
    lines = [f"campaign fsck — {rep['run_dir']}",
             f"  verified {rep['n_verified']} artifact(s), "
             f"{rep['n_unverified']} unverified (pre-plane)"]
    for p in rep["problems"]:
        lines.append(f"  {p['problem'].upper():<14} "
                     f"[{p['kind'] or p['class']}] {p['path']}"
                     + (f" — {p['detail']}" if p["detail"] else ""))
    for s in rep["stumps"]:
        lines.append(f"  torn stump: {s}")
    if rep["orphan_objects"]:
        lines.append(f"  {len(rep['orphan_objects'])} unreferenced "
                     "tile object(s) (GC fodder, not corruption)")
    for a in actions:
        lines.append(f"  repair: {a}")
    lines.append("clean" if rep["ok"] else
                 f"{len(rep['problems'])} problem(s)"
                 f" ({rep['n_corrupt']} corrupt)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?", default=None,
                    help="the campaign run directory to audit")
    ap.add_argument("--repair", action="store_true",
                    help="triage per artifact class: unlink+rebuild "
                    "re-derivable state, quarantine-with-evidence "
                    "anything else")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    ap.add_argument("--selftest", action="store_true",
                    help="audit + repair a synthetic corrupted run "
                    "dir; exit 0 on full convergence")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.run_dir:
        ap.error("run_dir is required (or --selftest)")

    rep = scan(args.run_dir)
    actions = []
    if args.repair and not rep["ok"]:
        for _ in range(4):  # cascade: object unlink -> manifest demote
            actions.extend(repair(args.run_dir, rep))
            rep = scan(args.run_dir)
            if rep["ok"]:
                break
    print(json.dumps({**rep, "repair_actions": actions})
          if args.json else render(rep, actions))
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
