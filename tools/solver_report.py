#!/usr/bin/env python
"""Convergence diagnostics over the destriper's solver traces.

    python tools/solver_report.py LOG_DIR_OR_FILE [--json]
        [--registry PATH] [--window N]
    python tools/solver_report.py --selftest

Reads every ``solver.rank*.jsonl`` under the run's ``[Global]
log_dir`` (``telemetry/solver_trace.py`` — written whenever telemetry
is on) and renders, per (band, preconditioner id):

- iterations run / to tolerance, first and final residual, and the
  fitted convergence slope in decades per iteration (least squares
  over log10 residual — the number the live plane's ETA gauge
  extrapolates);
- stall windows (trailing ``STALL_WINDOW`` iterations flatter than
  ``STALL_SLOPE`` decades/iter on an unconverged solve) and divergence
  annotations (residual growth past 100x the best-so-far);
- per-preconditioner aggregation — iterations per rung, so a
  preconditioner that stopped earning its matvecs is one table away;
- with ``--registry`` (default ``evidence/runs.jsonl`` when present):
  the preconditioner-effectiveness delta of THIS run's iteration
  counts against the trailing run-registry window
  (``telemetry/registry.py`` — the same series ``campaign_watch.py
  trend`` alerts on).

``--selftest`` synthesises converging / stalling / diverged bands plus
a torn trailing line, round-trips them through the real append/read
path and validates every diagnostic — the CI smoke.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _slope(iters: list, residuals: list) -> float | None:
    """Least-squares slope of log10(residual) vs iteration (decades per
    iteration; negative = converging). None with < 2 usable points."""
    pts = [(float(i), math.log10(r)) for i, r in zip(iters, residuals)
           if r and r > 0.0]
    if len(pts) < 2:
        return None
    n = len(pts)
    sx = sum(p[0] for p in pts)
    sy = sum(p[1] for p in pts)
    sxx = sum(p[0] * p[0] for p in pts)
    sxy = sum(p[0] * p[1] for p in pts)
    denom = n * sxx - sx * sx
    if denom == 0:
        return None
    return (n * sxy - sx * sy) / denom


def summarize_solver(records: list) -> dict:
    """Fold solver-trace records into the report structure: one entry
    per (band, precond_id) plus a per-preconditioner aggregation."""
    from comapreduce_tpu.telemetry.solver_trace import (STALL_SLOPE,
                                                       STALL_WINDOW)

    solves: dict = {}
    for rec in records:
        key = (str(rec.get("band", "")), str(rec.get("precond_id", "")))
        s = solves.setdefault(key, {"iterations": [], "summaries": []})
        if rec.get("kind") == "iteration":
            s["iterations"].append(rec)
        elif rec.get("kind") == "solve":
            s["summaries"].append(rec)

    bands, rungs = [], {}
    for (band, precond_id), s in sorted(solves.items()):
        its = sorted(s["iterations"], key=lambda r: r.get("iter", 0))
        residuals = [float(r.get("residual") or 0.0) for r in its]
        iter_nos = [int(r.get("iter", 0)) for r in its]
        summaries = s["summaries"]
        last = summaries[-1] if summaries else {}
        n_iter = (sum(int(x.get("n_iter") or 0) for x in summaries)
                  if summaries else len(its))
        converged = bool(last.get("converged"))
        diverging = sum(1 for r in its if r.get("diverging"))
        tail = min(len(its), STALL_WINDOW)
        tail_slope = (_slope(iter_nos[-tail:], residuals[-tail:])
                      if tail >= 2 else None)
        entry = {
            "band": band,
            "precond_id": precond_id,
            "precision_id": str(last.get("precision_id")
                                or (its[0].get("precision_id")
                                    if its else "")),
            "n_iter": int(n_iter),
            "n_solves": len(summaries),
            "threshold": float(last.get("threshold") or 0.0),
            "first_residual": residuals[0] if residuals else None,
            "final_residual": (float(last["residual"])
                               if last.get("residual") is not None
                               else (residuals[-1] if residuals
                                     else None)),
            "converged": converged,
            "diverged": bool(last.get("diverged")) or diverging > 0,
            "diverging_iters": diverging,
            "stalled": any(x.get("stalled") for x in summaries),
            "stalled_at": next((x.get("stalled_at") for x in summaries
                                if x.get("stalled")), None),
            "slope_decades_per_iter": _slope(iter_nos, residuals),
            "tail_slope_decades_per_iter": tail_slope,
            "tail_stalled": (not converged and tail_slope is not None
                             and tail_slope > -STALL_SLOPE),
        }
        bands.append(entry)
        rung = precond_id.split("|")[0] or "<unknown>"
        agg = rungs.setdefault(rung, {"bands": 0, "iters": 0,
                                      "converged": 0, "stalled": 0,
                                      "diverged": 0})
        agg["bands"] += 1
        agg["iters"] += entry["n_iter"]
        agg["converged"] += int(entry["converged"])
        agg["stalled"] += int(entry["stalled"] or entry["tail_stalled"])
        agg["diverged"] += int(entry["diverged"])
    return {"bands": bands, "preconditioners": rungs,
            "n_records": len(records)}


def registry_deltas(summary: dict, registry_path: str,
                    window: int = 5) -> dict:
    """This run's iteration counts vs the trailing run-registry window:
    the median of every ``*cg_iters*`` metric in the last ``window``
    records against the traced solves' mean iterations. A preconditioner
    suddenly needing 2x the registry's historical iterations shows up
    here before it shows up in wall clocks."""
    from comapreduce_tpu.telemetry.registry import read_runs

    runs = read_runs(registry_path)
    if not runs:
        return {}
    hist: dict = {}
    for run in runs[-window:]:
        for k, v in (run.get("metrics") or {}).items():
            if "cg_iters" in k and isinstance(v, (int, float)):
                hist.setdefault(k, []).append(float(v))
    if not hist:
        return {}
    bands = summary.get("bands") or []
    cur = (sum(b["n_iter"] for b in bands) / len(bands)
           if bands else None)
    out = {"current_mean_iters": cur, "window": window, "metrics": {}}
    for k, vals in sorted(hist.items()):
        vals = sorted(vals)
        med = vals[len(vals) // 2]
        out["metrics"][k] = {
            "registry_median": med,
            "ratio": (round(cur / med, 3)
                      if cur is not None and med else None)}
    return out


def format_report(summary: dict, deltas: dict | None = None) -> str:
    def g(v, spec=".3g"):
        return "-" if v is None else format(float(v), spec)

    lines = [f"solver traces: {len(summary['bands'])} (band, "
             f"preconditioner) solve(s), {summary['n_records']} "
             "record(s)"]
    for b in summary["bands"]:
        state = ("CONVERGED" if b["converged"] else
                 "DIVERGED" if b["diverged"] else
                 "STALLED" if b["stalled"] or b["tail_stalled"] else
                 "unconverged")
        stall = (f" (stalled at iter {b['stalled_at']})"
                 if b["stalled_at"] is not None else "")
        lines.append(
            f"  {b['band']} [{b['precond_id']}]: {b['n_iter']} iters "
            f"-> residual {g(b['final_residual'])} "
            f"(threshold {g(b['threshold'])}) {state}{stall} | "
            f"slope {g(b['slope_decades_per_iter'])} dec/iter "
            f"(tail {g(b['tail_slope_decades_per_iter'])})")
    lines.append("per-preconditioner rungs:")
    for rung, agg in sorted(summary["preconditioners"].items()):
        lines.append(
            f"  {rung}: {agg['iters']} iters over {agg['bands']} "
            f"band-solve(s) | converged {agg['converged']} "
            f"stalled {agg['stalled']} diverged {agg['diverged']}")
    if deltas and deltas.get("metrics"):
        lines.append(
            f"vs run registry (trailing {deltas['window']} runs, "
            f"current mean {g(deltas['current_mean_iters'])} iters):")
        for k, d in deltas["metrics"].items():
            lines.append(f"  {k}: registry median "
                         f"{g(d['registry_median'])} "
                         f"(ratio {g(d['ratio'])})")
    return "\n".join(lines)


def run_report(source: str, as_json: bool = False,
               registry: str = "", window: int = 5) -> int:
    from comapreduce_tpu.telemetry.solver_trace import read_solver

    records = read_solver(source)
    if not records:
        print(f"no solver trace records under {source} (is [telemetry] "
              "enabled = true?)", file=sys.stderr)
        return 1
    summary = summarize_solver(records)
    deltas = None
    if registry != "none":
        path = registry or ""
        if not path:
            from comapreduce_tpu.telemetry.registry import (
                default_registry_path)

            path = default_registry_path()
        if os.path.exists(path):
            deltas = registry_deltas(summary, path, window=window)
    if as_json:
        print(json.dumps({"summary": summary, "registry": deltas}))
    else:
        print(format_report(summary, deltas))
    return 0


def _selftest() -> int:
    """Synthetic converging / stalling / diverged bands + a torn tail,
    through the real append/read path."""
    from comapreduce_tpu.telemetry.solver_trace import (append_solver,
                                                       read_solver,
                                                       solve_summary,
                                                       solver_path)

    with tempfile.TemporaryDirectory() as tmp:
        path = solver_path(tmp, 0)

        def band(name, resid_fn, n, threshold=1e-6, precond="jacobi"):
            recs = []
            best = float("inf")
            for k in range(n):
                r = resid_fn(k)
                recs.append({"schema": 1, "kind": "iteration",
                             "band": name, "iter": k, "residual": r,
                             "rr": r * r, "alpha": 1.0, "beta": 0.1,
                             "precond_id": f"{precond}|L50",
                             "precision_id": "tod=f32|cgdot=f32",
                             "threshold": threshold, "rank": 0,
                             "diverging": r > 100.0 * best})
                best = min(best, r)
            recs.append(solve_summary(
                recs, band=name, n_iter=n, residual=resid_fn(n - 1),
                diverged=any(r["diverging"] for r in recs),
                precond_id=f"{precond}|L50",
                precision_id="tod=f32|cgdot=f32", threshold=threshold,
                base=0, rank=0))
            append_solver(path, recs)

        band("band0", lambda k: 10.0 ** (-0.2 * k), 40,
             precond="multigrid")                     # converges
        band("band1", lambda k: max(1e-3, 10.0 ** (-0.5 * k)),
             60)                                      # stalls flat
        band("band2", lambda k: 1e-3 * (10.0 ** k if k > 6 else
                                        10.0 ** (-0.1 * k)), 10)
        with open(path, "a") as f:
            f.write('{"kind": "iteration", "band": "to')  # torn tail

        records = read_solver(tmp)
        summary = summarize_solver(records)
        by_band = {b["band"]: b for b in summary["bands"]}
        b0, b1, b2 = (by_band[f"band{i}"] for i in range(3))
        ok = (b0["converged"] and not b0["stalled"]
              and b0["slope_decades_per_iter"] is not None
              and abs(b0["slope_decades_per_iter"] + 0.2) < 0.02
              and (b1["stalled"] or b1["tail_stalled"])
              and not b1["converged"]
              and b2["diverged"] and b2["diverging_iters"] > 0
              and summary["preconditioners"]["multigrid"]["iters"] == 40
              and len(records) == 41 + 61 + 11  # torn line dropped
              and format_report(summary))
        print(json.dumps({"selftest_ok": bool(ok),
                          "bands": len(summary["bands"]),
                          "n_records": len(records)}))
        return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", nargs="?", default="",
                    help="log dir holding solver.rank*.jsonl (or one "
                         "trace file)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    ap.add_argument("--registry", default="",
                    help="runs.jsonl for effectiveness deltas (default "
                         "evidence/runs.jsonl when present; 'none' "
                         "disables)")
    ap.add_argument("--window", type=int, default=5,
                    help="trailing registry records to compare against")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic round-trip (the CI smoke)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.source:
        ap.error("source is required (or use --selftest)")
    return run_report(args.source, as_json=args.json,
                      registry=args.registry, window=args.window)


if __name__ == "__main__":
    raise SystemExit(main())
