#!/usr/bin/env python
"""Operate the map tile read tier (``comapreduce_tpu.tiles``).

Subcommands::

    serve     run the HTTP tile server over one tiles root: epoch
              manifests, content-addressed tiles, sky cutouts
    status    one-line health of a tiles root: current epoch, tile
              count, bytes, delta sizes
    tile      cut published epoch(s) into the tiles root by hand
              (the map server does this automatically with
              ``--tiles-dir``; this is the backfill/repair path)

Examples::

    python tools/tile_server.py tile --epochs-dir run/epochs \\
        --tiles-dir run/tiles
    python tools/tile_server.py serve --tiles-dir run/tiles \\
        --port 8080 --epochs-dir run/epochs
    python tools/tile_server.py status --tiles-dir run/tiles --json

``serve`` is read-only over immutable content — any number of tile
servers (and HTTP caches in front of them) can share one tiles root.
``status`` imports no jax and returns instantly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _add_tiles_dir(ap):
    ap.add_argument("--tiles-dir", required=True,
                    help="tiles root (objects/ + manifests/)")


def cmd_serve(args) -> int:
    from comapreduce_tpu.telemetry import TELEMETRY, serving_lane_rank
    from comapreduce_tpu.tiles.http import TileServer

    if args.telemetry_dir:
        # same stream layout as the map server: the tile server is a
        # serving-lane rank in the campaign's telemetry dir, on its
        # own stream so it never collides with the map server's
        rank = args.telemetry_rank
        if rank is None:
            rank = serving_lane_rank(args.telemetry_dir)
        TELEMETRY.configure(args.telemetry_dir, rank=rank)
    server = TileServer(args.tiles_dir, host=args.host, port=args.port,
                        epochs_root=args.epochs_dir or None)
    # the bound port on stdout FIRST: with --port 0 (tests/drills) the
    # parent reads it from our output
    print(f"tile-server: listening on http://{server.host}:"
          f"{server.port}/ (root {args.tiles_dir})", flush=True)
    if args.max_wall_s is not None:
        server.start()
        time.sleep(float(args.max_wall_s))
        server.stop()
    else:
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
    st = server.status()
    print(f"tile-server: served {st['http']['n_requests']} request(s), "
          f"{st['http']['bytes_sent']} byte(s)")
    return 0


def cmd_status(args) -> int:
    from comapreduce_tpu.tiles.tiler import TileSet

    ts = TileSet(args.tiles_dir)
    cur = ts.current()
    if cur is None:
        print(f"{args.tiles_dir}: no tiled epoch yet")
        return 1
    man = ts.manifest(cur) or {}
    stale = time.time() - float(man.get("t_publish_unix", 0.0))
    line = (f"current epoch-{cur:06d}: {man.get('n_tiles', '?')} tiles "
            f"({man.get('n_empty', 0)} empty skipped), "
            f"{man.get('total_bytes', 0)} bytes, "
            f"tiled {stale:.0f}s ago")
    delta = ts.delta(cur) or {}
    if delta.get("prev") is not None:
        line += (f"; delta vs epoch-{delta['prev']:06d}: "
                 f"{delta.get('n_changed', '?')} changed / "
                 f"{delta.get('n_removed', '?')} removed "
                 f"({delta.get('changed_bytes', 0)} bytes)")
    print(line)
    if args.json:
        out = {"current": cur, "tiled": ts.list_tiled(),
               "manifest": {k: v for k, v in man.items()
                            if k != "tiles"},
               "delta": delta}
        print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def cmd_tile(args) -> int:
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.tiles.tiler import TileSet, tile_epoch

    store = EpochStore(args.epochs_dir)
    ts = TileSet(args.tiles_dir)
    if args.epoch is not None:
        todo = [int(args.epoch)]
    else:
        tiled = set(ts.list_tiled())
        todo = [n for n in store.list_epochs() if n not in tiled]
    if not todo:
        print("tile: nothing to do (every complete epoch is tiled)")
        return 0
    for n in todo:
        man = tile_epoch(store.epoch_dir(n), args.tiles_dir,
                         tile_px=args.tile_px,
                         tile_nside=args.tile_nside)
        print(f"tiled epoch-{n:06d}: {man['n_tiles']} tiles, "
              f"{man['total_bytes']} bytes "
              f"({man['t_tile_s']:.2f}s)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="run the HTTP tile server")
    _add_tiles_dir(s)
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8080,
                   help="0 binds an ephemeral port (printed on stdout)")
    s.add_argument("--epochs-dir", default="",
                   help="source epochs root: enables /v1/epochs/N/meta "
                   "solve metadata")
    s.add_argument("--max-wall-s", type=float, default=None,
                   help="exit after this long (drills; default: forever)")
    s.add_argument("--telemetry-dir", default="",
                   help="emit request counters/spans into this "
                   "telemetry dir (the campaign state dir)")
    s.add_argument("--telemetry-rank", type=int, default=None,
                   help="serving-lane telemetry rank (default: next "
                   "free stream >= 1000)")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("status", help="current tiled epoch + sizes")
    _add_tiles_dir(s)
    s.add_argument("--json", action="store_true",
                   help="also dump manifests summary JSON")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("tile", help="tile published epoch(s) by hand")
    _add_tiles_dir(s)
    s.add_argument("--epochs-dir", required=True,
                   help="source epochs root")
    s.add_argument("--epoch", type=int, default=None,
                   help="one epoch number (default: every complete "
                   "epoch not yet tiled)")
    s.add_argument("--tile-px", type=int, default=64,
                   help="WCS tile edge in pixels")
    s.add_argument("--tile-nside", type=int, default=0,
                   help="HEALPix tile grid nside (0 = nside/64)")
    s.set_defaults(fn=cmd_tile)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
