#!/usr/bin/env python
"""Resilience smoke: run the chaos drill, exit 0 iff every promise held.

Usage::

    python tools/check_resilience.py [--workdir DIR] [--seed N] [--keep]
                                     [--elastic-only | --serving-only
                                      | --tiles-only | --synthetic-only]

Injects one fault of every class (read error, truncated file,
first-attempt flake, NaN burst, slow read, HANGING read) over a
synthetic Level-2 fixture set and asserts the resilience layer's
contract (``comapreduce_tpu/resilience/drill.py``): zero unhandled
exceptions, every fault ledgered with the correct classification
(including the hung read: soft-deadline ``stalled`` warning, then
hard-deadline cancel triaged ``hang``/``rejected``), the destriped map
byte-identical to the clean run with the faulted units zero-weighted,
quarantine skip/re-admit behaving across runs, and every cancelled
hang landing within ``hard deadline + grace`` — the watchdog contract
is exercised on every run.

``--elastic-only`` runs criterion 7 instead: the rank-kill/rank-pause
elastic-campaign drill (``run_elastic_drill`` — three real worker
processes; one SIGKILLed mid-lease, one zombified mid-unit, one
survivor that steals both leases), asserting exactly-once commits, the
zombie's late commit fence-rejected, stolen/recovered ledgered, and
the map byte-identical to a clean run. Kept as a separate CI step
("Rank-kill drill") because it spawns subprocesses and costs ~20 s.

``--serving-only`` runs criterion 8: the incremental map-server drill
(``run_serving_drill`` — server subprocesses folding committed files
in waves), asserting exactly-once folding across epochs, SIGKILL
mid-publish leaving ``current`` on the last complete epoch and the
resumed run byte-identical to an uninterrupted twin, an epoch built
from per-file incremental aggregates byte-identical to a batch
read+solve, and a warm-started epoch converging in strictly fewer CG
iterations than a cold solve of the same census (maps agreeing modulo
the weighted-mean null mode).

``--live-only`` runs the live observability drill (``run_live_drill``
— two real worker ranks under a ``LiveServer`` sidecar, one SIGKILLed
mid-lease then restarted), asserting ``/healthz`` flips 200→503 within
one heartbeat TTL of the kill and back to 200 after the steal +
restart, the ``/metrics`` Prometheus page parses with its commit
counter equal to the scheduler's commit count EXACTLY, and
``/v1/campaign`` serves the schema-2 report
(docs/OPERATIONS.md §16).

``--tiles-only`` runs criterion 9: the map tile read tier drill
(``run_tiles_drill`` — server subprocesses tiling published epochs
into a content-addressed root, a real ``tools/tile_server.py`` HTTP
front), asserting a SIGKILL between tile object writes and the
manifest rename leaves readers on the previous complete tile set
(old-or-new, never torn), the CLI backfill + fresh-root re-tile is
byte-identical (deterministic encoding; exact deltas), an HTTP cutout
is bit-identical to slicing the expanded epoch FITS with 304s
surviving a ``/v1/current`` rollback, each serving process takes its
own telemetry lane, and ``MapServer.evict`` reproduces the
pre-eviction epoch's tile hashes exactly.

``--synthetic-only`` runs the synthetic scale drill
(``comapreduce_tpu/synthetic/loadgen.py`` — a generated ``synth://``
campaign of ``--n-files`` virtual Level-1 files pointed at three real
elastic reduce ranks, the map server, and the tile tier
simultaneously): rank 1 is SIGKILLed while holding a live lease and a
fresh process rejoins mid-run, asserting exactly-once lease commits
(survivor counts + the stolen leak sum to the campaign), ``/healthz``
flipping 503 within one TTL and recovering after the rejoin, a
mid-run epoch published under load plus a fresh final epoch whose
census is the full campaign, the tile manifest tracking ``current``,
and the ``/metrics`` per-rank commit counters EXACTLY equal to each
surviving scheduler's own count (docs/OPERATIONS.md §18).

``--integrity-only`` runs criterion 10: the end-to-end integrity
plane (docs/OPERATIONS.md §20). One byte is flipped in a committed
artifact of every durable class — Level-2 checkpoint, BlockCache
spill entry, solver snapshot, epoch FITS, tile object, quarantine
ledger line — and the drill asserts ``tools/campaign_fsck.py``
detects 100% of the damage, every read boundary triages its class
correctly (corrupt disposition / cache miss / cold solve /
``verify_epoch`` problem / ``CorruptArtifactError`` / dropped line),
chaos ``bit_rot`` rot is always detectable and fires at most once per
basename, and ``--repair`` plus re-derivation converges to a final
map byte-identical to the clean run's.

``--control-only`` runs the closed-loop control-plane drill
(``comapreduce_tpu/control/drill.py`` — a ``Supervisor`` + real
``RankManager`` children over a 12-file elastic campaign): the
autoscaler's fill-to-the-floor performs the initial 4-rank rollout,
ranks 0 and 1 are SIGKILLed at their third claim and replaced by
fresh rank ids within one policy decision, a ``load_spike`` chaos
fault lands 3 pre-flagged files mid-run which every rank's admission
gate sheds ``deferred`` under SLO pressure and re-admits when it
clears (never dropped — asserted through the merged quarantine
ledgers), the ``/metrics`` commit counter equals the lease board's
done count EXACTLY, and the final map over the committed set is
byte-identical to an undisturbed run (docs/OPERATIONS.md §19).

Prints one JSON evidence line; non-zero exit (with the broken
criterion named) on any failure. Also wired into CI as ``bench.py
--config resilience``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="",
                    help="fixture/ledger directory (default: a tmpdir)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep", action="store_true",
                    help="keep the workdir (inspect the ledger/fixtures)")
    only = ap.add_mutually_exclusive_group()
    only.add_argument("--elastic-only", action="store_true",
                      help="run only criterion 7 (the rank-kill/"
                      "rank-pause elastic-campaign drill)")
    only.add_argument("--serving-only", action="store_true",
                      help="run only criterion 8 (the incremental "
                      "map-server kill/resume/warm-start drill)")
    only.add_argument("--tiles-only", action="store_true",
                      help="run only criterion 9 (the map tile read "
                      "tier kill/backfill/HTTP/evict drill)")
    only.add_argument("--live-only", action="store_true",
                      help="run only the live observability drill "
                      "(healthz flip on SIGKILL/recovery, exact "
                      "/metrics commit counter)")
    only.add_argument("--synthetic-only", action="store_true",
                      help="run only the synthetic scale drill (a "
                      "generated synth:// campaign through elastic "
                      "ranks + map server + tile tier with a mid-run "
                      "rank kill/rejoin)")
    only.add_argument("--integrity-only", action="store_true",
                      help="run only criterion 10 (the integrity "
                      "plane: one byte flipped per artifact class, "
                      "100%% fsck detection, correct per-class "
                      "triage, repair converges to a byte-identical "
                      "map)")
    only.add_argument("--control-only", action="store_true",
                      help="run only the control-plane drill (the "
                      "supervisor rolls out 4 worker ranks, 2 are "
                      "SIGKILLed mid-campaign and replaced within the "
                      "policy, a load_spike lands flagged files that "
                      "admission sheds 'deferred' and re-admits, with "
                      "exact /metrics commit audit and a byte-"
                      "identical final map)")
    ap.add_argument("--n-files", type=int, default=200,
                    help="campaign size for --synthetic-only "
                    "(default 200)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from comapreduce_tpu.resilience.drill import (run_drill,
                                                  run_elastic_drill,
                                                  run_integrity_drill,
                                                  run_live_drill,
                                                  run_serving_drill,
                                                  run_tiles_drill)

    if args.synthetic_only:
        from comapreduce_tpu.synthetic.loadgen import run_synthetic_drill

        def drill(workdir, seed=0):
            return run_synthetic_drill(workdir, seed=seed,
                                       n_files=args.n_files)
    elif args.control_only:
        from comapreduce_tpu.control.drill import run_control_drill

        drill = run_control_drill
    else:
        drill = (run_live_drill if args.live_only
                 else run_integrity_drill if args.integrity_only
                 else run_tiles_drill if args.tiles_only
                 else run_serving_drill if args.serving_only
                 else run_elastic_drill if args.elastic_only
                 else run_drill)
    workdir = args.workdir or tempfile.mkdtemp(prefix="check_resilience_")
    try:
        try:
            evidence = drill(workdir, seed=args.seed)
        except AssertionError as exc:
            print(json.dumps({"ok": False, "criterion": str(exc)}))
            return 1
        print(json.dumps({"ok": True, **evidence}))
        return 0
    finally:
        if not args.keep and not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
