#!/usr/bin/env python
"""Operator stall report: render heartbeats + quarantine ledger.

Usage::

    python tools/watchdog_report.py OUTPUT_DIR [--stale-s 60]
                                    [--n-ranks N] [--json]

Reads every ``heartbeat.rank*.json``, ``quarantine*.jsonl``,
``lease.*.json`` and the ``queue.json`` manifest in the run's state
directory (the directory given, falling back to ``<dir>/logs`` — the
default ``[Global] log_dir`` layout) and answers the on-call questions
in one screen: which ranks are alive, where each one is
(stage/unit/progress counters), how stale each heartbeat is, which
operations stalled or hung, which units the run deferred
(``rejected``) or durably skipped (``quarantined``), and — for
elastic campaigns (docs/OPERATIONS.md §11) — who holds which lease at
what generation, how many units are done/claimed/pending, and whether
any expired lease is sitting unreclaimed.

The report itself is built by
:mod:`comapreduce_tpu.resilience.status` (shared with the live
observability plane's ``/v1/campaign`` endpoint —
docs/OPERATIONS.md §16); this tool only renders and sets the exit
code.

When a control-plane supervisor ran in the same state directory
(``supervisor.json`` present — docs/OPERATIONS.md §19) the report is
schema 3 and adds the supervisor block: desired vs live ranks, the
last ``control.decision``, the shed backlog, and a STUCK verdict when
the supervisor stopped republishing mid-campaign.

Exit code: 0 when every expected rank's heartbeat is fresher than
``--stale-s`` AND no lease is expired-but-unreclaimed AND no stuck
supervisor; 1 otherwise
(so the report doubles as a liveness probe in cron/CI). ``--n-ranks``
sets the expected rank count (default: the ranks that have heartbeat
files — a fully dead rank that never wrote one can only be caught
with an explicit count). ``--stale-s`` doubles as the lease-expiry
TTL for the report (pass the campaign's ``lease_ttl_s`` to match the
scheduler's view).

The runbook lives in docs/OPERATIONS.md ("Hangs, deadlines &
heartbeats").
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from comapreduce_tpu.resilience.status import (build_report,  # noqa: E402
                                               report_healthy)


def render_text(rep: dict) -> str:
    lines = [f"watchdog report — {rep['output_dir']} "
             f"(stale threshold {rep['stale_s']:.0f} s)", ""]
    if not rep["ranks"]:
        lines.append("no heartbeat files found (heartbeat_s = 0, or "
                     "the run never started)")
    for r in rep["ranks"]:
        if not r.get("present"):
            lines.append(f"  rank {r['rank']}: NO HEARTBEAT "
                         "(never started, or died before first beat)")
            continue
        flag = "STALE" if r["stale"] else "ok"
        prog = " ".join(f"{k}={v}" for k, v in
                        sorted(r["progress"].items())) or "-"
        lines.append(
            f"  rank {r['rank']} [{flag}] age {r['age_s']:.1f} s  "
            f"seq {r['seq']}  {r['host']}:{r['pid']}")
        lines.append(f"    at: {r['stage'] or '-'}  "
                     f"unit: {r['unit'] or '-'}  progress: {prog}")
        dl = r.get("deadline")
        if dl:
            lines.append(f"    last deadline event: {dl.get('name')} "
                         f"{dl.get('state')} after "
                         f"{dl.get('elapsed_s')} s")
    lines.append("")
    sup = rep.get("supervisor")
    if sup:
        # schema 3: a control plane ran here — desired vs live ranks,
        # the last decision, and the shed backlog are the on-call view
        flag = ("  STUCK (stopped republishing mid-campaign)"
                if sup.get("stuck")
                else "  drained" if sup.get("drained") else "")
        lines.append(
            f"supervisor: desired {sup.get('desired_ranks')} rank(s), "
            f"live {sup.get('live_ranks')}, dead {sup.get('dead_ranks')}"
            f"  (snapshot age {sup.get('age_s', 0):.1f} s){flag}")
        lines.append(
            f"  backlog {sup.get('backlog')}  shed backlog "
            f"{sup.get('shed_backlog')}  "
            f"{sup.get('files_per_hour') or 0:.1f} files/h  "
            f"eta {sup.get('eta_s') if sup.get('eta_s') is not None else '-'} s  "
            f"{sup.get('n_decisions', 0)} decision(s)")
        last = sup.get("last_decision") or {}
        if last:
            lines.append(f"  last decision: [{last.get('loop')}] "
                         f"{last.get('action')} — {last.get('reason')}")
        if sup.get("stuck"):
            lines.append(
                "  a stuck supervisor cannot replace the next dead "
                "rank — restart it (docs/OPERATIONS.md §19)")
        lines.append("")
    if rep.get("queue"):
        q = rep["queue"]
        lines.append(
            f"queue: {q['n_files']} unit(s) — {q['n_done']} done, "
            f"{q['n_claimed']} claimed, {q['n_pending']} pending"
            + (f", {q['n_torn']} torn" if q["n_torn"] else "")
            + (f", {rep['n_stolen']} steal(s) ledgered"
               if rep.get("n_stolen") else ""))
        held: dict = {}
        for l in rep["leases"]:
            if l["state"] == "claimed":
                held.setdefault(l["owner"], []).append(l)
        for owner in sorted(held, key=lambda o: (o is None, o)):
            rows = held[owner]
            lines.append(f"  rank {owner}: {len(rows)} held lease(s)")
            for l in rows:
                flag = "  EXPIRED (unreclaimed)" if l["expired"] else ""
                lines.append(f"    {l['key']}  gen {l['generation']}  "
                             f"age {l['age_s']:.1f} s{flag}")
        torn = [l for l in rep["leases"] if l["state"] == "torn"]
        for l in torn:
            flag = "  EXPIRED (unreclaimed)" if l["expired"] else ""
            lines.append(f"  TORN lease {l['key']}  "
                         f"age {l['age_s']:.1f} s{flag}")
        if rep.get("n_expired_leases"):
            lines.append(
                f"  {rep['n_expired_leases']} expired lease(s) with no "
                "survivor reclaiming them — the campaign is wedged "
                "(docs/OPERATIONS.md §11: start a rank, it will steal)")
        lines.append("")
    if rep["ledger_summary"]:
        lines.append(f"ledger ({', '.join(rep['ledger_files'])}): " +
                     ", ".join(f"{k}: {v}" for k, v in
                               sorted(rep["ledger_summary"].items())))
    else:
        lines.append("ledger: no events")
    for title, rows in (("stall warnings", rep["stalls"]),
                        ("hangs / deferred shards", rep["hangs"]),
                        ("corrupt artifacts",
                         rep.get("corruption", []))):
        if rows:
            lines.append(f"{title} (latest {len(rows)}):")
            for e in rows:
                lines.append(f"  {e['t']} {e['disposition']:<9} "
                             f"{e['stage']:<22} "
                             f"{os.path.basename(e['unit'] or '')} "
                             f"{e['message']}")
    if rep.get("n_corrupt_ledger_lines"):
        lines.append(f"{rep['n_corrupt_ledger_lines']} ledger line(s) "
                     "dropped for failing their integrity seal — run "
                     "tools/campaign_fsck.py (docs/OPERATIONS.md §20)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("output_dir", help="the run's output directory "
                    "(holds heartbeat.rank*.json + quarantine*.jsonl)")
    ap.add_argument("--stale-s", type=float, default=60.0,
                    help="heartbeat age beyond which a rank counts as "
                    "stale (default 60)")
    ap.add_argument("--n-ranks", type=int, default=0,
                    help="expected rank count (default: the ranks that "
                    "wrote heartbeats)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)

    rep = build_report(args.output_dir, stale_s=args.stale_s,
                       n_ranks=args.n_ranks)
    print(json.dumps(rep) if args.json else render_text(rep))
    return 0 if report_healthy(rep) else 1


if __name__ == "__main__":
    raise SystemExit(main())
