#!/usr/bin/env python
"""Operator stall report: render heartbeats + quarantine ledger.

Usage::

    python tools/watchdog_report.py OUTPUT_DIR [--stale-s 60]
                                    [--n-ranks N] [--json]

Reads every ``heartbeat.rank*.json``, ``quarantine*.jsonl``,
``lease.*.json`` and the ``queue.json`` manifest in the run's state
directory (the directory given, falling back to ``<dir>/logs`` — the
default ``[Global] log_dir`` layout) and answers the on-call questions
in one screen: which ranks are alive, where each one is
(stage/unit/progress counters), how stale each heartbeat is, which
operations stalled or hung, which units the run deferred
(``rejected``) or durably skipped (``quarantined``), and — for
elastic campaigns (docs/OPERATIONS.md §11) — who holds which lease at
what generation, how many units are done/claimed/pending, and whether
any expired lease is sitting unreclaimed.

Exit code: 0 when every expected rank's heartbeat is fresher than
``--stale-s`` AND no lease is expired-but-unreclaimed; 1 otherwise
(so the report doubles as a liveness probe in cron/CI). ``--n-ranks``
sets the expected rank count (default: the ranks that have heartbeat
files — a fully dead rank that never wrote one can only be caught
with an explicit count). ``--stale-s`` doubles as the lease-expiry
TTL for the report (pass the campaign's ``lease_ttl_s`` to match the
scheduler's view).

The runbook lives in docs/OPERATIONS.md ("Hangs, deadlines &
heartbeats").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _resolve_state_dir(output_dir: str) -> str:
    """The directory actually holding the run state: ``output_dir``
    itself, else its ``logs/`` child (the default ``[Global] log_dir``
    routing) when only that one has state files."""
    import glob as _glob

    def has_state(d: str) -> bool:
        return any(_glob.glob(os.path.join(d, pat))
                   for pat in ("heartbeat.rank*.json", "lease.*.json",
                               "queue.json", "quarantine*.jsonl"))

    logs = os.path.join(output_dir, "logs")
    if not has_state(output_dir) and os.path.isdir(logs) \
            and has_state(logs):
        return logs
    return output_dir


def build_report(output_dir: str, stale_s: float = 60.0,
                 n_ranks: int = 0) -> dict:
    """The report as data (rendering and exit policy live in main)."""
    from comapreduce_tpu.resilience.heartbeat import (heartbeat_age_s,
                                                      read_heartbeats)
    from comapreduce_tpu.resilience.ledger import QuarantineLedger

    now = time.time()
    output_dir = _resolve_state_dir(output_dir)
    beats = read_heartbeats(output_dir)
    expected = range(n_ranks) if n_ranks > 0 else sorted(beats)
    ranks = []
    for r in expected:
        hb = beats.get(r)
        if hb is None:
            ranks.append({"rank": r, "present": False, "stale": True})
            continue
        age = heartbeat_age_s(hb, now)
        ranks.append({
            "rank": r, "present": True,
            "age_s": round(age, 1),
            # out-of-range on EITHER side is stale: too old is dead,
            # and a negative age (future clock) is a skewed host with
            # no live evidence — exit-1 material for the cron probe
            "stale": not 0.0 <= age <= stale_s,
            "stage": hb.get("stage", ""),
            "unit": hb.get("unit", ""),
            "seq": hb.get("seq", 0),
            "pid": hb.get("pid"),
            "host": hb.get("host", ""),
            "progress": hb.get("progress", {}),
            "deadline": hb.get("deadline"),
        })

    # one merged read-only view over every rank's ledger file
    import glob as _glob

    ledgers = sorted(_glob.glob(os.path.join(output_dir,
                                             "quarantine*.jsonl")))
    entries = []
    summary: dict = {}
    stalls, hangs = [], []
    if ledgers:
        led = QuarantineLedger(ledgers[0],
                               read_paths=tuple(ledgers[1:]))
        entries = led.entries
        summary = led.summary()
        for e in entries:
            if e.failure_class != "hang":
                continue
            row = {"t": e.t, "unit": e.unit.get("file", ""),
                   "stage": e.stage, "message": e.message,
                   "disposition": e.disposition}
            (stalls if e.disposition == "stalled" else hangs).append(row)

    queue, leases = _queue_report(output_dir, beats, stale_s, now)
    return {
        "schema": 2,
        "output_dir": output_dir,
        "stale_s": stale_s,
        "ranks": ranks,
        "n_stale": sum(1 for r in ranks if r["stale"]),
        "ledger_files": [os.path.basename(p) for p in ledgers],
        "ledger_summary": summary,
        "n_ledger_events": len(entries),
        "n_stolen": sum(1 for e in entries
                        if e.disposition == "stolen"),
        "stalls": stalls[-20:],
        "hangs": hangs[-20:],
        "queue": queue,
        "leases": leases,
        "n_expired_leases": sum(1 for l in leases if l["expired"]),
    }


def _queue_report(state_dir: str, beats: dict, stale_s: float,
                  now: float) -> tuple:
    """Elastic-campaign state: the ``queue.json`` manifest summary and
    one row per ``lease.*.json``. ``expired`` marks a lease whose
    owner shows no live heartbeat within ``stale_s`` yet which no
    survivor has reclaimed — the signal that a campaign is wedged
    (no rank left to steal)."""
    import glob as _glob

    from comapreduce_tpu.resilience.heartbeat import heartbeat_age_s
    from comapreduce_tpu.resilience.lease import read_lease

    leases = []
    for p in sorted(_glob.glob(os.path.join(state_dir, "lease.*.json"))):
        try:
            age = now - os.stat(p).st_mtime
        except OSError:
            continue  # vanished mid-scan (a commit or steal in flight)
        st = read_lease(p)
        if st is None:
            # torn lease: no valid owner to be alive — reclaimable
            # (and 'expired' for the probe) once past the TTL
            leases.append({"key": os.path.basename(p), "state": "torn",
                           "owner": None, "generation": None,
                           "age_s": round(age, 1),
                           "expired": age > stale_s})
            continue
        row = {"key": st.get("key", os.path.basename(p)),
               "state": st.get("state", "?"),
               "owner": st.get("owner"),
               "generation": st.get("generation"),
               "stolen_from": st.get("stolen_from"),
               "done_by": st.get("done_by"),
               "age_s": round(age, 1), "expired": False}
        if row["state"] == "claimed" and age > stale_s:
            hb = beats.get(int(st.get("owner", -1)))
            row["expired"] = (hb is None or
                              not 0.0 <= heartbeat_age_s(hb, now)
                              <= stale_s)
        leases.append(row)

    queue = None
    qpath = os.path.join(state_dir, "queue.json")
    try:
        with open(qpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = None
    if manifest is not None or leases:
        n_files = len((manifest or {}).get("files", [])) or len(leases)
        n_done = sum(1 for l in leases if l["state"] == "done")
        n_claimed = sum(1 for l in leases if l["state"] == "claimed")
        queue = {"n_files": n_files, "n_done": n_done,
                 "n_claimed": n_claimed,
                 "n_pending": max(n_files - len(leases), 0),
                 "n_torn": sum(1 for l in leases
                               if l["state"] == "torn")}
    return queue, leases


def render_text(rep: dict) -> str:
    lines = [f"watchdog report — {rep['output_dir']} "
             f"(stale threshold {rep['stale_s']:.0f} s)", ""]
    if not rep["ranks"]:
        lines.append("no heartbeat files found (heartbeat_s = 0, or "
                     "the run never started)")
    for r in rep["ranks"]:
        if not r.get("present"):
            lines.append(f"  rank {r['rank']}: NO HEARTBEAT "
                         "(never started, or died before first beat)")
            continue
        flag = "STALE" if r["stale"] else "ok"
        prog = " ".join(f"{k}={v}" for k, v in
                        sorted(r["progress"].items())) or "-"
        lines.append(
            f"  rank {r['rank']} [{flag}] age {r['age_s']:.1f} s  "
            f"seq {r['seq']}  {r['host']}:{r['pid']}")
        lines.append(f"    at: {r['stage'] or '-'}  "
                     f"unit: {r['unit'] or '-'}  progress: {prog}")
        dl = r.get("deadline")
        if dl:
            lines.append(f"    last deadline event: {dl.get('name')} "
                         f"{dl.get('state')} after "
                         f"{dl.get('elapsed_s')} s")
    lines.append("")
    if rep.get("queue"):
        q = rep["queue"]
        lines.append(
            f"queue: {q['n_files']} unit(s) — {q['n_done']} done, "
            f"{q['n_claimed']} claimed, {q['n_pending']} pending"
            + (f", {q['n_torn']} torn" if q["n_torn"] else "")
            + (f", {rep['n_stolen']} steal(s) ledgered"
               if rep.get("n_stolen") else ""))
        held: dict = {}
        for l in rep["leases"]:
            if l["state"] == "claimed":
                held.setdefault(l["owner"], []).append(l)
        for owner in sorted(held, key=lambda o: (o is None, o)):
            rows = held[owner]
            lines.append(f"  rank {owner}: {len(rows)} held lease(s)")
            for l in rows:
                flag = "  EXPIRED (unreclaimed)" if l["expired"] else ""
                lines.append(f"    {l['key']}  gen {l['generation']}  "
                             f"age {l['age_s']:.1f} s{flag}")
        torn = [l for l in rep["leases"] if l["state"] == "torn"]
        for l in torn:
            flag = "  EXPIRED (unreclaimed)" if l["expired"] else ""
            lines.append(f"  TORN lease {l['key']}  "
                         f"age {l['age_s']:.1f} s{flag}")
        if rep.get("n_expired_leases"):
            lines.append(
                f"  {rep['n_expired_leases']} expired lease(s) with no "
                "survivor reclaiming them — the campaign is wedged "
                "(docs/OPERATIONS.md §11: start a rank, it will steal)")
        lines.append("")
    if rep["ledger_summary"]:
        lines.append(f"ledger ({', '.join(rep['ledger_files'])}): " +
                     ", ".join(f"{k}: {v}" for k, v in
                               sorted(rep["ledger_summary"].items())))
    else:
        lines.append("ledger: no events")
    for title, rows in (("stall warnings", rep["stalls"]),
                        ("hangs / deferred shards", rep["hangs"])):
        if rows:
            lines.append(f"{title} (latest {len(rows)}):")
            for e in rows:
                lines.append(f"  {e['t']} {e['disposition']:<9} "
                             f"{e['stage']:<22} "
                             f"{os.path.basename(e['unit'] or '')} "
                             f"{e['message']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("output_dir", help="the run's output directory "
                    "(holds heartbeat.rank*.json + quarantine*.jsonl)")
    ap.add_argument("--stale-s", type=float, default=60.0,
                    help="heartbeat age beyond which a rank counts as "
                    "stale (default 60)")
    ap.add_argument("--n-ranks", type=int, default=0,
                    help="expected rank count (default: the ranks that "
                    "wrote heartbeats)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)

    rep = build_report(args.output_dir, stale_s=args.stale_s,
                       n_ranks=args.n_ranks)
    print(json.dumps(rep) if args.json else render_text(rep))
    # an expired-but-unreclaimed lease means work nobody will finish:
    # probe-fail it like a stale rank
    return 1 if rep["n_stale"] or rep["n_expired_leases"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
