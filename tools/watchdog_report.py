#!/usr/bin/env python
"""Operator stall report: render heartbeats + quarantine ledger.

Usage::

    python tools/watchdog_report.py OUTPUT_DIR [--stale-s 60]
                                    [--n-ranks N] [--json]

Reads every ``heartbeat.rank*.json`` and ``quarantine*.jsonl`` in the
run's output directory and answers the on-call questions in one
screen: which ranks are alive, where each one is (stage/unit/progress
counters), how stale each heartbeat is, which operations stalled or
hung, and which units the run deferred (``rejected``) or durably
skipped (``quarantined``).

Exit code: 0 when every expected rank's heartbeat is fresher than
``--stale-s``; 1 when any rank is stale/missing (so the report doubles
as a liveness probe in cron/CI). ``--n-ranks`` sets the expected rank
count (default: the ranks that have heartbeat files — a fully dead
rank that never wrote one can only be caught with an explicit count).

The runbook lives in docs/OPERATIONS.md ("Hangs, deadlines &
heartbeats").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_report(output_dir: str, stale_s: float = 60.0,
                 n_ranks: int = 0) -> dict:
    """The report as data (rendering and exit policy live in main)."""
    from comapreduce_tpu.resilience.heartbeat import (heartbeat_age_s,
                                                      read_heartbeats)
    from comapreduce_tpu.resilience.ledger import QuarantineLedger

    now = time.time()
    beats = read_heartbeats(output_dir)
    expected = range(n_ranks) if n_ranks > 0 else sorted(beats)
    ranks = []
    for r in expected:
        hb = beats.get(r)
        if hb is None:
            ranks.append({"rank": r, "present": False, "stale": True})
            continue
        age = heartbeat_age_s(hb, now)
        ranks.append({
            "rank": r, "present": True,
            "age_s": round(age, 1),
            # out-of-range on EITHER side is stale: too old is dead,
            # and a negative age (future clock) is a skewed host with
            # no live evidence — exit-1 material for the cron probe
            "stale": not 0.0 <= age <= stale_s,
            "stage": hb.get("stage", ""),
            "unit": hb.get("unit", ""),
            "seq": hb.get("seq", 0),
            "pid": hb.get("pid"),
            "host": hb.get("host", ""),
            "progress": hb.get("progress", {}),
            "deadline": hb.get("deadline"),
        })

    # one merged read-only view over every rank's ledger file
    import glob as _glob

    ledgers = sorted(_glob.glob(os.path.join(output_dir,
                                             "quarantine*.jsonl")))
    entries = []
    summary: dict = {}
    stalls, hangs = [], []
    if ledgers:
        led = QuarantineLedger(ledgers[0],
                               read_paths=tuple(ledgers[1:]))
        entries = led.entries
        summary = led.summary()
        for e in entries:
            if e.failure_class != "hang":
                continue
            row = {"t": e.t, "unit": e.unit.get("file", ""),
                   "stage": e.stage, "message": e.message,
                   "disposition": e.disposition}
            (stalls if e.disposition == "stalled" else hangs).append(row)

    return {
        "output_dir": output_dir,
        "stale_s": stale_s,
        "ranks": ranks,
        "n_stale": sum(1 for r in ranks if r["stale"]),
        "ledger_files": [os.path.basename(p) for p in ledgers],
        "ledger_summary": summary,
        "n_ledger_events": len(entries),
        "stalls": stalls[-20:],
        "hangs": hangs[-20:],
    }


def render_text(rep: dict) -> str:
    lines = [f"watchdog report — {rep['output_dir']} "
             f"(stale threshold {rep['stale_s']:.0f} s)", ""]
    if not rep["ranks"]:
        lines.append("no heartbeat files found (heartbeat_s = 0, or "
                     "the run never started)")
    for r in rep["ranks"]:
        if not r.get("present"):
            lines.append(f"  rank {r['rank']}: NO HEARTBEAT "
                         "(never started, or died before first beat)")
            continue
        flag = "STALE" if r["stale"] else "ok"
        prog = " ".join(f"{k}={v}" for k, v in
                        sorted(r["progress"].items())) or "-"
        lines.append(
            f"  rank {r['rank']} [{flag}] age {r['age_s']:.1f} s  "
            f"seq {r['seq']}  {r['host']}:{r['pid']}")
        lines.append(f"    at: {r['stage'] or '-'}  "
                     f"unit: {r['unit'] or '-'}  progress: {prog}")
        dl = r.get("deadline")
        if dl:
            lines.append(f"    last deadline event: {dl.get('name')} "
                         f"{dl.get('state')} after "
                         f"{dl.get('elapsed_s')} s")
    lines.append("")
    if rep["ledger_summary"]:
        lines.append(f"ledger ({', '.join(rep['ledger_files'])}): " +
                     ", ".join(f"{k}: {v}" for k, v in
                               sorted(rep["ledger_summary"].items())))
    else:
        lines.append("ledger: no events")
    for title, rows in (("stall warnings", rep["stalls"]),
                        ("hangs / deferred shards", rep["hangs"])):
        if rows:
            lines.append(f"{title} (latest {len(rows)}):")
            for e in rows:
                lines.append(f"  {e['t']} {e['disposition']:<9} "
                             f"{e['stage']:<22} "
                             f"{os.path.basename(e['unit'] or '')} "
                             f"{e['message']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("output_dir", help="the run's output directory "
                    "(holds heartbeat.rank*.json + quarantine*.jsonl)")
    ap.add_argument("--stale-s", type=float, default=60.0,
                    help="heartbeat age beyond which a rank counts as "
                    "stale (default 60)")
    ap.add_argument("--n-ranks", type=int, default=0,
                    help="expected rank count (default: the ranks that "
                    "wrote heartbeats)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)

    rep = build_report(args.output_dir, stale_s=args.stale_s,
                       n_ranks=args.n_ranks)
    print(json.dumps(rep) if args.json else render_text(rep))
    return 1 if rep["n_stale"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
