#!/usr/bin/env python
"""Watch a running campaign: live plane, health probe, run trends.

Subcommands::

    serve    run the live observability sidecar (blocking):
             /metrics (Prometheus), /healthz, /v1/campaign,
             /v1/quality over one campaign state directory
    status   one-shot campaign report (the watchdog report — schema 3
             with the supervisor block when a control plane ran,
             schema 2 otherwise — fetched from a running sidecar with
             --url, else built straight from the state directory;
             local reads also surface the latest control.decision
             events, docs/OPERATIONS.md §19)
    check    liveness probe for cron/CI: exit 0 healthy, 1 not
             (same rule as /healthz and watchdog_report's exit code)
    trend    compare the newest run-registry record against the
             trailing window; exit 1 on regression

Examples::

    python tools/campaign_watch.py serve run/logs --port 9100
    python tools/campaign_watch.py status run/logs --stale-s 30
    python tools/campaign_watch.py check run/logs --n-ranks 3
    python tools/campaign_watch.py trend --kind perf_gate --window 5

``serve``/``status``/``check`` read the same on-disk state as
``tools/watchdog_report.py`` — heartbeats, leases, the quarantine and
quality ledgers — and never write. The runbook is
docs/OPERATIONS.md §16.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from comapreduce_tpu.resilience.status import (build_report,  # noqa: E402
                                               report_healthy)
from comapreduce_tpu.telemetry.registry import (  # noqa: E402
    default_registry_path, format_trend, read_runs, trend)


def cmd_serve(args) -> int:
    from comapreduce_tpu.telemetry.live import LiveServer

    srv = LiveServer(args.state_dir, host=args.host, port=args.port,
                     stale_s=args.stale_s, n_ranks=args.n_ranks)
    print(f"live plane: http://{srv.host}:{srv.port}/metrics  "
          f"/healthz  /v1/campaign  /v1/quality")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def _fetch_report(args) -> dict:
    if args.url:
        from urllib.request import urlopen

        with urlopen(args.url.rstrip("/") + "/v1/campaign",
                     timeout=10) as r:
            return json.load(r)
    return build_report(args.state_dir, stale_s=args.stale_s,
                        n_ranks=args.n_ranks)


def _render_decisions(state_dir: str, last: int = 10) -> str:
    """The latest ``control.decision`` events of this campaign, one
    line each — the control plane's audit trail in the live view
    (docs/OPERATIONS.md §19). Empty string when no loop ever decided
    anything here (no control plane ran, or every loop is off)."""
    from comapreduce_tpu.control.decisions import read_decisions

    events = read_decisions(state_dir)
    if not events:
        return ""
    lines = [f"control decisions ({len(events)} total, "
             f"latest {min(last, len(events))}):"]
    for e in events[-last:]:
        lines.append(f"  {e.get('t')} [{e.get('loop')}] "
                     f"{e.get('action'):<10} {e.get('reason')}")
    return "\n".join(lines)


def cmd_status(args) -> int:
    from tools.watchdog_report import render_text

    rep = _fetch_report(args)
    if args.json:
        print(json.dumps(rep, sort_keys=True))
    else:
        print(render_text(rep))
        # the decision ledger is on-disk state (not part of the
        # /v1/campaign payload) — readable only when we have the dir
        if not args.url:
            dec = _render_decisions(rep.get("output_dir")
                                    or args.state_dir)
            if dec:
                print()
                print(dec)
    return 0 if report_healthy(rep) else 1


def cmd_check(args) -> int:
    rep = _fetch_report(args)
    ok = report_healthy(rep)
    stuck = bool((rep.get("supervisor") or {}).get("stuck"))
    n_corrupt = (rep.get("n_corrupt", 0)
                 + rep.get("n_corrupt_ledger_lines", 0))
    print(f"{'healthy' if ok else 'UNHEALTHY'}: "
          f"{rep['n_stale']} stale rank(s), "
          f"{rep['n_expired_leases']} expired lease(s)"
          + (", STUCK supervisor" if stuck else "")
          + (f", {n_corrupt} CORRUPT artifact(s)/line(s) — run "
             "tools/campaign_fsck.py" if n_corrupt else "")
          + f" ({rep['output_dir']})")
    return 0 if ok else 1


def cmd_trend(args) -> int:
    path = args.registry or default_registry_path()
    runs = read_runs(path, kind=args.kind)
    res = trend(runs, window=args.window, tolerance=args.tolerance)
    print(f"registry: {path}"
          + (f" (kind={args.kind})" if args.kind else ""))
    print(format_trend(res))
    return 0 if res["ok"] else 1


def _add_state_args(ap) -> None:
    ap.add_argument("state_dir", nargs="?", default=".",
                    help="campaign state dir ([Global] log_dir; "
                    "<output_dir>/logs also resolves)")
    ap.add_argument("--url", default="",
                    help="fetch from a running sidecar instead of "
                    "reading the state dir (e.g. http://host:9100)")
    ap.add_argument("--stale-s", type=float, default=60.0,
                    help="heartbeat TTL for the probe (default 60; "
                    "pass the campaign's lease_ttl_s)")
    ap.add_argument("--n-ranks", type=int, default=0,
                    help="expected rank count (default: ranks with "
                    "heartbeat files)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="run the live HTTP sidecar")
    s.add_argument("state_dir")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=9100)
    s.add_argument("--stale-s", type=float, default=60.0)
    s.add_argument("--n-ranks", type=int, default=0)
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("status", help="print the campaign report")
    _add_state_args(s)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("check", help="liveness probe (exit 0/1)")
    _add_state_args(s)
    s.set_defaults(fn=cmd_check)

    s = sub.add_parser("trend",
                       help="latest registry record vs trailing window")
    s.add_argument("--registry", default="",
                   help="runs.jsonl path (default: "
                   "$COMAP_RUNS_REGISTRY or evidence/runs.jsonl)")
    s.add_argument("--kind", default=None,
                   help="only compare records of this kind")
    s.add_argument("--window", type=int, default=5)
    s.add_argument("--tolerance", type=float, default=0.2,
                   help="fractional slack before a metric counts as "
                   "regressed (default 0.2)")
    s.set_defaults(fn=cmd_trend)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
