#!/usr/bin/env python
"""Roofline table from the compiled-program registry + measured walls.

    python tools/roofline_report.py PROGRAMS_DIR_OR_FILE
        [--walls BENCH_JSON] [--peak-flops TFLOPS] [--peak-bw GBS]
        [--json]
    python tools/roofline_report.py --selftest

Earlier ROOFLINE.md rounds were assembled by hand from ad-hoc
``cost_analysis()`` calls. This tool renders the same table from
``programs.jsonl`` (``telemetry/programs.py`` — written by any
telemetry-on run or by ``bench.py --config destriper``): per program
the XLA FLOP count, bytes accessed, HBM footprint
(argument/output/temp), and the arithmetic intensity FLOPs/byte.

``--walls`` takes a bench detail JSON (any ``bench.py`` evidence blob —
nested ``wall_s``/``ms_per_iter`` entries are found by key suffix
match, e.g. ladder entry ``multigrid`` pairs with program
``destriper.multigrid``) and adds achieved GFLOP/s and GB/s per
program; with ``--peak-flops``/``--peak-bw`` (defaults: the round-3
bench-host envelope, 45 TFLOP/s f32 MXU and 565 GB/s marginal HBM)
each program is placed against its roofline bound: percent of the
min(compute, bandwidth) ceiling and which side it sits on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# measured bench-host envelope (ROOFLINE.md "Platform envelope")
DEFAULT_PEAK_TFLOPS = 45.0
DEFAULT_PEAK_GBS = 565.0


def collect_walls(blob, prefix: str = "") -> dict:
    """Flatten a bench evidence blob into ``{dotted.key: wall_s}`` —
    any dict carrying ``wall_s`` (or only ``ms_per_iter``) contributes
    one entry under its key path."""
    out: dict = {}
    if not isinstance(blob, dict):
        return out
    if isinstance(blob.get("wall_s"), (int, float)):
        out[prefix or "run"] = float(blob["wall_s"])
    elif isinstance(blob.get("ms_per_iter"), (int, float)):
        out[prefix or "run"] = float(blob["ms_per_iter"]) / 1e3
    for k, v in blob.items():
        if isinstance(v, dict):
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(collect_walls(v, key))
    return out


def match_wall(name: str, walls: dict) -> float | None:
    """Pair program ``destriper.multigrid`` with wall key
    ``...ladder.multigrid`` by longest suffix-segment overlap."""
    best, best_len = None, 0
    tail = name.split(".")[-1]
    for key, wall in walls.items():
        ktail = key.split(".")[-1]
        if ktail == tail or key.endswith(name) or name.endswith(ktail):
            score = len(os.path.commonprefix([name[::-1], key[::-1]]))
            score = max(score, len(ktail) if ktail == tail else 0)
            if score > best_len:
                best, best_len = float(wall), score
    return best


def build_rows(records: list, walls: dict | None = None,
               peak_tflops: float = DEFAULT_PEAK_TFLOPS,
               peak_gbs: float = DEFAULT_PEAK_GBS) -> list:
    rows = []
    for rec in records:
        flops = rec.get("flops")
        nbytes = rec.get("bytes_accessed")
        hbm = ((rec.get("temp_bytes") or 0)
               + (rec.get("output_bytes") or 0))
        row = {
            "name": rec.get("name", ""),
            "shape_bucket": rec.get("shape_bucket", ""),
            "precision_id": rec.get("precision_id", ""),
            "backend": rec.get("backend", ""),
            "flops": flops,
            "bytes_accessed": nbytes,
            "intensity_flops_per_byte": (flops / nbytes
                                         if flops and nbytes else None),
            "argument_bytes": rec.get("argument_bytes"),
            "hbm_temp_output_bytes": hbm or None,
        }
        wall = match_wall(row["name"], walls) if walls else None
        if wall and wall > 0:
            row["wall_s"] = wall
            if flops:
                row["achieved_gflops"] = flops / wall / 1e9
            if nbytes:
                row["achieved_gbs"] = nbytes / wall / 1e9
            if flops and nbytes:
                # the roofline ceiling for this intensity: bandwidth-
                # bound below the ridge, compute-bound above it
                intensity = flops / nbytes
                bw_bound = peak_gbs * 1e9 * intensity   # FLOP/s
                fl_bound = peak_tflops * 1e12
                bound = min(bw_bound, fl_bound)
                row["bound"] = ("bandwidth" if bw_bound < fl_bound
                                else "compute")
                row["pct_of_roof"] = 100.0 * (flops / wall) / bound
        rows.append(row)
    rows.sort(key=lambda r: -(r.get("flops") or 0))
    return rows


def format_table(rows: list) -> str:
    def g(v, spec=".3g"):
        return "-" if v is None else format(float(v), spec)

    have_walls = any("wall_s" in r for r in rows)
    head = ["program", "shapes", "GFLOP", "GB moved", "FLOP/B",
            "HBM t+o MB"]
    if have_walls:
        head += ["wall s", "GFLOP/s", "GB/s", "% roof (bound)"]
    lines = ["| " + " | ".join(head) + " |",
             "|" + "---|" * len(head)]
    for r in rows:
        cells = [
            r["name"], r["shape_bucket"] or "-",
            g(r["flops"] / 1e9 if r["flops"] else None),
            g(r["bytes_accessed"] / 1e9 if r["bytes_accessed"]
              else None),
            g(r["intensity_flops_per_byte"]),
            g(r["hbm_temp_output_bytes"] / 1e6
              if r["hbm_temp_output_bytes"] else None),
        ]
        if have_walls:
            pct = (f"{r['pct_of_roof']:.1f} ({r['bound']})"
                   if r.get("pct_of_roof") is not None else "-")
            cells += [g(r.get("wall_s")), g(r.get("achieved_gflops")),
                      g(r.get("achieved_gbs")), pct]
        lines.append("| " + " | ".join(str(c) for c in cells) + " |")
    return "\n".join(lines)


def run_report(source: str, walls_path: str = "", as_json: bool = False,
               peak_tflops: float = DEFAULT_PEAK_TFLOPS,
               peak_gbs: float = DEFAULT_PEAK_GBS) -> int:
    from comapreduce_tpu.telemetry.programs import read_programs

    records = read_programs(source)
    if not records:
        print(f"no program records under {source} (run a telemetry-on "
              "campaign or bench.py --config destriper)",
              file=sys.stderr)
        return 1
    walls = None
    if walls_path:
        with open(walls_path) as f:
            walls = collect_walls(json.load(f))
    rows = build_rows(records, walls, peak_tflops, peak_gbs)
    if as_json:
        print(json.dumps({"programs": rows,
                          "peak_tflops": peak_tflops,
                          "peak_gbs": peak_gbs}))
    else:
        print(format_table(rows))
    return 0


def _selftest() -> int:
    """Synthetic registry + walls through the full merge path."""
    from comapreduce_tpu.telemetry.programs import programs_path

    with tempfile.TemporaryDirectory() as tmp:
        recs = [
            {"schema": 1, "kind": "program", "name": "destriper.mg",
             "shape_bucket": "f32[1000]", "precision_id": "f32",
             "backend": "cpu", "flops": 2.0e9, "bytes_accessed": 1.0e8,
             "output_bytes": 4000, "temp_bytes": 6000},
            {"schema": 1, "kind": "program", "name": "level1.bin",
             "shape_bucket": "f32[64]", "precision_id": "f32",
             "backend": "cpu", "flops": 1.0e6, "bytes_accessed": 1.0e9},
        ]
        with open(programs_path(tmp), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
            f.write('{"kind": "program", "na')   # torn tail
        walls = collect_walls(
            {"detail": {"ladder": {"mg": {"wall_s": 0.5},
                                   "bin": {"ms_per_iter": 2.0}}}})
        from comapreduce_tpu.telemetry.programs import read_programs

        rows = build_rows(read_programs(tmp), walls,
                          peak_tflops=1.0, peak_gbs=1000.0)
        by = {r["name"]: r for r in rows}
        mg, b1 = by["destriper.mg"], by["level1.bin"]
        # ridge point at these peaks: 1e12 / 1e12 = 1 FLOP/B. mg at
        # intensity 20 sits compute-bound; achieved 2e9/0.5 = 4 GFLOP/s
        # -> 0.4% of the 1 TFLOP/s roof. bin at 1e-3 FLOP/B is
        # bandwidth-bound.
        ok = (rows[0]["name"] == "destriper.mg"      # sorted by flops
              and abs(mg["intensity_flops_per_byte"] - 20.0) < 1e-9
              and mg["hbm_temp_output_bytes"] == 10000
              and mg["bound"] == "compute"
              and abs(mg["pct_of_roof"] - 0.4) < 1e-6
              and mg["wall_s"] == 0.5
              and b1["bound"] == "bandwidth"
              and abs(b1["wall_s"] - 0.002) < 1e-12
              and "% roof" in format_table(rows))
        print(json.dumps({"selftest_ok": bool(ok),
                          "programs": len(rows)}))
        return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", nargs="?", default="",
                    help="directory holding programs.jsonl (or one "
                         "file)")
    ap.add_argument("--walls", default="",
                    help="bench evidence JSON to merge measured walls "
                         "from")
    ap.add_argument("--peak-flops", type=float,
                    default=DEFAULT_PEAK_TFLOPS,
                    help="peak TFLOP/s for the roofline ceiling")
    ap.add_argument("--peak-bw", type=float, default=DEFAULT_PEAK_GBS,
                    help="peak HBM GB/s for the roofline ceiling")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable rows")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic round-trip (the CI smoke)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.source:
        ap.error("source is required (or use --selftest)")
    return run_report(args.source, walls_path=args.walls,
                      as_json=args.json, peak_tflops=args.peak_flops,
                      peak_gbs=args.peak_bw)


if __name__ == "__main__":
    raise SystemExit(main())
