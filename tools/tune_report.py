#!/usr/bin/env python
"""Operator view of the shape-bucket autotuner's winners cache.

    python tools/tune_report.py LOG_DIR_OR_FILE [--json]
    python tools/tune_report.py --selftest

Reads ``tuning.jsonl`` under the run's ``[Global] log_dir``
(``tuning/cache.py`` — the sealed latest-wins winners ledger) and
renders one row per cached winner:

- the identity axes: knob group, backend platform / device kind, shape
  bucket, precision policy, knob-space version;
- the winning knob values against the defaults they beat, with the
  measured walls (``best_ms`` vs ``default_ms``) and the speedup;
- the sweep's cost: candidates timed and total measurements — the
  numerator of the amortization math in docs/OPERATIONS.md §21;
- a trailing summary: how many winners differ from their defaults
  (rows marked ``=`` kept the default — the noise floor held) and the
  total sweep measurements the cache now saves every warm campaign.

Torn, tampered and stale-space lines never reach the table — the
reader inherits the ledger's seal-verified latest-wins contract.

``--selftest`` writes winners through the real sealed append path
(including a torn trailing line and a superseded key), reads them back
and validates the report — the CI smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def summarize_tuning(records: dict) -> dict:
    """Fold the ``{key: record}`` cache into the report structure:
    rows sorted by (group, bucket), plus the totals the operator
    actually asks for (sweeps saved, winners beating defaults)."""
    rows = []
    for key, rec in records.items():
        winner = rec.get("winner") or {}
        default = rec.get("default") or {}
        best = rec.get("best_ms")
        base = rec.get("default_ms")
        rows.append({
            "key": str(key)[:12],
            "group": rec.get("group", ""),
            "platform": rec.get("platform", ""),
            "device_kind": rec.get("device_kind", ""),
            "bucket": rec.get("bucket"),
            "precision_id": rec.get("precision_id", ""),
            "space_version": rec.get("space_version"),
            "winner": winner,
            "default": default,
            "tuned": winner != default,
            "best_ms": best,
            "default_ms": base,
            "speedup": (round(base / best, 3)
                        if best and base else None),
            "candidates": rec.get("candidates"),
            "measurements": rec.get("measurements"),
            "t": rec.get("t", ""),
        })
    rows.sort(key=lambda r: (r["group"], json.dumps(r["bucket"],
                                                    sort_keys=True,
                                                    default=str)))
    return {
        "n_winners": len(rows),
        "n_tuned": sum(1 for r in rows if r["tuned"]),
        "measurements_saved": sum(int(r["measurements"] or 0)
                                  for r in rows),
        "rows": rows,
    }


def _bucket_str(bucket) -> str:
    if isinstance(bucket, dict):
        return "|".join(f"{k}={bucket[k]}" for k in sorted(bucket)
                        if k != "group")
    return str(bucket)


def _knobs_str(combo) -> str:
    if isinstance(combo, dict):
        return " ".join(f"{k}={combo[k]}" for k in sorted(combo))
    return str(combo)


def render(report: dict) -> str:
    lines = ["shape-bucket autotuner winners "
             f"({report['n_winners']} cached, {report['n_tuned']} beat "
             "their defaults)", ""]
    header = (f"{'group':<8} {'bucket':<22} {'winner':<28} "
              f"{'vs default':<24} {'speedup':>8} {'meas':>5}")
    lines += [header, "-" * len(header)]
    for r in report["rows"]:
        mark = " " if r["tuned"] else "="
        speed = f"{r['speedup']:.2f}x" if r["speedup"] else "-"
        walls = (f"{r['best_ms']}ms vs {r['default_ms']}ms"
                 if r["best_ms"] is not None else "-")
        lines.append(
            f"{r['group']:<8} {_bucket_str(r['bucket']):<22} "
            f"{mark}{_knobs_str(r['winner']):<27} {walls:<24} "
            f"{speed:>8} {r['measurements'] or 0:>5}")
    lines += ["", f"rows marked '=' kept the default (noise floor "
                  "held); a warm campaign re-measures nothing — "
                  f"{report['measurements_saved']} sweep "
                  "measurement(s) amortised (docs/OPERATIONS.md §21)"]
    return "\n".join(lines)


def selftest() -> int:
    from comapreduce_tpu.tuning.cache import (TuningCache, content_key,
                                              read_tuning, tuning_path)

    work = tempfile.mkdtemp(prefix="tune_report_selftest_")
    path = tuning_path(work)
    cache = TuningCache(path)
    key_p = content_key("cpu", "cpu", {"group": "plan", "N": 36864,
                                       "L": 50}, "", 1, "plan")
    key_s = content_key("cpu", "cpu", {"group": "solver", "L": 50},
                        "", 1, "solver")
    # a superseded winner first: latest-wins must hide it
    cache.put({"key": key_p, "group": "plan", "platform": "cpu",
               "device_kind": "cpu", "bucket": {"group": "plan",
                                                "N": 36864, "L": 50},
               "space_version": 1, "winner": {"pair_batch": 8},
               "default": {"pair_batch": 1}, "best_ms": 9.0,
               "default_ms": 12.0, "candidates": 4, "measurements": 9})
    cache.put({"key": key_p, "group": "plan", "platform": "cpu",
               "device_kind": "cpu", "bucket": {"group": "plan",
                                                "N": 36864, "L": 50},
               "space_version": 1, "winner": {"pair_batch": 4},
               "default": {"pair_batch": 1}, "best_ms": 8.1,
               "default_ms": 11.9, "candidates": 4, "measurements": 9})
    cache.put({"key": key_s, "group": "solver", "platform": "cpu",
               "device_kind": "cpu", "bucket": {"group": "solver",
                                                "L": 50},
               "space_version": 1,
               "winner": {"mg_block": 8, "mg_smooth": 1},
               "default": {"mg_block": 8, "mg_smooth": 1},
               "best_ms": 5.0, "default_ms": 5.0, "candidates": 6,
               "measurements": 12})
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"kind": "tuning", "key": "torn')  # no newline: torn

    records = read_tuning(work)
    assert len(records) == 2, f"expected 2 keys, got {len(records)}"
    report = summarize_tuning(records)
    assert report["n_winners"] == 2 and report["n_tuned"] == 1, report
    by_group = {r["group"]: r for r in report["rows"]}
    assert by_group["plan"]["winner"] == {"pair_batch": 4}, \
        "latest-wins lost: the superseded pair_batch=8 row surfaced"
    assert by_group["plan"]["speedup"] and \
        by_group["plan"]["speedup"] > 1.0
    assert not by_group["solver"]["tuned"], \
        "a default-keeping winner must render as '=' (not tuned)"
    assert report["measurements_saved"] == 21
    out = render(report)
    assert "pair_batch=4" in out and "§21" in out
    print(out)
    print("\ntune_report selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("source", nargs="?", default=".",
                    help="run log_dir (or a tuning.jsonl path)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()

    from comapreduce_tpu.tuning.cache import read_tuning

    records = read_tuning(args.source)
    if not records:
        print(f"no tuning winners under {args.source!r} (tuning.jsonl "
              "missing or empty — has a [tuning]-enabled sweep run?)",
              file=sys.stderr)
        return 1
    report = summarize_tuning(records)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
