"""Op-level profile of the bench programs: xprof ``hlo_stats`` table.

The round-3 review asked for the compiled programs' op table as
secondary perf evidence when wall-clock measurement is unavailable.
This traces one BENCH_SMALL-or-scaled bench iteration under
``jax.profiler.trace`` and prints the top ops by self time (the
``hlo_stats`` tool of xprof), excluding ``while`` rows (double counts).

Usage::

    python tools/hlo_stats.py [--scale 0.2] [--out HLO_STATS_r05.json]

Runs on whatever backend jax selects; meaningful numbers need the real
chip. Never signals children; safe under the relay rules.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    scale = "1.0"
    out_path = os.path.join(REPO, "HLO_STATS_r05.json")
    args = sys.argv[1:]
    while args:
        a = args.pop(0)
        if a == "--scale" and args:
            scale = args.pop(0)
        elif a == "--out" and args:
            out_path = args.pop(0)
        else:
            print(__doc__, file=sys.stderr)
            return 2

    os.environ.setdefault("BENCH_SCALE", scale)
    os.environ.setdefault("BENCH_BASELINE_S", "30")  # skip the baseline
    os.environ.setdefault("BENCH_NO_PROBE", "")      # keep the probe

    sys.path.insert(0, REPO)
    import bench

    import jax

    trace_dir = tempfile.mkdtemp(prefix="comap_hlo_")
    with jax.profiler.trace(trace_dir):
        bench.main()

    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError:
        print(f"trace written to {trace_dir}; xprof not importable "
              "here — convert offline", file=sys.stderr)
        return 1
    planes = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    data, _ = rtd.xspace_to_tool_data(planes, "hlo_stats", {})
    table = json.loads(data) if isinstance(data, (str, bytes)) else data
    # dump the raw table FIRST: the row count is cosmetic and must not
    # cost an expensive traced run its artifact
    with open(out_path, "w") as f:
        json.dump(table, f)
    try:
        rows = bench.gviz_rows(table)
    except Exception:   # noqa: BLE001 — count is cosmetic
        rows = []
    print(f"hlo_stats: {len(rows)} rows -> {out_path} "
          f"(trace in {trace_dir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
