"""End-to-end slice: synthetic Level-1 obs -> Level-2 TOD -> destriped map.

The minimum full-pipeline program (SURVEY.md §7): generate a synthetic
observation in the COMAP Level-1 HDF5 schema, vane-calibrate, reduce to
Level-2, bin and destripe into a WCS map — all device math under one jit.

Run:  PYTHONPATH=/root/repo:/root/.axon_site python examples/end_to_end.py
"""

from __future__ import annotations

import sys
import tempfile

import numpy as np


def main(n_feeds: int = 2, n_channels: int = 64) -> int:
    import jax

    from comapreduce_tpu.data.level import COMAPLevel1
    from comapreduce_tpu.data.synthetic import (SyntheticObsParams,
                                                generate_level1_file)
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.ops.vane import find_vane_events
    from comapreduce_tpu.parallel.mesh import local_mesh
    from comapreduce_tpu.parallel.step import ObservationStep

    print("devices:", jax.devices())

    with tempfile.NamedTemporaryFile(suffix=".hd5") as tmp:
        p = SyntheticObsParams(n_feeds=n_feeds, n_channels=n_channels,
                               source_amplitude_k=0.5)
        generate_level1_file(tmp.name, p)
        lvl1 = COMAPLevel1()
        lvl1.read(tmp.name)

        F, B, C, T = lvl1.tod_shape
        edges = lvl1.scan_edges
        print(f"obs {lvl1.obsid}: shape {(F, B, C, T)}, "
              f"{len(edges)} scans, Tvane={lvl1.vane_temperature:.1f} K")

        # host-side geometry: vane window, pixels, masks
        events = find_vane_events(lvl1.vane_flag)
        vs, ve = int(events[0, 0]), int(events[0, 1]) + 50
        wcs = WCS.from_field((p.ra0, p.dec0), (1.0 / 60, 1.0 / 60),
                             (120, 120))
        ra, dec = np.asarray(lvl1.ra), np.asarray(lvl1.dec)
        pixels = np.asarray(wcs.ang2pix(ra, dec), np.int32)  # (F, T)

        tod = np.stack([lvl1.read_tod_feed(i) for i in range(F)])
        scan_mask = np.zeros(T, np.float32)
        for s, e in edges:
            scan_mask[s:e] = 1.0
        mask = np.broadcast_to(scan_mask, (F, B, C, T)).astype(np.float32)
        freq = lvl1.frequency
        nu0 = freq.mean()
        freq_scaled = ((freq - nu0) / nu0).astype(np.float32)

        step = ObservationStep(
            local_mesh(), scan_edges=edges, n_samples=T, npix=wcs.npix,
            offset_length=50, n_iter=50, n_channels=C, medfilt_window=501,
            vane_temperature=lvl1.vane_temperature)
        level2, result = step(
            tod=tod.astype(np.float32), mask=mask,
            vane_tod=tod[..., vs:ve].astype(np.float32),
            airmass=np.asarray(lvl1.airmass, np.float32),
            pixels=pixels, freq_scaled=freq_scaled)
        jax.block_until_ready(result.destriped_map)

        m = np.asarray(result.destriped_map)
        hits = np.asarray(result.hit_map)
        peak = float(np.nanmax(np.where(hits > 0, m, -np.inf)))
        print(f"level2 tod: {np.asarray(level2['tod']).shape}, "
              f"cg iters: {int(result.n_iter)}, "
              f"residual: {float(result.residual):.2e}")
        print(f"map: {int((hits > 0).sum())}/{wcs.npix} px hit, "
              f"peak {peak * 1e3:.1f} mK "
              f"(injected {p.source_amplitude_k * 1e3:.0f} mK source)")
        # map-space source fit (photometry layer) on the destriped map
        from comapreduce_tpu.mapmaking.photometry import fit_map_source

        fit = fit_map_source(np.where(hits > 0, m, np.nan), wcs,
                             p.ra0, p.dec0, radius=0.4)
        if "amplitude" in fit:
            print(f"source fit: {fit['amplitude'] * 1e3:.1f} mK at "
                  f"({fit['lon']:.3f}, {fit['lat']:.3f}), "
                  f"chi2 {fit['chi2']:.1f}")
        ok = (np.isfinite(m).all() and int(result.n_iter) > 0
              and peak > 0.2 * p.source_amplitude_k)
        print("OK" if ok else "FAIL")
        return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
