"""Polarized (I/Q/U) destriping demo: scatter vs planned paths.

Simulates a polarized scan (rotating psi, 1/f offsets), solves it with
BOTH polarized destripers — the general scatter path and the
scatter-free planned path (``destripe_pol_planned``) — and reports the
I/Q/U recovery and the path agreement.

Run:  PYTHONPATH=/root/repo:/root/.axon_site python examples/polarization_demo.py
"""

from __future__ import annotations

import sys

import numpy as np


def main(npix: int = 64, revisits: int = 60) -> int:
    import jax.numpy as jnp

    from comapreduce_tpu.data.synthetic import one_over_f_noise
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan
    from comapreduce_tpu.mapmaking.polarization import (destripe_pol_jit,
                                                        destripe_pol_planned)

    rng = np.random.default_rng(11)
    n = (npix * revisits // 50) * 50
    pixels = np.arange(n) % npix
    psi = np.linspace(0, np.pi, n) + 0.3 * np.sin(np.arange(n) / 77.0)
    I = 1.0 + 0.3 * rng.normal(size=npix)
    Q = 0.3 * rng.normal(size=npix)
    U = 0.3 * rng.normal(size=npix)
    d = (I[pixels] + Q[pixels] * np.cos(2 * psi)
         + U[pixels] * np.sin(2 * psi))
    sigma = 0.05
    d = d + one_over_f_noise(rng, n, sigma, 1.0, 1.5, fs=50.0)
    w = np.full(n, 1.0 / sigma**2, np.float32)

    args = (jnp.asarray(d, jnp.float32),
            jnp.asarray(pixels.astype(np.int32)), jnp.asarray(w),
            jnp.asarray(psi, jnp.float32))
    scatter = destripe_pol_jit(*args, npix, offset_length=50, n_iter=80)
    plan = build_pointing_plan(pixels, npix, 50)
    planned = destripe_pol_planned(args[0], args[2], args[3], plan,
                                   n_iter=80)

    for label, res in (("scatter", scatter), ("planned", planned)):
        m = np.asarray(res.iqu_destriped)
        errs = [float(np.median(np.abs(m[:, k] - t)))
                for k, t in enumerate((I, Q, U))]
        print(f"{label:8s} I/Q/U median errors: "
              + " ".join(f"{e:.4f}" for e in errs)
              + f"  (iters {int(res.n_iter)}, "
              f"residual {float(res.residual):.2e})")
    agree = float(np.max(np.abs(np.asarray(scatter.iqu_destriped)
                                - np.asarray(planned.iqu_destriped))))
    print(f"path agreement: max |scatter - planned| = {agree:.2e}")
    ok = agree < 5e-3
    print("OK" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
